"""Struct-of-planes message records: the plane-major wire layout.

BENCH_NOTES' corrected cost model showed the 32k round paying HBM
round-trips on materialized ``[n, slots, W]`` intermediates: with the
record word on the MINOR axis, every per-word read is a strided gather
over a 12-wide (lane-padded) dimension, and ``ops/msg.py:build``'s
plane-interleave alone was ~25% of the round.  The fix is layout, not
op flavor (ROADMAP open item 1: "the lever is FUSION and LAYOUT
TOGETHER"): carry a round's messages as a **struct of word planes** —
``W`` separate ``[n, slots]`` tensors — from emission through the
outbound stack, compaction, the fused shed/fault filter and the route
sort, and interleave to the ``[n, slots, W]`` wire layout exactly ONCE
per round, at the exchange boundary (``tests/test_program_budget.py``
guards the one-interleave budget at the jaxpr level).

:class:`Planes` is a registered pytree that quacks like the interleaved
``int32[..., W]`` record tensor for the operations the round pipeline
actually uses — last-axis word reads (``p[..., W_KIND]``), word writes
(``p.at[..., W_KIND].set(v)``), row/slot gathers and scatters — so the
fault filter, the monotonic shed, metrics/latency/provenance readers
and the interposition hooks run unchanged on either layout.  Whole-
tensor jnp calls (``concatenate``/``where``/``zeros_like``) cannot
dispatch on a custom class; the layout-agnostic helpers below
(:func:`concat`, :func:`where`, :func:`zeros_like`) accept both.

**Bytes-first packing**: each plane is stored at the narrowest dtype
its word's value range permits (types.wire_dtype: kind/channel/flags
int8, ttl int16, the provenance hop int16), widening back to int32 only
at the interleave boundary — a pure-bandwidth cut on the dominant
``[n, cap, ·]`` traffic (~23% of record bytes at msg_words=12), and the
narrow planes ride the sharded all_gather exchange as-is (the "ship the
wire as packed planes" case).  Words whose values are unbounded or
id-sized (src/dst/clock/lane/payload, the provenance src, the latency
birth round) stay int32 so widened records are bit-identical to the
legacy path at ANY horizon — the parity contract in
tests/test_faults.py/test_latency.py/test_provenance.py.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import Array

__all__ = [
    "Planes", "is_planes", "concat", "where", "zeros_like",
    "zero_planes", "interleave", "deinterleave",
    "append_words", "append_tail", "stack_words", "stack_records",
    "take_records", "take_along", "take_rows", "take_flat",
]


class Planes:
    """A ``[..., W]`` message-record tensor stored as W word planes.

    All planes share one shape (the logical shape minus the word axis);
    ``shape``/``ndim`` report the LOGICAL interleaved shape, so shape-
    driven code (``emitted.shape[1]``, broadcasting ranks) is layout-
    agnostic.  Supported indexing mirrors the pipeline's usage:

    - ``p[..., i]``            -> word plane i (an Array, storage dtype)
    - ``p[..., a:b]``          -> Planes over the word subset
    - ``p[idx]`` (no word axis)-> per-plane fancy/basic indexing
    - ``p.at[..., i].set(v)``  -> replace word plane i
    - ``p.at[rows, slot].set(q, mode=...)`` -> per-plane scatter
      (``q`` a matching Planes or a scalar)
    """

    __slots__ = ("ws",)

    def __init__(self, ws: Sequence[Array]):
        self.ws = tuple(ws)

    # ---- pytree ------------------------------------------------------
    def tree_flatten(self):
        return self.ws, None

    @classmethod
    def tree_unflatten(cls, aux, ws):
        del aux
        return cls(ws)

    # ---- shape protocol ---------------------------------------------
    @property
    def n_words(self) -> int:
        return len(self.ws)

    @property
    def shape(self) -> tuple:
        return tuple(jnp.shape(self.ws[0])) + (len(self.ws),)

    @property
    def ndim(self) -> int:
        return jnp.ndim(self.ws[0]) + 1

    def __repr__(self) -> str:
        return (f"Planes(shape={self.shape}, "
                f"dtypes={[str(w.dtype) for w in self.ws]})")

    def __array__(self, dtype=None, copy=None):
        """Host-side ``np.asarray(planes)`` materializes the interleaved
        int32 wire tensor — test oracles and exporters read records
        layout-agnostically.  (Never hit inside jit: tracers reject
        __array__ exactly as they do for ordinary Arrays.)"""
        import numpy as np

        del copy
        arr = np.asarray(self.interleave())
        return arr.astype(dtype) if dtype is not None else arr

    # ---- maps --------------------------------------------------------
    def map(self, fn) -> "Planes":
        """Apply ``fn`` to every plane (shape-preserving transforms)."""
        return Planes(tuple(fn(w) for w in self.ws))

    def reshape(self, *shape) -> "Planes":
        """Reshape by LOGICAL shape; the last dim must stay the word
        count (plumtree's ``build(...).reshape(n, S*K, W)`` idiom)."""
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if shape[-1] != len(self.ws):
            raise ValueError(
                f"last dim {shape[-1]} != word count {len(self.ws)}")
        return self.map(lambda w: w.reshape(shape[:-1]))

    # ---- indexing ----------------------------------------------------
    def _split_word_axis(self, idx):
        """Normalize ``idx`` -> (plane_idx, word_sel) where word_sel is
        None (word axis untouched), an int, or a slice."""
        if not isinstance(idx, tuple):
            idx = (idx,)
        if any(i is Ellipsis for i in idx):
            pos = idx.index(Ellipsis)
            explicit = len(idx) - 1 - sum(i is None for i in idx)
            fill = self.ndim - explicit
            idx = idx[:pos] + (slice(None),) * fill + idx[pos + 1:]
        n_axes = sum(i is not None for i in idx)
        if n_axes == self.ndim:
            # the last non-None entry addresses the word axis
            last = idx[-1]
            if last is None:
                raise IndexError(f"unsupported Planes index {idx!r}")
            return idx[:-1], last
        if n_axes > self.ndim:
            raise IndexError(f"too many indices for Planes: {idx!r}")
        return idx, None

    def __getitem__(self, idx):
        plane_idx, wsel = self._split_word_axis(idx)
        if isinstance(wsel, int):
            w = self.ws[wsel]
            return w[plane_idx] if plane_idx else w
        ws = self.ws if wsel is None or wsel == slice(None) \
            else self.ws[wsel]
        if not isinstance(ws, tuple):
            ws = (ws,)
        if plane_idx:
            ws = tuple(w[plane_idx] for w in ws)
        return Planes(ws)

    @property
    def at(self):
        return _PlanesAt(self)

    def interleave(self) -> Array:
        """THE plane->wire boundary: widen every plane to int32 and
        stack on a new minor axis.  Call sites are budgeted — the round
        program may contain exactly one such stack (the jaxpr guard in
        tests/test_program_budget.py counts them)."""
        return jnp.stack([w.astype(jnp.int32) for w in self.ws],
                         axis=-1)


class _PlanesAt:
    __slots__ = ("p",)

    def __init__(self, p: Planes):
        self.p = p

    def __getitem__(self, idx):
        return _PlanesAtRef(self.p, idx)


class _PlanesAtRef:
    __slots__ = ("p", "idx")

    def __init__(self, p: Planes, idx):
        self.p = p
        self.idx = idx

    def set(self, val, **kw):
        plane_idx, wsel = self.p._split_word_axis(self.idx)
        if isinstance(wsel, int):
            w = self.p.ws[wsel]
            v = jnp.asarray(val).astype(w.dtype)
            if plane_idx and not all(
                    isinstance(s, slice) and s == slice(None)
                    for s in plane_idx):
                new = w.at[plane_idx].set(v, **kw)
            else:
                # a full-slice word write replaces the plane outright —
                # ``p.at[..., W_KIND].set(mask)`` is the pipeline's
                # bread-and-butter and must not trace a scatter per call
                new = jnp.broadcast_to(v, jnp.shape(w))
            ws = list(self.p.ws)
            ws[wsel] = new
            return Planes(ws)
        if wsel is not None:
            raise IndexError(
                f"unsupported Planes.at word selector {self.idx!r}")
        if is_planes(val):
            return Planes(tuple(
                w.at[plane_idx].set(v.astype(w.dtype), **kw)
                for w, v in zip(self.p.ws, val.ws)))
        v = jnp.asarray(val)
        if v.ndim >= 1 and v.shape[-1] == len(self.p.ws):
            # An interleaved record block: split it back into planes
            # (host-side injectors like bridge/server.py hand whole
            # int32 records to a plane buffer).
            return Planes(tuple(
                w.at[plane_idx].set(v[..., i].astype(w.dtype), **kw)
                for i, w in enumerate(self.p.ws)))
        return Planes(tuple(
            w.at[plane_idx].set(v.astype(w.dtype), **kw)
            for w in self.p.ws))


jax.tree_util.register_pytree_node(
    Planes,
    lambda p: p.tree_flatten(),
    Planes.tree_unflatten,
)


def is_planes(x) -> bool:
    return isinstance(x, Planes)


# ---------------------------------------------------------------------------
# Layout-agnostic helpers (Array | Planes)
# ---------------------------------------------------------------------------

def blocks_of(x) -> list:
    """Emission blocks of a manager/model ``step`` result.  Hot-path
    managers/models return a TUPLE of record blocks instead of one
    pre-concatenated stack, so the round assembles the emission stack
    with exactly ONE concatenate (the nested assembly used to copy
    every record byte twice — ~13% of the plain round's materialized
    bytes in the round-cost meter).  A single stack (legacy managers,
    third-party models) passes through as a one-block list."""
    return list(x) if isinstance(x, (tuple, list)) else [x]


def concat(blocks: Sequence, axis: int = 1):
    """Concatenate emission blocks on a record axis (NOT the word
    axis).  All-Planes blocks concatenate per plane; all-Array blocks
    fall through to ``jnp.concatenate`` — so manager/model assembly
    code is layout-agnostic.  A mixed list coerces the interleaved
    blocks into the Planes layout (third-party models may still build
    legacy int32 stacks; their word values must respect the documented
    ranges of types.NARROW_WIRE_DTYPES, like every wire record)."""
    blocks = list(blocks)
    if not any(is_planes(b) for b in blocks):
        return jnp.concatenate(blocks, axis=axis)
    nw = {b.n_words if is_planes(b) else b.shape[-1] for b in blocks}
    if len(nw) != 1:
        raise ValueError(
            f"cannot concat mixed widths: "
            f"{[getattr(b, 'shape', None) for b in blocks]}")
    k = nw.pop()
    dtypes = next(tuple(w.dtype for w in b.ws)
                  for b in blocks if is_planes(b))
    blocks = [b if is_planes(b) else deinterleave(b, dtypes)
              for b in blocks]
    return Planes(tuple(
        jnp.concatenate([b.ws[i] for b in blocks], axis=axis)
        for i in range(k)))


def append_words(p, *words):
    """Widen a record stack with trailing words (the latency birth /
    provenance pair stamps).  Planes: O(0) — the new planes join the
    struct.  Arrays: the legacy minor-axis concatenate."""
    if is_planes(p):
        shape = jnp.shape(p.ws[0])
        return Planes(p.ws + tuple(jnp.broadcast_to(w, shape)
                                   for w in words))
    return jnp.concatenate(
        [p] + [jnp.broadcast_to(w, p.shape[:-1])[..., None]
               for w in words], axis=-1)


def where(mask, a, b):
    """Record-granular select: ``mask`` has the record shape (no word
    axis).  Arrays get the legacy ``mask[..., None]`` broadcast."""
    if is_planes(a):
        bw = b.ws if is_planes(b) else [b] * a.n_words
        return Planes(tuple(
            jnp.where(mask, w, jnp.asarray(x).astype(w.dtype))
            for w, x in zip(a.ws, bw)))
    if is_planes(b):
        return Planes(tuple(
            jnp.where(mask, jnp.asarray(a).astype(w.dtype), w)
            for w in b.ws))
    return jnp.where(mask[..., None], a, b)


def append_tail(p, arr, dtype=jnp.int32):
    """Append ``arr [..., K]``'s minor-axis slices as K trailing word
    planes (the causal lanes' vector-clock block).  Arrays: the legacy
    minor-axis concatenate."""
    if is_planes(p):
        k = arr.shape[-1]
        return Planes(p.ws + tuple(arr[..., i].astype(dtype)
                                   for i in range(k)))
    return jnp.concatenate([p, arr.astype(p.dtype)], axis=-1)


def stack_words(p, lo: int = 0, hi: int | None = None) -> Array:
    """Materialize a CONTIGUOUS word block as one int32 array
    ``[..., hi-lo]`` — for payload-block math that genuinely needs a
    dense minor axis (plumtree handler payloads, shuffle samples, the
    causal clock block).  These blocks are a few words wide, far below
    the full record, so the stack is cheap and does NOT count against
    the one-wire-interleave budget (the jaxpr guard keys on the full
    record width).  Identity slice for interleaved arrays."""
    if is_planes(p):
        ws = p.ws[lo:hi] if hi is not None else p.ws[lo:]
        return jnp.stack([w.astype(jnp.int32) for w in ws], axis=-1)
    return p[..., lo:hi] if hi is not None else p[..., lo:]


def stack_records(blocks: Sequence, axis: int = 0):
    """``jnp.stack`` analogue over whole records (a NEW record axis, not
    the word axis) — e.g. scamp's two per-node control messages."""
    blocks = list(blocks)
    if not any(is_planes(b) for b in blocks):
        return jnp.stack(blocks, axis=axis)
    if not all(is_planes(b) for b in blocks):
        raise ValueError("cannot stack mixed layouts")
    k = blocks[0].n_words
    return Planes(tuple(
        jnp.stack([b.ws[i] for b in blocks], axis=axis)
        for i in range(k)))


def take_along(p, idx: Array, axis: int):
    """Per-plane ``take_along_axis`` over a RECORD axis: ``idx`` has the
    record shape (no trailing word-axis ``[..., None]`` — each plane
    already lacks the word axis).  Arrays get the legacy broadcast.
    Planes on the common ``axis=1`` of a [n, E] record stack take the
    dtype-grouped single-gather path (:func:`take_rows`)."""
    if is_planes(p):
        if axis == 1 and jnp.ndim(p.ws[0]) == 2:
            return take_rows(p, idx)
        return Planes(tuple(
            jnp.take_along_axis(w, idx, axis=axis) for w in p.ws))
    return jnp.take_along_axis(p, idx[..., None], axis=axis)


def zeros_like(p):
    if is_planes(p):
        return p.map(jnp.zeros_like)
    return jnp.zeros_like(p)


def zero_planes(shape: tuple, dtypes: Sequence) -> Planes:
    """All-empty records: one zero plane per wire word at its storage
    dtype (``shape`` is the record shape, without the word axis)."""
    return Planes(tuple(jnp.zeros(shape, dt) for dt in dtypes))


def take_records(p, plane_idx):
    """Gather whole records: ``p[plane_idx]`` per plane (generic fancy
    indexing — the hot compaction/route paths use the dtype-grouped
    :func:`take_rows`/:func:`take_flat` instead: W per-plane gathers
    each re-trace index normalization and dispatch as W ops, the
    single largest gather-eqn block the round-cost meter found)."""
    if is_planes(p):
        return Planes(tuple(w[plane_idx] for w in p.ws))
    return p[plane_idx]


# ---------------------------------------------------------------------------
# Dtype-grouped record gathers (the gather-coalescing surgery)
# ---------------------------------------------------------------------------
#
# A Planes record gather used to cost one gather EQUATION per word plane
# (W of them), each re-tracing its own index math.  On the relay-attached
# backend every equation is a dispatched op priced per fetched scalar
# (BENCH_NOTES corrected cost model), so the wire stage's two record
# gathers (compaction, route) alone were 32 of the plain 32k round's 102
# gather/scatter equations.  Planes sharing a storage dtype now stack on
# a NEW LEADING axis (never the minor/wire axis — the one-interleave
# budget keys on record-width minor-axis stacks and stays untouched) and
# ride ONE ``lax.gather`` per dtype group; the per-plane results are
# cheap leading-axis slices of the group result.  Out-of-range indices
# (>= the record count) fill with 0 under ``fill=True`` — the
# ``where(keep, taken, 0)`` select the callers used to trace per plane
# is folded into the gather itself.

def _group_gather(ws, pos, fill: bool):
    """One gather per dtype group of flat ``[m]`` planes.

    ``pos``: int32 index array (any shape) into the flat record axis;
    entries >= m (only legal with ``fill=True``) produce 0.  Returns the
    gathered planes (shape ``pos.shape``) in input order."""
    from jax import lax

    mode = (lax.GatherScatterMode.FILL_OR_DROP if fill
            else lax.GatherScatterMode.PROMISE_IN_BOUNDS)
    groups: dict = {}
    for i, w in enumerate(ws):
        groups.setdefault(jnp.result_type(w), []).append(i)
    out = [None] * len(ws)
    idx = pos[..., None]
    for idxs in groups.values():
        if len(idxs) == 1:
            w = ws[idxs[0]]
            dn = lax.GatherDimensionNumbers(
                offset_dims=(), collapsed_slice_dims=(0,),
                start_index_map=(0,))
            out[idxs[0]] = lax.gather(w, idx, dn, (1,), mode=mode,
                                      fill_value=0)
        else:
            g = len(idxs)
            stacked = jnp.stack([ws[i] for i in idxs], axis=0)  # [g, m]
            dn = lax.GatherDimensionNumbers(
                offset_dims=(0,), collapsed_slice_dims=(1,),
                start_index_map=(1,))
            got = lax.gather(stacked, idx, dn, (g, 1), mode=mode,
                             fill_value=0)                # [g, *pos]
            for j, i in enumerate(idxs):
                out[i] = got[j]
    return out


def take_flat(p, pos, *, fill: bool = False):
    """Gather whole records out of a FLAT ``[m]``-record stack by
    ``pos`` (any index shape) — the route sort's fetch.  ``fill=True``
    turns out-of-range positions into all-zero records (one fused
    fill-gather instead of a per-plane select)."""
    if is_planes(p):
        return Planes(tuple(_group_gather(p.ws, pos, fill)))
    if fill:
        return p.at[pos].get(mode="fill", fill_value=0)
    return p[pos]


def take_rows(p, idx, *, fill: bool = False):
    """Per-row record take: ``out[i, j] = p[i, idx[i, j]]`` over a
    ``[n, E]``-record stack (compaction / queue-admission gathers).
    ``idx`` is int32[n, k]; entries >= E (with ``fill=True``) yield
    all-zero records.  One gather per dtype group via a flat-composed
    index (rows are a multiply-add away, not a per-plane concatenated
    index pair)."""
    if is_planes(p):
        n, E = jnp.shape(p.ws[0])
        rows = jnp.arange(n, dtype=jnp.int32)[:, None]
        # OOB rides the compose: the sentinel must leave the WHOLE flat
        # axis (idx may already be E), and a NEGATIVE index is out of
        # bounds too — composed into a flat position it would silently
        # read a neighboring row's record (the Array path's fill mode
        # treats it as OOB, and layout parity is the module contract).
        if fill:
            # one negative-turn wrap, THEN out-of-range fills — exactly
            # jnp.take_along_axis(mode="fill")'s order, so the two
            # layouts agree record-for-record on any index
            w_idx = jnp.where(idx < 0, idx + E, idx)
            pos = jnp.where((w_idx >= E) | (w_idx < 0), n * E,
                            w_idx + rows * E)
        else:
            # wrap one negative turn (take_along_axis's negative-index
            # semantics), then clamp WITHIN the row: an unguarded
            # row-composed index would read a neighboring row's record.
            # (A truly out-of-range index clamps here where jnp's
            # default fills INT_MAX — callers promise in-range.)
            pos = jnp.clip(jnp.where(idx < 0, idx + E, idx),
                           0, E - 1) + rows * E
        flat = Planes(tuple(w.reshape(-1) for w in p.ws))
        return take_flat(flat, pos, fill=fill)
    if fill:
        return jnp.take_along_axis(p, idx[..., None], axis=1,
                                   mode="fill", fill_value=0)
    return jnp.take_along_axis(p, idx[..., None], axis=1)


def interleave(p):
    """Array | Planes -> interleaved int32 wire tensor (identity for
    arrays)."""
    return p.interleave() if is_planes(p) else p


def deinterleave(arr: Array, dtypes: Sequence | None = None) -> Planes:
    """Wire tensor -> Planes (the routed-inbox/un-interleave direction,
    and the coercion path for callers handing legacy arrays to a
    plane-layout stage).  ``dtypes`` narrows each plane to its storage
    dtype; None keeps int32."""
    k = arr.shape[-1]
    if dtypes is None:
        return Planes(tuple(arr[..., i] for i in range(k)))
    return Planes(tuple(
        arr[..., i].astype(dt) for i, dt in zip(range(k), dtypes)))
