"""Fixed-width partial-view arrays (HyParView active/passive views,
SCAMP partial/in views).

A view is ``int32[K]`` of global node ids with -1 marking empty slots.
The reference stores these as sets of node specs
(partisan_hyparview_peer_service_manager.erl:230-243); K is a small
protocol constant (active 6, passive 30 — include/partisan.hrl:204-217),
so fixed-width arrays + masked ops vectorize cleanly under vmap.

All ops are pure and per-node (1-D); batch with jax.vmap.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array

EMPTY = -1


def empty(k: int) -> Array:
    return jnp.full((k,), EMPTY, jnp.int32)


def empty_batch(n: int, k: int) -> Array:
    return jnp.full((n, k), EMPTY, jnp.int32)


def contains(view: Array, nid: Array) -> Array:
    return jnp.any((view == nid) & (nid >= 0))


def size(view: Array) -> Array:
    return jnp.sum(view >= 0)


def is_full(view: Array) -> Array:
    return jnp.all(view >= 0)


def add(view: Array, nid: Array, key: Array) -> tuple[Array, Array]:
    """Insert ``nid``; if full, evict a RANDOM member to make room
    (drop-random-if-full, add_to_active_view
    partisan_hyparview_peer_service_manager.erl:2344-2420).

    Returns (view', evicted) where evicted is the displaced id or -1.
    No-op (evicted=-1) if nid already present or nid < 0.
    """
    k = view.shape[0]
    already = contains(view, nid) | (nid < 0)
    # Target slot: first empty, else random occupied.
    has_empty = jnp.any(view == EMPTY)
    first_empty = jnp.argmax(view == EMPTY)
    rand_slot = jax.random.randint(key, (), 0, k)
    slot = jnp.where(has_empty, first_empty, rand_slot)
    evicted = jnp.where(has_empty, EMPTY, view[slot])
    new = view.at[slot].set(nid)
    view = jnp.where(already, view, new)
    return view, jnp.where(already, EMPTY, evicted)


def add_cap(view: Array, nid: Array, key: Array, cap) -> tuple[Array, Array]:
    """``add`` under a soft capacity: the view counts as full once
    ``size >= cap`` even if physical slots remain (reserved-slot support,
    reference reserve/1 + add_to_active_view :2344-2420).  At capacity a
    RANDOM member is evicted; ``cap <= 0`` rejects the add outright.

    Returns (view', evicted)."""
    already = contains(view, nid) | (nid < 0) | (jnp.asarray(cap) <= 0)
    cur = size(view)
    at_cap = cur >= jnp.asarray(cap)
    has_empty = jnp.any(view == EMPTY)
    first_empty = jnp.argmax(view == EMPTY)
    evictee = pick_one(view, key)
    evict_slot = jnp.argmax(view == evictee)
    use_evict = at_cap | ~has_empty
    slot = jnp.where(use_evict, evict_slot, first_empty)
    evicted = jnp.where(use_evict, view[slot], EMPTY)
    new = view.at[slot].set(nid)
    view = jnp.where(already, view, new)
    return view, jnp.where(already, EMPTY, evicted)


def worst_by(view: Array, cost_of_id) -> Array:
    """Member with the highest ``cost_of_id(id)`` (or -1 if empty) — the
    X-BOT 'worst active peer' selection (is_better/3 oracle consumer)."""
    ids = jnp.where(view >= 0, view, 0)
    costs = jnp.where(view >= 0, cost_of_id(ids), -jnp.inf)
    slot = jnp.argmax(costs)
    return jnp.where(jnp.any(view >= 0), view[slot], EMPTY)


def remove(view: Array, nid: Array) -> Array:
    return jnp.where((view == nid) & (nid >= 0), EMPTY, view)


def keep_only(view: Array, keep_mask_of_id) -> Array:
    """Clear slots whose id fails ``keep_mask_of_id`` (bool[n_global]
    lookup) — e.g. pruning dead active peers (TCP-EXIT analogue)."""
    ids = jnp.where(view >= 0, view, 0)
    ok = (view >= 0) & keep_mask_of_id[ids]
    return jnp.where(ok, view, EMPTY)


def sample(view: Array, key: Array, k: int, exclude: Array | None = None) -> Array:
    """k distinct random members (-1 padded), optionally excluding ids."""
    valid = view >= 0
    if exclude is not None:
        valid &= ~jnp.any(view[:, None] == exclude[None, :], axis=1)
    g = jax.random.gumbel(key, view.shape)
    score = jnp.where(valid, g, -jnp.inf)
    _, top = jax.lax.top_k(score, k)
    picked = view[top]
    return jnp.where(valid[top], picked, EMPTY)


def pick_one(view: Array, key: Array, exclude: Array | None = None) -> Array:
    """One random member (or -1)."""
    return sample(view, key, 1, exclude)[0]


def admit(view: Array, cands: Array, prio: Array, scores: Array,
          cap) -> tuple[Array, Array, Array]:
    """Batched multi-candidate admission with random eviction.

    The tensor equivalent of folding ``add_cap`` over the valid, deduped
    candidates (add_to_active_view drop-random-if-full semantics,
    partisan_hyparview_peer_service_manager.erl:2344-2420) in one shot:

    - candidates always enter while ``cap > 0`` (evicting RANDOM current
      members once the view is at capacity),
    - when more candidates arrive than ``cap`` admits, higher ``prio``
      wins, ties break uniformly at random,
    - a view already holding more than ``cap`` members (capacity lowered
      by ``reserve`` after fill) shrinks toward ``cap`` whenever an
      admission happens, instead of staying over capacity forever.

    Args: view int32[A]; cands int32[C] (-1 = no candidate, duplicates
    allowed — keep C SMALL, dedupe is pairwise O(C^2): compact wide slot
    lists first); prio int32[C] small non-negative priorities;
    scores: uint32[A + C] uniform ranking keys (ops/rng.rank32) — the
    randomness source for evictions and tie-breaks; cap scalar.
    Returns (view' int32[A], admitted bool[C], evicted int32[A]) where
    ``evicted`` holds displaced member ids slot-aligned with ``view``
    (-1 where the slot's occupant survived).
    """
    a_width = view.shape[0]
    cap = jnp.asarray(cap, jnp.int32)
    in_view = jax.vmap(lambda x: contains(view, x))(cands)
    valid_c = (cands >= 0) & (cap > 0) & ~in_view
    # Dedupe among candidates: keep the max-prio copy (first on ties).
    idx = jnp.arange(cands.shape[0])
    eff = jnp.where(valid_c, prio, -1)
    same = (cands[None, :] == cands[:, None]) & valid_c[None, :] \
        & valid_c[:, None]
    beats = (eff[None, :] > eff[:, None]) | \
        ((eff[None, :] == eff[:, None]) & (idx[None, :] < idx[:, None]))
    valid_c &= ~jnp.any(same & beats, axis=1)

    # Rank: candidates above members (always enter, evicting randomly),
    # priority above random tie-break.  Random bits live in the low 27
    # bits; prio shifts in units of 2^27; the member/candidate split in
    # 2^30 — all inside float32-exact... integers, so use int64-free
    # uint32 bucketed ranking.
    g = (scores >> 5).astype(jnp.uint32)         # 27 random bits
    rank_m = jnp.where(view >= 0, g[:a_width], jnp.uint32(0))
    rank_c = jnp.where(
        valid_c,
        g[a_width:] + jnp.uint32(1 << 30)
        + prio.astype(jnp.uint32) * jnp.uint32(1 << 27),
        jnp.uint32(0))
    score = jnp.concatenate([
        jnp.where(view >= 0, rank_m + jnp.uint32(1), jnp.uint32(0)),
        rank_c,
    ])
    # Only an actual admission triggers (shrink-to-cap) eviction; a
    # quiet round must not spontaneously evict an over-capacity view.
    n_keep = jnp.where(jnp.any(valid_c),
                       jnp.minimum(cap, a_width), a_width)
    vals, top = jax.lax.top_k(score, a_width)
    keep = (vals > 0) & (jnp.arange(a_width) < n_keep)
    ids_all = jnp.concatenate([view, cands])
    new_view = jnp.where(keep, ids_all[top], EMPTY)
    admitted = valid_c & jax.vmap(lambda x: contains(new_view, x))(cands)
    survived = jax.vmap(lambda x: contains(new_view, x))(view)
    evicted = jnp.where((view >= 0) & ~survived, view, EMPTY)
    return new_view, admitted, evicted


def bucket_slot(ids: Array, width: int) -> Array:
    """Stable bucket index for an id (see :func:`bucket_merge`)."""
    from partisan_tpu.faults import _mix32

    return (_mix32(jnp.asarray(ids, jnp.uint32))
            % jnp.uint32(width)).astype(jnp.int32)


def bucket_merge(view: Array, cands: Array, ranks: Array, self_id: Array,
                 exclude: Array | None = None) -> Array:
    """Merge candidates into an id-KEYED bucket cache view.

    TPU-native redesign of the passive-view merge
    (partisan_hyparview_peer_service_manager.erl:2569 merge_exchange /
    add_to_passive_view): instead of a set with uniform-random eviction,
    the view is a ``P``-bucket cache where id ``x`` always lives in slot
    ``mix32(x) % P``.  Insertion is a pure per-slot argmax — no sort, no
    pairwise dedupe — which is what the round's hot path needs (every
    sort costs milliseconds on the relay-attached TPU).  Semantics
    deviations, both benign for a healing-candidate cache: colliding ids
    evict each other deterministically instead of uniformly, and
    expected occupancy saturates at ~(1 - 1/e)·P rather than P.  Dedupe
    is inherent (same id → same slot).

    Args: view int32[P] (slot p holds -1 or an id with bucket p);
    cands int32[C] (-1 = none); ranks uint32[C] tie-break keys
    (ops/rng.rank32); exclude int32[E] ids barred from entry (e.g. the
    node's own active view).
    """
    p_width = view.shape[0]
    c_width = cands.shape[0]
    ok = (cands >= 0) & (cands != self_id)
    if exclude is not None:
        ok &= ~jnp.any(cands[:, None] == exclude[None, :], axis=1)
    slot = bucket_slot(cands, p_width)
    # Per-slot winner WITHOUT the [P, C] one-hot (vmapped it was an
    # [n, P, C] uint32 materialization — the round-cost meter priced it
    # the single largest intermediate of the manager phase): scatter-max
    # the `| 1`-lifted ranks into the P slots, then scatter-min the
    # candidate index among rank-winners, exactly reproducing the old
    # argmax's first-index tie-break.  Both scatters are commutative
    # (lint scatter-overlap clean); `| 1` keeps a hitting candidate's
    # rank nonzero so `best > 0` still means "some candidate hit".
    rank = jnp.where(ok, ranks | jnp.uint32(1), jnp.uint32(0))
    tgt = jnp.where(ok, slot, p_width)
    best = jnp.zeros((p_width,), jnp.uint32).at[tgt].max(rank,
                                                        mode="drop")
    is_best = ok & (rank == best[jnp.minimum(slot, p_width - 1)])
    idx = jnp.arange(c_width, dtype=jnp.int32)
    win = jnp.full((p_width,), c_width, jnp.int32).at[
        jnp.where(is_best, slot, p_width)].min(idx, mode="drop")
    has = best > 0
    return jnp.where(has, cands[jnp.minimum(win, c_width - 1)], view)


# (The former sequential merge_sample — and its env-gated batched
# variant that tripped a TPU kernel fault at 4k widths — are gone with
# their last caller: hot paths merge through admit / bucket_merge.)
