"""Plumtree epidemic broadcast trees (partisan_plumtree_broadcast.erl).

Reference behavior: per-root EAGER/LAZY peer sets carve a spanning tree
out of the overlay. A broadcast eager-pushes down tree links; receiving a
duplicate moves the sender to lazy and sends PRUNE (:843-857); lazy links
carry periodic I_HAVE adverts (flushed every lazy_tick, :990-1030); a
receiver missing an advertised message sends GRAFT, which re-activates the
link and re-sends the payload (:861-905); AAE exchanges with a random peer
every exchange_tick (:1040-1070), capped by
``broadcast_start_exchange_limit`` (partisan_config.erl:750-755).

TPU mapping (one tensor program per round, layered over ANY manager):

- payload semantics are PLUGGABLE via the broadcast-handler behaviour
  (models/handlers.py — partisan_plumtree_broadcast_handler.erl:47-78):
  the handler store is a slot table ``data int32[n, B, PW]`` merged by
  the handler's lattice join; ``merge``/``is_stale``/``graft``/
  ``exchange`` all derive from the handler.  The default
  :class:`~partisan_tpu.models.handlers.VersionHandler` is the
  heartbeat/version semantics of partisan_plumtree_backend.erl:191-260,
- eager/lazy sets become ``pruned bool[n, B, K]`` flags over the overlay's
  K neighbor slots: eager(b, k) = link k alive and not pruned for tree b.
  The reference keys trees by broadcast ROOT; we key by broadcast slot
  (identical while roots are distinct — a per-root tree cache is a later
  optimization). Overlay churn invalidates flags per link slot, which is
  the membership-update ``neighbors_down`` pruning (:910-950),
- per-round emission is bounded: ``push_slots`` fresh slots per node per
  round (excess carried over in ``need_push``) and ``lazy_cap`` I_HAVEs
  per lazy tick — the sim analogue of mailbox backpressure; I_HAVEs repeat
  every tick until acked by GRAFT or IGNORED_I_HAVE, the reference's
  outstanding-ETS retransmission contract (:880-905).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import Array

from partisan_tpu import faults as faults_mod
from partisan_tpu import managers as managers_mod
from partisan_tpu import types as T
from partisan_tpu.comm import LocalComm
from partisan_tpu.config import BROADCAST_CHANNEL, Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.models import handlers as handlers_mod
from partisan_tpu.ops import msg as msg_ops
from partisan_tpu.ops import plane as plane_ops
from partisan_tpu.ops import rng

_TAG_AAE = 401
_AAE_EDGE_TAG = 402


class PlumtreeState(NamedTuple):
    data: Array          # int32[n, B, PW] — handler store per slot
    rround: Array        # int32[n, B] — tree hop distance of our copy
    pruned: Array        # bool[n, B, K] — link k demoted to lazy for tree b
    lazy_pending: Array  # bool[n, B, K] — outstanding i_have to link k
    need_push: Array     # bool[n, B] — fresh slot awaiting eager push
    push_src: Array      # int32[n, B] — eager parent (excluded from push)
    tree_nbrs: Array     # int32[n, K] — link occupants flags refer to
    epoch: Array         # int32[n, B] — slot-recycle generation: the
    #                      reference keys trees by broadcast ROOT
    #                      (:118-160); slots are recycled under
    #                      sustained load, so each recycle (broadcast
    #                      with fresh=True) bumps the slot's epoch —
    #                      receivers adopting a higher epoch RESET the
    #                      slot's tree flags (the new root grows its own
    #                      tree) and stale-epoch traffic is ignored,
    #                      so two roots sharing a slot cannot conflate
    #                      trees.  The handler STORE is not reset: the
    #                      payload lattice is monotone across recycles
    #                      (a recycled broadcast must dominate — the
    #                      version bump / later timestamp / grown
    #                      counter all do), which keeps AAE exchange
    #                      epoch-oblivious and correct.  Epoch ADOPTION
    #                      rides eager/graft gossip, I_HAVE adverts AND
    #                      a scatter-max on the AAE exchange lane, so
    #                      AAE-satisfied nodes reset their flags in the
    #                      same round they pull recycled data, and a
    #                      node whose eager links were all pruned in the
    #                      old epoch is recruited by the first new-epoch
    #                      I_HAVE (it adopts, then grafts) instead of
    #                      waiting for the AAE walk.
    nonmono: Array       # int32[n] — detections of the monotone-recycle
    #                      constraint being VIOLATED: a new-epoch gossip
    #                      whose payload does not dominate the
    #                      receiver's store, or a broadcast(fresh=True)
    #                      whose payload does not dominate the
    #                      injecting node's slot.  The epoch design is
    #                      sound only while recycles dominate; this
    #                      counter turns a silent tree conflation into a
    #                      detectable event (telemetry.plumtree_metrics).


class Plumtree:
    name = "plumtree"

    def __init__(self, handler: handlers_mod.BroadcastHandler | None = None):
        self.handler = handler if handler is not None \
            else handlers_mod.VersionHandler()

    @property
    def prov_spec(self):
        """Provenance descriptor (provenance.py): PT_GOSSIP records
        carry [slot, payload×PW, hop, epoch] after the header — the hop
        word is the sender's tree depth (``rround``), the epoch word
        the slot-recycle generation the accumulator's reset tracks."""
        from partisan_tpu import provenance as provenance_mod

        PW = self.handler.payload_words
        return provenance_mod.ProvSpec(
            kind=int(T.MsgKind.PT_GOSSIP), slot_word=T.P0,
            hop_word=T.P1 + PW, epoch_word=T.P1 + PW + 1)

    def init(self, cfg: Config, comm: LocalComm) -> PlumtreeState:
        n, B = comm.n_local, cfg.max_broadcasts
        PW = self.handler.payload_words
        K = managers_mod.neighbor_width(cfg)
        # wire: gossip = [slot, payload×PW, hop, epoch]; header + 3 + PW
        need = T.HDR_WORDS + 3 + PW
        if cfg.msg_words < need:
            raise ValueError(
                f"plumtree with a {PW}-word handler payload needs "
                f"msg_words >= {need}, got {cfg.msg_words}")
        if cfg.inbox_cap > 1023:
            # the packed per-(tree, link) flag fold keeps one 10-bit
            # count field per condition (see step)
            raise ValueError(
                f"plumtree needs inbox_cap <= 1023, got {cfg.inbox_cap}")
        return PlumtreeState(
            data=jnp.broadcast_to(self.handler.bottom(),
                                  (n, B, PW)).astype(jnp.int32),
            rround=jnp.zeros((n, B), jnp.int32),
            pruned=jnp.zeros((n, B, K), jnp.bool_),
            lazy_pending=jnp.zeros((n, B, K), jnp.bool_),
            need_push=jnp.zeros((n, B), jnp.bool_),
            push_src=jnp.full((n, B), -1, jnp.int32),
            tree_nbrs=jnp.full((n, K), -1, jnp.int32),
            epoch=jnp.zeros((n, B), jnp.int32),
            nonmono=jnp.zeros((n,), jnp.int32),
        )

    # ------------------------------------------------------------------
    def step(self, cfg: Config, comm: LocalComm, state: PlumtreeState,
             ctx: RoundCtx, nbrs: Array) -> tuple[PlumtreeState, Array]:
        """One round, fully BATCHED over nodes × inbox slots.

        The reference processes one message at a time per gen_server; a
        per-slot ``lax.scan`` mirrors that but costs hundreds of small
        kernels per round (measured ~140 ms at 4k nodes).  Handler joins
        are (near-)commutative lattice ops, so the whole inbox folds in
        a handful of wide ops instead — a log-depth join tree for the
        store, one-hot matmul reductions (MXU) for the per-(tree, link)
        flags, and elementwise per-slot replies against the ROUND-START
        store.  Within-round ordering between conflicting flag updates
        resolves with unprune-precedence (graft/fresh-gossip/missing-
        ihave win over prune) — equivalent to SOME sequential order,
        which is all the reference's arbitrary mailbox interleaving
        guarantees.
        """
        pt = cfg.plumtree
        hd = self.handler
        W = cfg.msg_words
        PW = hd.payload_words
        n_local, B = state.data.shape[:2]
        K = nbrs.shape[1]
        S, L = pt.push_slots, pt.lazy_cap
        CH = cfg.channel_id(BROADCAST_CHANNEL)
        gids = comm.local_ids()

        # Overlay churn: a link slot with a new occupant sheds its flags
        # (neighbors_down/up membership handling, reference :910-950).
        changed = nbrs != state.tree_nbrs                       # [n, K]
        pruned = state.pruned & ~changed[:, None, :]
        lazyp = state.lazy_pending & ~changed[:, None, :]
        data, rr = state.data, state.rround
        npu, psrc = state.need_push, state.push_src

        inb = ctx.inbox.data                                    # [n, cap, W]
        cap = inb.shape[1]
        kind = inb[..., T.W_KIND]
        src = inb[..., T.W_SRC]
        b = jnp.clip(inb[..., T.P0], 0, B - 1)
        # Handler payload block as ONE dense [n, cap, PW] array: the
        # lattice joins/leq genuinely need the minor axis, and PW is a
        # couple of words — far below the record width, so this small
        # stack is not a wire interleave (the jaxpr budget guard keys
        # on full-record-width concatenates).
        pay = plane_ops.stack_words(inb, T.P1, T.P1 + PW)       # [n, cap, PW]
        mr = inb[..., T.P1 + PW]
        ep_w = inb[..., T.P1 + PW + 1]                          # [n, cap]
        is_g = kind == T.MsgKind.PT_GOSSIP
        is_ih = kind == T.MsgKind.PT_IHAVE
        is_gr = kind == T.MsgKind.PT_GRAFT
        is_pr = kind == T.MsgKind.PT_PRUNE
        is_ak = kind == T.MsgKind.PT_IHAVE_ACK

        # ---- the main-body gate: everything between here and the AAE
        # stage (epoch guard, gossip fold, flag updates, replies, eager
        # push, lazy flush) only matters when plumtree traffic exists or
        # is pending somewhere — one cross-shard lax.cond skips it for
        # rounds where the broadcast layer is idle (e.g. a settled
        # overlay between broadcasts, or a pure-membership phase).
        pt_go_local = (jnp.any(is_g | is_ih | is_gr | is_pr | is_ak)
                       | jnp.any(npu)
                       | jnp.any(lazyp & (nbrs >= 0)[:, None, :]))
        pt_go = comm.allsum(pt_go_local.astype(jnp.int32)) > 0
        # Emission blocks (replies / eager pushes / i_haves) stay a
        # TUPLE through the cond and the step return — round_body
        # concatenates the round's emission stack exactly once
        # (plane_ops.blocks_of).
        PT_SHAPES = (cap, S * K, L)

        def pt_skip(_):
            return (data, rr, pruned, lazyp, npu, psrc, state.epoch,
                    state.nonmono,
                    tuple(msg_ops.zero_stack(cfg, (n_local, k))
                          for k in PT_SHAPES))

        def pt_body(_, data=data, rr=rr, pruned=pruned, lazyp=lazyp,
                    npu=npu, psrc=psrc, is_g=is_g, is_ih=is_ih,
                    is_gr=is_gr, is_pr=is_pr, is_ak=is_ak):

            # ---- slot-epoch guard (per-root trees, :118-160) ----------
            # A higher epoch on gossip OR an i_have advert re-keys the slot
            # to its new root: adopt it, RESET the tree flags (the new
            # root's tree forms from scratch), and ignore every message
            # stamped with an older epoch — late traffic from the recycled
            # tree cannot prune/graft/advertise into the new one.  I_HAVE
            # adoption is the lazy-repair recruit path: a node whose eager
            # links were all pruned in the OLD epoch sees only adverts, so
            # without it the recycled slot could not graft it back in until
            # the AAE walk found it.  One scatter-max instead of an
            # [n, cap, B] where+reduce: epochs are the only slot-keyed MAX
            # on the hot path and the materialized one-hot cost ~12% of the
            # 32k round.
            r2e = jnp.broadcast_to(
                jnp.arange(n_local, dtype=jnp.int32)[:, None], b.shape)
            tgt_ep = state.epoch.at[
                r2e, jnp.where(is_g | is_ih, b, B)].max(ep_w, mode="drop")
            bumped = tgt_ep > state.epoch                           # [n, B]
            # ONE packed take serves every round-start B-axis read
            # (store, rround, epoch): cross-slot gathers price the round
            # on this backend (tools/profile_phases.py), and the three
            # separate takes cost ~3x this fused one.
            pre = jnp.concatenate(
                [data, state.rround[:, :, None], state.epoch[:, :, None]],
                axis=-1)                                    # [n, B, PW+2]
            pre_b = jnp.take_along_axis(pre, b[:, :, None], axis=1)
            data_b = pre_b[..., :PW]                        # [n, cap, PW]
            rr_b = pre_b[..., PW]                           # [n, cap]
            old_ep_b = pre_b[..., PW + 1]                   # [n, cap]
            bump_g = is_g & (ep_w > old_ep_b)   # raw mask, pre-epoch-filter
            pruned = pruned & ~bumped[:, :, None]
            lazyp = lazyp & ~bumped[:, :, None]
            rr = jnp.where(bumped, 0, rr)
            psrc = jnp.where(bumped, -1, psrc)
            ep_b = jnp.take_along_axis(tgt_ep, b, axis=1)           # [n, cap]
            cur_ep = ep_w == ep_b
            is_g = is_g & cur_ep
            is_ih = is_ih & cur_ep
            is_gr = is_gr & cur_ep
            is_pr = is_pr & cur_ep
            is_ak = is_ak & cur_ep

            # sender's link slot (slot_of): [n, cap]
            hit = (nbrs[:, None, :] == src[:, :, None]) & (src >= 0)[:, :, None]
            ks_ok = hit.any(-1)
            ki = jnp.argmax(hit, -1)

            # Monotone-recycle constraint check: an epoch-bumping gossip
            # whose payload does NOT dominate the receiver's store means
            # the recycled broadcast broke the lattice contract the
            # epoch-oblivious store depends on — count it (never silent).
            nonmono = state.nonmono + jnp.sum(
                bump_g & ~hd.leq(data_b, pay), axis=1, dtype=jnp.int32)

            # ---- gossip merge (handler join fold, Mod:merge :571-577) --
            stale_g = is_g & hd.leq(pay, data_b)                    # is_stale
            if isinstance(hd, handlers_mod.MaxJoinHandler):
                # Elementwise-max joins fold as ONE scatter-max instead of
                # materializing the [n, cap, B, PW] expansion + log-depth
                # tree (BENCH_NOTES corrected cost model; exact same
                # result: integer max is associative/commutative).  The
                # scatter target starts from the handler's bottom() — the
                # same padding contract the tree_fold path honors.
                joined_in = (jnp.broadcast_to(hd.bottom(), (n_local, B, PW))
                             .astype(jnp.int32).at[
                    r2e, jnp.where(is_g, b, B)].max(pay, mode="drop"))
            else:
                oh_b = (b[:, :, None]
                        == jnp.arange(B)[None, None, :])            # [n, cap, B]
                gmask = (oh_b & is_g[:, :, None])                   # [n, cap, B]
                expanded = jnp.where(gmask[..., None], pay[:, :, None, :],
                                     hd.bottom())                   # [n,cap,B,PW]
                joined_in = handlers_mod.tree_fold(hd, expanded, axis=1)
            fresh_any = ~hd.leq(joined_in, data)                    # [n, B]

            # Winner per (tree, round): prefer the first slot whose payload
            # EQUALS the fold (for max-joins that is the old "first slot
            # carrying the max version"); if payloads are incomparable (no
            # slot equals the fold) fall back to the first non-stale slot.
            # All other gossip senders for the tree count as stale — under
            # any sequential interleaving the first delivery wins and later
            # ones are duplicates whose senders get pruned to lazy.
            joined_b = jnp.take_along_axis(joined_in, b[:, :, None], axis=1)
            eq_fold = jnp.all(pay == joined_b, axis=-1)             # [n, cap]
            win_ns = is_g & ~stale_g
            slot_c = jnp.arange(cap)[None, :]

            # Winner per (tree, round) as ONE packed scatter-min: key =
            # slot, plus ``cap`` for non-eq_fold candidates, so a slot
            # whose payload EQUALS the fold always beats a fallback
            # slot, and within each class the first slot wins — exactly
            # the first_pref-else-first_ns selection the previous two
            # scatter-mins computed, in one scatter.
            keyp = jnp.broadcast_to(slot_c, b.shape) \
                + jnp.where(eq_fold, 0, cap)
            packed = jnp.full((n_local, B), 2 * cap, jnp.int32).at[
                r2e, jnp.where(win_ns, b, B)].min(keyp, mode="drop")
            got = packed < 2 * cap                                  # [n, B]
            chosen_c = jnp.minimum(
                jnp.where(packed >= cap, packed - cap, packed), cap - 1)
            chosen = jnp.where(got, chosen_c, cap)                  # [n, B]
            chosen_b = jnp.take_along_axis(chosen, b, axis=1)       # [n, cap]
            win = win_ns & (slot_c == chosen_b)
            # Non-winners demote ONLY if stale under the "winner delivered
            # first" interleaving: pay <= join(store, winner's payload) —
            # a valid sequential order.  Two concurrent INCOMPARABLE
            # payloads (e.g. distinct G-counter actors) both stay eager,
            # matching the reference where a non-stale Mod:merge keeps the
            # sender eager (:843-857); equal/dominated duplicates prune.
            # The winner's payload is gathered straight at each SLOT's
            # tree (one [n, cap, PW] take — no [n, B, PW] intermediate).
            after_win = hd.join(data_b, jnp.where(
                (chosen_b < cap)[:, :, None],
                jnp.take_along_axis(
                    pay, jnp.minimum(chosen_b, cap - 1)[:, :, None],
                    axis=1),
                hd.bottom()))                                  # [n, cap, PW]
            stale_g = stale_g | (is_g & ~win & hd.leq(pay, after_win))
            # the winner's (hop count, sender) in ONE packed take
            ms_win = jnp.take_along_axis(
                jnp.stack([mr, src], axis=-1), chosen_c[:, :, None],
                axis=1)                                     # [n, B, 2]
            mr_win = jnp.where(got, ms_win[..., 0], -1)
            src_win = jnp.where(got, ms_win[..., 1], -1)
            data = hd.join(data, joined_in)
            rr = jnp.where(fresh_any, mr_win + 1, rr)
            npu = npu | fresh_any
            psrc = jnp.where(fresh_any, src_win, psrc)

            # ---- per-(tree, link) flags -------------------------------
            missing_ih = is_ih & ~hd.leq(pay, data_b)
            # Three any-hit folds over (tree, link slot) in ONE packed
            # scatter-add: each condition keeps its own 10-bit count
            # field (cap <= 1023, validated in init), scattered at
            # (b, ki) with non-neighbor senders dropped.  Integer sums
            # are exact, so the >0 tests reproduce the previous one-hot
            # MXU folds' booleans bit for bit — minus the [n, cap, B] +
            # [n, cap, K] bfloat16 one-hot materializations the
            # round-cost meter priced as the model phase's largest
            # block.  scatter-add is commutative: lint-clean overlap.
            c_pr = is_pr | stale_g
            c_un = is_gr | missing_ih | (is_g & ~stale_g)
            c_ak = is_gr | is_ak
            packed_c = (c_pr.astype(jnp.int32)
                        + (c_un.astype(jnp.int32) << 10)
                        + (c_ak.astype(jnp.int32) << 20))
            acc = jnp.zeros((n_local, B, K), jnp.int32).at[
                r2e, b, jnp.where(ks_ok, ki, K)].add(packed_c,
                                                     mode="drop")
            # Field tests without unpacking: counts can't carry across
            # the 10-bit fields (each <= cap <= 1023), so mask-in-place
            # reads field 2 and the top field needs no mask at all
            # (acc < 2**30 keeps the arithmetic shift positive) — two
            # fewer full [n, B, K] intermediates, same booleans.
            prune_req = (acc & 1023) > 0
            unprune = (acc & (1023 << 10)) != 0
            pruned = (pruned | prune_req) & ~unprune
            lazyp = lazyp & ~((acc >> 20) > 0)

            # ---- per-slot replies (against the round-start store) -----
            present_b = hd.present(data_b)                          # [n, cap]
            rep_kind = jnp.select(
                [stale_g, missing_ih, is_ih & ~missing_ih,
                 is_gr & present_b],
                [jnp.int32(T.MsgKind.PT_PRUNE), jnp.int32(T.MsgKind.PT_GRAFT),
                 jnp.int32(T.MsgKind.PT_IHAVE_ACK),
                 jnp.int32(T.MsgKind.PT_GOSSIP)], 0)
            # graft replies serve the ROUND-START (payload, hop-count)
            # pair — rr_b rode the packed pre-merge take above, matching
            # the pre-merge data_b
            # payload: i_have-derived replies (graft/ack) echo the advert
            # (Mod:graft is keyed by the advertised id); gossip replies
            # serve the store
            rep_pay = jnp.where(is_ih[..., None], pay, data_b)      # [n, cap, PW]
            replies = msg_ops.build(
                cfg, rep_kind, gids[:, None],
                jnp.where(rep_kind > 0, src, -1), channel=CH,
                payload=(b, *jnp.unstack(rep_pay, axis=-1),
                         jnp.where(is_gr, rr_b, 0), ep_b))

            # ---- eager push: up to S carried-over fresh slots ----------
            pend = npu & hd.present(data)
            prio = jnp.where(pend, B - jnp.arange(B)[None, :], 0)
            pv, sel = jax.lax.top_k(prio, S)                        # [n, S]
            sel_ok = pv > 0
            rows = jnp.arange(n_local)[:, None]
            pruned_sel = pruned[rows, sel]                          # [n, S, K]
            live_k = (nbrs >= 0)[:, None, :]                        # [n, 1, K]
            # post-merge (store, rround, epoch, push_src) in ONE packed
            # gather — the lazy flush below reuses the same pack
            post = jnp.concatenate(
                [data, rr[:, :, None], tgt_ep[:, :, None],
                 psrc[:, :, None]], axis=-1)                # [n, B, PW+3]
            post_sel = post[rows, sel]                      # [n, S, PW+3]
            psrc_sel = post_sel[..., PW + 2]                # [n, S]
            eager = live_k & ~pruned_sel & (nbrs[:, None, :]
                                            != psrc_sel[:, :, None])
            gov_cut = None
            if cfg.control.fanout:
                # Fanout governor (control.py): bound this push's eager
                # set to the round-start budget ctx.control carries —
                # links beyond it take the lazy I_HAVE path below (a
                # pruned link's exact wire behavior), so the cut is
                # reversible per round and survives the slot-recycle
                # epoch resets that wipe the learned ``pruned`` flags.
                with jax.named_scope("round.control.fanout"):
                    gov_cap = ctx.control.fanout.eager_cap
                    erank = jnp.cumsum(eager, axis=-1) - 1
                    gov_cut = eager & (erank >= gov_cap)
                    eager = eager & ~gov_cut
            dst = jnp.where(sel_ok[:, :, None] & eager, nbrs[:, None, :], -1)
            data_sel = post_sel[..., :PW]                   # [n, S, PW]
            push_msgs = msg_ops.build(
                cfg, T.MsgKind.PT_GOSSIP, gids[:, None, None], dst, channel=CH,
                payload=(sel[:, :, None],
                         *(w[:, :, None] for w in jnp.unstack(data_sel, axis=-1)),
                         post_sel[..., PW][:, :, None],
                         post_sel[..., PW + 1][:, :, None]),
            ).reshape(n_local, S * K, W)
            lazy_sel = pruned_sel if gov_cut is None \
                else pruned_sel | gov_cut
            lazy_new = sel_ok[:, :, None] & live_k & lazy_sel       # [n, S, K]
            oh_sel = (sel[:, :, None] == jnp.arange(B)[None, None, :])
            lazyp = lazyp | (jnp.einsum(
                "nsb,nsk->nbk", oh_sel.astype(jnp.bfloat16),
                lazy_new.astype(jnp.bfloat16)) > 0.5)
            pushed_b = jnp.any(oh_sel & sel_ok[:, :, None], axis=1)  # [n, B]
            npu = npu & ~pushed_b

            # ---- lazy tick: flush up to L outstanding i_haves ----------
            fire = ((ctx.rnd + gids) % cfg.lazy_tick_every == 0)     # [n]
            flat = (lazyp & (nbrs >= 0)[:, None, :]).reshape(n_local, B * K)
            lprio = jnp.where(flat & fire[:, None],
                              B * K - jnp.arange(B * K)[None, :], 0)
            lv, li = jax.lax.top_k(lprio, L)                         # [n, L]
            bi, kix = li // K, li % K
            adv_pack = jnp.take_along_axis(post, bi[:, :, None],
                                           axis=1)       # [n, L, PW+3]
            ihave_msgs = msg_ops.build(
                cfg, T.MsgKind.PT_IHAVE, gids[:, None],
                jnp.where(lv > 0, nbrs[rows, kix], -1), channel=CH,
                payload=(bi, *jnp.unstack(adv_pack[..., :PW], axis=-1),
                         jnp.zeros_like(bi),
                         adv_pack[..., PW + 1]))

            return (data, rr, pruned, lazyp, npu, psrc, tgt_ep, nonmono,
                    (replies, push_msgs, ihave_msgs))

        (data, rr, pruned, lazyp, npu, psrc, tgt_ep, nonmono,
         emitted) = jax.lax.cond(pt_go, pt_body, pt_skip, 0)

        # ---- AAE exchange tick (Mod:exchange, :1040-1070): push the
        # whole store to up to ``exchange_limit`` random peers on the
        # monotonic state lane (the reference caps concurrently started
        # exchanges per node, default 1 — partisan_config.erl:750-755).
        # Handlers that don't support exchange (non-max joins) ignore it,
        # exactly like the reference's default backend
        # (partisan_plumtree_backend.erl:22-35).  The reference exchange
        # is a session between two nodes; the one-way periodic push
        # converges identically under symmetric firing.
        if pt.aae and hd.supports_exchange:
            # The whole AAE stage runs under ONE lax.cond: most rounds
            # have no fresh links and (with aligned timers,
            # Config.timer_stagger=False) no exchange tick due, so the
            # exchange scatter is skipped outright.  The predicate is a
            # cross-shard allsum — exchange_with_epochs contains
            # collectives, so every shard must take the same branch.
            # The AAE tick stays PER-NODE STAGGERED even under aligned
            # timers (cfg.timer_stagger=False): anti-entropy is the
            # last-mile repair for broadcast stragglers, and aligning
            # it makes a straggler wait up to a full exchange interval
            # — measured +10 convergence rounds at 32k for a ~0.5 s
            # saving, a bad trade.  The gate still skips the stage when
            # the walk is disabled and no links changed.
            hand_any = jnp.any(changed & (nbrs >= 0))
            go_local = hand_any
            if pt.exchange_limit > 0:
                fires = ((ctx.rnd + gids)
                         % cfg.exchange_tick_every == 0) & ctx.alive
                go_local = go_local | jnp.any(fires)
            aae_go = comm.allsum(go_local.astype(jnp.int32)) > 0

            def aae_body(_):
                # Connect-time state exchange: a link slot with a NEW
                # occupant gets the whole store pushed along it this
                # round — the reference's anti-entropy handshake
                # ({state, Tag, LocalState} on every fresh connection,
                # partisan_peer_service_server.erl:150-172).  Without
                # it a late (re)joiner waits on the random AAE walk to
                # stumble onto it (measured ~60+ rounds for the last 14
                # of 100k).  It is a handshake, not a periodic
                # exchange, so it fires even when exchange_limit=0
                # disables the random AAE walk (the reference handshake
                # is unconditional on connect).
                #
                # The handshake push is K links wide but fires only
                # when some link CHANGED occupant — never on a settled
                # overlay — so it runs under its own inner gate and the
                # per-round cost is the tick push's [n, exchange_limit]
                # scatter alone (~1/(K+1) of the fused-scatter
                # traffic).  Both pulls read the same round-start
                # store, so the split is exactly the previous single
                # concatenated scatter when both fire.
                def hand_pull(_):
                    tgt = jnp.where(changed & (nbrs >= 0)
                                    & ctx.alive[:, None], nbrs, -1)
                    tgt = faults_mod.filter_edges(
                        ctx.faults, gids, tgt, ctx.seed, ctx.rnd,
                        _AAE_EDGE_TAG)
                    return hd.exchange_with_epochs(comm, data, tgt_ep,
                                                   tgt)

                def hand_skip(_):
                    return (jnp.broadcast_to(hd.bottom(), data.shape)
                            .astype(data.dtype),
                            jnp.zeros_like(tgt_ep))

                if pt.exchange_limit > 0:
                    # hand_any is the [local] predicate already computed
                    # for the outer gate; with the walk disabled the
                    # outer gate IS the handshake gate and the inner
                    # cond would be always-true
                    hand_go = comm.allsum(hand_any.astype(jnp.int32)) > 0
                    pulled, pulled_ep = jax.lax.cond(hand_go, hand_pull,
                                                     hand_skip, 0)
                else:
                    pulled, pulled_ep = hand_pull(0)
                # Slot epochs ride the SAME exchange edges as the store
                # (fused into one scatter for stock max-join handlers —
                # handlers.exchange_with_epochs): a node whose data
                # arrives via AAE adopts the recycled epoch — and
                # resets its tree flags — in the same round instead of
                # waiting for the next eager wave.  Safe because the
                # store is lattice-monotone across recycles (adoption
                # never discards data).
                if pt.exchange_limit > 0:
                    def pick(key, row, fire):
                        slots = rng.choice_slots(
                            rng.subkey(key, _TAG_AAE), row >= 0,
                            pt.exchange_limit)
                        t = jnp.where(slots >= 0, row[slots],
                                      jnp.int32(-1))
                        return jnp.where(fire, t, jnp.int32(-1))

                    tick_tgt = jax.vmap(pick)(ctx.keys, nbrs, fires)
                    tick_tgt = faults_mod.filter_edges(
                        ctx.faults, gids, tick_tgt, ctx.seed, ctx.rnd,
                        _AAE_EDGE_TAG)
                    p_t, ep_t = hd.exchange_with_epochs(
                        comm, data, tgt_ep, tick_tgt)
                    pulled = hd.join(pulled, p_t)
                    pulled_ep = jnp.maximum(pulled_ep, ep_t)
                data2 = hd.join(
                    data, jnp.where(ctx.alive[:, None, None],
                                    pulled, hd.bottom()))
                aae_bump = ctx.alive[:, None] & (pulled_ep > tgt_ep)
                return (data2,
                        pruned & ~aae_bump[:, :, None],
                        lazyp & ~aae_bump[:, :, None],
                        jnp.where(aae_bump, 0, rr),
                        jnp.where(aae_bump, -1, psrc),
                        jnp.maximum(tgt_ep,
                                    jnp.where(ctx.alive[:, None],
                                              pulled_ep, 0)))

            def aae_skip(_):
                return data, pruned, lazyp, rr, psrc, tgt_ep

            data, pruned, lazyp, rr, psrc, tgt_ep = jax.lax.cond(
                aae_go, aae_body, aae_skip, 0)

        # Crash-stopped nodes are frozen and silent.
        dead = ~ctx.alive

        def keep(new, old):
            return jnp.where(
                dead.reshape((-1,) + (1,) * (new.ndim - 1)), old, new)

        emitted = tuple(
            b.at[..., T.W_KIND].set(
                jnp.where(dead[:, None], 0, b[..., T.W_KIND]))
            for b in emitted)
        new_state = PlumtreeState(
            data=keep(data, state.data),
            rround=keep(rr, state.rround),
            pruned=keep(pruned, state.pruned),
            lazy_pending=keep(lazyp, state.lazy_pending),
            need_push=keep(npu, state.need_push),
            push_src=keep(psrc, state.push_src),
            tree_nbrs=keep(nbrs, state.tree_nbrs),
            epoch=keep(tgt_ep, state.epoch),
            nonmono=keep(nonmono, state.nonmono),
        )
        return new_state, emitted

    # ---- scenario helpers (broadcast/2, partisan.erl:1556) -----------
    def broadcast(self, state: PlumtreeState, node: int, slot: int,
                  version=1, *, fresh: bool = False) -> PlumtreeState:
        """Inject a broadcast: Mod:broadcast_data — id = (node, slot),
        payload = handler vector (``version`` may be an int for the
        default handler or a payload sequence/dict for richer ones).

        ``fresh=True`` marks a NEW logical broadcast RECYCLING the slot
        (a different root, or the same root starting a new message):
        the slot's epoch bumps, so every node adopting it re-grows the
        tree for this root instead of inheriting the previous
        broadcast's eager/lazy shape (the reference's per-root keying,
        partisan_plumtree_broadcast.erl:118-160).  The payload must
        dominate the slot's previous store (monotone lattice across
        recycles) — version bumps, later timestamps and grown counters
        all qualify."""
        vec = self.handler.payload(version)
        merged = self.handler.join(state.data[node, slot], vec)
        st = state._replace(
            data=state.data.at[node, slot].set(merged),
            need_push=state.need_push.at[node, slot].set(True),
            push_src=state.push_src.at[node, slot].set(-1),
        )
        if fresh:
            # Detect a recycle that breaks the monotone-lattice
            # contract at the injection point (the payload must
            # dominate the slot's previous store); receivers detect
            # the same condition in-round (see ``nonmono`` in step).
            dom = self.handler.leq(state.data[node, slot], vec)
            st = st._replace(
                epoch=st.epoch.at[node, slot].add(1),
                pruned=st.pruned.at[node, slot].set(False),
                lazy_pending=st.lazy_pending.at[node, slot].set(False),
                rround=st.rround.at[node, slot].set(0),
                nonmono=st.nonmono.at[node].add(
                    jnp.where(dom, 0, 1).astype(jnp.int32)),
            )
        return st

    def coverage(self, state: PlumtreeState, alive: Array, slot: int,
                 version=1) -> Array:
        """Fraction of live nodes whose store dominates the target
        payload for ``slot``."""
        target = self.handler.payload(version)
        have = self.handler.leq(target, state.data[:, slot]) & alive
        return jnp.sum(have) / jnp.maximum(jnp.sum(alive), 1)

    def eager_degree(self, state: PlumtreeState, slot: int) -> Array:
        """Mean eager out-degree for a tree — flood = overlay degree,
        converged tree ~ spanning-tree degree (debug_get_tree analogue,
        partisan_plumtree_broadcast.erl:179-188)."""
        live = state.tree_nbrs >= 0
        eager = live & ~state.pruned[:, slot, :]
        return jnp.sum(eager) / state.data.shape[0]
