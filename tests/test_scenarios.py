"""Driver-config scenario tests (BASELINE.md benchmark configs 1-5),
run at CPU-smoke scale — the same code paths the TPU benchmark runs."""

from partisan_tpu import scenarios


def test_config1_anti_entropy():
    r = scenarios.config1_anti_entropy(n=16)
    assert r["convergence_rounds"] > 0
    assert r["rounds_per_sec"] > 0


def test_config2_rumor():
    r = scenarios.config2_rumor(n=96)
    assert r["infection_rounds"] > 0, r
    assert 0.5 <= r["coverage_plateau"] <= 1.0, r


def test_config3_plumtree_drop():
    r = scenarios.config3_plumtree_drop(n=128)
    assert r["repair_rounds"] > 0, r


def test_config4_scamp_churn():
    r = scenarios.config4_scamp_churn(n=128, rounds=60)
    assert r["alive"] > 0
    assert r["partial_view_mean"] > 1.0, r


def test_config5_causal_crash():
    r = scenarios.config5_causal_crash(n=128, senders=8, crashes=4)
    assert r["convergence_rounds"] > 0, r
    # any-node senders: every receiver delivered its sender's two
    # messages, per-edge FIFO, exactly once
    assert r["causal_deliveries"] == r["causal_expected"], r
    assert r["fifo_ok_receivers"] == r["n_receivers"], r
