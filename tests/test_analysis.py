"""Causality-analysis tests (reference src/partisan_analysis.erl +
annotations/ files): reaction graphs, background classification,
schedule-equivalence pruning."""

from partisan_tpu import analysis, trace as trace_mod
from partisan_tpu.cluster import Cluster
from partisan_tpu.models.direct_mail import DirectMail
from partisan_tpu.models.anti_entropy import AntiEntropy
from tests.support import fm_config, boot_fullmesh

N = 6


def _trace(model_cls, acked=False, rounds=12, seed=9):
    cfg = fm_config(N, seed=seed, ack_cap=8 if acked else 0)
    model = model_cls(acked=acked) if model_cls is DirectMail else model_cls()
    cl = Cluster(cfg, model=model)
    st = boot_fullmesh(cl)
    st = st._replace(model=model.broadcast(st.model, 0, 0))
    _, cap = cl.record(st, rounds)
    return trace_mod.from_capture(cap)


def test_acked_mail_reaction_graph_has_app_to_ack():
    tr = _trace(DirectMail, acked=True)
    g = analysis.reaction_graph(tr)
    # Receiving an acked APP mail causes an ACK emission.
    assert "ACK" in g.get("APP", set()), g


def test_background_vs_reactive_classification():
    tr = _trace(AntiEntropy)
    bg = analysis.background_kinds(tr)
    # Anti-entropy pushes are timer-driven: APP appears as background.
    assert "APP" in bg


def test_closure_and_prunable():
    g = {"A": {"B"}, "B": {"C"}, "D": set()}
    c = analysis.closure(g)
    assert c["A"] == {"B", "C"}
    assert not analysis.prunable(g, "A", "C")   # A can reach C
    assert analysis.prunable(g, "D", "C")       # D cannot
    assert not analysis.prunable(g, "C", "C")   # same kind never pruned


def test_annotations_roundtrip(tmp_path):
    tr = _trace(DirectMail, acked=True)
    p = tmp_path / "partisan-annotations-direct_mail.json"
    analysis.save_annotations(tr, p, protocol="demers_direct_mail_acked")
    doc = analysis.load_annotations(p)
    assert "APP" in doc["causality"]
    assert isinstance(doc["causality"]["APP"], set)
    assert isinstance(doc["background"], set)
