"""Node/process monitoring (reference src/partisan_monitor.erl).

Reference behavior: ``partisan:monitor/2`` records monitor refs in ETS
tables (in/out directions, partisan_monitor.erl:40-70, :460-475); the
manager's ``on_up``/``on_down`` callbacks fire ``{'DOWN', Ref, process,
Pid, Reason}`` signals to monitor owners and ``{nodedown, Node}`` /
``{nodeup, Node}`` messages to ``monitor_nodes`` subscribers.  The
failure detector is the TCP connection itself (README.md:66-70).

Sim mapping: the alive mask IS the ground truth the connection layer
would reveal; detection is modeled with one round of latency (the EXIT
signal propagation).  State carries who-monitors-whom matrices and
sticky signal flags the host consumes; monitors are one-shot (a fired
monitor is removed, matching erlang:monitor semantics), node
subscriptions persist and deliver both nodedown and nodeup.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import Array

from partisan_tpu.comm import LocalComm
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import msg as msg_ops


class MonitorState(NamedTuple):
    monitors: Array    # bool[n_local, n_global] — one-shot DOWN monitors
    node_subs: Array   # bool[n_local] — monitor_nodes subscription
    prev_alive: Array  # bool[n_global] — last round's liveness view
    down_sig: Array    # bool[n_local, n_global] — pending DOWN signals
    nodedown: Array    # bool[n_local, n_global] — pending nodedown msgs
    nodeup: Array      # bool[n_local, n_global] — pending nodeup msgs
    # Edge (channel) monitoring — the reference's channel-down
    # machinery: a connection EXIT prunes the registry and fires
    # channel-down callbacks once a peer's conn count hits 0 while the
    # node may still be up (partisan_pluggable_peer_service_manager.erl
    # :1489-1535; the on_down/3 channel variant of the behaviour).  The
    # sim's per-edge "all channels to peer X down" signal is edge
    # unreachability: peer crashed OR the (owner, peer) edge partitioned.
    edge_subs: Array   # bool[n_local, n_global] — persistent edge subs
    prev_reach: Array  # bool[n_local, n_global] — last round's edge view
    edge_down: Array   # bool[n_local, n_global] — pending edge-down
    edge_up: Array     # bool[n_local, n_global] — pending edge-up


class MonitorService:
    """Stackable model.  Emits no wire messages: liveness transitions are
    observed from the fault state (the sim's failure detector), exactly
    one round after they occur."""

    name = "monitor"

    def init(self, cfg: Config, comm: LocalComm) -> MonitorState:
        n, g = comm.n_local, comm.n_global
        zb = jnp.zeros((n, g), jnp.bool_)
        return MonitorState(
            monitors=zb, node_subs=jnp.zeros((n,), jnp.bool_),
            prev_alive=jnp.ones((g,), jnp.bool_),
            down_sig=zb, nodedown=zb, nodeup=zb,
            edge_subs=zb, prev_reach=jnp.ones((n, g), jnp.bool_),
            edge_down=zb, edge_up=zb)

    def step(self, cfg: Config, comm: LocalComm, st: MonitorState,
             ctx: RoundCtx, nbrs: Array) -> tuple[MonitorState, Array]:
        galive = ctx.faults.alive
        went_down = st.prev_alive & ~galive       # [n_global]
        came_up = ~st.prev_alive & galive

        alive_row = ctx.alive[:, None]
        fired = st.monitors & went_down[None, :] & alive_row
        down_sig = st.down_sig | fired
        monitors = st.monitors & ~fired           # one-shot
        nodedown = st.nodedown | (
            st.node_subs[:, None] & went_down[None, :] & alive_row)
        nodeup = st.nodeup | (
            st.node_subs[:, None] & came_up[None, :] & alive_row)

        # edge (channel-down) monitoring: reach(i, j) = both alive and
        # the edge not partitioned — the sim's "some connection to j
        # exists" ground truth (stochastic link_drop is message loss,
        # not a connection state, so it does not enter here)
        gids = comm.local_ids()
        part = ctx.faults.partition
        if part.ndim == 2:
            cut = jax.lax.dynamic_slice(
                part, (comm.node_offset, 0),
                (comm.n_local, comm.n_global))
        else:
            cut = part[gids][:, None] != part[None, :]
        # prev_reach tracks the PURE edge state (peer alive, edge
        # uncut) — the owner's own liveness only gates event DELIVERY.
        # Folding owner aliveness into the tracked state would make an
        # owner crash+recover read as a spurious edge_up with no
        # matching edge_down.
        reach = galive[None, :] & ~cut
        edge_down = st.edge_down | (
            st.edge_subs & st.prev_reach & ~reach & alive_row)
        edge_up = st.edge_up | (
            st.edge_subs & ~st.prev_reach & reach & alive_row)

        emitted = msg_ops.zero_stack(cfg, (comm.n_local, 0))
        return MonitorState(
            monitors=monitors, node_subs=st.node_subs, prev_alive=galive,
            down_sig=down_sig, nodedown=nodedown, nodeup=nodeup,
            edge_subs=st.edge_subs, prev_reach=reach,
            edge_down=edge_down, edge_up=edge_up), emitted

    # ---- host-side API ------------------------------------------------
    def monitor(self, st: MonitorState, owner: int, target: int
                ) -> MonitorState:
        """partisan:monitor/2 — one-shot DOWN monitor on ``target``.  A
        monitor on an already-known-dead node fires immediately (the
        reference's noproc DOWN, partisan_monitor.erl)."""
        if not bool(st.prev_alive[target]):
            return st._replace(
                down_sig=st.down_sig.at[owner, target].set(True))
        return st._replace(monitors=st.monitors.at[owner, target].set(True))

    def demonitor(self, st: MonitorState, owner: int, target: int,
                  flush: bool = True, info: bool = False):
        """erlang:demonitor options: ``flush`` also removes an
        already-pending DOWN signal (without it, a DOWN that fired
        before the demonitor is still delivered — the default OTP
        behavior is flush=false; the sim's historical default flushed,
        kept for compatibility); ``info=True`` additionally returns
        whether a monitor was actually removed."""
        existed = bool(st.monitors[owner, target])
        st = st._replace(monitors=st.monitors.at[owner, target].set(False))
        if flush:
            st = st._replace(
                down_sig=st.down_sig.at[owner, target].set(False))
        return (st, existed) if info else st

    # ---- edge (channel-down) subscriptions ----------------------------
    def monitor_edge(self, st: MonitorState, owner: int, peer: int,
                     flag: bool = True) -> MonitorState:
        """Subscribe ``owner`` to connectivity transitions of its edge
        to ``peer`` (the channel-down/up callback registration; the
        reference's on_down/3 with a channel argument).  Persistent —
        delivers both edge_down and edge_up until unsubscribed."""
        return st._replace(
            edge_subs=st.edge_subs.at[owner, peer].set(flag))

    @staticmethod
    def take_edge_down(st: MonitorState, owner: int, peer: int
                       ) -> tuple[MonitorState, bool]:
        got = bool(st.edge_down[owner, peer])
        return st._replace(
            edge_down=st.edge_down.at[owner, peer].set(False)), got

    @staticmethod
    def take_edge_up(st: MonitorState, owner: int, peer: int
                     ) -> tuple[MonitorState, bool]:
        got = bool(st.edge_up[owner, peer])
        return st._replace(
            edge_up=st.edge_up.at[owner, peer].set(False)), got

    def monitor_nodes(self, st: MonitorState, node: int,
                      flag: bool = True) -> MonitorState:
        """net_kernel:monitor_nodes analogue."""
        return st._replace(node_subs=st.node_subs.at[node].set(flag))

    @staticmethod
    def take_down(st: MonitorState, owner: int, target: int
                  ) -> tuple[MonitorState, bool]:
        """Consume a pending DOWN signal (receive {'DOWN', ...})."""
        got = bool(st.down_sig[owner, target])
        return st._replace(
            down_sig=st.down_sig.at[owner, target].set(False)), got

    @staticmethod
    def take_nodedown(st: MonitorState, owner: int, target: int
                      ) -> tuple[MonitorState, bool]:
        got = bool(st.nodedown[owner, target])
        return st._replace(
            nodedown=st.nodedown.at[owner, target].set(False)), got

    @staticmethod
    def take_nodeup(st: MonitorState, owner: int, target: int
                    ) -> tuple[MonitorState, bool]:
        got = bool(st.nodeup[owner, target])
        return st._replace(
            nodeup=st.nodeup.at[owner, target].set(False)), got
