"""Trace record / render / persist / replay tests (reference
partisan_trace_orchestrator.erl + partisan_trace_file.erl)."""

import numpy as np

from partisan_tpu import interpose, trace as trace_mod, types as T
from partisan_tpu.cluster import Cluster
from partisan_tpu.models.direct_mail import DirectMail
from tests.support import fm_config, boot_fullmesh

N = 8


def _booted(seed=5, interp=None, link_drop=0.0):
    cfg = fm_config(N, seed=seed)
    model = DirectMail()
    cl = Cluster(cfg, model=model, interpose=interp)
    st = boot_fullmesh(cl)
    st = st._replace(model=model.broadcast(st.model, 0, 0))
    if link_drop:
        st = st._replace(faults=st.faults._replace(
            link_drop=np.float32(link_drop)))
    return cl, model, st


def test_record_captures_app_sends():
    cl, model, st = _booted()
    st, cap = cl.record(st, 10)
    tr = trace_mod.from_capture(cap)
    assert tr.n_rounds == 10 and tr.n_nodes == N
    evs = [e for e in tr.events() if e.kind == T.MsgKind.APP]
    assert len(evs) == N - 1
    assert {e.dst for e in evs} == set(range(1, N))
    assert all(e.src == 0 and not e.dropped for e in evs)


def test_record_is_deterministic():
    _, _, st1 = _booted(seed=9)
    cl1, _, _ = _booted(seed=9)
    cl2, _, st2 = _booted(seed=9)
    _, cap1 = cl1.record(st1, 8)
    _, cap2 = cl2.record(st2, 8)
    t1, t2 = trace_mod.from_capture(cap1), trace_mod.from_capture(cap2)
    assert t1.matches(t2)
    assert np.array_equal(t1.sent, t2.sent)


def test_fault_drops_are_recorded():
    cl, model, st = _booted(seed=3, link_drop=0.5)
    st, cap = cl.record(st, 10)
    tr = trace_mod.from_capture(cap)
    evs = list(tr.events())
    dropped = [e for e in evs if e.dropped]
    kept = [e for e in evs if not e.dropped]
    assert dropped and kept  # p=0.5 over dozens of gossip+app messages
    # delivered() clears exactly the dropped slots.
    d = tr.delivered()
    assert (d[..., T.W_KIND] != 0).sum() == len(kept)


def test_render_lines():
    cl, model, st = _booted()
    st, cap = cl.record(st, 5)
    text = trace_mod.from_capture(cap).render()
    assert "APP" in text and "=>" in text


def test_save_load_roundtrip(tmp_path):
    cl, model, st = _booted()
    st, cap = cl.record(st, 6)
    tr = trace_mod.from_capture(cap)
    p = tmp_path / "trace.npz"
    tr.save(p)
    tr2 = trace_mod.Trace.load(p)
    assert np.array_equal(tr.sent, tr2.sent)
    assert np.array_equal(tr.dropped, tr2.dropped)
    assert tr.matches(tr2)


def test_schedule_execution_from_trace():
    """Synthesize an omission schedule from a recorded trace: drop every
    APP send observed in the clean run; re-execution loses the broadcast
    (the filibuster execute_schedule mechanism)."""
    cl, model, st0 = _booted(seed=11)
    _, cap = cl.record(st0, 10)
    tr = trace_mod.from_capture(cap)
    coords = [(e.rnd, e.src, e.slot) for e in tr.events()
              if e.kind == T.MsgKind.APP]
    assert coords
    sched = trace_mod.schedule_from_events(
        coords, tr.n_rounds, tr.n_nodes, tr.emit_width, start=tr.start)

    cl2, model2, st = _booted(
        seed=11, interp=interpose.OmissionSchedule(sched, start=tr.start))
    # Interposed run must align rounds with the recorded run: both start
    # stepping from the same post-boot round with rnd reset semantics
    # identical (same seed => same boot).
    st = cl2.steps(st, 10)
    assert float(model2.coverage(st.model, st.faults.alive, 0)) == 1.0 / N
