"""partisan_gen_event semantics OVER THE BRIDGE.

The reference ships a patched OTP gen_event
(priv/otp/24/partisan_gen_event.erl, 1014 LoC) with a conformance suite
(test/partisan_gen_event_SUITE.erl, 1520 LoC).  This suite ports ~8
representative behaviors at the semantics level, with the event-manager
process on one emulated BEAM node and notifiers on others (the
tests/test_bridge_gen_server.py pattern):

- add_handler: handlers receive events in ADD order, each with its own
  state,
- notify is fire-and-forget; sync_notify replies after every handler ran,
- call/2 targets ONE handler by id and returns its reply,
- delete_handler stops delivery to that handler only and returns its
  final state,
- a handler that crashes on an event is REMOVED silently; the remaining
  handlers keep running (OTP gen_event isolation),
- swap_handler atomically replaces a handler, seeding the new one with
  the old one's state,
- per-notifier FIFO event ordering.
"""

import pytest

from support import BridgeVM, bridge_rig

OP_NOTIFY, OP_SYNC_NOTIFY, OP_CALL, OP_REPLY = 1, 2, 3, 4
EV_ADD, EV_CRASH = 1, 99           # event kinds the handlers interpret


class Handler:
    """One installed handler: accumulates events, can be told to crash."""

    def __init__(self, hid: int, state: int = 0):
        self.id = hid
        self.state = state
        self.events: list[int] = []

    def handle(self, ev: int, arg: int) -> None:
        if ev == EV_CRASH and arg == self.id:
            raise RuntimeError(f"handler {self.id} crashed")
        if ev == EV_ADD:
            self.state += arg
        self.events.append(arg)


class EventMgrVM(BridgeVM):
    """The partisan_gen_event manager loop."""

    def __init__(self, srv, sim_id):
        super().__init__(srv, sim_id)
        self.handlers: list[Handler] = []

    def add_handler(self, hid, state=0):
        self.handlers.append(Handler(hid, state))

    def delete_handler(self, hid):
        for h in list(self.handlers):
            if h.id == hid:
                self.handlers.remove(h)
                return h.state           # terminate/2 returns the state
        return None

    def swap_handler(self, old_hid, new_hid):
        """swap_handler: the new handler is seeded with the old one's
        terminate result (OTP swap semantics), atomically in place."""
        for i, h in enumerate(self.handlers):
            if h.id == old_hid:
                self.handlers[i] = Handler(new_hid, h.state)
                return True
        return False

    def process(self):
        for src, words in self.drain():
            op, mref, ev, arg = words[0], words[1], words[2], words[3]
            if op in (OP_NOTIFY, OP_SYNC_NOTIFY):
                for h in list(self.handlers):
                    try:
                        h.handle(ev, arg)
                    except Exception:
                        # a crashing handler is removed; others continue
                        self.handlers.remove(h)
                if op == OP_SYNC_NOTIFY:
                    self.forward(src, [OP_REPLY, mref, 0, 0])
            elif op == OP_CALL:
                # call/2: ev carries the TARGET handler id
                for h in self.handlers:
                    if h.id == ev:
                        self.forward(src, [OP_REPLY, mref, 0, h.state])
                        break
                else:
                    self.forward(src, [OP_REPLY, mref, 1, 0])


class NotifierVM(BridgeVM):
    def __init__(self, srv, sim_id):
        super().__init__(srv, sim_id)
        self._mref = sim_id * 1000
        self.mailbox = []

    def notify(self, mgr, ev, arg):
        self.forward(mgr, [OP_NOTIFY, 0, ev, arg])

    def sync_notify(self, mgr_vm, ev, arg, timeout_steps=12):
        self._mref += 1
        self.forward(mgr_vm.id, [OP_SYNC_NOTIFY, self._mref, ev, arg])
        return self._wait_reply(mgr_vm, self._mref, timeout_steps)

    def call(self, mgr_vm, hid, timeout_steps=12):
        self._mref += 1
        self.forward(mgr_vm.id, [OP_CALL, self._mref, hid, 0])
        return self._wait_reply(mgr_vm, self._mref, timeout_steps)

    def _wait_reply(self, mgr_vm, mref, timeout_steps):
        for _ in range(timeout_steps):
            self.step(1)
            mgr_vm.process()
            self.mailbox.extend(self.drain())
            for i, (_src, words) in enumerate(self.mailbox):
                if words[0] == OP_REPLY and words[1] == mref:
                    del self.mailbox[i]
                    return (words[2] == 0, words[3])
        return ("timeout", mgr_vm.id)


@pytest.fixture()
def rig():
    srv = bridge_rig(4)
    vms = []
    try:
        mgr = EventMgrVM(srv, 0)
        a = NotifierVM(srv, 1)
        b = NotifierVM(srv, 2)
        vms = [mgr, a, b]
        yield mgr, a, b
    finally:
        for vm in vms:
            vm.close()
        srv.close()


def _pump(a, mgr, k=3):
    for _ in range(k):
        a.step(1)
        mgr.process()


def test_all_handlers_receive_in_add_order(rig):
    mgr, a, _ = rig
    mgr.add_handler(1)
    mgr.add_handler(2)
    a.notify(mgr.id, EV_ADD, 5)
    _pump(a, mgr)
    assert [h.id for h in mgr.handlers] == [1, 2]
    assert all(h.events == [5] for h in mgr.handlers)
    assert all(h.state == 5 for h in mgr.handlers)


def test_handlers_keep_independent_state(rig):
    mgr, a, _ = rig
    mgr.add_handler(1, state=100)
    mgr.add_handler(2)
    a.notify(mgr.id, EV_ADD, 3)
    _pump(a, mgr)
    assert a.call(mgr, 1) == (True, 103)
    assert a.call(mgr, 2) == (True, 3)


def test_sync_notify_replies_after_handlers_ran(rig):
    mgr, a, _ = rig
    mgr.add_handler(1)
    assert a.sync_notify(mgr, EV_ADD, 7) == (True, 0)
    assert mgr.handlers[0].state == 7     # already applied at reply time


def test_call_targets_one_handler(rig):
    mgr, a, _ = rig
    mgr.add_handler(1, state=11)
    mgr.add_handler(2, state=22)
    assert a.call(mgr, 2) == (True, 22)
    ok, _ = a.call(mgr, 9)                # no such handler
    assert ok is False


def test_delete_handler_stops_delivery_and_returns_state(rig):
    mgr, a, _ = rig
    mgr.add_handler(1)
    mgr.add_handler(2)
    a.notify(mgr.id, EV_ADD, 4)
    _pump(a, mgr)
    assert mgr.delete_handler(1) == 4     # terminate returns final state
    a.notify(mgr.id, EV_ADD, 6)
    _pump(a, mgr)
    assert a.call(mgr, 2) == (True, 10)
    assert a.call(mgr, 1)[0] is False     # deleted: no longer reachable


def test_crashing_handler_removed_others_survive(rig):
    mgr, a, _ = rig
    mgr.add_handler(1)
    mgr.add_handler(2)
    a.notify(mgr.id, EV_CRASH, 1)         # crashes handler 1 only
    _pump(a, mgr)
    assert [h.id for h in mgr.handlers] == [2]
    a.notify(mgr.id, EV_ADD, 9)
    _pump(a, mgr)
    assert a.call(mgr, 2) == (True, 9)    # survivor still running


def test_swap_handler_preserves_state(rig):
    mgr, a, _ = rig
    mgr.add_handler(1)
    a.notify(mgr.id, EV_ADD, 8)
    _pump(a, mgr)
    assert mgr.swap_handler(1, 3)
    assert a.call(mgr, 3) == (True, 8)    # new handler seeded with state
    assert a.call(mgr, 1)[0] is False


def test_per_notifier_fifo_ordering(rig):
    mgr, a, _ = rig
    mgr.add_handler(1)
    for arg in (1, 2, 3, 4):
        a.notify(mgr.id, EV_ADD, arg)
    _pump(a, mgr, 6)
    assert mgr.handlers[0].events == [1, 2, 3, 4]
