"""Ablation profile of the bench round at scale: where does the
per-round time go?

Times the steady-state round under config ablations (manager-only, AAE
off, monotonic shed off, emission-compaction widths, inbox widths) at a
given n.  Each variant pays its own XLA compile, so run at 32k (compile
~40 s cold) rather than 100k.  Results guide the hot-path work; keep
with BENCH_NOTES.md.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/partisan_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def measure(n: int, label: str, *, model: bool = True, **over) -> None:
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config, PlumtreeConfig
    from partisan_tpu.models.plumtree import Plumtree
    from partisan_tpu.scenarios import K_PROG, _boot_overlay, _sync

    kw = dict(n_nodes=n, seed=1, peer_service_manager="hyparview",
              msg_words=16, partition_mode="groups", max_broadcasts=8,
              inbox_cap=16, emit_compact=32,
              plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4))
    kw.update(over)
    cfg = Config(**kw)
    cl = Cluster(cfg, model=Plumtree() if model else None, donate=True)
    t0 = time.perf_counter()
    st = _boot_overlay(cl, n, settle_execs=2)
    boot = time.perf_counter() - t0
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        st = cl.steps(st, K_PROG)
        _sync(st)
        best = min(best, time.perf_counter() - t0)
    print(f"{label:34s} per-round {best / K_PROG * 1e3:7.1f} ms   "
          f"(boot+compile {boot:.0f}s)", flush=True)


if __name__ == "__main__":
    from partisan_tpu.config import HyParViewConfig, PlumtreeConfig

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 32_768
    measure(n, "baseline (bench config)")
    measure(n, "manager only (no plumtree)", model=False)
    measure(n, "aae off",
            plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4, aae=False))
    measure(n, "heartbeat off",
            hyparview=HyParViewConfig(heartbeat=False))
    measure(n, "monotonic shed off", monotonic_shed=False)
    measure(n, "emit_compact off", emit_compact=0)
    measure(n, "emit_compact 24", emit_compact=24)
    measure(n, "inbox_cap 12", inbox_cap=12)
