"""Test environment: force a hermetic 8-virtual-device CPU platform.

Tests never touch the TPU tunnel: this image's sitecustomize registers an
``axon`` PJRT plugin in every interpreter and force-selects it via
``jax.config.update('jax_platforms', 'axon,cpu')``; we undo both BEFORE
any backend initializes, then force 8 virtual CPU devices so sharding
tests exercise a real ``jax.sharding.Mesh`` without hardware (the
multi-node-without-a-cluster fixture analogue, reference
test/partisan_support.erl:46+).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from partisan_tpu.hostmesh import force_host_devices  # noqa: E402

force_host_devices()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from jax._src import xla_bridge  # noqa: E402

xla_bridge._backend_factories.pop("axon", None)

# Persistent compilation cache: the hyparview/plumtree round steps take
# seconds to compile; cache across test runs.
jax.config.update("jax_compilation_cache_dir", "/tmp/partisan_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    """Session-scoped 8-way mesh over the virtual CPU devices, shared
    by every sharded suite (ISSUE 13 runtime paydown: the mesh — and
    the jit caches keyed on it — build once per session instead of per
    module)."""
    from partisan_tpu.parallel.sharded import make_mesh

    assert len(jax.devices()) >= 8, "conftest must force 8 cpu devices"
    return make_mesh(8)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-thousand-round soaks and other long runs — excluded "
        "from the tier-1 gate (-m 'not slow'), run explicitly with "
        "-m slow")
