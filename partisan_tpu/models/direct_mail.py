"""Demers direct-mail broadcast (protocols/demers_direct_mail.erl) and
its acked variant (protocols/demers_direct_mail_acked.erl).

Reference behavior: ``broadcast`` sends the message directly to every
member once — no epidemics, no repair; the acked variant sends with
``{ack, true}`` so the manager's acknowledgement backend retransmits
until every receiver acks (SURVEY.md §2 protocol corpus).

TPU mapping: a pending-broadcast bitmap; a node with pending slots mails
one slot per round to all its neighbors as APP event messages (flagged
``F_ACK_REQUIRED`` in the acked variant — the delivery layer handles
store/ack/retransmit).  The store is the same seen-bitmap as
anti-entropy, so coverage is measured identically.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import Array

from partisan_tpu import types as T
from partisan_tpu.comm import LocalComm
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import msg as msg_ops

OP_MAIL = 2   # APP payload[0] opcode (OP_PULL=1 is anti-entropy's)


class DirectMailState(NamedTuple):
    store: Array    # bool[n_local, max_broadcasts] — received slots
    pending: Array  # bool[n_local, max_broadcasts] — queued to mail


class DirectMail:
    name = "demers_direct_mail"

    def __init__(self, acked: bool = False) -> None:
        self.acked = acked
        if acked:
            self.name = "demers_direct_mail_acked"

    def init(self, cfg: Config, comm: LocalComm) -> DirectMailState:
        z = jnp.zeros((comm.n_local, cfg.max_broadcasts), jnp.bool_)
        return DirectMailState(store=z, pending=z)

    def step(self, cfg: Config, comm: LocalComm, state: DirectMailState,
             ctx: RoundCtx, nbrs: Array) -> tuple[DirectMailState, Array]:
        gids = comm.local_ids()

        # Receive: APP/OP_MAIL messages set store bits (duplicates from
        # retransmission are naturally idempotent).
        inb = ctx.inbox.data
        is_mail = (inb[..., T.W_KIND] == T.MsgKind.APP) & \
                  (inb[..., T.P0] == OP_MAIL)
        slots = jnp.where(is_mail, inb[..., T.P1], 0)
        hits = jnp.zeros_like(state.store, jnp.int32)
        rows = jnp.broadcast_to(
            jnp.arange(state.store.shape[0])[:, None], slots.shape)
        hits = hits.at[rows, jnp.where(is_mail, slots, cfg.max_broadcasts)
                       ].add(1, mode="drop")
        store = state.store | (hits > 0) & ctx.alive[:, None]
        store = jnp.where(ctx.alive[:, None], store, state.store)

        # Send: mail the lowest pending slot to every neighbor
        # (demers_direct_mail.erl: send to all members once).
        has = state.pending & ctx.alive[:, None]
        slot = jnp.argmax(has, axis=1).astype(jnp.int32)
        any_p = has.any(axis=1)
        flags = T.F_ACK_REQUIRED if self.acked else 0
        dst = jnp.where(any_p[:, None], nbrs, -1)
        emitted = msg_ops.build(
            cfg, T.MsgKind.APP, gids[:, None], dst,
            flags=flags, payload=(jnp.int32(OP_MAIL), slot[:, None]))
        pending = state.pending & ~(
            (jnp.arange(cfg.max_broadcasts)[None, :] == slot[:, None])
            & any_p[:, None])
        return DirectMailState(store=store, pending=pending), emitted

    # ---- scenario helpers --------------------------------------------
    def broadcast(self, state: DirectMailState, node: int,
                  slot: int) -> DirectMailState:
        return DirectMailState(
            store=state.store.at[node, slot].set(True),
            pending=state.pending.at[node, slot].set(True))

    def coverage(self, state: DirectMailState, alive: Array,
                 slot: int) -> Array:
        have = state.store[:, slot] & alive
        return jnp.sum(have) / jnp.maximum(jnp.sum(alive), 1)
