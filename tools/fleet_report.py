"""Fleet-runner JSON-lines exporter (the ``BENCH_*.json`` idiom: one
self-describing JSON object per line).

Runs a vmapped cluster population (partisan_tpu/fleet.py) — W
independent hyparview+plumtree clusters, one seed salt each, as ONE
jitted program — and prints one ``member`` line per cluster
(rounds-to-converge from its health snapshot ring, whole-run
redundancy ratio), one ``distribution`` line per metric (p5/p50/p95
across the population), and a trailing ``summary``::

    python tools/fleet_report.py [W] [n] [--rounds R] [--search]

``--search`` additionally runs a small batched Filibuster-style
schedule search (fleet.search): a population of omission schedules
drawn from a golden trace plus one adversarial blackout schedule, one
``schedule`` line per member with its verdict, and a
``counterexample`` line for every failing schedule — each verified to
replay bit-identically through the unbatched path before it prints.

Importable: ``report(card)`` renders any ``scenarios.fleet_sweep``
card as JSON lines.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _help() -> None:
    print(__doc__.strip())


def report(card: dict, out=None) -> None:
    """Render a ``scenarios.fleet_sweep`` card as JSON lines."""
    out = out or sys.stdout
    members = card.get("members", {})
    conv = members.get("rounds_to_converge", [])
    red = members.get("redundancy_ratio", [])
    for j in range(card["width"]):
        print(json.dumps({
            "kind": "member", "member": j, "salt": j,
            "rounds_to_converge": conv[j] if j < len(conv) else None,
            "redundancy_ratio": red[j] if j < len(red) else None,
        }), file=out, flush=True)
    for metric in ("rounds_to_converge", "redundancy_ratio"):
        print(json.dumps({"kind": "distribution", "metric": metric,
                          **card[metric]}), file=out, flush=True)
    for ch, dist in card.get("p99", {}).items():
        print(json.dumps({"kind": "distribution", "metric": "p99",
                          "channel": ch, **dist}), file=out, flush=True)
    wall = card.get("wall_s") or 0
    print(json.dumps({
        "kind": "summary", "width": card["width"], "n": card["n"],
        "rounds": card["rounds"], "converged": card["converged"],
        "programs": card["programs"], "wall_s": card["wall_s"],
        # population-level throughput (perfwatch's rounds/s convention:
        # rounds advanced per wall second, all members in one program)
        "rounds_per_s": (round(card["rounds"] / wall, 3)
                         if wall > 0 else None),
    }), file=out, flush=True)


def _search_demo(n: int = 16, width: int = 6, horizon: int = 10) -> None:
    """A small end-to-end fleet.search: schedules from a golden trace
    plus one guaranteed-failing root blackout (plumtree with AAE off —
    dissemination is wire-only, so silencing the broadcast root for the
    whole horizon must break coverage)."""
    import jax
    import numpy as np

    from partisan_tpu import fleet as fleet_mod
    from partisan_tpu import interpose
    from partisan_tpu import trace as trace_mod
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config, PlumtreeConfig
    from partisan_tpu.models.plumtree import Plumtree

    cfg = Config(n_nodes=n, seed=5, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups",
                 salt_operand=True, plumtree=PlumtreeConfig(aae=False))
    joins, contacts = list(range(1, n)), [0] * (n - 1)

    def build(sched):
        fl = fleet_mod.Fleet(cfg, width=width, model=Plumtree(),
                             interpose=sched)
        st = fl.init(salts=np.zeros(width, np.uint32))
        st = st._replace(manager=fl.map_members(
            lambda m: fl.manager.join_many(cfg, m, joins, contacts),
            st.manager))
        st = fl.steps(st, 30)
        st = st._replace(model=fl.map_members(
            lambda m: fl.model.broadcast(m, 0, 0, 3), st.model))
        return fl, st

    cl = Cluster(cfg.replace(fleet_width=0), model=Plumtree(),
                 interpose=interpose.OmissionSchedule(
                     np.zeros((1, 1, 1), np.bool_), start=0))
    st = cl.init()
    st = st._replace(manager=cl.manager.join_many(
        cfg, st.manager, joins, contacts))
    st = cl.steps(st, 30)
    st = st._replace(model=cl.model.broadcast(st.model, 0, 0, 3))
    _, cap = cl.record(st, horizon)
    emit_w = cap.sent.shape[2]
    tr = trace_mod.from_capture(cap)
    boot = int(jax.device_get(st.rnd))
    scheds = fleet_mod.population(
        tr, lambda e: e.kind_name.startswith("PT_"),
        width=width - 1, max_faults=2, seed=1)
    scheds.append(frozenset(
        (r, 0, e) for r in range(boot, boot + horizon)
        for e in range(emit_w)))
    res = fleet_mod.search(build, scheds, horizon, sched_width=emit_w,
                           coverage_slot=0, coverage_version=3)
    for j, ok in enumerate(res.verdicts):
        print(json.dumps({"kind": "schedule", "member": j,
                          "omissions": len(scheds[j]), "pass": ok}),
              flush=True)
    for c in res.counterexamples:
        print(json.dumps({
            "kind": "counterexample", "member": c.member,
            "salt": c.salt, "seed": c.seed,
            "omissions": len(c.schedule), "replayed": c.replayed,
        }), flush=True)
    print(json.dumps({"kind": "search_summary", "width": res.width,
                      "passed": res.passed,
                      "failing": len(res.counterexamples),
                      "programs": res.programs}), flush=True)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--help" in argv or "-h" in argv:
        _help()
        return 0
    import jax

    # Persistent compile cache (the tools' shared discipline): the
    # vmapped fleet scan re-traces per width/length — cache across
    # invocations so the CLI smoke prices decode, not XLA.
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/partisan_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    from partisan_tpu import scenarios

    # consume flag VALUES before scanning positionals, so
    # `fleet_report.py --rounds 300` does not read 300 as the width
    argv = list(argv)
    rounds = 200
    if "--rounds" in argv:
        i = argv.index("--rounds")
        rounds = int(argv[i + 1])
        del argv[i:i + 2]
    sizes = [int(a) for a in argv
             if not a.startswith("--") and a.isdigit()]
    width = sizes[0] if sizes else 4
    n = sizes[1] if len(sizes) > 1 else 48
    card = scenarios.fleet_sweep(width=width, n=n, max_rounds=rounds)
    report(card)
    if "--search" in argv:
        _search_demo()
    return 0


if __name__ == "__main__":
    sys.exit(main())
