"""partisan_gen_statem semantics OVER THE BRIDGE.

The reference ships a patched OTP gen_statem
(priv/otp/24/partisan_gen_statem.erl, 3008 LoC) with a conformance
suite (test/partisan_gen_statem_SUITE.erl, 2773 LoC).  With no BEAM in
this image, this suite runs the PACKAGE event loop
(partisan_tpu.otp.gen_statem: postpone replay order, state_timeout,
event timeout) against the real bridge transport — only the two-state
switch callback module is suite-local.  ~11 representative behaviors at
the semantics level:

- state-transition calls with replies from the NEW state,
- keep_state (data updates without transition),
- event POSTPONE: events postponed in a state are retried — in original
  arrival order, ahead of newer events — when the state changes,
- STATE timeout: armed on entering a state, NOT cancelled by event
  arrival, cancelled by a state transition (OTP state_timeout),
- EVENT timeout: cancelled by ANY event arrival (OTP event timeout),
- ref/reply pairing across transitions with two concurrent clients.
"""

import pytest

from support import BridgeVM, bridge_rig

from partisan_tpu.otp import gen
from partisan_tpu.otp.gen_statem import (
    EV_EVENT_TIMEOUT, EV_STATE_TIMEOUT, GenStatem, Result)

# events
EV_FLIP, EV_GET, EV_WORK, EV_ARM_IDLE, EV_TICK = 1, 2, 3, 4, 5
OFF, ON = 0, 1
STATE_TIMEOUT = 6          # rounds in ON before auto-OFF (state_timeout)
IDLE_TIMEOUT = 5           # rounds without events after ARM_IDLE


class Switch:
    """The two-state switch with a counter — the shape of the SUITE's
    start/stop machines.  All loop semantics live in the package; this
    module only maps events to actions."""

    init_state = OFF

    def __init__(self, *, on_timeout=None):
        self.counter = 0
        self.on_timeout = on_timeout

    def state_timeout(self, state):
        return self.on_timeout if state == ON else None

    def handle_event(self, state, ev, arg, is_call):
        if ev in (EV_STATE_TIMEOUT, EV_EVENT_TIMEOUT):
            return Result(next_state=OFF)
        if ev == EV_FLIP:
            new = ON if state == OFF else OFF
            return Result(next_state=new, reply=new)
        if ev == EV_GET:       # keep_state + reply
            return Result(reply=state * 1000 + self.counter)
        if ev == EV_WORK:
            if state == OFF:
                return Result(postpone=True)
            self.counter = self.counter * 2 + arg   # order-sensitive op
            return Result(reply=self.counter)
        if ev == EV_ARM_IDLE:
            return Result(reply=0, event_timeout=IDLE_TIMEOUT)
        if ev == EV_TICK:
            return Result()    # no-op event (cancels event timeout)
        return Result(reply=0, error=True)


@pytest.fixture()
def rig():
    """Machine WITHOUT a state timeout (timeout behaviors get their own
    rig below — an always-armed ON timeout would fire mid-test)."""
    srv = bridge_rig(4)
    procs = []
    try:
        a = gen.Caller(BridgeVM(srv, 0))
        m = GenStatem(BridgeVM(srv, 1), Switch())
        c = gen.Caller(BridgeVM(srv, 2))
        procs = [a, m, c]
        yield srv, a, m, c
    finally:
        for p in procs:
            p.close()
        srv.close()


@pytest.fixture()
def rig_t():
    """Machine whose ON state arms a state_timeout."""
    srv = bridge_rig(4)
    procs = []
    try:
        a = gen.Caller(BridgeVM(srv, 0))
        m = GenStatem(BridgeVM(srv, 1),
                      Switch(on_timeout=STATE_TIMEOUT))
        procs = [a, m]
        yield srv, a, m
    finally:
        for p in procs:
            p.close()
        srv.close()


def _settle(a, m, k):
    for _ in range(k):
        m.process(a.step(1))


def _call(a, m, ev, arg=0):
    return a.call(m.id, ev, arg, pump=m.process)


def test_call_transitions_and_replies_from_new_state(rig):
    _, a, m, _ = rig
    assert _call(a, m, EV_FLIP) == (True, ON)
    assert _call(a, m, EV_FLIP) == (True, OFF)


def test_keep_state_preserves_data(rig):
    _, a, m, _ = rig
    assert _call(a, m, EV_FLIP) == (True, ON)
    assert _call(a, m, EV_WORK, 3) == (True, 3)
    # get is keep_state: two reads, same state and data
    assert _call(a, m, EV_GET) == (True, 1003)
    assert _call(a, m, EV_GET) == (True, 1003)


def test_postponed_events_replay_on_state_change(rig):
    """WORK is postponed in OFF; flipping to ON replays it."""
    _, a, m, _ = rig
    a.event(m.id, EV_WORK, 7)
    _settle(a, m, 3)
    assert _call(a, m, EV_GET) == (True, 0)   # still OFF, idle
    assert _call(a, m, EV_FLIP) == (True, ON)
    _settle(a, m, 2)
    assert _call(a, m, EV_GET) == (True, 1007)


def test_postponed_events_replay_in_arrival_order(rig):
    """counter = counter*2 + arg detects ordering: [2 then 3] -> 7."""
    _, a, m, _ = rig
    a.event(m.id, EV_WORK, 2)
    _settle(a, m, 2)
    a.event(m.id, EV_WORK, 3)
    _settle(a, m, 2)
    assert _call(a, m, EV_FLIP) == (True, ON)
    _settle(a, m, 2)
    assert _call(a, m, EV_GET) == (True, 1007)


def test_postponed_replay_ahead_of_newer_events(rig):
    """A postponed WORK(2) must apply before a WORK(3) that arrives in
    the same pass as the flip (gen_statem: postponed first)."""
    _, a, m, _ = rig
    a.event(m.id, EV_WORK, 2)
    _settle(a, m, 2)                       # WORK(2) postponed in OFF
    a.event(m.id, EV_FLIP)                 # same-round pair: flip …
    a.event(m.id, EV_WORK, 3)              # … then new work
    _settle(a, m, 3)
    assert _call(a, m, EV_GET) == (True, 1007)  # (0*2+2)*2+3


def test_state_timeout_fires_without_events(rig_t):
    _, a, m = rig_t
    assert _call(a, m, EV_FLIP) == (True, ON)
    _settle(a, m, STATE_TIMEOUT + 2)
    assert _call(a, m, EV_GET)[1] // 1000 == OFF


def test_state_timeout_not_cancelled_by_events(rig_t):
    """OTP state_timeout survives event arrival (only a transition
    cancels it): WORK events in ON do not keep it alive."""
    _, a, m = rig_t
    assert _call(a, m, EV_FLIP) == (True, ON)
    for _ in range(3):
        a.event(m.id, EV_WORK, 1)
        _settle(a, m, 2)
    _settle(a, m, STATE_TIMEOUT)
    assert _call(a, m, EV_GET)[1] // 1000 == OFF


def test_state_timeout_cancelled_by_transition(rig_t):
    """Flip ON->OFF before the deadline: no spurious later timeout, and
    a fresh ON arms a FRESH timer."""
    _, a, m = rig_t
    assert _call(a, m, EV_FLIP) == (True, ON)
    assert _call(a, m, EV_FLIP) == (True, OFF)  # cancels
    _settle(a, m, STATE_TIMEOUT + 2)
    assert _call(a, m, EV_FLIP) == (True, ON)   # fresh timer
    _settle(a, m, 2)
    assert _call(a, m, EV_GET)[1] // 1000 == ON


def test_event_timeout_cancelled_by_any_event(rig):
    _, a, m, _ = rig
    assert _call(a, m, EV_FLIP) == (True, ON)
    assert _call(a, m, EV_ARM_IDLE) == (True, 0)
    a.event(m.id, EV_TICK)          # any event cancels the idle timer
    _settle(a, m, IDLE_TIMEOUT + 3)
    assert _call(a, m, EV_GET)[1] // 1000 == ON
    # the GET above was itself an event — idle timer stays cancelled
    _settle(a, m, IDLE_TIMEOUT + 3)
    assert _call(a, m, EV_GET)[1] // 1000 == ON


def test_event_timeout_fires_when_idle():
    srv = bridge_rig(4)
    try:
        a = gen.Caller(BridgeVM(srv, 0))
        m = GenStatem(BridgeVM(srv, 1), Switch())  # no state_timeout
        assert _call(a, m, EV_FLIP) == (True, ON)
        assert _call(a, m, EV_ARM_IDLE) == (True, 0)
        for _ in range(IDLE_TIMEOUT + 2):
            m.process(a.step(1))   # silence
        assert _call(a, m, EV_GET)[1] // 1000 == OFF
        a.close()
        m.close()
    finally:
        srv.close()


def test_two_clients_refs_pair_across_transition(rig):
    _, a, m, c = rig
    ra = a.send_call(m.id, EV_FLIP)
    rc = c.send_call(m.id, EV_GET)
    got_a = got_c = None
    for _ in range(12):
        m.process(a.step(1))
        got_a = got_a or a.poll(ra)
        got_c = got_c or c.poll(rc)
        if got_a and got_c:
            break
    assert got_a == (True, ON)
    assert got_c is not None and got_c[0] is True
