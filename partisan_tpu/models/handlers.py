"""Pluggable plumtree broadcast-handler behaviour (tensor form).

The reference lets applications supply the payload semantics that ride
the epidemic broadcast tree: a handler module implements
``broadcast_data/1, merge/2, is_stale/1, graft/1, exchange/1``
(partisan_plumtree_broadcast_handler.erl:47-78) and the broadcast server
calls it at every decision point (partisan_plumtree_broadcast.erl:
565-577 merge, :861-905 graft service, :1040-1070 exchange).

The tensor transposition: a handler's payload is a fixed-width vector of
``payload_words`` int32 words, and its ``merge`` must be a lattice join —
associative, commutative, idempotent — so the per-round fold over inbox
slots can run batched (a tree reduction of ``join``) instead of one
gen_server call per message.  The behaviour maps:

    broadcast_data/1 -> :meth:`payload` + ``Plumtree.broadcast`` (id is
                        the (node, slot) pair; payload is the vector)
    merge/2          -> :meth:`join`  (store' = join(store, incoming))
    is_stale/1       -> :meth:`leq`   (stale iff payload <= store)
    graft/1          -> the store row itself, served back to the grafting
                        peer (Plumtree replies PT_GOSSIP with the store)
    exchange/1       -> :meth:`exchange` — AAE with a random peer; the
                        base class IGNORES exchange, exactly like the
                        reference's default handler
                        (partisan_plumtree_backend.erl:22-35 "no AAE,
                        exchange -> ignore"); :class:`MaxJoinHandler`
                        provides the scatter-max implementation valid
                        whenever ``join`` is elementwise max.

Handlers whose join IS elementwise max (version counters, G-counters,
grow-only flag sets) inherit :class:`MaxJoinHandler` and get working AAE
for free.  Joins that are not per-word max (:class:`LWWHandler`'s
timestamp-ordered register) still broadcast/repair through the tree —
eager push, i_have/graft, prune — with exchange ignored.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array


class BroadcastHandler:
    """Base behaviour: version-counter semantics hooks, exchange ignored."""

    payload_words: int = 1
    identity: int = 0        # join identity, per word

    # -- lattice ops (merge / is_stale) --------------------------------
    def join(self, a: Array, b: Array) -> Array:
        """Elementwise lattice join of payload vectors (broadcastable
        shapes ``[..., payload_words]``)."""
        raise NotImplementedError

    def word_leq(self, a: Array, b: Array) -> Array:
        """Elementwise per-word order test.  Default derives it from
        ``join`` (a <= b  iff  join(a, b) == b) — override when cheaper."""
        return self.join(a, b) == b

    def leq(self, a: Array, b: Array) -> Array:
        """Payload order ``a <= b`` (consumes the trailing word axis).
        ``is_stale`` is ``leq(incoming, store)``."""
        return jnp.all(self.word_leq(a, b), axis=-1)

    def bottom(self) -> Array:
        """The TRUE least element of the payload lattice (int32[PW]) —
        used to pad masked fold slots and for presence.  Defaults to a
        vector of ``identity``; a handler whose payload space extends
        BELOW that (negative timestamps or values) must override it,
        or padding would beat real payloads in the join and
        ``present`` would misread a legitimate payload as absent."""
        return jnp.full((self.payload_words,), self.identity, jnp.int32)

    def present(self, store: Array) -> Array:
        """bool[...]: slot carries data (graft can serve it)."""
        return jnp.any(store != self.bottom(), axis=-1)

    # -- host-side construction (broadcast_data) -----------------------
    def payload(self, value) -> Array:
        """Coerce a host value (int or sequence) to a payload vector."""
        if isinstance(value, (int, float)):
            vec = [int(value)] + [self.identity] * (self.payload_words - 1)
        else:
            vec = list(int(v) for v in value)
            if len(vec) != self.payload_words:
                raise ValueError(
                    f"payload has {len(vec)} words, handler carries "
                    f"{self.payload_words}")
        return jnp.asarray(vec, jnp.int32)

    # -- AAE (exchange) -------------------------------------------------
    supports_exchange: bool = False

    def exchange(self, comm, store: Array, dst: Array) -> Array | None:
        """Push ``store [n, B, PW]`` to the peers in ``dst [n, K]`` and
        return what arrived at each node (joined across senders), or
        ``None`` when exchange is unsupported (the reference default
        handler's ``exchange -> ignore``)."""
        return None

    def exchange_with_epochs(self, comm, store: Array, epochs: Array,
                             dst: Array):
        """AAE push of the store AND the slot-recycle epochs (plumtree's
        per-root tree keys ride the same exchange edges so AAE-satisfied
        nodes adopt a recycled epoch the round they pull its data).
        Returns (pulled_store | None, pulled_epochs int32[n, B])."""
        pulled = self.exchange(comm, store, dst)
        return pulled, comm.push_max(epochs, dst)


class MaxJoinHandler(BroadcastHandler):
    """Handlers whose join is elementwise max: batched fold AND AAE ride
    the scatter-max gossip lane (ops/gossip.py)."""

    supports_exchange = True

    def join(self, a: Array, b: Array) -> Array:
        return jnp.maximum(a, b)

    def word_leq(self, a: Array, b: Array) -> Array:
        return a <= b

    def exchange(self, comm, store: Array, dst: Array) -> Array:
        n, B, PW = store.shape
        pulled = comm.push_max(store.reshape(n, B * PW), dst)
        return pulled.reshape(n, B, PW)

    def exchange_with_epochs(self, comm, store: Array, epochs: Array,
                             dst: Array):
        """Fused store + epoch push: ONE scatter-max over the exchange
        edges (measured cost-neutral vs the store push alone; a second
        scatter for epochs cost ~6% of the 32k round).  A subclass that
        overrides :meth:`exchange` keeps its override — the fusion only
        applies to the stock max-join push."""
        if type(self).exchange is not MaxJoinHandler.exchange:
            return super().exchange_with_epochs(comm, store, epochs, dst)
        n, B, PW = store.shape
        rows = jnp.concatenate([store.reshape(n, B * PW), epochs], axis=1)
        pulled = comm.push_max(rows, dst)
        return pulled[:, :B * PW].reshape(n, B, PW), pulled[:, B * PW:]


class VersionHandler(MaxJoinHandler):
    """The default handler: one monotonically-versioned word per slot —
    the heartbeat/version semantics of partisan_plumtree_backend.erl
    (:191-260), where a re-broadcast bumps the version and re-propagates."""

    payload_words = 1


class GCounterHandler(MaxJoinHandler):
    """Grow-only counter CRDT: one word per actor, join = elementwise max
    (the state_orset-family merge the reference's membership rides,
    partisan_membership_set.erl:116-213 — transposed to its simplest
    lattice).  ``payload({actor: count})`` builds a vector contribution."""

    def __init__(self, n_actors: int):
        self.payload_words = n_actors

    def payload(self, value) -> Array:
        if isinstance(value, dict):
            vec = [0] * self.payload_words
            for actor, count in value.items():
                vec[int(actor)] = int(count)
            return jnp.asarray(vec, jnp.int32)
        return super().payload(value)

    def total(self, store: Array) -> Array:
        """Counter value per slot: sum over actor words."""
        return jnp.sum(store, axis=-1)


class LWWHandler(BroadcastHandler):
    """Last-writer-wins register: payload = [timestamp, value]; join keeps
    the pair with the larger (timestamp, value) — NOT a per-word max (the
    value rides with the winning timestamp), which exercises the general
    join path.  Exchange is ignored (base class), like the reference's
    default handler — tree repair (i_have/graft) is the delivery path."""

    payload_words = 2

    def bottom(self) -> Array:
        # (INT32_MIN, INT32_MIN): any real (ts, value) — including
        # negative timestamps and [0, 0] — beats the padding and reads
        # as present.
        return jnp.full((2,), jnp.iinfo(jnp.int32).min, jnp.int32)

    def join(self, a: Array, b: Array) -> Array:
        a_ts, b_ts = a[..., 0], b[..., 0]
        a_v, b_v = a[..., 1], b[..., 1]
        b_wins = (b_ts > a_ts) | ((b_ts == a_ts) & (b_v > a_v))
        return jnp.where(b_wins[..., None], b, a)

    def leq(self, a: Array, b: Array) -> Array:
        a_ts, b_ts = a[..., 0], b[..., 0]
        return (a_ts < b_ts) | ((a_ts == b_ts) & (a[..., 1] <= b[..., 1]))


def tree_fold(handler: BroadcastHandler, x: Array, axis: int) -> Array:
    """Reduce ``x`` over ``axis`` with the handler's join, as a log-depth
    tree of batched elementwise joins (works for any lattice join; XLA
    fuses the max case into the same code the hand-written fold had)."""
    x = jnp.moveaxis(x, axis, 0)
    while x.shape[0] > 1:
        m = x.shape[0]
        if m % 2:
            x = jnp.concatenate(
                [x, jnp.broadcast_to(handler.bottom().astype(x.dtype),
                                     (1,) + x.shape[1:])])
            m += 1
        x = handler.join(x[0::2], x[1::2])
    return x[0]
