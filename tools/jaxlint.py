"""jaxlint — run the jaxpr-level static auditor over the config matrix
(the ``BENCH_*.json`` idiom: one self-describing JSON object per line).

Traces the audited program matrix (each observability plane on/off,
plane-major x width-operand, capture and flight variants, the OTP
service stack, the soak chunk scan — see
``partisan_tpu/lint/matrix.py``), runs the rule catalog
(``partisan_tpu/lint/rules.py``), applies the pinned waiver baseline
(``partisan_tpu/lint/waivers.py``) and prints findings as JSON lines::

    python tools/jaxlint.py [--quick] [--rules r1,r2] [--no-stale]

Output lines: ``{"kind": "finding", ...}`` for every unwaived finding,
``{"kind": "waived", ...}`` for baseline-covered ones, then a trailing
``{"kind": "summary", "verdict": "CLEAN"|"DIRTY", ...}``.  Exit code is
0 only when the verdict is CLEAN (no unwaived findings, no stale
waivers).

``--quick`` runs the three-program subset (plain round, everything-on
scan, capture round) plus the package rules — the budget-guarded form
``bench.py`` folds into its artifact — and skips the stale-waiver check
(a subset legitimately leaves waivers unmatched).  ``--no-stale``
skips the stale check on a full run (for rule-filtered invocations).
Also importable: ``verdict(quick=True)`` returns the summary dict.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The matrix's sharded (shard_map) programs need a multi-device host
# platform — the one shared pin (partisan_tpu/hostmesh.py).
from partisan_tpu.hostmesh import force_host_devices

force_host_devices()

USAGE = "usage: jaxlint.py [--quick] [--rules r1,r2] [--no-stale]"


def _finding_row(kind, f, reason=None) -> dict:
    row = {"kind": kind, "rule": f.rule, "program": f.program,
           "file": f.file, "func": f.func, "line": f.line,
           "detail": f.detail, "fingerprint": f.fingerprint,
           "message": f.message}
    if reason is not None:
        row["waiver"] = reason
    return row


def run(quick: bool = False, rules=None, check_stale: bool = True,
        out=sys.stdout) -> dict:
    """Trace, audit, print JSON lines; returns the summary dict."""
    from partisan_tpu.lint import (
        PACKAGE_RULES,
        PROGRAM_RULES,
        matrix,
        run_programs,
    )

    programs = matrix.quick_matrix() if quick else \
        matrix.default_matrix()
    prog_rules = pkg_rules = None
    if rules is not None:
        unknown = [r for r in rules
                   if r not in PROGRAM_RULES and r not in PACKAGE_RULES]
        if unknown:
            raise SystemExit(f"unknown rules: {', '.join(unknown)}")
        prog_rules = [r for r in rules if r in PROGRAM_RULES]
        pkg_rules = [r for r in rules if r in PACKAGE_RULES]
    rep = run_programs(
        programs, rules=prog_rules, package_rules=pkg_rules,
        check_stale=check_stale and not quick and rules is None)
    for f in rep.findings:
        print(json.dumps(_finding_row("finding", f)), file=out)
    for f, reason in rep.waived:
        print(json.dumps(_finding_row("waived", f, reason)), file=out)
    for fp in rep.stale:
        print(json.dumps({"kind": "stale_waiver", "fingerprint": fp,
                          "message": "waiver matched no finding — the "
                          "documented exception no longer exists"}),
              file=out)
    summary = {
        "kind": "summary",
        "matrix": "quick" if quick else "full",
        "programs": [p.name for p in programs],
        "findings": len(rep.findings),
        "waived": len(rep.waived),
        "stale_waivers": len(rep.stale),
        "verdict": "CLEAN" if rep.clean else "DIRTY",
    }
    print(json.dumps(summary), file=out)
    return summary


def verdict(quick: bool = True) -> dict:
    """The bench-artifact entry: run silently, return the summary."""
    import io

    return run(quick=quick, out=io.StringIO())


def main() -> None:
    if "--help" in sys.argv or "-h" in sys.argv:
        print(USAGE)
        print(__doc__)
        return
    args = sys.argv[1:]
    quick = "--quick" in args
    check_stale = "--no-stale" not in args
    rules = None
    for a in args:
        if a.startswith("--rules"):
            try:
                val = a.split("=", 1)[1] if "=" in a else \
                    args[args.index(a) + 1]
            except IndexError:
                print(USAGE, file=sys.stderr)
                raise SystemExit(2)
            rules = [r.strip() for r in val.split(",") if r.strip()]
    known = {"--quick", "--no-stale"}
    for a in args:
        if a.startswith("--") and a not in known \
                and not a.startswith("--rules"):
            print(USAGE, file=sys.stderr)
            raise SystemExit(2)
    summary = run(quick=quick, rules=rules, check_stale=check_stale)
    raise SystemExit(0 if summary["verdict"] == "CLEAN" else 1)


if __name__ == "__main__":
    main()
