"""X-BOT overlay optimization + reserved-slot tests
(partisan_hyparview_peer_service_manager.erl:1880-2050 optimization
handshakes; reserved-slot admission)."""

import jax.numpy as jnp
import numpy as np

from partisan_tpu.cluster import Cluster
from partisan_tpu.managers.hyparview import link_cost
from tests.support import hv_config, boot_hyparview

N = 24
SEED = 6


def _mean_active_cost(cl, st):
    """Mean synthetic link cost over all active edges."""
    act = np.asarray(cl.manager.neighbors(cl.cfg, st.manager))
    total, cnt = 0.0, 0
    for i, row in enumerate(act):
        for j in row:
            if j >= 0:
                total += float(link_cost(SEED, jnp.int32(i), jnp.int32(j)))
                cnt += 1
    return total / max(cnt, 1)


def test_xbot_lowers_mean_link_cost():
    def build(xbot):
        import dataclasses

        cfg = hv_config(N, SEED)
        cfg = cfg.replace(
            hyparview=dataclasses.replace(cfg.hyparview, xbot=xbot))
        cl = Cluster(cfg)
        st = boot_hyparview(cl, settle=30)
        return cl, cl.steps(st, 120)   # several xbot cycles (every 10)

    cl0, st0 = build(False)
    cl1, st1 = build(True)
    c0, c1 = _mean_active_cost(cl0, st0), _mean_active_cost(cl1, st1)
    assert c1 < c0, f"xbot did not improve overlay cost: {c1:.3g} vs {c0:.3g}"
    # The optimized overlay stays connected.
    from tests.support import components
    act = np.asarray(cl1.manager.neighbors(cl1.cfg, st1.manager))
    alive = np.asarray(st1.faults.alive)
    assert len(components(act, alive)) == 1


def test_reserved_slots_cap_ordinary_admission():
    cfg = hv_config(12, 3)
    cl = Cluster(cfg)
    st = cl.init()
    # Reserve all but two active slots on node 0 before anyone joins.
    held = cfg.hyparview.active_max - 2
    st = st._replace(manager=cl.manager.reserve(cfg, st.manager, 0, held))
    m = st.manager
    for i in range(1, 12):
        m = cl.manager.join(cfg, m, i, 0)
    st = st._replace(manager=m)
    st = cl.steps(st, 40)
    act0 = np.asarray(st.manager.active[0])
    assert (act0 >= 0).sum() <= 2, f"reserved slots were filled: {act0}"
    # The rest of the overlay still forms.
    from tests.support import components
    act = np.asarray(cl.manager.neighbors(cfg, st.manager))
    assert len(components(act, np.ones(12, bool))) == 1


def test_reserve_validation():
    import pytest

    cfg = hv_config(8, 1)
    cl = Cluster(cfg)
    st = cl.init()
    with pytest.raises(ValueError):
        cl.manager.reserve(cfg, st.manager, 0, cfg.hyparview.active_max + 1)
    with pytest.raises(ValueError):
        cl.manager.reserve(cfg, st.manager, 0, -1)


def test_xbot_roundtrip_no_persistent_one_way_edges():
    """The 4-party replace handshake re-homes every demoted peer (swap
    i-o, c-d -> i-c, o-d): after optimization cycles settle, active
    views stay (almost entirely) SYMMETRIC — no lingering one-way edges
    — and node degrees are preserved rather than bled away."""
    import dataclasses

    cfg = hv_config(N, SEED)
    cfg = cfg.replace(
        hyparview=dataclasses.replace(cfg.hyparview, xbot=True))
    cl = Cluster(cfg)
    st = boot_hyparview(cl, settle=30)
    pre = np.asarray(cl.manager.neighbors(cfg, st.manager))
    pre_deg = (pre >= 0).sum(axis=1)
    st = cl.steps(st, 150)   # ~15 optimization cycles (xbot_every = 10)
    act = np.asarray(cl.manager.neighbors(cfg, st.manager))
    edges = {(i, int(j)) for i in range(N) for j in act[i] if j >= 0}
    sym = sum((b, a) in edges for (a, b) in edges) / max(len(edges), 1)
    # mid-flight chains may hold a handful of half-built edges; anything
    # persistent would crater this ratio
    assert sym >= 0.9, f"one-way edges persisted: symmetry {sym:.2f}"
    deg = (act >= 0).sum(axis=1)
    assert deg.mean() >= pre_deg.mean() - 0.5, (pre_deg.mean(), deg.mean())
    assert (deg >= 1).all(), f"isolated nodes: {np.where(deg == 0)[0]}"
