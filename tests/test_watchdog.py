"""In-scan invariant watchdog plane (the ISSUE 20 acceptance suite).

The tentpole claim: a conservation breach injected MID-SUPERSTEP into
a >1000-round single execution (``Config.superstep=8`` under the
soak's lifted chunk cap) is detected at EXACTLY its injection round by
the device-resident plane — latch, soak log, chunk poll and opslog
detection leg all agree — while the identical plane-off run can only
blame the chunk boundary, ``rounds - inject`` rounds late.

Around it, the plane's standing contracts: bit-parity when off AND
when on (the plane observes, never steers — trip mode aside),
replication under sharding, checkpoint/kill/restore latch replay
(including a kill BEFORE the injection round: the corruption is pure
in ``state.rnd``, so the resumed timeline re-injects and re-latches
identically), the trip mode freezing the flight recorder at the
breach round, zero traced cost when off, and the edge-triggered
telemetry replay.
"""

import jax
import pytest

import support
from partisan_tpu import latency as latency_mod
from partisan_tpu import opslog, soak, telemetry
from partisan_tpu import watchdog as watchdog_mod
from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config, WatchdogConfig
from partisan_tpu.trace import Trace

N = 16
BOOT = 15                  # boot_fullmesh settle rounds (rnd at entry)
ROUNDS = 1280              # ONE execution, > 1000 rounds (superstep=8)
INJECT = BOOT + 643        # mid-superstep (658 % 8 == 2), mid-chunk
AMOUNT = 3


def _cfg(**kw):
    kw.setdefault("metrics", True)
    kw.setdefault("metrics_ring", 32)
    return support.fm_config(N, kw.pop("seed", 7), **kw)


def _boot(cl):
    st = support.boot_fullmesh(cl)
    assert int(jax.device_get(st.rnd)) == BOOT
    return st


@pytest.fixture(scope="module")
def detection_runs():
    """The acceptance pair: the same 1280-round seeded soak (superstep
    8, fixed single chunk) with the plane armed vs absent, the same
    ledger corruption injected at round 658 in both."""
    runs = {}
    for armed in (True, False):
        cfg = _cfg(superstep=8,
                   watchdog=WatchdogConfig(
                       enabled=armed, ring=2048,
                       inject_round=INJECT, inject_amount=AMOUNT))
        cl = Cluster(cfg)
        st = _boot(cl)
        eng = soak.Soak(make_cluster=lambda cl=cl: cl,
                        invariants=(soak.conservation(),),
                        cfg=soak.SoakConfig(chunk_fixed=ROUNDS))
        runs[armed] = eng.run(st, rounds=ROUNDS)
    return runs


def test_exact_round_detection_inside_fused_superstep(detection_runs):
    """Acceptance: the armed run reports first_breach_rnd == the
    injection round from inside a single >1000-round execution; the
    plane-off run's host check can only blame the chunk boundary."""
    res = detection_runs[True]
    # one execution, longer than the unlifted 1000-round cap
    assert len(res.chunks) == 1 and res.chunks[0]["k"] == ROUNDS
    (cap,) = [e for e in res.log if e["kind"] == "superstep_cap"]
    assert cap["lifted"] and cap["chunk_cap"] >= ROUNDS
    # injected ground truth logged at run entry
    (inj,) = [e for e in res.log if e["kind"] == "breach_injected"]
    assert inj["round"] == INJECT and inj["armed"] is True
    # the latch, the soak verdict and the chunk poll all name the round
    assert res.breaches == 1
    (br,) = [e for e in res.log if e["kind"] == "invariant_breach"]
    assert br["invariant"] == "watchdog"
    assert br["round"] == INJECT
    assert br["info"]["rows"] == [
        {"round": INJECT, "word": (AMOUNT << watchdog_mod.DELTA_SHIFT)
         | watchdog_mod.V_CONSERVATION, "conservation": True,
         "negative": False, "digest": False, "age": False,
         "delta": AMOUNT}]
    verdict = watchdog_mod.poll(res.state.watchdog)
    assert verdict["first_breach_rnd"] == INJECT
    assert verdict["breaches"] == 1 and verdict["tripped"] == 0
    assert res.chunks[0]["watchdog"] == verdict

    # the plane-off run detects the same corruption via the delegated
    # host conservation check — at the boundary, 637 rounds late
    off = detection_runs[False]
    assert off.breaches >= 1
    (inj,) = [e for e in off.log if e["kind"] == "breach_injected"]
    assert inj["armed"] is False
    offs = [e for e in off.log if e["kind"] == "invariant_breach"]
    assert all(e["invariant"] == "conservation" for e in offs)
    boundary = min(e["round"] for e in offs)
    assert boundary == BOOT + ROUNDS                # the chunk boundary
    assert boundary - INJECT == ROUNDS - 643        # 637 rounds late


def test_opslog_detection_leg_uses_watchdog_round(detection_runs):
    """The incident span: armed, the ledger_breach detection leg is
    the watchdog's round (latency 0, cleared one round later); off,
    the only detect candidate is the boundary-round host breach."""
    j = opslog.from_soak(detection_runs[True])
    assert "watchdog" in j.streams
    spans = {s["rule"]: s for s in opslog.match(j)["spans"]}
    span = spans["ledger_breach"]
    assert span["status"] == "closed"
    assert span["cause_round"] == INJECT
    assert span["detect_event"] == "partisan.watchdog.breach_detected"
    assert span["detect_latency"] == 0              # round-exact
    assert span["recover_latency"] == 1             # cleared at +1

    j_off = opslog.from_soak(detection_runs[False])
    assert "watchdog" not in j_off.streams
    spans = {s["rule"]: s for s in opslog.match(j_off)["spans"]}
    span = spans["ledger_breach"]
    assert span["detect_event"] == "partisan.soak.invariant_breach"
    assert span["detect_latency"] == ROUNDS - 643   # boundary-late


def test_event_replay_edges(detection_runs):
    """replay_watchdog_events over the final ring: one detected edge
    at the injection round (word + delta), one cleared edge one round
    later, nothing else — and ops_watch's status line agrees."""
    snap = watchdog_mod.snapshot(detection_runs[True].state.watchdog)
    bus, rec = telemetry.Bus(), telemetry.Recorder()
    bus.attach("rec", ("partisan", "watchdog"), rec)
    n = telemetry.replay_watchdog_events(bus, snap)
    assert n == 2
    ((_, meas, meta),) = rec.of(telemetry.WATCHDOG_BREACH_DETECTED)
    assert meta["round"] == INJECT
    assert meas["delta"] == AMOUNT
    assert meas["word"] & watchdog_mod.V_CONSERVATION
    ((_, meas, meta),) = rec.of(telemetry.WATCHDOG_BREACH_CLEARED)
    assert meta["round"] == INJECT + 1
    assert meas["breach_rounds"] == 1
    assert not rec.of(telemetry.WATCHDOG_FLIGHT_TRIPPED)
    wd = opslog.watchdog_summary(opslog.from_soak(detection_runs[True]))
    assert wd == {"armed": True, "breaches": 1,
                  "first_breach_rnd": INJECT, "tripped": False}


def test_plane_off_and_on_bit_parity():
    """Off: the carry leaf is () and the run is bit-identical to a
    config without the plane.  On (no trip): every NON-watchdog leaf
    is still bit-identical — the plane observes, it never steers."""
    outs = {}
    for key, wd in (("absent", WatchdogConfig()),
                    ("off", WatchdogConfig(enabled=False, ring=8)),
                    ("on", WatchdogConfig(enabled=True, ring=8))):
        cl = Cluster(_cfg(watchdog=wd))
        st = cl.steps(_boot(cl), 40)
        outs[key] = st
    assert outs["absent"].watchdog == () and outs["off"].watchdog == ()
    support.assert_states_bitidentical(outs["absent"], outs["off"],
                                       "watchdog-off")
    assert outs["on"].watchdog != ()
    support.assert_states_bitidentical(
        outs["absent"], outs["on"]._replace(watchdog=()), "watchdog-on")
    assert watchdog_mod.poll(outs["on"].watchdog) == {
        "breaches": 0, "first_breach_rnd": -1, "tripped": 0}


def test_sharded_parity(mesh8):
    """Replication: the sharded round's watchdog leaf — ring, latch
    and trip word — is bit-identical to the single-device run's, with
    the injected breach latched at the same round on every shard."""
    from partisan_tpu.parallel import ShardedCluster

    cfg = _cfg(seed=21,
               watchdog=WatchdogConfig(enabled=True, ring=16,
                                       inject_round=BOOT + 20,
                                       inject_amount=2))
    local = Cluster(cfg)
    st_l = local.steps(_boot(local), 40)
    shard = ShardedCluster(cfg, mesh8)
    st_s = shard.steps(_boot(shard), 40)
    support.assert_states_bitidentical(st_l, st_s, "sharded-watchdog")
    assert watchdog_mod.poll(st_s.watchdog) \
        == watchdog_mod.poll(st_l.watchdog) \
        == {"breaches": 1, "first_breach_rnd": BOOT + 20, "tripped": 0}


def test_kill_restore_replays_latch(tmp_path):
    """Checkpoint/kill/restore bit-exactness, in the HARD direction:
    the run is killed BEFORE the injection round, so the fresh-engine
    resume must re-run the corruption from its checkpoint and latch
    the same first_breach_rnd the uninterrupted run latched."""
    inject = BOOT + 250
    cfg = _cfg(watchdog=WatchdogConfig(enabled=True, ring=64,
                                       inject_round=inject,
                                       inject_amount=AMOUNT))

    def mk():
        return Cluster(cfg)

    cl = mk()
    st = _boot(cl)
    ckpt = str(tmp_path / "ckpt")
    eng_a = soak.Soak(make_cluster=lambda: cl,
                      cfg=soak.SoakConfig(chunk_fixed=100,
                                          checkpoint_dir=ckpt))
    res_a = eng_a.run(st, until_round=BOOT + 200)   # killed pre-inject
    assert watchdog_mod.poll(res_a.state.watchdog)[
        "first_breach_rnd"] == -1
    eng_b = soak.Soak(make_cluster=mk,
                      cfg=soak.SoakConfig(chunk_fixed=100,
                                          checkpoint_dir=ckpt))
    res_b = eng_b.run(resume=True, until_round=BOOT + 400)
    eng_ref = soak.Soak(make_cluster=lambda: cl,
                        cfg=soak.SoakConfig(chunk_fixed=100))
    res_ref = eng_ref.run(st, until_round=BOOT + 400)
    support.assert_states_bitidentical(res_ref.state, res_b.state,
                                       "kill-restore")
    assert watchdog_mod.poll(res_b.state.watchdog) \
        == watchdog_mod.poll(res_ref.state.watchdog)
    assert watchdog_mod.poll(res_b.state.watchdog)[
        "first_breach_rnd"] == inject
    # both engines filed the round-exact soak verdict
    for res in (res_b, res_ref):
        (br,) = [e for e in res.log
                 if e["kind"] == "invariant_breach"]
        assert (br["invariant"], br["round"]) == ("watchdog", inject)


def test_trip_freezes_flight_ring(tmp_path):
    """Trip mode: the flight recorder's last written round is the
    breach round — the offending wire traffic survives arbitrarily far
    past the breach — and the frozen ring still round-trips through
    the Trace save/load path."""
    inject = BOOT + 20
    cfg = _cfg(flight_rounds=16,
               watchdog=WatchdogConfig(enabled=True, ring=16,
                                       trip_flight=True,
                                       inject_round=inject,
                                       inject_amount=AMOUNT))
    cl = Cluster(cfg)
    st = cl.steps(_boot(cl), 45)                    # 25 rounds past it
    assert watchdog_mod.poll(st.watchdog) == {
        "breaches": 1, "first_breach_rnd": inject, "tripped": 1}
    tr = latency_mod.flight_trace(st.flight)
    rounds = [int(r) for r in tr.rounds]
    # breach round written (the trip gate reads the CARRIED latch),
    # nothing after it — the ring froze 25 rounds ago
    assert max(rounds) == inject
    assert rounds == list(range(inject - 15, inject + 1))
    p = tmp_path / "frozen_flight.npz"
    tr.save(p)
    assert Trace.load(p).matches(tr)
    # without trip, the same config's ring holds the LAST 16 rounds
    cfg2 = _cfg(flight_rounds=16,
                watchdog=WatchdogConfig(enabled=True, ring=16,
                                        inject_round=inject,
                                        inject_amount=AMOUNT))
    cl2 = Cluster(cfg2)
    st2 = cl2.steps(_boot(cl2), 45)
    assert int(max(latency_mod.flight_trace(st2.flight).rounds)) \
        == BOOT + 45 - 1


def test_zero_cost_when_off_and_clean_when_on():
    """The scan lint: no round.watchdog scope and an empty carry leaf
    when off; the armed program (scope REQUIRED by the zero-cost
    rule's on-plane check) traces clean too."""
    for wd in (WatchdogConfig(),
               WatchdogConfig(enabled=True, ring=8)):
        cl = Cluster(_cfg(watchdog=wd))
        support.assert_scan_lint_clean(cl, _boot(cl), 6)


def test_config_validation():
    with pytest.raises(ValueError):
        Config(n_nodes=8, watchdog=WatchdogConfig(enabled=True))
    with pytest.raises(ValueError):
        Config(n_nodes=8, metrics=True,
               watchdog=WatchdogConfig(enabled=True, ring=0))
    with pytest.raises(ValueError):
        Config(n_nodes=8, metrics=True,
               watchdog=WatchdogConfig(enabled=True, trip_flight=True))
    with pytest.raises(ValueError):
        Config(n_nodes=8, metrics=True,
               watchdog=WatchdogConfig(enabled=True, age_bound=5))
    with pytest.raises(ValueError):
        Config(n_nodes=8, metrics=True,
               watchdog=WatchdogConfig(enabled=True, inject_round=3,
                                       inject_amount=0))
