"""Long-horizon mixed-fault soak: the system-level invariants under a
rolling storm of every fault class the test plane models.

The reference's long-running robustness evidence is its CT suites
cycling crash/partition/churn per group (partisan_SUITE.erl groups,
:214-315) — this is the simulator's equivalent: one 500-round run over
repeating fault cycles (iid link drop → crash batch → full partition →
heal → churn), asserting after EVERY heal window that

- the alive overlay re-converges to ONE component (healing works
  regardless of what the storm broke),
- a fresh plumtree broadcast reaches every alive node (the data plane
  recovers, not just the membership plane),
- stats accounting stays consistent (emitted == delivered + dropped —
  the round engine's conservation law).
"""

import jax.numpy as jnp
import numpy as np

from partisan_tpu import faults as faults_mod
from partisan_tpu.cluster import Cluster
from partisan_tpu.models.plumtree import Plumtree

from support import boot_hyparview, components, hv_config

N = 256


def _one_component(st) -> bool:
    alive = np.asarray(st.faults.alive)
    comps = components(np.asarray(st.manager.active), alive)
    return len(comps) == 1


def test_soak_500_rounds_mixed_faults():
    cfg = hv_config(N, seed=23, partition_mode="dense", max_broadcasts=8,
                    inbox_cap=16)
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    st = boot_hyparview(cl)
    window = cfg.rounds(cfg.hyparview.isolation_window_ms)
    rng = np.random.default_rng(41)
    slot = 0

    def heal_and_check(st, slot, phase):
        # clear all faults, give the heartbeat healing one window
        st = st._replace(faults=faults_mod.none(
            N, cfg.resolved_partition_mode)._replace(
                alive=st.faults.alive))
        alive_ids = np.flatnonzero(np.asarray(st.faults.alive))
        st = cl.steps(st, window + 30)
        assert _one_component(st), f"{phase}: overlay did not re-merge"
        src = int(rng.choice(alive_ids))
        ver = int(st.rnd)
        st = st._replace(model=model.broadcast(st.model, src, slot, ver))
        st, r = cl.run_until(
            st, lambda s, _sl=slot, _v=ver: float(model.coverage(
                s.model, s.faults.alive, _sl, version=_v)) >= 1.0,
            max_rounds=150, check_every=10)
        assert r != -1, f"{phase}: broadcast did not re-converge"
        s = st.stats
        assert int(s.emitted) == int(s.delivered) + int(s.dropped), phase
        return st, (slot + 1) % cfg.max_broadcasts

    # phase 1: iid link drop storm
    st = st._replace(faults=st.faults._replace(link_drop=jnp.float32(0.3)))
    st = cl.steps(st, 60)
    st, slot = heal_and_check(st, slot, "after link-drop storm")

    # phase 2: crash a random tenth of the cluster
    victims = rng.choice(N, size=N // 10, replace=False)
    alive = st.faults.alive
    for v in victims:
        alive = alive.at[int(v)].set(False)
    st = st._replace(faults=st.faults._replace(alive=alive))
    st = cl.steps(st, 60)
    st, slot = heal_and_check(st, slot, "after crash batch")

    # phase 3: full partition (two halves), then heal
    live = np.flatnonzero(np.asarray(st.faults.alive))
    half = live[: len(live) // 2]
    other = live[len(live) // 2:]
    st = st._replace(faults=faults_mod.inject_partition(
        st.faults, [int(x) for x in half], [int(x) for x in other]))
    st = cl.steps(st, 60)
    st, slot = heal_and_check(st, slot, "after partition")

    # phase 4: churn (birth/death) for 100 rounds
    churn = lambda f, rnd: faults_mod.churn_step(  # noqa: E731
        f, cfg.seed, rnd, 0.01, 0.01)
    for _ in range(10):
        st = st._replace(faults=churn(st.faults, st.rnd))
        st = cl.steps(st, 10)
    st, slot = heal_and_check(st, slot, "after churn")

    assert int(st.rnd) >= 500, int(st.rnd)


def test_soak_p2p_streams_under_crash_recovery_cycles():
    """Delivery-plane soak: long-horizon p2p-causal streams while their
    receivers repeatedly crash and recover.  Across every cycle the
    per-edge guarantee must hold: each receiver's log is duplicate-free
    and per-sender FIFO (crash windows may drop in-flight sends — the
    reference's causality backend loses what a dead node never stored —
    but nothing may be reordered or delivered twice)."""
    from partisan_tpu.config import Config
    from partisan_tpu.models.p2p_chat import P2PChat

    n = 32
    cfg = Config(n_nodes=n, seed=31, causal_p2p_labels=("chat",),
                 peer_service_manager="static")
    model = P2PChat()
    cl = Cluster(cfg, model=model)
    st = cl.init()
    rng = np.random.default_rng(17)
    senders = [1, 2, 3]
    receivers = [20, 21, 22]

    for cycle in range(4):
        # each sender fires two messages at its receiver this cycle
        m = st.model
        base = int(st.rnd)
        for i, s in enumerate(senders):
            m = model.schedule(m, node=s, rnd=base + 2, dst=receivers[i],
                               now=base + 1)
            m = model.schedule(m, node=s, rnd=base + 5, dst=receivers[i],
                               now=base + 1)
        st = st._replace(model=m)
        # crash one receiver mid-stream, then recover it
        victim = receivers[cycle % len(receivers)]
        st = cl.steps(st, 3)
        st = st._replace(faults=faults_mod.crash(st.faults, victim))
        st = cl.steps(st, 4)
        st = st._replace(faults=faults_mod.recover(st.faults, victim))
        st = cl.steps(st, cfg.retransmit_every * 6 + 6)

    logs = P2PChat.logs(st.model)
    delivered = 0
    for r in receivers:
        log = logs[r]
        assert len(log) == len(set(log)), f"node {r} duplicates: {log}"
        per_src = {}
        for t in log:
            per_src.setdefault(t // P2PChat.K, []).append(t % P2PChat.K)
        for src, seqs in per_src.items():
            assert seqs == sorted(seqs), \
                f"node {r} reordered stream from {src}: {seqs}"
        delivered += len(log)
    # the never-crashed cycles must deliver fully: at least half of all
    # sends land even with one receiver down per cycle
    assert delivered >= 12, f"only {delivered} of 24 sends delivered"


def test_boot_ladder_single_component_aligned_timers():
    """Regression guard for the r5 fragmentation fix: the width-ladder
    bootstrap under ALIGNED timers (bench configuration) must end with
    ONE connected component and converge a broadcast in the validated
    ~20-round envelope.  Factor-8 waves on the upper rungs measured
    6-14 disconnected islands at 100k (BENCH_NOTES r5); the default
    gentle upper rungs must keep this property at CPU scale too."""
    from partisan_tpu.config import Config, PlumtreeConfig
    from partisan_tpu.scenarios import _boot_ladder

    n = 4096
    model = Plumtree()

    def mk(width):
        return Cluster(Config(
            n_nodes=width, seed=1, peer_service_manager="hyparview",
            msg_words=16, partition_mode="groups", max_broadcasts=8,
            inbox_cap=16, emit_compact=32, timer_stagger=False,
            plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4)),
            model=model)

    cl, st = _boot_ladder(mk, n, widths=[1024, n])
    act = np.asarray(st.manager.active)
    alive = np.asarray(st.faults.alive)
    assert len(components(act, alive)) == 1
    st = st._replace(model=model.broadcast(st.model, 0, 0, int(st.rnd)))
    r0 = int(st.rnd)
    st, conv = cl.run_until(
        st, lambda s: float(model.coverage(
            s.model, s.faults.alive, 0)) == 1.0,
        max_rounds=60, check_every=5)
    assert conv != -1 and conv - r0 <= 30, (conv, r0)
