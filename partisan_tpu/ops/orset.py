"""Membership set: a rank-reduced OR-set over node ids.

The reference wraps ``state_orset`` (per-actor dot sets) in
partisan_membership_set.erl:116-213, whose observable semantics on node
specs are: add wins over concurrent absence, observed-remove deletes only
adds you have seen, and a node that leaves and rejoins is distinguished by
a fresh spec (staleness discussion, partisan_membership_set.erl:23-60).

Full per-actor dot sets explode at scale (SURVEY.md §7 "CRDT OR-set at
scale"), so the TPU encoding is rank-reduced: each node's view holds two
uint32 counters per member,

    add[j] — highest incarnation of j this view has observed joining
    rm[j]  — highest incarnation of j this view has observed leaving

with ``member(j) = add[j] > rm[j]`` and merge = elementwise max of both.
Incarnations play the role of dots: a rejoin bumps j's incarnation above
any observed remove, reproducing the OR-set's add/remove/re-add behavior
for the single-actor-per-spec case the managers actually exercise (each
node only ever adds/removes its own spec or relays others' observed
state).  ``compare`` mirrors partisan_membership_set:compare → {joiners,
leavers}.

A node view is ``uint32[2, n]`` (stacked add/rm) so a whole cluster's
views are ``uint32[n, 2, n]`` and a gossip round is one scatter-max.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array

DTYPE = jnp.uint32


def fresh_views(n: int) -> Array:
    """Every node starts knowing only itself at incarnation 1 (the
    reference boots a new OR-Set containing self —
    partisan_full_membership_strategy.erl:70-82)."""
    add = jnp.eye(n, dtype=DTYPE)
    rm = jnp.zeros((n, n), DTYPE)
    return jnp.stack([add, rm], axis=1)  # [n, 2, n]


def members(view: Array) -> Array:
    """bool[...] mask of live members in a view [..., 2, n]."""
    return view[..., 0, :] > view[..., 1, :]


def add(view: Array, member: Array, incarnation: Array | int = 1) -> Array:
    """Observe ``member`` joining at ``incarnation`` (max-merge)."""
    onehot = jnp.arange(view.shape[-1]) == member
    bumped = jnp.maximum(view[..., 0, :], jnp.where(onehot, DTYPE(incarnation), 0))
    return view.at[..., 0, :].set(bumped)


def remove(view: Array, member: Array) -> Array:
    """Observed-remove: delete every incarnation of ``member`` this view
    has seen (partisan_full_membership_strategy.erl:171-210 leave)."""
    onehot = jnp.arange(view.shape[-1]) == member
    newrm = jnp.where(onehot, jnp.maximum(view[..., 1, :], view[..., 0, :]),
                      view[..., 1, :])
    return view.at[..., 1, :].set(newrm)


def merge(a: Array, b: Array) -> Array:
    """CRDT join — elementwise max over both planes."""
    return jnp.maximum(a, b)


def equal(a: Array, b: Array) -> Array:
    return jnp.all(a == b, axis=(-2, -1))


def compare(old: Array, new: Array) -> tuple[Array, Array]:
    """(joiners, leavers) bool masks — partisan_membership_set:compare/2
    feeding the up/down callbacks
    (partisan_pluggable_peer_service_manager.erl:1583-1597)."""
    mo, mn = members(old), members(new)
    return mn & ~mo, mo & ~mn
