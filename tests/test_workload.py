"""Traffic-plane tests (partisan_tpu/workload.py): deterministic
open-loop arrivals, heavy-tailed shape, timeline actions through the
soak storm, zero cost when off, and the crash-replay acceptance gate —
a >=2000-round soak with traffic + storm surviving an injected worker
crash and replaying the arrival stream bit-for-bit from checkpoint."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from partisan_tpu import faults as faults_mod
from partisan_tpu import soak
from partisan_tpu import workload as W
from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config, TrafficConfig
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import plane as plane_ops

from support import assert_states_bitidentical


def _cfg(n=24, **kw):
    kw.setdefault("traffic", TrafficConfig(enabled=True, rate_x1000=800,
                                           ring=32))
    kw.setdefault("partition_mode", "groups")
    return Config(n_nodes=n, seed=3, peer_service_manager="hyparview",
                  msg_words=16, **kw)


def _ctx(cl, rnd=5, n_active=()):
    n = cl.cfg.n_nodes
    return RoundCtx(rnd=jnp.int32(rnd), alive=jnp.ones((n,), jnp.bool_),
                    keys=None, inbox=None,
                    faults=faults_mod.none(n, "groups"),
                    n_active=n_active, control=(), seed=cl.cfg.seed)


def _gen(cl, rnd=5, n_active=()):
    ts, emitted = W.generate(cl.cfg, cl.comm, W.init(cl.cfg),
                             _ctx(cl, rnd, n_active))
    return ts, np.asarray(jax.device_get(plane_ops.interleave(emitted)))


# ---------------------------------------------------------------------------
# The generator
# ---------------------------------------------------------------------------

def test_arrivals_deterministic_and_rate_shaped():
    """Same config => bit-identical arrival stream; the mean arrival
    count tracks the configured rate; bursts stay within burst_max."""
    cl = Cluster(_cfg())
    st = cl.init()
    m = cl.manager.join_many(cl.cfg, st.manager, list(range(1, 24)),
                             [0] * 23)
    st = cl.steps(st._replace(manager=m), 30)
    snap = W.snapshot(st.traffic)
    # a second, independently built cluster replays the identical stream
    cl2 = Cluster(_cfg())
    st2 = cl2.steps(cl2.init()._replace(manager=m), 30)
    snap2 = W.snapshot(st2.traffic)
    assert np.array_equal(snap["arrivals"], snap2["arrivals"])
    assert snap["sent"] == snap2["sent"] > 0
    # open-loop rate: 24 nodes x 0.8/round ~= 19; allow wide tolerance
    mean = float(snap["arrivals"][snap["rounds"] >= 0].mean())
    assert 0.5 * 19.2 <= mean <= 1.5 * 19.2, mean
    # conservation through the normal wire stages
    s = jax.device_get(st.stats)
    assert int(s.emitted) == int(s.delivered) + int(s.dropped)


def test_burst_bound_and_channel():
    """Every generated record is APP on the configured channel with an
    in-range destination; per-node bursts never exceed burst_max."""
    from partisan_tpu import types as T

    cl = Cluster(_cfg(traffic=TrafficConfig(
        enabled=True, rate_x1000=5000, burst_max=3, ring=8)))
    _ts, rec = _gen(cl)
    kind = rec[..., T.W_KIND]
    live = kind != 0
    assert rec.shape[1] == 3                     # burst_max slots
    assert live.any()
    assert (kind[live] == int(T.MsgKind.APP)).all()
    assert (rec[..., T.W_CHANNEL][live]
            == cl.cfg.channel_id(cl.cfg.traffic.channel)).all()
    dst = rec[..., T.W_DST][live]
    assert (0 <= dst).all() and (dst < cl.cfg.n_nodes).all()
    # no self-sends
    src = rec[..., T.W_SRC][live]
    assert (src != dst).all()


def test_hot_skew_concentrates_destinations():
    """hot_skew squares the destination draw toward low ids: the hot
    eighth of the id space receives a clearly super-uniform share."""
    from partisan_tpu import types as T

    def share(hot_skew):
        cl = Cluster(_cfg(n=64, traffic=TrafficConfig(
            enabled=True, rate_x1000=4000, burst_max=4,
            hot_skew=hot_skew, ring=8)))
        dsts = []
        for rnd in range(1, 30):
            _ts, rec = _gen(cl, rnd=rnd)
            live = rec[..., T.W_KIND] != 0
            dsts.append(rec[..., T.W_DST][live])
        d = np.concatenate(dsts)
        return float((d < 8).mean())

    uniform = share(0)
    hot = share(2)
    assert uniform < 0.25, uniform      # ~1/8 under the uniform draw
    assert hot > 2 * uniform, (hot, uniform)


def test_width_operand_prefix_parity():
    """Arrivals on an n_active=w prefix match a native n_nodes=w run
    bit-for-bit (rows [0, w)): the draws key off the operand, and
    inert rows stay silent."""
    w = 16
    cl_wide = Cluster(_cfg(n=32, width_operand=True))
    cl_nat = Cluster(_cfg(n=w))
    ctx_w = _ctx(cl_wide, rnd=7, n_active=jnp.int32(w))
    # inert rows read dead through ctx.alive, like round_body masks them
    ctx_w = ctx_w._replace(
        alive=ctx_w.alive & (jnp.arange(32) < w))
    _, em_w = W.generate(cl_wide.cfg, cl_wide.comm,
                         W.init(cl_wide.cfg), ctx_w)
    _, em_n = W.generate(cl_nat.cfg, cl_nat.comm,
                         W.init(cl_nat.cfg), _ctx(cl_nat, rnd=7))
    rw = np.asarray(jax.device_get(plane_ops.interleave(em_w)))
    rn = np.asarray(jax.device_get(plane_ops.interleave(em_n)))
    assert np.array_equal(rw[:w], rn)
    assert (rw[w:, :, 0] == 0).all()    # inert rows emit nothing


def test_traffic_off_zero_cost_and_scan_lint():
    """Off (the default): the carry leaf is () — and the traced scan
    with traffic ON stays lint-clean (no-host-callback, zero-cost keys
    for the OTHER planes, narrow dtypes, scatter overlap)."""
    from support import assert_scan_lint_clean

    cl_off = Cluster(Config(n_nodes=16, seed=3, msg_words=16,
                            peer_service_manager="hyparview",
                            partition_mode="groups"))
    assert cl_off.init().traffic == ()
    cl_on = Cluster(_cfg(n=16))
    assert_scan_lint_clean(cl_on, cl_on.init(), k=4)


# ---------------------------------------------------------------------------
# Timeline actions
# ---------------------------------------------------------------------------

def test_actions_validate_prerequisites():
    cl_off = Cluster(Config(n_nodes=8, seed=1))
    st = cl_off.init()
    with pytest.raises(ValueError, match="traffic plane"):
        W.SetRate(2000).apply(cl_off, st, 0)
    cl_nochurn = Cluster(_cfg(n=8))
    st2 = cl_nochurn.init()
    with pytest.raises(ValueError, match="churn stage"):
        W.SetChurn(1000).apply(cl_nochurn, st2, 0)
    with pytest.raises(ValueError, match="StragglerDelay"):
        W.Stragglers(nodes=(1,), mult=2).apply(cl_nochurn, st2, 0)


def test_timeline_composes_with_storm_actions():
    """flash_crowd + diurnal + diurnal_churn build sorted event tuples
    that merge with fault actions into ONE soak.Storm."""
    ev = W.flash_crowd(10, 20, 3000, 500)
    assert [off for off, _ in ev] == [10, 30]
    di = W.diurnal(80, 200, 1000, steps=2)
    # the wave CLOSES at the base level (a one-shot splice must not
    # strand the elevated rate; the closing offset clamps inside the
    # period so repeating storms stay valid)
    assert [off for off, _ in di] == [0, 20, 40, 60, 79]
    assert [a.x1000 for _, a in di] == [200, 600, 1000, 600, 200]
    dc = W.diurnal_churn(80, 8000, steps=2)
    assert isinstance(dc[0][1], W.SetChurn)
    assert dc[-1][1].x1e6 == 0 and dc[-1][0] < 80
    storm = W.Traffic(ev).storm(
        start=5, extra=((0, soak.LinkDrop(0.1)),))
    assert [a.__class__.__name__ for a in storm.due(5)] == ["LinkDrop"]
    assert [a.__class__.__name__ for a in storm.due(15)] == ["SetRate"]


def test_directed_cut_action_one_way():
    cl = Cluster(_cfg(n=8, partition_mode="dense"))
    st = cl.init()
    st = W.DirectedCut(src=(1, 2), dst=(5,)).apply(cl, st, 0)
    cut_fwd = faults_mod.edge_cut(st.faults, jnp.asarray([1]),
                                  jnp.asarray([5]), 0, jnp.int32(0), 1)
    cut_rev = faults_mod.edge_cut(st.faults, jnp.asarray([5]),
                                  jnp.asarray([1]), 0, jnp.int32(0), 1)
    assert bool(cut_fwd[0]) and not bool(cut_rev[0])
    healed = soak.Heal().apply(cl, st, 0)
    assert not bool(np.asarray(healed.faults.partition).any())


def test_in_scan_churn_rate_rides_the_carry():
    """SetChurn arms the in-scan birth/death stage; churn_x1e6=0 (the
    init value) leaves liveness bit-identical to a churn-compiled run
    that never arms it."""
    cfg = _cfg(n=24, traffic=TrafficConfig(enabled=True, rate_x1000=500,
                                           churn=True, ring=16))
    cl = Cluster(cfg)
    st0 = cl.init()
    quiet = cl.steps(st0, 20)
    assert bool(np.asarray(quiet.faults.alive).all())
    armed = W.SetChurn(50_000).apply(cl, st0, 0)    # 5%/round
    churned = cl.steps(armed, 20)
    alive = int(np.asarray(churned.faults.alive).sum())
    assert alive < 24, "5%/round churn over 20 rounds killed nobody"


# ---------------------------------------------------------------------------
# The acceptance gate: long soak + storm + crash, bit-exact replay
# ---------------------------------------------------------------------------

def test_2000_round_traffic_soak_survives_crash_bitexact(tmp_path):
    """A >=2000-round soak under a repeating traffic+fault storm
    (periodic flash crowds, diurnal churn ramps, link-drop pulses)
    survives an injected worker crash mid-horizon — retry, fresh
    context, checkpoint restore — and the final state (arrival stream
    included) is bit-identical to the unchunked reference
    composition."""
    rounds = 2000
    cfg = _cfg(n=32, traffic=TrafficConfig(
        enabled=True, rate_x1000=400, churn=True, hot_skew=1, ring=64))

    def mk():
        return Cluster(cfg)

    cl = mk()
    st = cl.init()
    m = cl.manager.join_many(cl.cfg, st.manager, list(range(1, 32)),
                             [0] * 31)
    st = cl.steps(st._replace(manager=m), 20)
    r0 = int(jax.device_get(st.rnd))
    period = 400
    # Every offset is a multiple of 100 so both the chunked run and
    # the unchunked reference execute ONE scan length — the test's
    # wall cost is runtime, not a compile per storm gap.  (The churn
    # window is hand-rolled for that alignment; the diurnal_churn
    # builder's shape is unit-tested above.)
    storm = W.Traffic(
        W.flash_crowd(100, 100, 2500, 400)
        + ((100, W.SetChurn(6000)), (300, W.SetChurn(0)))
        + ((200, soak.LinkDrop(0.1)), (300, soak.Heal()))
    ).storm(start=r0, period=period)

    crash_round = r0 + 1000
    fired = {"done": False}

    def step(c, s, k):
        r = int(jax.device_get(s.rnd))
        if not fired["done"] and r + k > crash_round:
            fired["done"] = True
            raise jax.errors.JaxRuntimeError("injected worker crash")
        return c.steps(s, k)

    eng = soak.Soak(
        make_cluster=mk, storm=storm, step_fn=step,
        invariants=[soak.conservation()],
        cfg=soak.SoakConfig(chunk_fixed=200,
                            checkpoint_dir=str(tmp_path),
                            cooldown_s=0.0),
        sleep_fn=lambda s: None)
    res = eng.run(st, rounds=rounds)
    assert res.rounds == rounds
    assert res.retries == 1 and fired["done"]
    assert res.breaches == 0

    ref = soak.reference_run(mk(), st, r0 + rounds, storm=storm)
    assert_states_bitidentical(res.state, ref, "traffic_soak_vs_ref")
    assert W.poll(res.state.traffic) == W.poll(ref.traffic)
    assert W.poll(res.state.traffic)["sent"] > 0

def test_windowed_p99_reanchors_at_restore(tmp_path):
    """poll_latency windows after a crash-retry rewind diff from the
    CHECKPOINT's histograms, not from init: the replayed rows must
    equal an undisturbed run's rows (a None anchor would make the
    first post-restore window cumulative and double-count everything
    the kept rows already covered)."""
    cfg = _cfg(n=24, latency=True)

    def mk():
        return Cluster(cfg)

    cl = mk()
    st = cl.init()
    m = cl.manager.join_many(cl.cfg, st.manager, list(range(1, 24)),
                             [0] * 23)
    st = cl.steps(st._replace(manager=m), 10)

    def run(crash_at):
        fired = {"done": False}

        def step(c, s, k):
            r = int(jax.device_get(s.rnd))
            if crash_at is not None and not fired["done"] \
                    and r + k > crash_at:
                fired["done"] = True
                raise jax.errors.JaxRuntimeError("injected crash")
            return c.steps(s, k)

        eng = soak.Soak(
            make_cluster=mk, step_fn=step,
            cfg=soak.SoakConfig(chunk_fixed=10, cooldown_s=0.0,
                                checkpoint_dir=str(tmp_path),
                                poll_latency=True),
            sleep_fn=lambda s: None)
        return eng.run(st, rounds=60)

    r0 = int(jax.device_get(st.rnd))
    clean = run(None)
    crashed = run(r0 + 35)
    assert crashed.retries == 1
    assert [c["p99"] for c in crashed.chunks] \
        == [c["p99"] for c in clean.chunks]


def test_replay_traffic_events_windows():
    """telemetry.replay_traffic_events: edge-triggered flash crowds and
    maximal consecutive breach windows from synthetic chunk rows."""
    from partisan_tpu import telemetry

    rows = [
        {"round": 0, "k": 10, "traffic": {"rate_x1000": 500, "sent": 1},
         "p99": {"bulk": 1, "default": 1}},
        {"round": 10, "k": 10, "traffic": {"rate_x1000": 4000, "sent": 2},
         "p99": {"bulk": 6, "default": 1}},
        {"round": 20, "k": 10, "traffic": {"rate_x1000": 4000, "sent": 3},
         "p99": {"bulk": 9, "default": 2}},
        {"round": 30, "k": 10, "traffic": {"rate_x1000": 500, "sent": 4},
         "p99": {"bulk": 2, "default": 1}},
        {"round": 40, "k": 10, "traffic": {"rate_x1000": 500, "sent": 5},
         "p99": {"bulk": 7, "default": 1}},
    ]
    rec = telemetry.Recorder()
    bus = telemetry.Bus()
    bus.attach("t", ("partisan", "traffic"), rec)
    n = telemetry.replay_traffic_events(bus, rows, slo_rounds=4)
    kinds = [e[0] for e in rec.events]
    assert n == 3
    assert kinds.count(telemetry.TRAFFIC_FLASH_CROWD) == 1
    windows = [e for e in rec.events
               if e[0] == telemetry.TRAFFIC_SLO_BREACH_WINDOW]
    assert len(windows) == 2
    first = windows[0]
    assert first[1]["worst_p99"] == 9 and first[1]["chunks"] == 2
    assert first[2]["round"] == 10 and first[2]["end_round"] == 30
    assert first[2]["channel"] == "bulk"
