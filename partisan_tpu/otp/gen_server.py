"""partisan_gen_server: the server-side loop (reference
priv/otp/24/partisan_gen_server.erl, 1360 LoC).

A :class:`GenServer` runs one server process on a port: it drains the
mailbox each scheduler pass and dispatches ``{'$gen_call', {Self, Mref},
Req}`` / ``{'$gen_cast', Req}`` control messages to a user *module* —
the handle_call/handle_cast callback object — pairing every reply with
its caller's Mref (the partisan_gen call protocol, partisan_gen.erl
:360-400).  ``Stop`` from a callback terminates the server: the stop
request itself is replied to, then all further messages are ignored
(the dead-process behavior the suite's stopped-server case checks).

The client side is :class:`partisan_tpu.otp.gen.Caller`.
"""

from __future__ import annotations

from typing import NamedTuple, Protocol

from partisan_tpu.otp import gen


class Stop(NamedTuple):
    """handle_call return: reply, then terminate the server."""

    ok: bool = True
    value: int = 0


class Module(Protocol):
    """The gen_server callback module."""

    def handle_call(self, fn: int, arg: int, src: int):
        """-> (ok, value) reply, or Stop(ok, value) to terminate."""
        ...

    def handle_cast(self, fn: int, arg: int, src: int) -> None:
        ...


class GenServer(gen.Proc):
    def __init__(self, port: gen.Port, module: Module) -> None:
        super().__init__(port)
        self.module = module
        self.stopped = False

    def process(self, _rnd: int = 0) -> None:
        """One scheduler pass of the server process."""
        for src, words in self.drain():
            if self.stopped:
                continue
            op = words[0]
            if op == gen.OP_CALL:
                mref, fn, arg = words[1], words[2], words[3]
                out = self.module.handle_call(fn, arg, src)
                if isinstance(out, Stop):
                    self.stopped = True
                    gen.reply(self, src, mref, out.ok, out.value)
                else:
                    ok, value = out
                    gen.reply(self, src, mref, ok, value)
            elif op == gen.OP_CAST:
                self.module.handle_cast(words[2], words[3], src)
