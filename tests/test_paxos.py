"""Single-decree Paxos as the in-repo consensus application-under-test
(the prop_partisan_paxoid.erl:385 role): protocol behavior, the
property harness at the crash-fault budget, the planted
quorum-intersection bug caught AND shrunk, and a filibuster omission
search over the proposal exchange.
"""

import numpy as np

from partisan_tpu import faults as faults_mod
from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config
from partisan_tpu.models.paxos import Paxos
from partisan_tpu.prop import CrashFaultModel, Harness
from partisan_tpu.prop_models import PaxosSystem

N = 5


def build(slots=2, quorum=None, **kw):
    model = Paxos(slots=slots, quorum=quorum)
    cfg = Config(n_nodes=N, seed=7, msg_words=13, inbox_cap=64,
                 **kw)
    cl = Cluster(cfg, model=model)
    st = cl.init()
    for i in range(1, N):
        st = st._replace(manager=cl.manager.join(cfg, st.manager, i, 0))
    st = cl.steps(st, 5)
    return cl, model, st


def test_single_proposer_decides_everywhere():
    cl, model, st = build()
    st = st._replace(model=model.propose(st.model, 2, 0, 111,
                                         int(st.rnd), N))
    st = cl.steps(st, 12)
    assert model.decided_nodes(st.model, 0) == list(range(N))
    assert {int(v) for v in np.asarray(st.model.decided)[:, 0]} == {111}
    assert model.agreement(st.model)


def test_competing_proposers_agree_on_one_value():
    cl, model, st = build()
    m = model.propose(st.model, 1, 0, 100, int(st.rnd), N)
    m = model.propose(m, 3, 0, 300, int(st.rnd), N)
    st = st._replace(model=m)
    st = cl.steps(st, 60)
    assert model.agreement(st.model)
    decided = {int(v) for v in np.asarray(st.model.decided)[:, 0]
               if v >= 0}
    assert len(decided) == 1 and decided <= {100, 300}
    assert len(model.decided_nodes(st.model, 0)) == N


def test_decision_survives_minority_crashes():
    cl, model, st = build()
    st = st._replace(model=model.propose(st.model, 0, 0, 42,
                                         int(st.rnd), N))
    st = cl.steps(st, 12)
    assert 42 in np.asarray(st.model.decided)[:, 0]
    # crash two acceptors, then a NEW proposer must still learn 42
    st = st._replace(faults=faults_mod.crash(st.faults, 3))
    st = st._replace(faults=faults_mod.crash(st.faults, 4))
    st = st._replace(model=model.propose(st.model, 1, 0, 999,
                                         int(st.rnd), N))
    st = cl.steps(st, 40)
    assert model.agreement(st.model)
    vals = {int(v) for v in np.asarray(st.model.decided)[:, 0] if v >= 0}
    assert vals == {42}           # the earlier decree wins; 999 cannot


def test_omitted_decide_leaves_learners_undecided_but_safe():
    """Omission of the proposer's DECIDE fan-out: nobody else learns,
    but no disagreement appears (safety under omission)."""
    from partisan_tpu import interpose
    from partisan_tpu import types as T

    def drop_decides(cfg, ctx, em):
        from partisan_tpu.models.paxos import OP_DECIDE
        return (em[..., T.W_KIND] == T.MsgKind.APP) \
            & (em[..., T.P0] == OP_DECIDE)

    model = Paxos(slots=1)
    cfg = Config(n_nodes=N, seed=7, msg_words=13, inbox_cap=64)
    cl = Cluster(cfg, model=model, interpose=interpose.Drop(drop_decides))
    st = cl.init()
    for i in range(1, N):
        st = st._replace(manager=cl.manager.join(cfg, st.manager, i, 0))
    st = cl.steps(st, 5)
    st = st._replace(model=model.propose(st.model, 2, 0, 77,
                                         int(st.rnd), N))
    st = cl.steps(st, 20)
    assert model.agreement(st.model)
    assert model.decided_nodes(st.model, 0) == [2]  # only the proposer


def test_prop_harness_passes_at_fault_budget():
    """The reference's check-paxoid.sh run: random proposals + crash and
    omission faults within tolerance; safety and conditional liveness
    hold."""
    sys = PaxosSystem(n_nodes=5, slots=2, seed=3)
    h = Harness(system=sys,
                fault_model=CrashFaultModel(tolerance=1),
                scheduler="finite_fault", n_runs=4, n_commands=5,
                seed=21)
    res = h.run()
    assert res.ok, res.render()


def test_weakened_adoption_rule_is_caught_and_shrunk():
    """unsafe_adopt breaks the Synod adoption rule: a later ballot
    pushes its own value over an already-chosen one, so two proposals
    on one decree choose DIFFERENT values.  The harness must FIND the
    disagreement and SHRINK the script to the two proposals."""
    sys = PaxosSystem(n_nodes=5, slots=1, seed=3, unsafe_adopt=True,
                      check_termination=False)
    h = Harness(system=sys, n_runs=8, n_commands=6, seed=5)
    res = h.run()
    assert not res.ok
    assert res.shrunk is not None and len(res.shrunk) <= 3
    assert all(c.name == "propose" for c in res.shrunk)
    assert len(res.shrunk) >= 2          # it takes two to disagree


def test_filibuster_omission_search_passes_on_correct_paxos():
    """Filibuster explores single-omission schedules over the proposal
    exchange; correct Paxos survives every one (the retry path heals)."""
    from partisan_tpu import filibuster
    from partisan_tpu import types as T

    model = Paxos(slots=1, retry_rounds=6)

    def build_fb(ip):
        cfg = Config(n_nodes=5, seed=11, msg_words=13, inbox_cap=64)
        cl = Cluster(cfg, model=model, interpose=ip)
        st = cl.init()
        for i in range(1, 5):
            st = st._replace(manager=cl.manager.join(cfg, st.manager,
                                                     i, 0))
        st = cl.steps(st, 5)
        st = st._replace(model=model.propose(st.model, 2, 0, 55,
                                             int(st.rnd), 5))
        return cl, st

    def assertion(cl, st):
        if not model.agreement(st.model):
            return False
        # liveness at the budget: the (alive) proposer re-drives the
        # decree through retries despite any single omission
        return len(model.decided_nodes(st.model, 0)) == 5

    chk = filibuster.Checker(
        build=build_fb, horizon=40, assertion=assertion,
        candidate=lambda e: e.kind == T.MsgKind.APP,
        max_faults=1, max_executions=60)
    res = chk.run()
    assert res.passed, res.render()
    assert res.executions > 10           # the search actually searched