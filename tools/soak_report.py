"""Soak-engine JSON-lines exporter (the ``BENCH_*.json`` idiom: one
self-describing JSON object per line).

Boots a HyParView+Plumtree overlay with the health plane on, then
drives it through the chunked soak engine (partisan_tpu/soak.py) under
a repeating fault storm — printing one line per chunk (round, size,
wall, rounds/s, dispatch gap, health digest), a ``dispatch_wall``
decomposition of the whole run into in-execution vs dispatch-gap time
(partisan_tpu/perfwatch.py), one line per recovery/breach event
(``chunk_retry`` / ``checkpoint_restored`` / ``invariant_breach`` with
its dump paths), the replayed ``partisan.soak.*`` bus events, and a
trailing summary::

    python tools/soak_report.py [n] [rounds] [--chunk K] [--crash-at R]
                                [--breach] [--control] [--traffic]
                                [--elastic] [--ckpt-dir DIR] [--spool]

``--spool`` arms the full-horizon telemetry spool (spool.py) on a
temp file (path announced as a ``{"kind": "spool"}`` line — tail it
live with ``tools/ops_watch.py --follow``): every chunk boundary
drains each plane's ring delta, chunk rows carry the measured drain
cost (``spool_s``), the ``dispatch_wall`` line separates that cost
from the dispatch gap, and the summary prints the drain-cost column
(``spool_s`` total + ``spool_chunks``).

``--crash-at R`` injects a ``JaxRuntimeError`` into the first chunk
dispatch that would cross R rounds into the soak — off-TPU proof of
the retry/backoff + checkpoint-restore path (the minute-mark worker
crash, tools/MINUTE_FAULT.md).  ``--breach`` holds a partition across the
final quarter with the one-component invariant armed, so the output
shows a real ``invariant_breach`` with black-box dumps.  ``--control``
closes the loop: all three in-scan controllers (control.py — plumtree
fanout governor, channel backpressure, healing escalation) ride the
soak with their prerequisite planes, every chunk row carries the
operands in force (``control``: eager cap / pressure / boost), and the
replayed ``partisan.control.*`` decision events print alongside the
soak events.  ``--traffic`` turns on the open-loop workload generator
(workload.py) with a mid-run flash crowd scripted through the same
storm: every chunk row carries the generator's operands (``traffic``:
rate / churn / cumulative arrivals) plus a WINDOWED per-channel p99
(``p99``, the latency plane's cumulative histograms diffed at chunk
boundaries), and the replayed ``partisan.traffic.*`` events
(``flash_crowd``, ``slo_breach_window``) print alongside the soak
events.  ``--elastic`` boots at HALF the capacity and scripts a
scale-out to full width plus a graceful scale-in (leave-path drain +
in-scan deactivation) through the same storm: every chunk row carries
the elastic operands in force (``elastic``: active width / pending
drain / resize count), and the replayed ``partisan.elastic.*`` resize
events print alongside the soak events.  Every run also prints its
matched incident spans (``ops_span`` lines — fault injected ->
detected -> reacted -> recovered, with round latencies; opslog.py)
and folds the span counts + gate verdict into the summary.
Importable: ``report(result)`` renders any ``soak.SoakResult``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def report(res, out=sys.stdout, channels=None, slo_rounds=None,
           storm=None) -> dict:
    """Dump a ``soak.SoakResult`` as JSON lines; returns (and prints as
    the last line) the summary dict.  ``channels`` optionally names the
    config's channels so controller shed events carry real labels;
    ``slo_rounds`` arms the traffic replay's breach-window events when
    chunk rows carry the windowed p99 series.  ``storm`` (the timeline
    the run was driven under) arms the incident observatory: the run
    fuses into an ops journal (``opslog.from_soak``), the matched
    detect->react->recover spans print as ``ops_span`` lines, and the
    summary carries the span counts + gate verdict."""
    from partisan_tpu import telemetry

    for row in res.chunks:
        print(json.dumps({"kind": "chunk", **row}), file=out)
    for entry in res.log:
        print(json.dumps(entry, default=str), file=out)
    rec = telemetry.Recorder()
    bus = telemetry.Bus()
    bus.attach("report", ("partisan", "soak"), rec)
    telemetry.replay_soak_events(bus, res.log)
    if getattr(res.state, "elastic", ()) != ():
        # resize events (scale_out / scale_in), replayed from the
        # in-scan elastic timeline ring
        from partisan_tpu import elastic as elastic_mod

        bus.attach("elastic", ("partisan", "elastic"), rec)
        telemetry.replay_elastic_events(
            bus, elastic_mod.snapshot(res.state.elastic))
    if any(e.get("kind") == "ingress_drain" for e in res.log):
        bus.attach("ingress", ("partisan", "ingress"), rec)
        telemetry.replay_ingress_events(bus, res.log)
    if any("traffic" in row for row in res.chunks):
        # traffic-plane events (flash_crowd / slo_breach_window),
        # replayed from the chunk rows' operand + windowed-p99 series
        bus.attach("traffic", ("partisan", "traffic"), rec)
        telemetry.replay_traffic_events(bus, res.chunks,
                                        slo_rounds=slo_rounds)
    if getattr(res.state, "control", ()) != ():
        # controller decision events (fanout_adjusted /
        # shed_threshold_changed / healing_escalated), replayed from
        # the in-scan decision rings with real channel names
        from partisan_tpu import control as control_mod

        bus.attach("control", ("partisan", "control"), rec)
        telemetry.replay_control_events(
            bus, control_mod.snapshot(res.state.control),
            channels=channels)
    # dispatch-wall decomposition (perfwatch): the chunk rows' wall_s /
    # gap_s brackets split the run into in-execution vs dispatch-gap
    # time — the measured form of ROADMAP item 1(b)'s ~80 ms wall
    from partisan_tpu import perfwatch

    disp = perfwatch.decompose_chunks(res.chunks)
    if disp:
        print(json.dumps({"kind": "dispatch_wall", **disp}), file=out)
        bus.attach("perf", ("partisan", "perf"), rec)
        telemetry.replay_perf_events(bus, dispatch=disp)
    for event, meas, meta in rec.events:
        print(json.dumps({"kind": "event", "event": list(event),
                          **meas, **meta}, default=str), file=out)
    summary = {"kind": "summary", "rounds": res.rounds,
               "chunks": len(res.chunks), "programs": res.programs,
               "retries": res.retries, "breaches": res.breaches,
               "healthy": res.healthy()}
    if disp:
        summary["gap_share"] = disp["gap_share"]
    # drain-cost column: total host seconds the telemetry spool's
    # per-boundary drains took (stamped per chunk row; perfwatch's
    # decomposition already separates it from the dispatch gap)
    spool_cost = [row["spool_s"] for row in res.chunks
                  if "spool_s" in row]
    if spool_cost:
        summary["spool_s"] = round(sum(spool_cost), 4)
        summary["spool_chunks"] = len(spool_cost)
    if storm is not None:
        # the incident observatory: injected ground truth fused with
        # every replayed stream, spans matched over the one timeline
        from partisan_tpu import opslog

        journal = opslog.from_soak(res, storm=storm, channels=channels,
                                   slo_rounds=slo_rounds)
        matched = opslog.match(journal)
        for span in matched["spans"]:
            print(json.dumps(span), file=out)
        for orphan in matched["orphans"]:
            print(json.dumps(orphan), file=out)
        verdict = opslog.gate(matched)
        print(json.dumps(verdict), file=out)
        summary["ops"] = {**matched["counts"], "ok": verdict["ok"]}
    print(json.dumps(summary), file=out)
    return summary


USAGE = ("usage: soak_report.py [n] [rounds] [--chunk K] [--crash-at R] "
         "[--breach] [--control] [--traffic] [--elastic] "
         "[--ckpt-dir DIR] [--spool]")


def main() -> None:
    if "--help" in sys.argv or "-h" in sys.argv:
        print(USAGE)
        print(__doc__.strip())
        return
    import jax

    from partisan_tpu import soak
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config
    from partisan_tpu.models.plumtree import Plumtree

    # Persistent compile cache (the scenarios.py __main__ discipline):
    # the smoke's scan programs reload across subprocess runs.
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/partisan_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    # Hand-rolled argv split: value flags consume their operand, so a
    # flag value never leaks into the positional [n, rounds] slots.
    VALUE_FLAGS = ("--chunk", "--crash-at", "--ckpt-dir")
    argv = sys.argv[1:]
    args, opts, breach, control, traffic = [], {}, False, False, False
    elastic = spool_on = False
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in VALUE_FLAGS:
            if i + 1 >= len(argv):
                raise SystemExit(f"{a} needs a value\n{USAGE}")
            opts[a] = argv[i + 1]
            i += 2
        elif a == "--breach":
            breach = True
            i += 1
        elif a == "--control":
            control = True
            i += 1
        elif a == "--traffic":
            traffic = True
            i += 1
        elif a == "--elastic":
            elastic = True
            i += 1
        elif a == "--spool":
            spool_on = True
            i += 1
        elif a.startswith("--"):
            raise SystemExit(f"unknown flag {a}\n{USAGE}")
        else:
            args.append(a)
            i += 1
    n = int(args[0]) if args else 128
    rounds = int(args[1]) if len(args) > 1 else 120
    chunk = int(opts.get("--chunk", 0))
    crash_at = opts.get("--crash-at")
    ckpt_dir = opts.get("--ckpt-dir")

    from partisan_tpu.config import ControlConfig, TrafficConfig

    TRAFFIC_BASE = 400     # base rate ×1000; flash crowd = 8x it

    ctl = {}
    if control:
        # close the loop: the controllers + their prerequisite planes
        ctl = dict(latency=True, channel_capacity=True,
                   provenance=True, provenance_ring=max(128, rounds),
                   control=ControlConfig(fanout=True, backpressure=True,
                                         healing=True,
                                         ring=max(64, rounds)))
    if traffic:
        # the open-loop generator + the latency plane its windowed-p99
        # rows read (flash crowd scripted through the storm below);
        # composes with --control, which already set latency=True
        ctl.setdefault("latency", True)
        ctl["traffic"] = TrafficConfig(enabled=True,
                                       rate_x1000=TRAFFIC_BASE,
                                       hot_skew=1,
                                       ring=max(64, rounds))
    if elastic:
        # the runtime-resize machinery: boot at half capacity below,
        # then scale out to full + gracefully back in via the storm
        ctl["width_operand"] = True
        ctl["elastic"] = True

    def mk():
        return Cluster(Config(
            n_nodes=n, seed=9, peer_service_manager="hyparview",
            msg_words=16, partition_mode="groups",
            health=5, health_ring=max(64, rounds),
            metrics=True, metrics_ring=max(128, rounds),
            # The flight ring (the breach black box) forces the generic
            # wire path and roughly doubles compile time — carry it
            # only when the breach demo will dump it.
            flight_rounds=8 if breach else 0, **ctl), model=Plumtree())

    cl = mk()
    # The per-run memory card (the bench artifact's `memory` sibling):
    # per-plane resident bytes of the scan carry, censused abstractly
    # (jax.eval_shape — no device buffers) so every soak records the
    # HBM footprint its config pins for the whole horizon.
    from partisan_tpu.lint import cost as cost_mod

    mem_rows = cost_mod.resident_memory_rows(
        jax.eval_shape(cl._build_init))
    print(json.dumps({"kind": "memory",
                      "mib_resident": mem_rows[-1]["mib_per_device"],
                      "planes": mem_rows[:-1]}))
    # The canonical batched staggered bootstrap (K_PROG-grained waves +
    # settle), not a re-implementation that would drift from it.
    from partisan_tpu.scenarios import _boot_overlay

    boot_w = n
    if elastic:
        from partisan_tpu.cluster import activate

        if n < 4:
            raise SystemExit(
                f"--elastic needs n >= 4 (got {n}): the demo boots at "
                "half capacity and scales out to full")
        boot_w = n // 2
        st0 = activate(cl.init(), boot_w)
        st = _boot_overlay(cl, boot_w, settle_execs=2, state=st0)
    else:
        st = _boot_overlay(cl, n, settle_execs=2)
    start = int(jax.device_get(st.rnd))

    q = max(10, rounds // 4)
    events = [(0, soak.LinkDrop(0.15)), (q, soak.Heal()),
              (2 * q, soak.CrashBatch(frac=0.05)),
              (2 * q + q // 2, soak.Heal(revive=True))]
    if elastic:
        # scale out to full capacity early, scale gracefully back to
        # the boot width across a bounded drain in the final quarter
        events.append((q // 2, soak.ScaleOut(n)))
        events.append((3 * q, soak.ScaleIn(boot_w,
                                           drain=max(2, q // 4))))
    if breach:
        # Hold a split across the tail so the armed one-component
        # invariant breaches at the following chunk boundaries.
        events.append((3 * q, soak.Partition()))
    if traffic:
        # A flash crowd through the SAME storm: 8x the base rate for a
        # quarter of the soak — the timeline composition the traffic
        # plane is built around (workload.Traffic docs).
        from partisan_tpu import workload

        events.extend(workload.flash_crowd(q, q, 8 * TRAFFIC_BASE,
                                           TRAFFIC_BASE))
    storm = soak.Storm(events=tuple(sorted(events, key=lambda e: e[0])),
                       start=start)

    step_fn = None
    if crash_at is not None:
        crash_round = start + int(crash_at)   # R rounds INTO the soak
        fired = {"done": False}

        def step_fn(c, s, k):  # noqa: F811 — the injection seam
            r = int(jax.device_get(s.rnd))
            if not fired["done"] and r + k > crash_round:
                fired["done"] = True
                raise jax.errors.JaxRuntimeError(
                    f"injected worker crash at round {r} (--crash-at "
                    f"{crash_round})")
            return c.steps(s, k)

    # Dump dir only when the breach demo can actually write to it, and
    # announced in the output so the artifacts are findable.
    dump_dir = None
    if breach:
        dump_dir = tempfile.mkdtemp(prefix="soak_dumps_")
        print(json.dumps({"kind": "dump_dir", "path": dump_dir}))
    # --spool: arm the full-horizon telemetry spool on a temp file
    # (announced so ops_watch can one-shot or --follow it live)
    sp = None
    if spool_on:
        from partisan_tpu import spool as spool_mod

        fd, sp_path = tempfile.mkstemp(prefix="soak_",
                                       suffix=".spool.jsonl")
        os.close(fd)
        os.unlink(sp_path)      # Spool appends; start from empty
        sp = spool_mod.Spool(sp_path)
        print(json.dumps({"kind": "spool", "path": sp_path}))
    warm = [cl]      # first _cluster() reuses the booted instance
    eng = soak.Soak(
        make_cluster=lambda: warm.pop() if warm else mk(),
        storm=storm, step_fn=step_fn,
        invariants=[soak.conservation(), soak.digest_healthy()],
        cfg=soak.SoakConfig(chunk_fixed=chunk, checkpoint_dir=ckpt_dir,
                            cooldown_s=0.0, dump_dir=dump_dir,
                            poll_latency=traffic),
        sleep_fn=lambda s: None, spool=sp)
    res = eng.run(st, rounds=rounds)
    if sp is not None:
        sp.close()
        print(json.dumps({"kind": "spool_stats", **sp.stats()}))
    report(res, channels=tuple(c.name for c in cl.cfg.channels),
           slo_rounds=4 if traffic else None, storm=storm)


if __name__ == "__main__":
    main()
