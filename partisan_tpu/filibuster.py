"""Filibuster: counterexample-guided omission-fault model checking.

Mirrors the reference's fault-injection pipeline (test/filibuster_SUITE.erl,
driven by bin/check-model.sh:17-28 / bin/filibuster.sh:31-33):

1. record a passing execution (the golden trace),
2. generate schedules of send omissions against the observed messages,
   bounded by a fault-tolerance budget (``FAULT_TOLERANCE``,
   prop_partisan_crash_fault_model.erl:33-37),
3. prune invalid/equivalent schedules: an omission is only meaningful for
   a message that was actually sent in the parent execution — the dynamic
   analogue of the reference's causality-annotation pruning
   (schedule_valid_causality, filibuster_SUITE.erl:1023;
   classify_schedule :1155-1192),
4. execute each schedule by preloading it as an interposition
   (partisan_trace_orchestrator.erl:598-650 → interpose.OmissionSchedule),
5. on failure, shrink the counterexample by greedily re-executing with
   omissions removed (the SHRINKING/REPLAY loop,
   partisan_config.erl:593-607).

Determinism makes each execution a pure function of its schedule, so the
checker needs no replay machinery beyond re-running (SURVEY.md §5.3:
"omissions/crashes = boolean masks over the ... message tensors per
round"; the north star explicitly requires replaying filibuster schedules
against the simulated manager).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Callable, Iterable

from partisan_tpu import interpose, trace as trace_mod

Coord = tuple[int, int, int]          # (absolute round, sender, emit slot)


@dataclasses.dataclass
class Execution:
    """One executed schedule and its outcome."""

    schedule: frozenset[Coord]
    trace: trace_mod.Trace
    passed: bool


@dataclasses.dataclass
class Result:
    passed: bool                      # no counterexample within budget
    executions: int                   # schedules actually run
    pruned: int                       # schedules skipped by pruning
    counterexample: Execution | None  # minimal failing schedule (shrunk)
    candidates: int                   # distinct omission candidates seen
    base_trace: trace_mod.Trace | None = None

    def render(self) -> str:
        """Human-readable verdict (the counterexample print of
        bin/counterexample-find.sh; omitted messages are described from
        the fault-free golden trace since they never hit the wire in the
        failing one)."""
        if self.passed:
            return (f"filibuster: PASSED — {self.executions} executions, "
                    f"{self.pruned} pruned, {self.candidates} candidates")
        by_coord = {}
        if self.base_trace is not None:
            by_coord = {(e.rnd, e.src, e.slot): e
                        for e in self.base_trace.events()}
        lines = [f"filibuster: FAILED — minimal counterexample "
                 f"({len(self.counterexample.schedule)} omissions, "
                 f"{self.executions} executions):"]
        for coord in sorted(self.counterexample.schedule):
            ev = by_coord.get(coord)
            if ev is not None:
                lines.append(f"  omit r={ev.rnd} {ev.src} => {ev.dst} "
                             f"{ev.kind_name} payload={list(ev.payload)}")
            else:
                lines.append(f"  omit (rnd={coord[0]}, src={coord[1]}, "
                             f"slot={coord[2]})")
        return "\n".join(lines)


@dataclasses.dataclass
class Checker:
    """``build(interposition) -> (cluster, initial_state)`` constructs the
    system under test — called ONCE with a zeroed
    ``interpose.OmissionSchedule``; every schedule execution then swaps
    the schedule into the (immutable) initial state and re-runs the SAME
    jitted program, so the search costs one compile total (the reference
    re-boots its ct fixture per schedule; determinism lets us reuse the
    booted state).  ``assertion(cluster, final_state) -> bool`` is the
    system model's postcondition.  ``candidate(TraceEvent) -> bool`` marks
    messages eligible for omission (the annotation files' message classes,
    annotations/partisan-annotations-*)."""

    build: Callable[[Any], tuple[Any, Any]]
    horizon: int
    assertion: Callable[[Any, Any], bool]
    candidate: Callable[[trace_mod.TraceEvent], bool]
    max_faults: int = 1
    max_executions: int = 200
    sched_width: int = 64   # >= emission width (OmissionSchedule clips)
    # OPT-IN causality-annotation pruning (analysis.reaction_graph /
    # analysis.ensemble_reaction): omissions of kinds whose closure
    # cannot reach any ``target_kinds`` are skipped (the reference feeds
    # partisan_analysis output into schedule_valid_causality the same
    # way, filibuster_SUITE.erl:1023).  SOUNDNESS CAVEAT: the reference
    # derives its graph from STATIC source analysis, which
    # over-approximates and is sound; trace-derived graphs
    # UNDER-approximate — a reaction no trace exercised (in particular
    # any ABSENCE-triggered reaction, which never appears as a receipt
    # edge) is invisible, and pruning against it can skip the very
    # schedule that triggers a bug.  The default (None) prunes nothing
    # and is exhaustive within the budget; pass a graph only as a
    # search-cost optimization, preferably an ensemble union with a
    # saturating coverage report, and never for protocols with
    # absence-triggered behavior outside the built-in ack lane.
    reaction: dict | None = None
    target_kinds: tuple = ()

    def __post_init__(self) -> None:
        import numpy as np

        self._np = np
        self._closure = None   # transitive closure of `reaction`, cached
        # Probe shape-free: build with a 1-round zero schedule to learn n
        # and the boot round, then rebuild the canonical-size schedule
        # state directly (same cluster/jit — only state is remade).
        self._cl, self._st0 = self.build(interpose.OmissionSchedule(
            np.zeros((1, 1, 1), np.bool_), start=0))
        n = self._cl.cfg.n_nodes
        self._total = int(self._st0.rnd) + self.horizon
        zeros = np.zeros((self._total, n, self.sched_width), np.bool_)
        self._st0 = self._st0._replace(interpose=self._sched_state(zeros))

    def _sched_state(self, drops):
        """Build the schedule state through OmissionSchedule.init — the
        single source of truth for the compiled apply()'s state layout."""
        return interpose.OmissionSchedule(drops, start=0).init(
            self._cl.cfg, self._cl.comm)

    # ---- one execution -------------------------------------------------
    def _execute(self, schedule: frozenset[Coord]) -> Execution:
        drops = schedule_drops(schedule, self._total,
                               self._cl.cfg.n_nodes, self.sched_width)
        st = self._st0._replace(interpose=self._sched_state(drops))
        st, cap = self._cl.record(st, self.horizon)
        tr = trace_mod.from_capture(cap)
        return Execution(schedule=schedule, trace=tr,
                         passed=bool(self.assertion(self._cl, st)))

    def _relevant_kind(self, kind_name: str) -> bool:
        if self.reaction is None or not self.target_kinds:
            return True
        if self._closure is None:
            from partisan_tpu import analysis

            self._closure = analysis.closure(self.reaction)
        reach = self._closure.get(kind_name, set())
        return any(t == kind_name or t in reach for t in self.target_kinds)

    def _candidates(self, tr: trace_mod.Trace) -> list[Coord]:
        return [(e.rnd, e.src, e.slot) for e in tr.events()
                if not e.dropped and self.candidate(e)
                and self._relevant_kind(e.kind_name)]

    # ---- shrinking (counterexample-replay.sh / SHRINKING) --------------
    def _shrink(self, cex: Execution) -> Execution:
        current = cex
        for om in sorted(cex.schedule):
            if om not in current.schedule or len(current.schedule) == 1:
                continue
            trial = self._execute(current.schedule - {om})
            if not trial.passed:
                current = trial
        return current

    # ---- the search ----------------------------------------------------
    def run(self, *, verbose: bool = False) -> Result:
        base = self._execute(frozenset())
        if not base.passed:
            return Result(passed=False, executions=1, pruned=0,
                          counterexample=base, candidates=0,
                          base_trace=base.trace)

        seen: set[frozenset[Coord]] = {frozenset()}
        all_candidates: set[Coord] = set(self._candidates(base.trace))
        executions, pruned = 1, 0
        # Worklist of (schedule, parent-observed candidates): extend each
        # passing execution's schedule with one more omission drawn from
        # messages observed IN THAT execution (causality-valid schedules
        # only — an omission of a never-sent message is equivalent to its
        # parent, filibuster_SUITE.erl:1155-1192).
        work: list[tuple[frozenset[Coord], list[Coord]]] = [
            (frozenset(), self._candidates(base.trace))]
        while work and executions < self.max_executions:
            schedule, cands = work.pop(0)
            if len(schedule) >= self.max_faults:
                continue
            for om in cands:
                nxt = schedule | {om}
                if nxt in seen:
                    pruned += 1
                    continue
                seen.add(nxt)
                ex = self._execute(nxt)
                executions += 1
                if verbose:
                    print(f"  schedule {sorted(nxt)} -> "
                          f"{'pass' if ex.passed else 'FAIL'}")
                if not ex.passed:
                    cex = self._shrink(ex)
                    return Result(passed=False, executions=executions,
                                  pruned=pruned, counterexample=cex,
                                  candidates=len(all_candidates),
                                  base_trace=base.trace)
                obs = self._candidates(ex.trace)
                all_candidates.update(obs)
                # Only extend with omissions at/after the newest one to
                # avoid permuted duplicates (schedules are sets; ordering
                # by coordinate canonicalizes the enumeration).
                newest = max(nxt)
                later = [c for c in obs if c > newest and c not in nxt]
                if later and len(nxt) < self.max_faults:
                    work.append((nxt, later))
                if executions >= self.max_executions:
                    break
        return Result(passed=True, executions=executions, pruned=pruned,
                      counterexample=None, candidates=len(all_candidates),
                      base_trace=base.trace)


def schedule_drops(schedule, total: int, n: int, width: int):
    """Compile omission coordinates into the drops tensor an
    ``interpose.OmissionSchedule`` executes — the translation between
    the checker's schedule representation and the interposition layer
    (a soak ``Omission`` action takes such a tensor plus its own
    absolute ``start`` anchor).

    Two input shapes:

    - ONE schedule (an iterable of ``(absolute round, sender, emit
      slot)`` coordinate tuples) compiles to ``bool[total, n, width]``;
    - a BATCH of ``W`` schedules (an iterable whose elements are
      themselves schedules) compiles to the STACKED
      ``bool[W, total, n, width]`` tensor the fleet runner installs as
      one vmapped state operand (fleet.search) — member ``w`` of the
      leading axis executes exactly ``schedule_drops(schedules[w],
      ...)``.

    FRAME CONVENTION (shared with ``interpose.OmissionSchedule`` and
    the soak ``Omission`` action): row ``t`` of the round axis applies
    at absolute round ``start + t`` of the executing cluster
    (``start=0`` here — coordinates are absolute rounds); rounds at or
    past ``total`` pass everything through (schedules are finite
    windows — a schedule SHORTER than the execution horizon omits
    nothing in its tail, by design, never by broadcast).  Out-of-range
    coordinates raise: a silently clipped omission would make the
    checker report a schedule "tolerated" that it never actually ran.
    """
    import numpy as np

    sched = list(schedule)

    def is_coord(c):
        # a coordinate is any 3-sequence of ints (tuples from the
        # trace, lists from JSON) — anything else is a nested schedule
        return (isinstance(c, (tuple, list)) and len(c) == 3
                and all(isinstance(x, (int, np.integer)) for x in c))

    if sched and not is_coord(sched[0]):
        # batch of schedules -> stacked [W, total, n, width]
        return np.stack([schedule_drops(s, total, n, width)
                         for s in sched])
    drops = np.zeros((total, n, width), np.bool_)
    for (r, s, e) in sched:
        if e >= width:
            raise ValueError(f"emit slot {e} >= sched_width {width}; "
                             "raise sched_width")
        if not 0 <= r < total:
            raise ValueError(
                f"omission round {r} outside the schedule window "
                f"[0, {total}) — size the schedule to cover the "
                "execution horizon")
        drops[r, s, e] = True
    return drops


def app_messages(ev: trace_mod.TraceEvent) -> bool:
    """Default candidate class: application-lane messages (the reference
    omits protocol messages of the system under test, not membership
    gossip — annotations/partisan-annotations-* background sets)."""
    from partisan_tpu import types as T
    return ev.kind in (T.MsgKind.APP, T.MsgKind.RPC_CALL,
                       T.MsgKind.RPC_RESPONSE)


def iter_schedules(candidates: Iterable[Coord], k: int):
    """Exhaustive ≤k-subset enumeration (the static schedule generator;
    the Checker uses the dynamic trace-guided variant instead)."""
    cands = sorted(set(candidates))
    for r in range(1, k + 1):
        yield from (frozenset(c) for c in itertools.combinations(cands, r))
