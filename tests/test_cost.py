"""The round-cost meter (partisan_tpu/lint/cost.py) and its budget
gate: meter semantics on synthetic programs (known gather/scatter
counts, phase attribution, byte accounting), the budget rule's
over/stale firing directions, the pin that every budget entry names a
real matrix program, and the PR 11 headline — the gather-coalesced
round's census stays at or below the surgery's landing point.
"""

import jax
import jax.numpy as jnp

from partisan_tpu import lint
from partisan_tpu.lint import cost, cost_budgets
from partisan_tpu.lint.rules import round_cost_budget
from test_lint import _matrix   # session-shared matrix trace (tier-1
#                                 runtime: tracing the 16 programs twice
#                                 would cost ~60 s on this container)

_CACHE: dict = {}


def _bench32():
    if "c" not in _CACHE:
        _CACHE["c"] = cost.census_program(cost.bench_round_program(32))
    return _CACHE["c"]


# ---------------------------------------------------------------------------
# meter semantics on synthetic programs
# ---------------------------------------------------------------------------

def test_census_counts_gathers_and_scatters():
    n = 8

    def f(x):
        idx = jnp.zeros((n, 2), jnp.int32)
        g = jnp.take_along_axis(x, idx, axis=1)          # 1 gather
        s = x.at[jnp.arange(n), 0].max(g[:, 0])          # 1 scatter-max
        return g, s

    c = cost.census(jax.make_jaxpr(f)(jnp.zeros((n, 4), jnp.int32)), n)
    assert c.total.gathers == 1
    assert c.total.scatters == 1
    # fetched scalars: gather output (n*2) + scatter updates (n)
    assert c.total.fetched == n * 2 + n


def test_census_phase_attribution_inherits_into_cond():
    """Equations inside a lax.cond branch carry no named_scope of their
    own — they must inherit the phase of the call site (the walker's
    phase inheritance), and an inner scope overrides it."""
    n = 4

    def f(x):
        with jax.named_scope("round.manager"):
            y = jax.lax.cond(x[0, 0] > 0,
                             lambda v: v * 2 + 1,
                             lambda v: v - 1, x)
        with jax.named_scope("round.model"):
            z = y + 3
        return z

    c = cost.census(jax.make_jaxpr(f)(jnp.zeros((n, 3), jnp.int32)), n)
    assert "round.manager" in c.phases
    assert "round.model" in c.phases
    # the cond's branch arithmetic landed under round.manager
    assert c.phases["round.manager"].eqns >= 2


def test_census_byte_metric_keys_on_node_axis():
    """Only [n, ., .]-shaped non-view outputs count: an [n, k] add
    counts its bytes, a broadcast/reshape of the same shape does not,
    and an [m, k] tensor (no node axis) is ignored."""
    n, k = 16, 5

    def f(x):
        a = x + 1                            # [n, k] int32 — counted
        b = jnp.reshape(a, (k, n))           # view — not counted
        c = jnp.zeros((7, 3), jnp.int32) + 1   # no node axis — ignored
        return a, b, c

    cen = cost.census(jax.make_jaxpr(f)(jnp.zeros((n, k), jnp.int32)), n)
    assert cen.total.interm_bytes == n * k * 4


def test_census_scan_body_counted_once():
    n = 4

    def f(x):
        def body(c, _):
            return c.at[jnp.arange(n), 0].max(c[:, 0]), None
        return jax.lax.scan(body, x, None, length=10)[0]

    c = cost.census(jax.make_jaxpr(f)(jnp.zeros((n, 2), jnp.int32)), n)
    assert c.total.scatters == 1   # static census: 10 iterations, 1 eqn


def test_rows_orders_heaviest_first_with_total_tail():
    rows = _bench32().rows()
    assert rows[-1]["phase"] == "total"
    weights = [r["interm_mib"] for r in rows[:-1]]
    assert weights == sorted(weights, reverse=True)


# ---------------------------------------------------------------------------
# the budget gate
# ---------------------------------------------------------------------------

def _prog(name="round/planes-off"):
    return next(p for p in _matrix() if p.name == name)


def test_budget_entries_name_matrix_programs():
    """A budget keyed to a renamed/removed matrix program would never
    fire again — the baseline must not silently detach."""
    names = {p.name for p in _matrix()}
    for key in cost_budgets.BUDGETS:
        assert key in names, f"budget {key!r} names no matrix program"


def test_pinned_budgets_are_clean():
    """The committed pins match the committed code exactly (the same
    acceptance the waiver baseline gets in test_lint)."""
    finds = []
    for name in cost_budgets.BUDGETS:
        finds += round_cost_budget(_prog(name))
    assert not finds, [f"{f.detail}: {f.message}" for f in finds]


def test_budget_rule_fires_on_regression_and_stale():
    prog = _prog()
    c = cost.census_program(prog).total
    pin = dict(cost_budgets.BUDGETS[prog.name])
    try:
        # regression direction: pin BELOW the actual census
        cost_budgets.BUDGETS[prog.name] = {
            "gather_scatter": c.gather_scatter - 1,
            "interm_kib": round(c.interm_bytes / 1024.0 - 50, 1),
            "eqns": c.eqns - 100,
        }
        over = round_cost_budget(prog)
        assert {f.detail.split(":", 1)[1] for f in over} == {
            "over:gather_scatter", "over:interm_kib", "over:eqns"}, over
        # stale direction: pin far ABOVE the actual census
        cost_budgets.BUDGETS[prog.name] = {
            "gather_scatter": c.gather_scatter + 5,
            "interm_kib": round(c.interm_bytes / 1024.0 * 2, 1),
            "eqns": c.eqns * 2,
        }
        stale = round_cost_budget(prog)
        assert {f.detail.split(":", 1)[1] for f in stale} == {
            "stale:gather_scatter", "stale:interm_kib", "stale:eqns"}, \
            stale
        # unbudgeted programs are not judged
        assert round_cost_budget(prog._replace(name="no/such")) == []
    finally:
        cost_budgets.BUDGETS[prog.name] = pin


def test_budget_rule_rides_the_lint_report():
    """The rule is registered: an inflated budget fails a lint run over
    the matrix program like any other finding (fingerprint-stable, so
    it could even be waived — it never should be)."""
    prog = _prog()
    pin = dict(cost_budgets.BUDGETS[prog.name])
    try:
        cost_budgets.BUDGETS[prog.name] = dict(pin, gather_scatter=1)
        rep = lint.run_programs([prog], rules=["round-cost-budget"],
                                package_rules=[], waivers={})
        assert rep.findings
        fp = rep.findings[0].fingerprint
        assert fp.startswith("round-cost-budget:")
        assert "over:gather_scatter" in fp
    finally:
        cost_budgets.BUDGETS[prog.name] = pin


# ---------------------------------------------------------------------------
# the PR 11 headline: the coalesced round's census
# ---------------------------------------------------------------------------

def test_gather_coalescing_landing_point():
    """The surgery's landing point, pinned as ceilings (the budgets pin
    the matrix configs exactly; this pins the BENCH-config round the
    acceptance criterion quotes): PR 10's plain round traced 102
    gather/scatter eqns and ~2473 MiB of materialized [n, ., .]
    intermediates at 32k — the coalesced round must stay >= 25% / >= 30%
    below that.  Counts are n-independent; bytes scale linearly, so the
    32-node trace stands in for 32k (2473 MiB * 32/32768 = 2.4 MiB)."""
    c = _bench32().total
    assert c.gather_scatter <= 76, \
        f"{c.gather_scatter} gather/scatter eqns — the 25%-below-HEAD " \
        f"acceptance ceiling is 76"
    head_bytes_at_32 = 2472.8 * 2**20 * 32 / 32768
    assert c.interm_bytes <= 0.70 * head_bytes_at_32, \
        f"{c.interm_bytes / 2**20:.1f} MiB at n=32 — the 30%-below-HEAD " \
        f"ceiling is {0.70 * head_bytes_at_32 / 2**20:.1f}"


def test_wire_fast_phase_is_coalesced():
    """The wire stage's record fetches ride dtype-grouped gathers: the
    phase that traced 39 gather/scatter eqns at HEAD must stay under
    16 (3 dtype groups x 2 fetch sites + index plumbing)."""
    c = _bench32()
    assert c.phases["round.wire_fast"].gather_scatter <= 16
