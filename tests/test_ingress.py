"""Streaming ingress (ISSUE 15): the double-buffered host→device
inject ring, admission control, the journal replay contract, and the
delivery-equivalence gate — a recorded external trace injected through
the ring delivers exactly what the same arrivals born in-scan deliver.
"""

import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from partisan_tpu import ingress, metrics, soak, workload
from partisan_tpu.cluster import Cluster
from partisan_tpu.config import (Config, IngressConfig, PlumtreeConfig,
                                 TrafficConfig)
from partisan_tpu.ingress import IngressFeed, IngressRing, Request
from partisan_tpu.models.plumtree import Plumtree
from support import (assert_scan_lint_clean, assert_states_bitidentical,
                     boot_hyparview)


def _cfg(n=24, **kw):
    kw.setdefault("msg_words", 16)
    kw.setdefault("ingress", IngressConfig(enabled=True, slots=8))
    return Config(n_nodes=n, seed=5, peer_service_manager="hyparview",
                  partition_mode="groups", max_broadcasts=8,
                  inbox_cap=24, timer_stagger=False,
                  plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4),
                  **kw)


# ---------------------------------------------------------------------------
# the host ring
# ---------------------------------------------------------------------------

def test_ring_bounded_offer_sheds_deterministically():
    ring = IngressRing(cap=4)
    reqs = [Request(0, i, i + 1) for i in range(6)]
    assert ring.offer(reqs) == 4
    assert ring.offered == 6 and ring.shed_full == 2
    assert len(ring) == 4
    # tail-drop: the FIRST four survived
    batch = ring.begin_drain()
    assert [r.src for r in batch] == [0, 1, 2, 3]


def test_ring_double_buffer_overlaps_offer_with_drain():
    ring = IngressRing(cap=16)
    ring.offer([Request(0, 1, 2), Request(0, 2, 3)])
    batch = ring.begin_drain()
    assert len(batch) == 2 and len(ring) == 0
    # offers during the drain land in the fresh front buffer
    ring.offer([Request(1, 3, 4)])
    # quota-rejected requests go back to the head of the line
    ring.defer(batch[1:])
    nxt = ring.begin_drain()
    assert [r.src for r in nxt] == [2, 3], "deferred drains FIRST"


# ---------------------------------------------------------------------------
# the in-scan release + admission accounting
# ---------------------------------------------------------------------------

def test_release_emits_at_release_round_and_conserves():
    cfg = _cfg(metrics=True, metrics_ring=64)
    cl = Cluster(cfg, model=Plumtree())
    st = boot_hyparview(cl, settle=20)
    r = int(jax.device_get(st.rnd))
    reqs = [Request(r + 2, 1, 5, 0, 91), Request(r + 2, 2, 6, 0, 91),
            Request(r + 4, 3, 7, 3, 91)]
    st, shed, invalid = ingress.stage(cfg, st, reqs, r)
    assert shed == 0 and invalid == 0
    st2, tr = cl.record(st, 6)
    from partisan_tpu import types as T

    sent = np.asarray(tr.sent)
    is_ing = (sent[..., T.W_KIND] == T.MsgKind.APP) \
        & (sent[..., T.P0] == 91)
    # each request emitted exactly once, in its release round
    rounds = np.asarray(tr.rnd)
    by_round = {int(rounds[t]): int(is_ing[t].sum())
                for t in range(sent.shape[0])}
    assert by_round[r + 2] == 2 and by_round[r + 4] == 1
    assert sum(by_round.values()) == 3
    assert ingress.poll(st2.ingress) == {"staged": 0, "injected": 3,
                                         "shed": 0}
    s = jax.device_get(st2.stats)
    assert int(s.emitted) == int(s.delivered) + int(s.dropped)


def test_admission_sheds_count_emitted_and_dropped_under_cause():
    """Buffer-full staging sheds and dead-source releases both land
    under CAUSE_INGRESS — and count as OFFERED load, so conservation
    and the metrics reconciliation hold exactly."""
    cfg = _cfg(metrics=True, metrics_ring=64,
               ingress=IngressConfig(enabled=True, slots=2))
    cl = Cluster(cfg, model=Plumtree())
    st = boot_hyparview(cl, settle=20)
    r = int(jax.device_get(st.rnd))
    # 3 requests on one row with 2 slots -> 1 buffer-full shed
    reqs = [Request(r + 1, 4, 5), Request(r + 1, 4, 6),
            Request(r + 1, 4, 7)]
    st, shed, invalid = ingress.stage(cfg, st, reqs, r)
    assert shed == 1 and invalid == 0
    # a MALFORMED request (src beyond the id space) sheds under its
    # own counter — a bad trace never masquerades as buffer pressure
    st, shed_m, invalid_m = ingress.stage(
        cfg, st, [Request(r + 1, 999, 3)], r)
    assert shed_m == 0 and invalid_m == 1
    # a request on a row crashed before release -> dead-source shed
    st, shed2, inv2 = ingress.stage(cfg, st, [Request(r + 1, 9, 3)], r)
    assert shed2 == 0 and inv2 == 0
    st = st._replace(faults=st.faults._replace(
        alive=st.faults.alive.at[9].set(False)))
    st = cl.steps(st, 4)
    s = jax.device_get(st.stats)
    assert int(s.emitted) == int(s.delivered) + int(s.dropped)
    tot = metrics.totals(metrics.snapshot(st.metrics))
    assert tot["drops_by_cause"]["ingress_shed"] == 3
    assert tot["dropped"] == int(s.dropped)
    assert ingress.poll(st.ingress)["shed"] == 3


def test_ingress_scan_lint_clean():
    cl = Cluster(_cfg(), model=Plumtree())
    assert_scan_lint_clean(cl, cl.init(), k=4, name="ingress-scan")


def test_feed_quota_defers_and_rides_backpressure():
    cfg = _cfg(ingress=IngressConfig(enabled=True, slots=8, quota=2))
    cl = Cluster(cfg, model=Plumtree())
    st = cl.init()
    ring = IngressRing(cap=64)
    ring.offer([Request(0, i, i + 1, 0) for i in range(5)])
    feed = IngressFeed(ring=ring)
    st, rep = feed.drain(cl, st, 0)
    assert rep["staged"] == 2 and rep["deferred"] == 3
    st, rep = feed.drain(cl, st, 1)
    assert rep["staged"] == 2 and rep["deferred"] == 1
    # release-round window: far-future requests stay in the ring
    ring2 = IngressRing(cap=64)
    ring2.offer([Request(100, 1, 2), Request(3, 2, 3)])
    feed2 = IngressFeed(ring=ring2, window=10)
    st2, rep2 = feed2.drain(cl, cl.init(), 0)
    assert rep2["staged"] == 1 and rep2["deferred"] == 1


# ---------------------------------------------------------------------------
# delivery equivalence: recorded trace through the ring == in-scan
# ---------------------------------------------------------------------------

def test_recorded_trace_delivery_equivalent_to_in_scan():
    """The same arrival stream, two ways: (A) born in-scan by the
    open-loop generator; (B) recorded by the host mirror
    (workload.trace_arrivals), written as a replay trace, and injected
    through the inject ring at soak chunk boundaries.  Every record
    carries the same (round, src, dst, channel, payload), so stats and
    the per-channel delivered series are identical."""
    n, r_run = 24, 24
    base = dict(metrics=True, metrics_ring=128)
    rate = 400

    # A: in-scan traffic.  The generator boots at rate 0 (a quiet boot
    # both arrival modes share record-for-record) and the storm steps
    # the rate up exactly at the comparison window's start.
    cfg_a = _cfg(n, traffic=TrafficConfig(enabled=True, rate_x1000=0,
                                          burst_max=2),
                 ingress=IngressConfig(enabled=False), **base)
    cl_a = Cluster(cfg_a, model=Plumtree())
    st_a = boot_hyparview(cl_a, settle=20)
    r0 = int(jax.device_get(st_a.rnd))
    eng_a = soak.Soak(
        make_cluster=lambda: cl_a,
        storm=soak.Storm(events=((0, workload.SetRate(rate)),),
                         start=r0),
        cfg=soak.SoakConfig(chunk_fixed=6))
    st_a = eng_a.run(st_a, rounds=r_run).state

    # B: the same arrivals, recorded host-side and ring-injected.
    # Config identical except the arrival LANE (traffic off, ingress
    # on) — the calm window keeps the mirror exact (alive constant).
    cfg_b = _cfg(n, traffic=TrafficConfig(enabled=False, rate_x1000=0,
                                          burst_max=2),
                 ingress=IngressConfig(enabled=True, slots=16), **base)
    cl_b = Cluster(cfg_b, model=Plumtree())
    st_b = boot_hyparview(cl_b, settle=20)
    assert int(jax.device_get(st_b.rnd)) == r0
    alive = np.asarray(jax.device_get(st_b.faults.alive))
    reqs = workload.trace_arrivals(cfg_a, r0, r0 + r_run,
                                   rate_x1000=rate, alive=alive)
    assert reqs, "the window generated no arrivals — raise the rate"
    ring = IngressRing(cap=len(reqs) + 1)
    ring.offer(reqs)
    feed = IngressFeed(ring=ring, window=6)
    eng = soak.Soak(make_cluster=lambda: cl_b, ingress=feed,
                    cfg=soak.SoakConfig(chunk_fixed=6))
    res = eng.run(st_b, rounds=r_run)
    st_b = res.state

    sa, sb = jax.device_get(st_a.stats), jax.device_get(st_b.stats)
    assert int(sa.emitted) == int(sb.emitted)
    assert int(sa.delivered) == int(sb.delivered)
    assert int(sa.dropped) == int(sb.dropped)
    ta = metrics.snapshot(st_a.metrics)
    tb = metrics.snapshot(st_b.metrics)
    assert np.array_equal(ta["delivered"], tb["delivered"]), \
        "per-channel delivered series diverge between arrival modes"
    assert np.array_equal(ta["emitted"], tb["emitted"])
    # nothing shed on the way in: the buffer was sized for the window
    assert ingress.poll(st_b.ingress)["shed"] == 0


# ---------------------------------------------------------------------------
# journal replay: kill/restore re-injects the recorded batches
# ---------------------------------------------------------------------------

def test_journal_replay_after_kill_restores_bit_identical(tmp_path):
    n = 24

    def mk():
        return Cluster(_cfg(n, metrics=True, metrics_ring=128),
                       model=Plumtree())

    cl0 = mk()
    st0 = boot_hyparview(cl0, settle=20)
    start = int(jax.device_get(st0.rnd))
    # release rounds span [start+3, start+17]: boundary start+12
    # drains a batch, so the crash injected there rewinds PAST a
    # journaled drain and must replay it
    reqs = [Request(start + 3 + (i % 15), i % n, (i * 5 + 1) % n, 0, 91)
            for i in range(40)]

    def run(tag, crash):
        ring = IngressRing(cap=64)
        ring.offer(reqs)
        feed = IngressFeed(ring=ring,
                           journal_path=str(tmp_path / f"{tag}.jsonl"),
                           window=6)
        warm = [mk()]
        fired = {"done": False}

        def step_fn(c, s, k):
            r = int(jax.device_get(s.rnd))
            if crash and not fired["done"] and r >= start + 12:
                fired["done"] = True
                raise jax.errors.JaxRuntimeError("injected crash")
            return c.steps(s, k)

        eng = soak.Soak(
            make_cluster=lambda: warm.pop() if warm else mk(),
            ingress=feed, step_fn=step_fn,
            invariants=[soak.conservation()],
            cfg=soak.SoakConfig(chunk_fixed=6, cooldown_s=0.0),
            sleep_fn=lambda s: None)
        return eng.run(jax.device_put(jax.device_get(st0)), rounds=24)

    ref = run("ref", crash=False)
    got = run("crash", crash=True)
    assert got.retries == 1 and ref.retries == 0
    assert ref.breaches == 0 and got.breaches == 0
    assert_states_bitidentical(ref.state, got.state, "journal_replay")
    # the rewound boundary re-injected from the journal, not the ring
    replays = [e for e in got.log if e.get("kind") == "ingress_drain"
               and e.get("replayed")]
    assert replays, "no boundary was replayed from the journal"
    # and a journal alone (no ring) is a complete arrival mode
    feed3 = IngressFeed(journal_path=str(tmp_path / "ref.jsonl"))
    eng3 = soak.Soak(make_cluster=mk, ingress=feed3,
                     cfg=soak.SoakConfig(chunk_fixed=6))
    res3 = eng3.run(jax.device_put(jax.device_get(st0)), rounds=24)
    assert_states_bitidentical(ref.state, res3.state, "trace_mode")


def test_write_trace_and_ingress_events(tmp_path):
    from partisan_tpu import telemetry

    p = str(tmp_path / "trace.jsonl")
    reqs = [Request(3, 1, 2), Request(4, 2, 3), Request(9, 3, 4)]
    assert ingress.write_trace(p, reqs, every=4) == 3
    loaded = ingress.Journal.load(p)
    assert sorted(loaded) == [0, 4, 8]
    assert loaded[0] == [Request(3, 1, 2, 0, 0)]

    rec = telemetry.Recorder()
    bus = telemetry.Bus()
    bus.attach("t", ("partisan", "ingress"), rec)
    log = [{"kind": "ingress_drain", "round": 7, "staged": 4,
            "shed_buffer_full": 1, "shed_invalid": 1, "deferred": 2,
            "replayed": False}]
    assert telemetry.replay_ingress_events(bus, log) == 2
    kinds = [e[0][2] for e in rec.events]
    assert kinds == ["drain", "shed"]


def test_adaptive_chunking_lands_boundaries_on_recorded_rounds(
        tmp_path):
    """With ADAPTIVE chunk sizing (chunk_fixed=0 — the default) the
    soak's sizer must clip at the feed's recorded rounds, exactly like
    storm events, so a replayed trace's batches are never skipped."""
    cl = Cluster(_cfg(16, metrics=True, metrics_ring=64),
                 model=Plumtree())
    st0 = boot_hyparview(cl, settle=20)
    start = int(jax.device_get(st0.rnd))
    # batches at off-ladder boundary rounds the adaptive sizer would
    # otherwise stride straight past
    reqs = [Request(start + r, (r + i) % 16, (r + i + 1) % 16, 0, 91)
            for r in (3, 7, 13, 19) for i in range(3)]
    p = str(tmp_path / "trace.jsonl")
    j = ingress.Journal(p)
    for r in (3, 7, 13, 19):
        j.append(start + r, [q for q in reqs if q.rnd == start + r])
    feed = IngressFeed(journal_path=p)
    eng = soak.Soak(make_cluster=lambda: cl, ingress=feed,
                    invariants=[soak.conservation()],
                    cfg=soak.SoakConfig(chunk_init=100))
    res = eng.run(st0, rounds=30)
    assert res.breaches == 0
    assert ingress.poll(res.state.ingress)["injected"] == len(reqs)
    drains = [e["round"] for e in res.log
              if e.get("kind") == "ingress_drain"]
    assert drains == [start + r for r in (3, 7, 13, 19)], \
        "boundaries did not land on the recorded rounds"


def test_feed_requires_armed_lane():
    cl = Cluster(_cfg(ingress=IngressConfig(enabled=False)),
                 model=Plumtree())
    feed = IngressFeed(ring=IngressRing(cap=4))
    feed.ring.offer([Request(0, 1, 2)])
    with pytest.raises(ValueError, match="enabled=True"):
        feed.drain(cl, cl.init(), 0)
