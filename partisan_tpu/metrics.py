"""Device-resident metrics plane: per-round / per-channel / per-cause
counters accumulated INSIDE the jitted round.

The reference exposes rich runtime introspection — the telemetry event
catalog (doc_extras/telemetry.md), per-peer connection counts
(partisan_peer_connections.erl:107-110), the trace orchestrator's typed
send/receive/DROPPED records — where the TPU rebuild's ``Stats``
(cluster.py) collapses everything into three cumulative globals.  This
module is the native equivalent of that catalog: a statically-shaped
ring buffer of per-round counters carried in ``ClusterState`` and
written by ``round_body`` with ZERO host syncs (the metrics state is a
scan carry, never a callback), then decoded host-side after a batch of
rounds.

Design constraints (ARCHITECTURE.md "Observability"):

- **statically shaped** — a ring of ``Config.metrics_ring`` rounds;
  slot = ``rnd % ring`` so a long scan keeps the most recent window,
- **replicated under sharding** — every recorded value is reduced with
  ``comm.allsum``/``comm.allmax`` before the ring write, so sharded
  runs record cluster-wide series bit-identical to single-device runs
  (parallel/sharded.py replicates the metrics leaves, like Stats),
- **free when disabled** — ``Config.metrics=False`` (the default) keeps
  the ClusterState leaf an empty ``()`` pytree: no arrays, no ops, no
  bytes on the hot path.

Cause taxonomy (trailing axis of ``MetricsState.drops``): the event
lane's per-round ``emitted - delivered`` delta — exactly what legacy
``Stats.dropped`` accumulates — broken out by WHERE the message died:

- ``compact_shed``   — emission-compaction overflow (``emit_compact``),
- ``fault_cut``      — crash/partition/omission masks (faults.py),
- ``inbox_overflow`` — receiver inbox past ``inbox_cap`` (route drops),
- ``dead_receiver``  — addressed to a crash-stopped node,
- ``outbox_shed``    — channel-capacity outbox overflow (channels.py),
- ``ingress_shed``   — streaming-ingress admission sheds (ingress.py):
  externally-offered requests the device could not honor — source row
  dead/deactivated at release, or the per-node inject buffer full at
  the boundary drain.  By the open-loop stance these count as offered
  load: the round adds them to BOTH the emitted count and this drops
  row, so the conservation law holds exactly through admission control,
- ``other``          — the residual: everything the direct counters
  cannot see from round_body (all_to_all quota sheds inside the sharded
  exchange, and the transient defer/release imbalance of channel-
  capacity backpressure — a deferred send counts emitted in round r but
  delivers in round r+k, so per-round ``other`` may go NEGATIVE; it
  telescopes to the true loss over a window).

By construction ``sum(drops, axis=-1)`` equals the per-round legacy
``Stats.dropped`` delta, so the series always reconciles exactly with
the cumulative counters (tests/test_metrics.py gates this).

Monotonic-channel sheds are a separate ``shed`` series: the reference's
transport treats them as sanctioned load-shedding, and legacy Stats
excludes them from ``emitted`` (so they are NOT part of ``dropped``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import Array

from partisan_tpu import types as T
from partisan_tpu.config import Config

# Drop-cause taxonomy: indices into the trailing axis of
# ``MetricsState.drops`` (see module docstring for semantics).
CAUSE_COMPACT = 0
CAUSE_FAULT = 1
CAUSE_INBOX = 2
CAUSE_DEAD = 3
CAUSE_OUTBOX = 4
CAUSE_INGRESS = 5
CAUSE_OTHER = 6
N_CAUSES = 7
CAUSE_NAMES = ("compact_shed", "fault_cut", "inbox_overflow",
               "dead_receiver", "outbox_shed", "ingress_shed", "other")


class MetricsState(NamedTuple):
    """Ring buffer of per-round counters (all int32, all replicated).

    ``R`` = Config.metrics_ring, ``C`` = Config.n_channels.  Slot
    ``rnd % R`` holds round ``rnd``; ``rnd[slot] == -1`` marks a slot
    never written (a run shorter than the ring)."""

    rnd: Array          # int32[R] — absolute round recorded (-1 = empty)
    emitted: Array      # int32[R, C] — counted emissions per channel
    delivered: Array    # int32[R, C] — event-lane deliveries per channel
    causal: Array       # int32[R] — causal-lane deliveries (no channel)
    shed: Array         # int32[R] — monotonic-channel sheds (not drops)
    drops: Array        # int32[R, N_CAUSES] — cause-tagged drops
    inbox_hwm: Array    # int32[R] — max inbox occupancy over nodes
    inbox_occ: Array    # int32[R] — total inbox occupancy (sum)
    edges_total: Array  # int32[R] — live overlay out-edges, cluster-wide
    edges_min: Array    # int32[R] — min live out-edges over ALIVE nodes
    edges_max: Array    # int32[R] — max live out-edges over alive nodes
    alive: Array        # int32[R] — alive-node count
    dlv_overflow: Array  # int32[R] — delivery-plane drop delta
    #                      (ack/causal/p2p overflow+aborted+invalid)


def enabled(cfg: Config) -> bool:
    return cfg.metrics


def init(cfg: Config, comm) -> MetricsState:
    R, C = cfg.metrics_ring, cfg.n_channels

    def z(*shape):
        return jnp.zeros(shape, jnp.int32)

    return MetricsState(
        rnd=jnp.full((R,), -1, jnp.int32),
        emitted=z(R, C), delivered=z(R, C), causal=z(R), shed=z(R),
        drops=z(R, N_CAUSES), inbox_hwm=z(R), inbox_occ=z(R),
        edges_total=z(R), edges_min=z(R), edges_max=z(R), alive=z(R),
        dlv_overflow=z(R),
    )


def channel_counts(cfg: Config, msgs: Array,
                   mask: Array | None = None) -> Array:
    """int32[C]: live messages in ``msgs [..., W]`` counted by channel
    (shard-local; callers ``comm.allsum`` the vector).  ``mask``
    optionally restricts the count to a bool subset of the slots (e.g.
    the shed mask) — live-ness is still required."""
    valid = msgs[..., T.W_KIND] != 0
    if mask is not None:
        valid = valid & mask
    ch = jnp.clip(msgs[..., T.W_CHANNEL], 0, cfg.n_channels - 1)
    onehot = (ch[..., None] == jnp.arange(cfg.n_channels)) \
        & valid[..., None]
    return jnp.sum(onehot, axis=tuple(range(onehot.ndim - 1)),
                   dtype=jnp.int32)


_BIG = jnp.int32(2**30)


def record_round(cfg: Config, comm, ms: MetricsState, *, rnd: Array,
                 emitted_ch: Array, delivered_ch: Array, causal: Array,
                 shed: Array, drops: Array, inbox_count: Array,
                 alive_local: Array, alive_global: Array, nbrs: Array,
                 dlv_overflow: Array) -> MetricsState:
    """Write one round's counters into ring slot ``rnd % R``.

    ``emitted_ch``/``delivered_ch``/``causal``/``shed``/``drops``/
    ``dlv_overflow`` arrive already globally reduced (replicated);
    ``inbox_count`` [n_local] and ``nbrs`` [n_local, K] are shard-local
    and reduced here.  ``alive_local``/``alive_global`` arrive
    pre-masked by the active prefix under ``Config.width_operand``
    (round_body passes ``alive & (gid < n_active)``), so the
    alive/edge series match a native-width run's exactly.  Everything
    stays on device — this runs inside the round's jitted scan body."""
    slot = jnp.mod(rnd, cfg.metrics_ring)

    occ = comm.allsum(jnp.sum(inbox_count, dtype=jnp.int32))
    hwm = comm.allmax(jnp.max(inbox_count))

    # Per-node live out-edges (the connection-count analogue,
    # partisan_peer_connections.erl:107-110): an edge is live only if
    # both endpoints are alive — a crashed peer's socket is gone.
    live_nbr = (nbrs >= 0) \
        & alive_global[jnp.clip(nbrs, 0, cfg.n_nodes - 1)]
    e = jnp.sum(live_nbr, axis=1, dtype=jnp.int32)
    e = jnp.where(alive_local, e, 0)
    n_alive = comm.allsum(jnp.sum(alive_local, dtype=jnp.int32))
    e_total = comm.allsum(jnp.sum(e, dtype=jnp.int32))
    e_max = comm.allmax(jnp.max(e))
    # min over ALIVE nodes only (dead rows are structurally 0):
    # -max(-e) over alive rows; an all-dead cluster reports 0.
    e_min = jnp.where(
        n_alive > 0,
        -comm.allmax(jnp.max(jnp.where(alive_local, -e, -_BIG))),
        jnp.int32(0))

    return MetricsState(
        rnd=ms.rnd.at[slot].set(rnd),
        emitted=ms.emitted.at[slot].set(emitted_ch),
        delivered=ms.delivered.at[slot].set(delivered_ch),
        causal=ms.causal.at[slot].set(causal),
        shed=ms.shed.at[slot].set(shed),
        drops=ms.drops.at[slot].set(drops),
        inbox_hwm=ms.inbox_hwm.at[slot].set(hwm),
        inbox_occ=ms.inbox_occ.at[slot].set(occ),
        edges_total=ms.edges_total.at[slot].set(e_total),
        edges_min=ms.edges_min.at[slot].set(e_min),
        edges_max=ms.edges_max.at[slot].set(e_max),
        alive=ms.alive.at[slot].set(n_alive),
        dlv_overflow=ms.dlv_overflow.at[slot].set(dlv_overflow),
    )


# ---------------------------------------------------------------------------
# Host-side readers
# ---------------------------------------------------------------------------

_SERIES = ("emitted", "delivered", "causal", "shed", "drops",
           "inbox_hwm", "inbox_occ", "edges_total", "edges_min",
           "edges_max", "alive", "dlv_overflow")


def ring_order(rnd) -> "np.ndarray":
    """Decode a ring's round-label vector (-1 = slot never written)
    into the slot order that yields rounds ascending — shared by every
    carry-resident ring (this module's counter ring, the latency
    plane's flight recorder)."""
    import numpy as np

    rnd = np.asarray(rnd)
    keep = np.flatnonzero(rnd >= 0)
    return keep[np.argsort(rnd[keep], kind="stable")]


def host_int(x):
    """Host view of a scalar carry leaf: a plain int, or the per-member
    int list when the leaf arrives fleet-batched with a leading member
    axis (fleet.py states) — shared by every poll/invariant that must
    read both shapes (control.poll, workload.poll, the soak
    invariants)."""
    import jax
    import numpy as np

    a = np.asarray(jax.device_get(x))
    return a.astype(int).tolist() if a.ndim else int(a)


def snapshot(ms: MetricsState) -> dict:
    """Decode the ring into per-round series ordered by round (one
    device->host transfer, AFTER the scan — never inside it).

    Returns ``{"rounds": int array [k], <series>: array [k, ...]}``
    where k <= metrics_ring is the number of recorded rounds (the most
    recent window once the ring wraps)."""
    import jax
    import numpy as np

    host = jax.device_get(ms)
    rnd = np.asarray(host.rnd)
    idx = ring_order(rnd)
    out: dict = {"rounds": rnd[idx]}
    for name in _SERIES:
        out[name] = np.asarray(getattr(host, name))[idx]
    return out


def rows(snap: dict, channels: tuple[str, ...] | None = None) -> list[dict]:
    """JSON-lines-friendly view of a snapshot: one dict per round, with
    channel and cause axes labeled (the ``BENCH_*.json`` idiom — every
    row is a self-describing JSON object)."""
    C = snap["emitted"].shape[1] if len(snap["emitted"]) else 0
    ch_names = tuple(channels) if channels is not None \
        else tuple(f"ch{i}" for i in range(C))
    out = []
    for i, r in enumerate(snap["rounds"]):
        out.append({
            "round": int(r),
            "emitted": {ch_names[c]: int(snap["emitted"][i, c])
                        for c in range(C)},
            "delivered": {ch_names[c]: int(snap["delivered"][i, c])
                          for c in range(C)},
            "causal_delivered": int(snap["causal"][i]),
            "shed": int(snap["shed"][i]),
            "drops": {CAUSE_NAMES[j]: int(snap["drops"][i, j])
                      for j in range(N_CAUSES)},
            "inbox_hwm": int(snap["inbox_hwm"][i]),
            "inbox_occupancy": int(snap["inbox_occ"][i]),
            "edges": {"total": int(snap["edges_total"][i]),
                      "min": int(snap["edges_min"][i]),
                      "max": int(snap["edges_max"][i])},
            "alive": int(snap["alive"][i]),
            "delivery_overflow": int(snap["dlv_overflow"][i]),
        })
    return out


def totals(snap: dict) -> dict:
    """Whole-window aggregates — the reconciliation view against the
    legacy cumulative ``Stats`` counters (equal when the run fits the
    ring; see tests/test_metrics.py)."""
    return {
        "rounds": int(len(snap["rounds"])),
        "emitted": int(snap["emitted"].sum()),
        "delivered": int(snap["delivered"].sum())
        + int(snap["causal"].sum()),
        "dropped": int(snap["drops"].sum()),
        "shed": int(snap["shed"].sum()),
        "drops_by_cause": {
            CAUSE_NAMES[j]: int(snap["drops"][:, j].sum())
            for j in range(N_CAUSES)},
    }
