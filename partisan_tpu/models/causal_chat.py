"""Causal broadcast workload (driver config #5's application layer).

Mirrors the reference's causal-delivery usage (partisan_causality_backend
driven through forward_message with a causal label — partisan_SUITE's
`with_causal_labels`/`with_causal_send` groups): each sender emits
causally-ordered broadcasts (one logical record, fanned to every node by
the delivery layer's wide lanes), and receivers log delivery order; logs
must respect happened-before.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from partisan_tpu import types as T
from partisan_tpu.comm import LocalComm
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import msg as msg_ops


class ChatState(NamedTuple):
    log: Array      # int32[n, LOG] — delivered tokens in arrival order
    log_len: Array  # int32[n]
    seq: Array      # int32[n] — next send sequence number
    send_at: Array  # int32[n, SLOTS] — scripted send rounds (-1 empty)


class CausalChat:
    """Scripted causal broadcasts + delivery-order logging."""

    name = "causal_chat"

    def __init__(self, log_cap: int = 32, slots: int = 8) -> None:
        self.LOG = log_cap
        self.SLOTS = slots

    def init(self, cfg: Config, comm: LocalComm) -> ChatState:
        n = comm.n_local
        return ChatState(
            log=jnp.zeros((n, self.LOG), jnp.int32),
            log_len=jnp.zeros((n,), jnp.int32),
            seq=jnp.ones((n,), jnp.int32),
            send_at=jnp.full((n, self.SLOTS), -1, jnp.int32),
        )

    def step(self, cfg: Config, comm: LocalComm, state: ChatState,
             ctx: RoundCtx, nbrs: Array) -> tuple[ChatState, Array]:
        gids = comm.local_ids()
        n = state.log.shape[0]

        # Log arrived causal APP messages in inbox order (the delivery
        # layer already enforced causal order).
        inb = ctx.inbox.data
        is_chat = (inb[..., T.W_KIND] == T.MsgKind.APP) & \
                  (inb[..., T.W_FLAGS] & T.F_CAUSAL != 0)
        tok = jnp.where(is_chat,
                        inb[..., T.W_SRC] * 1000 + inb[..., T.P0], 0)
        rank = jnp.cumsum(is_chat, axis=1) - 1
        slot = jnp.where(is_chat, state.log_len[:, None] + rank, self.LOG)
        rows = jnp.broadcast_to(jnp.arange(n)[:, None], slot.shape)
        log = state.log.at[rows, slot].set(tok, mode="drop")
        log_len = state.log_len + is_chat.sum(axis=1, dtype=jnp.int32)

        # Scripted sends: ONE causal record per logical broadcast (the
        # delivery layer fans it to every node).
        fire = (state.send_at == ctx.rnd).any(axis=1) & ctx.alive
        dst = jnp.where(fire, gids, -1)
        emitted = msg_ops.build(
            cfg, T.MsgKind.APP, gids[:, None], dst[:, None],
            flags=T.F_CAUSAL, payload=(state.seq[:, None],))
        seq = state.seq + fire.astype(jnp.int32)
        return ChatState(log=log, log_len=log_len, seq=seq,
                         send_at=state.send_at), emitted

    # ---- scenario helpers --------------------------------------------
    def schedule(self, state: ChatState, node: int, rnd: int) -> ChatState:
        row = np.asarray(state.send_at[node])
        free = int(np.argmax(row < 0))
        if row[free] >= 0:
            raise ValueError(f"no free send slot on node {node}")
        return state._replace(send_at=state.send_at.at[node, free].set(rnd))

    @staticmethod
    def logs(state: ChatState) -> list[list[int]]:
        logs = np.asarray(state.log)
        lens = np.asarray(state.log_len)
        return [list(map(int, logs[i, :lens[i]]))
                for i in range(logs.shape[0])]
