"""Provenance-plane JSON-lines exporter (the ``BENCH_*.json`` idiom:
one self-describing JSON object per line).

Runs a HyParView + Plumtree broadcast with ``Config(provenance=True)``,
then prints the decoded dissemination record — one line per round of
the redundancy/control rings (duplicate deliveries per channel, first
deliveries, PRUNE/GRAFT/I_HAVE/IGNORED_I_HAVE emitted+delivered), the
``partisan.broadcast.*`` bus events replayed from the rings, one line
per broadcast slot's reconstructed dissemination TREE (parent forest
depth/branching + time-to-coverage), and a trailing summary with the
whole-run redundancy ratio::

    python tools/broadcast_report.py [n] [rounds] [--fault]

``--fault`` adds 10% iid link drop after the broadcast starts, so the
eager tree breaks and the report shows the lazy I_HAVE/GRAFT repair
traffic (a graft_storm / tree_repaired event pair).  Importable:
``report(state)`` renders any provenance-carrying state.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools._lib.jaxcache import enable_persistent_cache

enable_persistent_cache()

USAGE = "usage: broadcast_report.py [n] [rounds] [--fault]"


def report(state, channels=None, slots=(0,), out=sys.stdout) -> dict:
    """Dump ``state``'s provenance plane as JSON lines; returns the
    summary dict (also printed as the last line)."""
    from partisan_tpu import provenance, telemetry

    if state.provenance == ():
        raise ValueError("state carries no provenance plane — build "
                         "the cluster with Config(provenance=True)")
    snap = provenance.snapshot(state.provenance)
    for row in provenance.rows(snap, channels=channels):
        print(json.dumps({"kind": "round", **row}), file=out)
    rec = telemetry.Recorder()
    bus = telemetry.Bus()
    bus.attach("report", ("partisan", "broadcast"), rec)
    telemetry.replay_broadcast_events(bus, snap)
    for event, meas, meta in rec.events:
        print(json.dumps({"kind": "event", "event": list(event),
                          **meas, **meta}), file=out)
    for slot in slots:
        t = provenance.tree(snap, slot)
        print(json.dumps({"kind": "tree",
                          **{k: v for k, v in t.items()
                             if k not in ("parent", "hop")}}), file=out)
    summary = {"kind": "summary", "rounds": int(len(snap["rounds"])),
               **provenance.redundancy(snap),
               "depth_hwm": snap["depth_hwm"].astype(int).tolist(),
               "cover_rnd": snap["cover_rnd"].astype(int).tolist()}
    print(json.dumps(summary), file=out)
    return summary


def main() -> None:
    if "--help" in sys.argv or "-h" in sys.argv:
        print(USAGE)
        print(__doc__.strip())
        return
    import jax.numpy as jnp
    import numpy as np

    from partisan_tpu import provenance
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config, PlumtreeConfig
    from partisan_tpu.models.plumtree import Plumtree

    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    n = int(args[0]) if args else 128
    rounds = int(args[1]) if len(args) > 1 else 60
    fault = "--fault" in sys.argv

    # aae=False: the provenance plane observes the WIRE — and on a
    # live overlay the connect-handshake/AAE state scatter (which
    # bypasses the wire) otherwise does most of the dissemination
    # (measured: 14 vs 303 wire gossip sends at 96 nodes).  Disabling
    # the walk here shows the pure Plumtree eager/lazy dynamics the
    # report exists to render; the plane itself is correct either way.
    cfg = Config(n_nodes=n, seed=9, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups",
                 max_broadcasts=4, inbox_cap=64, provenance=True,
                 provenance_ring=max(128, rounds + 10 * n.bit_length()),
                 plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4,
                                         aae=False))
    cl = Cluster(cfg, model=Plumtree())
    st = cl.init()
    rng = np.random.default_rng(7)
    base = 1
    while base < n:
        hi = min(base * 4, n)
        nodes = np.arange(base, hi, dtype=np.int32)
        tgts = rng.integers(0, base, size=nodes.shape[0]).astype(np.int32)
        st = st._replace(manager=cl.manager.join_many(
            cfg, st.manager, nodes, tgts))
        st = cl.steps(st, 10)
        base = hi
    st = cl.steps(st, 10)
    start = int(st.rnd)
    st = st._replace(
        model=cl.model.broadcast(st.model, 0, 0, start),
        provenance=provenance.mark_origin(st.provenance, 0, 0,
                                          rnd=start))
    if fault:
        st = st._replace(faults=st.faults._replace(
            link_drop=jnp.float32(0.1)))
    st = cl.steps(st, rounds)
    report(st, channels=tuple(c.name for c in cfg.channels))


if __name__ == "__main__":
    main()
