"""Channel semantics: monotonic load-shedding under backpressure
(partisan_peer_socket.erl:108-129 — the reference's only sanctioned
transport drop: stale monotonic-channel state is shed when the
receiver is backed up)."""

import jax.numpy as jnp

from partisan_tpu import types as T
from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config, MEMBERSHIP_CHANNEL
from partisan_tpu.ops import msg as msg_ops
from tests.support import boot_fullmesh


class Spam:
    """Every node floods node 0 on a chosen channel each round."""

    name = "spam"

    def __init__(self, channel_id: int) -> None:
        self.channel_id = channel_id

    def init(self, cfg, comm):
        return ()

    def step(self, cfg, comm, state, ctx, nbrs):
        gids = comm.local_ids()
        dst = jnp.where(gids[:, None] != 0, 0, -1)   # everyone -> node 0
        emitted = msg_ops.build(
            cfg.msg_words, T.MsgKind.APP, gids[:, None], dst,
            channel=self.channel_id, payload=(jnp.int32(1),))
        return state, emitted


def _run(channel_name, rounds=12):
    cfg = Config(n_nodes=8, seed=4, inbox_cap=4)
    cl = Cluster(cfg, model=Spam(cfg.channel_id(channel_name)))
    st = boot_fullmesh(cl, settle=3)
    base = st.stats
    st = cl.steps(st, rounds)
    return (int(st.stats.emitted - base.emitted),
            int(st.stats.delivered - base.delivered),
            int(st.stats.dropped - base.dropped))


def test_monotonic_channel_sheds_under_backpressure():
    em_d, de_d, dr_d = _run("default")            # not monotonic
    em_m, de_m, dr_m = _run(MEMBERSHIP_CHANNEL)   # monotonic
    # Non-monotonic: every round 7 sends, 4 delivered, 3 overflow drops.
    assert em_d > em_m, "monotonic channel should shed sends pre-wire"
    assert dr_m < dr_d, "shedding should prevent overflow drops"
    assert de_m > 0, "shedding must not starve the receiver entirely"


def test_shed_only_when_backed_up():
    # With a roomy inbox there is no backpressure: nothing is shed.
    cfg = Config(n_nodes=8, seed=4, inbox_cap=32)
    cl = Cluster(cfg, model=Spam(cfg.channel_id(MEMBERSHIP_CHANNEL)))
    st = boot_fullmesh(cl, settle=3)
    base = st.stats
    st = cl.steps(st, 10)
    emitted = int(st.stats.emitted - base.emitted)
    delivered = int(st.stats.delivered - base.delivered)
    assert emitted == delivered == 10 * 7
