"""The per-round message exchange: route emitted messages into inboxes.

This collapses the reference's entire hot send path — connection dispatch
(partisan_peer_connections.erl:897-942), per-connection encode/send
(partisan_peer_service_client.erl:173-196) and the server-side receive
funnel (partisan_peer_service_server.erl:88-103) — into ONE batched,
statically-shaped kernel per round:

    emitted int32[n, emit_cap, W]  --route-->  Inbox(data int32[n, cap, W])

Algorithm (all static shapes, jit/TPU friendly):
  1. flatten to [n*emit_cap] messages; empty slots (kind==NONE) get a
     sentinel destination ``n`` so they sort to the end,
  2. stable-sort by destination — stability preserves per-sender emission
     order, the tensor analogue of per-connection FIFO ordering,
  3. per-destination counts via bincount, slot = rank within destination,
  4. scatter rows into inbox slots; slots beyond ``cap`` fall out of bounds
     and XLA's default scatter drop-semantics discards them — these are
     counted as drops (the reference's TCP never silently drops except on
     monotonic channels, so callers surface ``drops`` — SURVEY.md §7
     "Hard parts": overflow accounting).

The destination id in W_DST is a GLOBAL node id; the sharded wrapper in
parallel/ all-gathers emissions and lets each shard route only its own
node range (see parallel/sharded.py).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import Array

from partisan_tpu.types import W_DST, W_KIND


class Inbox(NamedTuple):
    """One round's deliveries. data[i, s] is the s-th message for node i."""

    data: Array   # int32[n, cap, W]; kind==NONE marks empty slots
    count: Array  # int32[n] — valid slots per node
    drops: Array  # int32[n] — messages dropped for this node (overflow)


def empty_inbox(n: int, cap: int, msg_words: int) -> Inbox:
    return Inbox(
        data=jnp.zeros((n, cap, msg_words), jnp.int32),
        count=jnp.zeros((n,), jnp.int32),
        drops=jnp.zeros((n,), jnp.int32),
    )


def route(emitted: Array, n: int, cap: int, *, node_offset: int | Array = 0) -> Inbox:
    """Route ``emitted`` int32[m, E, W] (or [m*E, W]) into an n-node inbox.

    ``node_offset``: the global id of local node 0 — destinations outside
    [node_offset, node_offset+n) are ignored (used by the sharded exchange,
    where each shard routes the globally-gathered emissions into its own
    node range).
    """
    flat = emitted.reshape(-1, emitted.shape[-1])
    kind = flat[:, W_KIND]
    dst = flat[:, W_DST] - node_offset
    # Empty slots and out-of-range destinations -> sentinel bucket n.
    local = (kind != 0) & (dst >= 0) & (dst < n)
    dst = jnp.where(local, dst, n)

    order = jnp.argsort(dst, stable=True)
    dst_sorted = dst[order]
    msgs_sorted = flat[order]

    counts = jnp.bincount(dst, length=n + 1)              # int32[n+1]
    starts = jnp.cumsum(counts) - counts                  # first flat index per dst
    slot = jnp.arange(dst.shape[0], dtype=jnp.int32) - starts[dst_sorted]

    # Out-of-bounds (slot >= cap, or sentinel dst) => dropped by scatter.
    row = jnp.where(dst_sorted < n, dst_sorted, n + cap)
    data = jnp.zeros((n, cap, flat.shape[-1]), jnp.int32)
    data = data.at[row, slot].set(msgs_sorted, mode="drop")

    delivered = jnp.minimum(counts[:n], cap)
    return Inbox(data=data, count=delivered, drops=counts[:n] - delivered)


def merge_inboxes(a: Inbox, b: Inbox) -> Inbox:
    """Append b's messages after a's (capacity permitting) — used to merge
    locally-routed and remotely-routed traffic or delayed re-deliveries.
    ``b`` may have any slot count (and need not be compacted); the result
    keeps a's capacity."""
    n, cap, w = a.data.shape
    both = jnp.concatenate(
        [a.data, b.data], axis=1
    )  # [n, cap + bcap, w] — a's slots first
    m = both.shape[1]
    # Re-route through the same compaction: positions keep relative order.
    kind = both[:, :, W_KIND]
    valid = kind != 0
    slot = jnp.cumsum(valid, axis=1) - 1
    slot = jnp.where(valid, slot, m)  # invalid -> dropped (>= cap)
    rows = jnp.broadcast_to(jnp.arange(n)[:, None], (n, m))
    data = jnp.zeros_like(a.data).at[rows, slot].set(both, mode="drop")
    total = a.count + b.count
    delivered = jnp.minimum(total, cap)
    return Inbox(
        data=data,
        count=delivered,
        drops=a.drops + b.drops + total - delivered,
    )
