"""Minimal reproducer for the per-execution wall-limit fault.

Round-1/2 observation: a single XLA execution (one lax.scan program) that
keeps the relay-attached TPU busy for longer than ~the minute mark
reproducibly faults, poisoning the process context.  bench.py works
around it by capping scan length so each execution stays ~15 s.

This tool isolates the trigger with two self-contained programs:

  pure    — a lax.scan over a bfloat16 matmul chain (no partisan code,
            no host traffic during execution), sized by --seconds.
  traffic — the partisan hyparview+plumtree round scan at --n nodes
            (the bench workload), scan length --k.

Usage:  python tools/minute_fault_repro.py pure --seconds 90
        python tools/minute_fault_repro.py traffic --n 4096 --k 2500

If `pure` faults at the same horizon as `traffic`, the limit is the
runtime/relay's per-execution deadline — an environment property, not a
formulation bug in the simulator.  Findings are recorded in
tools/MINUTE_FAULT.md.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def _sync_scalar(x) -> float:
    # jax.block_until_ready does not reliably block on the relay-attached
    # backend (see bench.py); a scalar device->host transfer is a true
    # barrier.
    return float(jax.device_get(jnp.ravel(x)[0]))


def run_pure(seconds: float) -> None:
    d = 2048

    @jax.jit
    def chain(x, k):
        def body(c, _):
            return jnp.tanh(c @ w), None
        return jax.lax.scan(body, x, None, length=k)[0]

    w = jax.random.normal(jax.random.key(0), (d, d), jnp.bfloat16)
    x = jax.random.normal(jax.random.key(1), (d, d), jnp.bfloat16)

    # Adaptive: double the scan length until ONE execution holds the
    # chip for >= `seconds` (static calibration underestimates — the
    # relay's ~0.3 s dispatch overhead pollutes short probes).
    k = 20_000
    while True:
        prog = jax.jit(lambda x: jax.lax.scan(
            lambda c, _: (jnp.tanh(c @ w), None), x, None, length=k)[0])
        t0 = time.perf_counter()
        _sync_scalar(prog(x))
        took = time.perf_counter() - t0
        print(f"pure: k={k} single execution ran {took:.1f}s without "
              f"fault", flush=True)
        if took >= seconds:
            print(f"pure: OK — {took:.1f}s >= {seconds:.0f}s target",
                  flush=True)
            return
        # cap growth (4x) but don't floor it: the final step should be
        # able to land just past the target instead of leaping over the
        # fault horizon
        k = int(k * max(1.15, min(4.0, (seconds * 1.15) / max(took, 0.5))))


def run_traffic(n: int, k: int) -> None:
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config, PlumtreeConfig
    from partisan_tpu.models.plumtree import Plumtree
    import numpy as np

    cfg = Config(n_nodes=n, seed=1, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups", max_broadcasts=8,
                 inbox_cap=16,
                 plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4))
    cl = Cluster(cfg, model=Plumtree())
    st = cl.init()
    rng = np.random.default_rng(7)
    base = 1
    while base < n:
        hi = min(base * 4, n)
        nodes = np.arange(base, hi, dtype=np.int32)
        targets = rng.integers(0, base, size=nodes.shape[0]).astype(np.int32)
        st = st._replace(manager=cl.manager.join_many(
            cfg, st.manager, nodes, targets))
        st = cl.steps(st, 10)
        base = hi
    _sync_scalar(st.rnd)
    # estimate per-round cost, then one LONG execution
    t0 = time.perf_counter()
    st = cl.steps(st, 10)
    _sync_scalar(st.rnd)
    per = (time.perf_counter() - t0) / 10
    print(f"traffic: n={n} per-round {per*1e3:.1f} ms, running ONE "
          f"execution of k={k} (~{per*k:.0f}s)", flush=True)
    t0 = time.perf_counter()
    st = cl.steps(st, k)
    _sync_scalar(st.rnd)
    print(f"traffic: OK — single {k}-round execution ran "
          f"{time.perf_counter()-t0:.1f}s without fault; rnd={int(st.rnd)}",
          flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("mode", choices=["pure", "traffic"])
    ap.add_argument("--seconds", type=float, default=90.0)
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--k", type=int, default=2500)
    args = ap.parse_args()
    if args.mode == "pure":
        run_pure(args.seconds)
    else:
        run_traffic(args.n, args.k)
    print("done", file=sys.stderr)


if __name__ == "__main__":
    main()
