"""Demers anti-entropy broadcast (protocols/demers_anti_entropy.erl).

Reference behavior (:118-196): every 2 s each node picks FANOUT=2 random
members and pushes its whole message store; the receiver merges and replies
with ITS store (push-pull), so stores converge epidemically.

TPU mapping: the store is a seen-bitmap ``bool[n, max_broadcasts]`` riding
the state-gossip lane.

- push: firing nodes scatter-OR their store to their fanout targets,
- pull: the same targets get an AE_PULL event message; owners answer it
  next round by scatter-ORing their store back to each requester (one
  virtual-time round of reply latency — within the 2 s timer cadence).

Broadcast injection (`broadcast/2` in the reference) sets a store bit at
the origin; convergence = every alive node's row contains the bit.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import Array

from partisan_tpu import faults as faults_mod
from partisan_tpu import types as T
from partisan_tpu.comm import LocalComm
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import msg as msg_ops
from partisan_tpu.ops import rng

FANOUT = 2                 # demers_anti_entropy.erl:42 ?FANOUT
INTERVAL_MS = 2_000        # :118 anti-entropy timer
OP_PULL = 1                # APP payload[0] opcode

_FANOUT_TAG = 201
_PUSH_EDGE_TAG = 202
_PULL_EDGE_TAG = 203


class AntiEntropyState(NamedTuple):
    store: Array  # bool[n_local, max_broadcasts]


class AntiEntropy:
    name = "demers_anti_entropy"

    def init(self, cfg: Config, comm: LocalComm) -> AntiEntropyState:
        return AntiEntropyState(
            store=jnp.zeros((comm.n_local, cfg.max_broadcasts), jnp.bool_)
        )

    def step(self, cfg: Config, comm: LocalComm, state: AntiEntropyState,
             ctx: RoundCtx, nbrs: Array) -> tuple[AntiEntropyState, Array]:
        n_local = state.store.shape[0]
        gids = comm.local_ids()
        every = cfg.rounds(INTERVAL_MS)
        fires = ((ctx.rnd + gids) % every == 0) & ctx.alive

        # Pick FANOUT random neighbors (do_gossip, demers_anti_entropy.erl:176-189).
        def pick(key, row, fire):
            slots = rng.choice_slots(rng.subkey(key, _FANOUT_TAG), row >= 0, FANOUT)
            ids = jnp.where(slots >= 0, row[slots], jnp.int32(-1))
            return jnp.where(fire, ids, jnp.int32(-1))

        targets = jax.vmap(pick)(ctx.keys, nbrs, fires)       # int32[n_local, FANOUT]

        push_dst = faults_mod.filter_edges(
            ctx.faults, gids, targets, ctx.seed, ctx.rnd, _PUSH_EDGE_TAG)

        # Pull replies for LAST round's AE_PULL requests (inbox).
        in_msgs = ctx.inbox.data
        is_pull = (in_msgs[:, :, T.W_KIND] == T.MsgKind.APP) & \
                  (in_msgs[:, :, T.P0] == OP_PULL)
        pull_dst = jnp.where(is_pull, in_msgs[:, :, T.W_SRC], jnp.int32(-1))
        pull_dst = jnp.where(ctx.alive[:, None], pull_dst, jnp.int32(-1))
        pull_dst = faults_mod.filter_edges(
            ctx.faults, gids, pull_dst, ctx.seed, ctx.rnd, _PULL_EDGE_TAG)

        dst = jnp.concatenate([push_dst, pull_dst], axis=1)
        pushed = comm.push_or(state.store, dst)
        store = state.store | (pushed & ctx.alive[:, None])

        # Emit this round's pull requests (answered next round).
        emitted = msg_ops.build(
            cfg, T.MsgKind.APP, gids[:, None], targets,
            payload=(jnp.int32(OP_PULL),),
        )
        return AntiEntropyState(store=store), emitted

    # ---- scenario helpers --------------------------------------------
    def broadcast(self, state: AntiEntropyState, node: int, slot: int) -> AntiEntropyState:
        """Inject a broadcast at ``node`` (demers_anti_entropy:broadcast/2)."""
        return AntiEntropyState(store=state.store.at[node, slot].set(True))

    def coverage(self, state: AntiEntropyState, alive: Array, slot: int) -> Array:
        """Fraction of alive nodes that have received ``slot``."""
        have = state.store[:, slot] & alive
        return jnp.sum(have) / jnp.maximum(jnp.sum(alive), 1)
