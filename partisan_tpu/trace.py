"""Trace recording / rendering / replay — the trace-orchestrator analogue.

The reference's ``partisan_trace_orchestrator`` records typed message
events from every node (partisan_trace_orchestrator.erl:80-86), renders
them as send/receive/DROPPED lines (:250-323), persists them via dets
(partisan_trace_file.erl:26-61), and replays them by enforcing the
recorded delivery order (:197-240).

In the simulator determinism is native (SURVEY.md §5.1): the trace IS the
per-round send-tensor captured by ``Cluster.record`` — ``TraceRound(sent,
dropped)`` stacked over rounds.  Replay = re-running the same
configuration (same seed ⇒ identical rounds), or re-running with the
recorded drops compiled into an ``interpose.OmissionSchedule`` so the
delivery schedule is enforced even under different fault settings —
exactly filibuster's preloaded-omission mechanism
(partisan_trace_orchestrator.erl:598-650).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np

from partisan_tpu import types as T

TRACE_VERSION = 1


@dataclasses.dataclass(frozen=True)
class TraceEvent:
    """One send-path event (the `pre_interposition_fun` record analogue)."""

    rnd: int
    src: int
    dst: int
    kind: int
    channel: int
    clock: int
    slot: int          # (sender, emission-slot) coordinate within the round
    dropped: bool      # cut by the fault stage before delivery
    payload: tuple     # protocol payload words

    @property
    def kind_name(self) -> str:
        try:
            return T.MsgKind(self.kind).name
        except ValueError:
            return f"KIND<{self.kind}>"


class Trace:
    """A recorded execution: ``sent`` int32[T, n, E, W], ``dropped``
    bool[T, n, E] (host numpy)."""

    def __init__(self, sent, dropped, rounds=None) -> None:
        self.sent = np.asarray(sent)
        self.dropped = np.asarray(dropped)
        self.rounds = (np.arange(self.sent.shape[0], dtype=np.int32)
                       if rounds is None else np.asarray(rounds))
        assert self.sent.ndim == 4 and self.dropped.ndim == 3
        assert self.sent.shape[:3] == self.dropped.shape
        assert self.rounds.shape == (self.sent.shape[0],)

    # ---- shape ---------------------------------------------------------
    @property
    def n_rounds(self) -> int:
        return self.sent.shape[0]

    @property
    def n_nodes(self) -> int:
        return self.sent.shape[1]

    @property
    def emit_width(self) -> int:
        return self.sent.shape[2]

    @property
    def start(self) -> int:
        """Absolute round of the first recorded round."""
        return int(self.rounds[0])

    # ---- event access (trace/1 record analogue) ------------------------
    def events(self, *, include_dropped: bool = True) -> Iterator[TraceEvent]:
        snt, drp = self.sent, self.dropped
        rs, ns, es = np.nonzero(snt[..., T.W_KIND])
        for r, n, e in zip(rs, ns, es):
            m = snt[r, n, e]
            if not include_dropped and drp[r, n, e]:
                continue
            yield TraceEvent(
                rnd=int(self.rounds[r]), src=int(m[T.W_SRC]),
                dst=int(m[T.W_DST]),
                kind=int(m[T.W_KIND]), channel=int(m[T.W_CHANNEL]),
                clock=int(m[T.W_CLOCK]), slot=int(e),
                dropped=bool(drp[r, n, e]),
                payload=tuple(int(w) for w in m[T.HDR_WORDS:]),
            )

    def delivered(self) -> np.ndarray:
        """sent with fault-dropped slots cleared — what actually hit the
        wire (for replay equivalence checks)."""
        out = self.sent.copy()
        out[..., T.W_KIND] = np.where(self.dropped, 0, out[..., T.W_KIND])
        return out

    # ---- rendering (print/0, :250-323) ---------------------------------
    def render(self, *, limit: int | None = None) -> str:
        lines = []
        total = int((self.sent[..., T.W_KIND] != 0).sum())
        for i, ev in enumerate(self.events()):
            if limit is not None and i >= limit:
                lines.append(f"... ({total} events)")
                break
            tag = "DROPPED " if ev.dropped else ""
            lines.append(
                f"r={ev.rnd:<4} {tag}{ev.src} => {ev.dst} "
                f"{ev.kind_name} ch={ev.channel} clock={ev.clock} "
                f"payload={list(ev.payload)}")
        return "\n".join(lines)

    def tail(self, k: int) -> "Trace":
        """The last ``k`` recorded rounds as a Trace — the window a
        flight-recorder ring of size k retains (latency.flight_trace),
        for capture-vs-recorder equivalence checks.  ``k <= 0`` yields
        an empty zero-round Trace (an explicit start index, not ``-k:``
        — ``[-0:]`` would silently return everything)."""
        k = max(0, min(k, self.n_rounds))
        lo = self.n_rounds - k
        return Trace(self.sent[lo:], self.dropped[lo:], self.rounds[lo:])

    # ---- persistence (partisan_trace_file.erl:26-61) -------------------
    def save(self, path) -> None:
        np.savez_compressed(path, version=TRACE_VERSION, sent=self.sent,
                            dropped=self.dropped, rounds=self.rounds)

    @classmethod
    def load(cls, path) -> "Trace":
        with np.load(path) as z:
            if int(z["version"]) != TRACE_VERSION:
                raise ValueError(f"trace version {int(z['version'])} != "
                                 f"{TRACE_VERSION}")
            return cls(z["sent"], z["dropped"], z["rounds"])

    # ---- replay / schedule synthesis -----------------------------------
    def omission_schedule(self) -> np.ndarray:
        """bool[T, n, E] — the recorded fault drops as an explicit
        schedule; feed to ``interpose.OmissionSchedule`` to replay this
        execution's deliveries under zeroed stochastic faults."""
        return self.dropped.copy()

    def matches(self, other: "Trace") -> bool:
        """Same delivered traffic (the replay fidelity check)?"""
        a, b = self.delivered(), other.delivered()
        return a.shape == b.shape and bool(np.array_equal(a, b))


def from_capture(traced) -> Trace:
    """Build a Trace from ``Cluster.record``'s stacked TraceRound pytree."""
    return Trace(np.asarray(traced.sent), np.asarray(traced.dropped),
                 np.asarray(traced.rnd))


def schedule_from_events(events, n_rounds: int, n_nodes: int,
                         emit_width: int, *, start: int = 0) -> np.ndarray:
    """Compile (absolute-rnd, src, slot) omission coordinates into a dense
    schedule bool[T, n, E] whose row 0 is absolute round ``start`` — feed
    to ``interpose.OmissionSchedule(sched, start=start)`` (the
    classify/preload step of filibuster schedule execution,
    filibuster_SUITE.erl:1155-1192 → trace orchestrator preload)."""
    sched = np.zeros((n_rounds, n_nodes, emit_width), np.bool_)
    for (r, s, e) in events:
        if 0 <= r - start < n_rounds:
            sched[r - start, s, e] = True
    return sched
