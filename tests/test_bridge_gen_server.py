"""partisan_gen_server call/reply semantics OVER THE BRIDGE.

The reference ships a drop-in OTP layer whose remote calls funnel
through ``partisan:forward_message`` (priv/otp/24/partisan_gen.erl
:360-400: monitor + ``{'$gen_call', {Self, Mref}, Req}``; reply =
``{Mref, Reply}``; timeout demonitors and discards late replies; a DOWN
aborts the call).  With no BEAM in this image (see
test_bridge_conformance), this suite runs the PACKAGE implementation of
that protocol (partisan_tpu.otp.gen + otp.gen_server) against the real
bridge transport: each "VM" is an emulated BEAM node holding a TCP
connection to the shared simulator (`socket_server`) — a port in the
:class:`partisan_tpu.otp.gen.Port` sense.  ~10 representative behaviors
of test/partisan_gen_server_SUITE.erl (2241 LoC) at the semantics
level; only the counter callback module is suite-local.
"""

import pytest

from support import BridgeVM, bridge_rig

from partisan_tpu.otp import gen
from partisan_tpu.otp.gen_server import GenServer, Stop

FN_INCR, FN_GET, FN_STOP = 1, 2, 3


class Counter:
    """The suite's counter callback module (handle_call/handle_cast)."""

    def __init__(self):
        self.value = 0

    def handle_call(self, fn, arg, src):
        if fn == FN_INCR:
            self.value += arg
            return True, self.value
        if fn == FN_GET:
            return True, self.value
        if fn == FN_STOP:
            return Stop(True, 0)
        return False, 0          # unknown -> error reply

    def handle_cast(self, fn, arg, src):
        if fn == FN_INCR:
            self.value += arg


@pytest.fixture()
def rig():
    srv = bridge_rig(4)
    procs = []
    try:
        a = gen.Caller(BridgeVM(srv, 0))
        b = GenServer(BridgeVM(srv, 1), Counter())
        c = gen.Caller(BridgeVM(srv, 2))
        d = GenServer(BridgeVM(srv, 3), Counter())
        procs = [a, b, c, d]
        yield srv, a, b, c, d
    finally:
        for p in procs:
            p.close()
        srv.close()


def test_call_reply_and_state_across_calls(rig):
    _, a, b, _, _ = rig
    assert a.call(b.id, FN_INCR, 5, pump=b.process) == (True, 5)
    assert a.call(b.id, FN_INCR, 3, pump=b.process) == (True, 8)
    assert a.call(b.id, FN_GET, pump=b.process) == (True, 8)


def test_cast_is_async_and_observable(rig):
    _, a, b, _, _ = rig
    a.cast(b.id, FN_INCR, 10)
    a.step(2)
    b.process()
    assert a.call(b.id, FN_GET, pump=b.process) == (True, 10)


def test_unknown_request_error_reply(rig):
    _, a, b, _, _ = rig
    ok, _ = a.call(b.id, 99, pump=b.process)
    assert ok is False


def test_concurrent_calls_get_their_own_replies(rig):
    """Two clients call simultaneously; each reply pairs with ITS ref
    (the alias/Mref pairing of partisan_gen)."""
    _, a, b, c, _ = rig
    ra = a.send_call(b.id, FN_INCR, 100)
    rc = c.send_call(b.id, FN_INCR, 1)
    got_a = got_c = None
    for _ in range(12):
        a.step(1)
        b.process()
        got_a = got_a or a.poll(ra)
        got_c = got_c or c.poll(rc)
        if got_a and got_c:
            break
    assert got_a is not None and got_c is not None
    # both admitted, order unspecified; final counter saw both
    assert {got_a[1], got_c[1]} <= {1, 100, 101}
    assert a.call(b.id, FN_GET, pump=b.process) == (True, 101)


def test_pipelined_calls_reply_in_fifo_order(rig):
    """Per-sender FIFO (the transport's per-connection ordering): three
    pipelined calls reply in issue order."""
    _, a, b, _, _ = rig
    refs = [a.send_call(b.id, FN_INCR, 1) for _ in range(3)]
    replies = []
    for _ in range(16):
        a.step(1)
        b.process()
        for r in list(refs):
            got = a.poll(r)
            if got is not None:
                replies.append((r, got[1]))
                refs.remove(r)
    assert [r for r, _ in replies] == sorted(r for r, _ in replies)
    assert [v for _, v in replies] == [1, 2, 3]


def test_call_times_out_when_server_silent(rig):
    _, a, _, _, _ = rig
    # node 3's server exists but is never pumped -> no reply -> timeout
    assert a.call(3, FN_INCR, 1, timeout_steps=6) == ("timeout", 3)


def test_late_reply_after_timeout_is_discarded(rig):
    """partisan_gen discards a reply arriving after the caller timed
    out (the stale-ref rule) — the next call is NOT confused by it."""
    _, a, b, _, _ = rig
    mref = a.send_call(b.id, FN_INCR, 7)
    a.mark_stale(mref)          # caller timed out: ref demonitored
    a.step(2)
    b.process()                 # server replies late
    a.step(2)
    # a fresh call must pair with its OWN reply, skipping the stale one
    got = a.call(b.id, FN_GET, pump=b.process)
    assert got == (True, 7)     # late incr applied server-side; stale
    #                             reply itself never surfaced as a result


def test_monitor_down_aborts_call(rig):
    """monitor-during-call: the destination crashes mid-call; the
    caller gets DOWN instead of hanging (partisan_gen monitor path over
    the manager's liveness signal)."""
    srv, a, b, _, _ = rig
    from partisan_tpu.bridge import etf
    from partisan_tpu.bridge.etf import Atom

    a.send_call(b.id, FN_INCR, 1)              # in flight...
    assert a.port.rpc((Atom("crash"), b.id)) == etf.OK
    out = a.call(b.id, FN_GET, monitor=True, timeout_steps=20)
    assert out == ("DOWN", b.id)


def test_two_servers_route_independently(rig):
    _, a, b, _, d = rig
    assert a.call(b.id, FN_INCR, 5, pump=b.process) == (True, 5)
    assert a.call(d.id, FN_INCR, 9, pump=d.process) == (True, 9)
    assert a.call(b.id, FN_GET, pump=b.process) == (True, 5)
    assert a.call(d.id, FN_GET, pump=d.process) == (True, 9)


def test_stopped_server_ignores_further_calls(rig):
    _, a, b, _, _ = rig
    assert a.call(b.id, FN_STOP, pump=b.process)[0] is True
    assert a.call(b.id, FN_GET, pump=b.process, timeout_steps=6) == \
        ("timeout", b.id)


def test_mux_stacks_two_behaviours_on_one_node():
    """One node runs BOTH a gen_server and a supervisor child host (the
    registered-process table): a Mux routes each opcode to its
    behaviour, so calls and START/STOP orders interleave on one port
    without stealing each other's mail."""
    from partisan_tpu.otp.supervisor import ChildHost, PERMANENT, Supervisor

    srv = bridge_rig(4)
    try:
        mux = gen.Mux(BridgeVM(srv, 1))
        b = GenServer(mux.attach(gen.OP_CALL, gen.OP_CAST), Counter())
        host = ChildHost(mux.attach(gen.OP_START, gen.OP_STOP))
        a = gen.Caller(BridgeVM(srv, 0))
        sup = Supervisor(BridgeVM(srv, 2), [(30, 1, PERMANENT)])
        sup.start_all()

        def pump(rnd):
            b.process()
            host.process()
            sup.process(rnd)

        assert a.call(b.id, FN_INCR, 5, pump=pump) == (True, 5)
        assert host.running == {30: 1}          # START wasn't stolen
        host.kill(sup.id, 30)
        for _ in range(6):
            pump(a.step(1))
        assert host.running == {30: 2}          # supervision healed it
        assert a.call(b.id, FN_GET, pump=pump) == (True, 5)
        a.close()
        sup.close()
        mux.close()
    finally:
        srv.close()
