"""Sharded-execution tests on the virtual 8-device CPU mesh: the sharded
round must produce EXACTLY the same cluster evolution as the single-device
round (placement invariance), across managers/models/faults."""

import jax
import jax.numpy as jnp
import pytest

from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config
from partisan_tpu import faults as faults_mod
from partisan_tpu.models.anti_entropy import AntiEntropy
from partisan_tpu.parallel import ShardedCluster, make_mesh
from partisan_tpu.parallel.sharded import _shard_map


def _test_shard_map(f, **kw):
    kw.pop("check_vma", None)
    return _shard_map(f, kw.pop("mesh"), in_specs=kw.pop("in_specs"),
                      out_specs=kw.pop("out_specs"))


def bootstrap(cl, st):
    m = st.manager
    for i in range(1, cl.cfg.n_nodes):
        m = cl.manager.join(cl.cfg, m, i, 0)
    return st._replace(manager=m)


# mesh8 is the session-scoped fixture from conftest.py (shared with
# tests/test_sharded_health.py — one mesh per session).


def test_sharded_matches_local(mesh8):
    cfg = Config(n_nodes=16, seed=21)
    model = AntiEntropy()

    local = Cluster(cfg, model=AntiEntropy())
    st_l = bootstrap(local, local.init())
    st_l = st_l._replace(model=model.broadcast(st_l.model, 0, 0))
    st_l = local.steps(st_l, 40)

    shard = ShardedCluster(cfg, mesh8, model=AntiEntropy())
    st_s = bootstrap(shard, shard.init())
    st_s = st_s._replace(model=model.broadcast(st_s.model, 0, 0))
    st_s = shard.steps(st_s, 40)

    assert bool(jnp.all(st_l.manager.view == st_s.manager.view))
    assert bool(jnp.all(st_l.model.store == st_s.model.store))
    assert int(st_l.stats.delivered) == int(st_s.stats.delivered)
    assert int(st_l.stats.dropped) == int(st_s.stats.dropped)


def test_sharded_matches_local_under_faults(mesh8):
    cfg = Config(n_nodes=16, seed=33)
    model = AntiEntropy()

    def prep(cl):
        st = bootstrap(cl, cl.init())
        st = cl.steps(st, 20)
        st = st._replace(
            faults=faults_mod.crash(
                st.faults._replace(link_drop=jnp.float32(0.1)), 7),
            model=model.broadcast(st.model, 3, 2),
        )
        return cl.steps(st, 30)

    st_l = prep(Cluster(cfg, model=AntiEntropy()))
    st_s = prep(ShardedCluster(cfg, mesh8, model=AntiEntropy()))
    assert bool(jnp.all(st_l.manager.view == st_s.manager.view))
    assert bool(jnp.all(st_l.model.store == st_s.model.store))
    assert int(st_l.stats.delivered) == int(st_s.stats.delivered)


def test_mesh_size_invariance(mesh8):
    """2-shard and 8-shard runs agree (placement-invariant RNG)."""
    cfg = Config(n_nodes=16, seed=55)

    def run(n_dev):
        cl = ShardedCluster(cfg, make_mesh(n_dev), model=AntiEntropy())
        st = bootstrap(cl, cl.init())
        st = st._replace(model=AntiEntropy().broadcast(st.model, 1, 0))
        return cl.steps(st, 25)

    a, b = jax.device_get(run(2)), jax.device_get(run(8))
    assert (a.manager.view == b.manager.view).all()
    assert (a.model.store == b.model.store).all()


def test_indivisible_nodes_rejected(mesh8):
    with pytest.raises(ValueError, match="not divisible"):
        ShardedCluster(Config(n_nodes=12), mesh8)


def test_sharded_trace_matches_local():
    """Trace recording is placement-invariant: the sharded cluster's
    TraceRound stream equals the single-device one (determinism across
    shardings — the replay guarantee extends to multi-device)."""
    import numpy as np

    from partisan_tpu import trace as trace_mod
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config
    from partisan_tpu.models.anti_entropy import AntiEntropy
    from partisan_tpu.parallel import ShardedCluster, make_mesh

    cfg = Config(n_nodes=16, seed=12, inbox_cap=32)

    def boot(cl):
        st = cl.init()
        m = st.manager
        for i in range(1, cfg.n_nodes):
            m = cl.manager.join(cfg, m, i, 0)
        st = st._replace(manager=m)
        st = cl.steps(st, 10)
        st = st._replace(model=cl.model.broadcast(st.model, 0, 0))
        return st

    local = Cluster(cfg, model=AntiEntropy())
    _, cap_l = local.record(boot(local), 6)

    sharded = ShardedCluster(cfg, make_mesh(4), model=AntiEntropy())
    _, cap_s = sharded.record(boot(sharded), 6)

    tl = trace_mod.from_capture(cap_l)
    ts = trace_mod.from_capture(cap_s)
    assert np.array_equal(tl.sent, ts.sent)
    assert np.array_equal(tl.dropped, ts.dropped)
    assert tl.matches(ts)


def test_all_to_all_exchange_matches_local(mesh8):
    """The destination-sharded all_to_all exchange (sort by dest shard +
    lax.all_to_all, parallel/sharded.py _route_a2a) evolves the cluster
    bit-identically to the single-device run when the quota is not
    exceeded — same contract as the all_gather parity tests above."""
    cfg = Config(n_nodes=16, seed=21, sharded_exchange="all_to_all")
    model = AntiEntropy()

    local = Cluster(cfg, model=AntiEntropy())
    st_l = bootstrap(local, local.init())
    st_l = st_l._replace(model=model.broadcast(st_l.model, 0, 0))
    st_l = local.steps(st_l, 40)

    shard = ShardedCluster(cfg, mesh8, model=AntiEntropy())
    st_s = bootstrap(shard, shard.init())
    st_s = st_s._replace(model=model.broadcast(st_s.model, 0, 0))
    st_s = shard.steps(st_s, 40)

    assert bool(jnp.all(st_l.manager.view == st_s.manager.view))
    assert bool(jnp.all(st_l.model.store == st_s.model.store))
    assert int(st_l.stats.delivered) == int(st_s.stats.delivered)
    assert int(st_l.stats.dropped) == int(st_s.stats.dropped)


def test_all_to_all_hyparview_plumtree_parity(mesh8):
    """a2a parity on the bench workload (hyparview + plumtree): overlay
    views AND broadcast stores agree with the single-device run."""
    from partisan_tpu.models.plumtree import Plumtree

    def run(make):
        cfg = Config(n_nodes=16, seed=5, peer_service_manager="hyparview",
                     msg_words=16, sharded_exchange="all_to_all")
        model = Plumtree()
        cl = make(cfg, model)
        st = bootstrap(cl, cl.init())
        st = cl.steps(st, 15)
        st = st._replace(model=model.broadcast(st.model, 0, 0))
        st = cl.steps(st, 25)
        return st, model

    st_l, model = run(lambda c, m: Cluster(c, model=m))
    st_s, _ = run(lambda c, m: ShardedCluster(c, mesh8, model=m))
    assert bool(jnp.all(st_l.manager.active == st_s.manager.active))
    assert bool(jnp.all(st_l.model.data == st_s.model.data))
    assert float(model.coverage(st_s.model, st_s.faults.alive, 0)) == 1.0


def test_all_to_all_quota_semantics(mesh8):
    """The a2a quota spec, exercised at the comm level with synthetic
    emissions: within quota everything routes identically to the local
    exchange; a shard-pair exceeding Q delivers exactly the first Q
    messages in per-sender FIFO order and sheds the rest."""
    from functools import partial

    from partisan_tpu import types as T
    from partisan_tpu.ops import exchange, msg as msg_ops
    from partisan_tpu.parallel.sharded import AXIS, ShardComm

    n, shards, E, W = 16, 8, 6, 12
    comm = ShardComm(n_global=n, inbox_cap=8, msg_words=W, n_shards=shards,
                     exchange_mode="all_to_all", a2a_factor=1)
    # per shard: n_local=2, M=12, Q = 1*ceil(12/8) = 2 slots per dest shard
    # Every node on shard 3 (nodes 6,7) sends E=6 messages to node 0 →
    # 12 messages into shard 0's quota of 2 from that source shard.
    src = jnp.arange(n, dtype=jnp.int32)[:, None]
    dst = jnp.where((src == 6) | (src == 7), 0, -1)
    dst = jnp.broadcast_to(dst, (n, E))
    seqs = jnp.broadcast_to(jnp.arange(E, dtype=jnp.int32)[None], (n, E))
    emitted = msg_ops.build(W, T.MsgKind.APP,
                            jnp.broadcast_to(src, (n, E)), dst,
                            payload=(seqs,))

    @partial(jax.jit, out_shardings=None)
    def run(emitted):
        body = _test_shard_map(
            lambda e: comm.route(e), mesh=mesh8,
            in_specs=(jax.sharding.PartitionSpec(AXIS),),
            out_specs=exchange.Inbox(
                data=jax.sharding.PartitionSpec(AXIS),
                count=jax.sharding.PartitionSpec(AXIS),
                drops=jax.sharding.PartitionSpec(AXIS)),
            check_vma=False)
        return body(emitted)

    inbox = jax.device_get(run(emitted))
    # quota Q=2 per (src shard, dst shard): of the 12 messages only the
    # first 2 in flattened emission order survive the exchange
    assert int(inbox.count[0]) == 2
    got = inbox.data[0][: 2]
    assert list(got[:, T.W_SRC]) == [6, 6]            # sender FIFO head
    assert list(got[:, T.P0]) == [0, 1]               # first two seqs
    assert int(inbox.count[1:].sum()) == 0


def test_distance_plane_sharded_parity(mesh8):
    """The distance/RTT plane (round 4) under shard_map: the measured
    RTT caches, pending-pong buffers and X-BOT-visible state evolve
    BIT-IDENTICALLY to the single-device run (placement invariance of
    the ping/pong exchange and the modeled link geometry)."""
    from partisan_tpu.config import DistanceConfig

    def run(make):
        cfg = Config(n_nodes=16, seed=9, peer_service_manager="hyparview",
                     msg_words=16, distance_interval_ms=2_000,
                     distance=DistanceConfig(enabled=True, model="ring",
                                             max_latency_rounds=3))
        cl = make(cfg)
        st = bootstrap(cl, cl.init())
        return cl.steps(st, 40)

    st_l = run(lambda c: Cluster(c))
    st_s = run(lambda c: ShardedCluster(c, mesh8))
    assert bool(jnp.all(st_l.manager.active == st_s.manager.active))
    assert bool(jnp.all(st_l.manager.dist.rtt_node ==
                        st_s.manager.dist.rtt_node))
    assert bool(jnp.all(st_l.manager.dist.rtt_val ==
                        st_s.manager.dist.rtt_val))
    assert int((st_s.manager.dist.rtt_node >= 0).sum()) > 0


def test_slot_epoch_recycling_sharded_parity(mesh8):
    """Slot-epoch recycling (round 4 per-root trees) under shard_map:
    recycled-slot epochs, tree flags and stores match the single-device
    evolution exactly."""
    from partisan_tpu.models.plumtree import Plumtree

    def run(make):
        cfg = Config(n_nodes=16, seed=5, peer_service_manager="hyparview",
                     msg_words=16, max_broadcasts=4)
        model = Plumtree()
        cl = make(cfg, model)
        st = bootstrap(cl, cl.init())
        st = cl.steps(st, 15)
        st = st._replace(model=model.broadcast(st.model, 3, 0, 1))
        st = cl.steps(st, 15)
        # recycle slot 0 for a different root
        st = st._replace(model=model.broadcast(st.model, 8, 0, 2,
                                               fresh=True))
        st = cl.steps(st, 20)
        return st, model

    st_l, model = run(lambda c, m: Cluster(c, model=m))
    st_s, _ = run(lambda c, m: ShardedCluster(c, mesh8, model=m))
    assert bool(jnp.all(st_l.model.epoch == st_s.model.epoch))
    assert bool(jnp.all(st_l.model.data == st_s.model.data))
    assert bool(jnp.all(st_l.model.pruned == st_s.model.pruned))
    # the recycled epoch spread to EVERY node (eager gossip carries it;
    # AAE-satisfied nodes adopt via the epoch scatter-max on the
    # exchange lane)
    assert int((st_s.model.epoch[:, 0] == 1).sum()) == 16
    assert float(model.coverage(st_s.model, st_s.faults.alive, 0, 2)) == 1.0


def test_wide_sharded_parity_through_convergence(mesh8):
    """VERDICT r4 weak #6: all sharded evidence ran 16 nodes on mesh8
    (2/shard).  This runs the bench stack (hyparview + plumtree +
    distance, aligned timers, a2a exchange) at support.WIDE_N nodes
    (512/shard under PARTISAN_TEST_FULL=1, 128/shard default — both
    multi-wave, cross-shard) through a factor-8 wave bootstrap AND
    broadcast convergence, asserting bit-parity with the single-device
    run; then a factor-1 quota soak at the same width must still
    converge (repair absorbs any quota shed)."""
    import numpy as np

    from partisan_tpu.config import DistanceConfig
    from partisan_tpu.models.plumtree import Plumtree

    from support import WIDE_N as n

    def cfg_for(factor):
        return Config(n_nodes=n, seed=91, peer_service_manager="hyparview",
                      msg_words=16, partition_mode="groups",
                      emit_compact=32, timer_stagger=False,
                      sharded_exchange="all_to_all", a2a_factor=factor,
                      distance_interval_ms=2_000,
                      distance=DistanceConfig(enabled=True, model="ring"))

    def run(make, cfg, converge=False):
        model = Plumtree()
        cl = make(cfg, model)
        st = cl.init()
        rng = np.random.default_rng(3)
        base = 1
        while base < n:
            hi = min(base * 8, n)
            nodes = np.arange(base, hi, dtype=np.int32)
            tgts = rng.integers(0, base,
                                size=nodes.shape[0]).astype(np.int32)
            st = st._replace(manager=cl.manager.join_many(
                cfg, st.manager, nodes, tgts))
            st = cl.steps(st, 10)
            base = hi
        st = st._replace(model=model.broadcast(st.model, 0, 0))
        st = cl.steps(st, 30)
        if converge:
            # the quota soak sheds traffic by design; the invariant is
            # that repair converges within a BOUNDED extra budget, not
            # that a fixed 30 rounds always suffice for every stream.
            # Record the extra 10-round batches actually consumed and
            # keep the bound TIGHT (ADVICE r5 #3): the soak was
            # measured to need <= 2 extra batches; more than 4 means
            # shed/repair behavior regressed, even if it would still
            # converge eventually.
            extra = 0
            for _ in range(12):
                if float(model.coverage(st.model, st.faults.alive,
                                        0)) == 1.0:
                    break
                st = cl.steps(st, 10)
                extra += 1
            assert extra <= 4, (
                f"quota-soak repair consumed {extra} extra 10-round "
                f"batches (> 4): shed/repair convergence regressed")
        return jax.device_get(st), model

    cfg = cfg_for(4)
    st_l, model = run(lambda c, m: Cluster(c, model=m), cfg)
    st_s, _ = run(lambda c, m: ShardedCluster(c, mesh8, model=m), cfg)
    assert bool(jnp.all(st_l.manager.active == st_s.manager.active))
    assert bool(jnp.all(st_l.manager.passive == st_s.manager.passive))
    assert bool(jnp.all(st_l.model.data == st_s.model.data))
    assert bool(jnp.all(st_l.model.pruned == st_s.model.pruned))
    assert bool(jnp.all(st_l.manager.dist.rtt_val
                        == st_s.manager.dist.rtt_val))
    assert int(st_l.stats.dropped) == int(st_s.stats.dropped)
    assert float(model.coverage(st_s.model, st_s.faults.alive, 0)) == 1.0
    # quota-pressure soak: factor 1 shrinks every (src shard, dst shard)
    # budget 4x; convergence must survive whatever it sheds
    st_q, _ = run(lambda c, m: ShardedCluster(c, mesh8, model=m),
                  cfg_for(1), converge=True)
    assert float(model.coverage(st_q.model, st_q.faults.alive, 0)) == 1.0


def test_all_to_all_quota_pressure_wide(mesh8):
    """Per-shard emission volume EXCEEDING the a2a quota, at realistic
    width: shard 7's 512 nodes each aim a full emission row at shard-0
    nodes — 4096 real messages against a Q=2048-slot budget.  The first
    Q survive in flattened (sender, slot) order; the rest shed; other
    shards' inboxes stay empty."""
    from functools import partial

    from partisan_tpu import types as T
    from partisan_tpu.ops import exchange, msg as msg_ops
    from partisan_tpu.parallel.sharded import AXIS, ShardComm

    n, shards, E, W = 4096, 8, 8, 12
    n_local = n // shards
    comm = ShardComm(n_global=n, inbox_cap=16, msg_words=W,
                     n_shards=shards, exchange_mode="all_to_all",
                     a2a_factor=4)
    # M = n_local*E = 4096 slots -> Q = 4*ceil(M/8) = 2048 per dst shard
    src = jnp.arange(n, dtype=jnp.int32)[:, None]
    on7 = src >= 7 * n_local
    dst = jnp.where(on7, (src - 7 * n_local) % n_local, -1)
    dst = jnp.broadcast_to(dst, (n, E))
    emitted = msg_ops.build(
        W, T.MsgKind.APP, jnp.broadcast_to(src, (n, E)), dst,
        payload=(jnp.broadcast_to(jnp.arange(E)[None], (n, E)),))

    @partial(jax.jit, out_shardings=None)
    def run(emitted):
        body = _test_shard_map(
            lambda e: comm.route(e), mesh=mesh8,
            in_specs=(jax.sharding.PartitionSpec(AXIS),),
            out_specs=exchange.Inbox(
                data=jax.sharding.PartitionSpec(AXIS),
                count=jax.sharding.PartitionSpec(AXIS),
                drops=jax.sharding.PartitionSpec(AXIS)),
            check_vma=False)
        return body(emitted)

    inbox = jax.device_get(run(emitted))
    got = int(inbox.count[:n_local].sum())
    assert got == 2048                      # exactly the quota survived
    assert int(inbox.count[n_local:].sum()) == 0


def test_sharded_plane_vs_legacy_layout(mesh8):
    """Cross-layout x cross-placement parity: the sharded plane-major
    round (packed planes over the all_gather exchange) evolves the
    cluster bit-identically to the sharded legacy-interleaved round —
    and both match their single-device twins (covered by the other
    tests; normalized comparison here)."""
    import dataclasses

    from support import assert_states_bitidentical

    base = Config(n_nodes=16, seed=21)
    model = AntiEntropy()

    def run(pm):
        cfg = dataclasses.replace(base, plane_major=pm)
        cl = ShardedCluster(cfg, mesh8, model=AntiEntropy())
        st = bootstrap(cl, cl.init())
        st = st._replace(model=model.broadcast(st.model, 0, 0))
        st = cl.steps(st, 10)
        st = st._replace(faults=faults_mod.crash(st.faults, 3))
        return cl.steps(st, 10)

    assert_states_bitidentical(run(True), run(False), "sharded_layouts")


def test_traffic_plane_sharded_parity(mesh8):
    """The open-loop traffic generator under sharding: the arrival
    stream is a pure function of (seed, round, node) and its state a
    reduced scalar + ring, so the sharded run must evolve
    bit-identically to the single-device one — traffic leaf included
    (this also guards ShardedCluster.init()'s traffic leaf: a missing
    one crashes at trace time)."""
    import numpy as np

    from partisan_tpu import workload as workload_mod
    from partisan_tpu.config import TrafficConfig
    from partisan_tpu.models.plumtree import Plumtree

    cfg = Config(n_nodes=16, seed=27, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups",
                 traffic=TrafficConfig(enabled=True, rate_x1000=900,
                                       hot_skew=1, ring=16))

    def run(make):
        cl = make()
        st = cl.init()
        m = cl.manager.join_many(cfg, st.manager, list(range(1, 16)),
                                 [0] * 15)
        st = cl.steps(st._replace(manager=m), 24)
        return jax.device_get(st)

    st_l = run(lambda: Cluster(cfg, model=Plumtree()))
    st_s = run(lambda: ShardedCluster(cfg, mesh8, model=Plumtree()))
    assert workload_mod.poll(st_l.traffic) \
        == workload_mod.poll(st_s.traffic)
    assert np.array_equal(np.asarray(st_l.traffic.arr_ring),
                          np.asarray(st_s.traffic.arr_ring))
    assert int(st_l.stats.delivered) == int(st_s.stats.delivered)
    assert int(st_l.stats.dropped) == int(st_s.stats.dropped)
    assert bool(jnp.all(st_l.manager.active == st_s.manager.active))
