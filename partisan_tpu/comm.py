"""Communication substrate: how a round's traffic actually moves.

The reference's transport stack (L0/L1: sockets, framing, connection
registry — SURVEY.md §2 "Connection layer") is replaced by two batched
primitives that managers/models program against:

- ``route(emitted)``  — event-message delivery into per-node inboxes
- ``push_max(rows, dst)`` / ``push_or`` — monotonic state-gossip merge

``LocalComm`` runs them on one device.  ``ShardComm`` (parallel/sharded.py)
runs the same interface inside ``shard_map`` over a device mesh: emissions
are all-gathered over ICI and each shard routes/merges only its own node
range — the TPU-native replacement for the reference's TCP fan-out.
Protocol code is identical under both, which is the analogue of the
reference's manager-behaviour portability across transports.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import Array

from partisan_tpu.ops import exchange, gossip


@dataclasses.dataclass(frozen=True)
class LocalComm:
    """Single-device communication: all nodes live on one shard."""

    n_global: int
    inbox_cap: int
    msg_words: int

    # Shard geometry (trivial here; ShardComm overrides).
    @property
    def n_local(self) -> int:
        return self.n_global

    @property
    def node_offset(self) -> int:
        return 0

    def local_ids(self) -> Array:
        """Global ids of the nodes this shard owns."""
        return jnp.arange(self.n_global, dtype=jnp.int32)

    def route(self, emitted: Array) -> exchange.Inbox:
        """Deliver int32[n_local, E, W] emissions -> local Inbox."""
        return exchange.route(emitted, self.n_global, self.inbox_cap)

    def push_max(self, rows: Array, dst: Array) -> Array:
        """Scatter-max rows along edges; returns merged rows for local nodes
        (zeros where nothing arrived)."""
        return gossip.push_max(rows, dst, n_out=self.n_global)

    def push_or(self, rows: Array, dst: Array) -> Array:
        return self.push_max(rows.astype(jnp.uint8), dst).astype(jnp.bool_)

    def allsum(self, x: Array) -> Array:
        """Sum a per-shard scalar across all shards (identity here)."""
        return x

    def allmax(self, x: Array) -> Array:
        """Max of a per-shard scalar across all shards (identity here;
        the metrics plane's high-water-mark reduction)."""
        return x

    def allmin(self, x: Array) -> Array:
        """Min of a per-shard value across all shards (identity here).
        Elementwise on arrays — the health plane's segment-local FastSV
        reduces its per-shard label proposals through this."""
        return x

    def actor_gather(self, x: Array, a: int) -> Array:
        """Rows of ``x`` for global nodes 0..a-1 (the causal actor
        space), visible to every shard.  Requires a <= n_local so the
        actor block lives on one shard (cross-shard it is a psum of
        zero-padded local slices)."""
        if a > self.n_local:
            raise ValueError(
                f"n_actors={a} must be <= nodes per shard ({self.n_local})")
        return x[:a]

    def gather_vec(self, x: Array) -> Array:
        """Concatenate a per-node local vector into the global one
        (identity here; an all_gather on shards)."""
        return x
