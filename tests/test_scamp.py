"""SCAMP v1/v2 membership tests — sim analogues of the reference's
membership-strategy coverage (partisan_SUITE.erl group
`with_scamp_membership_strategy`): subscription walks populate partial
views, the overlay stays connected, view sizes track (c+1)·log n,
removals/leaves propagate, isolation detection re-subscribes, and the
overlay survives churn (driver config #4)."""

import jax
import numpy as np

from partisan_tpu import faults as faults_mod
from partisan_tpu.cluster import Cluster
from partisan_tpu.parallel import ShardedCluster, make_mesh

from support import components, staggered_join


def sc_config(n, seed, version=2, **kw):
    from partisan_tpu.config import Config, ScampConfig
    kw.setdefault("scamp", ScampConfig(partial_max=16, in_max=16))
    return Config(n_nodes=n, seed=seed,
                  peer_service_manager=f"scamp_v{version}", **kw)


def boot(cfg, settle=60):
    cl = Cluster(cfg)
    st = staggered_join(cl, cl.init())
    return cl, cl.steps(st, settle)


def test_v1_overlay_forms_and_is_connected():
    cfg = sc_config(32, seed=11, version=1)
    cl, st = boot(cfg)
    partial = np.asarray(st.manager.partial)
    alive = np.asarray(st.faults.alive)

    sizes = (partial >= 0).sum(axis=1)
    assert (sizes >= 1).all(), f"empty views: {np.where(sizes == 0)[0]}"
    # Paper scaling: mean view size ~ (c+1)·ln n = 6·3.47 ≈ 21 for the
    # asymptotic regime; at n=32 with capped views expect a loose band.
    assert 2.0 < sizes.mean() < cfg.scamp.partial_max, sizes.mean()
    comps = components(partial, alive)
    assert len(comps) == 1, f"overlay partitioned into {len(comps)}"
    # No self-loops or duplicates.
    for i in range(cfg.n_nodes):
        row = [x for x in partial[i] if x >= 0]
        assert i not in row
        assert len(row) == len(set(row))


def test_v2_overlay_and_in_views():
    cfg = sc_config(32, seed=23, version=2)
    cl, st = boot(cfg)
    partial = np.asarray(st.manager.partial)
    in_view = np.asarray(st.manager.in_view)
    alive = np.asarray(st.faults.alive)

    assert len(components(partial, alive)) == 1
    # keep_subscription notifications populated in-views: every kept
    # subscription registered an in-edge somewhere.
    assert (in_view >= 0).sum() > 0
    # In-view entries correspond to real out-edges most of the time
    # (keeper holds us in its partial view).
    hits = total = 0
    for i in range(cfg.n_nodes):
        for keeper in in_view[i]:
            if keeper >= 0:
                total += 1
                hits += i in set(partial[int(keeper)])
    assert total > 0 and hits / total > 0.6, (hits, total)


def test_v1_leave_propagates_removal():
    cfg = sc_config(24, seed=7, version=1)
    cl, st = boot(cfg)
    before = np.asarray(st.manager.partial)
    holders_before = [i for i in range(24) if i != 5 and 5 in set(before[i])]
    st = st._replace(manager=cl.manager.leave(cfg, st.manager, 5))
    st = cl.steps(st, 40)
    partial = np.asarray(st.manager.partial)
    assert (partial[5] < 0).all(), "leaver kept its view"
    holders = [i for i in range(24) if i != 5 and 5 in set(partial[i])]
    # Holders (re-gossip "when present", v1 :239-262) take removals;
    # non-holders forward them as TTL-bounded walks so the wave can
    # cross from the leaver's out-view to its in-view (the reference's
    # remove_subscription rides periodic gossip until it lands).  Stale
    # out-edges may still linger past the TTL — exactly as in the
    # reference, where they die when a connect to the left node fails.
    # Require real shrinkage.
    assert len(holders) < len(holders_before), (holders, holders_before)
    assert len(holders) <= max(2, len(holders_before) // 2), holders


def test_v2_graceful_leave_rebalances():
    cfg = sc_config(24, seed=41, version=2)
    cl, st = boot(cfg)
    st = st._replace(manager=cl.manager.leave(cfg, st.manager, 5))
    st = cl.steps(st, 40)
    partial = np.asarray(st.manager.partial)
    alive = np.asarray(st.faults.alive)
    assert (partial[5] < 0).all()
    holders = [i for i in range(24) if i != 5 and 5 in set(partial[i])]
    assert not holders, f"leaver still referenced by {holders}"
    # Replacement edges keep the survivors connected.
    mask = np.ones(24, bool)
    mask[5] = False
    comps = components(partial, alive & mask)
    assert len(comps) == 1, f"leave partitioned the overlay: {comps}"


def test_isolation_resubscription():
    """A node whose in-edges all vanish re-subscribes after the
    message_window (scamp_v1 :196-215)."""
    from partisan_tpu.config import ScampConfig
    cfg = sc_config(16, seed=3, version=2,
                    scamp=ScampConfig(partial_max=16, in_max=16,
                                      message_window=2))
    cl, st = boot(cfg, settle=40)
    # Sever node 9 from everyone's views (but keep its out-view so it
    # can re-subscribe through a member).
    m = st.manager
    partial = np.array(m.partial)
    for i in range(16):
        if i != 9:
            partial[i] = np.where(partial[i] == 9, -1, partial[i])
    st = st._replace(manager=m._replace(
        partial=jax.numpy.asarray(partial)))
    st = cl.steps(st, cfg.gossip_every * (cfg.scamp.message_window + 6))
    partial = np.asarray(st.manager.partial)
    holders = [i for i in range(16) if i != 9 and 9 in set(partial[i])]
    assert holders, "isolated node never re-entered any partial view"


def test_survives_churn():
    """Driver config #4: SCAMP v2 under a birth/death process."""
    cfg = sc_config(32, seed=99, version=2)
    cl, st = boot(cfg)

    @jax.jit
    def churn_round(st):
        f = faults_mod.churn_step(st.faults, cfg.seed, st.rnd,
                                  death_p=0.01, birth_p=0.2)
        return cl._round(st._replace(faults=f))

    for _ in range(60):
        st = churn_round(st)
    alive = np.asarray(st.faults.alive)
    partial = np.asarray(st.manager.partial)
    assert alive.sum() > 16, "churn killed the cluster (tune rates)"
    comps = components(partial, alive)
    # The giant component holds nearly all alive nodes.
    giant = max(len(c) for c in comps)
    assert giant >= 0.8 * alive.sum(), (giant, alive.sum())


def test_sharded_parity():
    cfg = sc_config(16, seed=77, version=2)
    assert len(jax.devices()) >= 8

    def run(make):
        cl = make()
        st = cl.init()
        m = st.manager
        for i in range(1, 16):
            m = cl.manager.join(cfg, m, i, 0)
        st = st._replace(manager=m)
        return jax.device_get(cl.steps(st, 50))

    a = run(lambda: Cluster(cfg))
    b = run(lambda: ShardedCluster(cfg, make_mesh(8)))
    assert (a.manager.partial == b.manager.partial).all()
    assert (a.manager.in_view == b.manager.in_view).all()
    assert (a.manager.last_heard == b.manager.last_heard).all()
