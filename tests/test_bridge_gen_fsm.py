"""partisan_gen_fsm semantics OVER THE BRIDGE.

The reference ships the (deprecated, still supported) patched OTP
gen_fsm (priv/otp/24/partisan_gen_fsm.erl, 761 LoC).  gen_fsm is the
simpler ancestor of gen_statem: per-state event handlers, plus
ALL-STATE events that any state handles.  This suite runs the PACKAGE
loop (partisan_tpu.otp.gen_fsm) over the bridge transport — only the
idle/busy callback module is suite-local.  Representative behaviors:

- send_event (async) dispatches to the CURRENT state's handler,
- sync_send_event replies from the handler's return,
- events unknown to the current state are DROPPED (gen_fsm semantics —
  unlike gen_statem there is no postpone),
- send_all_state_event reaches the all-state handler regardless of
  state,
- state timeout (the {next_state, S, Data, Timeout} form): fires only
  if NO event arrives within the timeout (any event cancels it —
  gen_fsm timeouts are event timeouts, unlike gen_statem's
  state_timeout),
- two clients' sync replies pair with their own refs.
"""

import pytest

from support import BridgeVM, bridge_rig

from partisan_tpu.otp.gen_fsm import (
    EV_TIMEOUT, FsmClient, GenFsm, Outcome)

EV_GO, EV_WORK, EV_WHO = 1, 2, 3     # per-state events
IDLE, BUSY = 0, 1
FSM_TIMEOUT = 5                      # the {next_state,...,Timeout} form


class IdleBusy:
    """StateName/2-3 dispatch: per-state handlers + the all-state log."""

    init_state = IDLE

    def __init__(self, *, timeout=None):
        self.counter = 0
        self.timeout = timeout
        self.all_state_log = []

    def handle_all_state(self, arg):
        self.all_state_log.append(arg)

    def state_handler(self, state, ev, arg):
        if ev == EV_TIMEOUT:
            return Outcome(True, 0, next_state=IDLE)
        if state == IDLE:
            if ev == EV_GO:
                return Outcome(True, BUSY, next_state=BUSY,
                               timeout=self.timeout)
            if ev == EV_WHO:
                return Outcome(True, IDLE * 1000 + self.counter)
            return Outcome(False)
        if state == BUSY:
            if ev == EV_WORK:
                self.counter += arg
                return Outcome(True, self.counter)
            if ev == EV_WHO:
                return Outcome(True, BUSY * 1000 + self.counter)
            if ev == EV_GO:
                return Outcome(True, IDLE, next_state=IDLE)
            return Outcome(False)
        return Outcome(False)


@pytest.fixture()
def rig():
    srv = bridge_rig(4)
    procs = []
    try:
        a = FsmClient(BridgeVM(srv, 0))
        m = GenFsm(BridgeVM(srv, 1), IdleBusy())
        c = FsmClient(BridgeVM(srv, 2))
        procs = [a, m, c]
        yield a, m, c
    finally:
        for p in procs:
            p.close()
        srv.close()


def _pump(a, m, k=3):
    for _ in range(k):
        m.process(a.step(1))


def test_send_event_dispatches_to_current_state(rig):
    a, m, _ = rig
    a.send_event(m.id, EV_GO)
    _pump(a, m)
    assert m.state == BUSY
    a.send_event(m.id, EV_WORK, 4)
    _pump(a, m)
    assert m.module.counter == 4


def test_sync_send_event_replies(rig):
    a, m, _ = rig
    assert a.sync_send_event(m, EV_GO) == (True, BUSY)
    assert a.sync_send_event(m, EV_WORK, 7) == (True, 7)
    assert a.sync_send_event(m, EV_WHO) == (True, 1007)


def test_unknown_event_dropped_no_postpone(rig):
    """EV_WORK in IDLE is dropped — NOT replayed after entering BUSY
    (gen_fsm has no postpone; contrast test_bridge_gen_statem)."""
    a, m, _ = rig
    a.send_event(m.id, EV_WORK, 9)        # unknown in IDLE: dropped
    _pump(a, m)
    assert a.sync_send_event(m, EV_GO) == (True, BUSY)
    _pump(a, m, 4)
    assert a.sync_send_event(m, EV_WHO) == (True, 1000)   # counter 0


def test_all_state_event_reaches_any_state(rig):
    a, m, _ = rig
    a.send_all_state_event(m.id, 11)
    _pump(a, m)
    a.sync_send_event(m, EV_GO)
    a.send_all_state_event(m.id, 22)
    _pump(a, m)
    assert m.module.all_state_log == [11, 22]


def test_fsm_timeout_fires_only_when_idle():
    srv = bridge_rig(4)
    try:
        a = FsmClient(BridgeVM(srv, 0))
        m = GenFsm(BridgeVM(srv, 1), IdleBusy(timeout=FSM_TIMEOUT))
        assert a.sync_send_event(m, EV_GO) == (True, BUSY)
        for _ in range(FSM_TIMEOUT + 2):      # silence
            m.process(a.step(1))
        assert m.state == IDLE                # timeout fired
        # …but traffic cancels it: go BUSY, keep sending events
        assert a.sync_send_event(m, EV_GO) == (True, BUSY)
        for _ in range(3):
            a.send_event(m.id, EV_WORK, 1)
            m.process(a.step(1))
            m.process(a.step(1))
        assert m.state == BUSY                # events kept it alive
        a.close()
        m.close()
    finally:
        srv.close()


def test_two_clients_sync_replies_pair(rig):
    a, m, c = rig
    assert a.sync_send_event(m, EV_GO) == (True, BUSY)
    assert c.sync_send_event(m, EV_WORK, 5) == (True, 5)
    assert a.sync_send_event(m, EV_WHO) == (True, 1005)
