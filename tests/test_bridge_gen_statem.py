"""partisan_gen_statem semantics OVER THE BRIDGE.

The reference ships a patched OTP gen_statem
(priv/otp/24/partisan_gen_statem.erl, 3008 LoC) with a conformance
suite (test/partisan_gen_statem_SUITE.erl, 2773 LoC).  With no BEAM in
this image, this suite ports ~10 representative behaviors at the
semantics level, running the statem event loop against the real bridge
transport (each "VM" is an emulated BEAM node on the shared simulator,
the pattern of tests/test_bridge_gen_server.py):

- state-transition calls with replies from the NEW state,
- keep_state (data updates without transition),
- event POSTPONE: events postponed in a state are retried — in original
  arrival order, ahead of newer events — when the state changes
  (gen_statem postpone semantics),
- STATE timeout: armed on entering a state, NOT cancelled by event
  arrival, cancelled by a state transition (OTP state_timeout),
- EVENT timeout: cancelled by ANY event arrival (OTP event timeout),
- ref/reply pairing across transitions with two concurrent clients.

The machine under test is the two-state switch (OFF/ON) with a counter —
the shape of the SUITE's start/stop machines.
"""

import pytest

from support import BridgeVM, bridge_rig

OP_CALL, OP_REPLY, OP_EVENT = 1, 2, 4
# events
EV_FLIP, EV_GET, EV_WORK, EV_ARM_IDLE, EV_TICK = 1, 2, 3, 4, 5
OFF, ON = 0, 1
STATE_TIMEOUT = 6          # rounds in ON before auto-OFF (state_timeout)
IDLE_TIMEOUT = 5           # rounds without events after ARM_IDLE


class StatemVM(BridgeVM):
    """The partisan_gen_statem loop: one state machine process."""

    def __init__(self, srv, sim_id, *, state_timeout=None):
        super().__init__(srv, sim_id)
        self.state = OFF
        self.counter = 0
        self.postponed = []        # [(src, words)] in arrival order
        self.state_deadline = None     # round at which state_timeout fires
        self.state_timeout = state_timeout
        self.idle_deadline = None      # event-timeout deadline
        self.rnd = 0

    # -- the gen_statem event loop -------------------------------------
    def process(self, rnd):
        self.rnd = rnd
        queue = list(self.drain())
        # timeouts fire as internal events BEFORE new external events if
        # their deadline has passed (timer messages were already "sent")
        if self.state_deadline is not None and rnd >= self.state_deadline:
            self.state_deadline = None
            self._transition(OFF)
        if self.idle_deadline is not None:
            if queue:
                self.idle_deadline = None       # any event cancels it
            elif rnd >= self.idle_deadline:
                self.idle_deadline = None
                self._transition(OFF)
        while queue:
            src, words = queue.pop(0)
            consumed, changed = self._handle(src, words)
            if not consumed:
                self.postponed.append((src, words))
            if changed:
                # postponed events are retried in original order, ahead
                # of the not-yet-processed remainder of the queue
                queue = self.postponed + queue
                self.postponed = []

    def _transition(self, new_state):
        changed = new_state != self.state
        self.state = new_state
        if changed:
            self.state_deadline = None         # cancelled by transition
            if new_state == ON and self.state_timeout is not None:
                self.state_deadline = self.rnd + self.state_timeout
        return changed

    def _handle(self, src, words):
        """Returns (consumed, state_changed)."""
        op = words[0]
        mref, ev, arg = words[1], words[2], words[3]
        if op not in (OP_CALL, OP_EVENT):
            return True, False
        if ev == EV_FLIP:
            changed = self._transition(ON if self.state == OFF else OFF)
            if op == OP_CALL:
                self.forward(src, [OP_REPLY, mref, 0, self.state])
            return True, changed
        if ev == EV_GET:
            if op == OP_CALL:      # keep_state + reply
                self.forward(src, [OP_REPLY, mref, 0,
                                   self.state * 1000 + self.counter])
            return True, False
        if ev == EV_WORK:
            if self.state == OFF:
                return False, False            # postpone in OFF
            self.counter = self.counter * 2 + arg   # order-sensitive op
            if op == OP_CALL:
                self.forward(src, [OP_REPLY, mref, 0, self.counter])
            return True, False
        if ev == EV_ARM_IDLE:
            self.idle_deadline = self.rnd + IDLE_TIMEOUT
            if op == OP_CALL:
                self.forward(src, [OP_REPLY, mref, 0, 0])
            return True, False
        if ev == EV_TICK:
            return True, False     # no-op event (cancels event timeout)
        if op == OP_CALL:
            self.forward(src, [OP_REPLY, mref, 1, 0])
        return True, False


class ClientVM(BridgeVM):
    def __init__(self, srv, sim_id):
        super().__init__(srv, sim_id)
        self._mref = sim_id * 1000
        self.mailbox = []

    def send_call(self, dst, ev, arg=0):
        self._mref += 1
        self.forward(dst, [OP_CALL, self._mref, ev, arg])
        return self._mref

    def event(self, dst, ev, arg=0):
        self.forward(dst, [OP_EVENT, 0, ev, arg])

    def poll(self, mref):
        self.mailbox.extend(self.drain())
        for i, (_src, words) in enumerate(self.mailbox):
            if words[0] == OP_REPLY and words[1] == mref:
                del self.mailbox[i]
                return (words[2] == 0, words[3])
        return None

    def call(self, dst, ev, arg=0, *, machine, timeout_steps=12):
        mref = self.send_call(dst, ev, arg)
        for _ in range(timeout_steps):
            rnd = self.step(1)
            machine.process(rnd)
            got = self.poll(mref)
            if got is not None:
                return got
        return ("timeout", dst)


@pytest.fixture()
def rig():
    """Machine WITHOUT a state timeout (timeout behaviors get their own
    rig below — an always-armed ON timeout would fire mid-test)."""
    srv = bridge_rig(4)
    vms = []
    try:
        a = ClientVM(srv, 0)
        m = StatemVM(srv, 1)
        c = ClientVM(srv, 2)
        vms = [a, m, c]
        yield srv, a, m, c
    finally:
        for vm in vms:
            vm.close()
        srv.close()


@pytest.fixture()
def rig_t():
    """Machine whose ON state arms a state_timeout."""
    srv = bridge_rig(4)
    vms = []
    try:
        a = ClientVM(srv, 0)
        m = StatemVM(srv, 1, state_timeout=STATE_TIMEOUT)
        vms = [a, m]
        yield srv, a, m
    finally:
        for vm in vms:
            vm.close()
        srv.close()


def _settle(a, m, k):
    for _ in range(k):
        m.process(a.step(1))


def test_call_transitions_and_replies_from_new_state(rig):
    _, a, m, _ = rig
    assert a.call(m.id, EV_FLIP, machine=m) == (True, ON)
    assert a.call(m.id, EV_FLIP, machine=m) == (True, OFF)


def test_keep_state_preserves_data(rig):
    _, a, m, _ = rig
    assert a.call(m.id, EV_FLIP, machine=m) == (True, ON)
    assert a.call(m.id, EV_WORK, 3, machine=m) == (True, 3)
    # get is keep_state: two reads, same state and data
    assert a.call(m.id, EV_GET, machine=m) == (True, 1003)
    assert a.call(m.id, EV_GET, machine=m) == (True, 1003)


def test_postponed_events_replay_on_state_change(rig):
    """WORK is postponed in OFF; flipping to ON replays it."""
    _, a, m, _ = rig
    a.event(m.id, EV_WORK, 7)
    _settle(a, m, 3)
    assert a.call(m.id, EV_GET, machine=m) == (True, 0)   # still OFF, idle
    assert a.call(m.id, EV_FLIP, machine=m) == (True, ON)
    _settle(a, m, 2)
    assert a.call(m.id, EV_GET, machine=m) == (True, 1007)


def test_postponed_events_replay_in_arrival_order(rig):
    """counter = counter*2 + arg detects ordering: [2 then 3] -> 7."""
    _, a, m, _ = rig
    a.event(m.id, EV_WORK, 2)
    _settle(a, m, 2)
    a.event(m.id, EV_WORK, 3)
    _settle(a, m, 2)
    assert a.call(m.id, EV_FLIP, machine=m) == (True, ON)
    _settle(a, m, 2)
    assert a.call(m.id, EV_GET, machine=m) == (True, 1007)


def test_postponed_replay_ahead_of_newer_events(rig):
    """A postponed WORK(2) must apply before a WORK(3) that arrives in
    the same pass as the flip (gen_statem: postponed first)."""
    _, a, m, _ = rig
    a.event(m.id, EV_WORK, 2)
    _settle(a, m, 2)                       # WORK(2) postponed in OFF
    a.event(m.id, EV_FLIP)                 # same-round pair: flip …
    a.event(m.id, EV_WORK, 3)              # … then new work
    _settle(a, m, 3)
    assert a.call(m.id, EV_GET, machine=m) == (True, 1007)  # (0*2+2)*2+3


def test_state_timeout_fires_without_events(rig_t):
    _, a, m = rig_t
    assert a.call(m.id, EV_FLIP, machine=m) == (True, ON)
    _settle(a, m, STATE_TIMEOUT + 2)
    assert a.call(m.id, EV_GET, machine=m)[1] // 1000 == OFF


def test_state_timeout_not_cancelled_by_events(rig_t):
    """OTP state_timeout survives event arrival (only a transition
    cancels it): WORK events in ON do not keep it alive."""
    _, a, m = rig_t
    assert a.call(m.id, EV_FLIP, machine=m) == (True, ON)
    for _ in range(3):
        a.event(m.id, EV_WORK, 1)
        _settle(a, m, 2)
    _settle(a, m, STATE_TIMEOUT)
    assert a.call(m.id, EV_GET, machine=m)[1] // 1000 == OFF


def test_state_timeout_cancelled_by_transition(rig_t):
    """Flip ON->OFF before the deadline: no spurious later timeout, and
    a fresh ON arms a FRESH timer."""
    _, a, m = rig_t
    assert a.call(m.id, EV_FLIP, machine=m) == (True, ON)
    assert a.call(m.id, EV_FLIP, machine=m) == (True, OFF)  # cancels
    _settle(a, m, STATE_TIMEOUT + 2)
    assert a.call(m.id, EV_FLIP, machine=m) == (True, ON)   # fresh timer
    _settle(a, m, 2)
    assert a.call(m.id, EV_GET, machine=m)[1] // 1000 == ON


def test_event_timeout_cancelled_by_any_event(rig):
    _, a, m, _ = rig
    assert a.call(m.id, EV_FLIP, machine=m) == (True, ON)
    assert a.call(m.id, EV_ARM_IDLE, machine=m) == (True, 0)
    a.event(m.id, EV_TICK)          # any event cancels the idle timer
    _settle(a, m, IDLE_TIMEOUT + 3)
    assert a.call(m.id, EV_GET, machine=m)[1] // 1000 == ON
    # the GET above was itself an event — idle timer stays cancelled
    _settle(a, m, IDLE_TIMEOUT + 3)
    assert a.call(m.id, EV_GET, machine=m)[1] // 1000 == ON


def test_event_timeout_fires_when_idle():
    srv = bridge_rig(4)
    try:
        a = ClientVM(srv, 0)
        m = StatemVM(srv, 1)       # no state_timeout: isolate idle timer
        assert a.call(m.id, EV_FLIP, machine=m) == (True, ON)
        assert a.call(m.id, EV_ARM_IDLE, machine=m) == (True, 0)
        for _ in range(IDLE_TIMEOUT + 2):
            m.process(a.step(1))   # silence
        assert a.call(m.id, EV_GET, machine=m)[1] // 1000 == OFF
        a.close()
        m.close()
    finally:
        srv.close()


def test_two_clients_refs_pair_across_transition(rig):
    _, a, m, c = rig
    ra = a.send_call(m.id, EV_FLIP)
    rc = c.send_call(m.id, EV_GET)
    got_a = got_c = None
    for _ in range(12):
        m.process(a.step(1))
        got_a = got_a or a.poll(ra)
        got_c = got_c or c.poll(rc)
        if got_a and got_c:
            break
    assert got_a == (True, ON)
    assert got_c is not None and got_c[0] is True
