"""Single source of the persistent-compilation-cache setting for the
CLI tools (the conftest.py / profile_round.py cache dir).

The exporter tools are run as fresh subprocesses by the CLI smokes in
tests/test_tools_cli.py on every tier-1 run; without the persistent
cache each run recompiles the same round programs from scratch
(measured 21.4 s -> 8.6 s for the health+broadcast pair with it).  The
setting rides env-var defaults rather than ``jax.config.update`` so a
tool's ``--help`` fast path never pays a jax import — call before any
jax-importing code runs.
"""

import os


def enable_persistent_cache() -> None:
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/partisan_tpu_jax_cache")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "1.0")
