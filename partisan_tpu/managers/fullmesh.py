"""Full-mesh manager + full membership strategy.

TPU rebuild of the reference default stack:
``partisan_pluggable_peer_service_manager`` (full mesh, SURVEY.md §2) with
``partisan_full_membership_strategy`` (OR-set membership, gossip to all
peers every periodic tick — partisan_full_membership_strategy.erl:101-110).

State is one OR-set view per node (ops/orset.py).  A periodic gossip tick
pushes the node's whole view to every peer it believes is a member and
merges by elementwise max — the reference's CRDT-merge-on-receive
(full_membership_strategy.erl:131-163) batched into one scatter-max.

Timer phasing: each node's periodic timer fires at
``(round + node_id) % gossip_every == 0`` — staggered like the reference's
independently-started wall-clock timers rather than lockstep.

Join/leave mirror partisan_peer_service:join/leave: a joiner learns the
target's spec (out-of-band node_spec, as in service discovery) and both
sides converge via gossip; joins/leaves mark the node "urgent" so it
gossips next round instead of waiting for its periodic tick (the
reference gossips immediately on connect —
partisan_pluggable_peer_service_manager.erl:1557-1570).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import Array

from partisan_tpu import faults as faults_mod
from partisan_tpu.comm import LocalComm
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import msg as msg_ops
from partisan_tpu.ops import orset

_GOSSIP_EDGE_TAG = 101  # fault-hash call-site salt for gossip edges


class FullMeshState(NamedTuple):
    view: Array    # uint32[n_local, 2, n_global] — OR-set views
    urgent: Array  # bool[n_local] — gossip next round regardless of phase


class FullMesh:
    name = "fullmesh"

    def init(self, cfg: Config, comm: LocalComm) -> FullMeshState:
        gids = comm.local_ids()
        add = (jnp.arange(comm.n_global)[None, :] == gids[:, None]).astype(orset.DTYPE)
        rm = jnp.zeros_like(add)
        return FullMeshState(
            view=jnp.stack([add, rm], axis=1),
            urgent=jnp.zeros((comm.n_local,), jnp.bool_),
        )

    def step(self, cfg: Config, comm: LocalComm, state: FullMeshState,
             ctx: RoundCtx) -> tuple[FullMeshState, Array]:
        n_local, _, n_global = state.view.shape
        gids = comm.local_ids()

        # Periodic gossip timer (partisan_full_membership_strategy.erl:101-110).
        phase = gids % cfg.gossip_every
        fires = ((ctx.rnd + phase) % cfg.gossip_every == 0) | state.urgent
        fires = fires & ctx.alive

        member = orset.members(state.view)                      # [n_local, n_global]
        all_ids = jnp.arange(n_global, dtype=jnp.int32)
        peer = member & (all_ids[None, :] != gids[:, None])
        dst = jnp.where(fires[:, None] & peer, all_ids[None, :], jnp.int32(-1))

        dst = faults_mod.filter_edges(
            ctx.faults, gids, dst, ctx.seed, ctx.rnd, _GOSSIP_EDGE_TAG)

        flat = state.view.reshape(n_local, 2 * n_global)
        pushed = comm.push_max(flat, dst).reshape(n_local, 2, n_global)
        merged = orset.merge(state.view, pushed)
        # Crashed nodes are frozen (their gen_server is dead) — including
        # their pending-urgent flag, which survives until they recover.
        view = jnp.where(ctx.alive[:, None, None], merged, state.view)
        urgent = jnp.where(ctx.alive, False, state.urgent)

        emitted = msg_ops.zero_stack(cfg, (n_local, 0))
        return FullMeshState(view=view, urgent=urgent), emitted

    # ---- views -------------------------------------------------------
    def neighbors(self, cfg: Config, state: FullMeshState,
                  comm: LocalComm | None = None) -> Array:
        n_local, _, n_global = state.view.shape
        gids = (comm.local_ids() if comm is not None
                else jnp.arange(n_local, dtype=jnp.int32))
        member = orset.members(state.view)
        all_ids = jnp.arange(n_global, dtype=jnp.int32)
        peer = member & (all_ids[None, :] != gids[:, None])
        return jnp.where(peer, all_ids[None, :], jnp.int32(-1))

    def members(self, cfg: Config, state: FullMeshState,
                comm: LocalComm | None = None) -> Array:
        return orset.members(state.view)

    # ---- scenario scripting (host-side) ------------------------------
    def join(self, cfg: Config, state: FullMeshState, node: int,
             target: int) -> FullMeshState:
        """``node`` joins via ``target`` (partisan_peer_service:join/1).
        The joiner learns the target's current spec (incarnation) and
        gossips urgently; the target learns the joiner when that gossip
        lands (handle_info connected -> strategy join, pluggable :1537)."""
        inc = jnp.maximum(state.view[target, 0, target], 1)
        view = state.view.at[node].set(orset.add(state.view[node], target, inc))
        return FullMeshState(view=view, urgent=state.urgent.at[node].set(True))

    def leave(self, cfg: Config, state: FullMeshState, node: int) -> FullMeshState:
        """Graceful leave: observed-remove own spec + urgent gossip
        (full_membership_strategy.erl:171-210)."""
        view = state.view.at[node].set(orset.remove(state.view[node], node))
        return FullMeshState(view=view, urgent=state.urgent.at[node].set(True))

    def rejoin(self, cfg: Config, state: FullMeshState, node: int,
               target: int) -> FullMeshState:
        """Rejoin after a leave: a fresh incarnation distinguishes the new
        spec from the removed one (partisan_membership_set.erl:23-60
        staleness semantics)."""
        inc = state.view[node, 0, node] + 1
        view = state.view.at[node].set(orset.add(state.view[node], node, inc))
        st = FullMeshState(view=view, urgent=state.urgent)
        return self.join(cfg, st, node, target)
