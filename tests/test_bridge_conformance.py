"""Bridge protocol conformance: recorded `.erl`-side frames replayed
byte-for-byte through the port server.

No Erlang runtime exists in this image (`erl`/`erlc` absent, the
reference vendors only 14 patched OTP modules — not a buildable tree —
and the environment has no network egress to fetch one), so the
north-star live-BEAM run is executed as a PROTOCOL-CONFORMANCE replay
instead (VERDICT round-1 fallback): the frames below are the exact
bytes OTP's ``term_to_binary/1`` + ``{packet,4}`` framing produce for
the requests ``partisan_sim_peer_service_manager.erl`` issues — most
importantly the BEAM's quirk of encoding lists of small integers as
``STRING_EXT`` (tag 107), which a hand-rolled codec that only emits
``LIST_EXT`` would never exercise on its own output.

Two layers:

1. golden REQUEST bytes (BEAM -> bridge): replayed through a real
   subprocess pipe (`python -m partisan_tpu.bridge.server`, the
   ``open_port`` transport) and over a real TCP socket
   (the ``gen_tcp`` transport) — both byte-identical framings;
2. replies must ``binary_to_term``-decode (any valid external encoding
   is legal on the reply path; the BEAM's decoder accepts all of them).
"""

import os
import struct
import subprocess
import sys
from pathlib import Path

from partisan_tpu.bridge import etf
from partisan_tpu.bridge.etf import Atom

# ---------------------------------------------------------------------------
# A BEAM-faithful encoder (OTP 23+ default external encodings): atoms ->
# SMALL_ATOM_UTF8_EXT, 0..255 -> SMALL_INTEGER_EXT, other 32-bit ->
# INTEGER_EXT, tuples -> SMALL_TUPLE_EXT, lists of bytes -> STRING_EXT,
# other lists -> LIST_EXT + NIL, maps -> MAP_EXT.
# ---------------------------------------------------------------------------


def beam_enc(t) -> bytes:
    if isinstance(t, bool):
        return beam_enc(Atom("true" if t else "false"))
    if isinstance(t, Atom):
        b = str(t).encode()
        return bytes([119, len(b)]) + b
    if isinstance(t, int):
        if 0 <= t <= 255:
            return bytes([97, t])
        return bytes([98]) + struct.pack(">i", t)
    if isinstance(t, tuple):
        return bytes([104, len(t)]) + b"".join(beam_enc(x) for x in t)
    if isinstance(t, list):
        if not t:
            return bytes([106])
        if all(isinstance(x, int) and not isinstance(x, bool)
               and 0 <= x <= 255 for x in t) and len(t) < 65536:
            return bytes([107]) + struct.pack(">H", len(t)) + bytes(t)
        return (bytes([108]) + struct.pack(">I", len(t))
                + b"".join(beam_enc(x) for x in t) + bytes([106]))
    if isinstance(t, dict):
        out = bytes([116]) + struct.pack(">I", len(t))
        for k, v in t.items():
            out += beam_enc(k) + beam_enc(v)
        return out
    raise TypeError(t)


def beam_frame(t) -> bytes:
    p = bytes([131]) + beam_enc(t)
    return struct.pack(">I", len(p)) + p


# Golden spot-checks: these hex strings are the full {packet,4} frames a
# BEAM emits for representative bridge requests (hand-assembled from the
# published External Term Format).  If beam_enc drifts, these fail.
GOLDEN = [
    ((1, (Atom("init"), {Atom("n_nodes"): 8, Atom("seed"): 3})),
     "00000025836802610168027704696e6974740000000277076e5f6e6f646573"
     "61087704736565646103"),
    ((2, (Atom("set_self"), 0)),
     "000000138368026102680277087365745f73656c666100"),
    ((3, (Atom("join"), 1, 0)),
     "000000118368026103680377046a6f696e61016100"),
    ((12, (Atom("forward_message"), 0, 5, [42])),
     "00000020836802610c6804770f666f72776172645f6d657373616765610061"
     "056b00012a"),
    ((16, (Atom("inject_partition"), [0], [1, 2, 3, 4, 5, 6, 7])),
     "00000027836802611068037710696e6a6563745f706172746974696f6e6b00"
     "01006b000701020304050607"),
]


def test_golden_frames_match_beam_encoding():
    for term, hexpect in GOLDEN:
        assert beam_frame(term).hex() == hexpect, term


def test_bridge_decoder_reads_beam_frames():
    """Our ETF decoder must read EXACTLY what a BEAM writes — including
    STRING_EXT int lists, which our own encoder never produces."""
    for term, hexpect in GOLDEN:
        raw = bytes.fromhex(hexpect)[4:]      # strip length prefix
        assert etf.decode(raw) == term


# The recorded session: what partisan_sim_peer_service_manager.erl sends
# over its port for a boot + join + forward + fault cycle, in order,
# with the expected reply SHAPE for each.
def _session():
    yield (1, (Atom("init"), {Atom("n_nodes"): 8, Atom("seed"): 3})), \
        (1, Atom("ok"))
    yield (2, (Atom("set_self"), 0)), (2, Atom("ok"))
    for i in range(1, 8):
        yield (2 + i, (Atom("join"), i, 0)), (2 + i, Atom("ok"))
    yield (10, (Atom("step"), 20)), (10, (Atom("ok"), 20))
    yield (11, (Atom("members"), 0)), None      # checked separately
    yield (12, (Atom("forward_message"), 0, 5, [42])), (12, Atom("ok"))
    yield (13, (Atom("step"), 1)), (13, (Atom("ok"), 21))
    yield (14, (Atom("drain"), 5)), None
    yield (15, (Atom("reserve"), 0, 1)), (15, Atom("ok"))
    # complement form: what the .erl module sends ("sever me from all")
    yield (16, (Atom("inject_partition"), [0], [])), (16, Atom("ok"))
    yield (17, (Atom("resolve_partition"),)), (17, Atom("ok"))
    yield (18, (Atom("stats"),)), None
    yield (19, (Atom("stop"),)), (19, Atom("ok"))


def _check_special(seq, reply):
    tag, body = reply
    assert tag == seq
    if seq == 11:     # members
        ok, members = body
        assert ok == Atom("ok") and sorted(members) == list(range(8))
    elif seq == 14:   # drain
        ok, delivered = body
        assert ok == Atom("ok") and len(delivered) == 1
        src, words = delivered[0]
        assert src == 0 and words[0] == 42
    elif seq == 18:   # stats
        ok, stats = body
        assert ok == Atom("ok") and stats[Atom("round")] == 21


def test_replay_recorded_session_over_port_pipe():
    repo_root = str(Path(__file__).resolve().parents[1])
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo_root)
    proc = subprocess.Popen(
        [sys.executable, "-m", "partisan_tpu.bridge.server"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
        cwd=repo_root)
    try:
        for req, expect in _session():
            proc.stdin.write(beam_frame(req))
            proc.stdin.flush()
            reply = etf.read_frame(proc.stdout)
            if expect is not None:
                assert reply == expect, (req, reply)
            else:
                _check_special(req[0], reply)
        proc.wait(timeout=30)
        assert proc.returncode == 0
    finally:
        proc.kill()


def test_replay_recorded_session_over_tcp():
    """Same byte stream over the gen_tcp transport (a raw socket is
    byte-identical to `gen_tcp:connect(..., [{packet,4}, binary])`)."""
    import socket

    from partisan_tpu.bridge.socket_server import BridgeSocketServer

    srv = BridgeSocketServer()
    srv.serve_background()
    try:
        conn = socket.create_connection((srv.host, srv.port))
        for req, expect in _session():
            conn.sendall(beam_frame(req))
            head = b""
            while len(head) < 4:
                head += conn.recv(4 - len(head))
            (n,) = struct.unpack(">I", head)
            buf = b""
            while len(buf) < n:
                buf += conn.recv(n - len(buf))
            reply = etf.decode(buf)
            if expect is not None:
                assert reply == expect, (req, reply)
            else:
                _check_special(req[0], reply)
        conn.close()
    finally:
        srv.close()
