"""Runtime performance observatory CLI (partisan_tpu/perfwatch.py).

Measures where wall-clock actually goes — the runtime complement to
the static cost meter (`lint/cost.py`) — in three modes::

    python tools/perf_report.py --one N            # measured phase table
    python tools/perf_report.py --dispatch N       # dispatch-wall meter
    python tools/perf_report.py --pipeline-probe N # double-buffer probe

``--one`` boots the PLAIN bench-config cluster (`lint.cost.bench_cfg`
— the exact program the cost census prices), captures a
``jax.profiler`` trace of steady-state executions, attributes device
time to the ``round.*`` named_scope phases, and reconciles measured ms
against the census's predicted byte footprint: one ``perf_phase`` JSON
line per census phase (measured_ms / predicted_bytes / eff_bytes_per_s
/ outlier) and a ``perf`` summary with ``keys_match`` — the measured
phase keys are the census keys, so outlier rows are a machine-generated
VMEM-fusion target list (ROADMAP item 1(a)).

``--dispatch`` runs a short chunked soak and decomposes its chunk rows
into in-execution vs dispatch-gap time (``dispatch_wall`` line).
``--pipeline-probe`` measures double-buffered dispatch (chain K
submits, sync once) against the serial submit+sync loop, quantifying
ROADMAP item 1(b)'s claimed headroom (``pipeline_probe`` line).

Flags: ``--chunks C`` (dispatch/probe repetitions, default 6),
``--k K`` (rounds per chunk, default scenarios.K_PROG),
``--pipeline D`` (dispatch mode: run the soak engine's pipelined
dispatch at depth D — overlapped rows land in the decomposition),
``--superstep R`` (fuse R rounds per scan step, ISSUE 18), and
``--superstep-axis`` (sweep R in {1, 4, 8, 16} — one
dispatch_wall/pipeline_probe line per R, the fused-dispatch headroom
curve).  Outlier and dispatch events replay through telemetry
(``partisan.perf.*``).  Works on CPU with the same code paths an
on-chip session uses.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools._lib.jaxcache import enable_persistent_cache

USAGE = ("usage: perf_report.py (--one | --dispatch | --pipeline-probe) N"
         " [--chunks C] [--k K] [--pipeline D] [--superstep R |"
         " --superstep-axis]")


def _boot(n: int, superstep: int = 1):
    import dataclasses

    from partisan_tpu.cluster import Cluster
    from partisan_tpu.lint.cost import bench_cfg
    from partisan_tpu.models.plumtree import Plumtree
    from partisan_tpu.scenarios import _boot_overlay

    cfg = bench_cfg(n)
    if superstep > 1:
        cfg = dataclasses.replace(cfg, superstep=superstep)
    cl = Cluster(cfg, model=Plumtree())
    st = _boot_overlay(cl, n, settle_execs=2)
    return cl, st


def _emit(line: dict, out) -> None:
    print(json.dumps(line), file=out, flush=True)


def phase_table(n: int, *, execs: int = 3, out=None) -> list[dict]:
    """Capture → attribute → reconcile; returns the reconciled rows."""
    from partisan_tpu import perfwatch, telemetry
    from partisan_tpu.lint.cost import bench_round_program, \
        census_program
    from partisan_tpu.scenarios import K_PROG, _sync

    out = out or sys.stdout
    cl, st = _boot(n)
    with tempfile.TemporaryDirectory() as td:
        with perfwatch.capture(td):
            for _ in range(execs):
                st = cl.steps(st, K_PROG)
                _sync(st)
        measured = perfwatch.attribute(td)
    cens = census_program(bench_round_program(n))
    rows = perfwatch.reconcile(measured, cens, rounds=execs * K_PROG)
    for row in rows:
        _emit({"kind": "perf_phase", "n": n, **row}, out)
    meas_keys = {k for k in measured if k.startswith("round.")}
    summary = {
        "kind": "perf", "n": n, "execs": execs, "k": K_PROG,
        "phases": len(cens.phases),
        "measured_ms": round(sum(m["ms"] for m in measured.values()), 4),
        "keys_match": meas_keys <= set(cens.phases),
        "outliers": [r["phase"] for r in rows if r["outlier"]],
    }
    _emit(summary, out)
    bus = telemetry.Bus()
    bus.attach("perf-report", ("partisan", "perf"),
               lambda ev, m, meta: _emit(
                   {"kind": "event", "event": list(ev), **m, **meta},
                   out))
    telemetry.replay_perf_events(bus, phases=rows)
    return rows


def dispatch_meter(n: int, *, chunks: int = 6, k: int | None = None,
                   superstep: int = 1, depth: int = 1,
                   out=None) -> dict:
    """Short chunked soak → chunk rows → dispatch-wall decomposition.
    ``superstep`` fuses R rounds per scan step (the engine's guarded
    cap lift + ladder-of-R sizing engage); ``depth`` >= 2 runs the
    pipelined dispatch so the decomposition shows the overlapped
    regime (busy_s spans, true-stall gaps)."""
    from partisan_tpu import perfwatch, soak as soak_mod, telemetry
    from partisan_tpu.scenarios import K_PROG

    out = out or sys.stdout
    k = k or K_PROG
    cl, st = _boot(n, superstep=superstep)
    warm = [cl]
    engine = soak_mod.Soak(
        make_cluster=lambda: warm.pop() if warm else cl.rebuild(),
        cfg=soak_mod.SoakConfig(chunk_fixed=k,
                                checkpoint_every=chunks * k,
                                pipeline_depth=depth))
    res = engine.run(st, rounds=chunks * k)
    for row in res.chunks:
        _emit({"kind": "chunk", **row}, out)
    disp = perfwatch.decompose_chunks(res.chunks)
    if superstep > 1:
        disp["superstep"] = superstep
    if depth > 1:
        disp["pipeline_depth"] = depth
    _emit({"kind": "dispatch_wall", "n": n, **disp}, out)
    bus = telemetry.Bus()
    bus.attach("perf-report", ("partisan", "perf"),
               lambda ev, m, meta: _emit(
                   {"kind": "event", "event": list(ev), **m, **meta},
                   out))
    telemetry.replay_perf_events(bus, dispatch=disp)
    return disp


def pipeline_probe(n: int, *, reps: int = 6, k: int | None = None,
                   superstep: int = 1, out=None) -> dict:
    """Measured double-buffered-dispatch overlap (ROADMAP item 1(b)).
    With ``superstep=R`` the probed program fuses R rounds per scan
    step — swept over the axis, the line quantifies how much of the
    serial dispatch wall fusion already removed before pipelining."""
    from partisan_tpu import perfwatch
    from partisan_tpu.scenarios import K_PROG, _sync

    out = out or sys.stdout
    k = k or K_PROG
    cl, st = _boot(n, superstep=superstep)
    probe, _ = perfwatch.pipeline_probe(
        lambda s, kk: cl.steps(s, kk), _sync, st, reps=reps, k=k)
    if superstep > 1:
        probe["superstep"] = superstep
    _emit({"kind": "pipeline_probe", "n": n, **probe}, out)
    return probe


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--help" in argv or "-h" in argv or not argv:
        print(USAGE)
        print(__doc__.strip())
        return 0
    enable_persistent_cache()

    def flag_val(name, default):
        if name in argv:
            i = argv.index(name)
            v = int(argv[i + 1])
            del argv[i:i + 2]
            return v
        return default

    chunks = flag_val("--chunks", 6)
    k = flag_val("--k", None)
    depth = flag_val("--pipeline", 1)
    ss_axis = "--superstep-axis" in argv
    if ss_axis:
        argv.remove("--superstep-axis")
    superstep = flag_val("--superstep", 1)
    supersteps = (1, 4, 8, 16) if ss_axis else (superstep,)
    modes = [m for m in ("--one", "--dispatch", "--pipeline-probe")
             if m in argv]
    for m in modes:
        argv.remove(m)
    sizes = [int(a) for a in argv if a.isdigit()]
    n = sizes[0] if sizes else 512
    bogus = [a for a in argv if not a.isdigit()]
    if not modes or bogus:
        print(USAGE, file=sys.stderr)
        return 2
    for m in modes:
        if m == "--one":
            phase_table(n)       # the phase table prices the plain round
            continue
        for ss in supersteps:
            if m == "--dispatch":
                dispatch_meter(n, chunks=chunks, k=k, superstep=ss,
                               depth=depth)
            else:
                pipeline_probe(n, reps=chunks, k=k, superstep=ss)
    return 0


if __name__ == "__main__":
    sys.exit(main())
