"""TWO emulated BEAM VMs on the coded TCP transport.

The `.erl` manager now ships the multi-VM branch as CODE
(partisan_sim_peer_service_manager.erl `connect_bridge/0`, selected by
``{sim_transport, tcp}``): every node gen_tcp-connects to ONE shared
simulator, exactly one (``{sim_primary, true}``) sends ``{init, _}``,
each sets its own id, and each drains its own deliveries.  This suite
drives that exact flow with BYTE-FAITHFUL BEAM frames (the
``term_to_binary`` bytes the Erlang side puts on the socket — the
STRING_EXT small-int-list quirk included) against the real
``socket_server``:

- VM A's ``forward_message`` arrives in VM B's drain (the single-
  simulator multi-node topology the reference gets for free),
- the secondary VM does NOT init (a second init would wipe the shared
  cluster) — its first frames are ``set_self`` only,
- A's join is visible in B's ``members`` (membership diffs reach every
  VM → on_up/on_down),
- the ``is_alive`` probe sees A's crash from B — the liveness signal
  behind ``supports_capability(monitoring) -> true``.
"""

import socket
import struct

import pytest

from partisan_tpu.bridge import etf
from partisan_tpu.bridge.etf import Atom
from partisan_tpu.bridge.socket_server import BridgeSocketServer

from test_bridge_conformance import beam_frame


class TcpVM:
    """One Erlang node's gen_tcp connection, speaking BEAM bytes."""

    def __init__(self, srv, sim_id: int, *, primary: bool,
                 n_nodes: int = 8, seed: int = 13) -> None:
        self.id = sim_id
        self._seq = sim_id * 100
        self.sock = socket.create_connection((srv.host, srv.port))
        if primary:          # {sim_primary, true}: exactly one init
            assert self.rpc((Atom("init"),
                             {Atom("n_nodes"): n_nodes,
                              Atom("seed"): seed})) == etf.OK
        assert self.rpc((Atom("set_self"), sim_id)) == etf.OK

    def rpc(self, term):
        """Sequenced {Seq, Req} -> {Seq, Reply}, BEAM-encoded request
        bytes (the .erl's rpc_port/2 on the tcp branch)."""
        from partisan_tpu.bridge.socket_server import recv_exact

        self._seq += 1
        self.sock.sendall(beam_frame((self._seq, term)))
        (n,) = struct.unpack(">I", recv_exact(self.sock, 4))
        seq, reply = etf.decode(recv_exact(self.sock, n))
        assert seq == self._seq
        return reply

    def close(self):
        self.sock.close()


@pytest.fixture()
def rig():
    srv = BridgeSocketServer()
    srv.serve_background()
    vms = []
    try:
        a = TcpVM(srv, 0, primary=True)
        b = TcpVM(srv, 1, primary=False)     # no init: shared cluster
        vms = [a, b]
        yield a, b
    finally:
        for vm in vms:
            vm.close()
        srv.close()


def test_forward_message_crosses_vms(rig):
    """Node A's forward_message arrives in node B's drain."""
    a, b = rig
    assert a.rpc((Atom("forward_message"), a.id, b.id, [7, 9])) == etf.OK
    ok, _rnd = a.rpc((Atom("step"), 1))
    assert ok == etf.OK
    ok, got = b.rpc((Atom("drain"),))      # argument-less: MY inbox
    assert ok == etf.OK
    assert got == [(a.id, [7, 9] + [0, 0])] or \
        (len(got) == 1 and got[0][0] == a.id and got[0][1][:2] == [7, 9])


def test_drain_is_per_vm(rig):
    """B's deliveries never leak into A's drain (self-id scoping)."""
    a, b = rig
    assert a.rpc((Atom("forward_message"), a.id, b.id, [5])) == etf.OK
    a.rpc((Atom("step"), 1))
    ok, got_a = a.rpc((Atom("drain"),))
    assert ok == etf.OK and got_a == []
    ok, got_b = b.rpc((Atom("drain"),))
    assert ok == etf.OK and len(got_b) == 1


def test_membership_diff_reaches_both_vms(rig):
    """B joins the cluster via A; then node 2's join (issued by A)
    becomes visible in B's member view via membership gossip (the on_up
    path both VMs poll via {members, Me})."""
    a, b = rig
    assert b.rpc((Atom("join"), b.id, a.id)) == etf.OK
    a.rpc((Atom("step"), 8))
    assert a.rpc((Atom("join"), 2, a.id)) == etf.OK
    a.rpc((Atom("step"), 12))
    ok, members_b = b.rpc((Atom("members"), b.id))
    assert ok == etf.OK
    assert 2 in members_b


def test_is_alive_probe_sees_remote_crash(rig):
    """B observes A's crash via {is_alive, A} — the liveness signal
    behind supports_capability(monitoring) -> true."""
    a, b = rig
    ok, alive = b.rpc((Atom("is_alive"), a.id))
    assert ok == etf.OK and alive is True
    assert b.rpc((Atom("crash"), a.id)) == etf.OK
    ok, alive = b.rpc((Atom("is_alive"), a.id))
    assert ok == etf.OK and alive is False


def test_bidirectional_traffic_same_round(rig):
    a, b = rig
    assert a.rpc((Atom("forward_message"), a.id, b.id, [1])) == etf.OK
    assert b.rpc((Atom("forward_message"), b.id, a.id, [2])) == etf.OK
    a.rpc((Atom("step"), 1))
    ok, got_b = b.rpc((Atom("drain"),))
    assert ok == etf.OK and got_b[0][1][0] == 1
    ok, got_a = a.rpc((Atom("drain"),))
    assert ok == etf.OK and got_a[0][1][0] == 2
