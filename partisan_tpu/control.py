"""In-scan feedback controllers: close the loop the observability
planes opened (ROADMAP item 5).

PRs 1/2/4/5 built four device-resident planes that *observe* the
cluster — message counts (metrics.py), delivery ages (latency.py),
overlay topology (health.py), dissemination structure (provenance.py).
This module *acts* on them: three small pure functions of plane state
evaluated inside ``round_body``'s jitted scan, each one the live
version of a self-tuning mechanism the cited papers describe:

- **Plumtree fanout governor** (``Config.control.fanout``).  Plumtree
  (Leitão et al., SRDS'07) is explicitly a self-tuning broadcast: the
  eager set narrows when duplicates prove it redundant and widens when
  GRAFT repair proves it too sparse.  The sim's slot-recycle epochs
  RESET the learned ``pruned`` flags on every fresh broadcast (a new
  root grows its own tree — models/plumtree.py epoch docs), so a
  recycled-slot workload re-floods at full overlay fanout forever.
  The governor retains what the flags cannot: it reads the provenance
  ring's per-round duplicate/gossip counts and GRAFT delivered counter
  and steps a per-(node, tree) eager-link BUDGET between
  ``fanout_min`` and the overlay width.  The budget is applied at push
  time (models/plumtree.py eager push): links beyond it are demoted to
  the lazy I_HAVE path for that push — exactly a pruned link's wire
  behavior, but reversible each round and immune to epoch resets —
  and a GRAFT storm (repair pressure) promotes immediately.

- **Channel backpressure** (``Config.control.backpressure``).
  Partisan's transport permits exactly one drop path: stale sends on
  monotonic channels under receiver backpressure
  (partisan_peer_socket.erl:108-129) — newer state supersedes older,
  so shedding is safe and membership never head-of-line-blocks behind
  bulk (the ATC'19 claim).  This controller generalizes the static
  boolean into feedback: each channel's per-round delivered-age
  high-water mark (the latency plane's signal) integrates into a
  pressure level; pressure lowers the channel's stale-shed AGE
  threshold in the channel-capacity outbox (channels.throttle), so a
  saturated bulk channel sheds its stalest queued records aggressively
  — bounding its delivery p99 — while an unsaturated membership/ack
  channel's threshold stays at infinity.

- **Self-healing escalation** (``Config.control.healing``).  The
  reference repairs its overlay on fixed wall-clock timers (shuffle
  10 s, promotion 5 s, isolation window 40 s).  This controller keys
  those cadences off the health digest instead: while the digest
  reports a degraded overlay (>1 component, isolated nodes, or alive
  nodes below the active_min degree floor) the shuffle/promotion
  intervals and the heartbeat isolation window are divided by
  ``2^heal_boost`` (managers/hyparview.py), escalating probe+rejoin
  rates exactly while partitioned; after ``heal_hold`` consecutive
  healthy snapshots the cadences relax to base.

Shared discipline (the planes' own, ARCHITECTURE.md "Observability"):

- **pure + deterministic** — controller state is a scan carry; every
  decision is a function of (config statics, replicated plane values),
  so runs replay bit-identically and checkpoint/restore mid-storm
  resumes the exact decision sequence (tests/test_soak.py),
- **replicated under sharding** — inputs are already allsum/allmax-
  reduced plane values (parallel/sharded.py replicates every control
  leaf), so all shards step identical controller state,
- **zero cost when off** — a disabled controller's ClusterState
  sub-leaf is ``()`` and no op carries its ``round.control.*``
  named_scope (the lint zero-cost rule audits both, over the extended
  matrix in partisan_tpu/lint/matrix.py),
- **observable** — each controller writes a per-round decision ring
  (shared ``metrics.ring_order`` decode); ``telemetry.
  replay_control_events`` turns ring transitions into
  ``partisan.control.*`` bus events and soak chunk rows carry a
  :func:`poll` summary.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import Array

from partisan_tpu.config import Config

_BIG = jnp.int32(2**30)


class FanoutState(NamedTuple):
    """Plumtree eager-fanout governor (replicated).

    ``R`` = Config.control.ring.  The ``band_*`` leaves are the
    governor's hysteresis BANDS promoted from ControlConfig statics to
    dynamic operands (initialized from the config, so an untouched
    state behaves bit-identically): the fleet runner's population-based
    tuner (fleet.tune) stacks a different band vector per vmapped
    member, evaluating a whole band population in ONE program."""

    eager_cap: Array    # int32 — eager links allowed per (node, tree)
    win_dup: Array      # int32 — duplicates in the current window
    win_gossip: Array   # int32 — gossip deliveries, current window
    win_graft: Array    # int32 — GRAFTs delivered, current window
    adjustments: Array  # int32 — cap changes over the whole run
    rnd: Array          # int32[R] — decision-ring round labels (-1)
    cap: Array          # int32[R] — cap in force after each round
    band_min: Array     # int32 — ControlConfig.fanout_min operand
    band_hi: Array      # int32 — ControlConfig.fanout_hi_pct operand
    band_lo: Array      # int32 — ControlConfig.fanout_lo_pct operand
    band_graft: Array   # int32 — ControlConfig.graft_hi_pct operand


class BackpressureState(NamedTuple):
    """Per-channel shed-pressure integrator (replicated).

    ``C`` = Config.n_channels, ``R`` = Config.control.ring; ``band_*``
    are the age bands as dynamic operands (see FanoutState)."""

    press: Array        # int32[C] — pressure level per channel
    adjustments: Array  # int32 — pressure-level changes, whole run
    rnd: Array          # int32[R]
    press_ring: Array   # int32[R, C] — pressure after each round
    band_age_hi: Array  # int32 — ControlConfig.age_hi operand
    band_age_lo: Array  # int32 — ControlConfig.age_lo operand


class HealingState(NamedTuple):
    """Overlay repair-escalation state (replicated); ``band_*`` are the
    escalation bands as dynamic operands (see FanoutState)."""

    boost: Array        # int32 — cadence right-shift in force (0 = base)
    streak: Array       # int32 — consecutive healthy snapshots
    adjustments: Array  # int32 — boost changes, whole run
    rnd: Array          # int32[R]
    boost_ring: Array   # int32[R] — boost after each round
    band_boost: Array   # int32 — ControlConfig.heal_boost operand
    band_hold: Array    # int32 — ControlConfig.heal_hold operand


class ControlState(NamedTuple):
    """Per-controller sub-states; a disabled controller's leaf is
    ``()`` (empty pytree — zero carry cost, like the planes)."""

    fanout: Any = ()
    backpressure: Any = ()
    healing: Any = ()


def enabled(cfg: Config) -> bool:
    return cfg.control.any


def _overlay_width(cfg: Config) -> int:
    """The eager-cap ceiling: the manager's neighbor-slot width K —
    the widest eager set a node can physically push to."""
    from partisan_tpu import managers as managers_mod

    return max(1, managers_mod.neighbor_width(cfg))


def init(cfg: Config) -> ControlState:
    R = cfg.control.ring
    ring = jnp.full((R,), -1, jnp.int32)
    fan, bp, heal = (), (), ()
    c = cfg.control
    if c.fanout:
        fan = FanoutState(
            eager_cap=jnp.int32(_overlay_width(cfg)),
            win_dup=jnp.int32(0), win_gossip=jnp.int32(0),
            win_graft=jnp.int32(0),
            adjustments=jnp.int32(0),
            rnd=ring, cap=jnp.zeros((R,), jnp.int32),
            band_min=jnp.int32(c.fanout_min),
            band_hi=jnp.int32(c.fanout_hi_pct),
            band_lo=jnp.int32(c.fanout_lo_pct),
            band_graft=jnp.int32(c.graft_hi_pct))
    if c.backpressure:
        C = cfg.n_channels
        bp = BackpressureState(
            press=jnp.zeros((C,), jnp.int32),
            adjustments=jnp.int32(0),
            rnd=ring, press_ring=jnp.zeros((R, C), jnp.int32),
            band_age_hi=jnp.int32(c.age_hi),
            band_age_lo=jnp.int32(c.age_lo))
    if c.healing:
        heal = HealingState(
            boost=jnp.int32(0), streak=jnp.int32(0),
            adjustments=jnp.int32(0),
            rnd=ring, boost_ring=jnp.zeros((R,), jnp.int32),
            band_boost=jnp.int32(c.heal_boost),
            band_hold=jnp.int32(c.heal_hold))
    return ControlState(fanout=fan, backpressure=bp, healing=heal)


# ---------------------------------------------------------------------------
# Operand readers (round_body / managers / models read the ROUND-START
# controller state; the update below writes the next round's)
# ---------------------------------------------------------------------------

def shed_age(cfg: Config, bp: BackpressureState) -> Array:
    """int32[C]: the per-channel stale-shed age threshold the capacity
    outbox applies this round (channels.throttle ``shed_age``).  Zero
    pressure = no shedding (threshold past any real age); each level
    halves the threshold from the carried ``band_age_hi`` operand down
    to a floor of 1 round."""
    floor = jnp.maximum(jnp.int32(1),
                        bp.band_age_hi >> jnp.maximum(bp.press - 1, 0))
    return jnp.where(bp.press > 0, floor, _BIG)


def pressure_signal(cfg: Config, comm, inbox_data, dead: Array,
                    rnd: Array) -> Array:
    """int32[C]: this round's per-channel delivered-age high-water mark
    — the backpressure loop's sensor, reduced (``comm.allmax``) so the
    pressure decision replicates across shards.  Reads the same
    pre-mask inbox and dead mask as ``latency.record_round`` through
    the shared :func:`latency.channel_age_max`, so the signal cannot
    drift from the plane's own high-water accounting."""
    from partisan_tpu import latency as latency_mod
    from partisan_tpu import types as T

    live = inbox_data[..., T.W_KIND] != 0
    delivered = live & ~dead[:, None]
    return comm.allmax(latency_mod.channel_age_max(
        cfg, inbox_data, delivered, rnd))


# ---------------------------------------------------------------------------
# The per-round update (pure; called at the end of round_body on the
# freshly written plane states)
# ---------------------------------------------------------------------------

def _fanout_update(cfg: Config, fs: FanoutState, rnd: Array,
                   pv) -> FanoutState:
    """Step the eager-link budget off the redundancy ring row the
    provenance plane just wrote for ``rnd`` (replicated values).

    The governor accumulates the round's duplicate/gossip/GRAFT counts
    into a window and evaluates once every ``fanout_every`` rounds —
    per-round ratios whipsaw (a dissemination wave's first hop looks
    redundancy-free, its fan-out hop heavily redundant), the window
    averages a wave.  A window whose duplicate fraction reaches
    ``fanout_hi_pct`` demotes one link (down to ``fanout_min``); a
    window at/below ``fanout_lo_pct`` — or one where GRAFT repair
    reaches ``graft_hi_pct`` of gossip (the eager set got too sparse
    and lazy repair is doing the work) — promotes one (up to the
    overlay width).  Windows with fewer than ``fanout_gossip_min``
    gossip deliveries hold the budget (quiet traffic is noise, the
    same stance as telemetry's redundancy_min)."""
    from partisan_tpu.provenance import CTL_NAMES

    c = cfg.control
    slot = jnp.mod(rnd, cfg.provenance_ring)
    w_dup = fs.win_dup + jnp.sum(pv.dup[slot], dtype=jnp.int32)
    w_gos = fs.win_gossip + pv.gossip[slot]
    w_gra = fs.win_graft + pv.ctl[slot, CTL_NAMES.index("graft"), 1]

    # Bands read from the CARRIED operands (fs.band_*, initialized from
    # ControlConfig — fleet.tune stacks a population of them), not the
    # config statics, so a vmapped fleet evaluates W band settings in
    # one program.
    evaluate = jnp.mod(rnd + 1, c.fanout_every) == 0
    measurable = w_gos >= c.fanout_gossip_min
    hot = measurable & (w_dup * 100 >= fs.band_hi * w_gos)
    storm = measurable & (w_gra * 100 >= fs.band_graft * w_gos)
    cold = measurable & (w_dup * 100 <= fs.band_lo * w_gos)
    promote = evaluate & (storm | cold)
    demote = evaluate & hot & ~promote
    cap = jnp.clip(
        fs.eager_cap + promote.astype(jnp.int32)
        - demote.astype(jnp.int32),
        fs.band_min, _overlay_width(cfg))
    stepped = cap != fs.eager_cap
    rslot = jnp.mod(rnd, c.ring)
    zero = jnp.int32(0)
    return fs._replace(
        eager_cap=cap,
        win_dup=jnp.where(evaluate, zero, w_dup),
        win_gossip=jnp.where(evaluate, zero, w_gos),
        win_graft=jnp.where(evaluate, zero, w_gra),
        adjustments=fs.adjustments + stepped.astype(jnp.int32),
        rnd=fs.rnd.at[rslot].set(rnd),
        cap=fs.cap.at[rslot].set(cap))


def _backpressure_update(cfg: Config, bp: BackpressureState, rnd: Array,
                         chmax: Array) -> BackpressureState:
    """Integrate each channel's per-round delivered-age high-water mark
    (``chmax`` int32[C], already allmax-reduced by round_body) into the
    pressure level: at/above ``age_hi`` raises it, at/below ``age_lo``
    decays it — a bounded integrator, so a transient spike sheds for a
    few rounds and a quiet channel relaxes back to no-shed."""
    c = cfg.control
    up = chmax >= bp.band_age_hi
    down = chmax <= bp.band_age_lo
    press = jnp.clip(bp.press + up.astype(jnp.int32)
                     - down.astype(jnp.int32), 0, c.press_max)
    changed = jnp.sum((press != bp.press).astype(jnp.int32))
    rslot = jnp.mod(rnd, c.ring)
    return bp._replace(
        press=press,
        adjustments=bp.adjustments + changed,
        rnd=bp.rnd.at[rslot].set(rnd),
        press_ring=bp.press_ring.at[rslot].set(press))


def _healing_update(cfg: Config, hs: HealingState, rnd: Array,
                    health) -> HealingState:
    """Re-key the escalation off the digest the health plane just
    (possibly) wrote.  Decisions only move on snapshot rounds — the
    digest is fresh exactly then ((rnd+1) % health == 0, the cadence
    round_body's snapshot cond uses) — so ``heal_hold`` counts
    SNAPSHOTS, not rounds; the ring still records every round's boost
    in force."""
    from partisan_tpu import health as health_mod

    c = cfg.control
    due = jnp.mod(rnd + 1, cfg.health) == 0
    word = health.digest
    valid = (word & health_mod.DIGEST_VALID) != 0
    ok_bits = health_mod.OVERLAY_BITS   # the shared graph-health bits
    degraded = valid & ((word & ok_bits) != ok_bits)
    streak_s = jnp.where(degraded, 0, hs.streak + valid.astype(jnp.int32))
    boost_s = jnp.where(
        degraded, hs.band_boost,
        jnp.where(streak_s >= hs.band_hold, jnp.int32(0), hs.boost))
    boost = jnp.where(due, boost_s, hs.boost)
    streak = jnp.where(due, streak_s, hs.streak)
    rslot = jnp.mod(rnd, c.ring)
    return hs._replace(
        boost=boost, streak=streak,
        adjustments=hs.adjustments + (boost != hs.boost).astype(jnp.int32),
        rnd=hs.rnd.at[rslot].set(rnd),
        boost_ring=hs.boost_ring.at[rslot].set(boost))


def update(cfg: Config, cs: ControlState, *, rnd: Array, pv=None,
           health=None, chmax: Array | None = None) -> ControlState:
    """One controller step, at the end of ``round_body`` on the planes'
    freshly written states.  Pure: (replicated inputs) -> (replicated
    controller state); the applied operands (eager cap, shed ages,
    heal boost) are read at the NEXT round's start from the carry —
    one round of actuation delay, the price of staying a scan carry.
    Each controller traces under its own ``round.control.*``
    named_scope (the lint zero-cost key)."""
    fan, bp, heal = cs.fanout, cs.backpressure, cs.healing
    if cfg.control.fanout:
        with jax.named_scope("round.control.fanout"):
            fan = _fanout_update(cfg, fan, rnd, pv)
    if cfg.control.backpressure:
        with jax.named_scope("round.control.backpressure"):
            bp = _backpressure_update(cfg, bp, rnd, chmax)
    if cfg.control.healing:
        with jax.named_scope("round.control.healing"):
            heal = _healing_update(cfg, heal, rnd, health)
    return ControlState(fanout=fan, backpressure=bp, healing=heal)


# ---------------------------------------------------------------------------
# Host-side readers (the planes' snapshot/rows idiom)
# ---------------------------------------------------------------------------

def poll(cs: ControlState) -> dict:
    """Tiny host summary of the controllers' CURRENT operands (a few
    scalar transfers — what soak chunk rows carry).  Scalar leaves of a
    FLEET state (fleet.py) arrive with a leading member axis and are
    reported as per-member lists."""
    from partisan_tpu.metrics import host_int

    out: dict = {}
    if cs.fanout != ():
        out["eager_cap"] = host_int(cs.fanout.eager_cap)
        out["fanout_adjustments"] = host_int(cs.fanout.adjustments)
    if cs.backpressure != ():
        out["press"] = host_int(cs.backpressure.press)
    if cs.healing != ():
        out["heal_boost"] = host_int(cs.healing.boost)
    return out


def snapshot(cs: ControlState) -> dict:
    """Decode the decision rings (one device->host transfer, after the
    scan), ordered by round via the shared ``metrics.ring_order``."""
    import jax as _jax
    import numpy as np

    from partisan_tpu.metrics import ring_order

    host = _jax.device_get(cs)
    out: dict = {}
    if host.fanout != ():
        rnd = np.asarray(host.fanout.rnd)
        idx = ring_order(rnd)
        out["fanout"] = {
            "rounds": rnd[idx],
            "cap": np.asarray(host.fanout.cap)[idx],
            "eager_cap": int(host.fanout.eager_cap),
            "adjustments": int(host.fanout.adjustments),
        }
    if host.backpressure != ():
        rnd = np.asarray(host.backpressure.rnd)
        idx = ring_order(rnd)
        out["backpressure"] = {
            "rounds": rnd[idx],
            "press": np.asarray(host.backpressure.press_ring)[idx],
            "current": np.asarray(host.backpressure.press),
            "adjustments": int(host.backpressure.adjustments),
        }
    if host.healing != ():
        rnd = np.asarray(host.healing.rnd)
        idx = ring_order(rnd)
        out["healing"] = {
            "rounds": rnd[idx],
            "boost": np.asarray(host.healing.boost_ring)[idx],
            "current": int(host.healing.boost),
            "adjustments": int(host.healing.adjustments),
        }
    return out


def decisions(snap: dict, *, channels: tuple[str, ...] | None = None
              ) -> list[dict]:
    """Derive the decision rings' DISCRETE controller moves — the
    single source of truth ``telemetry.replay_control_events`` (and
    through it the opslog journal) emits from.  The rings record the
    operand in force after EVERY round, so a decision is a round where
    it CHANGED.  One self-describing dict per move, round-keyed, in
    ring order:

    - ``fanout_adjusted`` — the plumtree eager-link budget stepped,
    - ``shed_threshold_changed`` — a channel's backpressure level
      moved (the channel name in the row),
    - ``healing_escalated`` — the overlay repair boost changed
      (escalations and relaxations both; ``direction`` tags which).
    """
    import numpy as np

    out: list[dict] = []
    fan = snap.get("fanout")
    if fan is not None:
        rounds = np.asarray(fan["rounds"])
        cap = np.asarray(fan["cap"])
        for i in range(1, len(rounds)):
            if cap[i] != cap[i - 1]:
                out.append({"kind": "fanout_adjusted",
                            "round": int(rounds[i]),
                            "cap": int(cap[i]), "prev": int(cap[i - 1])})
    bp = snap.get("backpressure")
    if bp is not None:
        rounds = np.asarray(bp["rounds"])
        press = np.asarray(bp["press"])
        C = press.shape[1] if press.ndim == 2 else 0
        # index-padded: a caller-supplied tuple shorter than the ring's
        # channel axis falls back to ch{i} instead of IndexError
        given = tuple(channels) if channels is not None else ()
        names = tuple(given[i] if i < len(given) else f"ch{i}"
                      for i in range(C))
        for i in range(1, len(rounds)):
            for c in range(C):
                if press[i, c] != press[i - 1, c]:
                    out.append({"kind": "shed_threshold_changed",
                                "round": int(rounds[i]),
                                "channel": names[c],
                                "press": int(press[i, c]),
                                "prev": int(press[i - 1, c])})
    heal = snap.get("healing")
    if heal is not None:
        rounds = np.asarray(heal["rounds"])
        boost = np.asarray(heal["boost"])
        for i in range(1, len(rounds)):
            if boost[i] != boost[i - 1]:
                out.append({"kind": "healing_escalated",
                            "round": int(rounds[i]),
                            "boost": int(boost[i]),
                            "prev": int(boost[i - 1]),
                            "direction": "escalate"
                            if boost[i] > boost[i - 1] else "relax"})
    return out
