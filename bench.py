"""Benchmark: simulated gossip rounds/sec (north-star metric, BASELINE.md).

Runs driver config #1 — full-mesh + full membership strategy +
demers_anti_entropy — sized up to 256 nodes, and measures how many whole
cluster rounds per second the jitted simulator steps on one chip.

``vs_baseline``: the reference is a LIVE system whose gossip timers tick
in wall-clock seconds — one simulated round == ``round_ms`` (1 s) of
virtual time.  A live Partisan cluster therefore advances 1 round/sec by
construction; ``vs_baseline`` is the simulation speedup over that
real-time baseline (rounds-per-sec / 1).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import jax
import jax.numpy as jnp


def main() -> None:
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config
    from partisan_tpu.models.anti_entropy import AntiEntropy

    n = 256
    cfg = Config(n_nodes=n, seed=1)
    model = AntiEntropy()
    cl = Cluster(cfg, model=model)
    st = cl.init()
    for i in range(1, n):
        st = st._replace(manager=cl.manager.join(cfg, st.manager, i, 0))
    st = st._replace(model=model.broadcast(st.model, 0, 0))

    k = 100
    st = cl.steps(st, k)               # warmup + compile
    jax.block_until_ready(st)
    assert float(model.coverage(st.model, st.faults.alive, 0)) == 1.0, (
        "anti-entropy broadcast failed to converge during warmup")

    reps = 3
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        st = cl.steps(st, k)
        jax.block_until_ready(st)
        best = min(best, time.perf_counter() - t0)

    rps = k / best
    print(json.dumps({
        "metric": f"simulated gossip rounds/sec ({n}-node full-mesh + anti-entropy)",
        "value": round(rps, 1),
        "unit": "rounds/sec",
        "vs_baseline": round(rps, 1),   # live system: 1 round == 1 s wall
    }))


if __name__ == "__main__":
    main()
