"""Distance/RTT metrics plane.

Reference: the pluggable manager schedules pings on the ``distance``
timer (partisan_pluggable_peer_service_manager.erl:1355-1378) and folds
each pong's microsecond diff into a per-peer distance map (:1716-1737);
HyParView's X-BOT uses live RTT comparisons as its optimization oracle
(partisan_hyparview_peer_service_manager.erl:2978-3000).

Sim transposition: RTTs are MEASURED through a modeled link geometry —

1. on the ``distance_interval_ms`` cadence (Config.distance_every) a
   node emits ``PING`` (payload: send round) to its probe targets,
2. the responder holds the ``PONG`` for the edge's modeled round trip
   (``2 x latency_rounds``) in a pending buffer, then sends it with the
   echoed send round,
3. the prober records ``receive_round - send_round`` into a
   direct-mapped per-peer RTT cache.

The pong rides the real message plane: it crosses the fault stage, a
crashed responder never sends it, and an omitted pong simply leaves the
cache stale — measurement, not an analytic echo of the model (the
PERF_ECHO lesson).  Consumers: :func:`telemetry.distance_metrics`
surfaces the cache host-side; HyParView's X-BOT consults it when
``DistanceConfig.xbot_oracle`` is set (managers/hyparview.py).

Two embeddings share this code: HyParView carries a
:class:`DistanceState` inside its manager state (the reference keeps
distance state in the manager), and :class:`DistanceService` is a
stackable model for any other manager (fullmesh/static/client-server),
probing the overlay's ``neighbors``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import Array

from partisan_tpu import types as T
from partisan_tpu.comm import LocalComm
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import msg as msg_ops
from partisan_tpu.ops import plane as plane_ops

_TAG_LAT = 351          # hash-model latency salt
_TAG_PROBE = 352        # DistanceService neighbor sampling


def latency_rounds(cfg: Config, a: Array, b: Array) -> Array:
    """Modeled ONE-WAY latency of edge (a, b) in whole rounds, in
    [0, max_latency_rounds].  Symmetric and stable across rounds.

    - ``ring``: distance on the node-id circle, scaled so antipodal
      pairs hit the ceiling — a real geometry an overlay optimizer can
      converge toward.
    - ``hash``: per-edge uniform hash — matches the spirit of X-BOT's
      synthetic oracle (managers/hyparview.py link_cost).
    """
    from partisan_tpu import faults as faults_mod

    d = cfg.distance
    a = jnp.asarray(a, jnp.int32)
    b = jnp.asarray(b, jnp.int32)
    if d.model == "ring":
        n = cfg.n_nodes
        diff = jnp.abs(a - b)
        ring = jnp.minimum(diff, n - diff)
        # antipodal distance n//2 maps to max_latency_rounds
        return (ring * d.max_latency_rounds * 2 + n // 2) // max(n, 1)
    lo, hi = jnp.minimum(a, b), jnp.maximum(a, b)
    h = faults_mod.edge_hash(cfg.seed, jnp.int32(0), _TAG_LAT, lo, hi)
    return (h % jnp.uint32(d.max_latency_rounds + 1)).astype(jnp.int32)


def modeled_rtt(cfg: Config, a: Array, b: Array) -> Array:
    """The RTT a measurement of edge (a, b) would find: two modeled
    one-way hops plus the two scheduling rounds every exchange costs.

    A lat-0 edge still pays one pong-buffer round: the responder's
    release pass runs BEFORE the same round's scheduling pass (see
    :func:`step`), so a pong due immediately cannot depart until the
    next round — the measured floor is 3, not 2."""
    return jnp.maximum(2 * latency_rounds(cfg, a, b), 1) + 2


class DistanceState(NamedTuple):
    pong_tgt: Array   # int32[n_local, B] — pending pong destination (-1)
    pong_due: Array   # int32[n_local, B] — release round
    pong_echo: Array  # int32[n_local, B] — echoed ping send round
    rtt_node: Array   # int32[n_local, K] — cache key: peer id (-1 empty)
    rtt_val: Array    # int32[n_local, K] — measured RTT in rounds


def init(cfg: Config, comm: LocalComm) -> DistanceState:
    n = comm.n_local
    d = cfg.distance
    return DistanceState(
        pong_tgt=jnp.full((n, d.pong_buf), -1, jnp.int32),
        pong_due=jnp.zeros((n, d.pong_buf), jnp.int32),
        pong_echo=jnp.zeros((n, d.pong_buf), jnp.int32),
        rtt_node=jnp.full((n, d.cache), -1, jnp.int32),
        rtt_val=jnp.zeros((n, d.cache), jnp.int32),
    )


def step(cfg: Config, comm: LocalComm, st: DistanceState, ctx: RoundCtx,
         targets: Array) -> tuple[DistanceState, Array]:
    """One round of the metrics plane.  ``targets`` int32[n_local, P]
    are the peers to probe when this node's distance tick fires (-1
    pads).  Returns (state', emitted)."""
    d = cfg.distance
    n, B = st.pong_tgt.shape
    K = st.rtt_node.shape[1]
    gids = comm.local_ids()
    inb = ctx.inbox.data
    kind = inb[..., T.W_KIND]
    src = inb[..., T.W_SRC]
    echo = inb[..., T.P0]
    rows = jnp.arange(n, dtype=jnp.int32)

    # ---- 1. release due pongs (round-start buffers) -------------------
    # Release BEFORE scheduling this round's arrivals, so a mature pong
    # departs before a re-ping could claim its slot.
    ripe = (st.pong_tgt >= 0) & (st.pong_due <= ctx.rnd) \
        & ctx.alive[:, None]
    pongs = msg_ops.build(
        cfg, T.MsgKind.PONG, gids[:, None],
        jnp.where(ripe, st.pong_tgt, -1), payload=(st.pong_echo,))
    pong_tgt = jnp.where(ripe, -1, st.pong_tgt)

    # ---- 2. inbound PING -> schedule a delayed PONG -------------------
    # Pending-pong slots are direct-mapped by pinger id.  A slot still
    # holding an immature pong is NOT overwritten (a faster re-ping
    # cadence than the edge's modeled RTT must not keep pushing the
    # deadline out — the pending measurement completes, the re-ping is
    # dropped and the pinger simply probes again next tick).
    is_ping = (kind == T.MsgKind.PING) & ctx.alive[:, None]
    cap = inb.shape[1]
    r2 = jnp.broadcast_to(rows[:, None], (n, cap))
    slot_free = jnp.take_along_axis(
        pong_tgt, jnp.where(is_ping, src % B, 0), axis=1) < 0
    cand = is_ping & slot_free
    # Same-round PINGs colliding on one slot: the three field scatters
    # below are independent, and XLA's duplicate-update order is
    # unspecified PER scatter — a surviving slot could mix tgt from one
    # ping with echo from another.  Resolve before scattering: only the
    # first (lowest inbox index) ping per slot per row wins.
    s_cand = jnp.where(cand, src % B, -1)
    earlier = jnp.tril(jnp.ones((cap, cap), bool), k=-1)
    dup = ((s_cand[:, :, None] == s_cand[:, None, :])
           & (s_cand[:, :, None] >= 0) & earlier[None]).any(-1)
    take = cand & ~dup
    slot = jnp.where(take, src % B, B)                 # B = discard
    hold = ctx.rnd + 2 * latency_rounds(
        cfg, jnp.broadcast_to(gids[:, None], src.shape), src)
    pong_tgt = pong_tgt.at[r2, slot].set(
        jnp.where(take, src, -1), mode="drop")
    pong_due = st.pong_due.at[r2, slot].set(hold, mode="drop")
    pong_echo = st.pong_echo.at[r2, slot].set(echo, mode="drop")

    # ---- 3. inbound PONG -> cache the measured RTT --------------------
    is_pong = (kind == T.MsgKind.PONG) & ctx.alive[:, None]
    rtt = ctx.rnd - echo
    cidx = jnp.where(is_pong, src % K, K)
    rtt_node = st.rtt_node.at[r2, cidx].set(
        jnp.where(is_pong, src, -1), mode="drop")
    rtt_val = st.rtt_val.at[r2, cidx].set(rtt, mode="drop")

    # ---- 4. distance tick: emit pings ---------------------------------
    fire = ((ctx.rnd + gids) % cfg.distance_every == 0) & ctx.alive
    ping_dst = jnp.where(fire[:, None] & (targets >= 0)
                         & (targets != gids[:, None]), targets, -1)
    pings = msg_ops.build(
        cfg, T.MsgKind.PING, gids[:, None], ping_dst,
        payload=(jnp.broadcast_to(ctx.rnd, ping_dst.shape),))

    emitted = plane_ops.concat([pongs, pings], axis=1)
    return DistanceState(pong_tgt=pong_tgt, pong_due=pong_due,
                         pong_echo=pong_echo, rtt_node=rtt_node,
                         rtt_val=rtt_val), emitted


def lookup_rows(st: DistanceState, peers: Array) -> tuple[Array, Array]:
    """Row-aligned cache lookup: ``peers`` int32[n_local, X] ->
    (rtt int32[n_local, X], hit bool[n_local, X])."""
    K = st.rtt_node.shape[1]
    idx = jnp.where(peers >= 0, peers % K, 0)
    node_at = jnp.take_along_axis(st.rtt_node, idx, axis=1)
    val_at = jnp.take_along_axis(st.rtt_val, idx, axis=1)
    hit = (peers >= 0) & (node_at == peers)
    return jnp.where(hit, val_at, 0), hit


def measured_or_modeled(cfg: Config, st: DistanceState, me: Array,
                        peers: Array) -> Array:
    """X-BOT oracle cost: the measured RTT where cached, else the
    modeled expectation (what a measurement of that edge would find —
    the reference's is_better pings on demand, :2978-3000; the sim
    substitutes the model it would measure).  float32, row-aligned."""
    val, hit = lookup_rows(st, peers)
    fb = modeled_rtt(cfg, me, jnp.maximum(peers, 0))
    return jnp.where(hit, val, fb).astype(jnp.float32)


class DistanceService:
    """Stackable model embedding the metrics plane over any manager's
    overlay (the pluggable-manager distance plane analogue): probes up
    to ``probe_k`` of the round's ``neighbors``."""

    name = "distance"

    def __init__(self, probe_k: int = 8) -> None:
        self.probe_k = probe_k

    def init(self, cfg: Config, comm: LocalComm) -> DistanceState:
        return init(cfg, comm)

    def step(self, cfg: Config, comm: LocalComm, st: DistanceState,
             ctx: RoundCtx, nbrs: Array) -> tuple[DistanceState, Array]:
        from partisan_tpu.ops import rng

        if nbrs.shape[1] <= self.probe_k:
            targets = nbrs
        else:
            # uniform sample of probe_k live neighbor slots (a fullmesh
            # neighbor row is id-positional — a head slice would only
            # ever probe the lowest ids)
            gids = comm.local_ids()
            r = rng.rank32(ctx.seed, ctx.rnd, _TAG_PROBE, gids[:, None],
                           jnp.arange(nbrs.shape[1])[None, :])
            sc = jnp.where(nbrs >= 0, r | jnp.uint32(1), jnp.uint32(0))
            v, top = jax.lax.top_k(sc, self.probe_k)
            targets = jnp.where(v > 0,
                                jnp.take_along_axis(nbrs, top, axis=1), -1)
        return step(cfg, comm, st, ctx, targets)
