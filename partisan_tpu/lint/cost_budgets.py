"""Pinned round-cost budgets: the op-count ratchet.

One entry per audited matrix program the repo treats as a hot path
(matrix.py names).  The ``round-cost-budget`` rule (rules.py) censuses
each with the round-cost meter (cost.py) and fails tier-1 when a value
regresses past its pin — the same loud-failure discipline as the
interleave budget — or when the program got CHEAPER than the slack band
below the pin (a stale budget: an improvement landed unpinned, so the
next regression up to the old pin would land silently).

Re-pin protocol (mirrors waivers.py): when a finding fires, reproduce
with ``python tools/profile_phases.py --cost --budgets``, decide whether
the delta is intended, and update the numbers here IN THE SAME CHANGE
with a justification in the commit.  Budgets are measured at the matrix
configs' n=32 — gather/scatter and equation counts are n-independent,
and intermediate bytes scale ~linearly in n, so a 32-node pin gates the
32k round's shape too (BENCH_NOTES round-7 records the 32k absolutes).

History: pinned at PR 11's gather-coalesced round — 59 gather/scatter
eqns in the plain 32k round vs 102 at PR 10 (-42%), 1716.5 MiB vs
2472.8 MiB materialized [n, ., .] intermediates (-31%).  Re-pinned at
ISSUE 18's outlier-driven phase fusion (rank32 XOR-reassociation +
single-pass murmur mix, integer-threshold fault draws, packed plumtree
flag fold, dead fast-wire column skip): 1402.0 MiB in the plain 32k
round vs 1716.5 (-18.3%), every matrix entry's bytes/eqns down in
lockstep, gather/scatter counts unchanged.
"""

from __future__ import annotations

# Below these fractions of the pin, a budget is STALE (improvement
# landed unpinned).  gather/scatter counts are pinned exactly.
STALE_EQN_FRACTION = 0.97
STALE_BYTE_FRACTION = 0.90

# The 1M-node per-device memory budget (ISSUE 13): the sharded round's
# carry-state residency on an 8-way mesh, censused abstractly by
# lint/cost.device_memory_census over the dry_run_cfg shape (bench
# capacities + health plane + a2a exchange).  Measured 159.2 MiB/device
# at pin time; the pin carries ~10% headroom so benign leaf additions
# don't trip it, while an O(n) replicated-matrix regression (the class
# the replicated-node-axis rule guards) blows straight through.
# Re-measure with `python bench.py --dry-1m`; re-pin here WITH the
# change that moves it.  tests/test_sharded_health.py gates it tier-1.
DRY_1M: dict = {
    "n": 1_000_000,
    "devices": 8,
    "state_mib_per_device": 176.0,
}

# The superstep cap-lift admission budget (ISSUE 18): soak's sizer may
# stretch one execution past chunk_cap rounds (to chunk_cap * R under
# Config.superstep=R) ONLY when the round program's materialized-
# intermediate census at the cluster's requested n clears this
# per-device pin — a longer execution holds its dispatch open past the
# envelope chunk_cap was measured under, so admission is justified by
# measured headroom, never assumed.  2048 MiB admits the plain 32k
# bench round (1402.0 MiB at the round-8 fusion, BENCH_NOTES) with
# ~45% headroom while refusing ~100k+ rounds whose per-round
# intermediates alone approach device HBM.  Soak._superstep_guard
# evaluates it abstractly (no compile); tests/test_superstep.py gates
# both verdict directions.
SUPERSTEP_INTERM_BUDGET_MIB = 2048.0

BUDGETS: dict = {
    # The plain bench round (hyparview+plumtree, planes off) — the hot
    # path every BENCH_r0x prices.
    "round/planes-off": {
        "gather_scatter": 56,
        "interm_kib": 1556.1,
        "eqns": 3173,
    },
    # Every observability plane + the width operand — the bench/soak
    # shape with full accounting on.  Re-pinned at ISSUE 13's
    # segment-local health plane: +3 gather/scatter, +32 eqns — the
    # halo-exchange FastSV's per-iteration label gather/slice and the
    # slot-column symmetry loop, traded for never materializing the
    # [n, cap] gathered graph (the 1M enabler; still n-independent
    # counts, so the 32-node pin gates every scale).
    # (re-pinned +2 eqns at ISSUE 15: the drop-cause taxonomy grew the
    # ingress_shed row — a structurally-zero constant in this config's
    # drops stack, priced at one broadcast + one add.)
    "round/all-planes+width": {
        "gather_scatter": 114,
        "interm_kib": 1984.4,
        "eqns": 4104,
    },
    # The open-loop traffic generator over the plain round (PR 12):
    # +2 gather/scatter (the burst-slot arrival draw's emission build)
    # and ~60 KiB of per-round arrival intermediates over the
    # planes-off pin — the whole price of the traffic plane when ON;
    # OFF is bit-identical to "round/planes-off" (zero-cost rule).
    "round/traffic": {
        "gather_scatter": 58,
        "interm_kib": 1614.0,
        "eqns": 3320,
    },
    # The elastic round (ISSUE 15): width operand + the in-scan drain
    # gauge/resize ring + the traffic generator with drain
    # redirection.  Over "round/traffic": +3 scatters (the resize
    # ring's conditional rnd/width/from writes) and ~47 eqns (deadline
    # compare, transition detect, the redirected source mask) — the
    # whole price of runtime elasticity when ON; OFF is bit-identical
    # to the planes-off round (zero-cost rule).
    "round/elastic": {
        "gather_scatter": 61,
        "interm_kib": 1614.2,
        "eqns": 3367,
    },
    # The ingress-armed round (ISSUE 15): staged-request release over
    # the plain round — ZERO extra gathers/scatters (the inject buffer
    # reads/writes are full-tensor wheres; the emission block joins
    # the existing assembly concat) and ~67 eqns of due/stale masking
    # + per-channel shed fold.  The scan entry ("scan/ingress")
    # audits the chunked shape the soak engine dispatches.
    "round/ingress": {
        "gather_scatter": 56,
        "interm_kib": 1586.0,
        "eqns": 3240,
    },
    # The watchdog-armed round (ISSUE 20): metrics + the in-scan
    # invariant plane.  Over the metrics-only round (gs 70 / 1625.5
    # KiB / 3349 eqns at this pin): +2 scatters (the violation-word
    # ring's slot write and its round-label write) and ~43 eqns of
    # bit packing, latch min-fold, and trip accumulation — ZERO
    # intermediate-byte growth, the plane is scalar words plus an
    # int32[ring] buffer.  OFF is bit-identical to the metrics round
    # (zero-cost rule keys on round.watchdog).
    "round/watchdog": {
        "gather_scatter": 72,
        "interm_kib": 1625.5,
        "eqns": 3392,
    },
    # The vmapped fleet round (ISSUE 14): W=4 members of the plain
    # hyparview+plumtree round batched by fleet.Fleet.  The
    # gather/scatter and eqn counts are the ratchet here — they must
    # stay ~one member round (+2 gs for the salt-batched fault hash's
    # batched gathers), NEVER O(W): a per-member Python branch sneaking
    # in would multiply them by the fleet width.  The byte census keys
    # materialized intermediates on a LEADING node axis, so batched
    # [W, n, ·] tensors are deliberately under-counted — bytes are
    # pinned for drift detection only.
    "fleet/round": {
        "gather_scatter": 58,
        "interm_kib": 19.0,
        "eqns": 5019,
    },
}
