"""Delivery-semantics tests — sim analogues of the reference suite's
`with_ack` and `with_causal_labels`/`with_causal_send` groups
(partisan_SUITE.erl:214-315): acked messages survive lossy links via
retransmission, and causal-lane messages are delivered exactly once, in
causal order, buffering out-of-order arrivals."""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from partisan_tpu import faults as faults_mod
from partisan_tpu import types as T
from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config
from partisan_tpu.models.direct_mail import DirectMail
from partisan_tpu.models.p2p_chat import P2PChat
from partisan_tpu.ops import msg as msg_ops
from partisan_tpu.parallel import ShardedCluster, make_mesh

from support import boot_fullmesh


# ---------------------------------------------------------------------------
# Acked delivery (partisan_acknowledgement_backend.erl)
# ---------------------------------------------------------------------------

def test_direct_mail_loses_under_drops_acked_does_not():
    """The unacked protocol misses receivers on a lossy link; the acked
    variant converges because un-acked sends retransmit."""
    def run(acked):
        cfg = Config(n_nodes=16, seed=21, ack_cap=16 if acked else 0)
        model = DirectMail(acked=acked)
        cl = Cluster(cfg, model=model)
        st = boot_fullmesh(cl)
        st = st._replace(
            faults=st.faults._replace(link_drop=jnp.float32(0.5)),
            model=model.broadcast(st.model, node=3, slot=0))
        st = cl.steps(st, 40)
        # Heal the link before measuring the acked drain below.
        st = st._replace(faults=st.faults._replace(link_drop=jnp.float32(0.0)))
        st = cl.steps(st, 10)
        return cl, model, st

    _, m0, st0 = run(acked=False)
    cov0 = float(m0.coverage(st0.model, st0.faults.alive, 0))
    assert cov0 < 1.0, "50% drop shouldn't yield full one-shot coverage"

    cl1, m1, st1 = run(acked=True)
    cov1 = float(m1.coverage(st1.model, st1.faults.alive, 0))
    assert cov1 == 1.0, f"acked coverage {cov1}"
    # All acks arrived: the outstanding store drains empty.
    out_kinds = np.asarray(st1.delivery.ack.outstanding[..., T.W_KIND])
    assert (out_kinds == 0).all(), "outstanding store never drained"


def test_ack_clock_uniqueness_and_overflow_counting():
    cfg = Config(n_nodes=8, seed=5, ack_cap=4)
    model = DirectMail(acked=True)
    cl = Cluster(cfg, model=model)
    st = boot_fullmesh(cl)
    # Queue more pending broadcasts than the store can hold at once
    # (7 neighbors per mail > ack_cap=4): overflow must be counted.
    m = st.model
    for s in range(3):
        m = model.broadcast(m, node=2, slot=s)
    st = st._replace(model=m)
    st = cl.steps(st, 30)
    assert int(st.delivery.ack.overflow) > 0
    for s in range(3):
        cov = float(model.coverage(st.model, st.faults.alive, s))
        assert cov == 1.0, f"slot {s} coverage {cov}"


# ---------------------------------------------------------------------------
# Causal delivery (partisan_causality_backend.erl)
# ---------------------------------------------------------------------------

class ChatState(NamedTuple):
    log: Array       # int32[n, L] — delivered (sender*1000 + seq), in order
    log_len: Array   # int32[n]
    seq: Array       # int32[n] — next seq for my own sends
    send_at: Array   # int32[n, R] — rounds at which I send (-1 pad)


class CausalChat:
    """Test workload: scripted causal sends to every node; receivers log
    delivery order.  A node's send is causally after everything it has
    delivered, so logs must respect the happened-before order."""

    name = "causal_chat"
    LOG = 32
    SLOTS = 8

    def init(self, cfg: Config, comm) -> ChatState:
        n = comm.n_local
        return ChatState(
            log=jnp.zeros((n, self.LOG), jnp.int32),
            log_len=jnp.zeros((n,), jnp.int32),
            seq=jnp.ones((n,), jnp.int32),
            send_at=jnp.full((n, self.SLOTS), -1, jnp.int32),
        )

    def step(self, cfg: Config, comm, state: ChatState, ctx, nbrs):
        gids = comm.local_ids()
        n = state.log.shape[0]

        # Log arrived causal APP messages in inbox order (the delivery
        # layer already enforced causal order).
        inb = ctx.inbox.data
        is_chat = (inb[..., T.W_KIND] == T.MsgKind.APP) & \
                  (inb[..., T.W_FLAGS] & T.F_CAUSAL != 0)
        tok = jnp.where(is_chat,
                        inb[..., T.W_SRC] * 1000 + inb[..., T.P0], 0)
        rank = jnp.cumsum(is_chat, axis=1) - 1
        slot = jnp.where(is_chat, state.log_len[:, None] + rank, self.LOG)
        rows = jnp.broadcast_to(jnp.arange(n)[:, None], slot.shape)
        log = state.log.at[rows, slot].set(tok, mode="drop")
        log_len = state.log_len + is_chat.sum(axis=1, dtype=jnp.int32)

        # Scripted sends: ONE causal record per logical broadcast (the
        # delivery layer fans it to every node).
        fire = (state.send_at == ctx.rnd).any(axis=1) & ctx.alive
        dst = jnp.where(fire, gids, -1)
        emitted = msg_ops.build(
            cfg.msg_words, T.MsgKind.APP, gids[:, None], dst[:, None],
            flags=T.F_CAUSAL, payload=(state.seq[:, None],))
        seq = state.seq + fire.astype(jnp.int32)
        return ChatState(log=log, log_len=log_len, seq=seq,
                         send_at=state.send_at), emitted

    def schedule(self, state: ChatState, node: int, rnd: int) -> ChatState:
        row = state.send_at[node]
        free = int(np.argmax(np.asarray(row) < 0))
        return state._replace(send_at=state.send_at.at[node, free].set(rnd))


def chat_config(n, seed, n_actors=None, **kw):
    return Config(n_nodes=n, seed=seed, causal_labels=("chat",),
                  n_actors=n_actors if n_actors is not None else n, **kw)


def _logs(st):
    logs = np.asarray(st.model.log)
    lens = np.asarray(st.model.log_len)
    return [list(logs[i, :lens[i]]) for i in range(logs.shape[0])]


def test_causal_fifo_per_sender():
    """Messages from one sender arrive at every node in send order."""
    cfg = chat_config(8, seed=31)
    model = CausalChat()
    cl = Cluster(cfg, model=model)
    st = boot_fullmesh(cl)
    m = st.model
    for rnd in (20, 22, 24):
        m = model.schedule(m, node=0, rnd=rnd)
    st = st._replace(model=m)
    st = cl.steps(st, 40)
    for i, log in enumerate(_logs(st)):
        mine = [t % 1000 for t in log if t // 1000 == 0]
        if i != 0:
            assert mine == [1, 2, 3], f"node {i} saw {mine}"


def test_causal_order_across_senders_with_loss():
    """B sends after delivering A's message; even when A->C drops A's
    original send, C must buffer B's message and deliver A's (recovered
    by history replay) FIRST — the reference's buffer-until-deps-met
    behavior (causality_backend.erl:204-220)."""
    cfg = chat_config(8, seed=13)
    model = CausalChat()
    cl = Cluster(cfg, model=model)
    st = boot_fullmesh(cl)

    # Partition A(0) -> C(2) while A broadcasts; B(1) hears A, then
    # sends its own (causally-later) message; C hears B first.
    st = st._replace(faults=faults_mod.inject_partition(
        st.faults, [0], [2]))
    m = model.schedule(st.model, node=0, rnd=int(st.rnd) + 1)
    st = st._replace(model=m)
    st = cl.steps(st, 3)
    b_log = _logs(st)[1]
    assert 1 in [t % 1000 for t in b_log if t // 1000 == 0], \
        "B never heard A (test setup)"
    assert not _logs(st)[2], "C heard A through the partition"
    m = model.schedule(st.model, node=1, rnd=int(st.rnd) + 1)
    st = st._replace(model=m)
    st = cl.steps(st, 3)
    # B's message reached C but must stay buffered (dep on A:1 unmet).
    assert not _logs(st)[2], f"C delivered out of order: {_logs(st)[2]}"
    # Heal; A's history replay re-delivers A:1, unblocking B:1.
    st = st._replace(faults=faults_mod.resolve_partition(st.faults))
    st = cl.steps(st, cfg.retransmit_every + 3)
    c_log = _logs(st)[2]
    assert c_log[:2] == [1, 1001], f"C's order: {c_log}"
    # Exactly-once: replays must not duplicate deliveries anywhere.
    for i, log in enumerate(_logs(st)):
        assert len(log) == len(set(log)), f"node {i} duplicates: {log}"


def test_causal_catchup_beyond_deliver_cap():
    """A node catching up after a partition may have more deliverable
    records than one round's delivery quota; the overflow must spill to
    later rounds, not vanish (clock may only advance WITH delivery)."""
    cfg = chat_config(8, seed=17, causal_deliver_cap=4, causal_hist_cap=8)
    model = CausalChat()
    cl = Cluster(cfg, model=model)
    st = boot_fullmesh(cl)
    # Cut node 6 off from every actor, then let 4 actors send 2 each.
    st = st._replace(faults=faults_mod.inject_partition(
        st.faults, [0, 1, 2, 3], [6]))
    m = st.model
    base = int(st.rnd) + 1
    for a in range(4):
        m = model.schedule(m, node=a, rnd=base)
        m = model.schedule(m, node=a, rnd=base + 2)
    st = st._replace(model=m)
    st = cl.steps(st, 6)
    assert len(_logs(st)[6]) == 0, "partitioned node heard actors"
    # Heal: 8 deliverable records > quota 4; all must land within a few
    # replay rounds, in per-sender order, exactly once.
    st = st._replace(faults=faults_mod.resolve_partition(st.faults))
    st = cl.steps(st, cfg.retransmit_every * 6 + 4)
    log = _logs(st)[6]
    assert len(log) == 8 and len(set(log)) == 8, log
    for a in range(4):
        seqs = [t % 1000 for t in log if t // 1000 == a]
        assert seqs == [1, 2], (a, log)


# ---------------------------------------------------------------------------
# Point-to-point causal delivery (partisan_causality_backend.erl:204-220,
# per-destination scheme — UNBOUNDED senders)
# ---------------------------------------------------------------------------

def p2p_config(n, seed, **kw):
    return Config(n_nodes=n, seed=seed, causal_p2p_labels=("chat",),
                  peer_service_manager="static", **kw)


def _edge_fifo_ok(log, K=1000):
    """Every sender's seqs at this receiver are 1,2,3,... in order."""
    per_src = {}
    for t in log:
        per_src.setdefault(t // K, []).append(t % K)
    return all(seqs == list(range(1, len(seqs) + 1))
               for seqs in per_src.values())


def test_p2p_fifo_per_edge_under_loss():
    """Per-(sender, destination) FIFO delivery survives a lossy link via
    sender-side replay; app-visible delivery is exactly-once per edge."""
    cfg = p2p_config(8, seed=3)
    model = P2PChat()
    cl = Cluster(cfg, model=model)
    st = cl.init()
    m = st.model
    for i, rnd in enumerate((5, 6, 7, 9)):
        m = model.schedule(m, node=0, rnd=rnd, dst=5)
    st = st._replace(
        model=m,
        faults=st.faults._replace(link_drop=jnp.float32(0.5)))
    st = cl.steps(st, 20)
    st = st._replace(faults=st.faults._replace(link_drop=jnp.float32(0.0)))
    st = cl.steps(st, cfg.retransmit_every * 4 + 4)
    log = _logs(st)[5]
    assert [t % 1000 for t in log if t // 1000 == 0] == [1, 2, 3, 4], log


def test_p2p_any_node_sends():
    """ANY of n nodes may send causally (no bounded actor space): all 64
    nodes message random destinations; every receiver's log is per-edge
    FIFO with no duplicates."""
    n = 64
    cfg = p2p_config(n, seed=11)
    model = P2PChat()
    cl = Cluster(cfg, model=model)
    st = cl.init()
    rng = np.random.default_rng(5)
    m = st.model
    for i in range(n):
        dst = int(rng.integers(0, n - 1))
        dst = dst if dst < i else dst + 1      # anyone but self
        for k in range(3):
            m = model.schedule(m, node=i, rnd=4 + 2 * k, dst=dst)
    st = st._replace(model=m)
    st = cl.steps(st, 30)
    total = 0
    for i, log in enumerate(_logs(st)):
        assert len(log) == len(set(log)), f"node {i} duplicates: {log}"
        assert _edge_fifo_ok(log), f"node {i} FIFO violation: {log}"
        total += len(log)
    assert total == 3 * n, f"delivered {total} != {3 * n}"


def test_p2p_4096_nodes_single_and_sharded():
    """The scale gate (any sender at n=4096), single-device and sharded:
    identical logs and tables under both (p2p state is shard-local)."""
    n = 4096
    cfg = p2p_config(n, seed=7)
    model = P2PChat()
    rng = np.random.default_rng(9)
    senders = rng.choice(n, size=48, replace=False)
    plan = [(int(s), int((s + 1 + rng.integers(0, n - 2)) % n))
            for s in senders]

    def run(make):
        cl = make()
        st = cl.init()
        m = st.model
        for s, dst in plan:
            m = model.schedule(m, node=s, rnd=3, dst=dst)
            m = model.schedule(m, node=s, rnd=5, dst=dst)
        st = st._replace(model=m)
        return jax.device_get(cl.steps(st, 12))

    a = run(lambda: Cluster(cfg, model=model))
    b = run(lambda: ShardedCluster(cfg, make_mesh(8), model=model))
    assert (a.model.log == b.model.log).all()
    assert (a.delivery.p2p[0].src_seq == b.delivery.p2p[0].src_seq).all()
    for i, log in enumerate(_logs(a)):
        assert _edge_fifo_ok(log), f"node {i}: {log}"
    assert int(a.model.log_len.sum()) == 96


def test_p2p_quota_spill_no_loss():
    """More same-round deliverable senders than one round's quota: the
    excess must spill to later rounds, never vanish (tables advance only
    WITH app delivery)."""
    n = 32
    cfg = p2p_config(n, seed=23, causal_deliver_cap=4)
    model = P2PChat()
    cl = Cluster(cfg, model=model)
    st = cl.init()
    m = st.model
    for s in range(1, 21):
        m = model.schedule(m, node=s, rnd=3, dst=0)
    st = st._replace(model=m)
    st = cl.steps(st, cfg.retransmit_every * 8 + 6)
    log = _logs(st)[0]
    assert len(log) == 20 and len(set(log)) == 20, log


def test_p2p_backpressure_never_wedges():
    """A full unacked store DROPS new sends (counted, seq not advanced)
    instead of silently overwriting an unacked record; the stream stays
    FIFO-contiguous and keeps flowing once acks drain the store."""
    cfg = p2p_config(8, seed=29, p2p_hist_cap=4)
    model = P2PChat()
    cl = Cluster(cfg, model=model)
    st = cl.init()
    m = st.model
    # Flood 8 sends during a total link outage (store holds only 4).
    for k in range(8):
        m = model.schedule(m, node=1, rnd=3 + k, dst=6)
    st = st._replace(
        model=m, faults=st.faults._replace(link_drop=jnp.float32(1.0)))
    st = cl.steps(st, 14)
    assert int(st.delivery.p2p[0].overflow) > 0, "no backpressure counted"
    st = st._replace(faults=st.faults._replace(link_drop=jnp.float32(0.0)))
    st = cl.steps(st, cfg.retransmit_every * 6 + 4)
    log = _logs(st)[6]
    seqs = [t % 1000 for t in log if t // 1000 == 1]
    # Exactly the admitted prefix arrived, in order, exactly once (the
    # app's payload counter runs ahead for the refused sends — the
    # refusal is the app-visible backpressure signal, not reordering).
    assert seqs == [1, 2, 3, 4], seqs
    # The stream still works afterwards: a fresh send lands next, after
    # the backlog, with no stall (payload counter is 9 by now).
    m = model.schedule(st.model, node=1, rnd=int(st.rnd) + 1, dst=6,
                       now=int(st.rnd))
    st = st._replace(model=m)
    st = cl.steps(st, cfg.retransmit_every * 3 + 3)
    seqs2 = [t % 1000 for t in _logs(st)[6] if t // 1000 == 1]
    assert seqs2 == [1, 2, 3, 4, 9], seqs2


def test_p2p_lost_head_delivers_before_later_sends():
    """A dropped stream HEAD must not be skipped by a later send that
    arrives first on a slow retransmit cadence: seq 2 buffers (no
    out-of-order new-stream delivery) until seq 1's replay lands."""
    cfg = p2p_config(8, seed=37, retransmit_interval_ms=8_000)
    model = P2PChat()
    cl = Cluster(cfg, model=model)
    st = cl.init()
    m = st.model
    m = model.schedule(m, node=1, rnd=3, dst=5)   # dropped on the wire
    m = model.schedule(m, node=1, rnd=6, dst=5)   # arrives first
    st = st._replace(
        model=m, faults=st.faults._replace(link_drop=jnp.float32(1.0)))
    st = cl.steps(st, 4)
    st = st._replace(faults=st.faults._replace(link_drop=jnp.float32(0.0)))
    st = cl.steps(st, 24)
    seqs = [t % 1000 for t in _logs(st)[5] if t // 1000 == 1]
    assert seqs == [1, 2], seqs
    assert len(_logs(st)[5]) == len(set(_logs(st)[5]))


def test_p2p_stream_survives_receiver_crash_recovery():
    """Records aborted while the destination is dead must not leave a
    seq gap: a recovered destination gets a FRESH stream and every
    post-recovery send delivers."""
    cfg = p2p_config(8, seed=41)
    model = P2PChat()
    cl = Cluster(cfg, model=model)
    st = cl.init()
    m = model.schedule(st.model, node=1, rnd=2, dst=5)
    m = model.schedule(m, node=1, rnd=3, dst=5)
    st = st._replace(model=m)
    st = cl.steps(st, 8)
    assert [t % 1000 for t in _logs(st)[5]] == [1, 2]
    st = st._replace(faults=faults_mod.crash(st.faults, 5))
    m = model.schedule(st.model, node=1, rnd=int(st.rnd) + 1, dst=5)
    st = st._replace(model=m)
    st = cl.steps(st, 6)                 # send 3 aborted (dst dead)
    assert int(st.delivery.p2p[0].aborted) > 0
    st = st._replace(faults=faults_mod.recover(st.faults, 5))
    m = model.schedule(st.model, node=1, rnd=int(st.rnd) + 1, dst=5)
    st = st._replace(model=m)
    st = cl.steps(st, cfg.retransmit_every * 4 + 4)
    seqs = [t % 1000 for t in _logs(st)[5] if t // 1000 == 1]
    # Crash wiped the receiver's model log state?  No — crash freezes
    # state; the log survives.  Send 3 died with the crash window; send
    # 4 must arrive on a fresh stream.
    assert seqs == [1, 2, 4], seqs


def test_causal_sharded_parity():
    # Actors must be resident on shard 0: n_actors <= n_nodes/n_shards.
    cfg = chat_config(16, seed=9, n_actors=2)
    assert len(jax.devices()) >= 8
    model = CausalChat()

    def run(make):
        cl = make()
        st = cl.init()
        mgr = st.manager
        for i in range(1, 16):
            mgr = cl.manager.join(cfg, mgr, i, 0)
        m = st.model
        for rnd in (18, 21):
            m = model.schedule(m, node=0, rnd=rnd)
        m = model.schedule(m, node=1, rnd=20)
        st = st._replace(manager=mgr, model=m)
        return jax.device_get(cl.steps(st, 40))

    a = run(lambda: Cluster(cfg, model=model))
    b = run(lambda: ShardedCluster(cfg, make_mesh(8), model=model))
    assert (a.model.log == b.model.log).all()
    assert (a.delivery.lanes[0].clock == b.delivery.lanes[0].clock).all()
