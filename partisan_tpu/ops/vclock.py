"""Vector clocks as dense uint32 vectors.

Mirrors the Riak-style vclock API in reference src/partisan_vclock.erl:36-110
(``fresh/increment/merge/descends/dominates/glb``), re-designed for TPU: a
clock is a dense ``uint32[n_actors]`` vector, so

- ``merge``    = elementwise max        (the MXU/VPU-friendly hot op),
- ``descends`` = all(a >= b) reduction,
- ``increment``= one-hot add,

and whole matrices of clocks (one row per node) merge in a single fused op.
The reference's list-of-{actor, count} encoding exists to keep sparse clocks
small on the wire; on TPU the dense form is both faster and simpler, and the
actor space is bounded by ``Config.n_actors``.

The reference also carries per-entry timestamps used only by pruning
(partisan_vclock.erl ``timestamp/0``); delivery semantics never read them,
so the dense encoding drops them (documented fidelity deviation).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array

DTYPE = jnp.uint32


def fresh(n_actors: int) -> Array:
    """partisan_vclock:fresh/0 — the zero clock."""
    return jnp.zeros((n_actors,), DTYPE)


def fresh_matrix(n_nodes: int, n_actors: int) -> Array:
    """One fresh clock per node: uint32[n_nodes, n_actors]."""
    return jnp.zeros((n_nodes, n_actors), DTYPE)


def increment(vc: Array, actor: Array) -> Array:
    """partisan_vclock:increment/2 — bump one actor's counter.

    ``actor`` may be a scalar or (under vmap) a per-row scalar.
    """
    onehot = (jnp.arange(vc.shape[-1]) == actor).astype(DTYPE)
    return vc + onehot


def merge(a: Array, b: Array) -> Array:
    """partisan_vclock:merge/1 — pairwise elementwise max (broadcasts)."""
    return jnp.maximum(a, b)


def descends(a: Array, b: Array) -> Array:
    """partisan_vclock:descends/2 — True iff a >= b pointwise (a happened
    after-or-equal b).  Reduces over the trailing actor axis."""
    return jnp.all(a >= b, axis=-1)


def dominates(a: Array, b: Array) -> Array:
    """partisan_vclock:dominates/2 — strict descent."""
    return descends(a, b) & jnp.any(a > b, axis=-1)


def concurrent(a: Array, b: Array) -> Array:
    """Neither descends the other."""
    return ~descends(a, b) & ~descends(b, a)


def glb(a: Array, b: Array) -> Array:
    """partisan_vclock:glb/2 — greatest lower bound (elementwise min)."""
    return jnp.minimum(a, b)


def get_counter(vc: Array, actor: Array) -> Array:
    """partisan_vclock:get_counter/2."""
    return jnp.take_along_axis(
        vc, jnp.asarray(actor, jnp.int32)[..., None], axis=-1
    )[..., 0]


def deliverable(msg_clock: Array, local: Array, sender: Array) -> Array:
    """Causal-delivery gate (partisan_causality_backend.erl:204-220).

    A message with clock ``msg_clock`` from ``sender`` is deliverable at a
    node with clock ``local`` iff

    - ``msg_clock[sender] == local[sender] + 1``  (next from that sender), and
    - ``msg_clock[k] <= local[k]`` for all k != sender (deps satisfied).
    """
    n = msg_clock.shape[-1]
    onehot = jnp.arange(n) == jnp.asarray(sender, jnp.int32)[..., None]
    nxt = jnp.where(onehot, local + 1, local)
    return jnp.all(msg_clock <= nxt, axis=-1) & (
        get_counter(msg_clock, sender) == get_counter(local, sender) + 1
    )
