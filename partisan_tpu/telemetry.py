"""Telemetry event bus (reference ``telemetry`` dep usage, SURVEY.md §5.1/5.5).

The reference emits ``telemetry:execute`` events with a documented catalog
(doc_extras/telemetry.md:1-60): ``[partisan, membership, peer,
join|leave|up|down]`` plus channel-configuration events
(partisan_config.erl:834-843).  Handlers attach by id and receive
(event, measurements, metadata).

The sim equivalent is host-side: jitted rounds accumulate counters in
``Stats`` (cluster.py), and this bus carries discrete events —
membership transitions derived by diffing states between round batches,
plus anything scenarios emit.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping

import numpy as np

# Event-name catalog (doc_extras/telemetry.md).
PEER_JOIN = ("partisan", "membership", "peer", "join")
PEER_LEAVE = ("partisan", "membership", "peer", "leave")
PEER_UP = ("partisan", "membership", "peer", "up")
PEER_DOWN = ("partisan", "membership", "peer", "down")
CHANNEL_CONFIGURED = ("partisan", "channel", "configured")

# Metrics-plane threshold events (metrics.py ring -> discrete events;
# the sim extension of the reference catalog — same bus, same shape).
# The ``*_cleared`` falling edges are opt-in (``falling=True``): the
# incident matcher's recovery markers for sustained spikes.
METRICS_SHED_SPIKE = ("partisan", "metrics", "shed_spike")
METRICS_DROP_SPIKE = ("partisan", "metrics", "drop_spike")
METRICS_PARTITION = ("partisan", "metrics", "partition_detected")
METRICS_SHED_CLEARED = ("partisan", "metrics", "shed_cleared")
METRICS_DROP_CLEARED = ("partisan", "metrics", "drop_cleared")
METRICS_PARTITION_CLEARED = ("partisan", "metrics", "partition_cleared")

# Latency-plane SLO events (latency.py histograms -> discrete events).
LATENCY_SLO_BREACH = ("partisan", "latency", "slo_breach")

# Health-plane overlay events (health.py snapshot ring -> discrete
# events): partition split / heal transitions of the device component
# counter, plus windowed churn.
HEALTH_PARTITION = ("partisan", "health", "partition_detected")
HEALTH_HEALED = ("partisan", "health", "overlay_healed")
HEALTH_CHURN = ("partisan", "health", "churn")
HEALTH_CHURN_SETTLED = ("partisan", "health", "churn_settled")

# Provenance-plane broadcast events (provenance.py rings -> discrete
# events): redundant-duplicate spikes, graft storms and their repair.
BROADCAST_REDUNDANCY = ("partisan", "broadcast", "redundancy_spike")
BROADCAST_GRAFT_STORM = ("partisan", "broadcast", "graft_storm")
BROADCAST_TREE_REPAIRED = ("partisan", "broadcast", "tree_repaired")

# Control-plane events (control.py decision rings -> discrete events):
# an in-scan controller changed its operand — the closed-loop analogue
# of the planes' threshold events above.
CONTROL_FANOUT_ADJUSTED = ("partisan", "control", "fanout_adjusted")
CONTROL_SHED_CHANGED = ("partisan", "control", "shed_threshold_changed")
CONTROL_HEALING = ("partisan", "control", "healing_escalated")

# Traffic-plane events (workload.py generator + soak chunk rows ->
# discrete events): the open-loop rate multiplier spiking into a flash
# crowd, and windows of chunks whose per-channel windowed p99 breached
# the SLO bound.
TRAFFIC_FLASH_CROWD = ("partisan", "traffic", "flash_crowd")
TRAFFIC_SLO_BREACH_WINDOW = ("partisan", "traffic", "slo_breach_window")

# Soak-engine recovery events (soak.py host log -> discrete events):
# chunk execution retried after a worker crash, state restored from a
# checkpoint, and a per-chunk invariant breach (with its dump paths).
SOAK_CHUNK_RETRY = ("partisan", "soak", "chunk_retry")
SOAK_CHECKPOINT_RESTORED = ("partisan", "soak", "checkpoint_restored")
SOAK_INVARIANT_BREACH = ("partisan", "soak", "invariant_breach")

# Elastic-resize events (elastic.py resize ring -> discrete events):
# every n_active transition the jitted round recorded — host
# activations (scale-out) and in-scan drain deactivations (scale-in)
# alike — direction-tagged.
ELASTIC_SCALE_OUT = ("partisan", "elastic", "scale_out")
ELASTIC_SCALE_IN = ("partisan", "elastic", "scale_in")

# Streaming-ingress events (ingress.py feed reports in the soak log ->
# discrete events): a boundary drain that staged external requests,
# and one that shed (buffer-full) or deferred (quota) some.
INGRESS_DRAIN = ("partisan", "ingress", "drain")
INGRESS_SHED = ("partisan", "ingress", "shed")

# Watchdog-plane events (watchdog.py violation ring -> discrete
# events): the in-scan invariant plane's breach edges.  Unlike every
# plane above, the DETECTION already happened on device at the exact
# round — these replays only surface it, so the opslog ingests them as
# round-exact detection legs instead of chunk-quantized ones.
WATCHDOG_BREACH_DETECTED = ("partisan", "watchdog", "breach_detected")
WATCHDOG_BREACH_CLEARED = ("partisan", "watchdog", "breach_cleared")
WATCHDOG_FLIGHT_TRIPPED = ("partisan", "watchdog", "flight_tripped")

# Performance-observatory events (perfwatch host-side measurements ->
# discrete events): the dispatch-wall decomposition of a chunked run,
# a measured-vs-predicted phase outlier (the VMEM-fusion target list),
# and a bench-ledger regression verdict.
PERF_DISPATCH_WALL = ("partisan", "perf", "dispatch_wall")
PERF_PHASE_OUTLIER = ("partisan", "perf", "phase_outlier")
PERF_REGRESSION = ("partisan", "perf", "regression")

# Full-horizon telemetry-spool records (spool.py): the ``*.row`` /
# ``*.resize`` / ``*.window`` / ``*.level`` names are the EVENT FIELD
# of the spool's append-only JSON-lines records (one per plane ring
# row drained at a soak chunk boundary — journal dedup identity, never
# emitted on a bus), registered here so the one registry stays the
# only event namespace.  ``drained`` is the live bus marker the soak
# engine emits after each drain (rows written + file line pointer).
SPOOL_METRICS_ROW = ("partisan", "spool", "metrics", "row")
SPOOL_HEALTH_ROW = ("partisan", "spool", "health", "row")
SPOOL_BROADCAST_ROW = ("partisan", "spool", "broadcast", "row")
SPOOL_CONTROL_FANOUT = ("partisan", "spool", "control", "fanout")
SPOOL_CONTROL_BACKPRESSURE = ("partisan", "spool", "control",
                              "backpressure")
SPOOL_CONTROL_HEALING = ("partisan", "spool", "control", "healing")
SPOOL_TRAFFIC_ROW = ("partisan", "spool", "traffic", "row")
SPOOL_ELASTIC_RESIZE = ("partisan", "spool", "elastic", "resize")
SPOOL_LATENCY_WINDOW = ("partisan", "spool", "latency", "window")
SPOOL_INGRESS_LEVEL = ("partisan", "spool", "ingress", "level")
SPOOL_WATCHDOG_ROW = ("partisan", "spool", "watchdog", "row")
SPOOL_DRAINED = ("partisan", "spool", "drained")


# ---------------------------------------------------------------------------
# The event-name registry: ONE catalog of every ``partisan.*`` event,
# its severity, and the measurement/metadata fields an emission must
# carry.  Every adapter in this module emits through :func:`emit`,
# which refuses unregistered names and missing required fields — the
# sync guard tests/test_opslog.py pins additionally fails on any
# ad-hoc ("partisan", ...) literal elsewhere in the tree.  The opslog
# journal reads severities from here, so a new event is registered
# once and every surface (bus, journal, incident report, Perfetto
# export) picks it up.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class EventSpec:
    """Registry row: the event name tuple, the severity the opslog
    journal files it under, and the REQUIRED measurement/metadata
    keys (emissions may carry more; they may not carry less)."""

    name: tuple
    severity: str = "info"        # "info" | "warn" | "error"
    measurements: tuple = ()
    metadata: tuple = ()


EVENTS: dict[tuple, EventSpec] = {spec.name: spec for spec in (
    EventSpec(PEER_JOIN, "info", ("count",), ("node", "round")),
    EventSpec(PEER_LEAVE, "warn", ("count",), ("node", "round")),
    EventSpec(PEER_UP, "info", ("count",), ("node", "round")),
    EventSpec(PEER_DOWN, "warn", ("count",), ("node", "round")),
    EventSpec(CHANNEL_CONFIGURED, "info", ("parallelism",),
              ("channel", "monotonic")),
    EventSpec(METRICS_SHED_SPIKE, "warn", ("shed",), ("round",)),
    EventSpec(METRICS_DROP_SPIKE, "warn", ("dropped",), ("round",)),
    EventSpec(METRICS_PARTITION, "error", ("edges_min", "alive"),
              ("round",)),
    EventSpec(METRICS_SHED_CLEARED, "info", ("shed",), ("round",)),
    EventSpec(METRICS_DROP_CLEARED, "info", ("dropped",), ("round",)),
    EventSpec(METRICS_PARTITION_CLEARED, "info", ("edges_min",),
              ("round",)),
    EventSpec(LATENCY_SLO_BREACH, "warn",
              ("age_rounds", "count", "max_age_rounds"),
              ("channel", "quantile", "slo_rounds")),
    EventSpec(HEALTH_PARTITION, "error", ("components", "isolated"),
              ("round",)),
    EventSpec(HEALTH_HEALED, "info", ("components",), ("round",)),
    EventSpec(HEALTH_CHURN, "warn", ("joins", "leaves", "ups", "downs"),
              ("round",)),
    EventSpec(HEALTH_CHURN_SETTLED, "info", ("quiet",), ("round",)),
    EventSpec(BROADCAST_REDUNDANCY, "warn",
              ("duplicates", "gossip", "ratio"), ("round",)),
    EventSpec(BROADCAST_GRAFT_STORM, "warn", ("grafts",), ("round",)),
    EventSpec(BROADCAST_TREE_REPAIRED, "info", ("storm_rounds",),
              ("round",)),
    EventSpec(CONTROL_FANOUT_ADJUSTED, "info", ("cap", "prev"),
              ("round",)),
    EventSpec(CONTROL_SHED_CHANGED, "info", ("press", "prev"),
              ("round", "channel")),
    EventSpec(CONTROL_HEALING, "info", ("boost", "prev"),
              ("round", "direction")),
    EventSpec(TRAFFIC_FLASH_CROWD, "warn", ("rate_x1000", "sent"),
              ("round",)),
    EventSpec(TRAFFIC_SLO_BREACH_WINDOW, "warn", ("worst_p99", "chunks"),
              ("round", "end_round", "channel", "slo_rounds")),
    EventSpec(SOAK_CHUNK_RETRY, "warn", (), ("round",)),
    EventSpec(SOAK_CHECKPOINT_RESTORED, "warn", (), ("round",)),
    EventSpec(SOAK_INVARIANT_BREACH, "error", (), ("round",)),
    EventSpec(ELASTIC_SCALE_OUT, "info", ("n_active",),
              ("round", "from")),
    EventSpec(ELASTIC_SCALE_IN, "info", ("n_active",),
              ("round", "from")),
    EventSpec(INGRESS_DRAIN, "info", ("staged",), ("round",)),
    EventSpec(INGRESS_SHED, "warn",
              ("shed_buffer_full", "shed_invalid", "deferred"),
              ("round",)),
    EventSpec(WATCHDOG_BREACH_DETECTED, "error", ("word", "delta"),
              ("round",)),
    EventSpec(WATCHDOG_BREACH_CLEARED, "info", ("breach_rounds",),
              ("round",)),
    EventSpec(WATCHDOG_FLIGHT_TRIPPED, "warn", ("word",), ("round",)),
    EventSpec(PERF_DISPATCH_WALL, "info",
              ("in_execution_s", "gap_s", "gap_share"), ("chunks",)),
    EventSpec(PERF_PHASE_OUTLIER, "warn",
              ("measured_ms", "predicted_bytes", "time_share"),
              ("phase",)),
    EventSpec(PERF_REGRESSION, "error", ("rounds_per_sec", "delta_pct"),
              ()),
    EventSpec(SPOOL_METRICS_ROW, "info",
              ("shed", "drops", "edges_min", "alive"), ()),
    EventSpec(SPOOL_HEALTH_ROW, "info",
              ("components", "isolated", "joins", "leaves", "ups",
               "downs"), ()),
    EventSpec(SPOOL_BROADCAST_ROW, "info", ("dup", "gossip", "ctl"), ()),
    EventSpec(SPOOL_CONTROL_FANOUT, "info", ("cap",), ()),
    EventSpec(SPOOL_CONTROL_BACKPRESSURE, "info", ("press",), ()),
    EventSpec(SPOOL_CONTROL_HEALING, "info", ("boost",), ()),
    EventSpec(SPOOL_TRAFFIC_ROW, "info", ("arrivals",), ()),
    EventSpec(SPOOL_ELASTIC_RESIZE, "info", ("width", "from"), ()),
    EventSpec(SPOOL_LATENCY_WINDOW, "info", ("k",), ()),
    EventSpec(SPOOL_INGRESS_LEVEL, "info",
              ("staged", "injected", "shed"), ()),
    EventSpec(SPOOL_WATCHDOG_ROW, "info", ("word",), ()),
    EventSpec(SPOOL_DRAINED, "info", ("rows",), ("round", "line")),
)}


def emit(bus: "Bus", event: tuple, measurements: Mapping[str, Any],
         metadata: Mapping[str, Any] | None = None) -> None:
    """The registry-checked emission path every adapter in this module
    uses: refuses an unregistered event name or an emission missing
    the spec's required fields, then forwards to ``bus.execute``."""
    spec = EVENTS.get(tuple(event))
    if spec is None:
        raise ValueError(
            f"unregistered telemetry event {tuple(event)!r} — add an "
            f"EventSpec to telemetry.EVENTS (the registry is the only "
            f"emission path)")
    missing = [k for k in spec.measurements if k not in measurements]
    missing += [k for k in spec.metadata if k not in (metadata or {})]
    if missing:
        raise ValueError(
            f"event {tuple(event)!r} emitted without required "
            f"field(s) {missing} (see telemetry.EVENTS)")
    bus.execute(event, measurements, metadata)


Handler = Callable[[tuple, Mapping[str, Any], Mapping[str, Any]], None]


@dataclasses.dataclass
class Bus:
    """telemetry:attach/execute/detach."""

    def __post_init__(self) -> None:
        self._handlers: dict[str, tuple[tuple, Handler]] = {}

    def attach(self, handler_id: str, event: tuple, fn: Handler) -> None:
        if handler_id in self._handlers:
            raise ValueError(f"handler {handler_id!r} already attached")
        self._handlers[handler_id] = (tuple(event), fn)

    def detach(self, handler_id: str) -> None:
        self._handlers.pop(handler_id, None)

    def execute(self, event: tuple, measurements: Mapping[str, Any],
                metadata: Mapping[str, Any] | None = None) -> None:
        event = tuple(event)
        for prefix, fn in list(self._handlers.values()):
            if event[:len(prefix)] == prefix:
                fn(event, dict(measurements), dict(metadata or {}))


@dataclasses.dataclass
class Recorder:
    """A handler that keeps every event (test/observability helper)."""

    events: list = dataclasses.field(default_factory=list)

    def __call__(self, event, measurements, metadata) -> None:
        self.events.append((event, measurements, metadata))

    def of(self, event: tuple) -> list:
        return [e for e in self.events if e[0] == tuple(event)]


def emit_membership_events(bus: Bus, cfg, manager, prev_state, state,
                           observer: int = 0) -> None:
    """Diff two cluster states' membership views (from ``observer``'s
    perspective) and emit peer join/leave events; diff liveness for
    up/down — the host-side analogue of the reference's event points in
    the managers (partisan_peer_service_events fan-out +
    telemetry.md catalog)."""
    before = np.asarray(manager.members(cfg, prev_state.manager))[observer]
    after = np.asarray(manager.members(cfg, state.manager))[observer]
    rnd = int(state.rnd)
    for node in np.flatnonzero(~before & after):
        emit(bus, PEER_JOIN, {"count": 1},
             {"node": int(node), "round": rnd})
    for node in np.flatnonzero(before & ~after):
        emit(bus, PEER_LEAVE, {"count": 1},
             {"node": int(node), "round": rnd})
    palive = np.asarray(prev_state.faults.alive)
    alive = np.asarray(state.faults.alive)
    for node in np.flatnonzero(~palive & alive):
        emit(bus, PEER_UP, {"count": 1}, {"node": int(node), "round": rnd})
    for node in np.flatnonzero(palive & ~alive):
        emit(bus, PEER_DOWN, {"count": 1},
             {"node": int(node), "round": rnd})


def replay_metrics_events(bus: Bus, snap: Mapping[str, Any], *,
                          shed_threshold: int = 1,
                          drop_threshold: int = 1,
                          falling: bool = False) -> int:
    """Replay a metrics snapshot (``metrics.snapshot``) as discrete
    threshold-crossing events through the bus — the host-side adapter
    from the device-resident counter ring to the reference's
    telemetry-event idiom (``telemetry:execute`` with measurements +
    metadata).

    Crossings are EDGE-triggered per series: an event fires on the
    first round at-or-above the threshold after a round below it, so a
    sustained spike is one event, not one per round.

    - ``shed_spike``  — monotonic-channel sheds >= ``shed_threshold``
    - ``drop_spike``  — cause-summed event-lane drops >= ``drop_threshold``
    - ``partition_detected`` — an ALIVE node with zero live out-edges
      while the cluster has >1 alive node (the conn-count-to-zero
      node-isolation signal, partisan_peer_connections.erl:1489-1535,
      read from the live-edge series).  Edge-LOSS gated: it only fires
      once some round in the window showed every alive node connected
      (edges_min > 0) — nodes that have not yet JOINED also have zero
      out-edges, and a cold bootstrap is not a partition.

    With ``falling=True`` the matching ``*_cleared`` falling edges are
    emitted too (first round back below the threshold after a hot run)
    — the opslog matcher's recovery markers; off by default so the
    adapter's historical event counts are unchanged.

    Returns the number of events emitted."""
    shed = np.asarray(snap["shed"])
    drops = np.asarray(snap["drops"]).sum(axis=1)
    edges_min = np.asarray(snap["edges_min"])
    rounds = np.asarray(snap["rounds"])
    if rounds.size and rounds[0] == 0:
        # Window covers the run start: suppress the cold-bootstrap
        # rounds before the overlay first fully connected.
        was_connected = np.cumsum(edges_min > 0) > 0
    else:
        # Ring wrapped — the window starts mid-run, bootstrap is long
        # past, and a zero-edge alive node is a real isolation signal
        # (a sustained partition must not be suppressed just because
        # the last connected round fell off the ring).
        was_connected = np.ones(rounds.shape, bool)
    isolated = (edges_min == 0) & (np.asarray(snap["alive"]) > 1) \
        & was_connected
    n_events = 0
    cleared = {METRICS_SHED_SPIKE: METRICS_SHED_CLEARED,
               METRICS_DROP_SPIKE: METRICS_DROP_CLEARED,
               METRICS_PARTITION: METRICS_PARTITION_CLEARED}
    prev = {"shed": False, "drop": False, "part": False}
    for i, rnd in enumerate(rounds):
        for key, hot, event, meas in (
                ("shed", bool(shed[i] >= shed_threshold),
                 METRICS_SHED_SPIKE, {"shed": int(shed[i])}),
                ("drop", bool(drops[i] >= drop_threshold),
                 METRICS_DROP_SPIKE, {"dropped": int(drops[i])}),
                ("part", bool(isolated[i]),
                 METRICS_PARTITION,
                 {"edges_min": int(snap["edges_min"][i]),
                  "alive": int(snap["alive"][i])})):
            if hot and not prev[key]:
                emit(bus, event, meas, {"round": int(rnd)})
                n_events += 1
            elif falling and prev[key] and not hot:
                emit(bus, cleared[event], meas, {"round": int(rnd)})
                n_events += 1
            prev[key] = hot
    return n_events


def replay_latency_events(bus: Bus, lat_snap: Mapping[str, Any], *,
                          slo_rounds: int, quantile: float = 0.99,
                          channels: tuple[str, ...] | None = None,
                          rnd: int | None = None) -> int:
    """Replay a latency snapshot (``latency.snapshot`` /
    ``latency.percentiles`` input) as SLO threshold-crossing events:
    one ``partisan.latency.slo_breach`` per channel whose ``quantile``
    delivery age is at or above ``slo_rounds`` rounds — the host-side
    adapter from the device-resident age histograms to the telemetry
    bus (same shape as :func:`replay_metrics_events`).

    The histograms are cumulative, so these events have no round of
    their own; pass ``rnd`` (the round the snapshot was taken at) to
    round-key them for the opslog journal's total order.

    Returns the number of events emitted."""
    from partisan_tpu import latency as latency_mod

    if quantile not in (0.50, 0.95, 0.99):
        raise ValueError(
            f"quantile must be one of 0.50/0.95/0.99 (the percentiles "
            f"the log2 histograms resolve), got {quantile}")
    pcts = latency_mod.percentiles(dict(lat_snap), channels=channels)
    label = f"p{int(round(quantile * 100))}"
    n_events = 0
    for ch_name, entry in pcts.items():
        age = entry.get(label)
        if age is None or age < slo_rounds:
            continue
        meta = {"channel": ch_name, "quantile": label,
                "slo_rounds": int(slo_rounds)}
        if rnd is not None:
            meta["round"] = int(rnd)
        emit(bus, LATENCY_SLO_BREACH,
             {"age_rounds": int(age), "count": entry["count"],
              "max_age_rounds": entry["max"]}, meta)
        n_events += 1
    return n_events


def replay_health_events(bus: Bus, snap: Mapping[str, Any], *,
                         churn_threshold: int = 1,
                         falling: bool = False) -> int:
    """Replay a health snapshot (``health.snapshot``) as discrete
    overlay events through the bus — the host-side adapter from the
    device-resident topology ring to the telemetry idiom (same shape as
    :func:`replay_metrics_events`).  The transition derivation itself
    lives in ``health.transitions`` (the plane owns its discrete-event
    semantics; this adapter owns the bus mapping):

    - ``partition_detected`` — a split of a previously-whole overlay
      (cold bootstrap suppressed).  Edge-triggered.
    - ``overlay_healed`` — the component count returns to 1 after a
      detected split.
    - ``churn`` — windowed join/leave/up/down totals at or above
      ``churn_threshold``; edge-triggered like the metrics spikes.
    - ``churn_settled`` (only with ``falling=True``) — the falling
      edge after a hot churn run; off by default so the adapter's
      historical event counts are unchanged.

    Returns the number of events emitted."""
    from partisan_tpu import health as health_mod

    events = {"partition_detected": HEALTH_PARTITION,
              "overlay_healed": HEALTH_HEALED,
              "churn": HEALTH_CHURN,
              "churn_settled": HEALTH_CHURN_SETTLED}
    n_events = 0
    for tr in health_mod.transitions(dict(snap),
                                     churn_threshold=churn_threshold,
                                     falling=falling):
        meas = {k: v for k, v in tr.items() if k not in ("kind", "round")}
        emit(bus, events[tr["kind"]], meas, {"round": tr["round"]})
        n_events += 1
    return n_events


def replay_broadcast_events(bus: Bus, snap: Mapping[str, Any], *,
                            redundancy_ratio: float = 0.5,
                            redundancy_min: int = 4,
                            graft_threshold: int = 1) -> int:
    """Replay a provenance snapshot (``provenance.snapshot``) as
    discrete broadcast-plane events through the bus — the host-side
    adapter from the dissemination rings to the telemetry idiom (same
    shape as :func:`replay_metrics_events`).

    - ``redundancy_spike`` — a round whose duplicate-delivery fraction
      (``dup / gossip_delivered``) is at or above ``redundancy_ratio``
      with at least ``redundancy_min`` gossip deliveries (small rounds
      are noise: one duplicate of two deliveries is not a spike).
      Edge-triggered: a sustained flood is one event — the state
      Plumtree's PRUNE exists to collapse.
    - ``graft_storm`` — grafts DELIVERED in a round at or above
      ``graft_threshold``: lazy repair is re-activating pruned links
      (partisan_plumtree_broadcast.erl:861-905).  Edge-triggered.
    - ``tree_repaired`` — the first graft-free round after a storm:
      the grafted links carried the payload and the repair traffic
      subsided, with the storm's span in the measurements.

    Returns the number of events emitted."""
    from partisan_tpu.provenance import CTL_NAMES

    gi = CTL_NAMES.index("graft")
    rounds = np.asarray(snap["rounds"])
    dup = np.asarray(snap["dup"]).sum(axis=1)
    gossip = np.asarray(snap["gossip"])
    grafts = np.asarray(snap["ctl"])[:, gi, 1]
    n_events = 0
    red_hot = False
    storm_start: int | None = None
    for i, rnd in enumerate(rounds):
        g = int(gossip[i])
        hot = g >= redundancy_min and dup[i] / g >= redundancy_ratio
        if hot and not red_hot:
            emit(bus, BROADCAST_REDUNDANCY,
                 {"duplicates": int(dup[i]), "gossip": g,
                  "ratio": round(float(dup[i]) / g, 4)},
                 {"round": int(rnd)})
            n_events += 1
        red_hot = hot
        storming = int(grafts[i]) >= graft_threshold
        if storming and storm_start is None:
            emit(bus, BROADCAST_GRAFT_STORM,
                 {"grafts": int(grafts[i])}, {"round": int(rnd)})
            n_events += 1
            storm_start = int(rnd)
        elif storm_start is not None and int(grafts[i]) == 0:
            emit(bus, BROADCAST_TREE_REPAIRED,
                 {"storm_rounds": int(rnd) - storm_start},
                 {"round": int(rnd)})
            n_events += 1
            storm_start = None
    return n_events


def replay_control_events(bus: Bus, snap: Mapping[str, Any], *,
                          channels: tuple[str, ...] | None = None) -> int:
    """Replay a controller snapshot (``control.snapshot``) as discrete
    ``partisan.control.*`` bus events — the host-side adapter from the
    in-scan decision rings to the telemetry idiom (same shape as the
    plane replays above).  The rings record the operand in force after
    EVERY round, so an event is a round where it CHANGED:

    - ``fanout_adjusted`` — the plumtree eager-link budget stepped
      (measurements carry the new and previous cap),
    - ``shed_threshold_changed`` — a channel's backpressure level moved
      (one event per changed channel, the channel in the metadata),
    - ``healing_escalated`` — the overlay repair boost changed
      (escalations and relaxations both; direction in the metadata).

    The ring diffing itself lives in ``control.decisions`` (the plane
    owns its discrete-event semantics; this adapter owns the bus
    mapping).  Returns the number of events emitted."""
    from partisan_tpu import control as control_mod

    events = {"fanout_adjusted": CONTROL_FANOUT_ADJUSTED,
              "shed_threshold_changed": CONTROL_SHED_CHANGED,
              "healing_escalated": CONTROL_HEALING}
    n_events = 0
    for d in control_mod.decisions(dict(snap), channels=channels):
        meta = {"round": d["round"]}
        for k in ("channel", "direction"):
            if k in d:
                meta[k] = d[k]
        meas = {k: v for k, v in d.items()
                if k not in ("kind", "round", "channel", "direction")}
        emit(bus, events[d["kind"]], meas, meta)
        n_events += 1
    return n_events


def replay_traffic_events(bus: Bus, chunks, *, slo_rounds: int | None = None,
                          crowd_x1000: int | None = None) -> int:
    """Replay a soak run's chunk rows (``soak.SoakResult.chunks`` —
    each row optionally carrying a ``traffic`` poll and, under
    ``SoakConfig.poll_latency``, a windowed per-channel ``p99`` dict)
    as discrete ``partisan.traffic.*`` bus events — the traffic plane's
    adapter to the telemetry idiom (same shape as the plane replays
    above).

    - ``flash_crowd`` — the open-loop rate multiplier crossed
      ``crowd_x1000`` (default: 2x the first row's rate).
      Edge-triggered: a sustained crowd is one event.
    - ``slo_breach_window`` — one event per MAXIMAL consecutive run of
      chunks in which some channel's windowed p99 EXCEEDED
      ``slo_rounds`` (p99 == bound passes, matching every other SLO
      gate; skipped when ``slo_rounds`` is None or no row carries a
      p99 series).  Measurements carry the window's worst
      p99 and chunk count; metadata its start round, end round and
      worst channel — the Dapper-style "which window breached, how
      badly" record the SLO suite commits.

    Returns the number of events emitted."""
    rows = [r for r in chunks if "traffic" in r]
    n_events = 0
    if rows:
        base = int(rows[0]["traffic"].get("rate_x1000", 0))
        thresh = crowd_x1000 if crowd_x1000 is not None \
            else 2 * max(base, 1)
        hot = False
        for r in rows:
            rate = int(r["traffic"].get("rate_x1000", 0))
            h = rate >= thresh
            if h and not hot:
                emit(bus, TRAFFIC_FLASH_CROWD,
                     {"rate_x1000": rate,
                      "sent": int(r["traffic"].get("sent", 0))},
                     {"round": int(r["round"])})
                n_events += 1
            hot = h
    if slo_rounds is not None:
        window: dict | None = None

        def _emit_window(w):
            emit(bus, TRAFFIC_SLO_BREACH_WINDOW,
                 {"worst_p99": w["worst_p99"],
                  "chunks": w["chunks"]},
                 {"round": w["start"], "end_round": w["end"],
                  "channel": w["channel"],
                  "slo_rounds": int(slo_rounds)})

        for r in chunks:
            p99 = r.get("p99") or {}
            over = {ch: v for ch, v in p99.items()
                    if v is not None and v > slo_rounds}
            worst = max(over.items(), key=lambda kv: kv[1]) \
                if over else None
            if worst is not None:
                end = int(r["round"]) + int(r.get("k", 0))
                if window is None:
                    window = {"start": int(r["round"]), "end": end,
                              "channel": worst[0],
                              "worst_p99": int(worst[1]), "chunks": 1}
                else:
                    window["chunks"] += 1
                    window["end"] = end
                    if worst[1] > window["worst_p99"]:
                        window["channel"] = worst[0]
                        window["worst_p99"] = int(worst[1])
            elif window is not None:
                _emit_window(window)
                n_events += 1
                window = None
        if window is not None:
            _emit_window(window)
            n_events += 1
    return n_events


def replay_soak_events(bus: Bus, log) -> int:
    """Replay a soak engine's host-side event log (``soak.SoakResult.log``
    — a list of self-describing dicts) as discrete
    ``partisan.soak.*`` bus events — the recovery-path analogue of the
    plane replays above.  Unlike those, the source here is already
    discrete (the engine records each retry/restore/breach as it
    happens), so the mapping is one log entry -> at most one event:

    - ``chunk_retry`` — a chunk execution died (worker crash /
      JaxRuntimeError) and was retried after a cool-down,
    - ``checkpoint_restored`` — state was rebuilt from a checkpoint
      (post-crash resume in a fresh context),
    - ``invariant_breach`` — a per-chunk invariant failed; the
      measurements carry the breach info and the metadata the dump
      paths written for post-mortem (flight trace, plane snapshots).

    Returns the number of events emitted."""
    kinds = {
        "chunk_retry": SOAK_CHUNK_RETRY,
        "checkpoint_restored": SOAK_CHECKPOINT_RESTORED,
        "invariant_breach": SOAK_INVARIANT_BREACH,
    }
    n_events = 0
    for entry in log:
        event = kinds.get(entry.get("kind"))
        if event is None:
            continue
        meas = {k: v for k, v in entry.items()
                if isinstance(v, (int, float)) and k != "round"}
        meta = {k: v for k, v in entry.items()
                if not isinstance(v, (int, float)) and k != "kind"}
        meta["round"] = int(entry.get("round", -1))
        emit(bus, event, meas, meta)
        n_events += 1
    return n_events


def replay_watchdog_events(bus: Bus, snap: Mapping[str, Any]) -> int:
    """Replay a watchdog snapshot (``watchdog.snapshot`` — the decoded
    violation ring plus the scalar latches) as discrete
    ``partisan.watchdog.*`` bus events — same edge-triggered shape as
    the plane replays above, with one crucial difference: the
    detection ROUND is the device's, not the boundary's, so the opslog
    files these as round-exact detection legs.

    - ``breach_detected`` — the first round of a nonzero-word run
      (measurements carry the packed word and its conservation delta),
    - ``breach_cleared`` — the first zero-word round after a run
      (measurements carry the run's length in rounds),
    - ``flight_tripped`` — once, at the first breach still in the
      ring, when the snapshot's trip latch is set (the flight recorder
      froze there — watchdog.py trip semantics).

    Returns the number of events emitted."""
    from partisan_tpu import watchdog as watchdog_mod

    n_events = 0
    hot = False
    hot_start = 0
    trip_pending = bool(snap.get("tripped"))
    for r, w in zip(snap["rounds"], snap["words"]):
        r, w = int(r), int(w)
        if w and not hot:
            emit(bus, WATCHDOG_BREACH_DETECTED,
                 {"word": w,
                  "delta": watchdog_mod.decode_word(w)["delta"]},
                 {"round": r})
            n_events += 1
            hot_start = r
            if trip_pending:
                emit(bus, WATCHDOG_FLIGHT_TRIPPED, {"word": w},
                     {"round": r})
                n_events += 1
                trip_pending = False
        elif not w and hot:
            emit(bus, WATCHDOG_BREACH_CLEARED,
                 {"breach_rounds": r - hot_start}, {"round": r})
            n_events += 1
        hot = bool(w)
    return n_events


def replay_elastic_events(bus: Bus, snap: Mapping[str, Any]) -> int:
    """Replay an elastic-timeline snapshot (``elastic.snapshot`` — the
    in-scan resize ring: round, n_active AFTER and BEFORE each
    transition) as direction-tagged ``partisan.elastic.*`` events —
    the stored from-width tags the direction, so the first entry of a
    wrapped (or shrink-first) window cannot misreport.  The transition
    derivation lives in ``elastic.transitions`` (the plane owns its
    discrete-event semantics; this adapter owns the bus mapping).
    Every event is round-keyed — the opslog span matcher closes resize
    spans on them.  Returns the number of events emitted."""
    from partisan_tpu import elastic as elastic_mod

    n_events = 0
    for tr in elastic_mod.transitions(dict(snap)):
        emit(bus, ELASTIC_SCALE_OUT if tr["kind"] == "scale_out"
             else ELASTIC_SCALE_IN,
             {"n_active": tr["n_active"]},
             {"round": tr["round"], "from": tr["from"]})
        n_events += 1
    return n_events


def replay_ingress_events(bus: Bus, log) -> int:
    """Replay a soak log's ``ingress_drain`` entries (the feed's
    boundary reports) as ``partisan.ingress.*`` events: one ``drain``
    per staging boundary, plus a ``shed`` when the boundary shed
    (per-node buffer full) or deferred (quota) requests.  Returns the
    number of events emitted."""
    n_events = 0
    for entry in log:
        if entry.get("kind") != "ingress_drain":
            continue
        meta = {"round": int(entry.get("round", -1)),
                "replayed": bool(entry.get("replayed", False))}
        emit(bus, INGRESS_DRAIN,
             {"staged": int(entry.get("staged", 0))}, meta)
        n_events += 1
        shed = int(entry.get("shed_buffer_full", 0))
        invalid = int(entry.get("shed_invalid", 0))
        deferred = int(entry.get("deferred", 0))
        if shed or invalid or deferred:
            emit(bus, INGRESS_SHED,
                 {"shed_buffer_full": shed,
                  "shed_invalid": invalid,
                  "deferred": deferred}, meta)
            n_events += 1
    return n_events


def replay_perf_events(bus: Bus, *, dispatch: Mapping[str, Any] | None = None,
                       phases=None, deltas=None,
                       rnd: int | None = None) -> int:
    """Replay perfwatch host-side measurements as ``partisan.perf.*``
    events: one ``dispatch_wall`` per decomposition (perfwatch
    ``decompose``/``decompose_chunks`` dict), one ``phase_outlier`` per
    reconciliation row flagged ``outlier`` (perfwatch ``reconcile``),
    and one ``regression`` per ledger delta flagged ``regression``
    (perfwatch ``ledger_deltas``).  These are whole-run measurements
    with no round of their own; pass ``rnd`` (the run's final round)
    to round-key them for the opslog journal's total order.  Returns
    the number of events emitted."""
    n_events = 0
    stamp = {} if rnd is None else {"round": int(rnd)}
    if dispatch:
        emit(bus, PERF_DISPATCH_WALL,
             {"in_execution_s": float(
                 dispatch.get("in_execution_s", 0.0)),
              "gap_s": float(dispatch.get("gap_s", 0.0)),
              "gap_share": float(dispatch.get("gap_share", 0.0))},
             {"chunks": int(dispatch.get("chunks", 0)), **stamp})
        n_events += 1
    for row in phases or []:
        if not row.get("outlier"):
            continue
        emit(bus, PERF_PHASE_OUTLIER,
             {"measured_ms": float(row.get("measured_ms", 0.0)),
              "predicted_bytes": int(
                  row.get("predicted_bytes", 0)),
              "time_share": float(row.get("time_share", 0.0))},
             {"phase": row.get("phase"), **stamp})
        n_events += 1
    for d in deltas or []:
        if not d.get("regression"):
            continue
        emit(bus, PERF_REGRESSION,
             {"rounds_per_sec": float(
                 d.get("rounds_per_sec", 0.0)),
              "delta_pct": float(d.get("delta_pct", 0.0))},
             {"n": d.get("n"), "host": d.get("host"),
              "source": d.get("source"), **stamp})
        n_events += 1
    return n_events


def emit_channels_configured(bus: Bus, cfg) -> None:
    """partisan_config.erl:834-843's channel-configured event."""
    for ch in cfg.channels:
        emit(bus, CHANNEL_CONFIGURED,
             {"parallelism": ch.parallelism},
             {"channel": ch.name, "monotonic": ch.monotonic})


def distance_metrics(dist_state) -> dict:
    """Host-side view of the distance plane's measured RTT cache (the
    reference's per-peer distance map,
    partisan_pluggable_peer_service_manager.erl:1716-1737).  Accepts a
    :class:`partisan_tpu.distance.DistanceState` — hyparview carries one
    at ``state.manager.dist``; stacked :class:`DistanceService` users
    pass their sub-state."""
    node = np.asarray(dist_state.rtt_node)
    val = np.asarray(dist_state.rtt_val)
    per_node = [
        {int(p): int(v) for p, v in zip(nr, vr) if p >= 0}
        for nr, vr in zip(node, val)
    ]
    known = node >= 0
    vals = val[known]
    return {
        "per_node": per_node,
        "measured_edges": int(known.sum()),
        "mean_rtt_rounds": float(vals.mean()) if vals.size else None,
    }


def plumtree_metrics(pt_state, mode: str = "auto") -> dict:
    """Host-side view of a :class:`partisan_tpu.models.plumtree
    .PlumtreeState` (debug_get_peers/debug_get_tree analogue,
    partisan_plumtree_broadcast.erl:179-188) plus the monotone-recycle
    guard: ``recycle_nonmonotone`` counts detections of a slot recycle
    whose payload failed to dominate the store — the constraint the
    slot-epoch design depends on (models/plumtree.py epoch docs).

    ``mode`` follows :func:`connection_counts`: ``"full"`` includes the
    O(n) ``recycle_nonmonotone_nodes`` id list, ``"summary"`` replaces
    it with the flagged-node count plus the first few ids (O(1) JSON),
    and ``"auto"`` (the default) picks full below
    :data:`CONNECTION_COUNTS_FULL_MAX` nodes and summary above — a
    100k-node poll stays O(1)."""
    if mode not in ("auto", "full", "summary"):
        raise ValueError(
            f"mode {mode!r} not in ('auto', 'full', 'summary')")
    live = np.asarray(pt_state.tree_nbrs) >= 0
    pruned = np.asarray(pt_state.pruned)
    eager = live[:, None, :] & ~pruned
    nonmono = np.asarray(pt_state.nonmono)
    flagged = np.flatnonzero(nonmono)
    out = {
        "eager_degree_per_slot": (
            eager.sum(axis=(0, 2)) / max(pruned.shape[0], 1)).tolist(),
        "recycle_nonmonotone": int(nonmono.sum()),
    }
    full = mode == "full" or (mode == "auto" and nonmono.shape[0]
                              <= CONNECTION_COUNTS_FULL_MAX)
    if full:
        out["recycle_nonmonotone_nodes"] = flagged.astype(int).tolist()
    else:
        out["recycle_nonmonotone_summary"] = {
            "nodes": int(flagged.size),
            "first": flagged[:16].astype(int).tolist(),
        }
    return out


# Above this node count, connection_counts defaults to the summarized
# view: the full per_node list is O(n) JSON — ~2 MB of text at 100k —
# where the summary (min/mean/max + degree histogram) is O(1).
CONNECTION_COUNTS_FULL_MAX = 4096


def connection_counts(cluster, state, mode: str = "auto") -> dict:
    """Connection introspection (partisan_peer_service:connections/0,
    partisan_peer_connections:count/0-3 —
    partisan_peer_connections.erl:107-110).  The sim's "connections" are
    the overlay's live out-edges; per-channel counts scale each edge by
    the channel's parallelism, mirroring conn-per-(edge × channel ×
    lane) accounting.

    ``mode``: ``"full"`` includes the O(n) ``per_node`` list,
    ``"summary"`` replaces it with min/mean/max + a degree histogram
    (the health plane's binning, health.DEG_BINS), and ``"auto"`` (the
    default) picks full below :data:`CONNECTION_COUNTS_FULL_MAX` nodes
    and summary above — a 100k-node poll stays O(1) JSON."""
    if mode not in ("auto", "full", "summary"):
        raise ValueError(
            f"mode {mode!r} not in ('auto', 'full', 'summary')")
    nbrs = np.asarray(cluster.manager.neighbors(
        cluster.cfg, state.manager))
    alive = np.asarray(state.faults.alive)
    # An edge is live only if BOTH endpoints are (a crashed peer's
    # socket is gone — the conn-count-to-zero node-down signal,
    # reference :1489-1535).
    live_edge = (nbrs >= 0) & alive[:, None] & alive[np.clip(nbrs, 0, None)]
    per_node = live_edge.sum(axis=1)
    total_edges = int(per_node.sum())
    lanes = sum(c.parallelism for c in cluster.cfg.channels)
    out = {
        "total_edges": total_edges,
        "total_connections": total_edges * lanes,   # edges × channel lanes
        "fully_connected": bool(
            (per_node[alive] > 0).all()) if alive.any() else False,
    }
    full = mode == "full" or (mode == "auto"
                              and alive.shape[0] <= CONNECTION_COUNTS_FULL_MAX)
    if full:
        out["per_node"] = per_node.astype(int).tolist()
    else:
        from partisan_tpu.health import DEG_BINS

        deg_alive = per_node[alive]
        hist = np.bincount(np.clip(deg_alive, 0, DEG_BINS - 1),
                           minlength=DEG_BINS)
        out["degrees"] = {
            "min": int(deg_alive.min()) if deg_alive.size else 0,
            "mean": float(deg_alive.mean()) if deg_alive.size else 0.0,
            "max": int(deg_alive.max()) if deg_alive.size else 0,
            "hist": hist.astype(int).tolist(),
        }
    return out
