"""Round-cost meter: a jaxpr-level census of what the traced round
actually dispatches — the static half of BENCH_NOTES' corrected cost
model ("the 32k round is dozens of 2-5 ms ops paying HBM round-trips on
materialized [n, cap, .] intermediates; gathers/scatters are priced per
fetched scalar").  The r5 fused-wire-filter surgery (one packed gather
replacing ~6 cross-row gathers, 246 -> 162 ms) was guided by exactly
this model; the meter makes it a measured, gated quantity instead of a
prose estimate.

Three numbers per phase (``round.*`` named_scope key, inherited down
into cond/scan sub-jaxprs the way the profiler's trace viewer groups
them):

- **gather/scatter equation count** — each is one dispatched op on the
  relay-attached backend, the per-op tax the round pays regardless of
  size.  ``gather`` covers take/take_along_axis/fancy indexing;
  ``scatter*`` covers every ``.at[].set/add/max/min`` flavor.
- **fetched scalars** — gather output elements + scatter update
  elements: the per-fetched-scalar price of the cost model.
- **materialized [n, ., .] intermediate bytes** — output bytes of every
  equation whose result carries the node axis with rank >= 2, excluding
  pure view/layout ops (broadcast/iota/reshape/slice/...) and call
  wrappers (pjit/cond/scan — their inner equations are counted, the
  wrapper result would double-count).  This is the HBM-round-trip
  traffic a fused backend could avoid and this backend pays.

The census is static — ``jax.make_jaxpr`` over ``jax.eval_shape``
state, no device, no compile — so a 32k-config round prices in ~1 s on
CPU (``tools/profile_phases.py --cost``), and the pinned budgets in
:mod:`partisan_tpu.lint.cost_budgets` gate op-count regressions in
tier-1 exactly like the interleave budget does (the ``round-cost-
budget`` rule in rules.py).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.extend.core as jex_core

from partisan_tpu.lint.core import Program, scope_of, sub_jaxprs

# Call wrappers: the walker descends into their sub-jaxprs, so counting
# the wrapper equation's own (forwarded) outputs would double-count.
_WRAPPER_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "xla_call", "named_call",
    "custom_jvp_call", "custom_vjp_call", "custom_vjp_call_jaxpr",
    "remat", "remat2", "checkpoint", "cond", "while", "scan",
    "shard_map", "custom_partitioning",
})

# Pure view/layout primitives: XLA serves these as lazy views or fuses
# them into consumers — they do not force an HBM round-trip of their
# own.  Everything else (arithmetic, selects, concatenates, sorts,
# gathers, reductions' inputs...) counts as materialized output.
_VIEW_PRIMS = frozenset({
    "broadcast_in_dim", "iota", "reshape", "squeeze", "expand_dims",
    "slice", "rev", "copy", "stop_gradient", "convert_element_type",
    "bitcast_convert_type",
})

# Primitives whose params carry a SCALAR combinator jaxpr (the
# scatter/reduce update lambda) rather than a program body: the eqn
# itself is counted, the lambda is not walked.
_SCALAR_BODY_PRIMS = frozenset({
    "reduce", "reduce_window", "select_and_scatter",
    "select_and_scatter_add", "reduce_precision",
})


class PhaseCost(NamedTuple):
    """Static cost census for one round phase (or a whole program)."""

    gathers: int = 0        # gather-family equations
    scatters: int = 0       # scatter-family equations
    fetched: int = 0        # gather output + scatter update elements
    interm_bytes: int = 0   # materialized [n, ., .]-output bytes
    eqns: int = 0           # every equation (wrappers excluded)

    def __add__(self, other: "PhaseCost") -> "PhaseCost":
        return PhaseCost(*(a + b for a, b in zip(self, other)))

    @property
    def gather_scatter(self) -> int:
        return self.gathers + self.scatters


class Census(NamedTuple):
    phases: dict         # phase label -> PhaseCost ("-" = unphased)
    total: PhaseCost
    n: int               # the node-axis width the byte metric keyed on

    def rows(self) -> list:
        """JSON-ready per-phase rows, heaviest interm_bytes first,
        with a trailing 'total' row."""
        out = []
        order = sorted(self.phases,
                       key=lambda p: -self.phases[p].interm_bytes)
        for ph in order:
            c = self.phases[ph]
            out.append({"phase": ph, **_row(c)})
        out.append({"phase": "total", **_row(self.total)})
        return out


def _row(c: PhaseCost) -> dict:
    return {
        "gather_eqns": c.gathers, "scatter_eqns": c.scatters,
        "gather_scatter_eqns": c.gather_scatter,
        "fetched_scalars": c.fetched,
        "interm_mib": round(c.interm_bytes / 2**20, 2),
        "eqns": c.eqns,
    }


def _nbytes(aval) -> int:
    b = aval.dtype.itemsize
    for d in aval.shape:
        b *= d
    return b


def _phase_of(eqn, inherited: str) -> str:
    """The eqn's round.* named_scope segment, else the enclosing one
    (sub-jaxpr equations do not re-enter the tracing-time scope stack,
    so cond/scan bodies inherit the phase of the call site)."""
    scope = scope_of(eqn)
    if scope:
        for seg in scope.split("/"):
            if seg.startswith("round."):
                return seg
    return inherited


def census(closed_jaxpr, n: int) -> Census:
    """Walk one traced program into a per-phase :class:`PhaseCost`.

    ``n`` keys the byte metric: only outputs whose LEADING axis is the
    node axis (shape[0] == n) with rank >= 2 count — the [n, slots, .]/
    [n, cap, .] temporaries of the cost model; [n]-vectors and
    node-free tensors are noise at every scale that matters."""
    phases: dict[str, PhaseCost] = {}

    def bump(phase: str, **kw) -> None:
        cur = phases.get(phase, PhaseCost())
        phases[phase] = cur._replace(
            **{k: getattr(cur, k) + v for k, v in kw.items()})

    def walk(jaxpr, inherited: str) -> None:
        if isinstance(jaxpr, jex_core.ClosedJaxpr):
            jaxpr = jaxpr.jaxpr
        for eqn in jaxpr.eqns:
            phase = _phase_of(eqn, inherited)
            name = eqn.primitive.name
            if name not in _WRAPPER_PRIMS:
                bump(phase, eqns=1)
                if name == "gather":
                    bump(phase, gathers=1,
                         fetched=max(_nelems(eqn.outvars[0].aval), 1))
                elif name.startswith("scatter"):
                    upd = eqn.invars[2].aval if len(eqn.invars) >= 3 \
                        else eqn.outvars[0].aval
                    bump(phase, scatters=1,
                         fetched=max(_nelems(upd), 1))
                if name not in _VIEW_PRIMS:
                    for ov in eqn.outvars:
                        av = getattr(ov, "aval", None)
                        shp = getattr(av, "shape", ())
                        if len(shp) >= 2 and shp[0] == n:
                            bump(phase, interm_bytes=_nbytes(av))
            if name in _SCALAR_BODY_PRIMS or name.startswith("scatter"):
                continue   # the sub-jaxpr is a scalar combinator lambda
            for sub in sub_jaxprs(eqn.params):
                walk(sub, phase)

    walk(closed_jaxpr, "-")
    total = PhaseCost()
    for c in phases.values():
        total = total + c
    return Census(phases=phases, total=total, n=n)


def _nelems(aval) -> int:
    e = 1
    for d in aval.shape:
        e *= d
    return e


def census_program(prog: Program) -> Census:
    """Census a lint :class:`Program` (node width from its config)."""
    n = prog.cfg.n_nodes if prog.cfg is not None else -1
    return census(prog.closed_jaxpr, n)


# ---------------------------------------------------------------------------
# The 32k-config reference program (the bench round)
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# Per-device memory budget meter (the sharded-by-default flip's gate:
# ROADMAP item 2's "budget HBM per device").  Everything here is
# abstract — jax.eval_shape state + jax.make_jaxpr programs under the
# real mesh specs, no device buffer ever allocated — so the 1M-node
# census runs tier-1, CPU-only, in seconds.
# ---------------------------------------------------------------------------

def _spec_shard_factor(spec, n_shards: int) -> int:
    """How many ways a leaf is split under its PartitionSpec on the
    1-D ``nodes`` mesh: every dim entry naming a mesh axis divides the
    per-device residency by the mesh size; P() (replicated) divides by
    nothing."""
    factor = 1
    for entry in tuple(spec):
        if entry is not None:
            factor *= n_shards
    return factor


def state_memory_rows(state, specs, n_shards: int) -> list[dict]:
    """Per-PLANE per-device resident bytes of a (possibly abstract)
    ClusterState under the sharding specs — one row per top-level
    carry field, heaviest first, plus a trailing total row.  This is
    the HBM the scan carry pins for the whole run; round intermediates
    ride on top (see :func:`device_memory_census`)."""
    import jax.tree_util as jtu
    from jax.sharding import PartitionSpec

    rows = []
    total = 0
    for field in state._fields:
        leaves = jtu.tree_leaves(getattr(state, field))
        spec_leaves = jtu.tree_leaves(
            getattr(specs, field),
            is_leaf=lambda x: isinstance(x, PartitionSpec))
        if not leaves:
            continue
        if len(leaves) != len(spec_leaves):
            raise ValueError(
                f"state/spec leaf mismatch under {field!r}: "
                f"{len(leaves)} vs {len(spec_leaves)} (the sharding-"
                f"spec-completeness rule should have caught this)")
        b = 0
        for leaf, spec in zip(leaves, spec_leaves):
            b += _nbytes(leaf) // _spec_shard_factor(spec, n_shards)
        rows.append({"plane": field, "mib_per_device":
                     round(b / 2**20, 3)})
        total += b
    rows.sort(key=lambda r: -r["mib_per_device"])
    rows.append({"plane": "total",
                 "mib_per_device": round(total / 2**20, 3)})
    return rows


def resident_memory_rows(state) -> list[dict]:
    """Single-device form of :func:`state_memory_rows` (everything
    resident on the one device) — what tools/soak_report.py stamps on
    every soak so the artifact carries its HBM footprint."""
    import jax
    from jax.sharding import PartitionSpec

    specs = jax.tree.map(lambda _: PartitionSpec(), state)
    return state_memory_rows(state, specs, 1)


def _shard_map_inner(closed_jaxpr):
    """(inner_jaxpr, n_shards) of the first shard_map equation in a
    traced program (None, 0 when absent)."""
    import jax.extend.core as jex_core

    from partisan_tpu.lint.core import iter_eqns
    from partisan_tpu.lint.rules import _mesh_shards

    for eqn in iter_eqns(closed_jaxpr):
        if eqn.primitive.name == "shard_map":
            for v in eqn.params.values():
                vals = v if isinstance(v, (tuple, list)) else (v,)
                for x in vals:
                    if isinstance(x, (jex_core.Jaxpr,
                                      jex_core.ClosedJaxpr)):
                        return x, _mesh_shards(eqn)
    return None, 0


def dry_run_cfg(n: int = 1_000_000):
    """The 1M-readiness config: bench.py's capacity knobs (hyparview +
    plumtree, inbox 16, emit_compact 32, width operand) plus the
    health plane ON (the segment-local FastSV is exactly what the
    budget prices) and the scalable destination-sharded exchange."""
    from partisan_tpu.config import Config, HyParViewConfig, \
        PlumtreeConfig

    return Config(n_nodes=n, seed=1, peer_service_manager="hyparview",
                  msg_words=16, partition_mode="groups",
                  max_broadcasts=8, inbox_cap=16, emit_compact=32,
                  timer_stagger=False, width_operand=True,
                  health=10, health_ring=64,
                  sharded_exchange="all_to_all",
                  hyparview=HyParViewConfig(isolation_window_ms=25_000),
                  plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4))


def device_memory_census(cfg, model=None, n_devices: int = 8) -> dict:
    """The per-device memory card: census one SHARDED round program
    under the real mesh specs — carry-state residency by plane (what
    the scan pins in HBM for the whole run), the round's materialized
    [n_local, ·, ·] intermediate volume (the transient working set a
    fused backend could avoid), and the replicated-node-axis audit
    (unwaived findings = an O(n) regression shipped).  All abstract:
    eval_shape + make_jaxpr, no device buffers."""
    from partisan_tpu import lint
    from partisan_tpu.lint import matrix as matrix_mod
    from partisan_tpu.lint import waivers as waivers_mod
    from partisan_tpu.lint.core import trace_program

    # ONE construction for the censused state AND the audited program
    # (matrix.sharded_parts), so the two cannot silently diverge.
    sc, state, specs, body = matrix_mod.sharded_parts(
        cfg, model=model, n_devices=n_devices)
    n_shards = sc.mesh.devices.size
    prog = trace_program(f"round/memory-{cfg.n_nodes}", body, state,
                         cfg)
    rows = state_memory_rows(state, specs, n_shards)

    inner, shards = _shard_map_inner(prog.closed_jaxpr)
    n_local = cfg.n_nodes // max(shards, 1)
    interm = census(inner, n_local).total if inner is not None \
        else PhaseCost()
    rep = lint.run_programs([prog], rules=["replicated-node-axis"],
                            package_rules=[],
                            waivers=waivers_mod.WAIVERS)
    return {
        "n": cfg.n_nodes, "devices": n_shards,
        "state_mib_per_device": rows[-1]["mib_per_device"],
        "planes": rows,
        "interm_mib_per_device": round(interm.interm_bytes / 2**20, 2),
        "replicated_node_axis": {
            "findings": len(rep.findings),
            "waived": len(rep.waived),
            "fingerprints": sorted({f.fingerprint
                                    for f in rep.findings}),
        },
    }


def dry_1m_report(n: int = 1_000_000, n_devices: int = 8) -> dict:
    """``bench.py --dry-1m``: the 1M-node readiness check — census the
    1M-node sharded round on the 8-way host mesh and judge the
    per-device resident bytes against the pinned budget
    (cost_budgets.DRY_1M).  PASS = within budget AND zero unwaived
    replicated-node-axis findings."""
    from partisan_tpu.lint import cost_budgets

    card = device_memory_census(dry_run_cfg(n), n_devices=n_devices)
    budget = cost_budgets.DRY_1M
    # Scale the pinned budget to the shape the census actually ran at:
    # linearly in n (every node-axis leaf is linear in n) and
    # inversely in the device count (the residency is sharded-leaf
    # dominated — 154 of 159 MiB at the 1M/8-way pin), so a 4-way run
    # is judged against ~2x the pin instead of spuriously FAILing and
    # a 16-way run cannot hide a 2x regression behind the 8-way pin.
    budget_mib = (budget["state_mib_per_device"] * (n / budget["n"])
                  * (budget["devices"] / card["devices"]))
    within = card["state_mib_per_device"] <= budget_mib
    clean = card["replicated_node_axis"]["findings"] == 0
    card.update({
        "kind": "dry_1m",
        "budget_mib_per_device": round(budget_mib, 1),
        "within_budget": bool(within),
        "verdict": "PASS" if (within and clean) else "FAIL",
    })
    return card


def bench_cfg(n: int = 32_768, *, width_operand: bool = False):
    """The PLAIN bench config (hyparview+plumtree, planes off —
    bench.py's make_cfg capacity knobs).  Single source for everything
    that must price/measure the SAME round program: the cost census
    (`bench_round_program`) and the measured phase attribution in
    tools/perf_report.py (perfwatch reconciliation only joins cleanly
    when predicted and measured runs share one config)."""
    from partisan_tpu.config import Config, HyParViewConfig, \
        PlumtreeConfig

    return Config(n_nodes=n, seed=1, peer_service_manager="hyparview",
                  msg_words=16, partition_mode="groups",
                  max_broadcasts=8, inbox_cap=16, emit_compact=32,
                  timer_stagger=False, width_operand=width_operand,
                  hyparview=HyParViewConfig(isolation_window_ms=25_000),
                  plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4))


def bench_round_program(n: int = 32_768, *,
                        width_operand: bool = False) -> Program:
    """Trace the PLAIN bench-config round (`bench_cfg`) at ``n``
    nodes, abstractly: this is the program BENCH_NOTES' cost model
    prices and the round-11 before/after numbers quote.  No device, no
    compile.

    ``width_operand=True`` adds the bootstrap ladder's active-prefix
    masking that bench.py actually runs with (``--cost --width-op``;
    bench.py's cost card uses it) — the default stays the plain round
    the pinned acceptance baseline was measured on."""
    import jax

    from partisan_tpu.cluster import Cluster
    from partisan_tpu.lint.core import trace_program
    from partisan_tpu.models.plumtree import Plumtree

    cfg = bench_cfg(n, width_operand=width_operand)
    cl = Cluster(cfg, model=Plumtree())
    state = jax.eval_shape(cl._build_init)
    name = f"round/bench-{n}" + ("+width" if width_operand else "")
    return trace_program(name, cl._round, state, cfg)
