"""Per-channel parallelism capacity: N lanes per edge, enforced.

The reference opens ``parallelism`` TCP connections per (peer, channel)
and dispatches onto them by partition key
(partisan_peer_connections.erl:897-954); each connection is a FIFO pipe
whose throughput bounds the edge.  The tensor transport's analogue
(opt-in via ``Config.channel_capacity``):

- a message's LANE is its partition-key affinity word modulo the
  channel's ``parallelism`` (dispatch_pid's partition-key modulo),
- each (edge, channel, lane) carries at most ``lane_rate`` messages per
  round — so an edge's per-channel throughput is
  ``parallelism × lane_rate`` per round, and raising ``parallelism``
  measurably raises it,
- excess sends DEFER into a bounded per-node outbox replayed first next
  round (backpressure, per-sender FIFO preserved: outbox slots precede
  fresh emissions and ranking is stable); outbox overflow SHEDS with
  accounting (the load-shedding the reference only permits on monotonic
  channels is surfaced as an explicit counter here).

``is_fully_connected`` (partisan_peer_connections.erl:951-954 — conn
count equals Σ parallelism) transposes to liveness: the tensor transport
has no connection setup, so an edge's lanes all exist exactly when both
endpoints are alive — see :func:`fully_connected`.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import Array

from partisan_tpu import types as T
from partisan_tpu.config import Config
from partisan_tpu.ops import msg as msg_ops
from partisan_tpu.ops import plane as plane_ops


class OutboxState(NamedTuple):
    data: Array  # [n_local, OB, W] records — deferred sends (kind==0
    #              free; W = wire_words: deferred copies carry the
    #              provenance pair and birth word verbatim, so a release
    #              names its true origin/hop and keeps its emission
    #              round).  Queued-copy invariant ("planes in queues,
    #              wire at the boundary"): under Config.plane_major the
    #              outbox holds the emission's Planes struct at storage
    #              dtypes — deferred records are never interleaved or
    #              re-widened while queued.
    shed: Array  # int32 — deferred sends dropped (outbox overflow)


def enabled(cfg: Config) -> bool:
    return cfg.channel_capacity


def init(cfg: Config, comm) -> OutboxState:
    return OutboxState(
        data=msg_ops.zero_wire(cfg, (comm.n_local, cfg.outbox_cap)),
        shed=jnp.int32(0),
    )


def throttle(cfg: Config, comm, ob: OutboxState, emitted,
             *, birth_rnd: Array | None = None,
             shed_age: Array | None = None):
    """Apply per-(edge, channel, lane) capacity to this round's sends.

    Returns (outbox', emitted') where emitted' carries the outbox's
    deferred sends first (FIFO) plus as many fresh sends as capacity
    admits; the rest defer (or shed when the outbox is full).  With
    ``birth_rnd`` set (the latency plane), a third value is returned:
    the shard-local age histogram of the sends SHED at the outbox cut
    (deferred-but-kept sends are not drops — their queueing time
    surfaces in their eventual delivery age).

    ``shed_age`` (int32[C], requires ``birth_rnd``) is the backpressure
    controller's per-channel stale-shed threshold (control.shed_age):
    any record whose age has reached its channel's threshold is SHED
    before the capacity ranking — Partisan's monotonic-channel load
    shedding (partisan_peer_socket.erl:108-129) generalized per
    channel, so a pressured bulk channel drops its stalest queued
    copies instead of delivering them rounds late, while channels at
    zero pressure (threshold = +inf) never shed here."""
    par_py = [c.parallelism for c in cfg.channels]
    par = jnp.asarray(par_py, jnp.int32)
    maxpar = max(par_py)
    rate = cfg.lane_rate
    OB = cfg.outbox_cap
    n = emitted.shape[0]

    both = plane_ops.concat([ob.data, emitted], axis=1)    # [n, M, W]
    M = both.shape[1]
    valid = both[..., T.W_KIND] != 0
    ch = jnp.clip(both[..., T.W_CHANNEL].astype(jnp.int32), 0,
                  cfg.n_channels - 1)
    stale = None
    if shed_age is not None:
        from partisan_tpu import latency as latency_mod

        assert birth_rnd is not None, \
            "shed_age needs birth_rnd (the latency plane's ages)"
        stale = valid & (latency_mod.ages(both, birth_rnd)
                         >= shed_age[ch])
        valid = valid & ~stale
    lane = (both[..., T.W_LANE] & 0x7FFFFFFF) % par[ch]
    dst = jnp.maximum(both[..., T.W_DST], 0)
    key = (dst * cfg.n_channels + ch) * maxpar + lane
    key = jnp.where(valid, key, -1)

    # Rank among same-key sends, stable by slot (outbox first = FIFO).
    # Sort-based: a stable per-row argsort groups equal keys while
    # preserving slot order, so rank = offset from the run start.  (The
    # obvious [n, M, M] pairwise-comparison matrix is ~1 GB of bools at
    # 100k nodes with M ≈ 100 — the round-2 judge's flagged cost.)
    m_idx = jnp.arange(M, dtype=jnp.int32)
    order = jnp.argsort(key, axis=1, stable=True)
    skey = jnp.take_along_axis(key, order, axis=1)
    is_start = jnp.concatenate(
        [jnp.ones((n, 1), bool), skey[:, 1:] != skey[:, :-1]], axis=1)
    run_start = jax.lax.cummax(
        jnp.where(is_start, m_idx[None, :], 0), axis=1)
    rank_sorted = m_idx[None, :] - run_start
    # `order` is a per-row argsort permutation — indices are unique by
    # construction, so the un-permuting scatter is race-free
    rank = jnp.zeros((n, M), jnp.int32).at[
        jnp.arange(n)[:, None], order].set(rank_sorted,
                                           unique_indices=True)
    budget = rate * jnp.ones((), jnp.int32)
    send_now = valid & (rank < budget)
    defer = valid & ~send_now

    out = both.at[..., T.W_KIND].set(
        jnp.where(send_now, both[..., T.W_KIND], 0))

    # Compact deferred sends into the outbox (slot order = FIFO): slot
    # s takes the s-th deferred record — ONE dtype-grouped fill-gather
    # over the sorted defer indices instead of W per-plane scatters
    # (the round-cost meter's coalescing rule; empty slots fill 0).
    drank = jnp.cumsum(defer, axis=1) - 1
    keep = defer & (drank < OB)
    pos = jnp.sort(jnp.where(keep, m_idx[None, :], M), axis=1)[:, :OB]
    new_data = plane_ops.take_rows(both, pos, fill=True)
    cut = defer & ~keep
    if stale is not None:
        # backpressure sheds join the outbox-cut accounting: same cut
        # site, same cause row (CAUSE_OUTBOX) in metrics and latency
        cut = cut | stale
    shed = comm.allsum(jnp.sum(cut, dtype=jnp.int32))
    ob_out = OutboxState(data=new_data, shed=ob.shed + shed)
    if birth_rnd is None:
        return ob_out, out
    from partisan_tpu import latency as latency_mod

    return ob_out, out, latency_mod.age_hist(both, cut, birth_rnd)


def shed_delta(before: OutboxState, after: OutboxState) -> Array:
    """int32: sends SHED at the outbox cut site this round (the
    cause-tagged accounting the metrics plane records as
    ``outbox_shed``).  ``shed`` is cumulative and already
    ``comm.allsum``-reduced inside :func:`throttle`, so the delta is
    replicated under sharding.  Deferred-but-kept sends are NOT drops —
    they deliver later and surface as the metrics plane's transient
    ``other`` residual."""
    return after.shed - before.shed


def fully_connected(cfg: Config, alive: Array) -> Array:
    """bool[n, n]: every configured lane of every channel between i and
    j is up.  In the tensor transport, lanes have no setup phase — the
    Σ-parallelism connection count of the reference's
    ``is_fully_connected`` holds exactly when both endpoints are alive
    (a crash severs all of a node's connections at once, the TCP-EXIT
    analogue)."""
    return alive[:, None] & alive[None, :]
