"""Rumor-mongering tests (protocols/demers_rumor_mongering.erl):
infect-and-die spread over full-mesh and hyparview overlays."""

import numpy as np

from partisan_tpu.cluster import Cluster
from partisan_tpu.models.rumor_mongering import RumorMongering

from support import boot_fullmesh, fm_config, hv_config, staggered_join


def test_rumor_spreads_over_fullmesh():
    cfg = fm_config(32, seed=23)
    model = RumorMongering()
    cl = Cluster(cfg, model=model)
    st = boot_fullmesh(cl)
    st = st._replace(model=model.broadcast(st.model, node=5, slot=0))
    st = cl.steps(st, 30)
    cov = float(model.coverage(st.model, st.faults.alive, 0))
    # Infect-and-die with fanout k converges to the y = 1 - e^(-k*y)
    # fixed point (~0.80 for k=2), NOT full coverage — which is why the
    # reference pairs it with anti-entropy for the tail.
    assert 0.5 <= cov < 1.0, cov
    # Each node forwarded at most once: pending fully drained.
    assert not np.asarray(st.model.pending).any()


def test_rumor_duplicates_do_not_reinfect():
    cfg = fm_config(16, seed=3)
    model = RumorMongering()
    cl = Cluster(cfg, model=model)
    st = boot_fullmesh(cl)
    st = st._replace(model=model.broadcast(st.model, node=0, slot=1))
    st = cl.steps(st, 20)
    pend_a = np.asarray(st.model.pending).sum()
    st = cl.steps(st, 20)
    pend_b = np.asarray(st.model.pending).sum()
    assert pend_a == 0 and pend_b == 0


def test_rumor_over_hyparview():
    cfg = hv_config(32, seed=41)
    model = RumorMongering()
    cl = Cluster(cfg, model=model)
    st = staggered_join(cl, cl.init())
    st = cl.steps(st, 50)
    st = st._replace(model=model.broadcast(st.model, node=9, slot=0))
    st = cl.steps(st, 40)
    cov = float(model.coverage(st.model, st.faults.alive, 0))
    assert cov >= 0.5, cov
