"""Health-plane suite (health.py + cluster.round_body snapshots):

- the device pointer-jumping component counter matches the numpy BFS
  oracle (tests/support.components) on dozens of randomized overlays
  (support.ORACLE_TRIALS sizes the sweep; PARTISAN_TEST_FULL=1 restores
  the original >= 50),
  including faulted (crashed nodes) and group-partitioned ones — the
  acceptance invariant,
- symmetry-violation and isolation counts match brute-force numpy,
- churn counters reconcile with telemetry.emit_membership_events'
  up/down diffs over the same window,
- the disabled flag keeps the ClusterState leaf an empty pytree and an
  enabled plane is READ-ONLY (identical non-health evolution),
- digest bit packing roundtrips,
- sharded runs record bit-identical rings (skips on jax<shard_map).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from partisan_tpu import health as health_mod
from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config
from tests import support


_N, _K = 200, 7   # ONE padded device shape for every random overlay —
#                   55+ trials share two compiled programs; variation
#                   rides the content (dead pad rows, -1 pad slots)


def _random_overlay(rng, n, k):
    """Random directed neighbor table and alive mask at logical size
    (n, k), PADDED to the fixed device shape (_N, _K): rows >= n are
    dead, slots >= k are -1 — identical component structure, no
    per-trial recompile."""
    nbrs = np.full((_N, _K), -1, np.int32)
    nbrs[:n, :k] = rng.integers(-1, n, size=(n, k))
    # no self edges (managers never hold their own id)
    ids = np.arange(_N, dtype=np.int32)[:, None]
    nbrs = np.where(nbrs == ids, -1, nbrs)
    alive = np.zeros(_N, bool)
    alive[:n] = rng.random(n) > rng.uniform(0.0, 0.4)
    return nbrs, alive


def test_component_count_matches_bfs_oracle_on_random_overlays():
    """Randomized overlays — sparse, dense, heavily faulted and
    group-partitioned — must agree EXACTLY with the host BFS oracle
    (support.ORACLE_TRIALS sizes the sweep)."""
    rng = np.random.default_rng(42)
    count = jax.jit(lambda nb, al: health_mod.component_count(nb, al)[1])
    count_p = jax.jit(
        lambda nb, al, p: health_mod.component_count(nb, al, p)[1])
    from support import ORACLE_TRIALS

    checked = 0
    for trial in range(ORACLE_TRIALS):
        n = int(rng.integers(2, _N + 1))
        k = int(rng.integers(1, _K + 1))
        nbrs, alive = _random_overlay(rng, n, k)
        got = int(count(jnp.asarray(nbrs), jnp.asarray(alive)))
        want = len(support.components(nbrs, alive))
        assert got == want, (trial, n, k, got, want)
        checked += 1
    # group-partitioned overlays: the partition severs cross-group
    # edges exactly like faults.edge_cut's static component
    for trial in range(max(10, ORACLE_TRIALS // 3)):
        n = int(rng.integers(4, 128))
        k = int(rng.integers(1, 6))
        nbrs, alive = _random_overlay(rng, n, k)
        part = rng.integers(0, int(rng.integers(2, 5)),
                            size=_N).astype(np.int32)
        got = int(count_p(jnp.asarray(nbrs), jnp.asarray(alive),
                          jnp.asarray(part)))
        want = len(support.components(nbrs, alive, partition=part))
        assert got == want, (trial, n, k, got, want)
        checked += 1
    # adversarial worst case for label propagation: a path graph (the
    # min label must travel the full diameter — naive relax-and-jump
    # creeps O(n) here; FastSV hooking converges in O(log n))
    for n in (2, 63, _N):
        nbrs = np.full((_N, _K), -1, np.int32)
        nbrs[1:n, 0] = np.arange(n - 1)
        alive = np.zeros(_N, bool)
        alive[:n] = True
        assert int(count(jnp.asarray(nbrs), jnp.asarray(alive))) == 1
        # cut the middle: two components
        alive[n // 2] = False
        got = int(count(jnp.asarray(nbrs), jnp.asarray(alive)))
        assert got == len(support.components(nbrs, alive)), n
        checked += 1
    assert checked >= ORACLE_TRIALS + 13


def test_symmetry_and_isolation_brute_force_parity():
    rng = np.random.default_rng(7)
    sym = jax.jit(lambda nb, al: health_mod.symmetry_violations(nb, al))
    deg = jax.jit(lambda nb, al: health_mod.out_degrees(nb, al))
    for trial in range(20):
        n = int(rng.integers(2, 96))
        k = int(rng.integers(1, 6))
        nbrs, alive = _random_overlay(rng, n, k)
        # brute force
        want_sym = 0
        want_deg = np.zeros(_N, int)
        for i in range(_N):
            if not alive[i]:
                continue
            for j in nbrs[i]:
                j = int(j)
                if j < 0 or not alive[j]:
                    continue
                want_deg[i] += 1
                if i not in set(int(x) for x in nbrs[j]):
                    want_sym += 1
        assert int(sym(jnp.asarray(nbrs), jnp.asarray(alive))) \
            == want_sym, trial
        got_deg = np.asarray(deg(jnp.asarray(nbrs), jnp.asarray(alive)))
        assert (got_deg == want_deg).all(), trial
        want_iso = int((alive & (want_deg == 0)).sum())
        hist = np.asarray(health_mod.degree_histogram(
            jnp.asarray(got_deg), jnp.asarray(alive)))
        assert hist[0] == want_iso, trial
        assert hist.sum() == alive.sum(), trial


def _hv_health_run(n=48, health=5, seed=3):
    cfg = support.hv_config(n, seed=seed, health=health, health_ring=64)
    cl = Cluster(cfg)
    return cfg, cl, support.boot_hyparview(cl)


def test_end_to_end_snapshot_matches_oracle_on_booted_overlay():
    """The in-round snapshot (gathered manager.neighbors + wire-stage
    alive) agrees with the oracle on the final state, including after
    crashes.  Stepping is aligned so the LAST snapshot (taken at round
    r with (r+1) % health == 0, on the post-transition state) describes
    exactly the final visible state."""
    cfg, cl, st = _hv_health_run()              # rnd 64 after boot
    st = cl.steps(st, 6)                        # rnd 70; snapshot at 69
    snap = health_mod.snapshot(st.health)
    act = np.asarray(st.manager.active)
    alive = np.asarray(st.faults.alive)
    assert snap["rounds"][-1] == int(st.rnd) - 1
    assert snap["components"][-1] == len(support.components(act, alive))
    # crash a third of the overlay and re-align one cadence
    victims = np.arange(3, 48, 3)
    al = st.faults.alive.at[jnp.asarray(victims)].set(False)
    st = st._replace(faults=st.faults._replace(alive=al))
    st = cl.steps(st, cfg.health)               # rnd 75; snapshot at 74
    snap = health_mod.snapshot(st.health)
    act = np.asarray(st.manager.active)
    alive = np.asarray(st.faults.alive)
    assert snap["components"][-1] == len(support.components(act, alive))
    # the dead third shows up as downs in the last churn window
    assert snap["downs"][-1] == len(victims)


def test_digest_pack_roundtrip():
    rng = np.random.default_rng(5)
    for _ in range(64):
        comps = int(rng.integers(0, 1 << 18))
        iso = int(rng.integers(0, 300))
        dmin = int(rng.integers(0, 9))
        n_alive = int(rng.integers(0, 1000))
        target = int(rng.integers(1, 5))
        cov = bool(rng.integers(0, 2))
        w = int(health_mod.pack_digest(
            jnp.int32(comps), jnp.int32(iso), jnp.int32(dmin),
            jnp.int32(n_alive), target, jnp.bool_(cov)))
        assert w > 0                      # int32-positive (bit 31 free)
        d = health_mod.decode_digest(w)
        assert d["valid"]
        assert d["one_component"] == (comps == 1)
        assert d["no_isolates"] == (iso == 0)
        assert d["min_degree_ok"] == (dmin >= target and n_alive > 0)
        assert d["coverage_complete"] == cov
        assert d["components"] == min(comps, 0xFFFF)
        assert d["isolated"] == min(iso, 0x7F)
        assert health_mod.healthy(w) == (
            d["one_component"] and d["no_isolates"]
            and d["min_degree_ok"] and cov)
        assert health_mod.digest_converged(w) == cov
        assert health_mod.digest_components(w) == min(comps, 0xFFFF)
    assert health_mod.decode_digest(0)["valid"] is False
    assert not health_mod.digest_converged(0)


def test_disabled_flag_zero_overhead_pytree():
    """health=0 (the default) must keep the state leaf an empty () —
    no arrays, no ring, no digest."""
    cl = Cluster(Config(n_nodes=16, seed=1))
    st = cl.init()
    assert st.health == ()
    assert len(jax.tree.leaves(st.health)) == 0
    st2 = cl.steps(st, 5)
    assert st2.health == ()
    assert health_mod.digest(st2) == 0


def test_health_plane_is_read_only():
    """Enabling the plane must not perturb the simulation: every
    non-health leaf of a health=K run equals the health=0 run's, bit
    for bit (the Config(health=0) bit-identity acceptance criterion's
    converse — the observatory only watches)."""
    def drive(health):
        cfg = support.hv_config(32, seed=11, health=health)
        cl = Cluster(cfg)
        st = support.boot_hyparview(cl, settle=20)
        al = st.faults.alive.at[5].set(False)
        st = st._replace(faults=st.faults._replace(alive=al))
        return cl.steps(st, 10)

    st_off = drive(0)
    st_on = drive(5)
    assert st_off.health == ()
    assert st_on.health != ()
    for name in ("rnd", "manager", "model", "inbox", "stats", "faults"):
        a = jax.tree.leaves(getattr(st_off, name))
        b = jax.tree.leaves(getattr(st_on, name))
        assert len(a) == len(b), name
        for x, y in zip(a, b):
            assert np.array_equal(np.asarray(x), np.asarray(y)), name


def test_churn_reconciles_with_membership_events():
    """The device up/down window counters equal the host-side
    telemetry.emit_membership_events up/down event counts over the same
    window (both diff the alive mask at the window edges)."""
    from partisan_tpu import telemetry

    cfg, cl, st = _hv_health_run(health=10)
    rec = telemetry.Recorder()
    bus = telemetry.Bus()
    bus.attach("t", ("partisan", "membership", "peer"), rec)
    prev = st
    # window 1: two crashes; window 2: one recovery
    al = st.faults.alive.at[jnp.asarray([4, 9])].set(False)
    st = cl.steps(st._replace(faults=st.faults._replace(alive=al)), 10)
    telemetry.emit_membership_events(bus, cfg, cl.manager, prev, st)
    prev = st
    al = st.faults.alive.at[4].set(True)
    st = cl.steps(st._replace(faults=st.faults._replace(alive=al)), 10)
    telemetry.emit_membership_events(bus, cfg, cl.manager, prev, st)
    snap = health_mod.snapshot(st.health)
    assert snap["downs"][-2] == len(rec.of(telemetry.PEER_DOWN)) == 2
    assert snap["ups"][-1] == len(rec.of(telemetry.PEER_UP)) == 1
    assert snap["downs"][-1] == 0 and snap["ups"][-2] == 0


def test_first_snapshot_reports_zero_churn():
    """Churn is a BETWEEN-snapshots diff: the first snapshot only
    establishes the baseline, so a fault-free run never reports
    spurious ups/joins (and replay_health_events never fires a bogus
    churn event) for nodes alive since round 0."""
    from partisan_tpu import telemetry

    cfg = support.hv_config(24, seed=4, health=5)
    cl = Cluster(cfg)
    st = cl.steps(cl.init(), 10)        # no joins yet: nothing changes
    snap = health_mod.snapshot(st.health)
    for name in ("ups", "downs", "joins", "leaves"):
        assert snap[name][0] == 0, (name, snap[name])
    assert (snap["ups"] == 0).all() and (snap["downs"] == 0).all()
    rec = telemetry.Recorder()
    bus = telemetry.Bus()
    bus.attach("t", ("partisan", "health", "churn"), rec)
    assert telemetry.replay_health_events(bus, snap) == 0
    assert rec.events == []


def test_all_dead_cluster_digest_not_converged():
    """The digest's coverage bit must agree with the legacy poll on a
    fully-crashed cluster: coverage reads 0.0 there, not vacuous
    success."""
    from partisan_tpu.models.anti_entropy import AntiEntropy

    cfg = Config(n_nodes=8, seed=2, inbox_cap=32, health=5,
                 health_ring=16)
    model = AntiEntropy()
    cl = Cluster(cfg, model=model)
    st = cl.init()
    st = st._replace(model=model.broadcast(st.model, 0, 0))
    st = st._replace(faults=st.faults._replace(
        alive=jnp.zeros(8, jnp.bool_)))
    st = cl.steps(st, 10)
    w = health_mod.digest(st)
    assert health_mod.decode_digest(w)["valid"]
    assert not health_mod.digest_converged(w)
    assert float(model.coverage(st.model, st.faults.alive, 0)) == 0.0


def test_symmetry_slotwise_path_matches_oneshot():
    """Wide neighbor tables (scamp/fullmesh) take the O(n·K)-memory
    slot-wise path; it must agree exactly with the one-shot gather."""
    rng = np.random.default_rng(3)
    nbrs, alive = _random_overlay(rng, 96, 6)
    want = int(health_mod.symmetry_violations(
        jnp.asarray(nbrs), jnp.asarray(alive)))
    orig = health_mod.SYM_ONESHOT_ELEMS
    try:
        health_mod.SYM_ONESHOT_ELEMS = 1     # force the fori_loop path
        got = int(health_mod.symmetry_violations(
            jnp.asarray(nbrs), jnp.asarray(alive)))
    finally:
        health_mod.SYM_ONESHOT_ELEMS = orig
    assert got == want


def test_digest_coverage_bit_tracks_model_coverage():
    """The digest folds the model's slot-0 coverage in: set once every
    alive node holds the broadcast — what scenarios._converge polls."""
    from partisan_tpu.models.anti_entropy import AntiEntropy

    cfg = Config(n_nodes=16, seed=1, inbox_cap=32, health=5,
                 health_ring=32)
    model = AntiEntropy()
    cl = Cluster(cfg, model=model)
    st = cl.init()
    m = st.manager
    for i in range(1, 16):
        m = cl.manager.join(cfg, m, i, 0)
    st = cl.steps(st._replace(manager=m), 20)
    w = health_mod.digest(st)
    assert not health_mod.digest_converged(w)     # nothing broadcast
    st = st._replace(model=model.broadcast(st.model, 0, 0))
    for _ in range(12):                           # poll like _converge
        st = cl.steps(st, 10)
        if health_mod.digest_converged(health_mod.digest(st)):
            break
    assert health_mod.digest_converged(health_mod.digest(st))
    cov = float(model.coverage(st.model, st.faults.alive, 0))
    assert cov == 1.0


def test_snapshot_cadence_and_ring_wraparound():
    """Snapshots land every `health` rounds at (rnd+1) % health == 0
    and the ring keeps the most recent window once it wraps."""
    cfg = support.hv_config(24, seed=2, health=4, health_ring=6)
    cl = Cluster(cfg)
    st = support.boot_hyparview(cl, settle=40)   # rnd = 12*2 + 40 = 52
    snap = health_mod.snapshot(st.health)
    rnds = snap["rounds"].tolist()
    assert len(rnds) == 6                        # ring full
    assert rnds == [31, 35, 39, 43, 47, 51]     # last 6 cadence points
    # latest digest scalar equals the last ring entry
    assert health_mod.digest(st) == int(snap["digests"][-1])


def test_health_state_is_scan_carry_no_callbacks():
    """No host transfer inside the scan: the health ring rides the
    lax.scan carry (shared lint rules — see tests/support.py)."""
    cfg = support.hv_config(16, seed=1, health=2, health_ring=8)
    cl = Cluster(cfg)
    st = cl.init()
    support.assert_scan_lint_clean(cl, st, 8)
    out = cl.steps(st, 8)
    assert health_mod.snapshot(out.health)["rounds"].tolist() == [1, 3, 5, 7]


def test_sharded_health_ring_matches_single_device():
    """Placement invariance: the same run on 1 device and on a mesh
    records bit-identical health rings (snapshots derive from the
    all-gathered global graph on every shard)."""
    if not hasattr(jax, "shard_map"):
        pytest.skip("jax.shard_map unavailable on this jax "
                    "(parallel/sharded.py requires it)")
    from partisan_tpu.models.anti_entropy import AntiEntropy
    from partisan_tpu.parallel.sharded import ShardedCluster, make_mesh

    cfg = Config(n_nodes=16, seed=3, inbox_cap=24, health=3,
                 health_ring=32)

    def drive(cl):
        st = cl.init()
        m = st.manager
        for i in range(1, 16):
            m = cl.manager.join(cfg, m, i, 0)
        st = cl.steps(st._replace(manager=m), 10)
        st = st._replace(model=cl.model.broadcast(st.model, 0, 0))
        alive = st.faults.alive.at[7].set(False)
        st = st._replace(faults=st.faults._replace(alive=alive))
        return cl.steps(st, 30)

    st_l = drive(Cluster(cfg, model=AntiEntropy()))
    st_s = drive(ShardedCluster(cfg, make_mesh(), model=AntiEntropy()))
    snap_l = health_mod.snapshot(st_l.health)
    snap_s = health_mod.snapshot(st_s.health)
    for name, series in snap_l.items():
        assert np.array_equal(series, snap_s[name]), name
    assert health_mod.digest(st_l) == health_mod.digest(st_s)
    # and the run recorded real snapshots with the crash visible
    assert snap_l["rounds"].size > 0
    assert snap_l["downs"].sum() == 1


def test_width_operand_masks_inactive_prefix_rows():
    """Under Config.width_operand, inactive rows are invisible to the
    observatory: a prefix-activated run snapshots the same topology
    series as a native-width run (the prefix-dynamics contract of
    tests/test_program_budget.py, extended to the health plane)."""
    from partisan_tpu import cluster as cluster_mod

    def boot(cl, n):
        st = cl.init()
        if cl.cfg.width_operand:
            st = cluster_mod.activate(st, n)
        for base in range(1, n, 4):
            m = st.manager
            for i in range(base, min(base + 4, n)):
                m = cl.manager.join(cl.cfg, m, i, 0)
            st = cl.steps(st._replace(manager=m), 2)
        return cl.steps(st, 20)

    n = 24
    cfg_n = support.hv_config(n, seed=6, health=4, health_ring=16)
    st_n = boot(Cluster(cfg_n), n)
    cfg_w = support.hv_config(2 * n, seed=6, health=4, health_ring=16,
                              width_operand=True)
    st_w = boot(Cluster(cfg_w), n)
    snap_n = health_mod.snapshot(st_n.health)
    snap_w = health_mod.snapshot(st_w.health)
    for name in ("rounds", "components", "isolated", "deg_min",
                 "deg_max", "sym_violations", "joins", "leaves", "ups",
                 "downs", "deg_hist"):
        assert np.array_equal(snap_n[name], snap_w[name]), name
