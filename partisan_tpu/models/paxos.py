"""Single-decree Paxos, vectorized — the in-repo consensus
application-under-test.

The reference hosts external consensus apps under its PropEr harness
(test/prop_partisan_paxoid.erl:385 drives the paxoid app with
ledger-convergence postconditions under the crash fault model,
prop_partisan_crash_fault_model.erl:33-37).  Those BEAM apps cannot run
in this image, so this model fills the role in-repo: classic
single-decree Paxos (Synod), every node a proposer + acceptor +
learner, stepped for all nodes at once over ``[n_local, slots]``
decree state (one slot per independent decree, the commit-engine slot
convention).

Protocol (the Synod rules):

- ``propose`` starts phase 1: the proposer picks a ballot unique to it
  (``attempt * n + id + 1``) and fans out PREPARE,
- an acceptor receiving PREPARE(b) with b > promised re-promises and
  answers PROMISE(b) carrying its highest accepted (ballot, value);
  lower ballots are ignored (the proposer's retry re-arms),
- on a quorum of promises the proposer enters phase 2 with the value of
  the highest accepted ballot seen (or its own if none) and fans out
  ACCEPT(b, v),
- an acceptor receiving ACCEPT(b, v) with b >= promised accepts
  (promised = accepted = b) and answers ACCEPTED(b),
- on a quorum of ACCEPTED the proposer DECIDES and fans out DECIDE(v)
  to the learners; a proposer stuck in either phase past its (id-
  jittered) retry window re-runs phase 1 with a higher ballot.

Fan-outs are edge-triggered (emitted once per phase entry) so omission
faults have real consequences, and acceptor state is monotonic in the
ballot order — the safety core Paxos rests on.  ``quorum`` defaults to
majority; passing a smaller value deliberately breaks the
quorum-intersection property (two disjoint "quorums" can decide
different values) — the weakened-invariant canary the property harness
must catch and shrink (tests/test_paxos.py).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import Array

from partisan_tpu import types as T
from partisan_tpu.comm import LocalComm
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import msg as msg_ops
from partisan_tpu.ops import plane as plane_ops

# APP payload layout: [op, slot, ballot, value, aux]
OP_PREPARE = 30
OP_PROMISE = 31     # aux = accepted ballot; value = accepted value (-1)
OP_ACCEPT = 32
OP_ACCEPTED = 33
OP_DECIDE = 34

# proposer phases
P_IDLE = 0
P_PREPARING = 1
P_ACCEPTING = 2


class PaxosState(NamedTuple):
    # acceptor [n, S]
    a_promised: Array   # int32 — highest promised ballot (0 = none)
    a_ballot: Array     # int32 — accepted ballot (0 = none)
    a_value: Array      # int32 — accepted value (-1 = none)
    # proposer [n, S]
    p_phase: Array      # int32 — P_IDLE / P_PREPARING / P_ACCEPTING
    p_ballot: Array     # int32 — current ballot
    p_value: Array      # int32 — own proposed value
    p_chosen: Array     # int32 — phase-2 value (highest-accepted or own)
    p_prom: Array       # bool[n, S, NG] — acceptors who PROMISEd
    #                     p_ballot.  Per-acceptor bits, not a counter:
    #                     a duplicated PROMISE (e.g. paxos traffic over
    #                     the at-least-once acked lane, or a duplicating
    #                     interposition) must not fake a quorum.
    p_hib: Array        # int32 — highest accepted ballot among promises
    p_hiv: Array        # int32 — its value
    p_acc: Array        # bool[n, S, NG] — acceptors who ACCEPTED p_ballot
    p_t0: Array         # int32 — round of phase entry (retry base)
    p_sent: Array       # bool — current phase's fan-out already emitted
    p_won: Array        # int32[n, S] — value this node CHOSE as the
    #                     winning proposer (-1 = none; first win kept) —
    #                     agreement is judged over chosen values, not
    #                     just learned ones (a learner keeps its first
    #                     DECIDE, which would mask a chosen-value split)
    won_conflict: Array # bool[n, S] — sticky: this proposer won the
    #                     same decree twice with DIFFERENT values (a
    #                     keep-first p_won alone would mask it)
    decided: Array      # int32[n, S] — learned decree value (-1 = none)


class Paxos:
    """slots independent decrees; quorum defaults to majority."""

    name = "paxos"

    def __init__(self, slots: int = 2, quorum: int | None = None,
                 retry_rounds: int = 8,
                 unsafe_adopt: bool = False) -> None:
        self.slots = slots
        self.quorum = quorum
        self.retry_rounds = retry_rounds
        # Planted bug for the property harness: ignore the
        # highest-accepted value reported by promises and always push
        # the proposer's own value — breaks the Synod adoption rule, so
        # a later ballot can choose a different value than an earlier
        # chosen one (caught + shrunk in tests/test_paxos.py).
        self.unsafe_adopt = unsafe_adopt

    def _quorum(self, cfg: Config) -> int:
        return self.quorum if self.quorum is not None \
            else cfg.n_nodes // 2 + 1

    def init(self, cfg: Config, comm: LocalComm) -> PaxosState:
        if T.payload_words(cfg.msg_words) < 5:
            raise ValueError("paxos needs msg_words >= 13 "
                             "(payload [op, slot, ballot, value, aux])")
        n, s = comm.n_local, self.slots
        zi = jnp.zeros((n, s), jnp.int32)
        zb = jnp.zeros((n, s, comm.n_global), jnp.bool_)
        return PaxosState(
            a_promised=zi, a_ballot=zi, a_value=jnp.full((n, s), -1,
                                                         jnp.int32),
            p_phase=zi, p_ballot=zi, p_value=zi, p_chosen=zi,
            p_prom=zb, p_hib=zi, p_hiv=zi, p_acc=zb, p_t0=zi,
            p_sent=jnp.zeros((n, s), jnp.bool_),
            p_won=jnp.full((n, s), -1, jnp.int32),
            won_conflict=jnp.zeros((n, s), jnp.bool_),
            decided=jnp.full((n, s), -1, jnp.int32))

    # ------------------------------------------------------------------
    def step(self, cfg: Config, comm: LocalComm, st: PaxosState,
             ctx: RoundCtx, nbrs: Array) -> tuple[PaxosState, Array]:
        n, S = st.p_phase.shape
        NG = comm.n_global
        Q = self._quorum(cfg)
        gids = comm.local_ids()
        alive = ctx.alive
        inb = ctx.inbox.data
        is_app = (inb[..., T.W_KIND] == T.MsgKind.APP) & alive[:, None]
        op = jnp.where(is_app, inb[..., T.P0], -1)          # [n, cap]
        mslot = inb[..., T.P1]
        mbal = inb[..., T.P2]
        mval = inb[..., T.P3]
        maux = inb[..., T.P3 + 1]
        msrc = inb[..., T.W_SRC]
        # decree-aligned masks: [n, S, cap]
        sl = jnp.arange(S, dtype=jnp.int32)
        on_slot = mslot[:, None, :] == sl[None, :, None]

        def per_slot(opk):
            return (op[:, None, :] == opk) & on_slot

        NEG = jnp.iinfo(jnp.int32).min

        # Within-round serialization: ACCEPTs are processed BEFORE
        # PREPAREs, and each PROMISE reports the post-accept state.  A
        # promise that omitted a same-round accept would let the new
        # proposer choose a fresh value while this acceptor's ACCEPTED
        # completes the old ballot's quorum — a quorum-intersection
        # violation (the Synod promise must cover every accept the
        # acceptor has performed).

        # ---- acceptor: ACCEPT(b >= promised) -> accept + ACCEPTED -----
        m_acc = per_slot(OP_ACCEPT) \
            & (mbal[:, None, :] >= st.a_promised[:, :, None])
        acc_bal = jnp.where(m_acc, mbal[:, None, :], NEG)
        acc_max = jnp.max(acc_bal, axis=2)
        acc_any = acc_max > NEG
        awho = jnp.argmax(acc_bal, axis=2)
        acc_src = jnp.take_along_axis(
            jnp.broadcast_to(msrc[:, None, :], acc_bal.shape), awho[:, :, None],
            axis=2)[:, :, 0]
        acc_val = jnp.take_along_axis(
            jnp.broadcast_to(mval[:, None, :], acc_bal.shape), awho[:, :, None],
            axis=2)[:, :, 0]
        accepted_msg = msg_ops.build(
            cfg, T.MsgKind.APP, gids[:, None],
            jnp.where(acc_any, acc_src, -1),
            payload=(jnp.full((n, S), OP_ACCEPTED),
                     jnp.broadcast_to(sl[None, :], (n, S)),
                     jnp.maximum(acc_max, 0), acc_val, 0))
        promised_mid = jnp.maximum(st.a_promised, jnp.maximum(acc_max, 0))
        a_ballot = jnp.where(acc_any, acc_max, st.a_ballot)
        a_value = jnp.where(acc_any, acc_val, st.a_value)

        # ---- acceptor: PREPARE -> re-promise + PROMISE the max --------
        m_prep = per_slot(OP_PREPARE)
        prep_bal = jnp.where(m_prep, mbal[:, None, :], NEG)
        prep_max = jnp.max(prep_bal, axis=2)                 # [n, S]
        prep_win = prep_max > promised_mid
        who = jnp.argmax(prep_bal, axis=2)                   # [n, S]
        prep_src = jnp.take_along_axis(
            jnp.broadcast_to(msrc[:, None, :], prep_bal.shape), who[:, :, None],
            axis=2)[:, :, 0]
        promise = msg_ops.build(
            cfg, T.MsgKind.APP, gids[:, None],
            jnp.where(prep_win, prep_src, -1),
            payload=(jnp.full((n, S), OP_PROMISE),
                     jnp.broadcast_to(sl[None, :], (n, S)),
                     jnp.maximum(prep_max, 0), a_value, a_ballot))

        a_promised = jnp.maximum(promised_mid, jnp.maximum(prep_max, 0))

        # ---- proposer: collect PROMISE / ACCEPTED ---------------------
        all_ids = jnp.arange(NG, dtype=jnp.int32)
        m_prom = per_slot(OP_PROMISE) \
            & (mbal[:, None, :] == st.p_ballot[:, :, None]) \
            & (st.p_phase == P_PREPARING)[:, :, None]
        # fold message sources into per-acceptor bits (quorum counts
        # DISTINCT acceptors — duplicate delivery cannot inflate it).
        # One scatter per mask: no [n, S, cap, NG] one-hot expansion
        # (duplicate .set writes all carry True — order-independent).
        r3 = jnp.broadcast_to(
            jnp.arange(n, dtype=jnp.int32)[:, None, None], m_prom.shape)
        s3 = jnp.broadcast_to(sl[None, :, None], m_prom.shape)
        src3 = jnp.broadcast_to(msrc[:, None, :], m_prom.shape)

        def fold_bits(bits, mask):
            return bits.at[r3, s3, jnp.where(mask, src3, NG)].set(
                True, mode="drop")

        p_prom = fold_bits(st.p_prom, m_prom)
        nprom = jnp.sum(p_prom, axis=2, dtype=jnp.int32)
        # highest accepted (ballot, value) among this round's promises
        pr_ab = jnp.where(m_prom, maux[:, None, :], NEG)
        pr_hib = jnp.max(pr_ab, axis=2)
        pwho = jnp.argmax(pr_ab, axis=2)
        pr_hiv = jnp.take_along_axis(
            jnp.broadcast_to(mval[:, None, :], pr_ab.shape), pwho[:, :, None],
            axis=2)[:, :, 0]
        upd = pr_hib > st.p_hib
        p_hib = jnp.where(upd, pr_hib, st.p_hib)
        p_hiv = jnp.where(upd, pr_hiv, st.p_hiv)

        m_accd = per_slot(OP_ACCEPTED) \
            & (mbal[:, None, :] == st.p_ballot[:, :, None]) \
            & (st.p_phase == P_ACCEPTING)[:, :, None]
        p_acc = fold_bits(st.p_acc, m_accd)
        nacc = jnp.sum(p_acc, axis=2, dtype=jnp.int32)

        # phase transitions
        to_accept = (st.p_phase == P_PREPARING) & (nprom >= Q)
        adopt = st.p_value if self.unsafe_adopt else \
            jnp.where(p_hib > 0, p_hiv, st.p_value)
        p_chosen = jnp.where(to_accept, adopt, st.p_chosen)
        win = (st.p_phase == P_ACCEPTING) & (nacc >= Q)
        p_phase = jnp.where(to_accept, P_ACCEPTING, st.p_phase)
        p_phase = jnp.where(win, P_IDLE, p_phase)
        p_sent = st.p_sent & ~to_accept                      # re-arm fan-out
        p_t0 = jnp.where(to_accept, ctx.rnd, st.p_t0)

        # ---- learner: DECIDE ------------------------------------------
        m_dec = per_slot(OP_DECIDE)
        dec_val = jnp.max(jnp.where(m_dec, mval[:, None, :], NEG), axis=2)
        got_dec = dec_val > NEG
        decided = jnp.where((st.decided < 0) & got_dec, dec_val,
                            st.decided)
        decided = jnp.where((st.decided < 0) & win, p_chosen, decided)
        p_won = jnp.where((st.p_won < 0) & win, p_chosen, st.p_won)
        won_conflict = st.won_conflict | \
            (win & (st.p_won >= 0) & (st.p_won != p_chosen))

        # ---- retry: jittered per-proposer window ----------------------
        retry_at = self.retry_rounds + (gids % 3)[:, None]
        stuck = (p_phase != P_IDLE) & ~win \
            & (ctx.rnd - p_t0 >= retry_at)
        p_ballot = jnp.where(stuck, st.p_ballot + NG, st.p_ballot)
        p_phase = jnp.where(stuck, P_PREPARING, p_phase)
        p_prom = jnp.where((stuck | to_accept)[:, :, None], False, p_prom)
        p_acc = jnp.where((stuck | win)[:, :, None], False, p_acc)
        p_hib = jnp.where(stuck, 0, p_hib)
        p_hiv = jnp.where(stuck, 0, p_hiv)
        p_sent = p_sent & ~stuck
        p_t0 = jnp.where(stuck, ctx.rnd, p_t0)

        # ---- edge-triggered fan-outs ----------------------------------
        # one [n, S, NG] block; op selected by the proposer's phase.
        # DECIDE additionally re-broadcasts from every decided node on a
        # slow stagger — the learner anti-entropy (paxoid's ledger
        # gossip) that heals an omitted DECIDE fan-out.
        fan_now = (p_phase != P_IDLE) & ~p_sent & alive[:, None]
        dec_now = win & alive[:, None]
        dec_rebc = (decided >= 0) & ~win & alive[:, None] \
            & ((ctx.rnd + gids[:, None]) % (2 * self.retry_rounds) == 0)
        dec_all = dec_now | dec_rebc
        any_fan = fan_now | dec_all
        fan_op = jnp.where(dec_all, OP_DECIDE,
                           jnp.where(p_phase == P_PREPARING, OP_PREPARE,
                                     OP_ACCEPT))
        fan_val = jnp.where(p_phase == P_ACCEPTING, p_chosen, st.p_value)
        fan_val = jnp.where(dec_now, p_chosen, fan_val)
        fan_val = jnp.where(dec_rebc, decided, fan_val)
        fan = msg_ops.build(
            cfg, T.MsgKind.APP, gids[:, None, None],
            jnp.where(any_fan[:, :, None], all_ids[None, None, :], -1),
            payload=(fan_op[:, :, None],
                     jnp.broadcast_to(sl[None, :, None], (n, S, NG)),
                     p_ballot[:, :, None], fan_val[:, :, None], 0))
        p_sent = p_sent | fan_now

        live = alive[:, None]
        out = PaxosState(
            a_promised=jnp.where(live, a_promised, st.a_promised),
            a_ballot=jnp.where(live, a_ballot, st.a_ballot),
            a_value=jnp.where(live, a_value, st.a_value),
            p_phase=jnp.where(live, p_phase, st.p_phase),
            p_ballot=jnp.where(live, p_ballot, st.p_ballot),
            p_value=st.p_value,
            p_chosen=jnp.where(live, p_chosen, st.p_chosen),
            p_prom=jnp.where(live[:, :, None], p_prom, st.p_prom),
            p_hib=jnp.where(live, p_hib, st.p_hib),
            p_hiv=jnp.where(live, p_hiv, st.p_hiv),
            p_acc=jnp.where(live[:, :, None], p_acc, st.p_acc),
            p_t0=jnp.where(live, p_t0, st.p_t0),
            p_sent=jnp.where(live, p_sent, st.p_sent),
            p_won=jnp.where(live, p_won, st.p_won),
            won_conflict=jnp.where(live, won_conflict, st.won_conflict),
            decided=jnp.where(live, decided, st.decided))
        emitted = plane_ops.concat(
            [promise, accepted_msg, fan.reshape(n, S * NG, cfg.msg_words)],
            axis=1)
        return out, emitted

    # ---- host-side API -----------------------------------------------
    def propose(self, st: PaxosState, node: int, slot: int, value: int,
                now: int, n_global: int) -> PaxosState:
        """Start (or restart) a proposal.  Ballots stay unique to the
        proposer: attempt * n + id + 1."""
        cur = int(st.p_ballot[node, slot])
        nxt = node + 1 if cur <= 0 else cur + n_global
        return st._replace(
            p_phase=st.p_phase.at[node, slot].set(P_PREPARING),
            p_ballot=st.p_ballot.at[node, slot].set(nxt),
            p_value=st.p_value.at[node, slot].set(value),
            p_prom=st.p_prom.at[node, slot].set(False),
            p_hib=st.p_hib.at[node, slot].set(0),
            p_hiv=st.p_hiv.at[node, slot].set(0),
            p_acc=st.p_acc.at[node, slot].set(False),
            p_t0=st.p_t0.at[node, slot].set(now),
            p_sent=st.p_sent.at[node, slot].set(False))

    # ---- invariants (the prop-model postconditions) -------------------
    @staticmethod
    def _slot_values(st: PaxosState, s: int) -> set:
        """Values observed as chosen for decree ``s``: learned
        (decided) AND chosen-as-proposer (p_won) — the latter catches a
        chosen-value split that first-DECIDE-wins learners would mask."""
        import numpy as np

        d = np.asarray(st.decided)[:, s]
        w = np.asarray(st.p_won)[:, s]
        return {int(v) for v in d if v >= 0} | \
               {int(v) for v in w if v >= 0}

    @classmethod
    def agreement(cls, st: PaxosState) -> bool:
        """At most one value is ever chosen per decree — checked across
        ALL nodes (safety is global; a crashed node's pre-crash
        learning still counts) and across both learner and proposer
        observations."""
        import numpy as np

        if bool(np.asarray(st.won_conflict).any()):
            return False
        return all(len(cls._slot_values(st, s)) <= 1
                   for s in range(st.decided.shape[1]))

    @classmethod
    def validity(cls, st: PaxosState, proposed: dict) -> bool:
        """Every chosen value was proposed for that decree."""
        return all(
            cls._slot_values(st, s) <= set(proposed.get(s, ()))
            for s in range(st.decided.shape[1]))

    @staticmethod
    def decided_nodes(st: PaxosState, slot: int):
        import numpy as np

        d = np.asarray(st.decided)[:, slot]
        return [i for i, v in enumerate(d) if v >= 0]
