"""HyParView overlay tests — sim analogues of the reference suite's
hyparview group (partisan_SUITE.erl:287-307): membership forms a connected
overlay with bounded view sizes, heals around crashes, and supports
transitive dissemination."""

import jax
import pytest
import numpy as np

from partisan_tpu.cluster import Cluster
from partisan_tpu import faults as faults_mod
from partisan_tpu.models.anti_entropy import AntiEntropy
from partisan_tpu.parallel import ShardedCluster, make_mesh

from support import boot_hyparview, components, hv_config, staggered_join


def test_overlay_forms_and_is_connected():
    cfg = hv_config(32, seed=13)
    cl = Cluster(cfg)
    st = staggered_join(cl, cl.init())
    st = cl.steps(st, 60)
    active = np.asarray(st.manager.active)
    alive = np.asarray(st.faults.alive)

    sizes = (active >= 0).sum(axis=1)
    assert sizes.max() <= cfg.hyparview.active_max
    assert (sizes >= 1).all(), f"isolated nodes: {np.where(sizes == 0)[0]}"
    comps = components(active, alive)
    assert len(comps) == 1, f"overlay partitioned into {len(comps)} comps"
    # Passive views populated by shuffles/walks.
    passive_sizes = (np.asarray(st.manager.passive) >= 0).sum(axis=1)
    assert passive_sizes.mean() > 2.0, passive_sizes.mean()
    # No self-loops, no dead ids, no duplicate active entries.
    for i in range(cfg.n_nodes):
        row = [x for x in active[i] if x >= 0]
        assert i not in row
        assert len(row) == len(set(row))


def test_active_views_mostly_symmetric():
    cfg = hv_config(24, seed=3)
    cl = Cluster(cfg)
    st = staggered_join(cl, cl.init())
    st = cl.steps(st, 80)
    active = np.asarray(st.manager.active)
    edges = {(i, int(j)) for i in range(cfg.n_nodes)
             for j in active[i] if j >= 0}
    sym = sum((b, a) in edges for (a, b) in edges) / max(len(edges), 1)
    assert sym > 0.8, f"symmetry ratio {sym}"


def test_crash_healing():
    cfg = hv_config(32, seed=29)
    cl = Cluster(cfg)
    st = staggered_join(cl, cl.init())
    st = cl.steps(st, 60)
    f = st.faults
    for node in (3, 7, 11, 19, 23):
        f = faults_mod.crash(f, node)
    st = st._replace(faults=f)
    st = cl.steps(st, 80)
    active = np.asarray(st.manager.active)
    alive = np.asarray(st.faults.alive)
    # Dead peers pruned from every live active view.
    for i in np.where(alive)[0]:
        for j in active[i]:
            assert j < 0 or alive[int(j)], f"node {i} holds dead peer {j}"
    comps = components(active, alive)
    assert len(comps) == 1, f"overlay did not heal: {len(comps)} comps"


def test_leave_disconnects():
    cfg = hv_config(16, seed=5)
    cl = Cluster(cfg)
    st = staggered_join(cl, cl.init())
    st = cl.steps(st, 40)
    st = st._replace(manager=cl.manager.leave(cfg, st.manager, 4))
    st = cl.steps(st, 20)
    active = np.asarray(st.manager.active)
    assert (active[4] < 0).all(), "leaver kept active peers"
    for i in range(16):
        if i != 4:
            assert 4 not in active[i][active[i] >= 0], f"{i} kept leaver"


def test_dissemination_over_overlay():
    """Anti-entropy gossip rides the hyparview active views (transitive
    delivery without full membership)."""
    cfg = hv_config(32, seed=17)
    model = AntiEntropy()
    cl = Cluster(cfg, model=model)
    st = staggered_join(cl, cl.init())
    st = cl.steps(st, 40)
    st = st._replace(model=model.broadcast(st.model, node=9, slot=0))
    st, r = cl.run_until(
        st, lambda s: float(model.coverage(s.model, s.faults.alive, 0)) == 1.0,
        max_rounds=200, check_every=5)
    assert r != -1, "gossip never covered the overlay"


def test_sharded_parity():
    cfg = hv_config(16, seed=77)
    assert len(jax.devices()) >= 8

    def run(make):
        cl = make()
        st = cl.init()
        m = st.manager
        for i in range(1, 16):
            m = cl.manager.join(cfg, m, i, 0)
        st = st._replace(manager=m)
        return jax.device_get(cl.steps(st, 50))

    a = run(lambda: Cluster(cfg))
    b = run(lambda: ShardedCluster(cfg, make_mesh(8)))
    assert (a.manager.active == b.manager.active).all()
    assert (a.manager.passive == b.manager.passive).all()


def test_rejoin_after_leave():
    """rejoin_test analogue (partisan_SUITE.erl:287-307): a node that
    left comes back via a scripted join and re-enters the overlay."""
    cfg = hv_config(16, 4)
    cl = Cluster(cfg)
    st = cl.steps(staggered_join(cl, cl.init()), 40)
    st = st._replace(manager=cl.manager.leave(cfg, st.manager, 5))
    st = cl.steps(st, 10)
    active = np.asarray(st.manager.active)
    assert (active[5] < 0).all()
    # rejoin via a different contact
    st = st._replace(manager=cl.manager.join(cfg, st.manager, 5, 2))
    st = cl.steps(st, 40)
    active = np.asarray(st.manager.active)
    assert (active[5] >= 0).any(), "rejoiner has no active peers"
    # overlay is one component again including the rejoiner
    assert len(components(active, np.ones(16, bool))) == 1


def test_saturated_clique_merges_via_heartbeat_isolation():
    """A disconnected SATURATED component (7 nodes whose full active
    views point only at each other) is unmergeable by shuffle/promotion
    — promotion fires only under-full, shuffles walk active edges.  The
    liveness heartbeat (node 0's epoch scatter-maxed along edges) goes
    stale inside the clique, and the isolation window triggers a
    discovery-seed rejoin that merges it back (HyParViewConfig.heartbeat
    doc: the plumtree-backend heartbeat + scamp_v2 isolation window)."""
    import jax.numpy as jnp

    cfg = hv_config(24, seed=13)
    cl = Cluster(cfg)
    st = boot_hyparview(cl)
    clique = np.arange(17, 24)
    active = st.manager.active
    passive = st.manager.passive
    A = active.shape[1]
    for nd in clique:
        others = [int(x) for x in clique if x != nd][:A]
        active = active.at[nd].set(jnp.asarray(others, jnp.int32))
        passive = passive.at[nd].set(-1)
    # sever the main component's links INTO the clique too
    in_clique = jnp.isin(active, jnp.asarray(clique))
    rows_main = jnp.arange(24)[:, None] < 17
    active = jnp.where(in_clique & rows_main, -1, active)
    st = st._replace(manager=st.manager._replace(
        active=active, passive=passive,
        joined=st.manager.joined | True,
        hb_rnd=jnp.full((24,), int(st.rnd), jnp.int32)))
    assert len(components(np.asarray(st.manager.active),
                          np.ones(24, bool))) == 2
    window = cfg.rounds(cfg.hyparview.isolation_window_ms)
    st = cl.steps(st, window + 30)
    comps = components(np.asarray(st.manager.active), np.ones(24, bool))
    assert len(comps) == 1, f"clique did not merge: {comps}"


def test_heartbeat_quiet_on_connected_overlay():
    """On a healthy connected overlay the isolation detector must never
    fire: every node's received epoch keeps advancing (hb_rnd within one
    window of now)."""
    cfg = hv_config(20, seed=17)
    cl = Cluster(cfg)
    st = boot_hyparview(cl)
    st = cl.steps(st, 60)
    window = cfg.rounds(cfg.hyparview.isolation_window_ms)
    lag = int(st.rnd) - np.asarray(st.manager.hb_rnd)
    assert (lag <= window).all(), f"stale heartbeat on connected overlay: {lag}"


def test_heartbeat_root_migrates_when_node0_crashes():
    """The epoch root is the lowest ALIVE id, not a fixed node: crashing
    nodes 0 and 1 hands root duty to node 2 — epochs keep advancing for
    every alive node and no rejoin storm fires (the fixed-root design
    would have put the whole cluster into a perpetual JOIN storm at the
    seeds once node 0 died)."""
    cfg = hv_config(24, seed=19)
    cl = Cluster(cfg)
    st = boot_hyparview(cl)
    st = st._replace(faults=faults_mod.crash(
        faults_mod.crash(st.faults, 0), 1))
    window = cfg.rounds(cfg.hyparview.isolation_window_ms)
    st = cl.steps(st, 2 * window + 20)
    alive = np.asarray(st.faults.alive)
    # epochs still advance under the migrated root: every alive node's
    # last-advance round is within one window of now
    lag = int(st.rnd) - np.asarray(st.manager.hb_rnd)
    assert (lag[alive] <= window + cfg.rounds(
        cfg.hyparview.heartbeat_every_ms)).all(), lag[alive]
    # and the surviving overlay is still one healthy component
    comps = components(np.asarray(st.manager.active), alive)
    assert len(comps) == 1, [len(c) for c in comps]


@pytest.mark.parametrize("seed", [29, 31, 37])
def test_heartbeat_merges_random_saturated_components(seed):
    """Property over random topologies: carve a RANDOM subset into a
    saturated clique (full views pointing only inside, empty passive,
    severed from outside) — whatever the cast, the heartbeat isolation
    detector merges the overlay back into one component within ~one
    isolation window."""
    import jax.numpy as jnp

    n = 20
    cfg = hv_config(n, seed=seed)
    cl = Cluster(cfg)
    st = boot_hyparview(cl)
    rng = np.random.default_rng(seed)
    A = st.manager.active.shape[1]
    size = int(rng.integers(3, A + 2))      # 3..7 members
    clique = rng.choice(np.arange(1, n), size=size, replace=False)
    active, passive = st.manager.active, st.manager.passive
    for nd in clique:
        others = [int(x) for x in clique if x != nd][:A]
        row = jnp.full((A,), -1, jnp.int32).at[:len(others)].set(
            jnp.asarray(others, jnp.int32))
        active = active.at[int(nd)].set(row)
        passive = passive.at[int(nd)].set(-1)
    in_clique = jnp.isin(active, jnp.asarray(clique))
    outside = ~jnp.isin(jnp.arange(n), jnp.asarray(clique))
    active = jnp.where(in_clique & outside[:, None], -1, active)
    st = st._replace(manager=st.manager._replace(
        active=active, passive=passive,
        joined=st.manager.joined | True,
        hb_rnd=jnp.full((n,), int(st.rnd), jnp.int32)))
    assert len(components(np.asarray(st.manager.active),
                          np.ones(n, bool))) >= 2
    window = cfg.rounds(cfg.hyparview.isolation_window_ms)
    st = cl.steps(st, 2 * window + 30)
    comps = components(np.asarray(st.manager.active), np.ones(n, bool))
    assert len(comps) == 1, f"seed {seed}: {[len(c) for c in comps]}"
