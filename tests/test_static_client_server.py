"""Static and client-server manager tests — sim analogues of the
reference's static/client-server coverage (partisan_SUITE `default`
group with those managers): explicit-join-only membership, star
topology with tag-refused client-client joins, membership gossip
convergence, and workload dissemination over the star."""

import numpy as np

from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config
from partisan_tpu.models.anti_entropy import AntiEntropy


def test_static_explicit_joins_only():
    cfg = Config(n_nodes=8, seed=1, peer_service_manager="static")
    cl = Cluster(cfg)
    st = cl.init()
    m = st.manager
    m = cl.manager.join(cfg, m, 1, 0)
    m = cl.manager.join(cfg, m, 2, 0)
    st = st._replace(manager=m)
    st = cl.steps(st, 30)
    members = np.asarray(cl.manager.members(cfg, st.manager))
    # No gossip: node 1 and 2 know the contact but NOT each other.
    assert members[1, 0] and members[2, 0]
    assert not members[1, 2] and not members[2, 1]
    nbrs = np.asarray(cl.manager.neighbors(cfg, st.manager))
    assert set(nbrs[0][nbrs[0] >= 0]) == {1, 2}


def test_static_leave_clears_edges():
    cfg = Config(n_nodes=6, seed=2, peer_service_manager="static")
    cl = Cluster(cfg)
    st = cl.init()
    m = st.manager
    for i in range(1, 6):
        m = cl.manager.join(cfg, m, i, 0)
    m = cl.manager.leave(cfg, m, 3)
    st = st._replace(manager=m)
    st = cl.steps(st, 5)
    nbrs = np.asarray(cl.manager.neighbors(cfg, st.manager))
    assert (nbrs[3] < 0).all()
    assert 3 not in set(nbrs[0][nbrs[0] >= 0])


def cs_config(n, seed, servers=2, **kw):
    return Config(n_nodes=n, seed=seed, peer_service_manager="client_server",
                  cs_servers=servers, **kw)


def boot_star(cl):
    """Servers full-mesh each other; client i joins server i % S."""
    cfg = cl.cfg
    st = cl.init()
    m = st.manager
    S = cfg.cs_servers
    for a in range(S):
        for b in range(a + 1, S):
            m = cl.manager.join(cfg, m, a, b)
    for c in range(S, cfg.n_nodes):
        m = cl.manager.join(cfg, m, c, c % S)
    return st._replace(manager=m)


def test_client_server_topology_and_refusal():
    cfg = cs_config(12, seed=7, servers=3)
    cl = Cluster(cfg)
    st = boot_star(cl)
    # Client-client join refused (accept_join_with_tag).
    st = st._replace(manager=cl.manager.join(cfg, st.manager, 5, 7))
    nbrs = np.asarray(cl.manager.neighbors(cfg, st.manager))
    assert 7 not in set(nbrs[5][nbrs[5] >= 0]), "client-client joined"
    # Clients only hold servers; servers hold servers + their clients.
    for c in range(3, 12):
        row = set(nbrs[c][nbrs[c] >= 0])
        assert row == {c % 3}, (c, row)
    for s in range(3):
        row = set(nbrs[s][nbrs[s] >= 0])
        assert {x for x in row if x < 3} == {0, 1, 2} - {s}


def test_client_server_membership_gossip_converges():
    cfg = cs_config(12, seed=11, servers=3)
    cl = Cluster(cfg)
    st = boot_star(cl)
    st = cl.steps(st, cfg.gossip_every * 4)
    members = np.asarray(cl.manager.members(cfg, st.manager))
    assert members.all(), (
        f"membership did not converge: {members.sum(axis=1)}")


def test_dissemination_via_servers():
    """A client's gossip reaches every other client THROUGH the star
    (clients never talk to clients directly)."""
    cfg = cs_config(12, seed=19, servers=2)
    model = AntiEntropy()
    cl = Cluster(cfg, model=model)
    st = boot_star(cl)
    st = cl.steps(st, 10)
    st = st._replace(model=model.broadcast(st.model, node=7, slot=0))
    st, r = cl.run_until(
        st, lambda s: float(model.coverage(s.model, s.faults.alive, 0)) == 1.0,
        max_rounds=200, check_every=5)
    assert r != -1, "star dissemination failed"
