"""End-to-end incident observatory over a REAL mixed-fault soak run
(the ISSUE 17 acceptance suite):

1. every injected fault class resolves to a CLOSED incident span with
   measured detect/react/recover round-latencies, and the ops gate
   passes — the claim ``scenarios.py --ops`` folds into its verdicts,
2. the span set survives a mid-incident kill + fresh-engine restore
   BIT-FOR-BIT: the killed run's journal with its resume's appended
   (``to_jsonl(append=True)`` + ``Journal.from_jsonl`` merge) matches
   an uninterrupted run's span set exactly,
3. building the journal traces ZERO eqns (perfwatch's census-parity
   contract: opslog is host-side bookkeeping only).

One module-scoped storm soak feeds all three: a full fault cycle
(link drop -> crash batch -> partition -> churn, each cured) with the
metrics + latency + health planes and the healing controller armed, so
every rule chain in the catalog has both its detection plane and its
reaction source live.
"""

import jax
import pytest

import support
from partisan_tpu import opslog, soak
from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config, ControlConfig
from partisan_tpu.models.plumtree import Plumtree

N = support.OPS_SOAK_N

# One full cycle, every action cured inside the run: LinkDrop cleared
# at +6, the crash batch revived at +30 (which also heals the +20
# partition), the churn stopped at +50 — 70 rounds covers every
# falling edge the matcher closes on.
STORM_EVENTS = (
    (0, soak.LinkDrop(0.2)),
    (6, soak.Heal()),
    (10, soak.CrashBatch(frac=0.05)),
    (20, soak.Partition()),
    (30, soak.Heal(revive=True)),
    (40, soak.Churn(0.05, 0.05)),
    (50, soak.Heal(revive=True)),
)
ROUNDS = 70
KILL_AT = 30          # mid-partition: injected at +20, healed at +30


def _mk():
    cfg = Config(n_nodes=N, seed=3, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups",
                 metrics=True, metrics_ring=128, latency=True,
                 health=5, health_ring=64,
                 control=ControlConfig(healing=True))
    return Cluster(cfg, model=Plumtree())


def _storm(start):
    return soak.Storm(events=STORM_EVENTS, start=start, period=0)


@pytest.fixture(scope="module")
def incident_run(tmp_path_factory):
    """The shared storm soak: an uninterrupted reference run PLUS the
    same timeline as a killed run (stopped at the partition-heal
    boundary, mid-incident) resumed by a fresh engine from its on-disk
    checkpoint."""
    ckpt = tmp_path_factory.mktemp("ops_ckpt")
    cl = _mk()
    n = cl.cfg.n_nodes
    st = cl.init()
    m = cl.manager.join_many(cl.cfg, st.manager,
                             list(range(1, n)), [0] * (n - 1))
    st = cl.steps(st._replace(manager=m), 20)
    st = st._replace(model=cl.model.broadcast(st.model, 0, 0,
                                              int(st.rnd)))
    st = cl.steps(st, 5)
    r0 = int(jax.device_get(st.rnd))

    eng_a = soak.Soak(make_cluster=lambda: cl, storm=_storm(r0),
                      cfg=soak.SoakConfig(chunk_fixed=10,
                                          checkpoint_dir=str(ckpt)))
    res_a = eng_a.run(st, until_round=r0 + KILL_AT)
    # the fresh-process path: new cluster, new (identically declared)
    # storm, resumed from the newest checkpoint
    eng_b = soak.Soak(make_cluster=_mk, storm=_storm(r0),
                      cfg=soak.SoakConfig(chunk_fixed=10,
                                          checkpoint_dir=str(ckpt)))
    res_b = eng_b.run(resume=True, until_round=r0 + ROUNDS)
    eng_ref = soak.Soak(make_cluster=lambda: cl, storm=_storm(r0),
                        cfg=soak.SoakConfig(chunk_fixed=10))
    res_ref = eng_ref.run(st, rounds=ROUNDS)
    return {"r0": r0, "res_a": res_a, "res_b": res_b,
            "res_ref": res_ref, "storm": _storm(r0)}


def test_every_injected_fault_resolves_to_closed_span(incident_run):
    r0 = incident_run["r0"]
    j = opslog.from_soak(incident_run["res_ref"],
                         storm=incident_run["storm"], slo_rounds=6)
    # the fusion recorded every live source's coverage
    for s in ("inject", "chunk", "metrics", "health", "control",
              "latency", "soak", "perf", "ops"):
        assert s in j.streams, f"stream {s} not covered"
    m = opslog.match(j)
    spans = {s["rule"]: s for s in m["spans"]}
    assert set(spans) == {"link_drop", "crash", "partition", "churn"}
    for rule, s in spans.items():
        assert s["status"] == "closed", f"{rule}: {s}"
        assert s["detect_latency"] >= 0
        assert s["recover_round"] > s["cause_round"] >= r0
        assert s["recover_latency"] >= s["detect_latency"]
    # the healing controller's escalation was claimed by its incident,
    # not orphaned
    assert spans["partition"]["react_event"] \
        == "partisan.control.healing_escalated" \
        or spans["crash"]["react_event"] \
        == "partisan.control.healing_escalated"
    assert m["orphans"] == []
    budgets = opslog.error_budgets(j, slo_rounds=6)
    verdict = opslog.gate(m, budgets)
    assert verdict["ok"], verdict


def test_kill_restore_reconstructs_identical_span_set(incident_run,
                                                      tmp_path):
    """Satellite 3: journal A (killed mid-partition) appended with
    journal B (fresh-engine resume) merges — via the JSON-lines
    artifact itself — to the exact span set of the uninterrupted run."""
    storm = incident_run["storm"]
    path = tmp_path / "ops.jsonl"
    ja = opslog.from_soak(incident_run["res_a"], storm=storm)
    spans_a = opslog.match(ja)["spans"]
    # the kill really was mid-incident: the partition is detected but
    # not yet recovered when the run stops
    (part_a,) = [s for s in spans_a if s["rule"] == "partition"]
    assert part_a["status"] == "open"
    ja.to_jsonl(path)
    jb = opslog.from_soak(incident_run["res_b"], storm=storm)
    jb.to_jsonl(path, append=True)

    merged = opslog.match(opslog.Journal.from_jsonl(path))
    ref = opslog.match(opslog.from_soak(incident_run["res_ref"],
                                        storm=storm))
    assert merged["spans"] == ref["spans"]
    assert merged["counts"]["closed"] == 4
    # (orphans are NOT compared: journal A preserves ring history the
    # uninterrupted run's decision ring evicted by the end — the merge
    # keeps strictly MORE evidence, and spans are identical anyway)


def test_journal_building_traces_zero_eqns(incident_run):
    """opslog is host-side only: fusing the journal, matching spans and
    accounting budgets change NOTHING in any traced program (the
    perfwatch census-parity pin)."""
    from partisan_tpu.lint.cost import bench_round_program, \
        census_program

    base = census_program(bench_round_program(64))
    j = opslog.from_soak(incident_run["res_ref"],
                         storm=incident_run["storm"], slo_rounds=6)
    opslog.gate(opslog.match(j), opslog.error_budgets(j, slo_rounds=6))
    under = census_program(bench_round_program(64))
    assert {p: c.eqns for p, c in base.phases.items()} == \
        {p: c.eqns for p, c in under.phases.items()}
    assert base.total.eqns == under.total.eqns
