"""Full-horizon telemetry spool: the always-on collection path.

Every observability plane records into a bounded device-resident ring,
so a post-hoc ``snapshot()`` only attests the ring's TAIL window — any
incident older than the window is "unobservable" to the opslog matcher
(opslog.py, the coverage map).  The spool closes that gap on the host
side: at every soak chunk boundary (the host-sync point that already
exists — no new traced equations, census parity pinned by
tests/test_spool.py) it drains each armed plane's ring *delta* since
the last drain into an append-only JSON-lines file.  The union of the
deltas is the full horizon: ``opslog.ingest_spool`` extends
``Journal.streams`` coverage back to the run's entry round, and
formerly-unobservable spans become real closed/undetected verdicts.

Contracts (ARCHITECTURE.md "Full-horizon telemetry spool & operator
console" documents each):

- **Record identity + merge.**  One JSON object per line, dedup
  identity ``(round, stream, event)`` — the journal's Entry identity
  with no channel/node/dup axis (spool rows are whole-cluster ring
  rows).  First copy wins; re-draining a replayed window after a
  kill/restore or a rewound retry appends nothing, because the
  re-executed rounds are bit-identical (deterministic scan from a
  checkpoint) and their keys are already present.
- **Bit-identity.**  Records carry ONLY device-derived values (ring
  rows, poll scalars) — never host timing — and every record is keyed
  by the round the device stamped it with.  Under pinned chunk
  boundaries (``SoakConfig.chunk_fixed``, a non-donating cluster) a
  kill/restore run and a ``pipeline_depth > 1`` run produce files
  byte-identical to the uninterrupted run's (tests/test_spool.py).
- **Pipeline-boundary rule.**  Drains happen only where the soak loop
  already synchronizes: after a completed chunk barrier, and — when
  the cluster donates its carry — only at drained-pipeline boundaries
  (the rows that poll at all).  The spool never adds a sync point.
- **Drain cost is accounted.**  The soak loop stamps each chunk row
  with ``spool_s`` (host seconds spent draining) and
  ``perfwatch.decompose`` subtracts it from the dispatch gap, so
  collection cost can't masquerade as dispatch wall.

Every record's ``event`` field is a dot-joined ``telemetry.EVENTS``
name (the ``partisan.spool.*`` family) — the one registry stays the
only event namespace, and the sync-guard test covers the spool too.

Known windowed-skip: ``health.deg_hist`` (a histogram row) and the
``digests`` words are not spooled — the discrete transitions the
journal consumes never read them, and rows stay flat JSON scalars and
short lists.
"""
from __future__ import annotations

import dataclasses
import json
import os

from partisan_tpu import telemetry

# Dot-joined record type names (the spool file's ``event`` field).
EV_METRICS = ".".join(telemetry.SPOOL_METRICS_ROW)
EV_HEALTH = ".".join(telemetry.SPOOL_HEALTH_ROW)
EV_BROADCAST = ".".join(telemetry.SPOOL_BROADCAST_ROW)
EV_CTL_FANOUT = ".".join(telemetry.SPOOL_CONTROL_FANOUT)
EV_CTL_BACKPRESSURE = ".".join(telemetry.SPOOL_CONTROL_BACKPRESSURE)
EV_CTL_HEALING = ".".join(telemetry.SPOOL_CONTROL_HEALING)
EV_TRAFFIC = ".".join(telemetry.SPOOL_TRAFFIC_ROW)
EV_ELASTIC = ".".join(telemetry.SPOOL_ELASTIC_RESIZE)
EV_LATENCY = ".".join(telemetry.SPOOL_LATENCY_WINDOW)
EV_INGRESS = ".".join(telemetry.SPOOL_INGRESS_LEVEL)
EV_WATCHDOG = ".".join(telemetry.SPOOL_WATCHDOG_ROW)

# record stream per event — the journal-facing plane names (opslog
# STREAM_RANK's vocabulary), fixed write order within a drain so the
# file is deterministic.
EVENT_STREAMS = (
    (EV_METRICS, "metrics"),
    (EV_HEALTH, "health"),
    (EV_BROADCAST, "broadcast"),
    (EV_CTL_FANOUT, "control"),
    (EV_CTL_BACKPRESSURE, "control"),
    (EV_CTL_HEALING, "control"),
    (EV_TRAFFIC, "traffic"),
    (EV_ELASTIC, "elastic"),
    (EV_LATENCY, "latency"),
    (EV_INGRESS, "ingress"),
    (EV_WATCHDOG, "watchdog"),
)
STREAM_OF = dict(EVENT_STREAMS)


def _jsonable(v):
    """Coerce numpy scalars/arrays into plain JSON values — the spool
    line must not depend on numpy's repr."""
    import numpy as np

    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, np.ndarray):
        return _jsonable(v.tolist())
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    return v


@dataclasses.dataclass
class Spool:
    """One run's append-only telemetry spool.

    ``arm(start)`` stamps the run's entry round (the coverage anchor
    ``opslog.ingest_spool`` extends streams back to); ``drain(state,
    rnd, ...)`` decodes each armed plane's ring and appends every
    not-yet-spooled row; ``reanchor(rnd)`` re-opens the delta windows
    after a soak rewind (re-drained rows dedup — first copy wins).

    Opening an existing file RESUMES it: the constructor recovers the
    dedup keys and per-event high-water marks from the lines on disk
    (tolerating a torn final line from a killed process), so a
    fresh-process ``resume=True`` soak appends exactly the rows the
    killed run never wrote.
    """

    path: str

    def __post_init__(self):
        self._keys: set = set()          # (round, stream, event)
        self._marks: dict[str, int] = {}  # event -> newest spooled round
        self._start: int | None = None
        self._meta: dict = {}
        self._fh = None
        self._lines = 0
        self._gaps = 0                    # ring windows that opened past
        #                                   the previous mark: rounds
        #                                   lost to wraparound between
        #                                   drains (in-memory only — a
        #                                   counter in the file would
        #                                   break bit-identity)
        self._load()

    # ---- file state ---------------------------------------------------
    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "r") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue              # torn tail from a killed run
                if "spool_meta" in obj:
                    self._meta = obj["spool_meta"]
                    self._start = self._meta.get("start")
                    self._lines += 1
                    continue
                key = (obj["round"], obj["stream"], obj["event"])
                self._keys.add(key)
                ev = obj["event"]
                self._marks[ev] = max(self._marks.get(ev, -1),
                                      int(obj["round"]))
                self._lines += 1

    def _open(self, planes, channels) -> None:
        if self._fh is None:
            self._fh = open(self.path, "a")
        if self._lines == 0:
            # lazy header at the FIRST drain (the armed planes are only
            # known then); a resumed file keeps its original header —
            # one header per file, byte-identity preserved
            self._meta = {"version": 1, "start": self._start,
                          "planes": list(planes),
                          "channels": list(channels or ())}
            self._fh.write(json.dumps({"spool_meta": self._meta},
                                      separators=(",", ":")) + "\n")
            self._lines += 1

    def arm(self, start: int) -> None:
        """Stamp the run's entry round — every plane attests from here
        (each ring row since ``start`` reaches some drain)."""
        if self._start is None:
            self._start = int(start)

    # ---- the drain ----------------------------------------------------
    def _emit(self, event: str, rnd: int, meas: dict) -> int:
        key = (int(rnd), STREAM_OF[event], event)
        if key in self._keys:
            return 0
        rec = {"round": key[0], "stream": key[1], "event": event,
               "measurements": _jsonable(meas)}
        self._fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._keys.add(key)
        self._lines += 1
        return 1

    def _ring_rows(self, event: str, rounds, fields) -> int:
        """Append the ring delta: rows newer than the event's mark, in
        round order.  ``fields(i) -> measurements``."""
        mark = self._marks.get(event, -1)
        fresh = [(int(r), i) for i, r in enumerate(rounds)
                 if int(r) > mark]
        if not fresh:
            return 0
        fresh.sort()
        if mark >= 0:
            # wraparound heuristic: the oldest surviving undrained row
            # should continue the ring's own cadence; a larger jump
            # means rows fell off between drains
            stride = min((b - a for (a, _), (b, _)
                          in zip(fresh, fresh[1:])), default=1)
            if fresh[0][0] > mark + stride:
                self._gaps += 1
        written = 0
        for r, i in fresh:
            written += self._emit(event, r, fields(i))
        self._marks[event] = max(mark, fresh[-1][0])
        return written

    def drain(self, state, rnd: int, *, channels=None, p99=None,
              k=None, window_round=None) -> dict:
        """Drain every armed plane's ring delta at a chunk boundary.

        ``rnd`` is the boundary round (the chunk's end); ``p99``/``k``/
        ``window_round`` carry the soak loop's windowed-latency poll
        (the chunk-start-keyed SLO series).  Returns ``{"rows": n,
        "line": file_line_count}`` — the chunk row's spool pointer.
        Host-side only: ring decodes reuse the planes' own snapshot
        readers (one device->host transfer each, never inside a scan).
        """
        planes = []
        for attr in ("metrics", "health", "provenance", "control",
                     "traffic", "elastic", "ingress", "watchdog"):
            if getattr(state, attr, ()) != ():
                planes.append(attr)
        if p99 is not None:
            planes.append("latency")
        self._open(planes, channels)
        w = 0

        if getattr(state, "metrics", ()) != ():
            from partisan_tpu import metrics as metrics_mod

            snap = metrics_mod.snapshot(state.metrics)
            w += self._ring_rows(EV_METRICS, snap["rounds"], lambda i: {
                "emitted": snap["emitted"][i],
                "delivered": snap["delivered"][i],
                "causal": snap["causal"][i],
                "shed": snap["shed"][i],
                "drops": snap["drops"][i],
                "inbox_hwm": snap["inbox_hwm"][i],
                "inbox_occ": snap["inbox_occ"][i],
                "edges_total": snap["edges_total"][i],
                "edges_min": snap["edges_min"][i],
                "edges_max": snap["edges_max"][i],
                "alive": snap["alive"][i],
                "dlv_overflow": snap["dlv_overflow"][i],
            })
        if getattr(state, "health", ()) != ():
            from partisan_tpu import health as health_mod

            snap = health_mod.snapshot(state.health)
            w += self._ring_rows(EV_HEALTH, snap["rounds"], lambda i: {
                "components": snap["components"][i],
                "isolated": snap["isolated"][i],
                "deg_min": snap["deg_min"][i],
                "deg_max": snap["deg_max"][i],
                "sym_violations": snap["sym_violations"][i],
                "joins": snap["joins"][i],
                "leaves": snap["leaves"][i],
                "ups": snap["ups"][i],
                "downs": snap["downs"][i],
            })
        if getattr(state, "provenance", ()) != ():
            from partisan_tpu import provenance as prov_mod

            snap = prov_mod.snapshot(state.provenance)
            w += self._ring_rows(EV_BROADCAST, snap["rounds"],
                                 lambda i: {
                "dup": snap["dup"][i],
                "gossip": snap["gossip"][i],
                "claims": snap["claims"][i],
                "ctl": snap["ctl"][i],
            })
        if getattr(state, "control", ()) != ():
            from partisan_tpu import control as control_mod

            snap = control_mod.snapshot(state.control)
            fan = snap.get("fanout")
            if fan is not None:
                w += self._ring_rows(
                    EV_CTL_FANOUT, fan["rounds"],
                    lambda i: {"cap": fan["cap"][i]})
            bp = snap.get("backpressure")
            if bp is not None:
                w += self._ring_rows(
                    EV_CTL_BACKPRESSURE, bp["rounds"],
                    lambda i: {"press": bp["press"][i]})
            heal = snap.get("healing")
            if heal is not None:
                w += self._ring_rows(
                    EV_CTL_HEALING, heal["rounds"],
                    lambda i: {"boost": heal["boost"][i]})
        if getattr(state, "traffic", ()) != ():
            from partisan_tpu import workload as workload_mod

            snap = workload_mod.snapshot(state.traffic)
            # rate_x1000 is the operand in force over the drained delta
            # (SetRate applies only at boundaries, and a non-donating
            # cluster drains every chunk) — deterministic device state,
            # so the row is boundary-invariant
            rate = int(snap["rate_x1000"])
            w += self._ring_rows(EV_TRAFFIC, snap["rounds"], lambda i: {
                "arrivals": snap["arrivals"][i],
                "rate_x1000": rate,
            })
        if getattr(state, "elastic", ()) != ():
            from partisan_tpu import elastic as elastic_mod

            snap = elastic_mod.snapshot(state.elastic)
            w += self._ring_rows(EV_ELASTIC, snap["rounds"], lambda i: {
                "width": snap["widths"][i],
                "from": snap["from"][i],
            })
        if p99 is not None and window_round is not None:
            w += self._emit(EV_LATENCY, int(window_round),
                            {"k": int(k or 0), "p99": dict(p99)})
        if getattr(state, "ingress", ()) != ():
            from partisan_tpu import ingress as ingress_mod

            lvl = ingress_mod.poll(state.ingress)
            w += self._emit(EV_INGRESS, int(rnd), {
                "staged": lvl["staged"],
                "injected": lvl["injected"],
                "shed": lvl["shed"],
            })
        if getattr(state, "watchdog", ()) != ():
            from partisan_tpu import watchdog as watchdog_mod

            snap = watchdog_mod.snapshot(state.watchdog)
            # The watchdog ring advances EVERY round (unlike the
            # cadenced planes above), so only breach rounds spool —
            # quiet rounds carry no signal, and an every-round drain
            # would dominate the file.  The mark still advances over
            # the whole delta so re-drains stay cheap.
            mark = self._marks.get(EV_WATCHDOG, -1)
            fresh = sorted((int(r), i)
                           for i, r in enumerate(snap["rounds"])
                           if int(r) > mark)
            for r, i in fresh:
                word = int(snap["words"][i])
                if word:
                    w += self._emit(EV_WATCHDOG, r, {
                        "word": word,
                        **watchdog_mod.decode_word(word)})
            if fresh:
                self._marks[EV_WATCHDOG] = max(mark, fresh[-1][0])
        self._fh.flush()
        return {"rows": w, "line": self._lines}

    # ---- rewind / introspection --------------------------------------
    def reanchor(self, rnd: int) -> None:
        """Re-open the delta windows after a soak rewind to round
        ``rnd``: re-executed rounds re-drain (and dedup — first copy
        wins) instead of being mark-skipped, so an adaptive-chunk rerun
        that lands NEW boundaries still spools its rows."""
        for ev in list(self._marks):
            self._marks[ev] = min(self._marks[ev], int(rnd))

    def stats(self) -> dict:
        return {"path": self.path, "lines": self._lines,
                "rows": len(self._keys), "ring_gaps": self._gaps,
                "start": self._start}

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def read(path: str) -> tuple[dict, list[dict]]:
    """Read a spool file: ``(meta, records)``.  Malformed lines (the
    torn tail of a live or killed writer — the ``--follow`` tailing
    path) are skipped; duplicate identities keep the FIRST copy (the
    journal's merge contract); records come back round-sorted per
    event, globally ordered by ``(round, stream, event)``."""
    meta: dict = {}
    seen: set = set()
    records: list[dict] = []
    if not os.path.exists(path):
        return meta, records
    with open(path, "r") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if "spool_meta" in obj:
                sm = obj["spool_meta"]
                if not meta:
                    meta = dict(sm)
                continue
            try:
                key = (int(obj["round"]), obj["stream"], obj["event"])
            except (KeyError, TypeError, ValueError):
                continue
            if key in seen:
                continue
            seen.add(key)
            records.append(obj)
    records.sort(key=lambda rec: (rec["round"], rec["stream"],
                                  rec["event"]))
    return meta, records
