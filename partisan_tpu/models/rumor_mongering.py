"""Demers rumor mongering (protocols/demers_rumor_mongering.erl).

Reference behavior: infect-and-die gossip — on FIRST receipt of a rumor
a node delivers it, stores it, and forwards it to FANOUT=2 random
members (excluding itself and the sender, :127-158); duplicates are
ignored.  Each node forwards a given rumor exactly once, so spread is a
branching process that can die out before full coverage (by design —
the reference pairs it with anti-entropy for completeness).

TPU mapping: ``store`` marks rumors seen; ``pending`` marks rumors that
still owe their one forwarding burst.  A node serves up to
``PER_ROUND`` pending rumors per round (excess wait — the mailbox-
backlog analogue), picking fanout targets from the manager's neighbors.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import Array

from partisan_tpu import types as T
from partisan_tpu.comm import LocalComm
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import msg as msg_ops
from partisan_tpu.ops import rng

FANOUT = 2        # demers_rumor_mongering.erl:42 ?THIS_FANOUT
PER_ROUND = 2     # pending rumors forwarded per node per round
OP_RUMOR = 3      # APP payload[0] opcode

_PICK_TAG = 211


class RumorState(NamedTuple):
    store: Array    # bool[n_local, max_broadcasts]
    pending: Array  # bool[n_local, max_broadcasts] — owe a forward burst


class RumorMongering:
    name = "demers_rumor_mongering"

    @property
    def prov_spec(self):
        """Provenance descriptor (provenance.py): rumor copies are APP
        records with payload [OP_RUMOR, slot].  Infect-and-die carries
        no depth counter, so there is no hop word — every claim lands
        at hop 1 (the parent forest and redundancy accounting stay
        exact; only depth stats are flat)."""
        from partisan_tpu import provenance as provenance_mod

        return provenance_mod.ProvSpec(
            kind=int(T.MsgKind.APP), slot_word=T.P1,
            match_word=T.P0, match_val=OP_RUMOR)

    def init(self, cfg: Config, comm: LocalComm) -> RumorState:
        z = jnp.zeros((comm.n_local, cfg.max_broadcasts), jnp.bool_)
        return RumorState(store=z, pending=z)

    def step(self, cfg: Config, comm: LocalComm, state: RumorState,
             ctx: RoundCtx, nbrs: Array) -> tuple[RumorState, Array]:
        n = state.store.shape[0]
        S = cfg.max_broadcasts
        gids = comm.local_ids()

        # First receipt -> store + owe a forward (infect); dup -> ignore.
        inb = ctx.inbox.data
        is_r = (inb[..., T.W_KIND] == T.MsgKind.APP) & \
               (inb[..., T.P0] == OP_RUMOR)
        hits = jnp.zeros((n, S), jnp.int32)
        rows = jnp.broadcast_to(jnp.arange(n)[:, None], is_r.shape)
        hits = hits.at[rows, jnp.where(is_r, inb[..., T.P1], S)
                       ].add(1, mode="drop")
        new = (hits > 0) & ~state.store & ctx.alive[:, None]
        store = state.store | new
        pending = state.pending | new

        # Serve up to PER_ROUND pending rumors: FANOUT random neighbors
        # each (die after forwarding: pending bit cleared).
        def per_node(key, pend, row, alive):
            slot_keys = jax.vmap(
                lambda i: rng.subkey(rng.subkey(key, _PICK_TAG), i)
            )(jnp.arange(PER_ROUND))
            # lowest PER_ROUND pending slot ids
            order = jnp.argsort(jnp.where(pend, 0, 1), stable=True)
            slots = jnp.where(pend[order[:PER_ROUND]],
                              order[:PER_ROUND].astype(jnp.int32), -1)
            slots = jnp.where(alive, slots, -1)

            def fan(k, slot):
                picked = rng.choice_slots(k, row >= 0, FANOUT)
                ids = jnp.where(picked >= 0, row[picked], -1)
                return jnp.where(slot >= 0, ids, -1)

            tgts = jax.vmap(fan)(slot_keys, slots)   # [PER_ROUND, FANOUT]
            return slots, tgts

        slots, tgts = jax.vmap(per_node)(
            ctx.keys, pending, nbrs, ctx.alive)

        emitted = msg_ops.build(
            cfg, T.MsgKind.APP, gids[:, None, None], tgts,
            payload=(jnp.int32(OP_RUMOR), slots[:, :, None]),
        ).reshape(n, PER_ROUND * FANOUT, cfg.msg_words)

        served = jnp.zeros_like(pending)
        served = served.at[
            jnp.broadcast_to(jnp.arange(n)[:, None], slots.shape),
            jnp.where(slots >= 0, slots, S)].set(True, mode="drop")
        pending = pending & ~served
        pending = jnp.where(ctx.alive[:, None], pending, state.pending)
        store = jnp.where(ctx.alive[:, None], store, state.store)
        return RumorState(store=store, pending=pending), emitted

    # ---- scenario helpers --------------------------------------------
    def broadcast(self, state: RumorState, node: int, slot: int) -> RumorState:
        return RumorState(
            store=state.store.at[node, slot].set(True),
            pending=state.pending.at[node, slot].set(True))

    def coverage(self, state: RumorState, alive: Array, slot: int) -> Array:
        have = state.store[:, slot] & alive
        return jnp.sum(have) / jnp.maximum(jnp.sum(alive), 1)
