"""Plumtree broadcast tests — sim analogues of the reference suite's
with_broadcast group (partisan_SUITE.erl:214-315): full dissemination over
full-mesh and hyparview overlays, tree convergence via prunes, lazy-link
repair via i_have/graft under message loss, and sharded parity."""

import jax
import numpy as np

from partisan_tpu.cluster import Cluster
from partisan_tpu.models.plumtree import Plumtree
from partisan_tpu.parallel import ShardedCluster, make_mesh

from support import boot_fullmesh, boot_hyparview, fm_config, hv_config


def test_broadcast_covers_fullmesh():
    cfg = fm_config(16, seed=11)
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    st = boot_fullmesh(cl)
    st = st._replace(model=model.broadcast(st.model, node=3, slot=0))
    st, r = cl.run_until(
        st, lambda s: float(model.coverage(s.model, s.faults.alive, 0)) == 1.0,
        max_rounds=60, check_every=2)
    assert r != -1, "broadcast never covered the cluster"


def test_tree_converges_via_prunes():
    """After a few broadcasts, stale-duplicate prunes carve the flood down
    toward a spanning tree (handle_broadcast stale path, reference
    :843-857): mean eager degree falls well below the full-mesh degree."""
    cfg = fm_config(16, seed=23)
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    st = boot_fullmesh(cl)
    flood_degree = cfg.n_nodes - 1
    for ver in range(1, 5):  # re-broadcasts bump the slot version
        st = st._replace(model=model.broadcast(st.model, 3, 0, version=ver))
        st = cl.steps(st, 12)
    assert float(model.coverage(st.model, st.faults.alive, 0, version=4)) == 1.0
    deg = float(model.eager_degree(st.model, 0))
    assert deg < 0.5 * flood_degree, (
        f"eager degree {deg} did not shrink from flood {flood_degree}")
    # The eager subgraph still spans the cluster: a fresh version over the
    # pruned tree reaches everyone.
    st = st._replace(model=model.broadcast(st.model, 3, 0, version=9))
    st, r = cl.run_until(
        st, lambda s: float(model.coverage(s.model, s.faults.alive, 0, 9)) == 1.0,
        max_rounds=40, check_every=2)
    assert r != -1, "pruned tree no longer spans the cluster"


def test_lazy_repair_under_link_drops():
    """Driver config #3: 5%+ link drops; i_have/graft repairs holes
    (reference :861-905)."""
    cfg = fm_config(16, seed=31)
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    st = boot_fullmesh(cl)
    st = st._replace(faults=st.faults._replace(link_drop=np.float32(0.2)))
    for ver in (1, 2):
        st = st._replace(model=model.broadcast(st.model, 5, 1, version=ver))
        st, r = cl.run_until(
            st,
            lambda s, v=ver: float(
                model.coverage(s.model, s.faults.alive, 1, v)) == 1.0,
            max_rounds=150, check_every=5)
        assert r != -1, f"version {ver} never repaired to full coverage"


def test_broadcast_over_hyparview():
    cfg = hv_config(32, seed=17)
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    st = boot_hyparview(cl)
    st = st._replace(model=model.broadcast(st.model, node=9, slot=2))
    st, r = cl.run_until(
        st, lambda s: float(model.coverage(s.model, s.faults.alive, 2)) == 1.0,
        max_rounds=120, check_every=5)
    assert r != -1, "broadcast never covered the hyparview overlay"


def test_concurrent_broadcast_slots():
    cfg = fm_config(16, seed=41)
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    st = boot_fullmesh(cl)
    m = st.model
    for slot in range(6):
        m = model.broadcast(m, node=slot, slot=slot)
    st = st._replace(model=m)

    def all_covered(s):
        return all(
            float(model.coverage(s.model, s.faults.alive, b)) == 1.0
            for b in range(6))

    st, r = cl.run_until(st, all_covered, max_rounds=80, check_every=4)
    assert r != -1, "concurrent broadcasts did not all converge"


def test_sharded_parity():
    cfg = fm_config(16, seed=77)
    assert len(jax.devices()) >= 8
    model = Plumtree()

    def run(make):
        cl = make()
        st = boot_fullmesh(cl)
        st = st._replace(model=model.broadcast(st.model, 0, 0))
        return jax.device_get(cl.steps(st, 30))

    a = run(lambda: Cluster(cfg, model=model))
    b = run(lambda: ShardedCluster(cfg, make_mesh(8), model=model))
    assert (a.model.data == b.model.data).all()
    assert (a.model.pruned == b.model.pruned).all()
    assert (a.model.lazy_pending == b.model.lazy_pending).all()


def test_slot_recycling_keeps_trees_separate():
    """Per-root tree keying via slot epochs (VERDICT r3 gap; reference
    keys by ROOT, partisan_plumtree_broadcast.erl:118-160): broadcast
    2 x max_broadcasts messages through reused slots from ALTERNATING
    roots.  Every broadcast must reach everyone, and a recycled slot's
    tree must re-form for ITS root — the new root's eager repair is not
    poisoned by the previous occupant's prune flags."""
    cfg = fm_config(12, seed=41, max_broadcasts=4)
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    st = boot_fullmesh(cl)
    B = cfg.max_broadcasts
    version = 0
    for wave in range(2):                       # 2 x B broadcasts total
        for slot in range(B):
            version += 1
            root = (3, 7)[(wave + slot) % 2]    # alternating roots
            st = st._replace(model=model.broadcast(
                st.model, root, slot, version=version,
                fresh=(wave > 0)))              # wave 1 recycles slots
            st, r = cl.run_until(
                st, lambda s, _sl=slot, _v=version: float(
                    model.coverage(s.model, s.faults.alive, _sl, _v)
                ) == 1.0, max_rounds=40, check_every=2)
            assert r != -1, (wave, slot, "broadcast did not cover")
    # the recycled slots' epochs propagated everywhere
    ep = np.asarray(st.model.epoch)
    assert (ep[:, :B] >= 1).all()


def test_higher_epoch_ihave_recruits_pruned_node():
    """ADVICE r4: a node whose eager links were ALL pruned in the old
    epoch sees only i_have adverts for a recycled slot.  A strict
    equality epoch filter would make it ignore them (heal waits on the
    AAE walk); instead the advert's higher epoch is adopted — flags
    reset — and the missing payload grafts in the same round."""
    import jax.numpy as jnp

    from partisan_tpu import faults as faults_mod
    from partisan_tpu import types as T
    from partisan_tpu.comm import LocalComm
    from partisan_tpu.managers.base import RoundCtx
    from partisan_tpu.ops import exchange
    from partisan_tpu.ops import msg as msg_ops

    cfg = fm_config(4, seed=3)
    model = Plumtree()
    comm = LocalComm(cfg.n_nodes, cfg.inbox_cap, cfg.msg_words)
    n, K = cfg.n_nodes, cfg.n_nodes
    nbrs = jnp.where(
        jnp.arange(K)[None, :] != jnp.arange(n)[:, None],
        jnp.arange(K)[None, :], -1).astype(jnp.int32)
    st = model.init(cfg, comm)
    # node 0: every link pruned for slot 0 under epoch 0
    st = st._replace(tree_nbrs=nbrs,
                     pruned=st.pruned.at[0, 0, :].set(True))
    vec = model.handler.payload(7)
    ih = msg_ops.build(
        cfg.msg_words, T.MsgKind.PT_IHAVE, jnp.int32(1), jnp.int32(0),
        payload=(jnp.int32(0), *jnp.unstack(vec),
                 jnp.int32(0), jnp.int32(1)))   # slot, pay, hop, epoch 1
    inbox = exchange.route(ih.reshape(1, 1, -1), n, cfg.inbox_cap)
    ctx = RoundCtx(rnd=jnp.int32(10), alive=jnp.ones(n, bool),
                   keys=jax.random.split(jax.random.PRNGKey(0), n),
                   inbox=inbox, faults=faults_mod.none(n),
                   seed=cfg.seed)
    st2, emitted = model.step(cfg, comm, st, ctx, nbrs)
    assert int(st2.epoch[0, 0]) == 1            # adopted the advert's epoch
    assert not bool(st2.pruned[0, 0, :].any())  # flags reset for new tree
    # step returns emission BLOCKS (plane_ops.blocks_of contract)
    from partisan_tpu.ops import plane as plane_ops

    em = np.concatenate([np.asarray(b)[0]
                         for b in plane_ops.blocks_of(emitted)], axis=0)
    grafts = em[(em[:, T.W_KIND] == T.MsgKind.PT_GRAFT)
                & (em[:, T.W_DST] == 1)]
    assert len(grafts) >= 1                     # grafted back in, same round


def test_nonmonotone_recycle_detected():
    """The slot-epoch design is sound only while a recycled broadcast's
    payload dominates the slot's store.  A violating recycle must be
    DETECTED (recycle_nonmonotone counter), not silently conflate
    trees; a dominating recycle keeps the counter at zero."""
    from partisan_tpu import telemetry

    cfg = fm_config(8, seed=47, max_broadcasts=4)
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    st = boot_fullmesh(cl)
    st = st._replace(model=model.broadcast(st.model, 3, 0, version=5))
    st = cl.steps(st, 10)
    # dominating recycle: no detections anywhere
    st = st._replace(model=model.broadcast(st.model, 6, 0, version=8,
                                           fresh=True))
    st = cl.steps(st, 10)
    assert telemetry.plumtree_metrics(st.model)["recycle_nonmonotone"] == 0
    # plant a higher version at ONE node only, then recycle below it:
    # injection-site check passes (root's store is dominated) but the
    # planted node receives new-epoch gossip that does not dominate
    st = st._replace(model=model.broadcast(st.model, 7, 0, version=50))
    st = st._replace(model=model.broadcast(st.model, 3, 0, version=9,
                                           fresh=True))
    st = cl.steps(st, 10)
    m = telemetry.plumtree_metrics(st.model)
    assert m["recycle_nonmonotone"] >= 1
    assert 7 in m["recycle_nonmonotone_nodes"]
    # host-side injection check: a recycle below the root's own store
    before = telemetry.plumtree_metrics(st.model)["recycle_nonmonotone"]
    st = st._replace(model=model.broadcast(st.model, 3, 0, version=1,
                                           fresh=True))
    after = telemetry.plumtree_metrics(st.model)["recycle_nonmonotone"]
    assert after == before + 1


def test_recycled_slot_regrows_tree_for_new_root():
    """After a slot's tree converged for root A, recycling it for root
    B resets the eager/lazy flags: B's first broadcast floods (degree
    jumps back up) instead of riding A's pruned shape."""
    cfg = fm_config(12, seed=43, max_broadcasts=4)
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    st = boot_fullmesh(cl)
    for ver in range(1, 5):                     # converge tree for root 3
        st = st._replace(model=model.broadcast(st.model, 3, 0,
                                               version=ver))
        st = cl.steps(st, 12)
    deg_a = float(model.eager_degree(st.model, 0))
    assert deg_a < 0.5 * (cfg.n_nodes - 1)
    # recycle for root 8: flags reset as the epoch spreads
    st = st._replace(model=model.broadcast(st.model, 8, 0, version=50,
                                           fresh=True))
    st, r = cl.run_until(
        st, lambda s: float(model.coverage(s.model, s.faults.alive,
                                           0, 50)) == 1.0,
        max_rounds=40, check_every=2)
    assert r != -1
    deg_b = float(model.eager_degree(st.model, 0))
    assert deg_b > deg_a, (deg_a, deg_b)        # fresh flood, not A's tree
    # stale-epoch traffic cannot re-prune: converge B's tree too
    for ver in (51, 52, 53):
        st = st._replace(model=model.broadcast(st.model, 8, 0,
                                               version=ver))
        st = cl.steps(st, 12)
    assert float(model.coverage(st.model, st.faults.alive, 0, 53)) == 1.0
