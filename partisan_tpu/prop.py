"""Property-based distributed testing harness.

Mirrors the reference's PropEr state-machine harness ``prop_partisan.erl``
(1162 LoC): a generic runner is parameterized by a **system model**
(node_commands / node_initial_state / node_postconditions —
prop_partisan.erl:1097-1113) and a **fault model** (fault_commands with a
tolerance budget — :1038-1040; crash + omission commands,
prop_partisan_crash_fault_model.erl:33-37, :158-190), under one of three
**schedulers** (default / finite_fault / single_success — :66-108).

Commands are host-side scenario actions between jitted round batches;
randomness is a seeded ``random.Random`` so every run replays from its
seed (the PropEr shrink-replay loop).  On failure the harness greedily
shrinks the command sequence (SHRINKING mode) and reports the minimal
failing script.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Any, Callable, Protocol

from partisan_tpu import faults as faults_mod


@dataclasses.dataclass(frozen=True)
class Command:
    """One scripted action: ``apply(cluster, state) -> state``.
    ``kind`` is "node" (system model) or "fault" (fault model)."""

    name: str
    args: tuple
    apply: Callable[[Any, Any], Any]
    kind: str = "node"

    def __repr__(self) -> str:  # readable counterexamples
        return f"{self.name}{self.args}"


class SystemModel(Protocol):
    """The node_commands/node_initial_state/node_postconditions triple."""

    name: str

    def build(self) -> tuple[Any, Any]:
        """Boot the system; returns (cluster, booted state)."""
        ...

    def gen_command(self, rng: random.Random, cl: Any, st: Any) -> Command:
        ...

    def postcondition(self, cl: Any, st: Any,
                      script: list["Command"]) -> bool:
        """Checked after the run settles (node_postconditions).  ``script``
        is the executed command list, so the model can derive which
        operations were issued (the PropEr symbolic-state analogue)."""
        ...

    def settle_rounds(self) -> int:
        ...


class FaultModel(Protocol):
    tolerance: int

    def gen_fault(self, rng: random.Random, cl: Any, st: Any) -> Command:
        ...


# ---------------------------------------------------------------------------
# Fault models
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CrashFaultModel:
    """Crash-stop + omission faults with a tolerance bound
    (prop_partisan_crash_fault_model.erl:33-37: begin/end send+receive
    omissions, crash/stop, bounded by FAULT_TOLERANCE)."""

    tolerance: int = 1
    allow_crash: bool = True
    allow_omission: bool = True
    protect: frozenset = frozenset()   # nodes that must stay up (e.g. primary)

    def gen_fault(self, rng: random.Random, cl: Any, st: Any) -> Command:
        n = cl.cfg.n_nodes
        choices = []
        victims = [i for i in range(n) if i not in self.protect]
        if self.allow_crash and victims:
            choices.append("crash")
        if self.allow_omission:
            choices.append("omission")
        if not choices:
            raise ValueError(
                "CrashFaultModel: no fault kind available (crash disabled "
                "or all nodes protected, and omission disabled)")
        kind = rng.choice(choices)
        if kind == "crash":
            node = rng.choice(victims)
            return Command(
                name="crash", args=(node,), kind="fault",
                apply=lambda c, s, _node=node: s._replace(
                    faults=faults_mod.crash(s.faults, _node)))
        src = rng.randrange(n)
        dst = rng.choice([i for i in range(n) if i != src])
        return Command(
            name="omit_edge", args=(src, dst), kind="fault",
            apply=lambda c, s, _s=src, _d=dst: s._replace(
                faults=faults_mod.inject_partition(s.faults, [_s], [_d])))


# ---------------------------------------------------------------------------
# The runner
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RunResult:
    ok: bool
    seed: int
    commands: list[Command]
    shrunk: list[Command] | None = None

    def render(self) -> str:
        if self.ok:
            return f"prop: PASSED (seed={self.seed}, " \
                   f"{len(self.commands)} commands)"
        script = self.shrunk if self.shrunk is not None else self.commands
        lines = [f"prop: FAILED (seed={self.seed}); minimal script:"]
        lines += [f"  {c}" for c in script]
        return "\n".join(lines)


@dataclasses.dataclass
class Harness:
    """``n_runs`` random command sequences; each run boots fresh, applies
    ``n_commands`` commands (node and — under the finite_fault scheduler —
    fault commands up to the tolerance budget), settles, and checks the
    postcondition (prop_partisan.erl run loop; Makefile:80-81 runs 10)."""

    system: SystemModel
    fault_model: FaultModel | None = None
    scheduler: str = "default"   # default | finite_fault | single_success
    n_runs: int = 10
    n_commands: int = 8
    rounds_between: int = 2
    seed: int = 0
    heal_before_settle: bool = True   # omissions are transient windows:
    # partitions injected by fault commands resolve before the settle
    # phase (the end_omission command of the crash fault model,
    # prop_partisan_crash_fault_model.erl:158-190)

    def _one_run(self, seed: int) -> RunResult:
        script = self._gen_script(seed)
        ok = self._execute(script)
        if ok:
            return RunResult(ok=True, seed=seed, commands=script)
        return RunResult(ok=False, seed=seed, commands=script,
                         shrunk=self._shrink(script))

    def _gen_script(self, seed: int) -> list[Command]:
        rng = random.Random(seed)
        cl, st = self.system.build()     # only for generator context
        faults_left = (self.fault_model.tolerance
                       if (self.fault_model is not None
                           and self.scheduler == "finite_fault") else 0)
        script: list[Command] = []
        for _ in range(self.n_commands):
            if faults_left and rng.random() < 0.3:
                script.append(self.fault_model.gen_fault(rng, cl, st))
                faults_left -= 1
            else:
                script.append(self.system.gen_command(rng, cl, st))
        return script

    def _execute(self, script: list[Command]) -> bool:
        cl, st = self.system.build()
        for cmd in script:
            st = cmd.apply(cl, st)
            st = cl.steps(st, self.rounds_between)
        if self.heal_before_settle:
            st = st._replace(
                faults=faults_mod.resolve_partition(st.faults))
        st = cl.steps(st, self.system.settle_rounds())
        return bool(self.system.postcondition(cl, st, script))

    def _shrink(self, script: list[Command]) -> list[Command]:
        """Greedy delta-debugging: drop commands that aren't needed for
        the failure (the reference shrinks via PropEr + the SHRINKING
        replay flag, partisan_config.erl:593-607)."""
        current = list(script)
        changed = True
        while changed:
            changed = False
            for i in range(len(current)):
                trial = current[:i] + current[i + 1:]
                if trial and not self._execute(trial):
                    current = trial
                    changed = True
                    break
        return current

    def run(self) -> RunResult:
        last = None
        for i in range(self.n_runs):
            res = self._one_run(self.seed + i)
            if not res.ok:
                return res
            last = res
            if self.scheduler == "single_success":
                return res
        return last
