"""In-sim vectorized gen_server: every node hosts a server process and
a call table, all stepped under one jitted round.

This is the partisan_gen call protocol (priv/otp/24/partisan_gen.erl
:360-400) transposed onto the node axis: calls are ``GEN_CALL`` records
``{fn, arg, mref}`` on the wire; the server side applies requests *in
mailbox arrival order* (gen_server serialization — a prefix-scan gives
each call the counter value as of its position in the queue); replies
are ``GEN_REPLY {result, mref}`` paired by ref.  A caller-side timeout
demonitors the ref (late replies no longer match a WAITING slot — the
stale-reply discard); a WAITING call whose destination is dead aborts
with DOWN (the monitor path: partisan_monitor turning nodedown into a
DOWN signal).

The stock server is the conformance suites' counter machine:
``FN_INCR`` adds and replies the post-application value, ``FN_GET``
reads, ``FN_STOP`` terminates the server (further requests are never
answered — callers time out, the stopped-server behavior).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import Array

from partisan_tpu import types as T
from partisan_tpu.comm import LocalComm
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import msg as msg_ops
from partisan_tpu.ops import plane as plane_ops
from partisan_tpu.otp import client as client_mod

# server functions
FN_INCR, FN_GET, FN_STOP = 1, 2, 3


class GenSimState(NamedTuple):
    # server side (one gen_server per node)
    counter: Array    # int32[n_local]
    stopped: Array    # bool[n_local]
    # caller side (per-node call table)
    status: Array     # int32[n_local, C]
    dst: Array        # int32[n_local, C]
    fn: Array         # int32[n_local, C]
    arg: Array        # int32[n_local, C]
    ref: Array        # int32[n_local, C]
    deadline: Array   # int32[n_local, C]
    result: Array     # int32[n_local, C]
    next_ref: Array   # int32[n_local]


class GenServerService:
    """Stackable model: the counter gen_server + its call client."""

    name = "gen_server"

    def __init__(self, cap: int = 8) -> None:
        self.cap = cap

    def init(self, cfg: Config, comm: LocalComm) -> GenSimState:
        n, c = comm.n_local, self.cap
        zi = jnp.zeros((n, c), jnp.int32)
        return GenSimState(
            counter=jnp.zeros((n,), jnp.int32),
            stopped=jnp.zeros((n,), jnp.bool_),
            status=zi, dst=zi, fn=zi, arg=zi, ref=zi, deadline=zi,
            result=zi, next_ref=jnp.ones((n,), jnp.int32))

    # ------------------------------------------------------------------
    def step(self, cfg: Config, comm: LocalComm, st: GenSimState,
             ctx: RoundCtx, nbrs: Array) -> tuple[GenSimState, Array]:
        n, c = st.status.shape
        gids = comm.local_ids()
        alive = ctx.alive
        inb = ctx.inbox.data

        # ---- server: apply requests in mailbox order -------------------
        serving = alive & ~st.stopped
        m_call = (inb[..., T.W_KIND] == T.MsgKind.GEN_CALL) \
            & serving[:, None]
        m_cast = (inb[..., T.W_KIND] == T.MsgKind.GEN_CAST) \
            & serving[:, None]
        fn_w = inb[..., T.P0]
        arg_w = inb[..., T.P1]
        ref_w = inb[..., T.P2]

        # A stop anywhere in the queue: requests AFTER it (inbox order)
        # go unserved — the server is gone by the time they'd dispatch.
        is_stop = m_call & (fn_w == FN_STOP)
        stop_before = jnp.cumsum(is_stop, axis=1) - is_stop  # exclusive
        served = (m_call | m_cast) & (stop_before == 0)
        m_call = m_call & (stop_before == 0)

        incr = served & (fn_w == FN_INCR)
        inc_prefix = jnp.cumsum(jnp.where(incr, arg_w, 0), axis=1)
        counter = st.counter + jnp.sum(
            jnp.where(incr, arg_w, 0), axis=1, dtype=jnp.int32)
        # reply value as of this call's queue position: incr sees the
        # inclusive prefix, get the exclusive one
        val_incr = st.counter[:, None] + inc_prefix
        val_get = st.counter[:, None] + (inc_prefix
                                         - jnp.where(incr, arg_w, 0))
        res = jnp.where(fn_w == FN_INCR, val_incr, val_get)
        res = jnp.where(fn_w == FN_STOP, 0, res)
        stopped = st.stopped | (alive & is_stop.any(axis=1))

        resp_dst = jnp.where(m_call & (ref_w > 0), inb[..., T.W_SRC], -1)
        resp = msg_ops.build(
            cfg, T.MsgKind.GEN_REPLY, gids[:, None], resp_dst,
            payload=(res, ref_w))

        # ---- caller side: the shared gen call client -------------------
        status, result, req = client_mod.client_round(
            cfg, comm, ctx, status=st.status, dst=st.dst, a=st.fn,
            b=st.arg, ref=st.ref, deadline=st.deadline, result=st.result)

        emitted = plane_ops.concat([resp, req], axis=1)
        return st._replace(counter=counter, stopped=stopped,
                           status=status, result=result), emitted

    # ---- host-side API (the partisan_gen_server:call surface) ---------
    def call(self, st: GenSimState, caller: int, dst: int, fn: int,
             arg: int, timeout_rounds: int, now: int
             ) -> tuple[GenSimState, int]:
        ref = int(st.next_ref[caller])
        st = client_mod.alloc(st, caller, dst=dst, fn=fn, arg=arg,
                              ref=ref, deadline=now + timeout_rounds,
                              result=0)
        return st._replace(next_ref=st.next_ref.at[caller].add(1)), ref

    def cast(self, st: GenSimState, caller: int, dst: int, fn: int,
             arg: int) -> GenSimState:
        return client_mod.alloc(st, caller, dst=dst, fn=fn, arg=arg,
                                ref=0, deadline=0, result=0)

    def response(self, st: GenSimState, caller: int, ref: int
                 ) -> tuple[str, int | None]:
        """('ok', value) | ('timeout', None) | ('down', None) |
        ('waiting', None)."""
        return client_mod.response(st, caller, ref)

    def free(self, st: GenSimState, caller: int, ref: int) -> GenSimState:
        return client_mod.free(st, caller, ref)
