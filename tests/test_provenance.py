"""Provenance-plane suite (provenance.py + the word-pair threading
through cluster/delivery/channels/interpose):

- the disabled default keeps the ClusterState leaf an empty () and the
  wire at its pre-provenance width — and enabling the plane must not
  perturb the simulation (read-only plane, bit-for-bit),
- the ACCEPTANCE gate: the device-accumulated dissemination forest and
  redundancy/control rings match the host trace-replay oracle
  (tests/support.py ProvenanceOracle) EXACTLY on dozens of randomized
  (support.ORACLE_TRIALS-sized),
  faulted and churned overlays, for both the plumtree spec (hop +
  epoch words) and the hop-less rumor-mongering spec,
- slot recycles (epoch bumps) reset the forest entry on both sides,
- sharded runs record identical tables (skips without shard_map), and
  width-operand prefix runs match native-width runs,
- host-side readers (tree/redundancy/rows), the partisan.broadcast.*
  bus events, the Perfetto flow-event export, and the bridge's widened
  injection path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from partisan_tpu import provenance as prov_mod
from partisan_tpu import telemetry
from partisan_tpu import types as T
from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config, PlumtreeConfig
from partisan_tpu.models.plumtree import Plumtree
from partisan_tpu.models.rumor_mongering import RumorMongering

from tests import support

K = 6           # record-batch grain: ONE compiled capture program


def _pt_cfg(n=14, **kw):
    kw.setdefault("seed", 13)
    kw.setdefault("provenance_ring", 128)
    kw.setdefault("plumtree", PlumtreeConfig(push_slots=2, lazy_cap=4))
    # monotonic_shed=False: the oracle's ctl EMITTED parity needs the
    # captured pre-fault stack to equal the accumulator's pre-wire
    # reference point (support.ProvenanceOracle docstring)
    return Config(n_nodes=n, peer_service_manager="hyparview",
                  msg_words=16, partition_mode="groups",
                  max_broadcasts=4, inbox_cap=16, provenance=True,
                  monotonic_shed=False, **kw)


_CACHE: dict = {}


def _cluster(key, make):
    if key not in _CACHE:
        _CACHE[key] = make()
    return _CACHE[key]


# ---------------------------------------------------------------------------
# Randomized trial driver (shared by the plumtree / rumor parity gates)
# ---------------------------------------------------------------------------

def _record(cl, st, oracle, batches=1):
    """Record `batches` K-round batches, replaying each into the
    oracle with the batch's (host-set, hence constant) alive mask."""
    for _ in range(batches):
        alive = np.asarray(jax.device_get(st.faults.alive)).copy()
        st, tr = cl.record(st, K)
        oracle.replay(np.asarray(tr.sent), np.asarray(tr.dropped),
                      np.asarray(tr.rnd), alive)
    return st


def _random_overlay_trial(cl, cfg, rng, *, inject):
    """One randomized/faulted/churned overlay: random join topology,
    random broadcast origins/slots, random crashes (and a recovery),
    random iid link drop — everything the wire can throw at the
    accumulator.  Returns (final state, replayed oracle)."""
    n = cfg.n_nodes
    st = cl.init()
    oracle = support.ProvenanceOracle(cfg, cl.model.prov_spec)

    # random join DAG: every node joins via a random already-joined node
    m = st.manager
    joined = [0]
    for i in rng.permutation(np.arange(1, n)):
        m = cl.manager.join(cfg, m, int(i), int(rng.choice(joined)))
        joined.append(int(i))
    st = _record(cl, st._replace(manager=m), oracle, 3)

    # 1-2 broadcasts from random origins into random distinct slots
    slots = rng.choice(cfg.max_broadcasts, size=int(rng.integers(1, 3)),
                       replace=False)
    for b in slots:
        node = int(rng.integers(0, n))
        start = int(jax.device_get(st.rnd))
        st = st._replace(
            model=inject(cl, st.model, node, int(b), start),
            provenance=prov_mod.mark_origin(st.provenance, node, int(b),
                                            rnd=start))
        oracle.mark_origin(node, int(b), rnd=start)
        st = _record(cl, st, oracle, 1)

    # faults: iid link drop, then up to 2 crashes, then one recovery
    if rng.random() < 0.5:
        st = st._replace(faults=st.faults._replace(
            link_drop=jnp.float32(float(rng.uniform(0.05, 0.2)))))
    victims = rng.choice(n, size=int(rng.integers(0, 3)), replace=False)
    if victims.size:
        alive = st.faults.alive
        for v in victims:
            alive = alive.at[int(v)].set(False)
        st = st._replace(faults=st.faults._replace(alive=alive))
    st = _record(cl, st, oracle, 2)
    if victims.size and rng.random() < 0.5:
        alive = st.faults.alive.at[int(victims[0])].set(True)
        st = st._replace(faults=st.faults._replace(alive=alive))
        st = _record(cl, st, oracle, 1)
    return st, oracle


def _assert_matches_oracle(cfg, st, oracle, trial):
    snap = prov_mod.snapshot(st.provenance)
    for name in ("parent", "hop", "claim_rnd", "epoch"):
        assert np.array_equal(snap[name], getattr(oracle, name)), \
            (trial, name, snap[name], getattr(oracle, name))
    assert np.array_equal(snap["depth_hwm"], oracle.depth_hwm), trial
    assert np.array_equal(snap["cover_rnd"], oracle.cover_rnd), trial
    assert snap["dup_total"] == oracle.dup_total, trial
    assert snap["gossip_total"] == oracle.gossip_total, trial
    # per-round rings (ring > total rounds here: zero wraparound loss)
    for i, rnd in enumerate(snap["rounds"]):
        want = oracle.rows[int(rnd)]
        assert np.array_equal(snap["dup"][i], want["dup"]), (trial, rnd)
        assert snap["gossip"][i] == want["gossip"], (trial, rnd)
        assert snap["claims"][i] == want["claims"], (trial, rnd)
        assert np.array_equal(snap["ctl"][i], want["ctl"]), (trial, rnd)


def test_plumtree_parity_with_oracle_on_randomized_overlays():
    """The acceptance gate: ORACLE_TRIALS plumtree overlays (randomized join
    topology, random origins, crashes, recovery, iid link drop) — the
    device plane must equal the host trace-replay oracle EXACTLY:
    forest tables, per-round redundancy/control rings, depth high-water
    marks, time-to-coverage, cumulative totals."""
    cfg = _pt_cfg()
    cl = _cluster("pt", lambda: Cluster(cfg, model=Plumtree()))
    from support import ORACLE_TRIALS

    rng = np.random.default_rng(42)
    gossip_seen = dup_seen = 0
    for trial in range(ORACLE_TRIALS):
        st, oracle = _random_overlay_trial(
            cl, cfg, rng,
            inject=lambda cl, m, node, b, start:
                cl.model.broadcast(m, node, b, start))
        _assert_matches_oracle(cfg, st, oracle, trial)
        gossip_seen += oracle.gossip_total
        dup_seen += oracle.dup_total
    # the trials exercised real dissemination AND real redundancy
    assert gossip_seen > 0 and dup_seen > 0


def test_rumor_parity_with_oracle_on_randomized_overlays():
    """The hop-less spec (no hop word, no epoch word, APP-kind payload
    filter): >= 10 randomized rumor-mongering overlays against the same
    oracle — every claim lands at hop 1, the forest stays exact."""
    cfg = Config(n_nodes=12, seed=7, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups",
                 max_broadcasts=4, inbox_cap=16, provenance=True,
                 provenance_ring=128, monotonic_shed=False)
    cl = _cluster("rumor", lambda: Cluster(cfg, model=RumorMongering()))
    rng = np.random.default_rng(11)
    gossip_seen = 0
    for trial in range(10):
        st, oracle = _random_overlay_trial(
            cl, cfg, rng,
            inject=lambda cl, m, node, b, start:
                cl.model.broadcast(m, node, b))
        _assert_matches_oracle(cfg, st, oracle, trial)
        gossip_seen += oracle.gossip_total
        snap = prov_mod.snapshot(st.provenance)
        claimed = snap["parent"] >= 0
        own = snap["parent"] == np.arange(cfg.n_nodes)[:, None]
        assert (snap["hop"][claimed & ~own] == 1).all()
    assert gossip_seen > 0


def test_slot_recycle_epoch_resets_forest_entry():
    """A fresh=True recycle bumps the slot epoch: receivers adopting
    the higher epoch RESET their forest entry and re-grow the tree for
    the new root — stale-epoch copies stay in the duplicate count
    (both sides, oracle-gated)."""
    cfg = _pt_cfg()
    cl = _cluster("pt", lambda: Cluster(cfg, model=Plumtree()))
    rng = np.random.default_rng(3)
    st = cl.init()
    oracle = support.ProvenanceOracle(cfg, cl.model.prov_spec)
    m = st.manager
    for i in range(1, cfg.n_nodes):
        m = cl.manager.join(cfg, m, i, 0)
    st = _record(cl, st._replace(manager=m), oracle, 3)

    start = int(jax.device_get(st.rnd))
    st = st._replace(model=cl.model.broadcast(st.model, 0, 0, start),
                     provenance=prov_mod.mark_origin(st.provenance, 0, 0,
                                                     rnd=start))
    oracle.mark_origin(0, 0, rnd=start)
    st = _record(cl, st, oracle, 3)
    first_parent = prov_mod.snapshot(st.provenance)["parent"][:, 0].copy()
    assert (first_parent >= 0).sum() > 1

    # recycle slot 0 from a DIFFERENT root with a dominating version
    start = int(jax.device_get(st.rnd))
    st = st._replace(model=cl.model.broadcast(st.model, 5, 0, start + 1,
                                              fresh=True))
    ep = int(jax.device_get(st.model.epoch)[5, 0])
    st = st._replace(provenance=prov_mod.mark_origin(
        st.provenance, 5, 0, rnd=start, epoch=ep))
    oracle.mark_origin(5, 0, rnd=start, epoch=ep)
    assert ep > 0
    st = _record(cl, st, oracle, 3)
    _assert_matches_oracle(cfg, st, oracle, "recycle")
    snap = prov_mod.snapshot(st.provenance)
    recycled = snap["epoch"][:, 0] == ep
    assert recycled.sum() > 1
    # re-grown entries claim within the new epoch; node 5 is the root
    assert snap["parent"][5, 0] == 5 and snap["hop"][5, 0] == 0
    _ = rng  # (kept for symmetry with the other drivers)


# ---------------------------------------------------------------------------
# Zero-cost default + read-only plane
# ---------------------------------------------------------------------------

def test_disabled_default_zero_overhead():
    """provenance=False (the default) keeps the state leaf an empty ()
    and the wire at its previous width; no provenance phase is compiled
    into the round."""
    cfg = Config(n_nodes=16, seed=1)
    cl = Cluster(cfg)
    st = cl.init()
    assert st.provenance == ()
    assert len(jax.tree.leaves(st.provenance)) == 0
    assert st.inbox.data.shape[-1] == cfg.msg_words
    st2 = cl.steps(st, 5)
    assert st2.provenance == ()
    # the lint zero-cost rule reads every equation's named_scope stack:
    # no round.provenance phase traced into the program (str(jaxpr)
    # greps never saw scope names — this is the real check)
    support.assert_scan_lint_clean(cl, st, 4)


def test_wire_layout_with_latency_plane():
    """Both planes on: wire = msg_words + 3, provenance pair at
    msg_words/msg_words+1, birth round LAST (latency.py's [..., -1]
    indexing holds — its histograms still reconcile)."""
    from partisan_tpu import latency as latency_mod
    from partisan_tpu import metrics as metrics_mod

    cfg = _pt_cfg(latency=True, metrics=True, metrics_ring=64)
    assert cfg.wire_words == cfg.msg_words + 3
    assert prov_mod.src_word(cfg) == cfg.msg_words
    assert prov_mod.hop_word(cfg) == cfg.msg_words + 1
    cl = Cluster(cfg, model=Plumtree())
    st = cl.init()
    m = st.manager
    for i in range(1, cfg.n_nodes):
        m = cl.manager.join(cfg, m, i, 0)
    st = cl.steps(st._replace(manager=m), 12)
    st = st._replace(model=cl.model.broadcast(st.model, 0, 0, 12),
                     provenance=prov_mod.mark_origin(st.provenance, 0, 0,
                                                     rnd=12))
    st = cl.steps(st, 12)
    assert st.inbox.data.shape[-1] == cfg.msg_words + 3
    lsnap = latency_mod.snapshot(st.latency)
    msnap = metrics_mod.snapshot(st.metrics)
    assert (lsnap["deliver"].sum(axis=1)
            == msnap["delivered"].sum(axis=0)).all()
    assert prov_mod.snapshot(st.provenance)["gossip_total"] > 0


def test_provenance_plane_is_read_only():
    """Enabling the plane must not perturb the simulation: every
    protocol leaf of a provenance run equals the off run's bit for bit,
    and the inbox's first msg_words words agree (the widened wire
    carries the pair strictly OUTSIDE the protocol record)."""
    def drive(on):
        cfg = _pt_cfg(18).replace(provenance=on)
        cl = Cluster(cfg, model=Plumtree())
        st = cl.init()
        m = st.manager
        for i in range(1, 18):
            m = cl.manager.join(cfg, m, i, 0)
        st = cl.steps(st._replace(manager=m), 12)
        st = st._replace(model=cl.model.broadcast(st.model, 0, 0, 12))
        al = st.faults.alive.at[5].set(False)
        st = st._replace(faults=st.faults._replace(
            alive=al, link_drop=jnp.float32(0.1)))
        return cl.steps(st, 12)

    st_off = drive(False)
    st_on = drive(True)
    assert st_off.provenance == () and st_on.provenance != ()
    for name in ("rnd", "manager", "model", "stats", "faults",
                 "delivery"):
        a = jax.tree.leaves(getattr(st_off, name))
        b = jax.tree.leaves(getattr(st_on, name))
        assert len(a) == len(b), name
        for x, y in zip(a, b):
            assert np.array_equal(np.asarray(x), np.asarray(y)), name
    off_w = st_off.inbox.data.shape[-1]
    assert np.array_equal(np.asarray(st_on.inbox.data)[..., :off_w],
                          np.asarray(st_off.inbox.data))
    assert np.array_equal(np.asarray(st_on.inbox.count),
                          np.asarray(st_off.inbox.count))


def test_provenance_state_is_scan_carry_no_callbacks():
    """No host transfer inside the scan: the forest + rings ride the
    lax.scan carry (shared lint rules — see tests/support.py)."""
    cfg = _pt_cfg(8, provenance_ring=8)
    cl = Cluster(cfg, model=Plumtree())
    st = cl.init()
    support.assert_scan_lint_clean(cl, st, 6)
    out = cl.steps(st, 6)
    assert prov_mod.snapshot(out.provenance)["rounds"].tolist() \
        == [0, 1, 2, 3, 4, 5]


# ---------------------------------------------------------------------------
# Host-side readers + ring semantics
# ---------------------------------------------------------------------------

def _tree_run():
    """Shared aae=False plumtree run with one fully-disseminated
    broadcast (aae off: the state-scatter walk bypasses the wire, and
    this run exists to read a complete WIRE tree)."""
    if "tree" in _CACHE:
        return _CACHE["tree"]
    cfg = _pt_cfg(16, plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4,
                                              aae=False))
    cl = Cluster(cfg, model=Plumtree())
    st = cl.init()
    m = st.manager
    for i in range(1, 16):
        m = cl.manager.join(cfg, m, i, 0)
    st = cl.steps(st._replace(manager=m), 20)
    start = int(jax.device_get(st.rnd))
    st = st._replace(model=cl.model.broadcast(st.model, 0, 0, start),
                     provenance=prov_mod.mark_origin(st.provenance, 0, 0,
                                                     rnd=start))
    st = cl.steps(st, 30)
    _CACHE["tree"] = (cfg, st)
    return _CACHE["tree"]


def test_tree_reconstruction_and_redundancy_readers():
    """provenance.tree(slot) reconstructs the spanning tree that
    ACTUALLY delivered: one root (the marked origin), every claimed
    node reachable root-ward, depth stats consistent with the hop
    table; redundancy() reports the duplicate fraction."""
    cfg, st = _tree_run()
    snap = prov_mod.snapshot(st.provenance)
    t = prov_mod.tree(snap, 0)
    assert t["roots"] == [0]
    assert t["claimed"] == 16                   # full wire coverage
    assert t["cover_round"] >= 0
    assert snap["cover_rnd"][0] == t["cover_round"]
    parent, hop = t["parent"], t["hop"]
    assert t["depth_max"] == hop.max() == snap["depth_hwm"][0]
    # every non-root claim walks to the root with hops DESCENDING by 1
    for i in range(16):
        if i == 0:
            continue
        j, steps = i, 0
        while j != 0 and steps <= 16:
            assert hop[parent[j]] == hop[j] - 1
            j, steps = parent[j], steps + 1
        assert j == 0
    red = prov_mod.redundancy(snap)
    assert red["gossip_delivered"] == snap["gossip_total"]
    assert red["duplicates"] == snap["dup_total"]
    if red["gossip_delivered"]:
        assert red["redundancy_ratio"] == pytest.approx(
            red["duplicates"] / red["gossip_delivered"], abs=1e-4)
    rows = prov_mod.rows(snap, channels=tuple(
        c.name for c in cfg.channels))
    assert sum(r["gossip_delivered"] for r in rows) \
        == snap["gossip_total"]
    assert sum(r["first_deliveries"] for r in rows) == 15  # non-origins


def test_ring_wraparound_keeps_cumulative_totals():
    """A ring smaller than the run: snapshot returns the most recent
    window (labels ascending), while dup_cum/gossip_cum keep the
    whole-run totals."""
    cfg = Config(n_nodes=8, seed=5, inbox_cap=32, provenance=True,
                 provenance_ring=8, monotonic_shed=False)
    cl = Cluster(cfg, model=RumorMongering())
    st = cl.init()
    m = st.manager
    for i in range(1, 8):
        m = cl.manager.join(cfg, m, i, 0)
    st = cl.steps(st._replace(manager=m), 4)
    st = st._replace(model=cl.model.broadcast(st.model, 0, 0),
                     provenance=prov_mod.mark_origin(st.provenance, 0, 0,
                                                     rnd=4))
    st = cl.steps(st, 26)
    snap = prov_mod.snapshot(st.provenance)
    assert len(snap["rounds"]) == 8
    assert snap["rounds"].tolist() == list(range(22, 30))
    assert snap["gossip_total"] >= snap["gossip"].sum()
    assert snap["dup_total"] >= snap["dup"].sum()
    assert snap["gossip_total"] > 0


def test_stack_exposes_first_submodel_spec():
    """Stack resolves prov_spec to the FIRST sub-model that defines one
    (the coverage first-wins rule)."""
    from partisan_tpu.models.p2p_chat import P2PChat
    from partisan_tpu.models.stack import Stack

    st = Stack([Plumtree(), P2PChat()])
    assert st.prov_spec == Plumtree().prov_spec
    assert Stack([P2PChat()]).prov_spec is None


# ---------------------------------------------------------------------------
# Telemetry events + plumtree_metrics summarization (satellite)
# ---------------------------------------------------------------------------

def _synthetic_snap():
    R, C = 8, 2
    gi = prov_mod.CTL_NAMES.index("graft")
    snap = {
        "rounds": np.arange(R),
        "dup": np.zeros((R, C), np.int64),
        "gossip": np.zeros(R, np.int64),
        "claims": np.zeros(R, np.int64),
        "ctl": np.zeros((R, prov_mod.N_CTL, 2), np.int64),
    }
    # rounds 1-2: sustained redundancy flood (one edge-triggered event)
    snap["gossip"][1:3] = 10
    snap["dup"][1, 0] = 6
    snap["dup"][2, 1] = 7
    # round 3: small round — 1 dup of 2 deliveries is NOT a spike
    snap["gossip"][3] = 2
    snap["dup"][3, 0] = 1
    # rounds 4-5: graft storm; round 6: first graft-free round
    snap["ctl"][4, gi, 1] = 3
    snap["ctl"][5, gi, 1] = 1
    return snap


def test_replay_broadcast_events_on_bus():
    rec = telemetry.Recorder()
    bus = telemetry.Bus()
    bus.attach("t", ("partisan", "broadcast"), rec)
    n = telemetry.replay_broadcast_events(bus, _synthetic_snap())
    assert n == 3
    events = [e for (e, _m, _meta) in rec.events]
    assert events == [telemetry.BROADCAST_REDUNDANCY,
                      telemetry.BROADCAST_GRAFT_STORM,
                      telemetry.BROADCAST_TREE_REPAIRED]
    spike = rec.of(telemetry.BROADCAST_REDUNDANCY)[0]
    assert spike[1]["ratio"] == pytest.approx(0.6)
    assert spike[2]["round"] == 1
    storm = rec.of(telemetry.BROADCAST_GRAFT_STORM)[0]
    assert storm[1]["grafts"] == 3 and storm[2]["round"] == 4
    healed = rec.of(telemetry.BROADCAST_TREE_REPAIRED)[0]
    assert healed[1]["storm_rounds"] == 2 and healed[2]["round"] == 6


def test_plumtree_metrics_summarized_above_threshold(monkeypatch):
    """The satellite: recycle_nonmonotone_nodes must not ship an O(n)
    id list for a 100k-node poll — above CONNECTION_COUNTS_FULL_MAX the
    auto mode summarizes (count + first ids), below it stays full."""
    import types as pytypes

    n, B, KK = 12, 2, 3
    nonmono = np.zeros(n, bool)
    nonmono[[3, 7]] = True
    pt = pytypes.SimpleNamespace(
        tree_nbrs=np.full((n, KK), -1, np.int64),
        pruned=np.zeros((n, B, KK), bool),
        nonmono=nonmono)
    full = telemetry.plumtree_metrics(pt)          # auto, small n
    assert full["recycle_nonmonotone"] == 2
    assert full["recycle_nonmonotone_nodes"] == [3, 7]
    assert "recycle_nonmonotone_summary" not in full
    monkeypatch.setattr(telemetry, "CONNECTION_COUNTS_FULL_MAX", 8)
    summ = telemetry.plumtree_metrics(pt)          # auto, "large" n
    assert "recycle_nonmonotone_nodes" not in summ
    assert summ["recycle_nonmonotone_summary"]["nodes"] == 2
    assert summ["recycle_nonmonotone_summary"]["first"] == [3, 7]
    # explicit modes override auto
    assert "recycle_nonmonotone_nodes" in telemetry.plumtree_metrics(
        pt, mode="full")
    monkeypatch.setattr(telemetry, "CONNECTION_COUNTS_FULL_MAX", 4096)
    assert "recycle_nonmonotone_summary" in telemetry.plumtree_metrics(
        pt, mode="summary")
    with pytest.raises(ValueError):
        telemetry.plumtree_metrics(pt, mode="bogus")


def test_perfetto_export_grows_dissemination_flow_events(tmp_path):
    """trace_export grows parent-linked flow events: every non-root
    claim becomes an s->f flow arrow from the parent's track at the
    parent's claim round to the child's track at its claim round — the
    dissemination tree as Perfetto renders it."""
    import json
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import trace_export

    _cfg, st = _tree_run()
    snap = prov_mod.snapshot(st.provenance)
    flows = trace_export.to_flow_events(snap, slots=(0,))
    starts = [e for e in flows if e["ph"] == "s"]
    ends = [e for e in flows if e["ph"] == "f"]
    assert len(starts) == len(ends) == 15      # one arrow per non-root
    by_id = {e["id"]: e for e in starts}
    parent = snap["parent"][:, 0]
    claim = snap["claim_rnd"][:, 0]
    for e in ends:
        s = by_id[e["id"]]
        child = e["tid"]
        assert s["tid"] == parent[child]
        assert s["ts"] <= e["ts"]
        assert e["ts"] == claim[child] * 1000 * 1000
    # export() merges the flows into the trace file
    out = tmp_path / "prov.json"
    from partisan_tpu.trace import Trace

    tr = Trace(np.zeros((1, 16, 1, 16), np.int32),
               np.zeros((1, 16, 1), bool))
    n = trace_export.export(tr, str(out), provenance=snap)
    data = json.loads(out.read_text())
    kinds = {e["ph"] for e in data["traceEvents"]}
    assert {"s", "f"} <= kinds
    assert n == 30      # 15 flow arrows x (s + f), nothing else live


# ---------------------------------------------------------------------------
# Sharded + width-operand parity
# ---------------------------------------------------------------------------

def test_sharded_forest_and_rings_match_single_device():
    """Placement invariance: the same run on 1 device and on the 8-way
    mesh records identical forest tables (node-sharded on axis 0) and
    redundancy/control rings (reduced before every write)."""
    if not hasattr(jax, "shard_map"):
        pytest.skip("jax.shard_map unavailable on this jax "
                    "(parallel/sharded.py requires it)")
    from partisan_tpu.parallel.sharded import ShardedCluster, make_mesh

    cfg = Config(n_nodes=16, seed=3, inbox_cap=24, provenance=True,
                 provenance_ring=64, monotonic_shed=False)

    def drive(cl):
        st = cl.init()
        m = st.manager
        for i in range(1, 16):
            m = cl.manager.join(cfg, m, i, 0)
        st = cl.steps(st._replace(manager=m), 4)
        st = st._replace(model=cl.model.broadcast(st.model, 0, 0),
                         provenance=prov_mod.mark_origin(
                             st.provenance, 0, 0, rnd=4))
        alive = st.faults.alive.at[7].set(False)
        st = st._replace(faults=st.faults._replace(alive=alive))
        return cl.steps(st, 20)

    st_l = drive(Cluster(cfg, model=RumorMongering()))
    st_s = drive(ShardedCluster(cfg, make_mesh(), model=RumorMongering()))
    snap_l = prov_mod.snapshot(st_l.provenance)
    snap_s = prov_mod.snapshot(st_s.provenance)
    for name, series in snap_l.items():
        assert np.array_equal(series, snap_s[name]), name
    assert snap_l["gossip_total"] > 0
    assert (snap_l["parent"][:, 0] >= 0).sum() > 1


def test_width_operand_masks_inactive_prefix_rows():
    """Under Config.width_operand, inactive rows are invisible: a
    prefix-activated run accumulates the same forest prefix and the
    same redundancy rings as a native-width run, and the inactive rows
    keep their init values."""
    from partisan_tpu import cluster as cluster_mod

    def boot(cl, n):
        st = cl.init()
        if cl.cfg.width_operand:
            st = cluster_mod.activate(st, n)
        m = st.manager
        for i in range(1, n):
            m = cl.manager.join(cl.cfg, m, i, 0)
        st = cl.steps(st._replace(manager=m), 12)
        start = int(jax.device_get(st.rnd))
        st = st._replace(model=cl.model.broadcast(st.model, 0, 0, start),
                         provenance=prov_mod.mark_origin(
                             st.provenance, 0, 0, rnd=start))
        return cl.steps(st, 16)

    n = 12
    st_n = boot(Cluster(_pt_cfg(n, seed=6), model=Plumtree()), n)
    st_w = boot(Cluster(_pt_cfg(2 * n, seed=6, width_operand=True),
                        model=Plumtree()), n)
    snap_n = prov_mod.snapshot(st_n.provenance)
    snap_w = prov_mod.snapshot(st_w.provenance)
    for name in ("parent", "hop", "claim_rnd", "epoch"):
        assert np.array_equal(snap_w[name][:n], snap_n[name]), name
        init = -1 if name in ("parent", "claim_rnd") else 0
        assert (snap_w[name][n:] == init).all(), name
    for name in ("rounds", "dup", "gossip", "claims", "ctl",
                 "depth_hwm", "cover_rnd"):
        assert np.array_equal(snap_w[name], snap_n[name]), name
    assert snap_w["gossip_total"] == snap_n["gossip_total"]
    assert snap_w["dup_total"] == snap_n["dup_total"]
    assert snap_n["gossip_total"] > 0


# ---------------------------------------------------------------------------
# Bridge injection path
# ---------------------------------------------------------------------------

def test_bridge_forward_drain_under_provenance():
    """The bridge widens injected records with the (emitter gid, hop 0)
    pair — and drains payloads WITHOUT leaking the pair (or the birth
    word when both planes are on) to the Erlang side."""
    from partisan_tpu.bridge import etf
    from partisan_tpu.bridge.etf import Atom
    from partisan_tpu.bridge.server import Bridge

    br = Bridge()
    assert br.handle((Atom("init"), {Atom("n_nodes"): 4,
                                     Atom("provenance"): True,
                                     Atom("latency"): True})) == etf.OK
    assert br.handle((Atom("forward_message"), 1, 0, [42, 7])) == etf.OK
    ok, _rnd = br.handle((Atom("step"), 1))
    assert ok == etf.OK
    ok, msgs = br.handle((Atom("drain"), 0))
    assert ok == etf.OK
    assert len(msgs) == 1
    src, payload = msgs[0]
    assert src == 1 and payload[:2] == [42, 7]
    assert len(payload) == 12 - T.HDR_WORDS


def test_plane_parity_provenance_pair():
    """Narrow-packing parity with the provenance pair (wire_words =
    msg_words + 2; the hop word stores int16)."""
    from support import plane_parity_case

    def mk(pm):
        return Config(n_nodes=64, seed=5, peer_service_manager="hyparview",
                      msg_words=16, partition_mode="groups",
                      max_broadcasts=4, inbox_cap=8, provenance=True,
                      plane_major=pm,
                      plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4))

    plane_parity_case(mk, label="prov_pair")


def test_plane_parity_full_wire():
    """Provenance pair + latency birth word together (wire_words =
    msg_words + 3) — the widest wire the planes carry."""
    from support import plane_parity_case

    def mk(pm):
        return Config(n_nodes=64, seed=5, peer_service_manager="hyparview",
                      msg_words=16, partition_mode="groups",
                      max_broadcasts=4, inbox_cap=8, provenance=True,
                      latency=True, plane_major=pm,
                      plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4))

    plane_parity_case(mk, label="full_wire")
