"""Python-level static hygiene: a pyflakes-lite fallback.

The tier-1 hygiene gate (tests/test_lint.py) prefers a real ``ruff
check`` under the pinned config in ``ruff.toml``; this module is the
dependency-free fallback for environments without ruff (this repo's
container bakes no lint toolchain and installing one is off the table).
It implements the same rule subset the pinned config selects, scoped
the way pyflakes scopes them:

- **F401** unused import — per-scope (module / function / class body):
  an import is used if its bound name is loaded anywhere in the binding
  scope's subtree (nested functions included — closure lookup), named
  in ``__all__``, or explicitly re-exported via a self-alias
  (``import x as x`` / ``from m import y as y``).  ``__init__.py``
  files are exempt wholesale (re-export surface), matching the
  per-file-ignores in ruff.toml.
- **F403** ``from m import *`` — bans the one construct that makes
  usage analysis (human or machine) impossible.
- **E401** multiple modules on one ``import`` statement.

``# noqa`` / ``# noqa: CODE`` comments on the flagged line suppress,
same contract as ruff.
"""

from __future__ import annotations

import ast
import os
import re
from typing import NamedTuple

_NOQA = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z0-9, ]+))?",
                   re.IGNORECASE)


class PyFinding(NamedTuple):
    file: str
    line: int
    code: str       # F401 | F403 | E401
    message: str


def _noqa_lines(src: str) -> dict[int, set[str] | None]:
    """line -> suppressed codes (None = bare noqa, suppress all)."""
    out: dict[int, set[str] | None] = {}
    for i, ln in enumerate(src.splitlines(), 1):
        m = _NOQA.search(ln)
        if m:
            codes = m.group("codes")
            out[i] = ({c.strip().upper() for c in codes.split(",")}
                      if codes else None)
    return out


class _Scope:
    """One binding scope: module, function, or class body."""

    def __init__(self, node):
        self.node = node
        # bound name -> (lineno, display, self_aliased)
        self.imports: dict[str, tuple[int, str, bool]] = {}
        self.used: set[str] = set()
        self.children: list[_Scope] = []

    def all_used(self) -> set[str]:
        u = set(self.used)
        for c in self.children:
            u |= c.all_used()
        return u


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


def _scan_nodes(nodes, scope, findings, noqa, fname):
    """Walk a statement/expression list inside one binding scope,
    recording import bindings and name uses, descending into nested
    scopes with fresh _Scope children."""
    for child in nodes:
        if isinstance(child, ast.Import):
            if len(child.names) > 1 and not _skip(noqa, child.lineno,
                                                  "E401"):
                findings.append(PyFinding(
                    fname, child.lineno, "E401",
                    "multiple imports on one line: "
                    + ", ".join(a.name for a in child.names)))
            for a in child.names:
                bound = (a.asname or a.name).split(".")[0]
                scope.imports[bound] = (
                    child.lineno, a.name, a.asname == a.name)
            continue
        if isinstance(child, ast.ImportFrom):
            if child.module == "__future__":
                continue
            for a in child.names:
                if a.name == "*":
                    if not _skip(noqa, child.lineno, "F403"):
                        findings.append(PyFinding(
                            fname, child.lineno, "F403",
                            f"star import from "
                            f"{child.module or '.'}"))
                    continue
                bound = a.asname or a.name
                scope.imports[bound] = (
                    child.lineno,
                    f"{child.module or '.'}.{a.name}",
                    a.asname == a.name)
            continue
        if isinstance(child, _SCOPE_NODES):
            sub = _Scope(child)
            scope.children.append(sub)
            # decorators/defaults/annotations/bases evaluate in the
            # ENCLOSING scope
            for field in ("decorator_list", "bases", "keywords"):
                for n in getattr(child, field, ()):
                    _uses(n, scope)
            args = getattr(child, "args", None)
            if args is not None:
                _ann_names(args, scope)
            returns = getattr(child, "returns", None)
            if returns is not None:
                _ann_names(returns, scope)
            _scan_nodes(child.body, sub, findings, noqa, fname)
            continue
        if isinstance(child, ast.Name):
            scope.used.add(child.id)
        if isinstance(child, ast.AnnAssign) \
                and child.annotation is not None:
            _ann_names(child.annotation, scope)
        _scan_nodes(ast.iter_child_nodes(child), scope, findings,
                    noqa, fname)


def _ann_names(ann, scope):
    """Names in an annotation subtree, parsing quoted annotations the
    way pyflakes does (``api: "Callable[[], ...]"`` marks Callable
    used)."""
    for n in ast.walk(ann):
        if isinstance(n, ast.Name):
            scope.used.add(n.id)
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            try:
                sub = ast.parse(n.value, mode="eval")
            except SyntaxError:
                continue
            for m in ast.walk(sub):
                if isinstance(m, ast.Name):
                    scope.used.add(m.id)


def _uses(node, scope):
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            scope.used.add(n.id)


def _skip(noqa, line, code) -> bool:
    if line not in noqa:
        return False
    codes = noqa[line]
    return codes is None or code.upper() in codes


def _dunder_all(tree) -> set[str]:
    names: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    for el in ast.walk(node.value):
                        if isinstance(el, ast.Constant) \
                                and isinstance(el.value, str):
                            names.add(el.value)
    return names


def scan_file(path: str, rel: str | None = None) -> list[PyFinding]:
    rel = rel or path
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, path)
    except SyntaxError as exc:
        return [PyFinding(rel, exc.lineno or 0, "E999",
                          f"syntax error: {exc.msg}")]
    noqa = _noqa_lines(src)
    findings: list[PyFinding] = []
    root = _Scope(tree)
    _scan_nodes(tree.body, root, findings, noqa, rel)
    is_init = os.path.basename(path) == "__init__.py"
    exported = _dunder_all(tree)

    def walk_scope(scope):
        used = scope.all_used()
        for bound, (line, display, self_alias) in scope.imports.items():
            if self_alias or bound in used:
                continue
            if scope is root and bound in exported:
                continue
            if is_init or _skip(noqa, line, "F401"):
                continue
            findings.append(PyFinding(
                rel, line, "F401", f"unused import: {display}"
                + (f" (as {bound})" if bound not in display.split(".")
                   else "")))
        for c in scope.children:
            walk_scope(c)

    walk_scope(root)
    return sorted(findings, key=lambda f: (f.file, f.line, f.code))


def scan_tree(root: str, rel_to: str | None = None) -> list[PyFinding]:
    """Scan every .py under ``root`` (file or directory), skipping
    __pycache__.  Paths in findings are relative to ``rel_to``."""
    rel_to = rel_to or os.getcwd()
    out: list[PyFinding] = []
    if os.path.isfile(root):
        return scan_file(root, os.path.relpath(root, rel_to))
    for dirpath, dirs, files in os.walk(root):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for f in sorted(files):
            if f.endswith(".py"):
                p = os.path.join(dirpath, f)
                out += scan_file(p, os.path.relpath(p, rel_to))
    return out
