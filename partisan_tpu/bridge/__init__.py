"""Erlang ↔ JAX bridge (SURVEY.md §5.8 / §7 step 7).

The north star requires the live Erlang ``protocols/`` suite and
filibuster replay to drive the simulated manager: an Erlang node loads
``partisan_sim_peer_service_manager`` (erl/ in this package), which
implements the peer-service-manager behaviour
(reference src/partisan_peer_service_manager.erl:93-170) by speaking a
``{packet, 4}``-framed External-Term-Format protocol over a port to the
Python process running :mod:`partisan_tpu.bridge.server`.

- :mod:`partisan_tpu.bridge.etf`    — wire codec (Erlang external term
  format, the ``term_to_binary`` framing of
  partisan_util.erl:171-183)
- :mod:`partisan_tpu.bridge.server` — the port server mapping behaviour
  calls onto a Cluster
- ``erl/partisan_sim_peer_service_manager.erl`` — the Erlang side
  (source; build with the reference's rebar project)
"""

from partisan_tpu.bridge import etf  # noqa: F401
