"""Interposition-fun parity tests (drop / rewrite / delay / schedules —
reference partisan_pluggable_peer_service_manager.erl:195-197, :58-130,
:1221-1237)."""

import jax.numpy as jnp
import numpy as np

from partisan_tpu import interpose, types as T
from partisan_tpu.cluster import Cluster
from partisan_tpu.models.direct_mail import DirectMail
from tests.support import fm_config, boot_fullmesh

N = 8


def _booted(interp=None, acked=False):
    cfg = fm_config(N, seed=5)
    model = DirectMail(acked=acked)
    cl = Cluster(cfg, model=model, interpose=interp)
    st = boot_fullmesh(cl)
    st = st._replace(model=model.broadcast(st.model, 0, 0))
    return cl, model, st


def _coverage(model, st):
    return float(model.coverage(st.model, st.faults.alive, 0))


def test_baseline_direct_mail_covers():
    cl, model, st = _booted()
    st = cl.steps(st, 10)
    assert _coverage(model, st) == 1.0


def test_drop_all_app_blocks_delivery():
    drop_app = interpose.Drop(
        lambda cfg, ctx, em: em[..., T.W_KIND] == T.MsgKind.APP)
    cl, model, st = _booted(drop_app)
    st = cl.steps(st, 10)
    # Only the broadcaster has the slot: every mail was interposed away.
    assert _coverage(model, st) == 1.0 / N


def test_rewrite_redirects_messages():
    # Rewrite every APP message's destination to node 1 (the
    # message-transformation interposition): the broadcast reaches node 1
    # but nobody else (direct mail has no repair path).
    def redirect(cfg, ctx, em):
        is_app = em[..., T.W_KIND] == T.MsgKind.APP
        return em.at[..., T.W_DST].set(
            jnp.where(is_app, 1, em[..., T.W_DST]))

    cl, model, st = _booted(interpose.Rewrite(redirect))
    st = cl.steps(st, 10)
    assert _coverage(model, st) == 2.0 / N
    assert bool(st.model.store[1, 0])


def test_delay_holds_then_delivers():
    d = 4
    delay_app = interpose.Delay(
        pred=lambda cfg, ctx, em: (em[..., T.W_KIND] == T.MsgKind.APP)
        & (em[..., T.W_FLAGS] & T.F_RETRANSMISSION == 0),
        rounds=d, cap=N + 2)
    cl, model, st = _booted(delay_app)
    base_round = int(st.rnd)
    # Two rounds in, nothing has arrived (messages are parked).
    st2 = cl.steps(st, 2)
    assert _coverage(model, st2) == 1.0 / N
    # After the delay matures (+1 round for delivery), everyone has it.
    st3 = cl.steps(st2, d + 2)
    assert _coverage(model, st3) == 1.0
    del base_round


def test_observe_counts_app_traffic():
    probe = interpose.Observe(
        fn=lambda cfg, ctx, em: jnp.sum(
            em[..., T.W_KIND] == T.MsgKind.APP, dtype=jnp.int32),
        combine=lambda s, aux: s + aux,
        init_state=jnp.int32(0))
    cl, model, st = _booted(probe)
    st = cl.steps(st, 10)
    # One broadcast mailed once to N-1 neighbors.
    assert int(st.interpose) == N - 1


def test_chain_order_pre_then_drop():
    # Chain = [Observe(pre), Drop]: the observer sees traffic the dropper
    # then removes (pre-interposition ordering, :58-130).
    probe = interpose.Observe(
        fn=lambda cfg, ctx, em: jnp.sum(
            em[..., T.W_KIND] == T.MsgKind.APP, dtype=jnp.int32),
        combine=lambda s, aux: s + aux, init_state=jnp.int32(0))
    drop_app = interpose.Drop(
        lambda cfg, ctx, em: em[..., T.W_KIND] == T.MsgKind.APP)
    cl, model, st = _booted(interpose.Chain([probe, drop_app]))
    st = cl.steps(st, 10)
    pre_count = int(st.interpose[0])
    assert pre_count == N - 1
    assert _coverage(model, st) == 1.0 / N


def test_omission_schedule_drops_exact_slots():
    # Drop everything node 0 emits in rounds 0..29: the broadcast (mailed
    # at the first post-boot round, ~15) dies on the wire (direct mail
    # never re-mails).  Membership is unaffected: state-gossip rides the
    # merge lane, not the event lane.
    sched = np.zeros((30, N, 64), np.bool_)
    sched[:, 0, :] = True
    cl, model, st = _booted(interpose.OmissionSchedule(sched))
    st = cl.steps(st, 10)
    assert _coverage(model, st) == 1.0 / N


def test_omission_schedule_expires():
    # Same schedule but the broadcast starts after it expires: unaffected.
    sched = np.zeros((3, N, 64), np.bool_)
    sched[:, 0, :] = True
    cl, model, st = _booted(interpose.OmissionSchedule(sched))
    # _booted already queued the broadcast at round ~15 (post-boot), which
    # is beyond the 3-round schedule.
    assert int(st.rnd) > 3
    st = cl.steps(st, 10)
    assert _coverage(model, st) == 1.0


def test_straggler_delay_per_node_mult():
    """StragglerDelay (the traffic plane's slow-node stage): mult=0
    nodes pass straight through; a straggler's mail arrives exactly
    mult rounds late, with its origin intact."""
    cl, model, st = _booted(interpose.StragglerDelay(cap=8))
    # mark the broadcaster slow by 3 rounds
    st = st._replace(interpose={
        **st.interpose,
        "mult": st.interpose["mult"].at[0].set(3)})
    r0 = int(st.rnd)
    st = cl.steps(st, 3)
    assert _coverage(model, st) == 1.0 / N   # still held
    st = cl.steps(st, 3)
    assert _coverage(model, st) == 1.0       # released + delivered
    assert int(st.interpose["missed"]) == 0
    # a fast node's broadcast in the same run is NOT delayed
    st = st._replace(model=model.broadcast(st.model, 1, 1))
    st = cl.steps(st, 2)
    assert float(model.coverage(st.model, st.faults.alive, 1)) == 1.0
    del r0


def test_straggler_workload_action_sets_and_clears():
    """workload.Stragglers scripts the per-node multiplier mid-run
    (bare stage and Chain-indexed), and validates the stage exists."""
    import pytest

    from partisan_tpu import workload as W

    cl, model, st = _booted(interpose.StragglerDelay(cap=8))
    st = W.Stragglers(nodes=(2, 3), mult=4).apply(cl, st, 0)
    assert np.asarray(st.interpose["mult"])[[2, 3]].tolist() == [4, 4]
    st = W.Stragglers(nodes=(2,), mult=0).apply(cl, st, 0)
    assert np.asarray(st.interpose["mult"])[[2, 3]].tolist() == [0, 4]
    # an explicit index against a bare (non-Chain) stage fails loudly
    with pytest.raises(ValueError, match="not a Chain"):
        W.Stragglers(nodes=(2,), mult=1, index=0).apply(cl, st, 0)

    chain = interpose.Chain([interpose.StragglerDelay(cap=4),
                             interpose.Drop(lambda c, x, e: jnp.zeros(
                                 e[..., T.W_KIND].shape, bool))])
    cl2, _m, st2 = _booted(chain)
    st2 = W.Stragglers(nodes=(1,), mult=2, index=0).apply(cl2, st2, 0)
    assert int(np.asarray(st2.interpose[0]["mult"])[1]) == 2
    # a lone StragglerDelay inside a Chain is found WITHOUT an index —
    # the egress/ingress config delay keys wrap a bare stage into a
    # Chain behind the caller's back, and the action must still land
    st2 = W.Stragglers(nodes=(2,), mult=3).apply(cl2, st2, 0)
    assert int(np.asarray(st2.interpose[0]["mult"])[2]) == 3
    with pytest.raises(ValueError, match="StragglerDelay"):
        W.Stragglers(nodes=(1,), mult=2, index=1).apply(cl2, st2, 0)
