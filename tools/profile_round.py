"""Ablation profile of the bench round at scale: where does the
per-round time go?

Times the steady-state round under config ablations (manager-only, AAE
off, monotonic shed off, emission-compaction widths, inbox widths) at a
given n.  Each variant pays its own XLA compile, so run at 32k (compile
~40 s cold) rather than 100k.  Results guide the hot-path work; keep
with BENCH_NOTES.md.

Phase attribution: ``round_body`` wraps each round phase in
``jax.named_scope`` (round.manager / round.model /
round.delivery_outbound / round.wire_fast / round.interpose /
round.throttle / round.fault / round.route / round.delivery_inbound /
round.metrics / round.health), so ops in a profiler trace carry their
phase name.
Set ``PROFILE_TRACE_DIR=/tmp/trace`` to capture a ``jax.profiler``
trace of the timed executions (each labeled with a
``TraceAnnotation``), viewable in TensorBoard/Perfetto, where the
timeline buckets map 1:1 onto those phase names.  The capture is also
parsed in-process (partisan_tpu/perfwatch.py — the shared trace-parsing
core behind tools/perf_report.py) into per-phase device-time JSON lines
on stderr.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/partisan_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def measure(n: int, label: str, *, model: bool = True, active: bool = False,
            **over) -> None:
    """``active``: keep a broadcast disseminating during the timed
    executions (re-inject a version bump before each), so the numbers
    reflect the convergence-phase round rather than the idle round —
    the distinction matters once quiet rounds are skippable
    (timer_stagger=False)."""
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config, PlumtreeConfig
    from partisan_tpu.models.plumtree import Plumtree
    from partisan_tpu.scenarios import K_PROG, _boot_overlay, _sync

    kw = dict(n_nodes=n, seed=1, peer_service_manager="hyparview",
              msg_words=16, partition_mode="groups", max_broadcasts=8,
              inbox_cap=16, emit_compact=32,
              plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4))
    kw.update(over)
    cfg = Config(**kw)
    pt = Plumtree() if model else None
    cl = Cluster(cfg, model=pt, donate=not active)
    t0 = time.perf_counter()
    st = _boot_overlay(cl, n, settle_execs=2)
    boot = time.perf_counter() - t0
    best = float("inf")
    ver = 1
    trace_dir = os.environ.get("PROFILE_TRACE_DIR")
    from partisan_tpu import perfwatch

    with perfwatch.capture(trace_dir):
        for i in range(3):
            if active and pt is not None:
                ver += 1
                st = st._replace(model=pt.broadcast(st.model, 0, 0, ver))
            # TraceAnnotation labels the host-side span; the device ops
            # inside carry round_body's jax.named_scope phase names.
            with jax.profiler.TraceAnnotation(
                    f"steady:{label}:exec{i}"):
                t0 = time.perf_counter()
                st = cl.steps(st, K_PROG)
                _sync(st)
                best = min(best, time.perf_counter() - t0)
    print(f"{label:34s} per-round {best / K_PROG * 1e3:7.1f} ms   "
          f"(boot+compile {boot:.0f}s)", flush=True)
    if trace_dir:
        # measured phase attribution (perfwatch parses the capture we
        # just wrote) — JSON lines on stderr so the aligned table above
        # stays greppable
        import json

        for name, slot in sorted(perfwatch.attribute(trace_dir).items()):
            print(json.dumps({"kind": "perf_phase", "label": label,
                              "phase": name, **slot}),
                  file=sys.stderr, flush=True)


USAGE = "usage: profile_round.py [n] [smoke|r5|ablations]"


def main() -> None:
    from partisan_tpu.config import HyParViewConfig, PlumtreeConfig

    if "--help" in sys.argv or "-h" in sys.argv:
        print(USAGE)
        print(__doc__.strip())
        return
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 32_768
    which = sys.argv[2] if len(sys.argv) > 2 else "r5"
    if which == "smoke":
        # CI smoke (tests/test_tools_cli.py): one variant at a tiny n so
        # the tool's full path — bootstrap, timed executions, profiler
        # annotations — runs end-to-end off-TPU in seconds.
        measure(n, "baseline (bench config)")
    elif which == "r5":
        measure(n, "stagger idle (r4 baseline)")
        measure(n, "stagger active", active=True)
        measure(n, "aligned idle", timer_stagger=False)
        measure(n, "aligned active", timer_stagger=False, active=True)
        measure(n, "aligned active inbox12", timer_stagger=False,
                active=True, inbox_cap=12)
        measure(n, "aligned manager only", timer_stagger=False,
                model=False)
    else:
        measure(n, "baseline (bench config)")
        measure(n, "manager only (no plumtree)", model=False)
        measure(n, "aae off",
                plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4, aae=False))
        measure(n, "heartbeat off",
                hyparview=HyParViewConfig(heartbeat=False))
        measure(n, "monotonic shed off", monotonic_shed=False)
        measure(n, "emit_compact off", emit_compact=0)
        measure(n, "emit_compact 24", emit_compact=24)
        measure(n, "inbox_cap 12", inbox_cap=12)


if __name__ == "__main__":
    main()
