"""The five driver benchmark configs (BASELINE.md "Benchmark configs to
stand up"):

1. 16-node full-mesh + full membership + demers_anti_entropy
2. 1k-node HyParView + demers_rumor_mongering (infection time vs fanout)
3. 10k-node HyParView + Plumtree under 5% link drop (tree repair)
4. 10k-node SCAMP v2 under 30%/min churn (partial-view distribution)
5. 100k-node HyParView + Plumtree + p2p-causal traffic under crash
   faults — ANY node sends causally (P2P lanes, not bounded actors)

plus the echo/latency matrix (config 6) mirroring the reference's
``performance_test`` sweep.

Each scenario returns a metrics dict; ``run_all`` (and the CLI) accepts
a ``scale`` to shrink node counts for CPU smoke runs — the tests run
scaled versions of the same code that produces the TPU numbers.

Program discipline (the round-2 lesson, see bench.py): every stepping
phase reuses ONE k=K_PROG scan per configuration — scan-length changes
recompile the round at full width — and timing uses a scalar transfer
barrier (block_until_ready does not reliably block on the relay-attached
backend).
"""

from __future__ import annotations

import time

import jax
import numpy as np

K_PROG = 10

# Metrics-plane opt-in (the CLI's --metrics flag sets this): scenarios
# run with the device-resident counter ring enabled and emit the
# per-round series to STDERR as JSON lines, ALONGSIDE the existing
# one-JSON-object-per-scenario stdout lines (which stay unchanged).
METRICS = False
# Latency-plane opt-in (--latency): birth-round threading + delivery-
# age histograms; percentiles emitted to stderr the same way.
LATENCY = False
# Health-plane opt-in (--health): device-resident topology snapshots
# every K_PROG rounds (cadence == the batch grain, so each batch ends
# with a digest describing exactly its final state) emitted to stderr;
# _converge polls the packed digest word — ONE scalar per check.
HEALTH = False
# Provenance-plane opt-in (--provenance): the (emitter gid, hop) wire
# pair + dissemination-forest/redundancy accumulation in the carry;
# redundancy ratio / tree depth / coverage round emitted to stderr.
PROVENANCE = False
# Ops-journal opt-in (--ops): soak-engine scenarios (configs 7/9 and
# the traffic suite) fuse their run into the unified ops journal
# (opslog.py), print the matched detect->react->recover incident spans
# to stderr as JSON lines, and fold the span gate — every observable
# injected fault must CLOSE — into their pass verdicts.
OPS = False
# --ops-out PATH: also commit the journal artifact (JSON lines,
# opslog.Journal.to_jsonl) so tools/incident_report.py --gate can
# re-judge it offline; the config label is suffixed before the
# extension when several scenarios write in one invocation.
OPS_OUT = None


def _emit_ops(res, storm, label, *, channels=None, slo_rounds=None,
              crowd_x1000=None) -> dict:
    """Fuse a soak run into the ops journal, print its incident spans
    (+ orphan reactions, error budgets, gate verdict) to stderr as
    JSON lines, optionally commit the journal artifact (OPS_OUT), and
    return the counts+verdict dict scenario gates fold in."""
    import json
    import sys

    from partisan_tpu import opslog

    journal = opslog.from_soak(res, storm=storm, channels=channels,
                               slo_rounds=slo_rounds,
                               crowd_x1000=crowd_x1000)
    matched = opslog.match(journal, crowd_x1000=crowd_x1000)
    for span in matched["spans"]:
        print(json.dumps({"config": label, **span}), file=sys.stderr)
    for orphan in matched["orphans"]:
        print(json.dumps({"config": label, **orphan}), file=sys.stderr)
    if slo_rounds is not None:
        # budgets print for the record; the scenario verdict gates on
        # spans only (incident_report.py --slo-rounds gates budgets)
        for row in opslog.error_budgets(journal, slo_rounds=slo_rounds):
            print(json.dumps({"config": label, **row}), file=sys.stderr)
    verdict = opslog.gate(matched)
    print(json.dumps({"config": label, **verdict}), file=sys.stderr)
    if OPS_OUT:
        root, ext = _os.path.splitext(OPS_OUT)
        journal.to_jsonl(f"{root}.{label}{ext or '.jsonl'}"
                         if label is not None else OPS_OUT)
    return {**matched["counts"], "ok": verdict["ok"]}


def _metrics_cfg(cfg):
    """Apply the module-level metrics/latency/health/provenance
    opt-ins to a scenario config."""
    if METRICS:
        cfg = cfg.replace(metrics=True, metrics_ring=512)
    if LATENCY:
        cfg = cfg.replace(latency=True)
    if HEALTH:
        cfg = cfg.replace(health=K_PROG, health_ring=512)
    if PROVENANCE:
        cfg = cfg.replace(provenance=True, provenance_ring=512)
    return cfg


def _mark_bcast(st, node, slot):
    """Mark a scenario broadcast's origin in the provenance forest
    (provenance.mark_origin) — a no-op when the plane is off, so the
    injection sites stay one-liners."""
    if getattr(st, "provenance", ()) == ():
        return st
    from partisan_tpu import provenance as prov_mod

    return st._replace(provenance=prov_mod.mark_origin(
        st.provenance, node, slot, rnd=int(jax.device_get(st.rnd))))


def _emit_metrics(cfg, st, label) -> None:
    """Decode a run's metrics ring (and latency histograms, when on) to
    stderr as JSON lines, tagged with the scenario label."""
    if st is None:
        return
    import json
    import sys

    names = tuple(c.name for c in cfg.channels)
    if st.metrics != ():
        from partisan_tpu import metrics as metrics_mod

        snap = metrics_mod.snapshot(st.metrics)
        for row in metrics_mod.rows(snap, channels=names):
            print(json.dumps({"kind": "metrics", "config": label, **row}),
                  file=sys.stderr)
        print(json.dumps({"kind": "metrics_totals", "config": label,
                          **metrics_mod.totals(snap)}), file=sys.stderr)
    if getattr(st, "latency", ()) != ():
        from partisan_tpu import latency as latency_mod

        print(json.dumps({"kind": "latency", "config": label,
                          **latency_mod.percentiles(st.latency,
                                                    channels=names)}),
              file=sys.stderr)
    if getattr(st, "health", ()) != ():
        from partisan_tpu import health as health_mod

        for row in health_mod.rows(health_mod.snapshot(st.health)):
            print(json.dumps({"kind": "health", "config": label, **row}),
                  file=sys.stderr)
    if getattr(st, "provenance", ()) != ():
        from partisan_tpu import provenance as prov_mod

        snap = prov_mod.snapshot(st.provenance)
        t = prov_mod.tree(snap, 0)
        print(json.dumps({"kind": "provenance", "config": label,
                          **prov_mod.redundancy(snap),
                          "tree_depth_mean": t["depth_mean"],
                          "tree_depth_max": t["depth_max"],
                          "coverage_round": t["cover_round"]}),
              file=sys.stderr)


def _sync(st) -> None:
    """True execution barrier: jax.block_until_ready does NOT reliably
    block on the relay-attached backend (measured: a 64-round execution
    "completing" in 0.4 ms); a scalar device->host transfer only
    materializes when the producing program has finished."""
    int(jax.device_get(st.rnd))


# Sharded-by-default threshold (ROADMAP item 2): at or above this node
# count, make_cluster_auto returns a node-sharded ShardedCluster over
# every visible device instead of a single-device Cluster.  65536 keeps
# the 32k round single-chip (the BENCH_r0x comparability anchor) and
# flips the 100k headline + the 1M target to the sharded path wherever
# more than one device exists; single-device environments (the CPU test
# container outside the 8-virtual-device harness, a lone chip) fall
# back to Cluster unchanged.  Override with PARTISAN_SHARDED_N.
import os as _os

SHARDED_N_MIN = int(_os.environ.get("PARTISAN_SHARDED_N", 65_536))


def make_cluster_auto(cfg, model=None, interpose=None, donate=False):
    """Cluster factory with the sharded path as the default at large n:
    node counts >= SHARDED_N_MIN on a multi-device backend get a
    ShardedCluster over a 1-D mesh of the LARGEST device count that
    divides n (all devices for the power-of-two ladder sizes and the
    100k/1M rungs on 8-way meshes; 100k on a 64-way slice still
    shards 50-way rather than falling back to one melting chip);
    only a prime-ish n with no usable divisor — or a single-device
    backend — gets the single-device Cluster.  Both expose the same
    API (init/step/steps/record/run_until, donate), so callers are
    placement-agnostic — which is the whole point:
    tests/test_sharded.py pins that the two evolve bit-identically."""
    from partisan_tpu.cluster import Cluster

    n_dev = len(jax.devices())
    if cfg.n_nodes >= SHARDED_N_MIN and n_dev > 1:
        for k in range(n_dev, 1, -1):
            if cfg.n_nodes % k == 0:
                from partisan_tpu.parallel.sharded import (
                    ShardedCluster, make_mesh)

                return ShardedCluster(cfg, make_mesh(k), model=model,
                                      interpose=interpose,
                                      donate=donate)
    return Cluster(cfg, model=model, interpose=interpose,
                   donate=donate)


def _boot_fullmesh(cl, n):
    st = cl.init()
    m = st.manager
    for i in range(1, n):
        m = cl.manager.join(cl.cfg, m, i, 0)
    st = cl.steps(st._replace(manager=m), K_PROG)
    return cl.steps(st, K_PROG)


def _boot_overlay(cl, n, settle_execs=3, on_wave=None, state=None,
                  wave_factor=4, stagger=0, wave_execs=1):
    """Batched staggered bootstrap (random contacts) for partial-view
    overlays; one k=K_PROG execution per wave.  ``on_wave(hi, state)``
    is an optional instrumentation hook and ``state`` an optional
    pre-built (e.g. compile-warmed) initial state — bench.py uses both
    to keep its per-phase timing.  ``wave_factor`` sets the per-wave
    growth: every wave costs one full-width K_PROG execution regardless
    of how many nodes join in it, so larger factors cut bootstrap wall
    time linearly in log_factor(n); joins whose contact's inbox
    overflows in a bigger wave simply retry next round (the JOIN retry
    loop), which the settle executions absorb.

    ``stagger`` (admissions/round, SCAMP only): bound each wave's join
    ADMISSIONS to that per-round rate (join_round gating in
    managers/scamp.py), running enough K_PROG executions per wave to
    cover the spread, so later admissions land on contact views settled
    by earlier ones.  A mass same-round join fans every subscription
    over half-built views and the walk storm overflows inboxes; a
    bounded admission rate keeps the subscription process close to the
    ideal sequential one at EVERY scale — the fidelity lever for
    VERDICT r4 weak #3.  ``wave_execs`` adds settle executions per wave
    on top of the coverage minimum."""
    rng = np.random.default_rng(7)
    if stagger > 0:
        join = jax.jit(lambda m, nodes, tgts, rnds: cl.manager.join_many(
            cl.cfg, m, nodes, tgts, rnds))
    else:
        join = jax.jit(lambda m, nodes, tgts: cl.manager.join_many(
            cl.cfg, m, nodes, tgts))
    st = cl.init() if state is None else state
    base = 1
    rnd_now = None
    while base < n:
        hi = min(base * wave_factor, n)
        nodes = np.arange(base, hi, dtype=np.int32)
        targets = rng.integers(0, base, size=nodes.shape[0]).astype(np.int32)
        execs = wave_execs
        if stagger > 0:
            if rnd_now is None:
                rnd_now = int(jax.device_get(st.rnd))
            window = max(1, -(-nodes.shape[0] // stagger))   # ceil
            rnds = rnd_now + rng.integers(
                0, window, size=nodes.shape[0]).astype(np.int32)
            st = st._replace(manager=join(st.manager, nodes, targets, rnds))
            execs = -(-window // K_PROG) + wave_execs - 1
        else:
            st = st._replace(manager=join(st.manager, nodes, targets))
        for _ in range(execs):
            st = cl.steps(st, K_PROG)
        if rnd_now is not None:
            rnd_now += K_PROG * execs
        if on_wave is not None:
            on_wave(hi, st)
        base = hi
    for _ in range(settle_execs):
        st = cl.steps(st, K_PROG)
    _sync(st)
    return st


def _grow_state(old_st, new_init, old_n: int, new_n: int):
    """LEGACY re-embedding of a ``old_n``-wide cluster state into a fresh
    ``new_n``-wide init state (the multi-program ladder): every
    node-axis leaf prefix-copies (rows >= old_n keep their init values —
    alive, unjoined, inert), same-shaped leaves (round counter, stats,
    link_drop) carry over.  Node ids are global and width-independent,
    and the per-node hash-RNG streams are keyed by id, so the prefix
    cluster's dynamics are unchanged by the re-embedding.

    The width-operand ladder (Config.width_operand — the default path
    in :func:`_boot_ladder`) replaces this with an in-place prefix
    activation (``cluster.activate``): the same contract, but no fresh
    XLA program per rung and no tree-wide copy.  This function remains
    for non-width-operand configs and as the contract's reference
    semantics (tests/test_program_budget.py asserts the two agree)."""
    def leaf(o, ni):
        osh, nsh = getattr(o, "shape", None), getattr(ni, "shape", None)
        if osh == nsh:
            return o
        if (osh is not None and nsh is not None and len(osh) == len(nsh)
                and osh[0] == old_n and nsh[0] == new_n
                and osh[1:] == nsh[1:]):
            return ni.at[:old_n].set(o)
        raise ValueError(
            f"cannot grow state leaf {osh} -> {nsh} ({old_n}->{new_n}); "
            "dense partition_mode does not support the width ladder")
    return jax.tree.map(leaf, old_st, new_init)


def _boot_ladder(make_cluster, n, widths=None, wave_factor=8,
                 settle_execs=1, on_wave=None, final_state=None,
                 upper_wave_factor=2):
    """Reduced-width bootstrap ladder: run the early join waves on a
    PREFIX of the cluster, widening between rungs.  Every bootstrap
    wave costs one K_PROG execution, so ramping the join storm through
    prefix rungs cuts the bootstrap's node-rounds (VERDICT r4 next #2)
    while the late waves + settle pay full width.

    Program discipline (the r5→r6 lesson): with ``Config.width_operand``
    on — the default path — EVERY rung runs the SAME full-width round
    program; the rung width is the dynamic ``n_active`` operand and a
    rung change is an in-place prefix activation (``cluster.activate``,
    the successor of :func:`_grow_state`).  One scan program is traced,
    compiled, serialized and relay-loaded per bench size instead of one
    per rung — the r5 two-rung ladder spent ~45 s loading ~90 MB of
    per-rung programs through the relay (~1.5 MB/s) to save ~6 s of
    full-width waves.  The trade: early waves now pay full-width
    COMPUTE (~10 s of simulated rounds at 100k) but zero extra program
    bytes.  Without the width operand the legacy multi-program path
    (separate Cluster per rung + ``_grow_state``) is used.

    ``make_cluster(width) -> Cluster`` builds one rung (same config at
    ``n_nodes=width``); the width-operand path calls it ONCE, at ``n``
    (tests/test_program_budget.py counts on this).  ``final_state``
    optionally supplies the pre-built (timed) init state for the full
    width.  The FIRST rung ramps at ``wave_factor`` (its rounds are
    cheap; factor 8 is the validated envelope); every rung above it
    uses the gentler ``upper_wave_factor`` — wide factor-8 join storms
    measured 6-14 disconnected components at 100k boot end under
    aligned timers, and the stragglers' slow rejoins cost more than
    the saved waves.  Factor 4 upper waves re-measured at 100k
    (r5-late, post walk-stream change): 3 components and 2x
    convergence rounds — the envelope holds; keep 2.  The rung widths
    only change where the inert high rows live (ids are global,
    per-node hash-RNG streams are id-keyed), so the wave schedule is
    IDENTICAL between the two paths."""
    rng = np.random.default_rng(7)
    if widths is None:
        # ONE sub-full-width rung: under the width operand rungs are
        # free (same program), but the wave SCHEDULE is kept identical
        # to the validated r5 envelope — an 8k first rung ramps the
        # factor-8 storm before the gentler upper waves.
        widths = [w for w in (8192,) if w < n] + [n]
    cl_full = make_cluster(n)
    if cl_full.cfg.width_operand:
        return cl_full, _boot_ladder_width_op(
            cl_full, n, widths, rng, wave_factor, settle_execs, on_wave,
            final_state, upper_wave_factor)
    st, cl, prev_w, base = None, None, None, 1
    for w in widths:
        cl = cl_full if w == n else make_cluster(w)
        init = final_state if (w == n and final_state is not None) \
            else cl.init()
        if st is None:
            st = init
        else:
            grow = jax.jit(lambda o, ni: _grow_state(o, ni, prev_w, w))
            st = grow(st, init)
        join = jax.jit(lambda m, nodes, tgts, _cl=cl: _cl.manager.join_many(
            _cl.cfg, m, nodes, tgts))
        # Gentle waves above the first rung (see docstring; factor 2 on
        # the final rung alone still left 6-7 components at 100k).
        factor = upper_wave_factor \
            if (upper_wave_factor and w != widths[0]) else wave_factor
        while base < w:
            hi = min(base * factor, w)
            nodes = np.arange(base, hi, dtype=np.int32)
            targets = rng.integers(0, base,
                                   size=nodes.shape[0]).astype(np.int32)
            st = st._replace(manager=join(st.manager, nodes, targets))
            st = cl.steps(st, K_PROG)
            if on_wave is not None:
                on_wave(hi, st, w)
            base = hi
        prev_w = w
    for _ in range(settle_execs):
        st = cl.steps(st, K_PROG)
    _sync(st)
    return cl, st


def _boot_ladder_width_op(cl, n, widths, rng, wave_factor, settle_execs,
                          on_wave, final_state, upper_wave_factor):
    """Width-operand ladder body: ONE cluster, ONE round program; rungs
    are prefix activations of the same state (see _boot_ladder doc)."""
    from partisan_tpu import cluster as cluster_mod

    st = final_state if final_state is not None else cl.init()
    join = jax.jit(lambda m, nodes, tgts: cl.manager.join_many(
        cl.cfg, m, nodes, tgts))
    base = 1
    for w in widths:
        st = cluster_mod.activate(st, w)
        factor = upper_wave_factor \
            if (upper_wave_factor and w != widths[0]) else wave_factor
        while base < w:
            hi = min(base * factor, w)
            nodes = np.arange(base, hi, dtype=np.int32)
            targets = rng.integers(0, base,
                                   size=nodes.shape[0]).astype(np.int32)
            st = st._replace(manager=join(st.manager, nodes, targets))
            st = cl.steps(st, K_PROG)
            if on_wave is not None:
                on_wave(hi, st, w)
            base = hi
    for _ in range(settle_execs):
        st = cl.steps(st, K_PROG)
    _sync(st)
    return st


def _throughput(cl, st):
    """Simulated rounds/sec from best-of-3 k=K_PROG executions.  The
    per-execution dispatch overhead (~0.3 s on the relay) is included,
    so this UNDER-reports at small n — bench.py's adaptive scan length
    is the headline-number instrument."""
    st = cl.steps(st, K_PROG)
    _sync(st)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        st = cl.steps(st, K_PROG)
        _sync(st)
        best = min(best, time.perf_counter() - t0)
    return K_PROG / best


def _converge(cl, st, coverage_fn, max_rounds, use_digest=True):
    """Step until converged (checked every K_PROG rounds).  Returns
    (state, converged_round|-1).

    With the health plane on at an ALIGNED cadence (``Config.health``
    dividing K_PROG — the --health opt-in sets K_PROG itself), each
    check transfers ONE packed int32: the health digest's coverage bit,
    folded in by the device snapshot that closed the last batch, so the
    digest describes exactly the state being checked.  CONTRACT: the
    digest's coverage predicate is the model's SLOT-0 coverage (first
    coverage-bearing sub-model of a Stack) — exactly what every current
    scenario's ``coverage_fn`` polls; a caller whose predicate targets
    a different slot or sub-model must pass ``use_digest=False``.  A
    non-dividing cadence would leave the digest up to health-1 rounds
    stale at the batch boundary, so it falls back to — and the plane
    off runs bit-identically on — the legacy jitted
    ``coverage_fn(state) == 1.0`` poll."""
    if use_digest and getattr(st, "health", ()) != () \
            and K_PROG % cl.cfg.health == 0:
        from partisan_tpu import health as health_mod

        def done(s):
            return health_mod.digest_converged(health_mod.digest(s))

        for _ in range(0, max_rounds, K_PROG):
            if done(st):
                return st, int(st.rnd)
            st = cl.steps(st, K_PROG)
        return (st, int(st.rnd)) if done(st) else (st, -1)
    for _ in range(0, max_rounds, K_PROG):
        if float(coverage_fn(st)) == 1.0:
            return st, int(st.rnd)
        st = cl.steps(st, K_PROG)
    return (st, int(st.rnd)) if float(coverage_fn(st)) == 1.0 else (st, -1)


# ---------------------------------------------------------------------------
# Conformance oracles (distribution-level expected values, derived from
# the reference/papers rather than from this codebase — VERDICT r3 §2).
# ---------------------------------------------------------------------------

import functools


@functools.lru_cache(maxsize=None)
def scamp_ideal_mean(n: int, c: int = 5, v2: bool = True, seeds=(0, 1),
                     ttl: int = 32) -> float:
    """Expected partial-view mean from the IDEAL SCAMP subscription
    process executed directly (paper §2.2 / reference v1 :264-297
    semantics, v2's c-1 fanout :119-134): n sequential joins through
    uniform contacts; the contact fans the subscription to its whole
    view + extra copies; each copy walks, kept w.p. 1/(1+|view incl
    self|), destroyed on TTL expiry when already known.

    The asymptotic law (c+1)·ln n (v1 :272-276) overshoots badly at
    finite n (the growth constant climbs toward c+1 only as n -> inf):
    the ideal process itself yields ~15 at n=128 and ~21 at n=512 where
    the law says 29/37.  This oracle is therefore the honest
    distribution-level conformance target; the law is reported beside
    it for context."""
    import random

    total = 0.0
    extras = c - 1 if v2 else c
    for seed in seeds:
        rng = random.Random(seed)
        view: dict[int, set] = {0: set()}
        for j in range(1, n):
            contact = rng.choice(list(view.keys()))
            view[j] = {contact}
            members = list(view[contact])
            targets = members + [rng.choice(members) if members else contact
                                 for _ in range(extras)]
            for t in targets:
                node, hops = t, 0
                while True:
                    hops += 1
                    known = (j == node) or (j in view[node])
                    if not known and (hops >= ttl or rng.random()
                                      < 1.0 / (2 + len(view[node]))):
                        view[node].add(j)
                        break
                    if hops >= ttl:
                        break           # known + expired: copy destroyed
                    nxts = [x for x in view[node] if x != j]
                    if not nxts:
                        if not known:
                            view[node].add(j)
                        break
                    node = rng.choice(nxts)
        total += sum(len(v) for v in view.values()) / n
    return total / len(seeds)


def rumor_fixed_point(fanout: int = 2) -> float:
    """Mean-field coverage plateau of blind infect-and-die rumor
    mongering (Demers et al.): the susceptible fraction s solves
    s = exp(-fanout·(1-s)); coverage = 1 - s.  For fanout 2 this is
    ~0.7968.  Overlay targeting (fanout picks ride persistent partial-
    view edges, self excluded) biases measured plateaus a few points
    ABOVE the complete-graph mean-field value."""
    import math

    s = 0.2
    for _ in range(64):
        s = math.exp(-fanout * (1.0 - s))
    return 1.0 - s


def hyparview_views(n=1000, settle_execs=6):
    """HyParView view-size conformance (include/partisan.hrl:204-217):
    after bootstrap, every active view holds within
    [active_min, active_max] and the overlay is ONE connected
    component.  Returns the size distribution + component count.

    The component count comes from the DEVICE health plane (health.py
    pointer-jumping counter — O(log n) gather steps inside the jitted
    round), not a host BFS: the boot's final round computes the
    snapshot, so reading it here is one packed-scalar transfer.  The
    numpy BFS lives on as the test oracle (tests/support.components;
    tests/test_health.py gates device==oracle on randomized, faulted
    and partitioned overlays)."""
    from partisan_tpu import health as health_mod
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config

    cfg = Config(n_nodes=n, seed=2, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups",
                 health=K_PROG, health_ring=64)
    cl = Cluster(cfg)
    st = _boot_overlay(cl, n, settle_execs=settle_execs)
    act = np.asarray(st.manager.active)
    alive = np.asarray(st.faults.alive)
    sizes = (act >= 0).sum(axis=1)[alive]
    digest = health_mod.digest(st)
    return {"config": "hyparview_views", "n": n,
            "active_min": cfg.hyparview.active_min,
            "active_max": cfg.hyparview.active_max,
            "size_mean": round(float(sizes.mean()), 2),
            "size_min": int(sizes.min()), "size_max": int(sizes.max()),
            "frac_at_least_min": round(
                float((sizes >= cfg.hyparview.active_min).mean()), 4),
            "components": health_mod.digest_components(digest),
            "healthy": health_mod.healthy(digest)}


def config1_anti_entropy(n=16, max_rounds=120):
    """16-node full-mesh anti-entropy (protocols/demers_anti_entropy.erl):
    rounds to full coverage + simulated rounds/sec."""
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config
    from partisan_tpu.models.anti_entropy import AntiEntropy

    cfg = _metrics_cfg(Config(n_nodes=n, seed=1, inbox_cap=max(32, n + 8)))
    model = AntiEntropy()
    cl = Cluster(cfg, model=model)
    cov = jax.jit(lambda s: model.coverage(s.model, s.faults.alive, 0))
    st = _boot_fullmesh(cl, n)
    start = int(st.rnd)
    st = _mark_bcast(st._replace(model=model.broadcast(st.model, 0, 0)),
                     0, 0)
    st, conv = _converge(cl, st, cov, max_rounds)
    _emit_metrics(cfg, st, 1)
    return {"config": 1, "n": n, "convergence_rounds": conv - start,
            "rounds_per_sec": round(_throughput(cl, st), 1)}


def config2_rumor(n=1000, max_rounds=200):
    """HyParView + rumor mongering: infection time vs fanout.  Demers
    infect-and-die gossip converges to a coverage FIXED POINT below 1.0
    (~0.80 at k=2 — demers_rumor_mongering.erl semantics); the metric is
    that plateau and the rounds to reach 95% of it."""
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config
    from partisan_tpu.models.rumor_mongering import RumorMongering

    cfg = _metrics_cfg(Config(n_nodes=n, seed=2,
                            peer_service_manager="hyparview",
                            msg_words=16, partition_mode="groups"))
    model = RumorMongering()
    cl = Cluster(cfg, model=model)
    cov = jax.jit(lambda s: model.coverage(s.model, s.faults.alive, 0))
    st = _boot_overlay(cl, n)
    start = int(st.rnd)
    st = _mark_bcast(st._replace(model=model.broadcast(st.model, 0, 0)),
                     0, 0)
    trail = []
    for _ in range(max_rounds // K_PROG):
        st = cl.steps(st, K_PROG)
        trail.append((int(st.rnd), float(cov(st))))
        if len(trail) >= 3 and trail[-1][1] == trail[-3][1]:
            break   # plateaued
    plateau = trail[-1][1]
    _emit_metrics(cfg, st, 2)
    infection = next(r for (r, c) in trail if c >= 0.95 * plateau) - start
    return {"config": 2, "n": n, "fanout": 2,
            "infection_rounds": infection,
            "coverage_plateau": round(plateau, 4),
            # Demers mean-field fixed point for blind infect-and-die at
            # fanout 2 (complete graph); overlay targeting biases the
            # measured plateau a few points above it (see
            # rumor_fixed_point) — the conformance band is
            # [fp - 0.03, fp + 0.13]
            "expected_plateau_meanfield": round(rumor_fixed_point(2), 4),
            "rounds_per_sec": round(_throughput(cl, st), 1)}


def config3_plumtree_drop(n=10_000, drop=0.05, max_rounds=400):
    """HyParView + Plumtree under iid link drop: the lazy i_have/graft
    repair path must still converge (tree repair,
    partisan_plumtree_broadcast.erl:861-905)."""
    import jax.numpy as jnp

    from partisan_tpu.config import Config
    from partisan_tpu.models.plumtree import Plumtree

    cfg = _metrics_cfg(Config(n_nodes=n, seed=3,
                            peer_service_manager="hyparview",
                            msg_words=16, partition_mode="groups",
                            emit_compact=32 if n > 4096 else 0))
    model = Plumtree()
    cl = make_cluster_auto(cfg, model=model)
    cov = jax.jit(lambda s: model.coverage(s.model, s.faults.alive, 0))
    st = _boot_overlay(cl, n)
    st = st._replace(faults=st.faults._replace(link_drop=jnp.float32(drop)))
    start = int(st.rnd)
    st = _mark_bcast(st._replace(
        model=model.broadcast(st.model, 0, 0, start)), 0, 0)
    st, conv = _converge(cl, st, cov, max_rounds)
    _emit_metrics(cfg, st, 3)
    # Repair-round bound: eager flood depth is O(log n) over the
    # HyParView overlay; each dropped edge heals within one lazy tick
    # (1 round) + a graft round trip (2 rounds), and at 5% iid drop a
    # handful of repair generations suffice.  The bound below (flood
    # depth + 8 repair cycles, rounded up to the K_PROG measurement
    # grain) is the conformance band the judge asked for
    # (partisan_plumtree_broadcast.erl:861-905 repair path).
    import math

    bound = (2 * math.ceil(math.log2(max(n, 2))) + 8 * 3 + K_PROG)
    return {"config": 3, "n": n, "link_drop": drop,
            "repair_rounds": (conv - start) if conv >= 0 else -1,
            "expected_max_repair_rounds": bound,
            "rounds_per_sec": round(_throughput(cl, st), 1)}


def config4_scamp_churn(n=10_000, churn_per_min=0.30, rounds=120):
    """SCAMP v2 under churn: partial-view size distribution after a
    sustained birth/death process (self-stabilizes to (c+1)·log n,
    partisan_scamp_v1_membership_strategy.erl:272-276)."""
    import jax.numpy as jnp

    from partisan_tpu import faults as faults_mod
    from partisan_tpu.config import Config

    # inbox_cap sized so the subscription-walk storms of the batched
    # bootstrap never shed (cap 32 measured 1.4k sheds at 1k nodes,
    # costing ~2 partial-view entries per node; the capacity knobs are
    # specified to be sized for zero steady sheds)
    cfg = _metrics_cfg(Config(n_nodes=n, seed=4,
                            peer_service_manager="scamp_v2",
                            msg_words=16, partition_mode="groups",
                            inbox_cap=96))
    cl = make_cluster_auto(cfg)
    # Admission stagger (join_round gating): each wave's subscriptions
    # enter spread over the wave's rounds, so fanouts land on contact
    # views settled by earlier admissions — without it a mass same-round
    # join fans over half-built views and the walk storm overflows
    # inboxes, leaving the stable mean at ~0.5-0.6x the ideal process
    # (the r4 deviation).
    st = _boot_overlay(cl, n, stagger=40, wave_execs=2)
    # settle the subscription walks, then measure the STABLE (pre-churn)
    # distribution — the state the (c+1)·ln n law and the ideal-process
    # oracle describe.
    for _ in range(6):
        st = cl.steps(st, K_PROG)
    _sync(st)
    stable = np.asarray(jnp.sum(st.manager.partial >= 0, axis=1))
    # churn probability per round (round = 1s of virtual time)
    p = churn_per_min / 60.0
    churn = jax.jit(lambda f, rnd: faults_mod.churn_step(
        f, cfg.seed, rnd, p, p))
    for _ in range(max(1, rounds // K_PROG)):
        st = st._replace(faults=churn(st.faults, st.rnd))
        st = cl.steps(st, K_PROG)
    _sync(st)
    _emit_metrics(cfg, st, 4)
    sizes = np.asarray(jnp.sum(st.manager.partial >= 0, axis=1))
    alive = np.asarray(st.faults.alive)
    s = sizes[alive]
    ideal = scamp_ideal_mean(n)
    ratio = float(stable.mean()) / ideal
    return {"config": 4, "n": n, "churn_per_min": churn_per_min,
            "alive": int(alive.sum()),
            "stable_partial_view_mean": round(float(stable.mean()), 2),
            "partial_view_mean": round(float(s.mean()), 2),
            "partial_view_p95": int(np.percentile(s, 95)),
            # the finite-n conformance oracle (see scamp_ideal_mean) and
            # the asymptotic law it converges to
            "expected_ideal_process": round(ideal, 1),
            "expected_c1_logn": round((cfg.scamp.c + 1) * np.log(n), 1),
            # conformance band, asserted at EVERY scale this config runs
            # at (tests/test_scenarios.py gates it; the 10k artifact
            # carries it)
            "ideal_ratio": round(ratio, 3),
            "in_band": bool(0.65 <= ratio <= 1.35),
            "rounds_per_sec": round(_throughput(cl, st), 1)}


def config5_causal_crash(n=100_000, senders=64, crashes=16,
                         max_rounds=400):
    """HyParView + Plumtree + POINT-TO-POINT causal traffic under
    scripted crash faults, at the north-star scale.

    The causal mode is the P2P lane (delivery.py `P2PLane`, transposing
    partisan_causality_backend.erl:204-220's per-destination scheme):
    ``senders`` nodes drawn uniformly from the WHOLE id space — any node
    may send, no bounded actor set — each send two causally-ordered
    messages to a random destination while ``crashes`` nodes are down
    and the overlay heals around them.  Checks: per-(sender, receiver)
    FIFO with exactly-once delivery at every receiver, and plumtree
    broadcast convergence across the healed overlay."""
    from partisan_tpu.config import Config, PlumtreeConfig
    from partisan_tpu.models.p2p_chat import P2PChat
    from partisan_tpu.models.plumtree import Plumtree
    from partisan_tpu.models.stack import Stack

    # Scale-down guards for smoke runs.
    n = max(n, 32)
    senders = min(senders, n // 4)
    crashes = min(crashes, n // 4)

    plum = Plumtree()
    chat = P2PChat()
    stack = Stack([plum, chat])

    def make_cfg(width):
        return _metrics_cfg(Config(n_nodes=width, seed=5,
                      peer_service_manager="hyparview",
                      msg_words=16, partition_mode="groups",
                      causal_p2p_labels=("chat",),
                      max_broadcasts=8, inbox_cap=16,
                      emit_compact=32 if n > 4096 else 0,
                      timer_stagger=False,
                      # one width-generic round program for the whole
                      # bootstrap ladder (the n_active prefix operand)
                      width_operand=True,
                      plumtree=PlumtreeConfig(push_slots=2,
                                              lazy_cap=4)))

    cfg = make_cfg(n)
    # sharded-by-default at scale (ROADMAP item 2): >= SHARDED_N_MIN on
    # a multi-device backend runs the node-sharded SPMD round
    cl = make_cluster_auto(cfg, model=stack)
    cov = jax.jit(lambda s: plum.coverage(stack.sub(s.model, 0),
                                          s.faults.alive, 0))

    def make_cluster(width):
        return cl if width == n else make_cluster_auto(make_cfg(width),
                                                       model=stack)

    _, st = _boot_ladder(make_cluster, n)
    start = int(st.rnd)

    # Cast: senders, receivers and crash victims, all disjoint, senders
    # drawn from the FULL id space (the any-node-sends claim).  Node 0
    # is excluded: it is the plumtree broadcast source below, and a
    # crashed broadcaster can never converge.
    rng = np.random.default_rng(11)
    cast = 1 + rng.choice(n - 1, size=2 * senders + crashes, replace=False)
    snd, rcv = cast[:senders], cast[senders:2 * senders]
    victims = cast[2 * senders:]

    # Two causally-ordered sends per sender (seq 1 then 2 per edge).
    nodes = np.repeat(snd, 2)
    rnds = np.stack([np.full(senders, start + 2),
                     np.full(senders, start + 6)], axis=1).reshape(-1)
    dsts = np.repeat(rcv, 2)
    st = st._replace(model=stack.replace_sub(
        st.model, 1, chat.schedule_many(stack.sub(st.model, 1),
                                        nodes, rnds, dsts)))

    # Crash the victims (the filibuster crash-fault-model shape).
    alive = st.faults.alive.at[jax.numpy.asarray(victims)].set(False)
    st = st._replace(faults=st.faults._replace(alive=alive))

    # Plumtree broadcast from node 0 over the healing overlay.  The
    # convergence wall is MEASURED (wall clock around the stepped loop,
    # as bench.py does — r4's artifact derived it from rounds/rps).
    st = _mark_bcast(st._replace(model=stack.replace_sub(
        st.model, 0,
        plum.broadcast(stack.sub(st.model, 0), 0, 0, start))), 0, 0)
    _sync(st)
    t_conv = time.perf_counter()
    st, conv = _converge(cl, st, cov, max_rounds)
    _sync(st)
    conv_wall = round(time.perf_counter() - t_conv, 3)
    # let the p2p streams drain (replay cadence = retransmit timer)
    for _ in range(max(1, (cfg.retransmit_every * 4) // K_PROG)):
        st = cl.steps(st, K_PROG)
    _sync(st)

    _emit_metrics(cfg, st, 5)
    # Per-edge FIFO + exactly-once at every receiver.
    chat_state = jax.device_get(stack.sub(st.model, 1))
    logs = P2PChat.logs(chat_state)
    ordered = delivered = 0
    for r in rcv:
        log = logs[int(r)]
        delivered += len(log)
        ordered += P2PChat.edge_fifo_ok(log)
    rps = _throughput(cl, st)
    return {"config": 5, "n": n, "senders": int(senders),
            "crashes": int(crashes),
            "convergence_rounds": (conv - start) if conv >= 0 else -1,
            "rounds_per_sec": round(rps, 1),
            # MEASURED: wall clock of the convergence phase itself
            # (includes the jitted coverage checks, like bench.py)
            "convergence_wall_sec": conv_wall if conv >= 0 else None,
            "causal_deliveries": int(delivered),
            "causal_expected": int(2 * senders),
            "fifo_ok_receivers": int(ordered),
            "n_receivers": int(senders)}


def config6_echo(n=2, sizes_kb=(1024, 2048, 4096, 8192),
                 concurrency=(1, 2, 4, 8), latencies_ms=(1, 20, 100),
                 parallelism=1, num_messages=1000,
                 bandwidth_mb_s=1000.0, csv_path=None) -> dict:
    """Echo/latency matrix (the reference's ``performance_test`` +
    ``bin/perf-suite.sh`` sweep: SIZE × CONCURRENCY × RTT): two nodes,
    ``concurrency`` ping-pong sender processes sharing the channel's
    ``parallelism`` lanes under capacity enforcement, ``num_messages``
    round trips each.

    EVERY cell is an independent simulation run.  Payload size enters
    the simulation as bytes-weighted lane capacity: one round is one
    link traversal worth ``per_round_ms = max(latency/2, size/bw)`` ms
    (tc-netem delay + serialization), so a lane moves
    ``floor(bw · per_round_ms / size)`` messages per round — large
    payloads on fast links throttle the lane and the measured
    rounds-to-complete grows (queueing), exactly where bandwidth binds
    physically.

    Column provenance (MEASURED vs DERIVED — the r3 artifact blurred
    this):

    - ``rounds``          MEASURED — simulated rounds to complete the
                          echo workload, from the actual run
    - ``measured_wall_s`` MEASURED — wall-clock seconds of that
                          simulation run on this host
    - ``measured``        1 for EVERY retained row: each cell runs its
                          own simulation — payload bytes reach both the
                          capacity model and the clock (r4 shared runs
                          between cells with identical (concurrency,
                          lane_rate); the sharing was sound — the sim
                          outcome depends on nothing else — but left a
                          third of the matrix as interpolation)
    - ``time``            DERIVED — ``rounds x per_round_ms x 1000``:
                          the virtual-clock µs conversion of the
                          measured rounds (the reference's wall-clock
                          column has no direct analogue: its wire moves
                          real bytes; the sim's virtual second is the
                          round)
    - ``lane_rate``       DERIVED — the capacity-model input computed
                          from (bytes, latency, bandwidth)
    """
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import ChannelSpec, Config, DEFAULT_CHANNEL
    from partisan_tpu.models.echo import CLIENT, Echo

    rows = []
    n_runs = 0
    for conc in concurrency:
        for size_kb in sizes_kb:
            for lat in latencies_ms:
                ser_ms = size_kb / 1024.0 / bandwidth_mb_s * 1000.0
                per_round_ms = max(lat / 2.0, ser_ms)
                lane_rate = max(1, int(
                    bandwidth_mb_s * 1024.0 * per_round_ms / 1000.0
                    // size_kb))
                model = Echo(concurrency=conc,
                             num_messages=num_messages)
                cfg = Config(
                    n_nodes=n, seed=11, peer_service_manager="static",
                    channel_capacity=True, lane_rate=lane_rate,
                    outbox_cap=max(32, 2 * conc),
                    channels=(ChannelSpec(DEFAULT_CHANNEL,
                                          parallelism=parallelism),))
                cl = Cluster(cfg, model=model)
                t0 = time.perf_counter()
                st, _ = cl.run_until(
                    cl.init(), lambda s: model.done(s.model),
                    max_rounds=2 * num_messages
                    + 4 * num_messages * conc
                    // max(parallelism * lane_rate, 1) + 50,
                    check_every=50)
                _sync(st)
                wall = round(time.perf_counter() - t0, 3)
                assert model.done(st.model), "echo run incomplete"
                echoes = int(st.model.echoed[CLIENT].sum())
                assert echoes == conc * num_messages, (echoes, conc)
                rounds = int(st.rnd)
                n_runs += 1
                rows.append({
                    "backend": "partisan_tpu", "concurrency": conc,
                    "parallelism": parallelism,
                    "bytes": size_kb * 1024,
                    "nummessages": num_messages, "latency": lat,
                    "lane_rate": lane_rate,
                    "time": int(rounds * per_round_ms * 1000),
                    "rounds": rounds,
                    "measured_wall_s": wall,
                    "measured": 1,
                })
    if csv_path:
        with open(csv_path, "w") as f:
            f.write("backend,concurrency,parallelism,bytes,"
                    "nummessages,latency,time,rounds,"
                    "measured_wall_s,measured\n")
            for r in rows:
                f.write(f"{r['backend']},{r['concurrency']},"
                        f"{r['parallelism']},{r['bytes']},"
                        f"{r['nummessages']},{r['latency']},"
                        f"{r['time']},{r['rounds']},"
                        f"{r['measured_wall_s']},{r['measured']}\n")
    return {"config": 6, "cells": len(rows),
            "measured_runs": n_runs, "rows": rows}


def config7_soak(n=10_000, rounds=2000, ckpt_dir=None, storm_period=200,
                 superstep=1, pipeline=1):
    """Long-horizon soak (ROADMAP item 4): a repeating fault storm —
    iid link drop → heal → crash batch → full partition → heal+revive →
    churn ticks → heal — driven for thousands of rounds through the
    chunked soak engine (soak.py): every execution bounded under the
    minute-mark wall (tools/MINUTE_FAULT.md), the carry device-resident
    between chunks, checkpoints at chunk boundaries, worker crashes
    retried from the last checkpoint, and the health digest polled per
    chunk (one int32) as the convergence signal.  Per-chunk rows go to
    stderr as JSON lines (``kind: soak_chunk``); the stdout object
    carries the engine's recovery/breach accounting.  ``superstep``
    fuses R rounds per scan step (the engine's guarded cap lift
    engages); ``pipeline`` >= 2 keeps that many chunk executions in
    flight between boundaries (ISSUE 18)."""
    from partisan_tpu import health as health_mod
    from partisan_tpu import soak as soak_mod
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config
    from partisan_tpu.models.plumtree import Plumtree

    n = max(n, 64)

    def mk():
        return Cluster(_metrics_cfg(Config(
            n_nodes=n, seed=7, peer_service_manager="hyparview",
            msg_words=16, partition_mode="groups",
            health=K_PROG, health_ring=512,
            superstep=superstep,
            emit_compact=32 if n > 4096 else 0)), model=Plumtree())

    cl = mk()
    st = _boot_overlay(cl, n)
    start = int(jax.device_get(st.rnd))
    p = storm_period
    storm = soak_mod.Storm(events=(
        (0, soak_mod.LinkDrop(0.2)),
        (p * 2 // 10, soak_mod.Heal()),
        (p * 3 // 10, soak_mod.CrashBatch(frac=0.02)),
        (p * 5 // 10, soak_mod.Partition()),
        (p * 7 // 10, soak_mod.Heal(revive=True)),
        (p * 8 // 10, soak_mod.Churn(0.01, 0.01)),
        (p * 85 // 100, soak_mod.Churn(0.01, 0.01)),
        (p * 9 // 10, soak_mod.Heal(revive=True)),
    ), start=start, period=p)
    # Seed the factory with the booted (compile-warm) cluster: the
    # engine's first _cluster() reuses it; only a post-crash
    # fresh-context rebuild pays mk() again.
    warm = [cl]
    eng = soak_mod.Soak(
        make_cluster=lambda: warm.pop() if warm else mk(), storm=storm,
        invariants=[soak_mod.conservation()],
        cfg=soak_mod.SoakConfig(checkpoint_dir=ckpt_dir,
                                checkpoint_every=10 * K_PROG,
                                pipeline_depth=pipeline))
    t0 = time.perf_counter()
    res = eng.run(st, rounds=rounds)
    wall = time.perf_counter() - t0
    import json as _json
    import sys as _sys

    for row in res.chunks:
        print(_json.dumps({"kind": "soak_chunk", "config": 7, **row}),
              file=_sys.stderr)
    _emit_metrics(cl.cfg, res.state, 7)
    digest = health_mod.digest(res.state)
    out = {"config": 7, "n": n, "rounds": res.rounds,
           "chunks": len(res.chunks), "programs": res.programs,
           "retries": res.retries, "breaches": res.breaches,
           "storm_period": p,
           "wall_s": round(wall, 1),
           "rounds_per_sec": round(res.rounds / max(wall, 1e-9), 1),
           "components": health_mod.digest_components(digest),
           "healthy": health_mod.healthy(digest)}
    if OPS:
        out["ops"] = _emit_ops(
            res, storm, 7, channels=tuple(c.name for c in cl.cfg.channels))
    return out


def config8_overload(n=96, waves=10, wave_len=12, adaptive=True,
                     seed=7):
    """Bulk-traffic overload under channel capacity: the backpressure
    controller's A/B harness (ROADMAP item 3's first SLO slice).

    Repeated bursts of simultaneous fresh plumtree broadcasts saturate
    the per-edge broadcast lanes (``lane_rate=1``): static config
    defers pile up in the shared outbox and deliver rounds late —
    exactly the head-of-line blocking Partisan's ATC'19 motivation
    names.  With ``adaptive=True`` the backpressure controller
    (``Config.control.backpressure``) integrates each channel's
    delivered-age high-water mark into a pressure level and sheds the
    stalest queued records, bounding per-channel delivery p99 while
    plumtree's repair path keeps coverage complete.  Returns the
    per-channel p99/max/count from ``latency.percentiles`` — the
    ``--slo`` gate's input."""
    from partisan_tpu import latency as latency_mod
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config, ControlConfig, PlumtreeConfig
    from partisan_tpu.models.plumtree import Plumtree

    n = max(n, 32)
    ctl = ControlConfig(backpressure=True) if adaptive \
        else ControlConfig()
    cfg = Config(n_nodes=n, seed=seed, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups",
                 latency=True, channel_capacity=True, lane_rate=1,
                 outbox_cap=48, max_broadcasts=8, control=ctl,
                 plumtree=PlumtreeConfig(aae=False))
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    st = _boot_joinall(cl, 40)
    rng = np.random.default_rng(9)
    ver = 1
    for _ in range(waves):
        mm = st.model
        for s in range(4):
            src = int(rng.integers(0, n))
            mm = model.broadcast(mm, src, s, ver + 1, fresh=True)
        ver += 1
        st = cl.steps(st._replace(model=mm), wave_len)
    _sync(st)
    names = tuple(c.name for c in cfg.channels)
    pct = latency_mod.percentiles(st.latency, channels=names)
    out = {"config": 8, "n": n, "adaptive": adaptive,
           "waves": waves, "wave_len": wave_len,
           "coverage": round(float(model.coverage(
               st.model, st.faults.alive, 3, version=ver)), 4),
           "outbox_shed": int(jax.device_get(st.outbox.shed)),
           "p99": {ch: pct[ch]["p99"] for ch in names},
           "age_max": {ch: pct[ch]["max"] for ch in names},
           "delivered": {ch: pct[ch]["count"] for ch in names}}
    if adaptive:
        from partisan_tpu import control as control_mod

        out["control"] = control_mod.poll(st.control)
    return out


def config9_elastic(n=8192, seed=7, drain=3 * K_PROG, bound=8,
                    ingress_trace=None, ckpt_dir=None):
    """Runtime elasticity under live traffic (ROADMAP item 5): a
    cluster booted at HALF its pre-allocated capacity scales OUT to
    full width mid-flash-crowd (activated rows enroll through the join
    path), survives a crash batch, then scales IN to a quarter through
    the graceful leave path (drain window + in-scan deactivation) —
    all as ONE storm timeline through the chunked soak engine, so the
    whole elastic trajectory checkpoints and replays bit-for-bit.

    Gates (the stdout object): conservation breaches == 0 across every
    resize, overlay recovery (health digest one-component + healthy at
    the end), per-channel delivered-age p99 <= ``bound``, and the
    recorded elastic timeline hitting exactly [half, full, quarter].
    ``ingress_trace`` optionally replays a recorded external-arrival
    trace (ingress.Journal format) through the inject ring alongside
    the in-scan traffic — the second arrival mode."""
    from partisan_tpu import elastic as elastic_mod
    from partisan_tpu import health as health_mod
    from partisan_tpu import latency as latency_mod
    from partisan_tpu import soak as soak_mod
    from partisan_tpu import workload as workload_mod
    from partisan_tpu.cluster import Cluster, activate
    from partisan_tpu.config import Config, IngressConfig, TrafficConfig
    from partisan_tpu.models.plumtree import Plumtree

    n = max(n, 64)
    w0, w_hi, w_lo = n // 2, n, n // 4
    base_rate, crowd_rate = 300, 1500

    def mk():
        cfg = Config(
            n_nodes=n, seed=seed, peer_service_manager="hyparview",
            msg_words=16, partition_mode="groups",
            width_operand=True, elastic=True,
            latency=True, metrics=True, metrics_ring=512,
            health=K_PROG, health_ring=512,
            traffic=TrafficConfig(enabled=True, rate_x1000=base_rate,
                                  burst_max=2, hot_skew=1),
            ingress=IngressConfig(enabled=ingress_trace is not None,
                                  slots=8),
            emit_compact=32 if n > 4096 else 0)
        return Cluster(cfg, model=Plumtree())

    cl = mk()
    st = activate(cl.init(), w0)
    rng = np.random.default_rng(7)
    base = 1
    join = jax.jit(lambda m, nodes, tgts: cl.manager.join_many(
        cl.cfg, m, nodes, tgts))
    while base < w0:
        hi = min(base * 8, w0)
        nodes = np.arange(base, hi, dtype=np.int32)
        tgts = rng.integers(0, base, size=nodes.shape[0]).astype(np.int32)
        st = cl.steps(st._replace(manager=join(st.manager, nodes, tgts)),
                      K_PROG)
        base = hi
    for _ in range(3):
        st = cl.steps(st, K_PROG)
    _sync(st)
    start = int(jax.device_get(st.rnd))

    # The elastic timeline: flash crowd -> scale OUT mid-crowd ->
    # crash batch -> crowd ends -> scale IN (drain + in-scan
    # deactivation) -> heal.  Offsets in K_PROG-sized phases.
    P = K_PROG
    events = (
        workload_mod.flash_crowd(P, 6 * P, crowd_rate, base_rate)
        + ((2 * P, soak_mod.ScaleOut(w_hi)),
           (4 * P, soak_mod.CrashBatch(frac=0.02)),
           (8 * P, soak_mod.ScaleIn(w_lo, drain=drain)),
           (8 * P + drain + P, soak_mod.Heal(revive=True))))
    storm = workload_mod.Traffic(events=()).storm(
        start=start, extra=events)
    feed = None
    if ingress_trace is not None:
        from partisan_tpu import ingress as ingress_mod

        feed = ingress_mod.IngressFeed(journal_path=ingress_trace)
    warm = [cl]
    eng = soak_mod.Soak(
        make_cluster=lambda: warm.pop() if warm else mk(), storm=storm,
        invariants=[soak_mod.conservation()],
        ingress=feed,
        cfg=soak_mod.SoakConfig(poll_latency=True,
                                checkpoint_dir=ckpt_dir,
                                checkpoint_every=10 * K_PROG))
    rounds = 8 * P + drain + 6 * P
    t0 = time.perf_counter()
    res = eng.run(st, rounds=rounds)
    wall = time.perf_counter() - t0
    import json as _json
    import sys as _sys

    for row in res.chunks:
        print(_json.dumps({"kind": "soak_chunk", "config": 9, **row}),
              file=_sys.stderr)
    _emit_metrics(cl.cfg, res.state, 9)
    digest = health_mod.digest(res.state)
    timeline = elastic_mod.snapshot(res.state.elastic)
    names = tuple(c.name for c in cl.cfg.channels)
    pct = latency_mod.percentiles(res.state.latency, channels=names)
    p99 = {ch: pct[ch]["p99"] for ch in names}
    slo_ok, _rows = slo_gate(p99, bound)
    widths = [int(w) for w in timeline["widths"]]
    out = {"config": 9, "n": n, "rounds": res.rounds,
           "chunks": len(res.chunks), "retries": res.retries,
           "breaches": res.breaches,
           "widths": widths, "resizes": timeline["resizes"],
           "n_active": timeline["n_active"],
           "traffic": workload_mod.poll(res.state.traffic),
           "p99": p99, "slo_bound": bound,
           "wall_s": round(wall, 1),
           "components": health_mod.digest_components(digest),
           "overlay_ok": health_mod.overlay_ok(digest),
           "pass": (res.breaches == 0 and bool(slo_ok)
                    and health_mod.overlay_ok(digest)
                    and widths == [w0, w_hi, w_lo]
                    and timeline["n_active"] == w_lo)}
    if feed is not None:
        from partisan_tpu import ingress as ingress_mod

        out["ingress"] = ingress_mod.poll(res.state.ingress)
    if OPS:
        out["ops"] = _emit_ops(res, storm, 9, channels=names,
                               slo_rounds=bound,
                               crowd_x1000=crowd_rate)
        out["pass"] = bool(out["pass"] and out["ops"]["ok"])
    return out


def slo_gate(p99: dict, bound: int) -> tuple[bool, list[dict]]:
    """Per-channel p99 pass/fail rows against ``bound`` rounds (the
    ``--slo`` gate over ``latency.percentiles`` output).  Channels
    with no traffic pass vacuously."""
    rows = []
    ok = True
    for ch, v in p99.items():
        passed = v is None or v <= bound
        ok = ok and passed
        rows.append({"kind": "slo", "channel": ch, "p99": v,
                     "bound": bound, "pass": passed})
    return ok, rows


def _boot_joinall(cl, settle: int):
    """All nodes join via node 0 in one scripted batch, then settle —
    the A/B harnesses' shared bootstrap (deterministic and cheap; the
    staggered _boot_overlay is for fidelity-sensitive scenarios)."""
    n = cl.cfg.n_nodes
    st = cl.init()
    m = cl.manager.join_many(cl.cfg, st.manager, list(range(1, n)),
                             [0] * (n - 1))
    return cl.steps(st._replace(manager=m), settle)


def fanout_ab_arm(adaptive: bool, n=128, waves=12, wave_len=10,
                  seed=3) -> dict:
    """ONE arm of the fanout governor's A/B (the single harness both
    ``control_ab`` — the committed CONTROL_AB.json — and the tier-1
    gate in tests/test_control.py run, so the evidence and the test
    cannot drift apart).  Recycled-slot broadcasts reset the learned
    pruned flags by design (per-root trees), so the static config
    re-floods at full overlay fanout every recycle; the governor
    retains the learned budget.  lazy_tick 3 rounds so I_HAVE adverts
    lag the eager wave (the reference's 1 s batching vs ms hops)
    instead of racing it.  AAE is off so dissemination is measurably
    eager+lazy (the exchange lane otherwise out-races the flood and
    leaves nothing to govern) — which makes the lazy advert chain the
    ONLY last-mile repair, so shuffles are quiesced for the run: link
    churn sheds ``lazy_pending`` flags by design (plumtree's
    neighbors_down handling) and with AAE off a shed advert toward a
    governor-cut straggler would never retransmit (production configs
    keep AAE on exactly for this).  Returns cumulative + steady-half
    redundancy ratios, final-slot coverage, and the controller's
    poll."""
    from partisan_tpu import control as control_mod
    from partisan_tpu import provenance as prov_mod
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import (Config, ControlConfig,
                                     HyParViewConfig, PlumtreeConfig)
    from partisan_tpu.models.plumtree import Plumtree

    ctl = ControlConfig(fanout=True) if adaptive else ControlConfig()
    cfg = Config(n_nodes=n, seed=seed, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups",
                 provenance=True, provenance_ring=512,
                 max_broadcasts=8, control=ctl, lazy_tick_ms=3000,
                 hyparview=HyParViewConfig(active_min=6, active_max=8,
                                           shuffle_interval_ms=60_000),
                 plumtree=PlumtreeConfig(aae=False))
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    st = _boot_joinall(cl, 60)
    rng = np.random.default_rng(5)
    ver = 1
    for w in range(waves):
        st = st._replace(model=model.broadcast(
            st.model, int(rng.integers(0, n)), w % 4, ver + 1,
            fresh=True))
        ver += 1
        st = cl.steps(st, wave_len)
    traffic_end = int(jax.device_get(st.rnd))
    # drain: the last wave's lazy/graft repair gets one more window
    # before coverage is judged (the claim is coverage-at-completion;
    # reading at the exact wave boundary races the final graft RTT)
    st = cl.steps(st, wave_len)
    _sync(st)
    snap = prov_mod.snapshot(st.provenance)
    rr = np.asarray(snap["rounds"])
    g = np.asarray(snap["gossip"]).astype(float)
    d = np.asarray(snap["dup"]).sum(axis=1).astype(float)
    # the steady half of the TRAFFIC phase (drain rounds excluded)
    tail = (rr >= traffic_end - (waves // 2) * wave_len) \
        & (rr < traffic_end)
    arm = {
        "redundancy_ratio": prov_mod.redundancy(
            snap)["redundancy_ratio"],
        "steady_redundancy_ratio": round(
            float(d[tail].sum()) / max(float(g[tail].sum()), 1), 4),
        "coverage": round(float(model.coverage(
            st.model, st.faults.alive, (waves - 1) % 4,
            version=ver)), 4),
    }
    if adaptive:
        arm.update(control_mod.poll(st.control))
        arm["_state"] = st               # for the tier-1 gate's ring
    return arm


def fanout_calm_arm(adaptive: bool, n=64, seed=4) -> dict:
    """The calm-run arm: one ordinary broadcast, no recycles, then 30
    further QUIET rounds.  The no-regression claim is outcome parity —
    identical coverage and redundancy to the static arm (the governor
    MAY take a step on the one dissemination wave; a single recoverable
    demotion with identical outcomes is the loop working, not a
    regression) — plus stillness on the quiet tail: once traffic
    stops, the governor must stop too (``quiet_adjustments`` == 0)."""
    from partisan_tpu import control as control_mod
    from partisan_tpu import provenance as prov_mod
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config, ControlConfig, PlumtreeConfig
    from partisan_tpu.models.plumtree import Plumtree

    ctl = ControlConfig(fanout=True) if adaptive else ControlConfig()
    cfg = Config(n_nodes=n, seed=seed,
                 peer_service_manager="hyparview", msg_words=16,
                 partition_mode="groups", provenance=True,
                 provenance_ring=256, max_broadcasts=4, control=ctl,
                 plumtree=PlumtreeConfig(aae=False))
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    st = _boot_joinall(cl, 40)
    st = st._replace(model=model.broadcast(st.model, 0, 0, 2))
    st = cl.steps(st, 30)
    adj_after_wave = (int(jax.device_get(st.control.fanout.adjustments))
                      if adaptive else 0)
    st = cl.steps(st, 30)                 # the quiet tail
    _sync(st)
    arm = {"redundancy_ratio": prov_mod.redundancy(
               st.provenance)["redundancy_ratio"],
           "coverage": round(float(model.coverage(
               st.model, st.faults.alive, 0, version=2)), 4)}
    if adaptive:
        arm.update(control_mod.poll(st.control))
        arm["quiet_adjustments"] = (arm["fanout_adjustments"]
                                    - adj_after_wave)
    return arm


def healing_ab_arm(adaptive: bool, n=128, seed=11,
                   crash_frac=0.35) -> dict:
    """ONE arm of the healing escalation A/B (shared by ``control_ab``
    and the tier-1 gate): a crash batch degrades the digest; the arm
    reports rounds until the controller's own graph-health predicate
    (``health.overlay_ok``) holds again."""
    from partisan_tpu import control as control_mod
    from partisan_tpu import faults as faults_mod
    from partisan_tpu import health as health_mod
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config, ControlConfig
    from partisan_tpu.models.plumtree import Plumtree

    ctl = ControlConfig(healing=True) if adaptive else ControlConfig()
    cfg = Config(n_nodes=n, seed=seed,
                 peer_service_manager="hyparview", msg_words=16,
                 partition_mode="groups", health=5, health_ring=256,
                 control=ctl)
    cl = Cluster(cfg, model=Plumtree())
    st = _boot_joinall(cl, 60)
    rng = np.random.default_rng(13)
    victims = rng.choice(np.arange(1, n), size=int(n * crash_frac),
                         replace=False)
    st = st._replace(faults=faults_mod.crash_many(
        st.faults, [int(v) for v in victims]))
    r0 = int(jax.device_get(st.rnd))
    healed = -1
    for _ in range(60):
        st = cl.steps(st, 5)
        if health_mod.overlay_ok(health_mod.digest(st)):
            healed = int(jax.device_get(st.rnd)) - r0
            break
    arm = {"rounds_to_heal": healed}
    if adaptive:
        arm.update(control_mod.poll(st.control))
        arm["_state"] = st               # for the tier-1 gate's follow-on
    return arm


def _strip_state(arm: dict) -> dict:
    """Drop the test-only state handle before JSON export."""
    return {k: v for k, v in arm.items() if k != "_state"}


def control_ab(scale: float = 1.0) -> dict:
    """The three controllers' A/B evidence (ISSUE 10 acceptance): for
    each, one scenario where the closed loop beats the best static
    config on its headline metric, plus a calm-run no-regression check
    for the fanout governor.  Every arm is deterministic (fixed seeds)
    and SHARED with the tier-1 gates in tests/test_control.py (the
    ``*_ab_arm`` harnesses above), so the committed CONTROL_AB.json
    reproduces exactly and certifies the same procedure the tests
    assert."""
    out: dict = {}

    # ---- 1. fanout governor: steady-state redundancy ratio ------------
    n = max(64, int(128 * scale))
    fan_s = fanout_ab_arm(False, n=n)
    fan_a = _strip_state(fanout_ab_arm(True, n=n))
    out["fanout"] = {
        "metric": "steady_redundancy_ratio", "n": n,
        "static": fan_s, "adaptive": fan_a,
        "win": fan_a["steady_redundancy_ratio"]
        < fan_s["steady_redundancy_ratio"],
        "coverage_ok": fan_a["coverage"] == 1.0,
    }

    # ---- 1b. fanout calm-run no-regression ----------------------------
    cn = max(48, int(64 * scale))
    calm_s = fanout_calm_arm(False, n=cn)
    calm_a = fanout_calm_arm(True, n=cn)
    out["fanout_calm"] = {
        "static": calm_s, "adaptive": calm_a,
        # outcome parity + quiet-tail stillness (see fanout_calm_arm)
        "no_regression": (calm_a["coverage"] == calm_s["coverage"]
                          and calm_a["redundancy_ratio"]
                          == calm_s["redundancy_ratio"]
                          and calm_a["quiet_adjustments"] == 0),
    }

    # ---- 2. backpressure: per-channel delivery p99 under overload -----
    bp_n = max(48, int(96 * scale))
    bp_s = config8_overload(n=bp_n, adaptive=False)
    bp_a = config8_overload(n=bp_n, adaptive=True)
    bulk = [ch for ch, v in bp_s["p99"].items() if v is not None]
    # A trafficked channel must STAY trafficked in the adaptive arm (a
    # loop that sheds a channel to silence has destroyed it, not
    # improved it) and strictly beat the static p99.
    out["backpressure"] = {
        "metric": "p99_delivery_age", "n": bp_n,
        "static": bp_s, "adaptive": bp_a,
        "win": bool(bulk) and all(
            bp_a["p99"][ch] is not None
            and bp_a["delivered"][ch] > 0
            and bp_a["p99"][ch] < bp_s["p99"][ch]
            for ch in bulk),
        "coverage_ok": bp_a["coverage"] == 1.0,
    }

    # ---- 3. healing: rounds-to-heal after a crash batch ---------------
    hn = max(64, int(128 * scale))
    heal_s = healing_ab_arm(False, n=hn)
    heal_a = _strip_state(healing_ab_arm(True, n=hn))
    out["healing"] = {
        "metric": "rounds_to_heal", "n": hn,
        "static": heal_s, "adaptive": heal_a,
        "win": (heal_a["rounds_to_heal"] != -1
                and (heal_s["rounds_to_heal"] == -1
                     or heal_a["rounds_to_heal"]
                     < heal_s["rounds_to_heal"])),
    }

    out["all_win"] = bool(out["fanout"]["win"]
                          and out["backpressure"]["win"]
                          and out["healing"]["win"]
                          and out["fanout_calm"]["no_regression"])
    return out


def fleet_sweep(width: int = 8, n: int = 256, seed: int = 0,
                max_rounds: int = 300, settle: int = 40,
                salts=None) -> dict:
    """Distribution card over a SEED POPULATION (ROADMAP item 4c): W
    independent hyparview+plumtree clusters — one per salt — run as ONE
    vmapped program (fleet.Fleet), each broadcasting from node 0 after
    the same scripted bootstrap, polled on the batched health digest
    until every member converges (or ``max_rounds``).  Emits
    p5/p50/p95 distributions — not single-seed points — for
    rounds-to-converge (from each member's health snapshot ring),
    whole-run redundancy ratio (provenance plane), and per-channel
    delivery-age p99 (latency plane): the statistical evaluation axes
    of Leitão et al. (SRDS'07), at one-program cost.  The CLI is
    ``bench.py --fleet W [n]``; ``tools/fleet_report.py`` exports
    per-member JSON lines."""
    from partisan_tpu import fleet as fleet_mod
    from partisan_tpu import health as health_mod
    from partisan_tpu import provenance as prov_mod
    from partisan_tpu.config import Config
    from partisan_tpu.metrics import ring_order
    from partisan_tpu.models.plumtree import Plumtree

    cfg = Config(n_nodes=n, seed=seed, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups",
                 health=K_PROG, health_ring=max(64, max_rounds // K_PROG + 8),
                 provenance=True, provenance_ring=256, latency=True,
                 max_broadcasts=8, salt_operand=True)
    model = Plumtree()
    fl = fleet_mod.Fleet(cfg, width=width, model=model)
    t0 = time.perf_counter()
    st = fl.init(salts)
    joins, contacts = list(range(1, n)), [0] * (n - 1)
    st = st._replace(manager=fl.map_members(
        lambda m: fl.manager.join_many(cfg, m, joins, contacts),
        st.manager))
    st = fl.steps(st, settle)
    r0 = int(jax.device_get(st.rnd))
    st = st._replace(model=fl.map_members(
        lambda m: model.broadcast(m, 0, 0, 2), st.model))
    for _ in range(0, max_rounds, K_PROG):
        words = health_mod.digest(st)
        if all(health_mod.digest_converged(w) for w in words):
            break
        st = fl.steps(st, K_PROG)
    wall = time.perf_counter() - t0

    # per-member reductions (host-side slices of the batched planes)
    conv, redund, p99 = [], [], {}
    for j in range(width):
        hs = jax.tree.map(lambda x: x[j], st.health)
        rr = np.asarray(jax.device_get(hs.rnd))
        dg = np.asarray(jax.device_get(hs.digests))
        order = ring_order(rr)
        rr, dg = rr[order], dg[order]
        hit = [int(r) - r0 for r, w in zip(rr, dg)
               if r >= r0 and health_mod.digest_converged(int(w))]
        conv.append(hit[0] if hit else -1)
        redund.append(prov_mod.redundancy(
            jax.tree.map(lambda x: x[j], st.provenance))
            ["redundancy_ratio"])
        for ch, v in fl.member_latency(
                st, j, channels=tuple(c.name for c in cfg.channels)
        ).items():
            p99.setdefault(ch, []).append(v["p99"])
    card = {
        "config": "fleet_sweep", "width": width, "n": n, "seed": seed,
        "rounds": int(jax.device_get(st.rnd)) - r0,
        "converged": sum(1 for c in conv if c >= 0),
        "rounds_to_converge": fleet_mod.distribution(conv),
        "redundancy_ratio": fleet_mod.distribution(redund),
        "p99": {ch: fleet_mod.distribution(vs)
                for ch, vs in p99.items() if any(v is not None
                                                 for v in vs)},
        "programs": fl.programs(),
        "wall_s": round(wall, 2),
        "members": {
            "rounds_to_converge": conv,
            "redundancy_ratio": redund,
        },
    }
    return card


# ---------------------------------------------------------------------------
# Traffic-plane SLO suite (ROADMAP item 3): the app models under
# sustained adversarial open-loop load — flash crowds, diurnal churn,
# partitions, one-way links, stragglers — every scenario gated
# Dapper-style on the latency plane's per-channel p99.  Partisan's
# ATC'19 claim operationalized: the bulk channel may degrade under a
# flash crowd; the membership/control channels must hold their p99.
# ---------------------------------------------------------------------------

BULK_CHANNEL = "bulk"
TRAFFIC_SLO_BOUND = 4          # rounds: control channels' p99 ceiling
TRAFFIC_MODELS = ("p2p_chat", "causal_chat", "paxos", "commit",
                  "alsberg_day")
# models whose controllers-off vs controllers-on A/B the suite runs
# (the backpressure-win evidence; the rest run the closed loop only)
TRAFFIC_AB_MODELS = ("p2p_chat", "causal_chat", "paxos")


def _traffic_build(model_name: str, n: int):
    """One app model's harness under the traffic plane: returns
    ``(model, extras, boot, drive, check)`` — the model (possibly a
    Stack), config extras, overlay bootstrap, the app's own scripted
    workload as (state, start, rounds) -> (state, storm-events), and
    the end-of-run application check (the protocol's own guarantee
    must survive the storm)."""
    from partisan_tpu import soak as soak_mod
    from partisan_tpu.config import PlumtreeConfig

    if model_name in ("p2p_chat", "causal_chat"):
        from partisan_tpu.models.plumtree import Plumtree
        from partisan_tpu.models.stack import Stack

        plum = Plumtree()
        # provenance ON: the chat scenarios now SCHEDULE plumtree
        # broadcasts (below), so the dissemination forest + redundancy
        # ring are live evidence — the fanout-governor × flash-crowd
        # interplay (ROADMAP item 3's remaining gap) and the
        # crowd-window redundancy gate both read it.
        extras = dict(peer_service_manager="hyparview", msg_words=16,
                      health=5, health_ring=256, max_broadcasts=8,
                      provenance=True, provenance_ring=512,
                      plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4,
                                              aae=True))
        senders = tuple(range(1, 5))
        receivers = tuple(range(n - 8, n - 4))

        def bcast(slot, root, value, off):
            """A scheduled plumtree broadcast as a storm Script: the
            ACTUAL broadcast workload the chat suites carried plumtree
            for but never exercised — one calm, one inside the flash
            crowd (callers pick offsets)."""
            def fn(cluster, state, rnd):
                m = stack.replace_sub(state.model, 0, plum.broadcast(
                    stack.sub(state.model, 0), root, slot, value))
                return _mark_bcast(state._replace(model=m), root, slot)
            return (off, soak_mod.Script(fn))

        def bcast_events(start, rounds):
            # The crowd-window geometry MUST mirror traffic_scenario's
            # timeline: flash crowd spans [g(q), g(q) + g(2q)) with
            # q = rounds/8 and g() the K_PROG grain snap.  The calm
            # broadcast fires STRICTLY BEFORE the window opens (offset
            # 0 at suite-smoke scale, where g(q) == K_PROG) so the
            # crowd-window gossip gate cannot be satisfied by the
            # baseline broadcast; the second fires at the window's
            # grain-snapped MIDPOINT — strictly inside at every scale
            # (at rounds=80: window [10, 30), broadcast at 20; a
            # rounds//4 formula would land exactly ON the restore
            # round there and the crowd gate would never see it).
            def g(off):
                return max(K_PROG, off // K_PROG * K_PROG)

            q = rounds // 8
            calm = max(0, g(q) - K_PROG)
            mid = g(q) + max(K_PROG,
                             g(2 * q) // 2 // K_PROG * K_PROG)
            return (bcast(0, 0, start + calm, calm),
                    bcast(1, 0, start + mid, mid))

        def bcast_check(st):
            """Both scheduled broadcasts fully covered the (healed)
            overlay — the crowd one proves dissemination survives the
            overload window."""
            alive = st.faults.alive
            cov = [float(jax.device_get(plum.coverage(
                stack.sub(st.model, 0), alive, s))) for s in (0, 1)]
            return cov

        if model_name == "p2p_chat":
            from partisan_tpu.models.p2p_chat import P2PChat

            chat = P2PChat()
            stack = Stack([plum, chat])
            extras["causal_p2p_labels"] = ("chat",)

            def drive(st, start, rounds):
                # two sends per sender: one calm, one INSIDE the flash
                # crowd — per-edge FIFO must survive the overload
                nodes = np.repeat(np.asarray(senders, np.int32), 2)
                rnds = np.stack([
                    np.full(len(senders), start + 4),
                    np.full(len(senders), start + rounds // 4 + 4),
                ], axis=1).reshape(-1)
                dsts = np.repeat(np.asarray(receivers, np.int32), 2)
                m = chat.schedule_many(stack.sub(st.model, 1),
                                       nodes, rnds, dsts)
                return st._replace(
                    model=stack.replace_sub(st.model, 1, m)), \
                    bcast_events(start, rounds)

            def check(st):
                import jax as _jax

                logs = P2PChat.logs(_jax.device_get(
                    stack.sub(st.model, 1)))
                got = sum(len(logs[int(r)]) for r in receivers)
                fifo = all(P2PChat.edge_fifo_ok(logs[int(r)])
                           for r in receivers)
                cov = bcast_check(st)
                return bool(fifo and got >= len(senders)
                            and all(c == 1.0 for c in cov)), \
                    {"causal_delivered": int(got),
                     "causal_expected": 2 * len(senders),
                     "bcast_coverage": cov}
        else:
            from partisan_tpu.models.causal_chat import CausalChat

            chat = CausalChat()
            stack = Stack([plum, chat])
            extras["causal_labels"] = ("chat",)
            extras["n_actors"] = n

            def drive(st, start, rounds):
                m = stack.sub(st.model, 1)
                for s in senders:
                    m = chat.schedule(m, int(s), start + 4)
                    m = chat.schedule(m, int(s),
                                      start + rounds // 4 + 4)
                return st._replace(
                    model=stack.replace_sub(st.model, 1, m)), \
                    bcast_events(start, rounds)

            def check(st):
                import jax as _jax

                logs = CausalChat.logs(_jax.device_get(
                    stack.sub(st.model, 1)))
                got = sum(len(lg) for lg in logs)
                cov = bcast_check(st)
                return bool(got > 0 and all(c == 1.0 for c in cov)), \
                    {"causal_delivered": int(got),
                     "bcast_coverage": cov}

        def boot(cl):
            return _boot_joinall(cl, 40)

        return stack, extras, boot, drive, check

    if model_name == "paxos":
        from partisan_tpu.models.paxos import Paxos

        model = Paxos(slots=2)
        extras = dict(msg_words=13, inbox_cap=96)

        def boot(cl):
            return _boot_fullmesh(cl, n)

        def drive(st, start, rounds):
            def prop(slot, node, value, off):
                def fn(cluster, state, rnd):
                    return state._replace(model=model.propose(
                        state.model, node, slot, value, rnd, n))
                return (off, soak_mod.Script(fn))
            # decree 0 proposed calm, decree 1 mid-flash-crowd by TWO
            # rival proposers at the same boundary (the overload must
            # not break safety).  Offsets sit on the K_PROG chunk
            # grain — an off-grain storm event would compile a second
            # scan length (see traffic_scenario's g()).
            crowd = rounds // 4 // K_PROG * K_PROG + K_PROG
            return st, (prop(0, 1, 111, K_PROG),
                        prop(1, 2, 222, crowd),
                        prop(1, 3, 333, crowd))

        def check(st):
            decided0 = len(model.decided_nodes(st.model, 0))
            decided1 = len(model.decided_nodes(st.model, 1))
            return bool(model.agreement(st.model)
                        and decided0 > n // 2 and decided1 > n // 2), \
                {"decided_0": int(decided0), "decided_1": int(decided1)}

        return model, extras, boot, drive, check

    if model_name == "commit":
        from partisan_tpu.models import commit as commit_mod

        model = commit_mod.CommitProtocol("lampson_2pc", slots=2)
        extras = dict(inbox_cap=96, emit_cap=16)

        def boot(cl):
            return _boot_fullmesh(cl, n)

        def drive(st, start, rounds):
            def begin(slot, coord, value, off):
                def fn(cluster, state, rnd):
                    return state._replace(model=model.begin(
                        state.model, coord, slot, value,
                        state.faults.alive, rnd))
                return (off, soak_mod.Script(fn))
            crowd = rounds // 4 // K_PROG * K_PROG + K_PROG
            return st, (begin(0, 0, 5, K_PROG),
                        begin(1, 1, 9, crowd))

        def check(st):
            agree = bool(jax.device_get(model.agreement(st.model)))
            delivered = int(np.asarray(jax.device_get(
                st.model.p_status == commit_mod.P_COMMIT)).sum())
            return agree and delivered > 0, \
                {"agreement": agree, "commits": delivered}

        return model, extras, boot, drive, check

    if model_name == "alsberg_day":
        from partisan_tpu.models.alsberg_day import AlsbergDay

        model = AlsbergDay(keys=4)
        extras = dict(inbox_cap=96, emit_cap=16)

        def boot(cl):
            return _boot_fullmesh(cl, n)

        def drive(st, start, rounds):
            def write(client, key, value, off):
                def fn(cluster, state, rnd):
                    return state._replace(model=model.write(
                        state.model, client, key, value))
                return (off, soak_mod.Script(fn))
            crowd = rounds // 4 // K_PROG * K_PROG + K_PROG
            return st, (write(5, 0, 42, K_PROG),
                        write(6, 1, 43, crowd))

        def check(st):
            ok = bool(jax.device_get(st.model.req_ok[5, 0])) \
                and bool(jax.device_get(st.model.req_ok[6, 1]))
            rep = bool(jax.device_get(AlsbergDay.replicated(
                st.model, 0, st.faults.alive)))
            return ok and rep, {"acked": ok, "replicated": rep}

        return model, extras, boot, drive, check

    raise ValueError(f"unknown traffic model {model_name!r}; have "
                     f"{TRAFFIC_MODELS}")


def traffic_scenario(model_name: str, n: int = 64, rounds: int = 240,
                     adaptive: bool = True, seed: int = 29,
                     bound: int = TRAFFIC_SLO_BOUND,
                     rate_x1000: int = 600,
                     crowd_x1000: int = 4000) -> dict:
    """ONE app model under the full adversarial traffic plane, driven
    through the chunked soak engine: open-loop bulk arrivals on a
    dedicated ``bulk`` channel (hot-spot skewed), a flash crowd at
    rounds/8..3/8, slow-node stragglers across the crowd, a diurnal
    churn pulse, a one-way (directed) link cut, and a regional
    partition+heal — while the app's own scripted workload runs and
    must keep its guarantee.  Gates (the returned dict): per-channel
    p99 (control channels <= ``bound`` while bulk degrades),
    conservation at every chunk boundary, overlay recovery (health
    digest, where the model runs on hyparview), and the app check.
    ``adaptive`` arms the backpressure controller (+ healing where the
    health plane is on) — the A/B the committed TRAFFIC_SLO.json
    carries."""
    from partisan_tpu import interpose as interpose_mod
    from partisan_tpu import latency as latency_mod
    from partisan_tpu import soak as soak_mod
    from partisan_tpu import workload
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import (ChannelSpec, Config, ControlConfig,
                                     DEFAULT_CHANNELS, TrafficConfig)

    n = max(n, 24)
    model, extras, boot, drive, check = _traffic_build(model_name, n)
    hx = extras.get("health", 0) > 0
    px = bool(extras.get("provenance"))
    # The chat scenarios carry provenance because they now SCHEDULE
    # plumtree broadcasts — so the adaptive arm also arms the eager-
    # fanout governor there: the fanout × flash-crowd interplay under
    # real overload, gated by the crowd-window redundancy below.
    ctl = ControlConfig(backpressure=True, healing=hx, fanout=px,
                        ring=64) if adaptive else ControlConfig()
    cfg = Config(
        n_nodes=n, seed=seed,
        channels=DEFAULT_CHANNELS + (ChannelSpec(BULK_CHANNEL),),
        latency=True, channel_capacity=True, lane_rate=1,
        outbox_cap=128, control=ctl,
        # dense faults so the one-way cut is expressible (n is far
        # under the dense threshold at suite scale)
        partition_mode="dense",
        traffic=TrafficConfig(enabled=True, rate_x1000=rate_x1000,
                              burst_max=4, zipf_s=1.0, hot_skew=2,
                              channel=BULK_CHANNEL, churn=True,
                              ring=256),
        **extras)
    cl = Cluster(cfg, model=model,
                 interpose=interpose_mod.StragglerDelay(cap=16))
    st = boot(cl)
    # The boot is scaffolding: a join storm through lane_rate=1
    # channels leaves a deferred-control backlog whose late deliveries
    # would dominate the cumulative p99 for the first chunks.  Zero
    # the histograms so the gate measures the STORM phase (stats and
    # queues carry over untouched — the conservation ledger is
    # from-init cumulative).
    st = st._replace(latency=latency_mod.init(cfg))
    start = int(jax.device_get(st.rnd))
    q = rounds // 8
    st, app_events = drive(st, start, rounds)

    slow = tuple(range(n - 4, n))      # high ids: never app-critical
    half = n // 2

    def g(off: int) -> int:
        """Snap a storm offset to the K_PROG chunk grain: the soak
        engine clips chunks at event rounds, so an off-grain offset
        would compile a SECOND scan length per scenario config (the
        file's one-k=K_PROG-program discipline)."""
        return max(K_PROG, off // K_PROG * K_PROG)

    timeline = workload.Traffic(
        # q..3q: flash crowd with slow-node stragglers riding it
        workload.flash_crowd(g(q), g(2 * q), crowd_x1000, rate_x1000)
        + ((g(q), workload.Stragglers(nodes=slow, mult=2)),
           (g(3 * q), workload.Stragglers(nodes=slow, mult=0)),
           # ~3.5q..4q — one-way cut: the upper half can't reach the
           # lower (the lower->upper direction still flows)
           (g(3 * q + q // 2), workload.DirectedCut(
               src=tuple(range(half, n)), dst=tuple(range(half)))),
           (g(4 * q), soak_mod.Heal()),
           # ~4.5q..5.5q — diurnal churn pulse (in-scan, 0.4%/round)
           (g(4 * q + q // 2), workload.SetChurn(4000)),
           (g(5 * q + q // 2), workload.SetChurn(0)),
           # ~5.5q..6q — regional partition, then heal + revive the
           # churn casualties; the last 2q rounds are the recovery
           # window the end-state health gate judges
           (g(5 * q + q // 2), soak_mod.Partition()),
           (g(6 * q), soak_mod.Heal(revive=True)))
        + tuple(app_events))
    storm = timeline.storm(start=start)

    # Conservation at every boundary — the flow ledger
    # (soak.flow_conservation): exact (slack 0) for the event-lane
    # models, capacity deferrals included; the chat models' causal
    # lanes get a small upward slack for their 8 scheduled sends'
    # fan-out bookkeeping, one-sided for the p2p duplicate netting
    # (see the invariant's docs).  Overlay recovery is judged on the
    # END state (a scripted partition is SUPPOSED to split the digest
    # mid-run, so the one-component invariant is not armed).
    causal = bool(cfg.causal_labels or cfg.causal_p2p_labels)
    # Upward slack: a broadcast-causal lane fans each of the 8
    # scheduled sends to up to n receivers; the p2p lane delivers
    # each exactly once.
    slack = (8 * n if cfg.causal_labels else 32) if causal else 0
    invariants = [soak_mod.flow_conservation(
        slack=slack, one_sided=bool(cfg.causal_p2p_labels))]
    warm = [cl]
    eng = soak_mod.Soak(
        make_cluster=lambda: warm.pop() if warm else Cluster(
            cfg, model=model,
            interpose=interpose_mod.StragglerDelay(cap=16)),
        storm=storm, invariants=invariants,
        cfg=soak_mod.SoakConfig(chunk_fixed=K_PROG,
                                poll_latency=True))
    t0 = time.perf_counter()
    res = eng.run(st, rounds=rounds)
    wall = time.perf_counter() - t0
    st = res.state

    names = tuple(c.name for c in cfg.channels)
    pct = latency_mod.percentiles(st.latency, channels=names)
    p99 = {ch: pct[ch]["p99"] for ch in names}
    delivered = {ch: pct[ch]["count"] for ch in names}
    # control channels = every trafficked channel except bulk
    control_ok = all(
        p99[ch] is not None and p99[ch] <= bound
        for ch in names
        if ch != BULK_CHANNEL and delivered[ch] > 0)
    app_ok, app_info = check(st)
    # Head-of-line isolation, judged per WINDOW inside the flash
    # crowd: chunks where the bulk channel's windowed p99 breached the
    # bound while every other trafficked channel held — the ATC'19
    # claim measured on the same clock as the overload.
    crowd_rows = [row for row in res.chunks
                  if row.get("traffic", {}).get("rate_x1000", 0)
                  >= crowd_x1000]

    def _isolated(row):
        p = row.get("p99") or {}
        bulk_w = p.get(BULK_CHANNEL)
        ctrl = [v for ch, v in p.items()
                if ch != BULK_CHANNEL and v is not None]
        # bulk breached while at least one MEASURED control channel
        # held (a window with no control deliveries is no evidence)
        return (bulk_w is not None and bulk_w > bound
                and bool(ctrl) and all(v <= bound for v in ctrl))

    out = {
        "model": model_name, "n": n, "rounds": res.rounds,
        "adaptive": adaptive, "bound": bound,
        "crowd_chunks": len(crowd_rows),
        "crowd_isolation_chunks": sum(
            1 for row in crowd_rows if _isolated(row)),
        "p99": p99, "age_max": {ch: pct[ch]["max"] for ch in names},
        "delivered": delivered,
        "bulk_p99": p99[BULK_CHANNEL],
        "control_ok": bool(control_ok),
        "outbox_shed": int(jax.device_get(st.outbox.shed)),
        "traffic": workload.poll(st.traffic),
        "breaches": res.breaches, "retries": res.retries,
        "chunks": len(res.chunks),
        "slo_windows": _slo_window_count(res.chunks, bound),
        "app_ok": bool(app_ok), "app": app_info,
        "wall_s": round(wall, 1),
    }
    if OPS:
        out["ops"] = _emit_ops(res, storm, f"traffic_{model_name}",
                               channels=names, slo_rounds=bound,
                               crowd_x1000=crowd_x1000)
    if px:
        # Broadcast-under-load gate (ROADMAP item 3 remaining): the
        # scheduled plumtree broadcasts' dissemination, judged in the
        # FLASH-CROWD window off the provenance ring — gossip copies
        # must actually move during the overload (coverage progresses
        # under load, not just after it) and duplicates must not exceed
        # gossip deliveries (redundancy ratio <= 1: the eager tree +
        # governor keep fan-out bounded while the crowd squeezes the
        # channels).  End-state coverage rides the app check
        # (bcast_coverage in `app`).
        from partisan_tpu import provenance as prov_mod

        snap = prov_mod.snapshot(st.provenance)
        lo, hi = start + g(q), start + g(q) + g(2 * q)
        mask = (snap["rounds"] >= lo) & (snap["rounds"] < hi)
        crowd_gossip = int(snap["gossip"][mask].sum())
        crowd_dup = int(snap["dup"][mask].sum())
        out["broadcast"] = {
            **prov_mod.redundancy(snap),
            "crowd_gossip": crowd_gossip,
            "crowd_dup": crowd_dup,
            "crowd_redundancy": (round(crowd_dup / crowd_gossip, 4)
                                 if crowd_gossip else None),
        }
        out["broadcast_ok"] = bool(crowd_gossip > 0
                                   and crowd_dup <= crowd_gossip)
    if hx:
        # Recovery gate: the GRAPH-health bits (one component, no
        # isolates, min degree — health.overlay_ok), judged over the
        # last few chunk snapshots: the storm heals at 6q and the gate
        # asks "did the overlay re-merge in the 2q recovery window".
        # The digest's coverage bit is not consulted — no broadcast is
        # scheduled on slot 0 in these scenarios, so it reads
        # incomplete by construction.
        from partisan_tpu import health as health_mod

        tail = [row["digest"] for row in res.chunks[-3:]
                if "digest" in row]
        out["overlay_ok"] = bool(any(
            health_mod.overlay_ok(d) for d in tail))
    if adaptive:
        from partisan_tpu import control as control_mod

        out["control"] = control_mod.poll(st.control)
    return out


def _slo_window_count(chunks, bound: int) -> int:
    """Breach windows in a soak's chunk rows (the same maximal-run
    definition telemetry.replay_traffic_events emits events for)."""
    from partisan_tpu import telemetry as telemetry_mod

    bus = telemetry_mod.Bus()
    counter = {"n": 0}
    bus.attach("w", telemetry_mod.TRAFFIC_SLO_BREACH_WINDOW,
               lambda *_a: counter.__setitem__("n", counter["n"] + 1))
    telemetry_mod.replay_traffic_events(bus, chunks, slo_rounds=bound,
                                        crowd_x1000=2 ** 31 - 1)
    return counter["n"]


def traffic_slo(scale: float = 1.0, bound: int = TRAFFIC_SLO_BOUND) -> dict:
    """The multi-scenario SLO suite (the committed TRAFFIC_SLO.json):
    every app model under the adversarial traffic plane with the
    controllers ON, plus controllers-off reference arms for the A/B
    models.  Deterministic seeds throughout — the artifact reproduces
    bit-for-bit from ``scenarios.py --slo``.

    Verdicts:
    - per scenario: control channels' p99 within ``bound`` +
      conservation + overlay recovery + the app's own guarantee,
    - ``isolation``: some static arm shows the bulk channel degraded
      past the bound while its control channels held — the ATC'19
      head-of-line-isolation demonstration,
    - ``wins``: adaptive bulk p99 strictly better than static on the
      A/B models (the controller-interplay answer from PR 9)."""
    out: dict = {"bound": bound, "scale": scale, "scenarios": {}}
    wins = 0
    isolation = 0
    all_ok = True
    for name in TRAFFIC_MODELS:
        base_n = 64 if name in ("p2p_chat", "causal_chat") else 48
        n = max(24, int(base_n * scale))
        rounds = max(80, int(240 * scale))
        entry: dict = {}
        adaptive = traffic_scenario(name, n=n, rounds=rounds,
                                    adaptive=True, bound=bound)
        entry["adaptive"] = adaptive
        ok = (adaptive["control_ok"] and adaptive["app_ok"]
              and adaptive["breaches"] == 0
              and adaptive.get("overlay_ok", True)
              and adaptive.get("broadcast_ok", True)
              and adaptive.get("ops", {}).get("ok", True))
        entry["ok"] = bool(ok)
        all_ok = all_ok and ok
        if name in TRAFFIC_AB_MODELS:
            static = traffic_scenario(name, n=n, rounds=rounds,
                                      adaptive=False, bound=bound)
            entry["static"] = static
            sb, ab = static["bulk_p99"], adaptive["bulk_p99"]
            win = (sb is not None and ab is not None and ab < sb)
            entry["win"] = bool(win)
            wins += int(win)
        # head-of-line isolation: some arm shows crowd windows where
        # bulk breached while every control channel held
        iso = max(entry.get("static", {}).get(
            "crowd_isolation_chunks", 0),
            adaptive["crowd_isolation_chunks"])
        if iso > 0:
            isolation += 1
            entry["isolation"] = True
        out["scenarios"][name] = entry
    out["wins"] = wins
    out["isolation_scenarios"] = isolation
    out["pass"] = bool(all_ok and wins >= 2 and isolation >= 1)
    return out


# ---------------------------------------------------------------------------

ALL = {
    1: config1_anti_entropy,
    2: config2_rumor,
    3: config3_plumtree_drop,
    4: config4_scamp_churn,
    5: config5_causal_crash,
    6: config6_echo,
    7: config7_soak,
    8: config8_overload,
    9: config9_elastic,
}

DEFAULT_SIZES = {1: 16, 2: 1000, 3: 10_000, 4: 10_000, 5: 100_000, 6: 2,
                 7: 10_000, 8: 96, 9: 8192}

# Scenarios excluded from run_all's default sweep (run them with
# --only/--soak/--slo/--elastic): the soak is hours of simulated time
# by design; the overload scenario is the backpressure controller's
# A/B harness and SLO-gate input, driven by --slo / --control-ab; the
# elastic scenario scales half->full->quarter mid-storm under a flash
# crowd through the soak engine (config 9).
OPT_IN = frozenset({7, 8, 9})


def run_all(scale: float = 1.0, only=None) -> list[dict]:
    out = []
    for i, fn in ALL.items():
        if only and i not in only:
            continue
        if not only and i in OPT_IN:
            continue
        if i == 6:
            out.append(fn(num_messages=max(50, int(1000 * scale))))
            continue
        if i == 7:
            out.append(fn(n=max(64, int(DEFAULT_SIZES[7] * scale)),
                          rounds=max(400, int(2000 * scale))))
            continue
        n = max(8, int(DEFAULT_SIZES[i] * scale))
        out.append(fn(n=n))
    return out


def _run_cli(args):
    import json

    if args.control_ab:
        print(json.dumps(control_ab(scale=args.scale)), flush=True)
        raise SystemExit(0)
    if args.slo is not None:
        n8 = max(48, int(DEFAULT_SIZES[8] * args.scale))
        static = config8_overload(n=n8, adaptive=False)
        adaptive = config8_overload(n=n8, adaptive=True)
        print(json.dumps({"kind": "overload_static", **static}),
              flush=True)
        print(json.dumps({"kind": "overload_adaptive", **adaptive}),
              flush=True)
        ok, rows = slo_gate(adaptive["p99"], args.slo)
        for row in rows:
            print(json.dumps(row), flush=True)
        print(json.dumps({"kind": "slo_verdict", "pass": ok,
                          "bound": args.slo}), flush=True)
        # the traffic-plane multi-scenario suite (ROADMAP item 3): one
        # verdict line per scenario, then the committed-artifact object
        suite = traffic_slo(scale=args.scale, bound=args.slo)
        for name, entry in suite["scenarios"].items():
            line = {"kind": "traffic_slo_scenario", "model": name,
                    "ok": entry["ok"],
                    "isolation": entry.get("isolation", False)}
            if "win" in entry:
                line["win"] = entry["win"]
                line["bulk_p99_static"] = entry["static"]["bulk_p99"]
                line["bulk_p99_adaptive"] = \
                    entry["adaptive"]["bulk_p99"]
            print(json.dumps(line), flush=True)
        print(json.dumps({"kind": "traffic_slo", **suite}), flush=True)
        if args.slo_out:
            with open(args.slo_out, "w") as f:
                json.dump(suite, f, indent=1)
        raise SystemExit(0 if (ok and suite["pass"]) else 1)
    if args.elastic:
        out9 = config9_elastic(
            n=max(64, int(DEFAULT_SIZES[9] * args.scale)),
            ingress_trace=args.ingress_trace,
            ckpt_dir=args.ckpt_dir)
        print(json.dumps(out9), flush=True)
        raise SystemExit(0 if out9["pass"] else 1)
    if args.soak:
        out7 = config7_soak(
            n=max(64, int(DEFAULT_SIZES[7] * args.scale)),
            rounds=args.soak_rounds, ckpt_dir=args.ckpt_dir,
            superstep=args.superstep, pipeline=args.pipeline)
        print(json.dumps(out7), flush=True)
        if not out7.get("ops", {}).get("ok", True):
            raise SystemExit(1)
    else:
        for r in run_all(scale=args.scale, only=args.only):
            print(json.dumps(r), flush=True)


if __name__ == "__main__":
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--only", type=int, nargs="*", default=None)
    ap.add_argument("--metrics", action="store_true",
                    help="run with the device-resident metrics ring on "
                         "and emit per-round series to stderr as JSON "
                         "lines (stdout is unchanged)")
    ap.add_argument("--latency", action="store_true",
                    help="run with the device-resident latency plane on "
                         "and emit per-channel delivery-age percentiles "
                         "to stderr as JSON lines (stdout is unchanged)")
    ap.add_argument("--health", action="store_true",
                    help="run with the device-resident health plane on "
                         "(topology snapshots every K_PROG rounds; "
                         "convergence polls the one-scalar digest) and "
                         "emit the snapshot series to stderr as JSON "
                         "lines (stdout is unchanged)")
    ap.add_argument("--provenance", action="store_true",
                    help="run with the device-resident provenance plane "
                         "on (dissemination forest + redundancy rings "
                         "in the scan carry) and emit redundancy ratio "
                         "/ tree depth / coverage round to stderr as "
                         "JSON lines (stdout is unchanged)")
    ap.add_argument("--soak", action="store_true",
                    help="run the long-horizon soak scenario (config 7) "
                         "only: a repeating fault storm driven through "
                         "the chunked soak engine — bounded executions, "
                         "checkpoints at chunk boundaries, crash "
                         "retry/restore, health digest per chunk "
                         "(equivalent to --only 7)")
    ap.add_argument("--soak-rounds", type=int, default=2000,
                    help="soak horizon in rounds (with --soak)")
    ap.add_argument("--superstep", type=int, default=1, metavar="R",
                    help="with --soak: fuse R rounds per scan step "
                         "(Config.superstep; the engine's census-"
                         "guarded cap lift engages)")
    ap.add_argument("--pipeline", type=int, default=1, metavar="D",
                    help="with --soak: keep up to D chunk executions "
                         "in flight between checkpoint/storm "
                         "boundaries (SoakConfig.pipeline_depth)")
    ap.add_argument("--elastic", action="store_true",
                    help="run the runtime-elasticity scenario (config "
                         "9) only: scale half->full->quarter mid-storm "
                         "under flash-crowd traffic through the "
                         "chunked soak engine — conservation + overlay "
                         "recovery + per-channel p99 gates; exit "
                         "non-zero if any gate breaches")
    ap.add_argument("--ingress-trace", default=None, metavar="PATH",
                    help="with --elastic: replay a recorded external-"
                         "arrival trace (ingress.Journal JSON lines) "
                         "through the host→device inject ring "
                         "alongside the in-scan traffic")
    ap.add_argument("--ckpt-dir", default=None,
                    help="persist soak checkpoints here (atomic, "
                         "fingerprinted; with --soak)")
    ap.add_argument("--slo", type=int, nargs="?", const=4, default=None,
                    metavar="P99_ROUNDS",
                    help="per-channel p99 SLO suite (default bound 4 "
                         "rounds): run the bulk-traffic overload "
                         "scenario (config 8, the backpressure A/B "
                         "harness) AND the traffic-plane multi-"
                         "scenario suite (traffic_slo: every app model "
                         "under flash crowds / stragglers / churn / "
                         "one-way cuts / partitions, controllers-off "
                         "vs -on) — print per-channel and per-scenario "
                         "verdict lines plus the TRAFFIC_SLO object, "
                         "and exit non-zero if any gate breaches")
    ap.add_argument("--slo-out", default=None, metavar="PATH",
                    help="also write the traffic_slo object (the "
                         "committed TRAFFIC_SLO.json) to PATH")
    ap.add_argument("--control-ab", action="store_true",
                    help="run the three in-scan controllers' A/B "
                         "evidence scenarios (fanout redundancy, "
                         "backpressure p99, healing rounds-to-heal, "
                         "calm no-regression) and print the comparison "
                         "object (the committed CONTROL_AB.json)")
    ap.add_argument("--ops", action="store_true",
                    help="fuse each soak-engine run (configs 7/9, the "
                         "traffic suite) into the unified ops journal "
                         "(opslog.py), print the matched detect->"
                         "react->recover incident spans + error "
                         "budgets + gate verdict to stderr as JSON "
                         "lines, and fold the span gate into the "
                         "scenario's pass verdict / exit status")
    ap.add_argument("--ops-out", default=None, metavar="PATH",
                    help="with --ops: also write the journal artifact "
                         "(opslog JSON lines; the config label is "
                         "suffixed before the extension) for "
                         "tools/incident_report.py --gate")
    ap.add_argument("--perf", action="store_true",
                    help="capture a jax.profiler trace of the run and "
                         "emit the measured per-phase device-time "
                         "table (partisan_tpu/perfwatch.py attribution "
                         "over the round.* named_scopes — the cost "
                         "meter's phase keys) to stderr as JSON lines "
                         "(stdout is unchanged)")
    args = ap.parse_args()
    METRICS = METRICS or args.metrics
    LATENCY = LATENCY or args.latency
    HEALTH = HEALTH or args.health
    PROVENANCE = PROVENANCE or args.provenance
    OPS = OPS or args.ops
    OPS_OUT = OPS_OUT or args.ops_out
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/partisan_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    _perf_cm = _perf_dir = None
    if args.perf:
        import tempfile

        from partisan_tpu import perfwatch

        _perf_dir = tempfile.mkdtemp(prefix="ptpu_perf_")
        _perf_cm = perfwatch.capture(_perf_dir)
        _perf_cm.__enter__()
    try:
        _run_cli(args)
    finally:
        if _perf_cm is not None:
            import shutil
            import sys

            # close the profiler FIRST (this finally also runs on the
            # branches' SystemExit), then attribute the capture
            _perf_cm.__exit__(None, None, None)
            for _name, _slot in sorted(
                    perfwatch.attribute(_perf_dir).items()):
                print(json.dumps({"kind": "perf_phase", "phase": _name,
                                  **_slot}), file=sys.stderr, flush=True)
            shutil.rmtree(_perf_dir, ignore_errors=True)
