"""Protocol workload corpus — the reference's ``protocols/`` directory
(SURVEY.md §2 "Protocol corpus") rebuilt as vectorized models that run on
top of any manager: anti-entropy, rumor mongering, direct mail, broadcast
(plumtree-backed), primary-backup, 2PC/3PC."""

from partisan_tpu.models.base import Model  # noqa: F401
from partisan_tpu.models.anti_entropy import AntiEntropy  # noqa: F401
from partisan_tpu.models.plumtree import Plumtree  # noqa: F401
from partisan_tpu.models.direct_mail import DirectMail  # noqa: F401
from partisan_tpu.models.rumor_mongering import RumorMongering  # noqa: F401
from partisan_tpu.models.commit import CommitProtocol  # noqa: F401
from partisan_tpu.models.alsberg_day import AlsbergDay  # noqa: F401
