"""Benchmark: the north-star scenario (BASELINE.md) — large-scale
HyParView + Plumtree simulated on one TPU chip.

Scenario: n-node HyParView overlay (staggered batched bootstrap) with
Plumtree epidemic broadcast layered on top; validates broadcast
convergence, then measures steady-state simulated **gossip rounds/sec**.

``vs_baseline``: the reference is a LIVE system whose protocol timers
tick in wall-clock seconds — one simulated round == ``round_ms`` (1 s)
of virtual time, so a live cluster advances 1 round/sec by construction
and ``vs_baseline`` is the simulation speedup over real time.  (The
reference also cannot reach this scale at all: its HyParView is
documented "up-to 2,000 nodes",
partisan_hyparview_peer_service_manager.erl:59.)

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import jax
import numpy as np

# Persistent compile cache: the hyparview round's XLA compile dominates
# at large n; cache across bench invocations.
jax.config.update("jax_compilation_cache_dir", "/tmp/partisan_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

TIME_BUDGET_S = 400.0          # hard self-imposed wall budget
PER_SIZE_CAP_S = 280.0         # no single rung may eat the whole budget


def run(n: int, verbose: bool = False) -> dict:
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config
    from partisan_tpu.models.plumtree import Plumtree

    # Capacity knobs size the tensors to the workload (the relay-attached
    # TPU prices ops by bytes): one broadcast slot in use -> small
    # max_broadcasts / push_slots / lazy_cap; inbox_cap=16 measured at
    # identical convergence (58 rounds @4096, zero drops) and ~30% less
    # per-round traffic than 32.
    from partisan_tpu.config import PlumtreeConfig
    cfg = Config(n_nodes=n, seed=1, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups", max_broadcasts=8,
                 inbox_cap=16,
                 plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4))
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    st = cl.init()

    # Staggered bootstrap: wave w joins via a random already-joined node.
    rng = np.random.default_rng(7)
    base = 1
    while base < n:
        hi = min(base * 4, n)
        nodes = np.arange(base, hi, dtype=np.int32)
        targets = rng.integers(0, base, size=nodes.shape[0]).astype(np.int32)
        st = st._replace(manager=cl.manager.join_many(
            cfg, st.manager, nodes, targets))
        st = cl.steps(st, 3)
        base = hi
    st = cl.steps(st, 30)          # settle the overlay
    jax.block_until_ready(st)

    # Broadcast convergence (the correctness gate for the numbers).
    st = st._replace(model=model.broadcast(st.model, 0, 0, int(st.rnd)))
    st, conv = cl.run_until(
        st, lambda s: float(model.coverage(s.model, s.faults.alive, 0)) == 1.0,
        max_rounds=max(300, 2 * int(np.log2(n)) * 20), check_every=10)
    if conv < 0:
        raise AssertionError(f"n={n}: plumtree broadcast did not converge")

    # Steady-state throughput.  One program execution must stay well
    # under the runtime's per-execution wall limit (long scans of a
    # traffic-carrying round reproducibly fault around the minute mark),
    # so size the scan length from a WARM probe's measured per-round
    # cost to target ~15 s per program (the convergence phase would
    # over-estimate on a cold compile cache), then time a few.
    st = cl.steps(st, 25)
    jax.block_until_ready(st)
    t0 = time.perf_counter()
    st = cl.steps(st, 25)
    jax.block_until_ready(st)
    est_round = max((time.perf_counter() - t0) / 25, 1e-4)
    k = int(min(250, max(25, 15.0 / est_round)))
    st = cl.steps(st, k)           # warm the k-specialized program
    jax.block_until_ready(st)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        st = cl.steps(st, k)
        jax.block_until_ready(st)
        best = min(best, time.perf_counter() - t0)
    rps = k / best
    if verbose:
        print(f"n={n}: {rps:.1f} rounds/s, broadcast converged by round "
              f"{conv}", file=sys.stderr)
    return {"n": n, "rounds_per_sec": rps, "converged_round": conv}


def _run_one_subprocess(n: int, timeout_s: float) -> dict | None:
    """Run one ladder size in a FRESH interpreter: a TPU device error
    poisons the process context, so in-process retries always fail —
    subprocess isolation makes each attempt independent."""
    import subprocess

    cmd = [sys.executable, __file__, "--one", str(n)]
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout_s)
    except subprocess.TimeoutExpired as e:
        print(f"n={n}: timed out after {timeout_s:.0f}s", file=sys.stderr)
        for stream in (e.stderr, e.stdout):
            if stream:
                text = stream.decode() if isinstance(stream, bytes) else stream
                sys.stderr.write(text[-2000:])
        return None
    sys.stderr.write(out.stderr[-2000:])
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            d = json.loads(line)
            if isinstance(d, dict) and "rounds_per_sec" in d:
                return d
        except json.JSONDecodeError:
            continue
    return None


def main() -> None:
    # Size ladder: secure one safety rung, then jump straight to the
    # largest sizes the budget allows (intermediate rungs would eat the
    # budget a 32k+ run needs — measured: 32768 takes ~250 s end to
    # end, 100k clears compile in ~15 s but its traffic rounds put the
    # full run beyond this budget today).
    t_start = time.time()
    result = None
    for n in (4_096, 32_768, 100_000):
        elapsed = time.time() - t_start
        if result is not None and elapsed > TIME_BUDGET_S / 2:
            break
        got = None
        attempts = 1 if elapsed > TIME_BUDGET_S * 0.4 else 2
        for attempt in range(1, attempts + 1):
            remaining = TIME_BUDGET_S - (time.time() - t_start) - 10
            if remaining < 60 and result is not None:
                break
            got = _run_one_subprocess(
                n, timeout_s=max(60.0, min(PER_SIZE_CAP_S, remaining)))
            if got is not None:
                break
            print(f"n={n} attempt {attempt} produced no result",
                  file=sys.stderr)
        if got is None:
            break                # keep the prior size's result
        result = got
    if result is None:
        raise SystemExit("bench failed at every size")
    print(json.dumps({
        "metric": (f"simulated gossip rounds/sec "
                   f"({result['n']}-node hyparview+plumtree)"),
        "value": round(result["rounds_per_sec"], 2),
        "unit": "rounds/sec",
        # live system: 1 round == 1 s wall clock (round_ms = 1000)
        "vs_baseline": round(result["rounds_per_sec"], 2),
    }))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--one":
        print(json.dumps(run(int(sys.argv[2]), verbose=True)))
    else:
        main()
