"""HyParView partial-view overlay manager.

TPU rebuild of ``partisan_hyparview_peer_service_manager`` (reference
src/partisan_hyparview_peer_service_manager.erl, paper-faithful moduledoc
:20-215): each node keeps a small symmetric ACTIVE view (its overlay
links) and a larger PASSIVE view (healing candidates), maintained by

- JOIN / FORWARD_JOIN random walks with TTL = ARWL, depositing the
  joiner into passive views at TTL == PRWL (:1234, :1381),
- NEIGHBOR request/accept/reject with priority (high when isolated)
  promoting passive peers into the active view (:1619-1746),
- DISCONNECT demoting peers to passive (:1565),
- periodic SHUFFLE random walks exchanging view samples (:1750-1795),
- periodic random promotion when the active view is under-full (:1046),
- crash healing: dead active peers are pruned (the TCP-EXIT failure
  detector analogue, :1134-1186) and promotion refills the view.

Tensor mapping: views are fixed-width id arrays (ops/views.py); the
whole inbox is handled BATCHED — no per-slot ``lax.scan``, no
``lax.switch`` (the original per-slot design cost ~250 sequential
micro-kernels per round and walled the benchmark at 8k nodes; the
batched fold is the plumtree pattern, models/plumtree.py):

  1. removals from the active view (DISCONNECT sources, X-BOT swaps),
  2. one central ADMISSION (ops/views.admit): every inbox slot
     contributes at most one active-view candidate (JOIN / walk-end
     FORWARD_JOIN adoption / NEIGHBOR request / NEIGHBOR_ACCEPTED /
     X-BOT), admitted together under drop-random-if-full semantics,
  3. per-slot replies decided against the round-start view plus the
     admission outcome (accepted iff the edge is really in the new
     view — no one-way links), eviction DISCONNECTs from the
     admission's displaced-member list,
  4. one batched passive merge (ops/views.bucket_merge — the passive
     view is an id-keyed bucket cache) folding every passive-bound id
     (disconnect sources, walk deposits, shuffle samples, demotions,
     evictees) in one shot.

Within-round ordering between conflicting updates resolves as ONE
simultaneous transition (equivalent to some arbitrary mailbox
interleaving, which is all the reference's asynchrony guarantees — the
same stance as the plumtree fold).  Every handled message emits at most
1 reply; the one JOIN fan-out per node per round gets its own
A_MAX-slot block (excess JOINs are dropped — the joiner's retry loop
re-sends until an accept lands).  Random-walk hops advance one virtual
round per hop — the round→virtual-time calibration note in SURVEY.md §7
applies.

X-BOT overlay optimization (:1880-2050) is config-gated
(``HyParViewConfig.xbot``) with a synthetic latency oracle (the
reference pings over the wire, :2978-3000) and the FULL 4-party replace
handshake: initiator i (worst peer o) → candidate c; a full c delegates
to its worst peer d (REPLACE); d switches to o (SWITCH) so the swap
i-o, c-d → i-c, o-d preserves every node's degree — demoted peers are
re-homed explicitly, one chain hop per round.  Reserved slots
(reserve/1) hold active capacity back from ordinary admission.  Epochs
are transposed away: reference epochs disambiguate same-name node
re-incarnations (:249-256), but sim node ids ARE incarnation-stable
identities.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import Array

from partisan_tpu import distance as distance_mod
from partisan_tpu import types as T
from partisan_tpu.comm import LocalComm
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import msg as msg_ops
from partisan_tpu.ops import plane as plane_ops
from partisan_tpu.ops import rng, views

# Shuffle wire format: payload[0] = origin, payload[1:1+S] = ids, where
# S = shuffle_k_active + shuffle_k_passive (config-dependent).


def _shuffle_sample(cfg: Config) -> int:
    return cfg.hyparview.shuffle_k_active + cfg.hyparview.shuffle_k_passive

# RNG stream tags (ops/rng.py discipline: distinct per call site).  The
# per-slot range starts at 1000 so it can NEVER collide with the named
# tags below (inbox_cap is far below 700).
_TAG_SHUFFLE = 303
_TAG_PROMOTE = 304
_TAG_JOIN = 305
_TAG_XBOT = 306
_TAG_XBOT_COST = 307
_TAG_ADMIT = 308
_TAG_PMERGE = 309
_TAG_FJPICK = 310
_TAG_SHPICK = 311
_TAG_MINE = 312
_TAG_CANDSEL = 313
_TAG_JOINSLOT = 314
_TAG_SHSAMP_A = 315
_TAG_SHSAMP_P = 316
_TAG_SHTGT = 317
_TAG_PRTGT = 318
_TAG_XCAND = 319
_TAG_PSEL = 320
_TAG_REJOIN = 321
_TAG_HBSEED = 322
_TAG_HBJIT = 323
_TAG_DPROBE = 324
_TAG_HBFALL = 325
_TAG_FJWALK = 330     # in-round forward_join walk (hop index rides the
#                       rank32 element coordinate: h*A + slot)
_TAG_SHWALK = 340     # in-round shuffle walk (same hop-coordinate form)


def link_cost(seed: int, a, b):
    """Synthetic symmetric link-latency oracle for X-BOT.  The reference
    measures live RTTs (is_better/3 via net_adm:ping timing,
    partisan_hyparview_peer_service_manager.erl:2978-3000); the sim has
    no wire, so cost is a deterministic uniform hash per unordered pair
    — stable across rounds and placements, which is what the
    optimization needs to converge."""
    from partisan_tpu import faults as faults_mod

    lo = jnp.minimum(a, b)
    hi = jnp.maximum(a, b)
    return faults_mod.edge_hash(seed, jnp.int32(0), _TAG_XBOT_COST, lo, hi) \
        .astype(jnp.float32)


class HyParViewState(NamedTuple):
    active: Array       # int32[n_local, active_max]
    passive: Array      # int32[n_local, passive_max]
    join_target: Array  # int32[n_local] — pending scripted JOIN (-1 none)
    leaving: Array      # bool[n_local] — send disconnects THIS round
    left: Array         # bool[n_local] — has left: inert until rejoin
    reserved: Array     # int32[n_local] — active slots held back from
    #                     ordinary admission (reserve/1, reference
    #                     reserved-slot map :230-243); scripted joins
    #                     may still use them
    joined: Array       # bool[n_local] — has ever held an active edge;
    #                     gates auto_rejoin (a never-joined node must
    #                     stay inert until its scripted join)
    hb_epoch: Array     # int32[n_local] — received liveness epoch
    #                     (HyParViewConfig.heartbeat: scatter-max
    #                     propagation of node 0's epoch counter)
    hb_rnd: Array       # int32[n_local] — round the epoch last advanced
    #                     (or the node joined); staleness beyond the
    #                     isolation window triggers a discovery rejoin
    dist: Any = ()      # distance.DistanceState when the RTT metrics
    #                     plane is enabled (Config.distance.enabled) —
    #                     the reference keeps distance state in the
    #                     manager (:1355-1378)


class HyParView:
    name = "hyparview"

    # ------------------------------------------------------------------
    def init(self, cfg: Config, comm: LocalComm) -> HyParViewState:
        need = T.HDR_WORDS + 1 + _shuffle_sample(cfg)
        if cfg.msg_words < need:
            raise ValueError(
                f"hyparview needs msg_words >= {need} "
                f"(shuffle sample wire format), got {cfg.msg_words}")
        n = comm.n_local
        return HyParViewState(
            active=views.empty_batch(n, cfg.hyparview.active_max),
            passive=views.empty_batch(n, cfg.hyparview.passive_max),
            join_target=jnp.full((n,), -1, jnp.int32),
            leaving=jnp.zeros((n,), jnp.bool_),
            left=jnp.zeros((n,), jnp.bool_),
            reserved=jnp.zeros((n,), jnp.int32),
            joined=jnp.zeros((n,), jnp.bool_),
            hb_epoch=jnp.zeros((n,), jnp.int32),
            hb_rnd=jnp.zeros((n,), jnp.int32),
            dist=(distance_mod.init(cfg, comm)
                  if cfg.distance.enabled else ()),
        )

    # ------------------------------------------------------------------
    def step(self, cfg: Config, comm: LocalComm, state: HyParViewState,
             ctx: RoundCtx) -> tuple[HyParViewState, Array]:
        """One round.  The heavy protocol machinery (removals, central
        admission, replies, passive merge, walk fan-outs, cadenced
        sends) runs under ONE ``lax.cond`` gated on a global BUSY
        predicate — any HyParView control message in any inbox, any
        cadenced timer due, any pending scripted join/leave.  A quiet
        round (steady state between cadence ticks) pays only the
        prologue (failure-detector pruning), the liveness heartbeat and
        the epilogue — the round-cost lever measured in BENCH_NOTES r5.
        The predicate is a cross-shard ``allsum`` so every shard takes
        the same branch (the busy body contains collectives).

        Random walks (FORWARD_JOIN :1381, SHUFFLE :1750-1795) hop
        IN-ROUND over a gathered snapshot of the active views: the
        reference's TTL walk crosses ~ms TCP hops — sub-round at the
        1 s/round calibration — so walking within the round is the
        faithful wall-clock timing (the old one-hop-per-round walks
        stretched a 6-hop walk to 6 virtual seconds).  The walk
        endpoint gets the FORWARD_JOIN with TTL 0 (stop/adopt at the
        receiver, locally re-checked); the PRWL-hop node gets a
        deposit-marked copy (payload word 2) for its passive view."""
        hv = cfg.hyparview
        W = cfg.msg_words
        SAMPLE = _shuffle_sample(cfg)
        A = hv.active_max
        n_local = state.active.shape[0]
        gids = comm.local_ids()
        cap = ctx.inbox.data.shape[1]
        ph = cfg.timer_phase(gids)

        # Failure detector: prune crash-stopped AND left peers from active
        # views (connection EXIT -> on_down, reference :1489-1535: a left
        # node's closed socket looks the same as a crashed one's).  Passive
        # views shed them too — the reference discovers stale passive
        # entries when a promotion's connect fails and moves on to the
        # next candidate (:1619-1746); eager purging collapses that retry
        # loop into one round.
        reachable = ctx.faults.alive & ~comm.gather_vec(state.left)
        # The prune gathers reachable[id] per view slot — per-scalar
        # gather cost in both runtime and generated code on this
        # backend — but it is the IDENTITY while every node is
        # reachable, so it runs under a cond on "anyone unreachable".
        # The predicate reads replicated global state (alive and the
        # gathered left mask), so every shard takes the same branch
        # without a collective, and the branches contain none.
        unreach = jnp.any(~reachable)

        def prune(_):
            # ONE packed gather over the concatenated views instead of a
            # keep_only gather per view (reachable[id] is priced per
            # fetched scalar either way, but each gather is its own
            # dispatched op — the round-cost meter's coalescing rule).
            both = jnp.concatenate([state.active, state.passive], axis=1)
            ok = (both >= 0) & reachable[jnp.maximum(both, 0)]
            cleaned = jnp.where(ok, both, -1)
            A_ = state.active.shape[1]
            return cleaned[:, :A_], cleaned[:, A_:]

        active, passive_in = jax.lax.cond(
            unreach, prune, lambda _: (state.active, state.passive), 0)

        active0, passive0 = active, passive_in
        me2 = gids[:, None]                                   # [n, 1]
        asize0 = jnp.sum(active0 >= 0, axis=1)                # [n]
        acap = jnp.int32(A) - state.reserved                  # [n]
        join_tgt = state.join_target

        inb = ctx.inbox.data                                  # [n, cap, W]
        kind = inb[..., T.W_KIND]
        src = inb[..., T.W_SRC]
        ttl = inb[..., T.W_TTL]
        p0 = inb[..., T.P0]
        p1 = inb[..., T.P1]
        dep_w = inb[..., T.P2]      # FORWARD_JOIN deposit marker (walk)
        is_join = kind == T.MsgKind.HPV_JOIN
        is_fj = kind == T.MsgKind.HPV_FORWARD_JOIN
        is_nb = kind == T.MsgKind.HPV_NEIGHBOR
        is_acc = kind == T.MsgKind.HPV_NEIGHBOR_ACCEPTED
        is_disc = kind == T.MsgKind.HPV_DISCONNECT
        is_sh = kind == T.MsgKind.HPV_SHUFFLE
        is_shr = kind == T.MsgKind.HPV_SHUFFLE_REPLY
        is_xo = (kind == T.MsgKind.HPV_XBOT_OPT) if hv.xbot else \
            jnp.zeros_like(is_join)
        is_xr = (kind == T.MsgKind.HPV_XBOT_OPT_REPLY) if hv.xbot else \
            jnp.zeros_like(is_join)

        def slot_in(view, ids):
            """bool[n, cap]: ids[n, cap] present in view[n, K]."""
            return jnp.any((view[:, None, :] == ids[:, :, None])
                           & (ids >= 0)[:, :, None], axis=2)

        # Randomness on the hot path is counter-hash ranking
        # (ops/rng.rank32) — placement-invariant like the threefry
        # discipline, but a few elementwise passes instead of per-site
        # key trees + gumbel tables (the relay-attached TPU prices every
        # op by bytes moved; see ARCHITECTURE.md performance note).
        slot_col = jnp.arange(cap, dtype=jnp.int32)[None, :]

        def ranked(tag, *coords):
            # ctx.seed, not cfg.seed: the salted per-run stream
            # (fleet members must draw independently — managers/base.py)
            return rng.rank32(ctx.seed, ctx.rnd, tag, *coords)

        def row_ranked(view, tag, k, exclude=None):
            """int32[n, k]: k distinct random members per row of
            view[n, K] (-1 padded), optionally excluding [n, E] ids."""
            r = ranked(tag, gids[:, None],
                       jnp.arange(view.shape[1])[None, :])
            okv = view >= 0
            if exclude is not None:
                okv &= ~jnp.any(view[:, :, None] == exclude[:, None, :],
                                axis=2)
            sc = jnp.where(okv, r | jnp.uint32(1), jnp.uint32(0))
            vals, t = jax.lax.top_k(sc, k)
            got = jnp.take_along_axis(view, t, axis=1)
            return jnp.where(vals > 0, got, -1)

        def compact(ids2d, score2d, k):
            """Select up to k valid entries of ids2d[n, cap] by
            descending score (int32[n, cap] >= 0; 0 = invalid), as ONE
            top_k.  (The previous k mask-and-argmax passes optimized
            bytes, but the relay runtime's round cost is per-op
            dispatch — see BENCH_NOTES.md profile — and 2k reduction
            passes lose to one fused sort at cap=16.)
            Returns (ids int32[n, k], picked_col int32[n, k])."""
            v, b = jax.lax.top_k(score2d, k)
            got = jnp.take_along_axis(ids2d, b, axis=1)
            ids = jnp.where(v > 0, got, -1)
            col = jnp.where(v > 0, b.astype(jnp.int32), -1)
            return ids, col

        # X-BOT latency oracle: the synthetic per-pair hash by default;
        # with the distance plane's xbot_oracle, MEASURED RTTs from the
        # round-start cache (modeled expectation for unprobed peers —
        # the reference's is_better pings on demand, :2978-3000).
        use_measured = (hv.xbot and cfg.distance.enabled
                        and cfg.distance.xbot_oracle)

        def cost(a2, b2):
            if not use_measured:
                return link_cost(cfg.seed, a2, b2)
            b_arr = jnp.asarray(b2)
            if b_arr.ndim == 1:
                return distance_mod.measured_or_modeled(
                    cfg, state.dist, jnp.reshape(a2, (-1, 1)),
                    b_arr[:, None])[:, 0]
            return distance_mod.measured_or_modeled(cfg, state.dist, a2,
                                                    b_arr)

        # ---- timer fire masks + the global BUSY predicates -----------
        # Two independent gates: message/join processing (admission,
        # replies, passive merge) runs only when control traffic or a
        # pending scripted join/leave exists anywhere; the cadenced
        # sends (shuffle walk, promotion, X-BOT probes) only on their
        # fire rounds.  Between cadence ticks of a settled overlay BOTH
        # skip, and during a broadcast's dissemination (no membership
        # churn) the manager stays almost entirely quiet.
        # All cadenced timers are alive-gated: a crash-stopped (or
        # width-operand-inactive) node must not flip a round cad-busy —
        # its emissions would be killed by the live mask below anyway,
        # but the cad body's view-snapshot gather + walks would still
        # run, and the dead-slot payload residue would break the
        # width-operand trace-parity contract (an inactive row firing
        # pr_fire made rounds busy that a native-width run leaves
        # quiet).
        # Self-healing escalation (control.py): while the health digest
        # reports a degraded overlay the repair cadences run at
        # interval >> boost — probe/promotion rates escalate exactly
        # while partitioned and relax once healed (the reference's
        # fixed wall-clock timers, made a feedback operand).
        sh_every = jnp.int32(cfg.shuffle_every)
        pr_every = jnp.int32(cfg.promotion_every)
        if cfg.control.healing:
            with jax.named_scope("round.control.healing"):
                boost = ctx.control.healing.boost
                sh_every = jnp.maximum(sh_every >> boost, 1)
                pr_every = jnp.maximum(pr_every >> boost, 1)
        sh_fire = ((ctx.rnd + ph) % sh_every == 0) \
            & (asize0 > 0) & ctx.alive
        # Random promotion stays PER-NODE STAGGERED even under aligned
        # timers: it is the view-healing path broadcast stragglers
        # depend on, and aligning it measured +18 convergence rounds at
        # 16k (a straggler waits out the whole promotion interval).  It
        # only fires for under-full nodes, so a settled overlay still
        # reaches the quiet path every non-shuffle round.
        pr_fire = ((ctx.rnd + gids) % pr_every == 0) & \
            (asize0 < hv.active_min) & ctx.alive
        if hv.xbot:
            x_timer = ((ctx.rnd + ph) % cfg.xbot_every == 0) \
                & (asize0 >= acap) & (acap > 0) & ctx.alive
        # built from the SAME masks the handlers consume, so the gate
        # can never fall out of sync with a new control kind
        is_ctl = (is_join | is_fj | is_nb | is_acc | is_disc | is_sh
                  | is_shr | is_xo | is_xr
                  | (kind == T.MsgKind.HPV_NEIGHBOR_REJECTED))
        if hv.xbot:
            is_ctl = is_ctl | (
                (kind >= T.MsgKind.HPV_XBOT_REPLACE)
                & (kind <= T.MsgKind.HPV_XBOT_REPLACE_REPLY))
        msg_busy_l = (jnp.any(is_ctl) | jnp.any(join_tgt >= 0)
                      | jnp.any(state.leaving))
        busy = comm.allsum(msg_busy_l.astype(jnp.int32)) > 0
        cad_l = jnp.any(sh_fire) | jnp.any(pr_fire)
        if hv.xbot:
            cad_l = cad_l | jnp.any(x_timer)
        cad_busy = comm.allsum(cad_l.astype(jnp.int32)) > 0

        # Per-block emission widths: step hands back a TUPLE of blocks
        # (plane_ops.blocks_of) so round_body concatenates the emission
        # stack exactly once — the busy/cad bodies and their quiet
        # twins must agree on this structure for the lax.cond.
        BUSY_SHAPES = [cap, A, A, A, A, 1, 1] + ([cap] if hv.xbot else [])
        CAD_SHAPES = [1, 1] + ([1] if hv.xbot else [])

        def quiet_body(_):
            return (active0, passive0,
                    tuple(msg_ops.zero_stack(cfg, (n_local, k))
                          for k in BUSY_SHAPES))

        def busy_body(_):
            in_active0 = slot_in(active0, src)                 # [n, cap]
            # ---- 1. removals -----------------------------------------
            disc_src = jnp.where(is_disc, src, -1)
            removed = jnp.any(
                (active0[:, :, None] == disc_src[:, None, :])
                & (active0 >= 0)[:, :, None], axis=2)          # [n, A]
            if hv.xbot:
                p2w = inb[..., T.P2]
                p3w = inb[..., T.P3]
                p4w = inb[..., T.P3 + 1]
                is_xrep = kind == T.MsgKind.HPV_XBOT_REPLACE       # at d
                is_xsw = kind == T.MsgKind.HPV_XBOT_SWITCH         # at o
                is_xswr = kind == T.MsgKind.HPV_XBOT_SWITCH_REPLY  # at d
                is_xrepr = kind == T.MsgKind.HPV_XBOT_REPLACE_REPLY
                costs0 = jnp.where(
                    active0 >= 0,
                    cost(jnp.broadcast_to(me2, active0.shape),
                         jnp.maximum(active0, 0)), -jnp.inf)
                zslot = jnp.argmax(costs0, axis=1)
                z = jnp.where(jnp.any(active0 >= 0, axis=1),
                              jnp.take_along_axis(
                                  active0, zslot[:, None], axis=1)[:, 0],
                              -1)
                have_room = (asize0 < acap) & (acap > 0)
                # candidate side (OPT at c): room -> take the initiator
                # now; full -> delegate to worst peer d via REPLACE
                xo_take = is_xo & have_room[:, None] & ~in_active0
                xo_dup = is_xo & in_active0
                xo_full = is_xo & ~have_room[:, None] & ~in_active0 \
                    & (z >= 0)[:, None]
                # d side (REPLACE): switch to o only if o beats c for ME
                xrep_sw = is_xrep & (p0 >= 0) \
                    & (cost(me2, jnp.maximum(p0, 0))
                       < cost(me2, jnp.maximum(p2w, 0)))
                xrep_no = is_xrep & ~xrep_sw
                # o side (SWITCH): accept iff the initiator is ours
                xsw_acc = is_xsw & slot_in(active0, p1)
                # d side (SWITCH_REPLY) / c side (REPLACE_REPLY)
                xswr_ok = is_xswr & (p4w == 1)
                xrepr_ok = is_xrepr & (p4w == 1)
                # i side (OPT_REPLY): swap out o once c committed
                ok_xr = is_xr & (p1 == 1)
                swap_xr = ok_xr & slot_in(active0, p0)         # [n, cap]
                # Demotions: o at i, i at o, c at d, d at c.
                xrm = jnp.select([swap_xr, xsw_acc, xswr_ok, xrepr_ok],
                                 [p0, p1, p2w, p3w], -1)
                removed |= jnp.any(
                    (active0[:, :, None] == xrm[:, None, :])
                    & (active0 >= 0)[:, :, None] & (xrm >= 0)[:, None, :],
                    axis=2)
            active1 = jnp.where(removed, -1, active0)

            # ---- 2. per-kind slot decisions (round-start views) ------
            # forward_join (reference :1381): payload [joiner, contact,
            # deposit?].  The walk already ran in-round at the contact;
            # a deposit-marked copy feeds the passive view, any other
            # FORWARD_JOIN is a walk endpoint -> stop/adopt (re-checked
            # locally: the walk used a snapshot).
            fjj = p0
            j_in_act = slot_in(active0, fjj)
            is_dep = is_fj & (dep_w == 1)
            stop_ok = is_fj & ~is_dep & (fjj != me2) & ~j_in_act
            deposit = is_dep & (fjj != me2)

            # join admission: one fresh JOIN per round fans out; the
            # rest are dropped (the joiner's per-round retry re-sends)
            fresh = is_join & ~in_active0
            slot_idx = jnp.arange(cap)[None, :]
            first_slot = jnp.argmin(jnp.where(fresh, slot_idx, cap),
                                    axis=1)
            has_fresh = jnp.any(fresh, axis=1)
            first = fresh & (slot_idx == first_slot[:, None])

            # neighbor request (:1619-1746)
            want_nb = is_nb & ((p0 == 1) | (asize0 < acap)[:, None])

            # shuffle (:1750-1795): payload [origin, ids...] — always
            # integrate+reply (the walk happened in-round at the origin)
            origin = p0
            sh_ids = inb[..., T.P1:T.P1 + SAMPLE]              # [n, cap, S]
            sh_int = is_sh

            # ---- 3. scripted-join pre-insert + central admission -----
            # The scripted join bypasses admission entirely (reference
            # reserve/1 holds slots for orchestrated joins, and the old
            # sequential path used a full-width views.add): first empty
            # slot, else a hash-random occupant is displaced — ordinary
            # inbox candidates below still compete only for acap.
            inview_j = jnp.any((active1 == join_tgt[:, None])
                               & (join_tgt >= 0)[:, None], axis=1)
            has_empty = jnp.any(active1 < 0, axis=1)
            first_empty = jnp.argmax(active1 < 0, axis=1)
            rslot = (ranked(_TAG_JOINSLOT, gids) % jnp.uint32(A)) \
                .astype(jnp.int32)
            slot_j = jnp.where(has_empty, first_empty, rslot)
            do_pre = (join_tgt >= 0) & ~inview_j & (join_tgt != gids)
            occupant = jnp.take_along_axis(
                active1, slot_j[:, None], axis=1)[:, 0]
            evicted_j = jnp.where(do_pre & ~has_empty, occupant, -1)
            oh_j = jnp.arange(A)[None, :] == slot_j[:, None]
            active1 = jnp.where(do_pre[:, None] & oh_j,
                                join_tgt[:, None], active1)

            # Ordinary candidates: one per inbox slot, compacted to a
            # small fixed width (excess candidates lose this round and
            # their senders retry — bounded intake, like every other
            # capacity in the tensor transport).
            cand_slot = jnp.select(
                [first, stop_ok, want_nb, is_acc]
                + ([xo_take, ok_xr, xsw_acc, xswr_ok, xrepr_ok]
                   if hv.xbot else []),
                [src, fjj, src, src]
                + ([src, src, p3w, p0, p1] if hv.xbot else []),
                -1)                                            # [n, cap]
            # Confirmations rank above requests: an ACCEPTED peer has
            # already committed its side, and each X-BOT chain step has
            # already demoted an edge for its candidate (phase 1) —
            # losing either to a mere request would strand a
            # one-way/teardown.
            commit_prio = is_acc | (
                (xo_take | ok_xr | xsw_acc | xswr_ok | xrepr_ok)
                if hv.xbot else jnp.zeros_like(is_acc))
            prio_slot = jnp.where(commit_prio, 2, 1)
            CAND = min(A, cap)
            # Built int32-non-negative: prio(<=2)<<28 + 28 hash bits +
            # the validity bit stay under 2^31.  (lax.top_k orders
            # uint32 correctly on this backend too — row_ranked/
            # views.admit rely on that; the int32 form here just
            # doesn't need to.)
            csc = jnp.where(
                cand_slot >= 0,
                (prio_slot << 28)
                | (ranked(_TAG_CANDSEL, gids[:, None], slot_col)
                   >> jnp.uint32(4)).astype(jnp.int32)
                | 1,
                0)
            cands, cand_col = compact(cand_slot, csc, CAND)    # [n, CAND]
            prios = jnp.where(
                cand_col >= 0,
                jnp.take_along_axis(prio_slot, jnp.maximum(cand_col, 0),
                                    axis=1), 0)
            adscores = ranked(_TAG_ADMIT, gids[:, None],
                              jnp.arange(A + CAND)[None, :])
            new_active, _admitted, evicted = jax.vmap(views.admit)(
                active1, cands, prios, adscores, acap)

            in_new = slot_in(new_active, src)                  # [n, cap]
            j_in_new = slot_in(new_active, fjj)

            # ---- 4. per-slot replies ---------------------------------
            # ONE shuffle is answered per node per round (bounded
            # intake — excess shuffles' ids still can't be integrated
            # beyond the passive merge budget below, and the origin's
            # own outgoing sample already carried our ids the other
            # way; a missed reply just thins one round's sample).  This
            # keeps the passive-sample table [n, SAMPLE] instead of
            # [n, cap, passive_max].
            sh_slot = jnp.argmax(sh_int, axis=1)               # first hit
            sh_any = jnp.any(sh_int, axis=1)
            shr_slot = jnp.argmax(is_shr, axis=1)
            shr_any = jnp.any(is_shr, axis=1)
            origin1 = jnp.take_along_axis(origin, sh_slot[:, None],
                                          axis=1)[:, 0]
            # ONE dtype-grouped take serves BOTH sample reads — the
            # integrated shuffle's ids here and the shuffle-reply's ids
            # in the passive merge below (previously 2 x S per-plane
            # gathers, the manager's largest gather-eqn block).
            both_ids = plane_ops.stack_words(plane_ops.take_along(
                sh_ids, jnp.stack([sh_slot, shr_slot], axis=1),
                axis=1))                                       # [n, 2, S]
            ids1 = both_ids[:, 0]                              # [n, S]
            shr_ids1 = both_ids[:, 1]
            mine1 = row_ranked(passive0, _TAG_MINE, SAMPLE)    # [n, S]
            shreply_msgs = msg_ops.build(
                cfg, T.MsgKind.HPV_SHUFFLE_REPLY, gids,
                jnp.where(sh_any & (origin1 != gids) & (origin1 >= 0),
                          origin1, -1),
                payload=(gids, *jnp.unstack(mine1, axis=1)))

            m_acc_join = is_join & in_new    # JOIN confirmed (edge exists)
            m_acc_fj = stop_ok & j_in_new    # walk-end adoption confirmed
            m_nb_acc = is_nb & in_new
            m_nb_rej = is_nb & ~in_new
            m_acc_fix = is_acc & ~in_new     # accept we could NOT honor:
            #                                  tear down the half-open
            #                                  edge instead of keeping a
            #                                  silent one-way link
            if hv.xbot:
                # an XBOT candidate that committed its accept but lost
                # the central admission must also be torn down (same
                # one-way-link reasoning as m_acc_fix)
                xr_fix = ok_xr & ~in_new
                i_in_new = slot_in(new_active, p1)
                o_in_new = slot_in(new_active, p0)
                d_in_new = slot_in(new_active, p3w)
                xo_acc = xo_take | xo_dup  # reply OPT_REPLY (flag below)
                xbot_conds = [xo_acc, xo_full, xrep_sw, xrep_no,
                              is_xsw, is_xswr, is_xrepr, xr_fix]
                xbot_kinds = [jnp.int32(T.MsgKind.HPV_XBOT_OPT_REPLY),
                              jnp.int32(T.MsgKind.HPV_XBOT_REPLACE),
                              jnp.int32(T.MsgKind.HPV_XBOT_SWITCH),
                              jnp.int32(T.MsgKind.HPV_XBOT_REPLACE_REPLY),
                              jnp.int32(T.MsgKind.HPV_XBOT_SWITCH_REPLY),
                              jnp.int32(T.MsgKind.HPV_XBOT_REPLACE_REPLY),
                              jnp.int32(T.MsgKind.HPV_XBOT_OPT_REPLY),
                              jnp.int32(T.MsgKind.HPV_DISCONNECT)]
                xbot_dsts = [src,
                             jnp.broadcast_to(z[:, None], src.shape),
                             p0, src, src, p2w, p1, src]

            rkind = jnp.select(
                [m_acc_join, m_acc_fj, m_nb_acc, m_nb_rej, m_acc_fix]
                + (xbot_conds if hv.xbot else []),
                [jnp.int32(T.MsgKind.HPV_NEIGHBOR_ACCEPTED)] * 2
                + [jnp.int32(T.MsgKind.HPV_NEIGHBOR_ACCEPTED),
                   jnp.int32(T.MsgKind.HPV_NEIGHBOR_REJECTED),
                   jnp.int32(T.MsgKind.HPV_DISCONNECT)]
                + (xbot_kinds if hv.xbot else []),
                0)
            rdst = jnp.select(
                [m_acc_fj] + (xbot_conds[:-1] if hv.xbot else []),
                [fjj] + (xbot_dsts[:-1] if hv.xbot else []),
                src)
            rdst = jnp.where(rkind > 0, rdst, -1)
            # Payload word 0: ACCEPTED carries the JOIN's contact (the
            # node the joiner addressed) so a pending scripted join is
            # confirmed only by ITS contact's walk — a coincidental
            # promotion accept can no longer cancel a join whose walk
            # was actually lost.
            w0 = jnp.select(
                [m_acc_join, m_acc_fj, m_nb_acc | m_nb_rej | m_acc_fix],
                [jnp.broadcast_to(me2, p0.shape), p1,
                 jnp.full_like(p0, -1)],
                p0)
            payload = [w0]
            for wi in range(1, W - T.HDR_WORDS):
                base = inb[..., T.HDR_WORDS + wi]
                if hv.xbot and wi == 1:
                    # P1: accepted flag on OPT_REPLY replies; the
                    # initiator id on a delegated REPLACE; i otherwise
                    # (chain pass-through).
                    base = jnp.where(
                        xo_acc, in_new.astype(jnp.int32), base)
                    base = jnp.where(xo_full, src, base)
                    base = jnp.where(
                        is_xrepr,
                        (xrepr_ok & i_in_new).astype(jnp.int32), base)
                if hv.xbot and wi == 2:
                    base = jnp.where(
                        xo_full, jnp.broadcast_to(me2, base.shape), base)
                if hv.xbot and wi == 3:
                    base = jnp.where(
                        xo_full,
                        jnp.broadcast_to(z[:, None], base.shape), base)
                if hv.xbot and wi == 4:
                    # P4: the chain's commit flag
                    base = jnp.where(
                        is_xsw, (xsw_acc & d_in_new).astype(jnp.int32),
                        base)
                    base = jnp.where(
                        is_xswr, (xswr_ok & o_in_new).astype(jnp.int32),
                        base)
                    base = jnp.where(xrep_no, 0, base)
                payload.append(base)
            replies = msg_ops.build(
                cfg, rkind, jnp.broadcast_to(me2, rdst.shape), rdst,
                payload=tuple(payload))                        # [n, cap, W]

            # eviction + demotion disconnects (slot-aligned [n, A])
            ev_disc = msg_ops.build(
                cfg, T.MsgKind.HPV_DISCONNECT,
                jnp.broadcast_to(me2, evicted.shape), evicted)
            if hv.xbot:
                # tear down the demoted side of each chain step: o at
                # i, i at o, c at d, d at c (the swap's disconnects)
                xdst = jnp.select(
                    [swap_xr, xsw_acc, xswr_ok, xrepr_ok],
                    [p0, p1, p2w, p3w], -1)
                x_disc = msg_ops.build(
                    cfg, T.MsgKind.HPV_DISCONNECT,
                    jnp.broadcast_to(me2, xdst.shape), xdst)

            # ---- 5. join fan-out: IN-ROUND walks (reference :1381) ---
            # The contact fans one FORWARD_JOIN per active member and
            # walks each copy ARWL hops over the gathered view snapshot
            # NOW (see step docstring); the endpoint gets the stop copy,
            # the PRWL-hop node a deposit copy.
            joiner = jnp.where(
                has_fresh,
                jnp.take_along_axis(src, first_slot[:, None],
                                    axis=1)[:, 0], -1)
            fj_tgt = jnp.where(
                (active0 >= 0) & (active0 != joiner[:, None])
                & (joiner >= 0)[:, None], active0, -1)
            me2b = jnp.broadcast_to(me2, fj_tgt.shape)
            arangeA = jnp.arange(A, dtype=jnp.int32)
            # the walk (and its view-snapshot gather) only runs when a
            # fresh JOIN exists anywhere — a further sub-gate inside
            # the message body (joins are bootstrap-time traffic)
            fj_go = comm.allsum(
                jnp.any(has_fresh).astype(jnp.int32)) > 0

            def fj_walk(_):
                glob_act = comm.gather_vec(active0)        # [n_glob, A]
                glob_asz = comm.gather_vec(asize0)         # [n_glob]
                jb = jnp.broadcast_to(joiner[:, None], fj_tgt.shape)

                # One fori_loop hop body instead of an arwl-times
                # unrolled trace: the walk's [n, A, A] gather + rank +
                # argmax is the largest single block of the round
                # program, and unrolling it 6x made the serialized
                # 100k executable (and its per-process persistent-cache
                # load, which dominates warm bootstrap) ~2x bigger.
                # The hop index rides the rank32 ELEMENT coordinate
                # (h*A + slot) instead of a per-hop tag — same
                # independence guarantees, loop-carried tag.
                def hop(h, carry):
                    curf, prevf, stopped, endpoint, depnode = carry
                    cc = jnp.clip(curf, 0, comm.n_global - 1)
                    vc = glob_act[cc]                      # [n, A, A]
                    j_in = jnp.any((vc == jb[:, :, None]) & (vc >= 0),
                                   axis=2)
                    small = glob_asz[cc] <= 1
                    r = ranked(_TAG_FJWALK, gids[:, None, None],
                               arangeA[None, :, None],
                               h * A + arangeA[None, None, :])
                    okm = (vc >= 0) & (vc != jb[:, :, None]) \
                        & (vc != prevf[:, :, None]) \
                        & (vc != curf[:, :, None])
                    sc = jnp.where(okm, r | jnp.uint32(1), jnp.uint32(0))
                    bi = jnp.argmax(sc, axis=2)
                    nxt = jnp.take_along_axis(vc, bi[:, :, None],
                                              axis=2)[:, :, 0]
                    has_nxt = jnp.max(sc, axis=2) > 0
                    live_w = (curf >= 0) & ~stopped
                    stop_here = live_w & (small | j_in | ~has_nxt)
                    endpoint = jnp.where(stop_here, curf, endpoint)
                    # deposit at the receiver whose incoming TTL would
                    # have been PRWL, iff the walk continues
                    dep_h = h == hv.arwl - hv.prwl
                    depnode = jnp.where(dep_h & live_w & ~stop_here,
                                        curf, depnode)
                    stopped = stopped | stop_here
                    prevf = jnp.where(live_w & ~stop_here, curf, prevf)
                    curf = jnp.where(live_w & ~stop_here, nxt, curf)
                    return curf, prevf, stopped, endpoint, depnode

                curf, _prevf, stopped, endpoint, depnode = \
                    jax.lax.fori_loop(
                        0, hv.arwl, hop,
                        (fj_tgt, me2b, fj_tgt < 0,
                         jnp.full_like(fj_tgt, -1),
                         jnp.full_like(fj_tgt, -1)))
                endpoint = jnp.where(stopped, endpoint, curf)  # TTL out
                jb2 = jnp.broadcast_to(joiner[:, None], fj_tgt.shape)
                return (msg_ops.build(
                            cfg, T.MsgKind.HPV_FORWARD_JOIN, me2b,
                            endpoint, payload=(jb2, me2b)),
                        msg_ops.build(
                            cfg, T.MsgKind.HPV_FORWARD_JOIN, me2b,
                            depnode,
                            payload=(jb2, me2b, jnp.ones_like(jb2))))

            def fj_none(_):
                return (msg_ops.zero_stack(cfg, (n_local, A)),
                        msg_ops.zero_stack(cfg, (n_local, A)))

            fanout_fj, fanout_dep = jax.lax.cond(fj_go, fj_walk,
                                                 fj_none, 0)
            lv_tgt = jnp.where(state.leaving[:, None], active0, -1)
            fanout_lv = msg_ops.build(
                cfg, T.MsgKind.HPV_DISCONNECT,
                jnp.broadcast_to(me2, lv_tgt.shape), lv_tgt)
            ev_join_disc = msg_ops.build(
                cfg, T.MsgKind.HPV_DISCONNECT, gids, evicted_j)

            # ---- 6. passive merge (id-keyed bucket cache) ------------
            # Candidate budget per round: PSEL slot-borne ids
            # (disconnect sources, walk deposits, X-BOT demotions) +
            # one shuffle's ids + one shuffle-reply's ids + admission
            # evictees + the scripted join's displaced occupant.
            # Excess candidates wait for the next shuffle/disconnect —
            # the passive view is a healing cache, not a ledger.
            pw0 = jnp.select(
                [is_disc, deposit]
                + ([swap_xr, xsw_acc, xswr_ok, xrepr_ok]
                   if hv.xbot else []),
                [src, fjj]
                + ([p0, p1, p2w, p3w] if hv.xbot else []),
                -1)                                            # [n, cap]
            PSEL = min(A, cap)
            psc = jnp.where(pw0 >= 0,
                            (ranked(_TAG_PSEL, gids[:, None], slot_col)
                             >> jnp.uint32(1)).astype(jnp.int32) | 1,
                            0)
            p_slotborne, _ = compact(pw0, psc, PSEL)           # [n, PSEL]
            # shr_slot/shr_any/shr_ids1 rode the packed shuffle take
            # above (one grouped gather for both sample reads)
            pcands = jnp.concatenate([
                p_slotborne,
                jnp.where(sh_any[:, None], ids1, -1),
                jnp.where((sh_any & (origin1 != gids))[:, None],
                          origin1[:, None], -1),
                jnp.where(shr_any[:, None], shr_ids1, -1),
                evicted,
                evicted_j[:, None],
            ], axis=1)
            pranks = ranked(_TAG_PMERGE, gids[:, None],
                            jnp.arange(pcands.shape[1])[None, :])
            # clear promoted ids out of the passive view, then merge
            promoted = jnp.any(
                (passive0[:, :, None] == new_active[:, None, :])
                & (passive0 >= 0)[:, :, None], axis=2)
            passive1 = jnp.where(promoted, -1, passive0)
            new_passive = jax.vmap(views.bucket_merge)(
                passive1, pcands, pranks, gids, new_active)

            # leave: clear own views after disconnecting
            new_active2 = jnp.where(state.leaving[:, None], -1,
                                    new_active)
            new_passive2 = jnp.where(state.leaving[:, None], -1,
                                     new_passive)

            blocks = [replies, ev_disc, fanout_fj, fanout_dep, fanout_lv,
                      ev_join_disc[:, None, :],
                      shreply_msgs[:, None, :]]
            if hv.xbot:
                blocks += [x_disc]
            return new_active2, new_passive2, tuple(blocks)

        new_active, new_passive, emitted_hv = jax.lax.cond(
            busy, busy_body, quiet_body, 0)

        # ---- cadenced sends: shuffle walk, promotion, X-BOT ----------
        # Under timer_stagger=True some node fires every round, so this
        # body (including the view-snapshot gather feeding the walk)
        # runs per-round — comparable to the old per-round slot_pick
        # forwarding it replaced.  The skip only pays off with aligned
        # timers, which is the point of the knob.
        def cad_body(_):
            arangeA = jnp.arange(A, dtype=jnp.int32)
            glob_act = comm.gather_vec(active0)                # [n_g, A]
            sh_tgt = row_ranked(active0, _TAG_SHTGT, 1)[:, 0]

            # fori_loop hop body (same program-size reasoning as the
            # forward-join walk; hop index rides the rank32 coordinate)
            def sh_hop(h, carry):
                curs, prevs = carry
                cc = jnp.clip(curs, 0, comm.n_global - 1)
                vc = glob_act[cc]                              # [n, A]
                r = ranked(_TAG_SHWALK, gids[:, None],
                           h * A + arangeA[None, :])
                okm = (vc >= 0) & (vc != gids[:, None]) \
                    & (vc != prevs[:, None]) & (vc != curs[:, None])
                sc = jnp.where(okm, r | jnp.uint32(1), jnp.uint32(0))
                bi = jnp.argmax(sc, axis=1)
                nxt = jnp.take_along_axis(vc, bi[:, None], axis=1)[:, 0]
                ok = (curs >= 0) & (jnp.max(sc, axis=1) > 0)
                return (jnp.where(ok, nxt, curs),
                        jnp.where(ok, curs, prevs))

            curs, _prevs = jax.lax.fori_loop(0, hv.arwl - 1, sh_hop,
                                             (sh_tgt, gids))
            smp = jnp.concatenate([
                row_ranked(active0, _TAG_SHSAMP_A, hv.shuffle_k_active),
                row_ranked(passive0, _TAG_SHSAMP_P,
                           hv.shuffle_k_passive),
            ], axis=1)[:, :SAMPLE]
            shuffle_msgs = msg_ops.build(
                cfg, T.MsgKind.HPV_SHUFFLE, gids,
                jnp.where(sh_fire & (curs >= 0), curs, -1), ttl=1,
                payload=(gids, *jnp.unstack(smp, axis=1)))
            pr_tgt = row_ranked(passive0, _TAG_PRTGT, 1,
                                exclude=active0)[:, 0]
            promote_msgs = msg_ops.build(
                cfg, T.MsgKind.HPV_NEIGHBOR, gids,
                jnp.where(pr_fire & (pr_tgt >= 0), pr_tgt, -1),
                payload=((asize0 == 0).astype(jnp.int32),))
            cblocks = [shuffle_msgs[:, None, :],
                       promote_msgs[:, None, :]]
            if hv.xbot:
                costs0 = jnp.where(
                    active0 >= 0,
                    cost(jnp.broadcast_to(me2, active0.shape),
                         jnp.maximum(active0, 0)), -jnp.inf)
                zslot = jnp.argmax(costs0, axis=1)
                z = jnp.where(jnp.any(active0 >= 0, axis=1),
                              jnp.take_along_axis(
                                  active0, zslot[:, None], axis=1)[:, 0],
                              -1)
                cand = row_ranked(passive0, _TAG_XCAND, 1,
                                  exclude=active0)[:, 0]
                cost_cand = cost(gids, jnp.maximum(cand, 0))
                cost_worst = cost(gids, jnp.maximum(z, 0))
                x_fire = x_timer & (cand >= 0) & (z >= 0) \
                    & (cost_cand < cost_worst)
                cblocks.append(msg_ops.build(
                    cfg, T.MsgKind.HPV_XBOT_OPT, gids,
                    jnp.where(x_fire, cand, -1), payload=(z,))[:, None, :])
            return tuple(cblocks)

        def cad_quiet(_):
            return tuple(msg_ops.zero_stack(cfg, (n_local, k))
                         for k in CAD_SHAPES)

        emitted_cad = jax.lax.cond(cad_busy, cad_body, cad_quiet, 0)

        # ---- 7. timers (scripted join, shuffle, promotion, X-BOT) ----
        # Liveness heartbeat: node 0's epoch (rnd // H) rides the active
        # edges by scatter-max each round; a node whose received epoch
        # has not advanced within the isolation window is (component-)
        # isolated — full views pointing only at each other can make a
        # disconnected clique no shuffle or promotion ever merges — and
        # re-joins via a random discovery seed (see HyParViewConfig
        # .heartbeat doc for the reference mechanisms this transposes).
        stale_hb = jnp.zeros_like(ctx.alive)
        hb_epoch, hb_rnd = state.hb_epoch, state.hb_rnd
        if hv.heartbeat:
            H = cfg.rounds(hv.heartbeat_every_ms)
            window = jnp.int32(cfg.rounds(hv.isolation_window_ms))
            if cfg.control.healing:
                # Escalated isolation window: a stale-epoch node
                # re-joins sooner while the digest shows the overlay
                # degraded (the rejoin-rate half of the escalation).
                with jax.named_scope("round.control.healing"):
                    window = jnp.maximum(
                        window >> ctx.control.healing.boost, 1)
            # The epoch root is the lowest-id ALIVE node — root duty
            # migrates on crash (a fixed node-0 root would freeze every
            # epoch when node 0 dies and put the whole cluster into a
            # perpetual rejoin storm).  faults.alive is global state,
            # replicated across shards, so the argmin needs no
            # collective.
            root = jnp.argmax(ctx.faults.alive).astype(jnp.int32)
            own = jnp.where(gids == root, ctx.rnd // H, 0)
            rows = jnp.maximum(hb_epoch, own)
            tgts = jnp.where(active0 >= 0, active0, -1)
            pulled = comm.push_max(rows[:, None], tgts)[:, 0]
            new_epoch = jnp.maximum(rows, pulled)
            # the join moment = the round the FIRST active edge lands
            # (same signal as the `joined` flag update below)
            first_join = ctx.alive & ~state.joined \
                & jnp.any(new_active >= 0, axis=1)
            # per-node jitter staggers the firing (a whole component
            # going stale at once must not JOIN-storm the seeds in one
            # round)
            jit = (ranked(_TAG_HBJIT, gids, jnp.uint32(0))
                   % jnp.uint32(max(H, 1))).astype(jnp.int32)
            stale_hb = ctx.alive & ~state.left & state.joined \
                & (ctx.rnd - hb_rnd > window + jit)
            hb_epoch = new_epoch
            # firing resets the clock: the retry cadence is one window
            hb_rnd = jnp.where(
                (new_epoch > state.hb_epoch) | first_join | stale_hb,
                ctx.rnd, hb_rnd)

        # Full-range random contact draws below are bounded by the
        # active prefix width when the width operand is on: a rejoin
        # contact or discovery fallback landing on an inactive row
        # would wake it (breaking the rows-are-inert contract) and
        # diverge from a native-width run's picker distribution.
        n_eff = (comm.n_global if isinstance(ctx.n_active, tuple)
                 else ctx.n_active)
        ng_eff = jnp.maximum(jnp.asarray(n_eff, jnp.int32) - 1, 1) \
            .astype(jnp.uint32)
        join_dst = join_tgt
        if hv.auto_rejoin:
            # Discovery-agent auto-rejoin (partisan_peer_discovery_agent
            # .erl auto-joins found peers; scamp_v2 isolation
            # re-subscription :180-222): a previously-joined, alive node
            # with NO active and NO passive entries fires a JOIN at a
            # fresh random contact each round until an accept re-admits
            # it.  No optimistic pre-insert — the edge must be two-way
            # to restore INBOUND delivery, so only the accept installs
            # it.  Without this, total isolation is unrecoverable
            # (HyParView heals from the passive view only).
            isolated = ctx.alive & ~state.left & state.joined \
                & (asize0 == 0) & ~jnp.any(passive0 >= 0, axis=1) \
                & (join_tgt < 0)
            contact = (ranked(_TAG_REJOIN, gids) % ng_eff) \
                .astype(jnp.int32)
            contact = contact + (contact >= gids)
            join_dst = jnp.where(isolated, contact, join_tgt)
        if hv.heartbeat and comm.n_global > 1:
            # Seed pool clamped to the active prefix (a native-width run
            # clamps to its n_global the same way).
            sc = jnp.minimum(
                jnp.int32(min(max(hv.seed_count, 2), comm.n_global)),
                jnp.maximum(jnp.asarray(n_eff, jnp.int32), 2)) \
                .astype(jnp.uint32)
            seedc = (ranked(_TAG_HBSEED, gids) % sc).astype(jnp.int32)
            seedc = jnp.where(seedc == gids,
                              ((seedc + 1) % sc.astype(jnp.int32)), seedc)
            # Seed-death fallback: with every discovery seed crashed, a
            # stale component would retry dead seeds forever — fall back
            # to a random full-range contact (the auto_rejoin picker's
            # range).  Liveness of the seed is ground truth the
            # discovery agent would learn from its connection failure.
            fallb = (ranked(_TAG_HBFALL, gids) % ng_eff).astype(jnp.int32)
            fallb = fallb + (fallb >= gids)
            seed_dead = ~ctx.faults.alive[jnp.clip(seedc, 0,
                                                   comm.n_global - 1)]
            seedc = jnp.where(seed_dead, fallb, seedc)
            join_dst = jnp.where(stale_hb & (join_dst < 0), seedc,
                                 join_dst)
        do_join = join_dst >= 0
        join_msgs = msg_ops.build(
            cfg, T.MsgKind.HPV_JOIN, gids, jnp.where(do_join, join_dst, -1))

        # ---- 8. distance/RTT metrics plane (config-gated) ------------
        # Probe targets: the active view (the reference pings its
        # connected peers on the distance timer) plus a passive sample
        # so X-BOT's candidate pool accumulates measurements.
        new_dist = state.dist
        if cfg.distance.enabled:
            psamp = row_ranked(passive0, _TAG_DPROBE,
                               cfg.distance.probe_passive)
            new_dist, dist_emit = distance_mod.step(
                cfg, comm, state.dist, ctx,
                jnp.concatenate([active0, psamp], axis=1))

        blocks = [*emitted_hv, *emitted_cad, join_msgs[:, None, :]]
        if cfg.distance.enabled:
            blocks += [dist_emit]

        # Crash-stopped and left nodes are frozen and silent (a left node
        # is inert until a scripted rejoin — the reference's leaver shuts
        # its partisan instance down, pluggable analogue :1790-1805).
        # A node IS still live during its leave round (it must emit the
        # disconnect fan-out), and a rejoin (join_target set) clears left.
        # The mask touches only each block's kind plane; the blocks ride
        # to round_body unconcatenated (plane_ops.blocks_of).
        live = ctx.alive & (~state.left | (state.join_target >= 0))
        new_active = jnp.where(live[:, None], new_active, state.active)
        new_passive = jnp.where(live[:, None], new_passive, state.passive)
        blocks = [b.at[..., T.W_KIND].set(
            jnp.where(live[:, None], b[..., T.W_KIND], 0)) for b in blocks]

        # A scripted JOIN retries every round until an explicit accept
        # (HPV_NEIGHBOR_ACCEPTED) arrives — the walk-end adoption or the
        # contact's admission both send one.  The reference's JOIN rides
        # reliable TCP and cannot be lost; in the sim a mass-join can
        # overflow the contact's bounded inbox (SURVEY.md §7 hard-parts:
        # overflow accounting), so fire-once JOINs would orphan nodes.
        # Only an accept attributable to THIS join clears the retry: the
        # accept's source is the contact itself, or its payload carries
        # the contact id (walk-end adoptions echo the FORWARD_JOIN's
        # contact word) — a coincidental promotion accept (payload -1)
        # cannot cancel a join whose JOIN message was actually lost.
        confirmed = jnp.any(
            (kind == T.MsgKind.HPV_NEIGHBOR_ACCEPTED)
            & ((src == join_tgt[:, None]) | (p0 == join_tgt[:, None])),
            axis=1) & (join_tgt >= 0)
        new_state = HyParViewState(
            active=new_active,
            passive=new_passive,
            join_target=jnp.where(ctx.alive & confirmed, -1,
                                  state.join_target),
            leaving=jnp.where(live, False, state.leaving),
            left=(state.left | (state.leaving & live))
                 & ~(state.join_target >= 0),
            reserved=state.reserved,
            joined=state.joined | (live & jnp.any(new_active >= 0, axis=1)),
            hb_epoch=jnp.where(live, hb_epoch, state.hb_epoch),
            hb_rnd=jnp.where(live, hb_rnd, state.hb_rnd),
            dist=(jax.tree.map(
                lambda new, old: jnp.where(live[:, None], new, old),
                new_dist, state.dist)
                if cfg.distance.enabled else state.dist),
        )
        return new_state, tuple(blocks)

    # ---- views -------------------------------------------------------
    def neighbors(self, cfg: Config, state: HyParViewState,
                  comm: LocalComm | None = None) -> Array:
        return state.active

    def members(self, cfg: Config, state: HyParViewState,
                comm: LocalComm | None = None) -> Array:
        """bool[n_local, n_global]: itself + its active view.  HyParView
        keeps no global membership — the members/1 callback returns the
        active view (reference moduledoc :20-215)."""
        n_local = state.active.shape[0]
        if comm is not None:
            n_global, gids = comm.n_global, comm.local_ids()
        else:
            n_global, gids = n_local, jnp.arange(n_local, dtype=jnp.int32)
        out = jnp.zeros((n_local, n_global), jnp.bool_)
        out = out.at[jnp.arange(n_local), gids].set(True)
        rows = jnp.repeat(jnp.arange(n_local), state.active.shape[1])
        cols = jnp.where(state.active >= 0, state.active, n_global).reshape(-1)
        return out.at[rows, cols].set(True, mode="drop")

    # ---- scenario scripting ------------------------------------------
    def join(self, cfg: Config, state: HyParViewState, node: int,
             target: int) -> HyParViewState:
        return state._replace(
            join_target=state.join_target.at[node].set(target))

    def reserve(self, cfg: Config, state: HyParViewState, node: int,
                count: int = 1) -> HyParViewState:
        """Hold back ``count`` active slots on ``node`` from ordinary
        admission (reserve/1 — the reference reserves slots per tag for
        orchestrated topologies).  Raises if the reservation exceeds the
        active-view width."""
        if count < 0:
            raise ValueError("count must be >= 0")
        new = int(state.reserved[node]) + count
        if new > cfg.hyparview.active_max:
            raise ValueError(
                f"reserving {new} > active_max={cfg.hyparview.active_max}")
        return state._replace(reserved=state.reserved.at[node].add(count))

    def join_many(self, cfg: Config, state: HyParViewState, nodes,
                  targets) -> HyParViewState:
        """Batched scripted joins (one scatter — required for 10k+-node
        bootstrap, where per-node join() dispatch dominates)."""
        nodes = jnp.asarray(nodes, jnp.int32)
        targets = jnp.asarray(targets, jnp.int32)
        return state._replace(
            join_target=state.join_target.at[nodes].set(targets))

    def leave(self, cfg: Config, state: HyParViewState, node: int) -> HyParViewState:
        return state._replace(leaving=state.leaving.at[node].set(True))

    def leave_many(self, cfg: Config, state: HyParViewState,
                   nodes) -> HyParViewState:
        """Batched graceful leave (one scatter — the elastic scale-in
        path marks thousands of departing rows at once; per-node
        leave() dispatch would dominate the boundary)."""
        idx = jnp.asarray(nodes, jnp.int32)
        return state._replace(leaving=state.leaving.at[idx].set(True))
