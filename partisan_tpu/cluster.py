"""The cluster engine: state pytree + the jitted round step.

One ``round(state) -> state`` is the whole cluster advancing ``round_ms``
of virtual time (SURVEY.md §7 architecture stance):

  1. derive per-node round keys (deterministic, placement-invariant),
  2. manager transition  — timers, handle_message over the inbox,
     membership gossip (vectorized over nodes),
  3. model transition    — the protocol workload, given the overlay,
  4. interposition       — fault masks over emitted event messages
     (the reference's interposition-fun injection point),
  5. exchange            — route events into next round's inboxes;
     crashed receivers drop their deliveries,
  6. stats accumulation.

Everything is statically shaped; ``Cluster.steps(state, k)`` runs k rounds
under one ``lax.scan`` so long simulations are a single XLA program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import Array

from partisan_tpu import channels as channels_mod
from partisan_tpu import control as control_mod
from partisan_tpu import delivery as delivery_mod
from partisan_tpu import elastic as elastic_mod
from partisan_tpu import faults as faults_mod
from partisan_tpu import ingress as ingress_mod
from partisan_tpu import health as health_mod
from partisan_tpu import latency as latency_mod
from partisan_tpu import managers as managers_mod
from partisan_tpu import metrics as metrics_mod
from partisan_tpu import provenance as provenance_mod
from partisan_tpu import watchdog as watchdog_mod
from partisan_tpu import workload as workload_mod
from partisan_tpu.comm import LocalComm
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import exchange, rng
from partisan_tpu.ops import plane as plane_ops

_MSG_FILTER_TAG = 11


class Stats(NamedTuple):
    """Cumulative counters (the telemetry-events analogue, SURVEY.md §5.5)."""

    emitted: Array    # int32 — event messages emitted (pre-fault)
    delivered: Array  # int32 — event messages delivered into inboxes
    dropped: Array    # int32 — overflow + fault + dead-receiver drops


class ClusterState(NamedTuple):
    rnd: Array              # int32 scalar — round counter (virtual time)
    faults: faults_mod.FaultState
    inbox: exchange.Inbox   # deliveries awaiting consumption this round
    manager: Any            # manager-specific pytree
    model: Any              # model-specific pytree (or () if no model)
    delivery: Any           # delivery.DeliveryState (or () if disabled)
    stats: Stats
    interpose: Any = ()     # interposition-chain state (or () if none)
    outbox: Any = ()        # channels.OutboxState (or () if capacity
    #                         enforcement is off)
    metrics: Any = ()       # metrics.MetricsState ring (or () when
    #                         Config.metrics is off — zero cost)
    latency: Any = ()       # latency.LatencyState histograms (or ()
    #                         when Config.latency is off — zero cost)
    flight: Any = ()        # latency.FlightState wire-capture ring (or
    #                         () when Config.flight_rounds is 0)
    n_active: Any = ()      # int32 scalar — active prefix width (or ()
    #                         when Config.width_operand is off).  Rows
    #                         with gid >= n_active are inert: dead to
    #                         the wire, frozen in managers/models, and
    #                         masked out of metrics/latency reductions,
    #                         so one full-width round program serves
    #                         every prefix width (the bootstrap ladder
    #                         shares ONE XLA program across rungs).
    health: Any = ()        # health.HealthState topology-snapshot ring
    #                         (or () when Config.health is 0 — zero
    #                         cost, trace bit-identical to pre-health)
    provenance: Any = ()    # provenance.ProvenanceState dissemination
    #                         forest + redundancy rings (or () when
    #                         Config.provenance is off — zero cost,
    #                         wire width and trace bit-identical)
    control: Any = ()       # control.ControlState in-scan feedback
    #                         controllers (or () when no Config.control
    #                         flag is on — zero cost).  The round reads
    #                         the ROUND-START operands (eager cap, shed
    #                         ages, heal boost) and writes the next
    #                         round's at the end of the body, so every
    #                         decision is a pure function of the carry
    #                         — deterministic and checkpoint-safe.
    traffic: Any = ()       # workload.TrafficState open-loop traffic
    #                         generator (or () when Config.traffic is
    #                         off — zero cost).  Carries the DYNAMIC
    #                         intensity (absolute arrival rate, in-scan
    #                         churn probability) that workload.SetRate /
    #                         SetChurn storm actions script, so flash
    #                         crowds and diurnal ramps checkpoint and
    #                         replay with the fault timeline.
    salt: Any = ()          # uint32 scalar — per-run seed salt (or ()
    #                         when Config.salt_operand is off).  The
    #                         round's every stochastic draw keys off
    #                         the EFFECTIVE seed cfg.seed + salt, so
    #                         one program serves any seed — what lets
    #                         fleet.Fleet vmap W independent clusters
    #                         (each member's salt is its stream
    #                         namespace) and what makes a member
    #                         bit-identical to the unbatched run at
    #                         Config(seed=cfg.seed + salt).
    elastic: Any = ()       # elastic.ElasticState runtime-resize
    #                         machinery (or () when Config.elastic is
    #                         off — zero cost).  Carries the scale-in
    #                         drain boundary + deadline (the ROUND
    #                         fires the deactivation in-scan when the
    #                         deadline passes) and the resize-event
    #                         ring — the elastic timeline, replayed
    #                         exactly across checkpoint restore.
    ingress: Any = ()       # ingress.IngressState host→device inject
    #                         buffer (or () when Config.ingress is off
    #                         — zero cost).  Externally-enqueued
    #                         requests staged at chunk boundaries emit
    #                         at their release rounds as ordinary APP
    #                         records; admission sheds count emitted
    #                         AND dropped (CAUSE_INGRESS) so the
    #                         conservation law survives admission
    #                         control.
    watchdog: Any = ()      # watchdog.WatchdogState in-scan invariant
    #                         plane (or () when Config.watchdog is off
    #                         — zero cost).  Evaluated at the END of
    #                         the round over the freshly committed
    #                         ledger deltas and plane words: one packed
    #                         violation word per round in a ring, a
    #                         latched first_breach_rnd (min-reduced),
    #                         and the trip latch that freezes the
    #                         flight recorder at a breach — so a fused
    #                         superstep execution detects invariant
    #                         violations at their EXACT round instead
    #                         of the next chunk boundary.


class TraceRound(NamedTuple):
    """One round's send-path capture (the trace-orchestrator event record,
    partisan_trace_orchestrator.erl:80-86): every post-interposition
    emission, and which of them the fault stage dropped before delivery."""

    rnd: Array      # int32 scalar — the absolute round these sends ran in
    sent: Array     # int32[n_local, E', W] — emissions entering the wire
    dropped: Array  # bool[n_local, E'] — cleared by the fault stage


def round_body(cfg: Config, manager: Any, model: Any, comm: Any,
               state: ClusterState, interpose: Any = None,
               capture: bool = False):
    """ONE round, generic over the comm substrate — executed directly on a
    single device (LocalComm) or per shard inside shard_map (ShardComm).
    Sharing this body is what guarantees single-device and sharded runs
    evolve identically (tests/test_sharded.py)."""
    mx = metrics_mod.enabled(cfg)   # static: specializes the trace
    lx = latency_mod.enabled(cfg)   # static: birth-word threading
    hx = health_mod.enabled(cfg)    # static: topology-snapshot cadence
    px = provenance_mod.enabled(cfg)  # static: provenance-pair threading
    pspec = provenance_mod.spec_of(model) if px else None
    # Flight recording needs the generic wire path's materialized
    # (sent, dropped) pair — same constraint as capture.  Gated on the
    # state actually carrying a ring so shape discovery (eval_shape on
    # a flight=() state) and latency-only runs stay recorder-free.
    fx = latency_mod.flight_enabled(cfg) and state.flight != ()
    wx = cfg.width_operand  # static: active-prefix masking
    tx = workload_mod.enabled(cfg)  # static: open-loop traffic plane
    # Effective seed (Config.salt_operand): every per-round stochastic
    # draw below keys off cfg.seed + state.salt instead of the static
    # cfg.seed, so ONE round program serves any seed — the fleet
    # runner's stream namespace (fleet.py).  uint32 wraparound equals
    # the static path's mod-2**32, so salt=0 is bit-identical to the
    # unsalted round and salt=s to a native Config(seed=cfg.seed + s)
    # run (tests/test_fleet.py pins both).
    seed = cfg.seed
    if cfg.salt_operand:
        seed = jnp.uint32(cfg.seed) + jnp.asarray(state.salt, jnp.uint32)
    ex = elastic_mod.enabled(cfg)   # static: runtime-resize machinery
    gx = ingress_mod.enabled(cfg)   # static: host→device inject lane
    wdx = watchdog_mod.enabled(cfg)  # static: in-scan invariant plane
    # Elastic stage FIRST (before any active-prefix mask derives): a
    # pending scale-in deactivation fires here when its drain deadline
    # passes — the only place the round program itself moves the
    # n_active operand — and every n_active transition lands in the
    # resize ring.  n_act replaces state.n_active for the REST of the
    # round, so the deactivation round's masks, reductions and pickers
    # all see the post-resize width (plane totals stay exact across
    # resizes by construction).
    estate = state.elastic
    n_act = state.n_active
    traffic_w = None
    if ex:
        with jax.named_scope("round.elastic"):
            estate, n_act, traffic_w = elastic_mod.track(
                cfg, state.elastic, state.rnd, state.n_active)
    if tx and cfg.traffic.churn:
        # In-scan diurnal churn: one birth/death tick at the carried
        # probability, applied at ROUND START so this round's ctx and
        # wire see the post-tick mask — the host-side boundary-action
        # timing, moved inside the scan (a per-round boundary action
        # would force soak chunks to length 1).
        with jax.named_scope("round.traffic"):
            state = state._replace(faults=workload_mod.churn(
                cfg, state.traffic, state.faults, state.rnd,
                n_act, seed=seed))
    gids = comm.local_ids()
    keys = rng.node_keys(seed, state.rnd, gids)
    alive_local = jax.lax.dynamic_slice(
        state.faults.alive, (comm.node_offset,), (comm.n_local,))
    # Active-prefix masking (Config.width_operand): rows with gid >=
    # n_active are inert — their ctx.alive reads dead (managers/models/
    # delivery freeze and silence them exactly like crash-stopped
    # nodes), the WIRE's destination facts mark them dead (nothing can
    # be delivered to them), and the metrics/latency alive reductions
    # exclude them — so the prefix [0, n_active) evolves bit-identically
    # to a native n_nodes=n_active run while high rows keep their init
    # values.  state.faults itself stays unmasked (see RoundCtx.faults).
    faults_wire = state.faults
    if wx:
        act_g = jnp.arange(cfg.n_nodes, dtype=jnp.int32) < n_act
        alive_g = state.faults.alive & act_g
        faults_wire = state.faults._replace(alive=alive_g)
        alive_local = jax.lax.dynamic_slice(
            alive_g, (comm.node_offset,), (comm.n_local,))
    cx = control_mod.enabled(cfg)   # static: in-scan feedback loops
    ctx = RoundCtx(rnd=state.rnd, alive=alive_local, keys=keys,
                   inbox=state.inbox, faults=state.faults,
                   n_active=n_act, control=state.control,
                   seed=seed)

    # jax.named_scope labels each phase in the HLO, so profiler traces
    # (tools/profile_round.py under jax.profiler) map to round phases.
    # The labels are load-bearing: the lint zero-cost-when-off rule
    # keys on them (an OFF plane's round.* scope must be absent, an ON
    # plane's present — partisan_tpu/lint/rules.py), so renaming one
    # fails the lint gate, not silently weakens it.
    with jax.named_scope("round.manager"):
        mstate, m_emit = manager.step(cfg, comm, state.manager, ctx)
    tstate = state.traffic
    t_blocks = ()
    if tx:
        # Open-loop arrivals: a fresh [n, burst_max] APP block joining
        # the single assembly concatenate below — traffic records ride
        # every downstream stage (provenance/latency stamps, shed,
        # interposition, faults, route) exactly like model emissions.
        # Under Config.elastic the arrival width is the elastic stage's
        # traffic_w: draining rows neither source nor attract NEW
        # arrivals (the graceful-leave half of a scale-in).
        with jax.named_scope("round.traffic"):
            tstate, t_emit = workload_mod.generate(cfg, comm,
                                                   state.traffic, ctx,
                                                   width=traffic_w)
            t_blocks = tuple(plane_ops.blocks_of(t_emit))
    gstate = state.ingress
    i_blocks = ()
    ing_shed = ing_shed_ch = None
    if gx:
        # Streaming-ingress release: externally-staged requests whose
        # release round arrived emit as a fresh [n, slots] APP block —
        # the same downstream ride as traffic arrivals.  shed counts
        # (source dead at release + boundary buffer-full) fold into
        # this round's emitted+dropped books below.
        with jax.named_scope("round.ingress"):
            gstate, g_emit, ing_shed, ing_shed_ch = ingress_mod.release(
                cfg, comm, state.ingress, ctx)
            i_blocks = tuple(plane_ops.blocks_of(g_emit))
    nbrs = None
    if model is not None:
        with jax.named_scope("round.model"):
            nbrs = manager.neighbors(cfg, mstate, comm)
            dstate_model, a_emit = model.step(cfg, comm, state.model,
                                              ctx, nbrs)
            # ONE assembly concatenate: managers/models hand back
            # block tuples (plane_ops.blocks_of), so no record byte is
            # copied twice between emission and the wire.
            emitted = plane_ops.concat(
                tuple(plane_ops.blocks_of(m_emit))
                + tuple(plane_ops.blocks_of(a_emit)) + t_blocks
                + i_blocks,
                axis=1)
    else:
        mb = tuple(plane_ops.blocks_of(m_emit)) + t_blocks + i_blocks
        dstate_model = ()
        emitted = mb[0] if len(mb) == 1 else plane_ops.concat(mb, axis=1)
    if px:
        # Provenance pair: widen every fresh emission by (emitter gid,
        # sender tree hop).  Appended BEFORE the birth word so the
        # latency plane's [..., -1] indexing still reads the birth.
        emitted = provenance_mod.stamp(cfg, pspec, emitted, gids)
    if lx:
        # Birth-round word: widen every fresh emission to wire_words.
        # Queued copies downstream (ack store, causal rings, outbox,
        # delay buffer, inbox) carry the widened record verbatim, so
        # the birth survives defers and retransmits.
        emitted = latency_mod.stamp(emitted, state.rnd)

    # Delivery semantics: ack generation/consumption/retransmit + causal
    # clock stamping (pulls causal messages onto their wide side lanes).
    dstate, wides = state.delivery, ()
    if delivery_mod.enabled(cfg):
        with jax.named_scope("round.delivery_outbound"):
            dstate, emitted, wides = delivery_mod.outbound(
                cfg, comm, dstate, emitted, ctx)
    # Provenance reads the post-outbound PRE-WIRE stack for its control
    # EMITTED counts (what the protocol built this round — retransmit
    # replays included, shed/interposition/fault cuts not yet applied);
    # the generic path reassigns `emitted` through the wire stages, so
    # the reference is taken here.
    prov_stack = emitted if px else None

    # ---- the wire stage: monotonic shed -> interposition -> emission
    # count -> channel throttling -> fault masks.  Two implementations:
    #
    # FAST PATH (the bench/scenario hot path — no interposition chain,
    # no channel-capacity stage, groups partition mode): every
    # destination-side fact (alive, backpressure, partition group) is
    # packed into ONE int32 word per node and fetched with a SINGLE
    # gather over the emission stack; the source side is the emitting
    # row itself (every emission's W_SRC is the row's own gid — the
    # wire has no relays).  The generic composition below prices the
    # same stage with ~6 independent cross-row gathers — measured
    # ~99 ms of the 246 ms 32k round (tools/profile_phases.py), the
    # single largest block of the round.  Fault decisions are
    # bit-identical (same hash stream/salt) —
    # tests/test_faults.py::test_fast_wire_path_matches_generic asserts
    # parity against the generic path.
    #
    # GENERIC PATH: any interposition chain (delays, rewrites), channel
    # capacity enforcement, or a dense partition matrix.
    istate = state.interpose
    obstate = state.outbox
    fstate = state.flight
    want_shed = cfg.monotonic_shed and any(c.monotonic
                                           for c in cfg.channels)
    fast_wire = (interpose is None and not channels_mod.enabled(cfg)
                 and cfg.resolved_partition_mode == "groups"
                 and not capture and not fx)
    if fast_wire:
        # Compaction runs FIRST here: code and runtime are priced per
        # gathered scalar on this backend (tools/profile_phases.py /
        # BENCH_NOTES r5), so shrinking the stack from E to
        # emit_compact slots before the info gather + fault hash + kind
        # writes cuts the whole wire stage proportionally.  Ordering
        # note vs the generic path: a fault-cut message now still
        # occupies a compacted slot — observable only when a node's
        # live emissions exceed emit_compact in a faulted round (which
        # drop counter carries the loss shifts; the delivered set under
        # no overflow is identical).  The whole stage (compaction sort,
        # gather, route) is skipped when no message was emitted
        # anywhere — the quiet-round path.
        kind_raw = emitted[..., 0]
        n_raw = jnp.sum(kind_raw != 0, dtype=jnp.int32)
        any_emit = comm.allsum(n_raw) > 0

        def wire_body(_):
            # compaction INSIDE the cond: a closed-over compacted stack
            # would be a cond operand, computed on quiet rounds too
            with jax.named_scope("round.wire_fast"):
                emc = exchange.compact_emissions(emitted, cfg.emit_compact) \
                    if cfg.emit_compact else emitted
                kind_w = emc[..., 0]
                dst_w = emc[..., 2]
                backed = (comm.gather_vec(state.inbox.drops > 0)
                          if want_shed else None)
                info_d = faults_mod.pack_wire_info(faults_wire, backed)[
                    jnp.clip(dst_w, 0, cfg.n_nodes - 1)]       # ONE gather
                shed_n = jnp.int32(0)
                shed_m = None
                if want_shed:
                    # monotonic-channel shed (partisan_peer_socket.erl
                    # :108-129 monotonic_should_send): the channel id is a
                    # static config constant per producer, so the tiny
                    # mono[ch] table lookup unrolls to fused equality tests
                    mono_m = jnp.zeros(kind_w.shape, jnp.bool_)
                    for i, c in enumerate(cfg.channels):
                        if c.monotonic:
                            mono_m = mono_m | (emc[..., 3] == i)
                    shed = mono_m & (((info_d >> 1) & 1) == 1) \
                        & (kind_w != 0)
                    kind_w = jnp.where(shed, 0, kind_w)
                    shed_n = jnp.sum(shed, dtype=jnp.int32)
                    shed_m = shed
                group_l = jax.lax.dynamic_slice(
                    state.faults.partition, (comm.node_offset,),
                    (comm.n_local,))
                cut = faults_mod.wire_cut_from_info(
                    faults_wire, info_d, kind_w != 0, gids, dst_w,
                    alive_local, group_l, seed, state.rnd,
                    _MSG_FILTER_TAG)
                final = emc.at[..., 0].set(jnp.where(cut, 0, kind_w))
                out = (comm.route(final), shed_n)
                if mx:
                    # cause counters for the metrics ring (shard-local;
                    # reduced outside the cond): fault cuts, and the
                    # per-channel shed so emitted-per-channel can be
                    # derived from the pre-wire stack
                    fault_n = jnp.sum(cut & (kind_w != 0),
                                      dtype=jnp.int32)
                    # emc's kind word is still pre-shed here, so the
                    # masked count sees the shed slots as live
                    shed_ch = (metrics_mod.channel_counts(
                        cfg, emc, mask=shed_m) if shed_m is not None
                        else jnp.zeros((cfg.n_channels,), jnp.int32))
                    out += (fault_n, shed_ch)
                if lx:
                    # fault-cut + compaction-overflow ages (shard-local,
                    # reduced in record_round) — INSIDE the cond so quiet
                    # rounds skip the histogram work, same discipline as
                    # the compaction itself.  The fault mask matches
                    # m_fault; the compact mask is live-beyond-cap on the
                    # PRE-shed stack, matching m_compact below.
                    out += (latency_mod.age_hist(
                        emc, cut & (kind_w != 0), state.rnd),)
                    if cfg.emit_compact:
                        l_rank = jnp.cumsum(kind_raw != 0, axis=1) - 1
                        out += (latency_mod.age_hist(
                            emitted,
                            (kind_raw != 0) & (l_rank >= cfg.emit_compact),
                            state.rnd),)
                    else:
                        out += (latency_mod.zero_hist(),)
                return out

        def wire_skip(_):
            out = (exchange.empty_inbox(comm.n_local, cfg.inbox_cap,
                                        cfg.wire_layout), jnp.int32(0))
            if mx:
                out += (jnp.int32(0),
                        jnp.zeros((cfg.n_channels,), jnp.int32))
            if lx:
                out += (latency_mod.zero_hist(), latency_mod.zero_hist())
            return out

        wire_out = jax.lax.cond(any_emit, wire_body, wire_skip, 0)
        inbox, shed_n = wire_out[0], wire_out[1]
        if lx:
            base_i = 4 if mx else 2
            lat_fault = wire_out[base_i]
            lat_compact = wire_out[base_i + 1]
            lat_outbox = latency_mod.zero_hist()  # no channel stage here
        # shed drops are excluded from the emitted count (same stance
        # as the generic path); compaction/fault/overflow drops are
        # counted emitted and surface via the emitted-delivered delta
        n_emitted = comm.allsum(n_raw - shed_n)
        if mx:
            m_fault = comm.allsum(wire_out[2])
            m_shed = comm.allsum(shed_n)
            m_outbox = jnp.int32(0)    # no channel-capacity stage here
            # per-channel emissions = pre-wire stack minus per-channel
            # sheds (the only exclusion the fast path applies before
            # the emitted count)
            emit_ch = comm.allsum(
                metrics_mod.channel_counts(cfg, emitted) - wire_out[3])
            # compaction overflow: the fast path compacts the PRE-shed
            # stack, so the per-row loss is live-beyond-cap on `emitted`
            # (zero on quiet rounds: nothing live anywhere)
            if cfg.emit_compact:
                live_row = jnp.sum(kind_raw != 0, axis=1,
                                   dtype=jnp.int32)
                m_compact = comm.allsum(jnp.sum(jnp.maximum(
                    live_row - cfg.emit_compact, 0), dtype=jnp.int32))
            else:
                m_compact = jnp.int32(0)
    else:
        # Monotonic-channel load shedding: sends on a monotonic channel
        # to a receiver whose inbox overflowed LAST round are dropped —
        # newer state supersedes older, so shedding under backpressure
        # is safe (partisan_peer_socket.erl:108-129
        # monotonic_should_send; the only drop path the reference's
        # transport permits).
        m_shed_local = jnp.int32(0)
        if want_shed:
            mono = jnp.asarray([c.monotonic for c in cfg.channels],
                               jnp.bool_)
            backed = comm.gather_vec(state.inbox.drops > 0)  # [n_global]
            ch = jnp.clip(emitted[..., 3], 0, cfg.n_channels - 1)
            dstv = jnp.clip(emitted[..., 2], 0, cfg.n_nodes - 1)
            shed = mono[ch] & backed[dstv] & (emitted[..., 0] != 0)
            emitted = emitted.at[..., 0].set(
                jnp.where(shed, 0, emitted[..., 0]))
            if mx:
                m_shed_local = jnp.sum(shed, dtype=jnp.int32)

        # Interposition chain (test plane): drop/rewrite/delay
        # transforms on the send path, before the stochastic fault
        # stage (mirrors the reference's interposition-before-wire
        # placement, :58-130).
        if interpose is not None:
            with jax.named_scope("round.interpose"):
                istate, emitted = interpose.apply(cfg, comm, istate,
                                                  emitted, ctx)

        n_emitted = comm.allsum(jnp.sum(emitted[..., 0] != 0,
                                        dtype=jnp.int32))
        if mx:
            m_shed = comm.allsum(m_shed_local)
            # per-channel emissions, counted exactly where the scalar
            # emitted count is (post-shed, post-interposition)
            emit_ch = comm.allsum(metrics_mod.channel_counts(cfg, emitted))

        # Channel-capacity stage (opt-in): per-(edge, channel, lane)
        # throughput enforcement with outbox backpressure.  Runs after
        # the emission count (a deferred send was already counted when
        # emitted) and before the fault stage (a deferred send rides
        # the wire — and its faults — the round it actually transmits).
        if lx:
            lat_outbox = latency_mod.zero_hist()
        if channels_mod.enabled(cfg):
            shed_ages = None
            if cfg.control.backpressure:
                # The ROUND-START pressure levels drive this round's
                # per-channel stale-shed thresholds (actuation side of
                # the backpressure loop; latency is on by validation,
                # so the lx branch below always runs).
                with jax.named_scope("round.control.backpressure"):
                    shed_ages = control_mod.shed_age(
                        cfg, state.control.backpressure)
            with jax.named_scope("round.throttle"):
                if lx:
                    obstate, emitted, lat_outbox = channels_mod.throttle(
                        cfg, comm, obstate, emitted, birth_rnd=state.rnd,
                        shed_age=shed_ages)
                else:
                    obstate, emitted = channels_mod.throttle(
                        cfg, comm, obstate, emitted)
        if mx:
            m_outbox = (channels_mod.shed_delta(state.outbox, obstate)
                        if channels_mod.enabled(cfg) else jnp.int32(0))

        # Fault stage: crash/partition/omission masks between emit and
        # deliver.
        with jax.named_scope("round.fault"):
            sent = emitted
            emitted = faults_mod.filter_msgs(
                faults_wire, emitted, seed, state.rnd,
                _MSG_FILTER_TAG)
            fault_dropped = (sent[..., 0] != 0) & (emitted[..., 0] == 0)
        # THE plane->wire interleave: capture/flight need the trace's
        # interleaved int32 [n, E, W] tensor (TraceRound.sent is the
        # layout-stable contract), and it is the ONLY interleave the
        # round program may contain (the lint interleave-budget rule
        # counts them at the jaxpr level — partisan_tpu/lint/rules.py,
        # budget 1 here, 0 for the plain round whose exchange ships
        # packed planes; tests/test_program_budget.py pins the exact
        # counts).
        sent_wire = plane_ops.interleave(sent) if (capture or fx) else None
        if fx:
            # Flight recorder: the same (sent, dropped) pair capture
            # mode returns, written into the carry's K-round ring.
            with jax.named_scope("round.flight"):
                def _flight_write():
                    return latency_mod.record_flight(
                        cfg, state.flight, rnd=state.rnd,
                        sent=sent_wire, dropped=fault_dropped)

                if wdx and cfg.watchdog.trip_flight:
                    # Trip mode (watchdog.py): once the PREVIOUS
                    # round's watchdog latched a breach, the ring
                    # freezes — the breach round itself is the last
                    # slot written (the latch is set AFTER this write,
                    # at the end of its round), so the offending wire
                    # traffic survives to the chunk boundary instead
                    # of wrapping.
                    fstate = jax.lax.cond(state.watchdog.tripped > 0,
                                          lambda: state.flight,
                                          _flight_write)
                else:
                    fstate = _flight_write()
        if lx:
            lat_fault = latency_mod.age_hist(sent, fault_dropped,
                                             state.rnd)
            # compaction here runs AFTER the fault stage (route_body
            # compacts the post-fault stack) — same accounting as
            # m_compact below
            if cfg.emit_compact:
                l_rank = jnp.cumsum(emitted[..., 0] != 0, axis=1) - 1
                lat_compact = latency_mod.age_hist(
                    emitted,
                    (emitted[..., 0] != 0) & (l_rank >= cfg.emit_compact),
                    state.rnd)
            else:
                lat_compact = latency_mod.zero_hist()
        if mx:
            m_fault = comm.allsum(jnp.sum(fault_dropped, dtype=jnp.int32))
            # compaction here runs AFTER the fault stage (route_body
            # compacts the post-fault stack), so the loss is
            # live-beyond-cap on the post-fault rows
            if cfg.emit_compact:
                live_row = jnp.sum(emitted[..., 0] != 0, axis=1,
                                   dtype=jnp.int32)
                m_compact = comm.allsum(jnp.sum(jnp.maximum(
                    live_row - cfg.emit_compact, 0), dtype=jnp.int32))
            else:
                m_compact = jnp.int32(0)

        # The exchange (compaction sort + route) is skipped when NO
        # message survived to the wire anywhere — common once the
        # managers' quiet-gates leave rounds without traffic.
        # Cross-shard predicate: route contains collectives.
        any_emit = comm.allsum(jnp.sum(emitted[..., 0] != 0,
                                       dtype=jnp.int32)) > 0

        def route_body(_):
            with jax.named_scope("round.route"):
                e = exchange.compact_emissions(emitted, cfg.emit_compact) \
                    if cfg.emit_compact else emitted
                return comm.route(e)

        def route_skip(_):
            return exchange.empty_inbox(comm.n_local, cfg.inbox_cap,
                                        cfg.wire_layout)

        inbox = jax.lax.cond(any_emit, route_body, route_skip, 0)
    if gx:
        # Open-loop admission accounting (ingress.py): shed external
        # requests are offered load — they join the emitted count here
        # and the CAUSE_INGRESS drops row below, so the conservation
        # law (emitted == delivered + dropped) holds exactly through
        # admission control.
        n_emitted = n_emitted + ing_shed
        if mx:
            emit_ch = emit_ch + ing_shed_ch
    # Crash-stopped receivers drop everything addressed to them.
    dead = ~alive_local
    if mx:
        # Inbox-overflow drops (route's counts-beyond-cap) are read
        # BEFORE the dead-receiver stage folds its own loss into the
        # same drops field — the two are distinct causes in the ring.
        m_inbox_of = comm.allsum(jnp.sum(inbox.drops, dtype=jnp.int32))
        m_dead = comm.allsum(jnp.sum(
            jnp.where(dead, inbox.count, 0), dtype=jnp.int32))
    ctrl_chmax = None
    if cfg.control.backpressure:
        # Sensing side of the backpressure loop: each channel's
        # per-round delivered-age high-water mark (same pre-mask inbox
        # and dead mask the latency plane reads), allmax-reduced so the
        # pressure decision replicates across shards.  Computed ONCE
        # and handed to record_round below, so the reduction (and its
        # cross-shard collective) does not trace twice.
        with jax.named_scope("round.control.backpressure"):
            ctrl_chmax = control_mod.pressure_signal(
                cfg, comm, inbox.data, dead, state.rnd)
    lt = state.latency
    if lx:
        # Delivery + dead-receiver ages read the PRE-mask inbox: the
        # delivered set here is exactly what the metrics plane counts
        # as deliver_ch below, so per-channel histogram sums reconcile
        # with the delivered series by construction.
        with jax.named_scope("round.latency"):
            lt = latency_mod.record_round(
                cfg, comm, lt, rnd=state.rnd, inbox_data=inbox.data,
                dead=dead, fault_hist=lat_fault,
                compact_hist=lat_compact, outbox_hist=lat_outbox,
                chmax=ctrl_chmax)
    pv = state.provenance
    if px:
        # Same delivered set as the metrics/latency planes (the routed
        # inbox before dead-receiver masking, `dead` covering crashed
        # and — under width_operand — inactive rows), so the redundancy
        # ring reconciles with the delivered series by construction.
        with jax.named_scope("round.provenance"):
            pv = provenance_mod.record_round(
                cfg, comm, pspec, pv, rnd=state.rnd, emitted=prov_stack,
                inbox_data=inbox.data, dead=dead,
                alive_local=alive_local)
    # Dead-receiver stage.  On the fast wire path the data mask is the
    # identity: wire_cut_from_info severs every edge whose destination
    # is dead (~alive_d, from the SAME faults_wire.alive that `dead`
    # complements), so no record addressed to a dead row survives into
    # route — skipping the per-plane [n, cap, ·] select consumes the
    # routed inbox in place (phase fusion; the generic path keeps the
    # mask because interposition chains may rewrite destinations after
    # the fault filter).  The count/drops arithmetic stays: [n]-vector
    # work is free and keeps the books uniform across both paths.
    inbox = exchange.Inbox(
        data=inbox.data if fast_wire
        else plane_ops.where(dead[:, None], 0, inbox.data),
        count=jnp.where(dead, 0, inbox.count),
        drops=inbox.drops + jnp.where(dead, inbox.count, 0),
    )
    ev_delivered = comm.allsum(jnp.sum(inbox.count, dtype=jnp.int32))
    if mx:
        # Event-lane deliveries per channel, counted before the causal
        # merge (causal deliveries are their own series — no channel).
        deliver_ch = comm.allsum(
            metrics_mod.channel_counts(cfg, inbox.data))

    causal_delivered = jnp.int32(0)
    if delivery_mod.needs_inbound(cfg):
        # Causal broadcast lanes bypass route(): inbound gathers the
        # bounded actor block itself, applies per-receiver transmission
        # faults, and suppresses dead receivers internally.  P2p causal
        # lanes ride route() and are re-ordered out of the inbox here.
        with jax.named_scope("round.delivery_inbound"):
            dstate, inbox, causal_delivered = delivery_mod.inbound(
                cfg, comm, dstate, inbox, wides, ctx)

    # `dropped` tracks the event lane only: a causal broadcast is one
    # emission with up-to-n deliveries, so it gets its own counter.
    drop_delta = n_emitted - ev_delivered
    if cfg.watchdog.inject_round >= 0:
        # Watchdog test plane: deterministic ledger corruption at
        # exactly one round — a pure function of the carried round
        # counter, so it replays identically across chunking,
        # superstep, checkpoint resume and sharding, and fires
        # regardless of watchdog.enabled (the plane-off baseline must
        # corrupt the same books the host invariants audit).
        drop_delta = drop_delta + jnp.where(
            state.rnd == cfg.watchdog.inject_round,
            jnp.int32(cfg.watchdog.inject_amount), jnp.int32(0))
    stats = Stats(
        emitted=state.stats.emitted + n_emitted,
        delivered=state.stats.delivered + ev_delivered + causal_delivered,
        dropped=state.stats.dropped + drop_delta,
    )
    mets = state.metrics
    if mx:
        with jax.named_scope("round.metrics"):
            # The residual cause closes the books by construction:
            # sum(drops) == this round's legacy dropped delta exactly.
            # It absorbs what round_body cannot see directly (a2a quota
            # sheds inside the sharded exchange; channel-capacity
            # defer/release churn, which makes it transiently negative).
            m_ingress = ing_shed if gx else jnp.int32(0)
            m_other = (n_emitted - ev_delivered) - (
                m_compact + m_fault + m_inbox_of + m_dead + m_outbox
                + m_ingress)
            drops_vec = jnp.stack([m_compact, m_fault, m_inbox_of,
                                   m_dead, m_outbox, m_ingress,
                                   m_other])
            dlv_of = (delivery_mod.overflow_total(dstate)
                      - delivery_mod.overflow_total(state.delivery))
            nbrs_m = nbrs if nbrs is not None \
                else manager.neighbors(cfg, mstate, comm)
            mets = metrics_mod.record_round(
                cfg, comm, state.metrics, rnd=state.rnd,
                emitted_ch=emit_ch, delivered_ch=deliver_ch,
                causal=causal_delivered, shed=m_shed, drops=drops_vec,
                inbox_count=inbox.count, alive_local=alive_local,
                alive_global=faults_wire.alive, nbrs=nbrs_m,
                dlv_overflow=dlv_of)
    hstate = state.health
    if hx:
        # Topology snapshot every cfg.health rounds, on the POST-
        # transition state (the state the host sees after this round),
        # so a batch whose length is a multiple of the cadence ends
        # with a digest describing exactly its final state — what
        # scenarios._converge polls as ONE scalar.  All the graph work
        # (neighbor gather, pointer-jumping components, symmetry
        # check, coverage) lives INSIDE the cond: non-snapshot rounds
        # pay only the predicate.
        with jax.named_scope("round.health"):
            due = jnp.mod(state.rnd + 1, cfg.health) == 0

            def health_body(h):
                nbrs_h = nbrs if nbrs is not None \
                    else manager.neighbors(cfg, mstate, comm)
                if model is not None and hasattr(model, "coverage"):
                    # Coverage-complete, cross-shard: every shard's
                    # alive nodes covered (d/d == 1.0 is float-exact;
                    # an alive-EMPTY shard is vacuously complete, but
                    # an all-dead CLUSTER is not — the legacy coverage
                    # poll reads 0.0 there, and the digest must agree).
                    cov_l = model.coverage(dstate_model, alive_local, 0)
                    n_al = jnp.sum(alive_local, dtype=jnp.int32)
                    ok_l = (n_al == 0) | (cov_l >= 1.0)
                    cov_ok = (comm.allsum(n_al) > 0) & (comm.allsum(
                        jnp.where(ok_l, 0, 1).astype(jnp.int32)) == 0)
                else:
                    cov_ok = jnp.bool_(True)
                return health_mod.record_snapshot(
                    cfg, comm, h, rnd=state.rnd, nbrs_local=nbrs_h,
                    alive_global=faults_wire.alive, cov_ok=cov_ok,
                    partition=state.faults.partition)

            hstate = jax.lax.cond(due, health_body, lambda h: h,
                                  state.health)
    ctrl = state.control
    if cx:
        # Controller step (control.py): a pure function of the planes'
        # freshly written states — the NEXT round reads the result as
        # its operands (one round of actuation delay, the price of
        # staying a scan carry).  Each controller traces under its own
        # round.control.* named_scope (the lint zero-cost key).
        ctrl = control_mod.update(cfg, state.control, rnd=state.rnd,
                                  pv=pv, health=hstate,
                                  chmax=ctrl_chmax)
    wstate = state.watchdog
    if wdx:
        # Invariant watchdog (watchdog.py): fold this round's freshly
        # committed ledger deltas + plane words into one violation
        # word and latch the first breach round.  Runs LAST so it
        # audits exactly the values the carry commits — including any
        # injected corruption in drop_delta above.  Every input is
        # already cross-shard reduced, so the plane replicates.
        with jax.named_scope("round.watchdog"):
            wstate = watchdog_mod.update(
                cfg, comm, state.watchdog, rnd=state.rnd,
                emitted=n_emitted,
                delivered=ev_delivered + causal_delivered,
                dropped=drop_delta, drops=drops_vec,
                digest=hstate.digest if hx else None,
                age_hwm=lt.age_hwm if lx else None)
    out = ClusterState(rnd=state.rnd + 1, faults=state.faults,
                       inbox=inbox, manager=mstate, model=dstate_model,
                       delivery=dstate, stats=stats, interpose=istate,
                       outbox=obstate, metrics=mets, latency=lt,
                       flight=fstate, n_active=n_act,
                       health=hstate, provenance=pv, control=ctrl,
                       traffic=tstate, salt=state.salt,
                       elastic=estate, ingress=gstate,
                       watchdog=wstate)
    if capture:
        return out, TraceRound(rnd=state.rnd, sent=sent_wire,
                               dropped=fault_dropped)
    return out


def activate(state: ClusterState, width) -> ClusterState:
    """Set the active prefix width (Config.width_operand runs): the
    in-place successor of scenarios._grow_state — rows [old, width)
    simply become live, their leaves already holding init values (the
    masking above guarantees inert rows were never written).  A dynamic
    operand change, so NO retrace/recompile: the same round program
    serves every width.

    Host-boundary validation (ISSUE 15 satellite): ``width`` must be a
    concrete integer in ``[1, n_nodes]`` — an out-of-range operand used
    to clamp silently downstream (every picker/mask clips), turning a
    typo'd 10_000 on a 4096-capacity program into a quiet no-op.  The
    guard is ``elastic.check_width`` — ONE rule shared with the
    ScaleOut/ScaleIn paths."""
    if isinstance(state.n_active, tuple):
        raise ValueError(
            "activate() needs Config.width_operand=True (the state "
            "carries no n_active operand)")
    w = elastic_mod.check_width("activate()", width,
                                state.faults.alive.shape[0])
    return state._replace(n_active=jnp.int32(w))


def with_salt(state: ClusterState, salt) -> ClusterState:
    """Set the per-run seed salt (Config.salt_operand runs): the
    round's stochastic draws key off ``cfg.seed + salt``.  A dynamic
    operand change, so NO retrace — the same program serves every
    seed (the salted sibling of :func:`activate`).  A run at salt=s is
    bit-identical to a native ``Config(seed=cfg.seed + s)`` run."""
    if isinstance(state.salt, tuple):
        raise ValueError(
            "with_salt() needs Config(salt_operand=True) (the state "
            "carries no salt operand)")
    return state._replace(salt=jnp.asarray(salt, jnp.uint32))


def active_alive(state: ClusterState) -> Array:
    """bool[n_global]: faults.alive restricted to the active prefix —
    what coverage/conformance reductions should use on width-operand
    states (on a fully-activated or non-width-operand state this IS
    faults.alive)."""
    alive = state.faults.alive
    if isinstance(state.n_active, tuple):
        return alive
    n = alive.shape[0]
    return alive & (jnp.arange(n, dtype=jnp.int32) < state.n_active)


def run_until(cluster: Any, state: ClusterState, pred, max_rounds: int,
              check_every: int = 1) -> tuple[ClusterState, int]:
    """Step until host-side ``pred(state)`` is True. Returns (state,
    rounds_taken) or (state, -1) if the bound was hit."""
    for _ in range(0, max_rounds, check_every):
        if pred(state):
            return state, int(state.rnd)
        state = cluster.steps(state, check_every)
    return (state, int(state.rnd)) if pred(state) else (state, -1)


@dataclasses.dataclass
class Cluster:
    """Builds and runs the jitted round step for one configuration.

    ``manager``/``model`` are static (they specialize the trace); state
    lives in the ClusterState pytree.
    """

    cfg: Config
    manager: Any = None
    model: Any = None
    interpose: Any = None   # interpose.Interposition (or a Chain), static
    donate: bool = False    # donate the state carry to steps() — the
    #                         caller must not reuse a donated input state
    #                         (bench/scenario drivers thread state
    #                         linearly; tests that fork states keep the
    #                         default)

    def __post_init__(self) -> None:
        if self.manager is None:
            self.manager = managers_mod.get(self.cfg.peer_service_manager)
        # egress/ingress delay config keys install a send-path Delay
        # stage after any user-supplied interposition chain.  The
        # pre-wrap interposition is kept so rebuild() can reconstruct
        # without double-wrapping the delay stage.
        from partisan_tpu import interpose as interpose_mod

        self._user_interpose = self.interpose
        self.interpose = interpose_mod.config_delays(self.cfg,
                                                     self.interpose)
        self.comm = LocalComm(
            n_global=self.cfg.n_nodes,
            inbox_cap=self.cfg.inbox_cap,
            msg_words=self.cfg.msg_words,
        )
        # Flight-recorder ring shape: the wire stack's emission width
        # depends on manager/model/delivery extras, so it is discovered
        # by an abstract trace of the captured round (eval_shape — no
        # compile, no device work) before the first real init.
        self._flight_shape = None
        if latency_mod.flight_enabled(self.cfg):
            base = jax.eval_shape(self._init_noflight)
            tr = jax.eval_shape(
                lambda s: round_body(self.cfg, self.manager, self.model,
                                     self.comm, s,
                                     interpose=self.interpose,
                                     capture=True)[1], base)
            self._flight_shape = tuple(tr.sent.shape)
        self._step = jax.jit(self._round)
        self._steps = jax.jit(self._scan, static_argnums=1,
                              donate_argnums=(0,) if self.donate else ())
        self._record = jax.jit(self._scan_traced, static_argnums=1)
        self._init = jax.jit(self._build_init)

    # ---- state construction ------------------------------------------
    def init(self) -> ClusterState:
        """Initial state, built as ONE jitted program — on a relay-attached
        device each eager allocation is a host round-trip, which made
        eager init cost ~7 s at 32k nodes."""
        return self._init()

    def _init_noflight(self) -> ClusterState:
        cfg, comm = self.cfg, self.comm
        return ClusterState(
            rnd=jnp.int32(0),
            faults=faults_mod.none(cfg.n_nodes,
                                   cfg.resolved_partition_mode),
            inbox=exchange.empty_inbox(comm.n_local, cfg.inbox_cap,
                                       cfg.wire_layout),
            manager=self.manager.init(cfg, comm),
            model=self.model.init(cfg, comm) if self.model is not None else (),
            delivery=(delivery_mod.init(cfg, comm)
                      if delivery_mod.enabled(cfg) else ()),
            stats=Stats(jnp.int32(0), jnp.int32(0), jnp.int32(0)),
            interpose=(self.interpose.init(cfg, comm)
                       if self.interpose is not None else ()),
            outbox=(channels_mod.init(cfg, comm)
                    if channels_mod.enabled(cfg) else ()),
            metrics=(metrics_mod.init(cfg, comm)
                     if metrics_mod.enabled(cfg) else ()),
            latency=(latency_mod.init(cfg)
                     if latency_mod.enabled(cfg) else ()),
            n_active=(jnp.int32(cfg.n_nodes) if cfg.width_operand
                      else ()),
            health=(health_mod.init(cfg)
                    if health_mod.enabled(cfg) else ()),
            provenance=(provenance_mod.init(cfg, comm)
                        if provenance_mod.enabled(cfg) else ()),
            control=(control_mod.init(cfg)
                     if control_mod.enabled(cfg) else ()),
            traffic=(workload_mod.init(cfg)
                     if workload_mod.enabled(cfg) else ()),
            salt=(jnp.uint32(0) if cfg.salt_operand else ()),
            elastic=(elastic_mod.init(cfg)
                     if elastic_mod.enabled(cfg) else ()),
            ingress=(ingress_mod.init(cfg, comm)
                     if ingress_mod.enabled(cfg) else ()),
            watchdog=(watchdog_mod.init(cfg)
                      if watchdog_mod.enabled(cfg) else ()),
        )

    def _build_init(self) -> ClusterState:
        state = self._init_noflight()
        if self._flight_shape is not None:
            state = state._replace(
                flight=latency_mod.flight_init(self.cfg,
                                               self._flight_shape))
        return state

    # ---- the round ----------------------------------------------------
    def _round(self, state: ClusterState) -> ClusterState:
        return round_body(self.cfg, self.manager, self.model, self.comm,
                          state, interpose=self.interpose)

    def _scan(self, state: ClusterState, k: int) -> ClusterState:
        # Fused supersteps (Config.superstep=R): an outer scan whose
        # body is an inner R-round scan.  The round body still traces
        # exactly ONCE (the inner scan's jaxpr is shared by reference
        # in the outer body), so program size is O(1) in R — guarded by
        # tests/test_program_budget.py::test_superstep_program_o1 —
        # and the result is the same R*outer+rem sequential round
        # applications as the flat scan: bit-identical for any R.
        # Cadence conds inside round_body key off the carried
        # state.rnd, never the scan index, so health/control/flight/
        # elastic fire on true round numbers across the fold.
        R = self.cfg.superstep
        if R <= 1:
            return jax.lax.scan(
                lambda s, _: (self._round(s), None), state, None, length=k
            )[0]
        outer, rem = divmod(k, R)

        def inner(s, r):
            return jax.lax.scan(
                lambda t, _: (self._round(t), None), s, None, length=r)[0]

        if outer:
            state = jax.lax.scan(
                lambda s, _: (inner(s, R), None), state, None,
                length=outer)[0]
        if rem:   # R non-divisors of k: a remainder scan, same body
            state = inner(state, rem)
        return state

    def _round_traced(self, state: ClusterState):
        return round_body(self.cfg, self.manager, self.model, self.comm,
                          state, interpose=self.interpose, capture=True)

    def _scan_traced(self, state: ClusterState, k: int):
        return jax.lax.scan(
            lambda s, _: self._round_traced(s), state, None, length=k)

    # ---- public API ---------------------------------------------------
    def step(self, state: ClusterState) -> ClusterState:
        return self._step(state)

    def steps(self, state: ClusterState, k: int) -> ClusterState:
        """Run k rounds as one XLA program (lax.scan)."""
        return self._steps(state, k)

    def record(self, state: ClusterState, k: int):
        """Run k rounds capturing the send-path trace.  Returns
        ``(state', TraceRound)`` with trace leaves stacked on a leading
        round axis — the trace-orchestrator record mode (SURVEY.md §5.1:
        "trace = the per-round message tensor itself")."""
        return self._record(state, k)

    def rebuild(self) -> "Cluster":
        """A functionally identical Cluster with FRESH jitted programs
        — the fresh-context factory for soak crash recovery: after a
        worker crash the old executables keep failing (the poisoned
        process context, tools/MINUTE_FAULT.md), so retries must
        dispatch against newly built ones."""
        return Cluster(self.cfg, manager=self.manager, model=self.model,
                       interpose=self._user_interpose,
                       donate=self.donate)

    def run_chunked(self, state: ClusterState, k: int,
                    chunk: int = 0) -> ClusterState:
        """Run k rounds as a sequence of bounded scan executions with
        the carry device-resident between them (soak.run) — the
        long-horizon driver for relay-attached devices, where a single
        execution past the ~60 s wall deadline kills the TPU worker
        (tools/MINUTE_FAULT.md).  ``chunk=0`` sizes chunks adaptively
        against the soak engine's wall budget; bit-identical to
        ``steps(state, k)`` (tests/test_soak.py chunking parity).  For
        crash retries, checkpoints and fault storms, drive a
        ``soak.Soak`` directly."""
        from partisan_tpu import soak as soak_mod

        return soak_mod.run(self, state, k, chunk=chunk)

    def run_until(self, state: ClusterState, pred, max_rounds: int,
                  check_every: int = 1) -> tuple[ClusterState, int]:
        return run_until(self, state, pred, max_rounds, check_every)
