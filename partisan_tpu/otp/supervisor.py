"""partisan_gen_supervisor: cross-node supervision (reference
priv/otp/24/partisan_gen_supervisor.erl, 1850 LoC).

A :class:`Supervisor` process on one node manages child processes
hosted on OTHER nodes — START/STOP orders and EXIT notifications ride
the transport, which is exactly what partisan_gen_supervisor enables
over partisan (children anywhere in the cluster).  Semantics owned
here (test/partisan_supervisor_SUITE.erl):

- one_for_one: only the crashed child restarts,
- rest_for_one: the crashed child and those started AFTER it restart —
  later children stopped in reverse start order, restarted in order,
- one_for_all: every child restarts (stop reverse, start in order),
- maximum restart intensity (MaxR within MaxT rounds): exceeding it
  stops ALL children and terminates the supervisor,
- restart types: permanent (always), transient (only abnormal exits),
  temporary (never — and the child spec is discarded),
- which_children / count_children / restart_child / delete_child,
- a stale EXIT from a superseded incarnation is ignored (the
  Mref-generation pairing of the monitor layer).

:class:`ChildHost` is the remote side: a node hosting child processes,
obeying START/STOP and reporting EXITs with the child's incarnation.
"""

from __future__ import annotations

from partisan_tpu.otp import gen

# exit reasons
NORMAL, CRASH = 0, 1
# restart types
PERMANENT, TRANSIENT, TEMPORARY = 0, 1, 2
# strategies
ONE_FOR_ONE = "one_for_one"
REST_FOR_ONE = "rest_for_one"
ONE_FOR_ALL = "one_for_all"


class ChildHost(gen.Proc):
    """A node hosting child processes: obeys START/STOP, reports EXITs."""

    def __init__(self, port: gen.Port) -> None:
        super().__init__(port)
        self.running: dict[int, int] = {}   # child_id -> incarnation
        self.log: list = []                 # (op, child, inc) in order

    def process(self, _rnd: int = 0) -> None:
        for _src, words in self.drain():
            op, child, inc = words[0], words[1], words[2]
            if op == gen.OP_START:
                self.running[child] = inc
                self.log.append(("start", child, inc))
            elif op == gen.OP_STOP:
                self.running.pop(child, None)
                self.log.append(("stop", child, inc))

    def kill(self, sup_id: int, child: int, reason: int = CRASH) -> None:
        """Child dies (crash- or test-injected): report EXIT to the
        supervisor with its incarnation — the monitor/link DOWN the
        reference delivers."""
        inc = self.running.pop(child, None)
        if inc is not None:
            self.forward(sup_id, [gen.OP_EXIT, child, inc, reason])


class Supervisor(gen.Proc):
    """The partisan_gen_supervisor loop (one supervisor process)."""

    def __init__(self, port: gen.Port, specs, strategy: str = ONE_FOR_ONE,
                 max_r: int = 3, max_t: int = 20) -> None:
        """specs: ordered [(child_id, host_node_id, restart_type)]."""
        super().__init__(port)
        self.specs = list(specs)
        self.strategy = strategy
        self.max_r, self.max_t = max_r, max_t
        self.inc = {c: 0 for c, _, _ in specs}      # current incarnation
        self.up = {c: False for c, _, _ in specs}
        self.restarts: list[int] = []               # rounds of restarts
        self.terminated = False
        self.rnd = 0

    # -- child plumbing -------------------------------------------------
    def _host(self, child: int):
        for c, h, _ in self.specs:
            if c == child:
                return h
        return None

    def _type(self, child: int):
        for c, _, t in self.specs:
            if c == child:
                return t
        return None

    def _start(self, child: int) -> None:
        self.inc[child] += 1
        self.up[child] = True
        self.forward(self._host(child),
                     [gen.OP_START, child, self.inc[child]])

    def _stop(self, child: int) -> None:
        self.up[child] = False
        self.forward(self._host(child),
                     [gen.OP_STOP, child, self.inc[child]])

    def start_all(self) -> None:
        for c, _, _ in self.specs:          # start order = spec order
            self._start(c)

    # -- the supervisor loop --------------------------------------------
    def process(self, rnd: int) -> None:
        self.rnd = rnd
        for _src, words in self.drain():
            if words[0] != gen.OP_EXIT or self.terminated:
                continue
            child, inc, reason = words[1], words[2], words[3]
            if child not in self.inc or inc != self.inc[child]:
                continue                    # stale incarnation: ignore
            if not self.up[child]:
                continue
            self.up[child] = False
            rtype = self._type(child)
            if rtype == TEMPORARY:
                # temporary children are never restarted and their spec
                # is discarded (OTP supervisor reference)
                self.specs = [s for s in self.specs if s[0] != child]
                del self.inc[child], self.up[child]
                continue
            if rtype == TRANSIENT and reason == NORMAL:
                continue                    # normal exit: no restart
            self._restart(child)

    def _restart(self, child: int) -> None:
        self.restarts.append(self.rnd)
        # prune to the intensity window: entries older than MaxT can
        # never count again, so the history stays O(MaxR) on long soaks
        window = [r for r in self.restarts if r > self.rnd - self.max_t]
        self.restarts = window
        if len(window) > self.max_r:
            # intensity exceeded: give up — stop all children (reverse
            # start order), terminate the supervisor itself
            for c, _, _ in reversed(self.specs):
                if self.up[c]:
                    self._stop(c)
            self.terminated = True
            return
        order = [c for c, _, _ in self.specs]
        if self.strategy == ONE_FOR_ONE:
            self._start(child)
            return
        idx = order.index(child)
        victims = order[idx + 1:] if self.strategy == REST_FOR_ONE \
            else [c for c in order if c != child]
        for c in reversed(victims):         # stop in reverse start order
            if self.up[c]:
                self._stop(c)
        for c in order:                     # restart in start order
            if c == child or c in victims:
                self._start(c)

    # -- admin API (supervisor:which_children/3 etc.) -------------------
    def which_children(self):
        return [(c, self.inc[c], self.up[c]) for c, _, _ in self.specs]

    def count_children(self):
        return {"specs": len(self.specs),
                "active": sum(self.up.values())}

    def restart_child(self, child: int) -> bool:
        if not self.up.get(child, True):
            self._start(child)
            return True
        return False

    def delete_child(self, child: int) -> bool:
        if self.up.get(child):
            return False                    # only stopped children
        self.specs = [s for s in self.specs if s[0] != child]
        self.inc.pop(child, None)
        self.up.pop(child, None)
        return True
