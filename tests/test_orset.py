"""Membership-set semantics tests, mirroring the observable behavior of
reference src/partisan_membership_set.erl (add/remove/merge/compare,
rejoin-with-fresh-incarnation staleness — moduledoc :23-60)."""

import jax.numpy as jnp

from partisan_tpu.ops import orset


def test_fresh_knows_only_self():
    v = orset.fresh_views(4)
    m = orset.members(v)
    assert m.tolist() == [
        [True, False, False, False],
        [False, True, False, False],
        [False, False, True, False],
        [False, False, False, True],
    ]


def test_add_remove_readd():
    v = orset.fresh_views(3)[0]       # node 0's view
    v = orset.add(v, 1, 1)
    assert orset.members(v).tolist() == [True, True, False]
    v = orset.remove(v, 1)
    assert orset.members(v).tolist() == [True, False, False]
    # Re-add at same incarnation is stale (observed-remove wins):
    v2 = orset.add(v, 1, 1)
    assert orset.members(v2).tolist() == [True, False, False]
    # Fresh incarnation rejoins:
    v3 = orset.add(v, 1, 2)
    assert orset.members(v3).tolist() == [True, True, False]


def test_merge_commutative_idempotent():
    a = orset.add(orset.fresh_views(3)[0], 1, 1)
    b = orset.remove(orset.add(orset.fresh_views(3)[2], 1, 1), 1)
    ab, ba = orset.merge(a, b), orset.merge(b, a)
    assert bool(orset.equal(ab, ba))
    assert bool(orset.equal(orset.merge(ab, ab), ab))
    # Remove observed the add -> member gone after merge.
    assert orset.members(ab).tolist() == [True, False, True]


def test_compare_joiners_leavers():
    old = orset.fresh_views(3)[0]
    new = orset.add(old, 1, 1)
    joiners, leavers = orset.compare(old, new)
    assert joiners.tolist() == [False, True, False]
    assert not bool(jnp.any(leavers))
    j2, l2 = orset.compare(new, orset.remove(new, 0))
    assert l2.tolist() == [True, False, False]
    assert not bool(jnp.any(j2))
