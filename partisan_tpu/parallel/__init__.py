"""Device-parallel execution: the node axis sharded over a TPU mesh.

The reference scales by adding BEAM nodes connected over TCP (its
distributed communication backend, SURVEY.md §5.8); the TPU-native
equivalent shards the simulated node axis across chips with
``jax.shard_map`` over a ``jax.sharding.Mesh`` and moves each round's
traffic with XLA collectives over ICI/DCN."""

from partisan_tpu.parallel.sharded import ShardComm, ShardedCluster, make_mesh  # noqa: F401
