"""OTP-runtime analogue tests: RPC (partisan_rpc/partisan_erpc), node
monitoring (partisan_monitor), remote refs (partisan_remote_ref), and the
service Stack (the rpc_test / monitor cases of partisan_SUITE.erl)."""

import jax.numpy as jnp
import pytest

from partisan_tpu import faults as faults_mod
from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config
from partisan_tpu.models.direct_mail import DirectMail
from partisan_tpu.models.stack import Stack
from partisan_tpu.otp import monitor as mon_mod
from partisan_tpu.otp import remote_ref, rpc as rpc_mod

N = 6

FNS = (lambda x: x + 1,          # fn 0: increment
       lambda x: x * 2,          # fn 1: double
       lambda x: jnp.int32(42))  # fn 2: constant


def build(extra=None, **cfg_kw):
    services = [rpc_mod.RpcService(FNS), mon_mod.MonitorService()]
    if extra is not None:
        services.append(extra)
    stack = Stack(services)
    cfg = Config(n_nodes=N, seed=13, inbox_cap=48, **cfg_kw)
    cl = Cluster(cfg, model=stack)
    st = cl.init()
    for i in range(1, N):
        st = st._replace(manager=cl.manager.join(cfg, st.manager, i, 0))
    st = cl.steps(st, 5)
    return cl, stack, st


def test_rpc_call_roundtrip():
    cl, stack, st = build()
    rpc = stack.models[0]
    rs, ref = rpc.call(stack.sub(st.model, 0), caller=2, dst=4, fn_id=1,
                       arg=21, timeout_rounds=10, now=int(st.rnd))
    st = st._replace(model=stack.replace_sub(st.model, 0, rs))
    st = cl.steps(st, 4)   # emit -> deliver -> reply -> deliver
    status, val = rpc.response(stack.sub(st.model, 0), 2, ref)
    assert status == "ok" and val == 42
    # freeing the slot allows reuse
    rs = rpc.free(stack.sub(st.model, 0), 2, ref)
    assert int(rs.status[2].sum()) == 0


def test_rpc_self_call_and_multicall():
    cl, stack, st = build()
    rpc = stack.models[0]
    rs, refs = rpc.multicall(stack.sub(st.model, 0), caller=1,
                             dsts=range(N), fn_id=0, arg=7,
                             timeout_rounds=10, now=int(st.rnd))
    st = st._replace(model=stack.replace_sub(st.model, 0, rs))
    st = cl.steps(st, 4)
    for ref in refs:
        status, val = rpc.response(stack.sub(st.model, 0), 1, ref)
        assert (status, val) == ("ok", 8)


def test_rpc_timeout_on_partition():
    cl, stack, st = build()
    rpc = stack.models[0]
    st = st._replace(faults=faults_mod.inject_partition(
        st.faults, [2], [4]))
    rs, ref = rpc.call(stack.sub(st.model, 0), caller=2, dst=4, fn_id=0,
                       arg=1, timeout_rounds=5, now=int(st.rnd))
    st = st._replace(model=stack.replace_sub(st.model, 0, rs))
    st = cl.steps(st, 8)
    status, val = rpc.response(stack.sub(st.model, 0), 2, ref)
    assert status == "badrpc_timeout" and val is None


def test_rpc_table_overflow_raises():
    cl, stack, st = build()
    rpc = stack.models[0]
    rs = stack.sub(st.model, 0)
    for i in range(rpc.cap):
        rs, _ = rpc.call(rs, 0, 1, 0, i, 10, int(st.rnd))
    with pytest.raises(RuntimeError):
        rpc.call(rs, 0, 1, 0, 99, 10, int(st.rnd))


def test_monitor_fires_down_once():
    cl, stack, st = build()
    mon = stack.models[1]
    ms = mon.monitor(stack.sub(st.model, 1), owner=0, target=3)
    st = st._replace(model=stack.replace_sub(st.model, 1, ms))
    st = cl.steps(st, 2)
    ms = stack.sub(st.model, 1)
    assert not bool(ms.down_sig[0, 3])
    st = st._replace(faults=faults_mod.crash(st.faults, 3))
    st = cl.steps(st, 2)
    ms, got = mon_mod.MonitorService.take_down(stack.sub(st.model, 1), 0, 3)
    assert got
    # one-shot: revive + re-crash does not fire again
    st = st._replace(model=stack.replace_sub(st.model, 1, ms),
                     faults=faults_mod.recover(st.faults, 3))
    st = cl.steps(st, 2)
    st = st._replace(faults=faults_mod.crash(st.faults, 3))
    st = cl.steps(st, 2)
    _, got2 = mon_mod.MonitorService.take_down(stack.sub(st.model, 1), 0, 3)
    assert not got2


def test_monitor_on_dead_node_fires_immediately():
    cl, stack, st = build()
    mon = stack.models[1]
    st = st._replace(faults=faults_mod.crash(st.faults, 5))
    st = cl.steps(st, 2)   # detector observes the crash
    ms = mon.monitor(stack.sub(st.model, 1), owner=2, target=5)
    _, got = mon_mod.MonitorService.take_down(ms, 2, 5)
    assert got


def test_monitor_nodes_down_and_up():
    cl, stack, st = build()
    mon = stack.models[1]
    ms = mon.monitor_nodes(stack.sub(st.model, 1), node=0)
    st = st._replace(model=stack.replace_sub(st.model, 1, ms))
    st = cl.steps(st, 1)
    st = st._replace(faults=faults_mod.crash(st.faults, 4))
    st = cl.steps(st, 2)
    ms, down = mon_mod.MonitorService.take_nodedown(
        stack.sub(st.model, 1), 0, 4)
    assert down
    st = st._replace(model=stack.replace_sub(st.model, 1, ms),
                     faults=faults_mod.recover(st.faults, 4))
    st = cl.steps(st, 2)
    _, up = mon_mod.MonitorService.take_nodeup(stack.sub(st.model, 1), 0, 4)
    assert up


def test_stack_composes_services_with_app_model():
    app = DirectMail()
    cl, stack, st = build(extra=app)
    st = st._replace(model=stack.replace_sub(
        st.model, 2, app.broadcast(stack.sub(st.model, 2), 0, 0)))
    rpc = stack.models[0]
    rs, ref = rpc.call(stack.sub(st.model, 0), 3, 5, 2, 0, 10, int(st.rnd))
    st = st._replace(model=stack.replace_sub(st.model, 0, rs))
    st = cl.steps(st, 10)
    assert float(app.coverage(stack.sub(st.model, 2),
                              st.faults.alive, 0)) == 1.0
    status, val = rpc.response(stack.sub(st.model, 0), 3, ref)
    assert (status, val) == ("ok", 42)


def test_remote_ref_formats():
    for fmt in (remote_ref.FORMAT_IMPROPER, remote_ref.FORMAT_TUPLE,
                remote_ref.FORMAT_URI):
        r = remote_ref.encode(3, 7, fmt=fmt)
        d = remote_ref.decode(r)
        assert d == {"node": 3, "kind": "pid", "target": 7}
        assert remote_ref.node_of(r) == 3
        assert remote_ref.is_local(r, 3) and not remote_ref.is_local(r, 4)
    nm = remote_ref.encode(2, name="rpc_backend",
                           fmt=remote_ref.FORMAT_URI)
    assert remote_ref.decode(nm)["target"] == "rpc_backend"
    node, proc = remote_ref.unpack(remote_ref.pack(9, 123))
    assert (node, proc) == (9, 123)
    with pytest.raises(ValueError):
        remote_ref.pack(0, 1 << 13)


def test_rpc_cast_executes_without_reply():
    cl, stack, st = build()
    rpc = stack.models[0]
    rs = rpc.cast(stack.sub(st.model, 0), caller=1, dst=4, fn_id=0,
                  arg=5, now=int(st.rnd))
    st = st._replace(model=stack.replace_sub(st.model, 0, rs))
    st = cl.steps(st, 1)
    # slot freed after emission; no response ever tracked
    rs = stack.sub(st.model, 0)
    assert int(rs.status[1].sum()) == 0
    st = cl.steps(st, 4)
    rs = stack.sub(st.model, 0)
    assert int(rs.status[1].sum()) == 0


def test_edge_monitor_fires_on_partition_and_heal():
    """Channel-down machinery (reference :1489-1535 conn-EXIT pruning
    firing channel-down callbacks; on_down/3): an edge subscription
    delivers edge_down when the (owner, peer) edge partitions while
    BOTH nodes stay up, and edge_up when it heals."""
    cl, stack, st = build()
    mon = stack.models[1]
    ms = mon.monitor_edge(stack.sub(st.model, 1), owner=1, peer=4)
    st = st._replace(model=stack.replace_sub(st.model, 1, ms))
    st = cl.steps(st, 1)
    st = st._replace(faults=faults_mod.inject_partition(
        st.faults, [1], [4]))
    st = cl.steps(st, 2)
    ms, down = mon_mod.MonitorService.take_edge_down(
        stack.sub(st.model, 1), 1, 4)
    assert down
    # both endpoints are still alive — this is a CHANNEL down, not DOWN
    assert bool(st.faults.alive[1]) and bool(st.faults.alive[4])
    _, node_down = mon_mod.MonitorService.take_down(
        stack.sub(st.model, 1), 1, 4)
    assert not node_down
    st = st._replace(model=stack.replace_sub(st.model, 1, ms),
                     faults=faults_mod.resolve_partition(st.faults))
    st = cl.steps(st, 2)
    _, up = mon_mod.MonitorService.take_edge_up(
        stack.sub(st.model, 1), 1, 4)
    assert up


def test_demonitor_flush_and_info_options():
    cl, stack, st = build()
    mon = stack.models[1]
    ms = mon.monitor(stack.sub(st.model, 1), owner=0, target=3)
    st = st._replace(model=stack.replace_sub(st.model, 1, ms))
    st = st._replace(faults=faults_mod.crash(st.faults, 3))
    st = cl.steps(st, 2)                      # DOWN fires, pending
    ms = stack.sub(st.model, 1)
    # flush=False keeps the pending DOWN (OTP default demonitor)
    ms2, existed = mon.demonitor(ms, 0, 3, flush=False, info=True)
    assert existed is False                   # already fired: one-shot
    _, got = mon_mod.MonitorService.take_down(ms2, 0, 3)
    assert got                                # signal survived
    # flush=True removes it
    ms3 = mon.demonitor(ms, 0, 3, flush=True)
    _, got2 = mon_mod.MonitorService.take_down(ms3, 0, 3)
    assert not got2


def test_owner_crash_recover_no_spurious_edge_up():
    """An edge subscriber that crashes and recovers must NOT receive an
    edge_up for an edge that never changed (prev_reach tracks the pure
    edge state; owner liveness only gates delivery)."""
    cl, stack, st = build()
    mon = stack.models[1]
    ms = mon.monitor_edge(stack.sub(st.model, 1), owner=1, peer=4)
    st = st._replace(model=stack.replace_sub(st.model, 1, ms))
    st = cl.steps(st, 2)
    st = st._replace(faults=faults_mod.crash(st.faults, 1))
    st = cl.steps(st, 2)
    st = st._replace(faults=faults_mod.recover(st.faults, 1))
    st = cl.steps(st, 2)
    ms, up = mon_mod.MonitorService.take_edge_up(
        stack.sub(st.model, 1), 1, 4)
    assert not up
    _, down = mon_mod.MonitorService.take_edge_down(ms, 1, 4)
    assert not down
