"""Device-resident health plane: topology snapshots of the live overlay
computed INSIDE the jitted round — the observatory for the one thing the
other planes cannot see.

Partisan's value proposition IS the overlay (ATC'19: pluggable
partial-view topologies measured to 1024 nodes), yet the rebuild's only
component counter was a host-side numpy BFS (O(n), feasible only at
small n) and its convergence poll burned a host transfer per check.
The metrics plane (metrics.py) counts dead messages and the latency
plane (latency.py) times live ones; this module closes the triad by
watching the graph they travel on, under the same discipline
(ARCHITECTURE.md "Observability"):

- **statically shaped** — every ``Config.health`` rounds (the snapshot
  cadence; 0 = off) the round body computes one topology snapshot and
  writes it into a ring of ``Config.health_ring`` slots,
- **replicated under sharding** — the snapshot's VALUES are identical
  on every shard, but (since the sharded-by-default overlay flip) they
  are computed SEGMENT-LOCALLY: each shard works on its own
  ``[n_local, cap]`` neighbor rows and the shards exchange only label
  VECTORS per iteration (the halo — see :func:`component_count_sharded`)
  plus scalar/histogram reductions.  The old formulation all-gathered
  the whole ``[n_global, cap]`` neighbor table onto every shard — the
  first O(n·cap) replicated tensor that cannot fit at 1M nodes
  (ROADMAP item 2); no kernel here may materialize a full-node-axis
  rank-2 tensor (the jaxlint ``replicated-node-axis`` rule gates this,
  partisan_tpu/lint/rules.py),
- **free when disabled** — ``Config.health=0`` (the default) keeps the
  ClusterState leaf an empty ``()`` pytree: no arrays, no ops, and the
  round trace is bit-identical to pre-health behavior.

Per snapshot:

- **connected-component count** of the undirected union of live
  overlay out-edges, via pointer-jumping min-label propagation —
  O(log n) gather/scatter steps on device, replacing the host BFS
  (the component count is the 100k bootstrap's key health signal:
  BENCH_NOTES "6-14 disconnected components at boot end"),
- **isolated-alive count** — alive nodes with zero live out-edges (the
  conn-count-to-zero isolation signal,
  partisan_peer_connections.erl:1489-1535),
- **per-node out-degree histogram** (+ min/max over alive nodes),
- **directed-edge symmetry-violation count** — live edges i->j whose
  reverse j->i is absent (HyParView active views should be symmetric;
  a persistent violation is a half-open connection),
- **churn counters** — join/leave (overlay connectivity gained/lost)
  and up/down (alive-mask flips) diffs since the previous snapshot.

The headline artifact is a packed **health digest word** — one int32
carrying (one-component | no-isolates | min-degree>=target |
coverage-complete | valid) predicate bits plus the clamped component
and isolate counts — so convergence checks and bench polling transfer
ONE scalar instead of running numpy graph walks (``scenarios._converge``
polls it when the plane is on).

Host side mirrors the sibling planes: :func:`snapshot`/:func:`rows`
decode the ring, ``telemetry.replay_health_events`` turns snapshot
transitions into ``partisan.health.*`` bus events, and
``tools/health_report.py`` exports JSON lines.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import Array

from partisan_tpu.config import Config

# Out-degree histogram bins: degree d lands in bin min(d, DEG_BINS-1);
# the last bin absorbs everything wider (HyParView active views are <=
# active_max ~ 6; SCAMP partial views can exceed the bins — the min/max
# series keep the exact extremes).
DEG_BINS = 16

# Digest word layout (int32, bit 31 unused so the word stays positive).
DIGEST_ONE_COMPONENT = 1 << 0   # exactly one connected component
DIGEST_NO_ISOLATES = 1 << 1     # no alive node with zero live out-edges
DIGEST_MIN_DEGREE = 1 << 2      # min alive out-degree >= target
DIGEST_COVERAGE = 1 << 3        # model coverage complete (slot 0)
DIGEST_VALID = 1 << 4           # a snapshot has been recorded
_COMP_SHIFT, _COMP_MASK = 8, 0xFFFF   # clamped component count
_ISO_SHIFT, _ISO_MASK = 24, 0x7F      # clamped isolated-alive count


class HealthState(NamedTuple):
    """Ring of topology snapshots + the latest packed digest.

    ``R`` = Config.health_ring; one slot per snapshot (every
    ``Config.health`` rounds), ``rnd[slot] == -1`` marks a slot never
    written.  ``prev_alive``/``prev_conn`` are the previous snapshot's
    reference vectors for the churn diffs (global, replicated)."""

    rnd: Array          # int32[R] — round the snapshot describes (-1 = empty)
    components: Array   # int32[R] — connected components of the live overlay
    isolated: Array     # int32[R] — alive nodes with zero live out-edges
    deg_hist: Array     # int32[R, DEG_BINS] — alive out-degree histogram
    deg_min: Array      # int32[R] — min live out-degree over alive nodes
    deg_max: Array      # int32[R] — max live out-degree over alive nodes
    sym_violations: Array  # int32[R] — live edges whose reverse is absent
    joins: Array        # int32[R] — nodes newly overlay-connected this window
    leaves: Array       # int32[R] — nodes that lost all overlay edges
    ups: Array          # int32[R] — dead->alive flips this window
    downs: Array        # int32[R] — alive->dead flips this window
    digests: Array      # int32[R] — the packed digest word per snapshot
    digest: Array       # int32 scalar — LATEST digest (the one-scalar poll)
    prev_alive: Array   # bool[n_global] — alive mask at the last snapshot
    prev_conn: Array    # bool[n_global] — alive & degree>0 at last snapshot


def enabled(cfg: Config) -> bool:
    return cfg.health > 0


def min_degree_target(cfg: Config) -> int:
    """Degree floor the digest's MIN_DEGREE bit asserts: HyParView's
    active_min (include/partisan.hrl:204-217) under the hyparview
    manager, else 1 (any overlay member should keep an edge)."""
    if cfg.peer_service_manager == "hyparview":
        return cfg.hyparview.active_min
    return 1


def init(cfg: Config) -> HealthState:
    R = cfg.health_ring

    def z(*shape):
        return jnp.zeros(shape, jnp.int32)

    return HealthState(
        rnd=jnp.full((R,), -1, jnp.int32),
        components=z(R), isolated=z(R), deg_hist=z(R, DEG_BINS),
        deg_min=z(R), deg_max=z(R), sym_violations=z(R),
        joins=z(R), leaves=z(R), ups=z(R), downs=z(R), digests=z(R),
        digest=jnp.int32(0),
        prev_alive=jnp.zeros((cfg.n_nodes,), jnp.bool_),
        prev_conn=jnp.zeros((cfg.n_nodes,), jnp.bool_),
    )


# ---------------------------------------------------------------------------
# Pure graph kernels (global arrays; shard-agnostic — callers gather)
# ---------------------------------------------------------------------------

def live_edges(nbrs: Array, alive: Array,
               partition: Array | None = None) -> Array:
    """bool[n, K]: out-edge slots that are live — a valid neighbor id,
    BOTH endpoints alive (a crashed peer's socket is gone), and the
    edge not severed by a partition (``partition`` is faults.py's
    groups vector int32[n] or dense matrix bool[n, n]; None = no
    partition).  The stochastic link_drop is NOT applied — it models
    per-message loss, not a severed connection."""
    n = alive.shape[0]
    nc = jnp.clip(nbrs, 0, n - 1)
    live = (nbrs >= 0) & alive[:, None] & alive[nc]
    if partition is not None and getattr(partition, "ndim", 0) > 0:
        if partition.ndim == 2:
            live = live & ~partition[
                jnp.arange(n, dtype=jnp.int32)[:, None], nc]
        else:
            live = live & (partition[:, None] == partition[nc])
    return live


def component_count(nbrs: Array, alive: Array,
                    partition: Array | None = None) -> tuple[Array, Array]:
    """Connected components of the undirected union of live out-edges.

    Pointer-jumping min-label propagation, FastSV-style (Zhang/Azad/Hu
    2020's linear-algebraic Shiloach-Vishkin): each node carries a
    parent pointer ``f`` into a min-forest; one iteration shortcuts
    (``f[f]``), aggressively hooks each endpoint onto the other's
    GRANDPARENT, and stochastically hooks each endpoint's PARENT onto
    the other's grandparent — hooking whole trees, not single nodes,
    which is what makes ceil(log2 n)+4 iterations converge on ANY
    topology (a naive relax-and-jump creeps O(n) on a permuted path —
    measured 24k iterations at n=100k where this update takes 17).
    Isolated alive nodes are singleton components; dead and
    partition-severed edges are excluded — exactly the host BFS
    oracle's semantics (tests/support.components).

    Returns ``(labels int32[n], count int32)``: ``labels[i]`` is the
    minimum alive id in i's component (own id for dead nodes)."""
    n = alive.shape[0]
    ids = jnp.arange(n, dtype=jnp.int32)
    if nbrs.shape[1] == 0 or n == 1:
        return ids, jnp.sum(alive, dtype=jnp.int32)
    nc = jnp.clip(nbrs, 0, n - 1)
    live = live_edges(nbrs, alive, partition)
    # per-edge endpoint target slots; index n = out-of-range: dropped
    tgt_v = jnp.where(live, nc, n).reshape(-1)

    def body(_, f):
        g = f[f]                                        # grandparent
        m = jnp.minimum(f, g)                           # shortcut
        gv = jnp.where(live, g[nc], n)                  # nbr grandparents
        gb = jnp.broadcast_to(g[:, None], live.shape)
        # aggressive hooking, both edge directions
        m = jnp.minimum(m, jnp.min(gv, axis=1))
        m = m.at[tgt_v].min(gb.reshape(-1), mode="drop")
        # stochastic hooking: my PARENT adopts their grandparent (and
        # symmetrically) — the tree-onto-tree step
        fu = jnp.where(live, jnp.broadcast_to(f[:, None], live.shape),
                       n).reshape(-1)
        m = m.at[fu].min(gv.reshape(-1), mode="drop")
        fv = jnp.where(live, f[nc], n).reshape(-1)
        m = m.at[fv].min(gb.reshape(-1), mode="drop")
        return m

    iters = int(math.ceil(math.log2(max(n, 2)))) + 4
    lbl = jax.lax.fori_loop(0, iters, body, ids)
    count = jnp.sum((lbl == ids) & alive, dtype=jnp.int32)
    return lbl, count


def out_degrees(nbrs: Array, alive: Array,
                partition: Array | None = None) -> Array:
    """int32[n]: live out-degree per node (0 for dead nodes)."""
    return jnp.sum(live_edges(nbrs, alive, partition), axis=1,
                   dtype=jnp.int32)


# ---------------------------------------------------------------------------
# Segment-local kernels (sharded-by-default path): each shard touches
# only its own [n_local, K] neighbor rows; cross-shard state is label /
# alive VECTORS (O(n_global) words) and scalar reductions — never a
# replicated [n_global, K] matrix.  With LocalComm every collective is
# the identity, so single-device and sharded runs share ONE code path
# and are bit-identical by construction (min/max reductions commute).
# ---------------------------------------------------------------------------

def live_edges_local(nbrs_local: Array, alive_local: Array,
                     alive_global: Array, gids: Array,
                     partition: Array | None = None) -> Array:
    """bool[n_local, K]: :func:`live_edges` for one shard's rows.
    ``gids`` are the rows' global ids; ``alive_global`` is the
    replicated global mask (a vector — remote endpoints are read from
    it, never from a gathered per-node matrix)."""
    n = alive_global.shape[0]
    nc = jnp.clip(nbrs_local, 0, n - 1)
    live = (nbrs_local >= 0) & alive_local[:, None] & alive_global[nc]
    if partition is not None and getattr(partition, "ndim", 0) > 0:
        if partition.ndim == 2:
            live = live & ~partition[gids[:, None], nc]
        else:
            live = live & (partition[gids][:, None] == partition[nc])
    return live


def component_count_sharded(nbrs_local: Array, alive_global: Array,
                            comm, partition: Array | None = None
                            ) -> tuple[Array, Array]:
    """Segment-local FastSV with halo exchange: the sharded form of
    :func:`component_count`, bit-identical to it by construction.

    Each shard carries labels only for its OWN rows (``f_l`` int32
    [n_local]) and pointer-jumps over its local ``[n_local, K]`` edges.
    Per iteration the shards exchange exactly two label vectors:

    - the **halo gather** — ``comm.gather_vec(f_l)`` assembles the
      global label vector so local edges can read the labels of the
      remote neighbors they reference (every boundary label, O(n)
      int32 words — vs the O(n·K) neighbor matrix the gathered
      formulation replicated),
    - the **halo reduce** — each shard scatter-mins its hook proposals
      for REMOTE nodes (a tree may hook onto a grandparent owned by
      another shard) into a full-range proposal vector, met elementwise
      across shards by ``comm.allmin`` and sliced back to the local
      range.

    min is commutative and associative, so decomposing the gathered
    update into local-shortcut + cross-shard-proposal parts changes
    nothing: after every iteration the concatenated ``f_l`` equals the
    gathered version's ``f`` exactly — which is what makes the health
    digest bit-identical between single-chip and sharded runs
    (tests/test_sharded_health.py gates this against the BFS oracle).

    Returns ``(labels int32[n_local], count int32)``; the count is
    allsum-reduced (replicated)."""
    n = alive_global.shape[0]
    n_local, K = nbrs_local.shape
    gids = comm.local_ids()
    alive_l = jax.lax.dynamic_slice(alive_global, (comm.node_offset,),
                                    (comm.n_local,))
    if K == 0 or n == 1:
        return gids, comm.allsum(jnp.sum(alive_l, dtype=jnp.int32))
    nc = jnp.clip(nbrs_local, 0, n - 1)
    live = live_edges_local(nbrs_local, alive_l, alive_global, gids,
                            partition)
    # per-edge endpoint target slots; index n = out-of-range: dropped
    tgt_v = jnp.where(live, nc, n).reshape(-1)

    def body(_, f_l):
        f_g = comm.gather_vec(f_l)                  # [n] — the halo
        g_g = f_g[f_g]                              # grandparents [n]
        g_l = jax.lax.dynamic_slice(g_g, (comm.node_offset,),
                                    (comm.n_local,))
        m = jnp.minimum(f_l, g_l)                   # shortcut
        gv = jnp.where(live, g_g[nc], n)            # nbr grandparents
        gb = jnp.broadcast_to(g_l[:, None], live.shape)
        # aggressive hooking, local side
        m = jnp.minimum(m, jnp.min(gv, axis=1))
        # hook proposals for (possibly remote) targets: endpoint,
        # my parent, their parent — same three scatters as the
        # gathered body, landing in a full-range proposal vector
        prop = jnp.full((n,), n, jnp.int32)
        prop = prop.at[tgt_v].min(gb.reshape(-1), mode="drop")
        fu = jnp.where(live, jnp.broadcast_to(f_l[:, None], live.shape),
                       n).reshape(-1)
        prop = prop.at[fu].min(gv.reshape(-1), mode="drop")
        fv = jnp.where(live, f_g[nc], n).reshape(-1)
        prop = prop.at[fv].min(gb.reshape(-1), mode="drop")
        prop = comm.allmin(prop)                    # the halo reduce
        return jnp.minimum(m, jax.lax.dynamic_slice(
            prop, (comm.node_offset,), (comm.n_local,)))

    iters = int(math.ceil(math.log2(max(n, 2)))) + 4
    lbl = jax.lax.fori_loop(0, iters, body, gids)
    count = comm.allsum(jnp.sum((lbl == gids) & alive_l,
                                dtype=jnp.int32))
    return lbl, count


def symmetry_violations_sharded(nbrs_local: Array, alive_global: Array,
                                comm,
                                partition: Array | None = None) -> Array:
    """Sharded :func:`symmetry_violations`: live directed edges i->j
    with no j->i entry in j's view.  The back-edge check needs REMOTE
    rows, but never a whole remote table: one neighbor-table COLUMN at
    a time is exchanged as a global [n] vector (K bounded halo reads
    per snapshot), and each shard compares only its own [n_local, K]
    edges against it — O(n·K) exchanged words and O(n_local·K²) local
    work, no [n_global, K] tensor anywhere.  Allsum-reduced
    (replicated)."""
    n = alive_global.shape[0]
    n_local, K = nbrs_local.shape
    if K == 0:
        return comm.allsum(jnp.int32(0))
    gids = comm.local_ids()
    alive_l = jax.lax.dynamic_slice(alive_global, (comm.node_offset,),
                                    (comm.n_local,))
    nc = jnp.clip(nbrs_local, 0, n - 1)
    live = live_edges_local(nbrs_local, alive_l, alive_global, gids,
                            partition)
    me = gids[:, None]

    def slot(s, has):
        col = comm.gather_vec(jax.lax.dynamic_slice_in_dim(
            nbrs_local, s, 1, axis=1)[:, 0])            # [n] column s
        return has | (col[nc] == me)

    has_back = jax.lax.fori_loop(
        0, K, slot, jnp.zeros((n_local, K), jnp.bool_))
    return comm.allsum(jnp.sum(live & ~has_back, dtype=jnp.int32))


# Above this many [n, K, K] elements the symmetry check runs slot-wise
# (O(n·K) memory per step instead of one O(n·K²) gather): partial-view
# overlays (hyparview K ~ 6 at 100k = 4.9M) take the one-shot; wide
# views (scamp partial_max 64 at 100k = 410M, fullmesh K = n) must not
# materialize the cube.
SYM_ONESHOT_ELEMS = 1 << 24


def symmetry_violations(nbrs: Array, alive: Array,
                        partition: Array | None = None) -> Array:
    """int32: live directed edges i->j with no j->i entry in j's view
    (HyParView active views should be symmetric — a violation is a
    half-open connection one side will eventually disconnect)."""
    n = alive.shape[0]
    K = nbrs.shape[1]
    if K == 0:
        return jnp.int32(0)
    nc = jnp.clip(nbrs, 0, n - 1)
    live = live_edges(nbrs, alive, partition)
    ids = jnp.arange(n, dtype=jnp.int32)
    if n * K * K <= SYM_ONESHOT_ELEMS:
        back = nbrs[nc]                              # [n, K, K]
        has_back = jnp.any(back == ids[:, None, None], axis=-1)
        return jnp.sum(live & ~has_back, dtype=jnp.int32)

    def slot(s, acc):
        back_s = nbrs[nc[:, s]]                      # [n, K]
        has = jnp.any(back_s == ids[:, None], axis=1)
        return acc + jnp.sum(live[:, s] & ~has, dtype=jnp.int32)

    return jax.lax.fori_loop(0, K, slot, jnp.int32(0))


def degree_histogram(deg: Array, alive: Array) -> Array:
    """int32[DEG_BINS]: alive nodes' out-degrees, last bin absorbing
    degrees >= DEG_BINS-1."""
    b = jnp.clip(deg, 0, DEG_BINS - 1)
    onehot = (b[:, None] == jnp.arange(DEG_BINS)) & alive[:, None]
    return jnp.sum(onehot, axis=0, dtype=jnp.int32)


_BIG = jnp.int32(2**30)


def pack_digest(components: Array, isolated: Array, deg_min: Array,
                n_alive: Array, min_deg_target: int,
                cov_ok: Array) -> Array:
    """The packed one-scalar health word (see module doc for layout).
    An all-dead overlay reports unhealthy (zero components, degree
    floor unmet) but still VALID — the snapshot ran."""
    one = (components == 1).astype(jnp.int32)
    noiso = (isolated == 0).astype(jnp.int32)
    degok = ((deg_min >= min_deg_target) & (n_alive > 0)).astype(jnp.int32)
    cov = jnp.asarray(cov_ok).astype(jnp.int32)
    word = (one * DIGEST_ONE_COMPONENT
            | noiso * DIGEST_NO_ISOLATES
            | degok * DIGEST_MIN_DEGREE
            | cov * DIGEST_COVERAGE
            | DIGEST_VALID
            | jnp.clip(components, 0, _COMP_MASK) << _COMP_SHIFT
            | jnp.clip(isolated, 0, _ISO_MASK) << _ISO_SHIFT)
    return word.astype(jnp.int32)


def decode_digest(word: int) -> dict:
    """Host-side view of a packed digest word."""
    word = int(word)
    return {
        "valid": bool(word & DIGEST_VALID),
        "one_component": bool(word & DIGEST_ONE_COMPONENT),
        "no_isolates": bool(word & DIGEST_NO_ISOLATES),
        "min_degree_ok": bool(word & DIGEST_MIN_DEGREE),
        "coverage_complete": bool(word & DIGEST_COVERAGE),
        "components": (word >> _COMP_SHIFT) & _COMP_MASK,
        "isolated": (word >> _ISO_SHIFT) & _ISO_MASK,
    }


def healthy(word: int) -> bool:
    """All four predicate bits set on a valid digest."""
    bits = (DIGEST_VALID | DIGEST_ONE_COMPONENT | DIGEST_NO_ISOLATES
            | DIGEST_MIN_DEGREE | DIGEST_COVERAGE)
    return (int(word) & bits) == bits


# The OVERLAY-health bit set (coverage excluded — coverage describes a
# workload, not the graph): the single definition the healing
# controller's degraded predicate (control.py), the A/B heal oracle
# (scenarios.control_ab) and the tests all key on, so the actuation
# predicate and its evidence cannot drift.
OVERLAY_BITS = (DIGEST_ONE_COMPONENT | DIGEST_NO_ISOLATES
                | DIGEST_MIN_DEGREE)


def overlay_ok(word: int) -> bool:
    """Valid digest whose one-component / no-isolates / min-degree
    bits are ALL set — the graph-health predicate, coverage aside."""
    bits = DIGEST_VALID | OVERLAY_BITS
    return (int(word) & bits) == bits


def digest_converged(word: int) -> bool:
    """The convergence predicate ``_converge`` polls: a recorded
    snapshot whose coverage bit is set."""
    bits = DIGEST_VALID | DIGEST_COVERAGE
    return (int(word) & bits) == bits


def digest_components(word: int) -> int:
    """Component count carried in the digest (clamped at 0xFFFF)."""
    return (int(word) >> _COMP_SHIFT) & _COMP_MASK


def digest(state) -> int | list:
    """ONE scalar device->host transfer: the latest packed digest word
    of a health-carrying ClusterState (0 = plane off or no snapshot
    yet).  A FLEET state (fleet.py — leading member axis on every leaf
    but rnd) returns the per-member list of digest words instead."""
    hs = getattr(state, "health", ())
    if hs == ():
        return 0
    word = jax.device_get(hs.digest)
    import numpy as np

    if np.ndim(word):
        return [int(w) for w in np.asarray(word)]
    return int(word)


# ---------------------------------------------------------------------------
# The snapshot writer (runs inside the jitted round, behind a lax.cond)
# ---------------------------------------------------------------------------

def record_snapshot(cfg: Config, comm, hs: HealthState, *, rnd: Array,
                    nbrs_local: Array, alive_global: Array,
                    cov_ok: Array,
                    partition: Array | None = None) -> HealthState:
    """Compute one topology snapshot and write it into the ring.

    ``nbrs_local`` is this shard's neighbor rows ([n_local, K], global
    ids); every graph kernel runs SEGMENT-LOCALLY over them — the
    cross-shard state is label/alive VECTORS (the FastSV halo) and
    allsum/allmin/allmax reductions, never a gathered [n_global, K]
    table — so each shard derives identical (replicated) ring values
    at O(n_local·K + n_global) resident words.  This is the health
    analogue of the metrics plane's allsum-before-write discipline,
    and the kernel the 1M-node budget (``bench.py --dry-1m``) keys on.
    ``alive_global`` arrives pre-masked by the active prefix under
    ``Config.width_operand`` (round_body passes the wire-stage alive),
    so snapshots match a native-width run's.  ``cov_ok`` is the
    cross-shard coverage-complete predicate round_body derives from the
    model (True when no model carries a coverage notion).  Runs behind
    a ``lax.cond`` in round_body — non-snapshot rounds pay nothing."""
    R = cfg.health_ring
    alive = alive_global
    gids = comm.local_ids()
    alive_l = jax.lax.dynamic_slice(alive, (comm.node_offset,),
                                    (comm.n_local,))

    _, comps = component_count_sharded(nbrs_local, alive, comm,
                                       partition)
    live_l = live_edges_local(nbrs_local, alive_l, alive, gids,
                              partition)
    deg_l = jnp.sum(live_l, axis=1, dtype=jnp.int32)   # [n_local]
    n_alive = comm.allsum(jnp.sum(alive_l, dtype=jnp.int32))
    iso = comm.allsum(jnp.sum(alive_l & (deg_l == 0), dtype=jnp.int32))
    hist = comm.allsum(degree_histogram(deg_l, alive_l))
    # min over ALIVE nodes only; an all-dead overlay reports 0/0
    dmin = jnp.where(n_alive > 0,
                     comm.allmin(jnp.min(jnp.where(alive_l, deg_l,
                                                   _BIG))),
                     jnp.int32(0))
    dmax = comm.allmax(jnp.max(jnp.where(alive_l, deg_l, 0)))
    sym = symmetry_violations_sharded(nbrs_local, alive, comm,
                                      partition)

    # Churn = diffs BETWEEN snapshots; the FIRST snapshot has no
    # predecessor window, so it only establishes the baseline (zero
    # churn) — otherwise every run's first window would report
    # spurious ups/joins against the zero-initialized reference
    # vectors (and fire a bogus churn bus event on a fault-free run).
    first = (hs.digest & DIGEST_VALID) == 0
    # connectivity vector: segment-local degrees, gathered back to the
    # replicated [n] reference vector the churn windows diff against
    conn = comm.gather_vec(alive_l & (deg_l > 0))

    def window(prev, now):
        return jnp.where(
            first, 0, jnp.sum(prev & now, dtype=jnp.int32))

    ups = window(~hs.prev_alive, alive)
    downs = window(hs.prev_alive, ~alive)
    joins = window(~hs.prev_conn, conn)
    leaves = window(hs.prev_conn, ~conn)

    word = pack_digest(comps, iso, dmin, n_alive,
                       min_degree_target(cfg), cov_ok)

    # Snapshot index: snapshots fire where (rnd+1) % health == 0, so
    # consecutive snapshots get consecutive slots regardless of cadence.
    idx = (rnd + 1) // cfg.health - 1
    slot = jnp.mod(idx, R)
    return HealthState(
        rnd=hs.rnd.at[slot].set(rnd),
        components=hs.components.at[slot].set(comps),
        isolated=hs.isolated.at[slot].set(iso),
        deg_hist=hs.deg_hist.at[slot].set(hist),
        deg_min=hs.deg_min.at[slot].set(dmin),
        deg_max=hs.deg_max.at[slot].set(dmax),
        sym_violations=hs.sym_violations.at[slot].set(sym),
        joins=hs.joins.at[slot].set(joins),
        leaves=hs.leaves.at[slot].set(leaves),
        ups=hs.ups.at[slot].set(ups),
        downs=hs.downs.at[slot].set(downs),
        digests=hs.digests.at[slot].set(word),
        digest=word,
        prev_alive=alive,
        prev_conn=conn,
    )


# ---------------------------------------------------------------------------
# Host-side readers (the metrics.snapshot/rows idiom)
# ---------------------------------------------------------------------------

_SERIES = ("components", "isolated", "deg_hist", "deg_min", "deg_max",
           "sym_violations", "joins", "leaves", "ups", "downs", "digests")


def snapshot(hs: HealthState) -> dict:
    """Decode the ring into per-snapshot series ordered by round (one
    device->host transfer, AFTER the scan — never inside it)."""
    import numpy as np

    from partisan_tpu.metrics import ring_order

    host = jax.device_get(hs)
    rnd = np.asarray(host.rnd)
    idx = ring_order(rnd)
    out: dict = {"rounds": rnd[idx]}
    for name in _SERIES:
        out[name] = np.asarray(getattr(host, name))[idx]
    return out


def transitions(snap: dict, *, churn_threshold: int = 1,
                falling: bool = False) -> list[dict]:
    """Derive the ring's DISCRETE overlay transitions — the single
    source of truth ``telemetry.replay_health_events`` (and through it
    the opslog journal) emits from.  One self-describing dict per
    transition, round-keyed:

    - ``partition_detected`` — component count rises above 1 AFTER some
      snapshot in the window showed one component (a cold bootstrap's
      half-built components are not a partition).  Edge-triggered.
    - ``overlay_healed`` — the count returns to 1 after a detected
      split.
    - ``churn`` — windowed join/leave/up/down totals at or above
      ``churn_threshold``; edge-triggered.
    - ``churn_settled`` (only with ``falling=True``) — the first
      window back below the threshold after a hot run: the falling
      edge the incident matcher closes churn spans on.
    """
    import numpy as np

    comps = np.asarray(snap["components"])
    rounds = np.asarray(snap["rounds"])
    churn_total = (np.asarray(snap["joins"]) + np.asarray(snap["leaves"])
                   + np.asarray(snap["ups"]) + np.asarray(snap["downs"]))
    out: list[dict] = []
    was_one = False
    split = False
    churn_hot = False
    for i, rnd in enumerate(rounds):
        c = int(comps[i])
        if split and c == 1:
            out.append({"kind": "overlay_healed", "round": int(rnd),
                        "components": c})
            split = False
        if was_one and not split and c > 1:
            out.append({"kind": "partition_detected", "round": int(rnd),
                        "components": c,
                        "isolated": int(snap["isolated"][i])})
            split = True
        was_one = was_one or c == 1
        hot = int(churn_total[i]) >= churn_threshold
        if hot and not churn_hot:
            out.append({"kind": "churn", "round": int(rnd),
                        "joins": int(snap["joins"][i]),
                        "leaves": int(snap["leaves"][i]),
                        "ups": int(snap["ups"][i]),
                        "downs": int(snap["downs"][i])})
        elif falling and churn_hot and not hot:
            out.append({"kind": "churn_settled", "round": int(rnd),
                        "quiet": int(churn_total[i])})
        churn_hot = hot
    return out


def rows(snap: dict) -> list[dict]:
    """JSON-lines-friendly view: one self-describing dict per snapshot
    (the ``BENCH_*.json`` idiom)."""
    out = []
    for i, r in enumerate(snap["rounds"]):
        out.append({
            "round": int(r),
            "components": int(snap["components"][i]),
            "isolated": int(snap["isolated"][i]),
            "degree": {"min": int(snap["deg_min"][i]),
                       "max": int(snap["deg_max"][i]),
                       "hist": snap["deg_hist"][i].astype(int).tolist()},
            "symmetry_violations": int(snap["sym_violations"][i]),
            "churn": {"joins": int(snap["joins"][i]),
                      "leaves": int(snap["leaves"][i]),
                      "ups": int(snap["ups"][i]),
                      "downs": int(snap["downs"][i])},
            "digest": decode_digest(snap["digests"][i]),
        })
    return out
