"""Building fixed-width message records (see types.py for the layout)."""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array

from partisan_tpu import types as T


def build(msg_words: int, kind: Array | int, src: Array, dst: Array, *,
          channel: Array | int = 0, ttl: Array | int = 0,
          clock: Array | int = 0, lane: Array | int = 0,
          flags: Array | int = 0, payload: tuple = ()) -> Array:
    """Build message records of shape broadcast(src, dst, ...) + [msg_words].

    A record whose ``dst`` is negative is marked empty (kind NONE) so
    callers can pass -1 destinations from unused sampling slots directly.
    """
    shape = jnp.broadcast_shapes(
        jnp.shape(kind), jnp.shape(src), jnp.shape(dst),
        jnp.shape(channel), jnp.shape(ttl), jnp.shape(clock),
        jnp.shape(lane), jnp.shape(flags),
        *(jnp.shape(p) for p in payload),
    )
    out = jnp.zeros(shape + (msg_words,), jnp.int32)
    dst = jnp.broadcast_to(jnp.asarray(dst, jnp.int32), shape)
    valid = dst >= 0
    kind = jnp.where(valid, jnp.asarray(kind, jnp.int32), 0)
    out = out.at[..., T.W_KIND].set(jnp.broadcast_to(kind, shape))
    out = out.at[..., T.W_SRC].set(jnp.broadcast_to(jnp.asarray(src, jnp.int32), shape))
    out = out.at[..., T.W_DST].set(jnp.where(valid, dst, 0))
    out = out.at[..., T.W_CHANNEL].set(jnp.broadcast_to(jnp.asarray(channel, jnp.int32), shape))
    out = out.at[..., T.W_TTL].set(jnp.broadcast_to(jnp.asarray(ttl, jnp.int32), shape))
    out = out.at[..., T.W_CLOCK].set(jnp.broadcast_to(jnp.asarray(clock, jnp.int32), shape))
    out = out.at[..., T.W_LANE].set(jnp.broadcast_to(jnp.asarray(lane, jnp.int32), shape))
    out = out.at[..., T.W_FLAGS].set(jnp.broadcast_to(jnp.asarray(flags, jnp.int32), shape))
    for i, p in enumerate(payload):
        out = out.at[..., T.HDR_WORDS + i].set(jnp.broadcast_to(jnp.asarray(p, jnp.int32), shape))
    return out


def is_kind(msgs: Array, kind: int) -> Array:
    """bool mask over [..., W] records."""
    return msgs[..., T.W_KIND] == kind
