"""partisan_gen_supervisor restart semantics OVER THE BRIDGE.

The reference ships a patched OTP supervisor
(priv/otp/24/partisan_gen_supervisor.erl, 1850 LoC) with a conformance
suite (test/partisan_supervisor_SUITE.erl, 3755 LoC).  This suite ports
~9 representative behaviors at the semantics level: a supervisor process
on one emulated BEAM node manages child processes hosted on OTHER nodes,
with START/STOP orders and EXIT notifications riding the real bridge
transport (the cross-node supervision partisan_gen_supervisor enables).

Covered semantics (OTP supervisor reference behavior):
- one_for_one: only the crashed child restarts,
- rest_for_one: the crashed child and those started AFTER it restart —
  later children stopped in reverse start order, restarted in order,
- one_for_all: every child restarts (stop reverse, start in order),
- maximum restart intensity (MaxR within MaxT): exceeding it makes the
  supervisor give up — stop ALL children, terminate,
- restart types: permanent (always), transient (only abnormal exits),
  temporary (never — and the child spec is discarded),
- which_children / count_children across restarts,
- restart_child / delete_child admin API,
- stale EXIT from a superseded incarnation is ignored.
"""

import pytest

from support import BridgeVM, bridge_rig

OP_START, OP_STOP, OP_EXIT = 10, 11, 12
NORMAL, CRASH = 0, 1
PERMANENT, TRANSIENT, TEMPORARY = 0, 1, 2

ONE_FOR_ONE, REST_FOR_ONE, ONE_FOR_ALL = "one_for_one", "rest_for_one", \
    "one_for_all"


class HostVM(BridgeVM):
    """A node hosting child processes: obeys START/STOP, reports EXITs."""

    def __init__(self, srv, sim_id):
        super().__init__(srv, sim_id)
        self.running = {}          # child_id -> incarnation
        self.log = []              # (op, child, inc) in receive order

    def process(self):
        for src, words in self.drain():
            op, child, inc = words[0], words[1], words[2]
            if op == OP_START:
                self.running[child] = inc
                self.log.append(("start", child, inc))
            elif op == OP_STOP:
                self.running.pop(child, None)
                self.log.append(("stop", child, inc))

    def kill(self, sup_id, child, reason=CRASH):
        """Child dies (test-injected): report EXIT to the supervisor with
        its incarnation — the monitor/link DOWN the reference delivers."""
        inc = self.running.pop(child, None)
        if inc is not None:
            self.forward(sup_id, [OP_EXIT, child, inc, reason])


class SupervisorVM(BridgeVM):
    """The partisan_gen_supervisor loop (one supervisor process)."""

    def __init__(self, srv, sim_id, specs, strategy=ONE_FOR_ONE,
                 max_r=3, max_t=20):
        """specs: ordered [(child_id, host_sim_id, restart_type)]."""
        super().__init__(srv, sim_id)
        self.specs = list(specs)
        self.strategy = strategy
        self.max_r, self.max_t = max_r, max_t
        self.inc = {c: 0 for c, _, _ in specs}       # current incarnation
        self.up = {c: False for c, _, _ in specs}
        self.restarts = []                           # rounds of restarts
        self.terminated = False
        self.rnd = 0

    # -- child plumbing -------------------------------------------------
    def _host(self, child):
        for c, h, _ in self.specs:
            if c == child:
                return h
        return None

    def _type(self, child):
        for c, _, t in self.specs:
            if c == child:
                return t
        return None

    def _start(self, child):
        self.inc[child] += 1
        self.up[child] = True
        self.forward(self._host(child), [OP_START, child, self.inc[child]])

    def _stop(self, child):
        self.up[child] = False
        self.forward(self._host(child), [OP_STOP, child, self.inc[child]])

    def start_all(self):
        for c, _, _ in self.specs:           # start order = spec order
            self._start(c)

    # -- the supervisor loop --------------------------------------------
    def process(self, rnd):
        self.rnd = rnd
        for _src, words in self.drain():
            if words[0] != OP_EXIT or self.terminated:
                continue
            child, inc, reason = words[1], words[2], words[3]
            if child not in self.inc or inc != self.inc[child]:
                continue                     # stale incarnation: ignore
            if not self.up[child]:
                continue
            self.up[child] = False
            rtype = self._type(child)
            if rtype == TEMPORARY:
                # temporary children are never restarted and their spec
                # is discarded (OTP supervisor reference)
                self.specs = [s for s in self.specs if s[0] != child]
                del self.inc[child], self.up[child]
                continue
            if rtype == TRANSIENT and reason == NORMAL:
                continue                     # normal exit: no restart
            self._restart(child)

    def _restart(self, child):
        self.restarts.append(self.rnd)
        window = [r for r in self.restarts if r > self.rnd - self.max_t]
        if len(window) > self.max_r:
            # intensity exceeded: give up — stop all children (reverse
            # start order), terminate the supervisor itself
            for c, _, _ in reversed(self.specs):
                if self.up[c]:
                    self._stop(c)
            self.terminated = True
            return
        order = [c for c, _, _ in self.specs]
        if self.strategy == ONE_FOR_ONE:
            self._start(child)
            return
        idx = order.index(child)
        victims = order[idx + 1:] if self.strategy == REST_FOR_ONE \
            else [c for c in order if c != child]
        for c in reversed(victims):          # stop in reverse start order
            if self.up[c]:
                self._stop(c)
        for c in order:                      # restart in start order
            if c == child or c in victims:
                self._start(c)

    # -- admin API (supervisor:which_children/3 etc.) -------------------
    def which_children(self):
        return [(c, self.inc[c], self.up[c]) for c, _, _ in self.specs]

    def count_children(self):
        return {"specs": len(self.specs),
                "active": sum(self.up.values())}

    def restart_child(self, child):
        if not self.up.get(child, True):
            self._start(child)
            return True
        return False

    def delete_child(self, child):
        if self.up.get(child):
            return False                     # only stopped children
        self.specs = [s for s in self.specs if s[0] != child]
        self.inc.pop(child, None)
        self.up.pop(child, None)
        return True


def _pump(sup, host, k=4, *, hosts=None):
    for _ in range(k):
        rnd = sup.step(1)
        for h in (hosts or [host]):
            h.process()
        sup.process(rnd)


def _rig(strategy, types=(PERMANENT, PERMANENT, PERMANENT), **kw):
    srv = bridge_rig(4)
    host = HostVM(srv, 1)
    sup = SupervisorVM(srv, 0,
                       [(10, 1, types[0]), (11, 1, types[1]),
                        (12, 1, types[2])],
                       strategy=strategy, **kw)
    sup.start_all()
    _pump(sup, host, 4)
    assert host.running == {10: 1, 11: 1, 12: 1}
    return srv, sup, host


def test_one_for_one_restarts_only_the_crashed_child():
    srv, sup, host = _rig(ONE_FOR_ONE)
    try:
        host.kill(sup.id, 11)
        _pump(sup, host, 6)
        assert host.running == {10: 1, 11: 2, 12: 1}
        # no STOP was ever sent; exactly one extra START (child 11 inc 2)
        assert ("stop", 10, 1) not in host.log
        assert host.log.count(("start", 11, 2)) == 1
    finally:
        srv.close()


def test_rest_for_one_restarts_crashed_and_later_children():
    srv, sup, host = _rig(REST_FOR_ONE)
    try:
        host.kill(sup.id, 11)
        _pump(sup, host, 6)
        assert host.running == {10: 1, 11: 2, 12: 2}    # 10 untouched
        tail = host.log[3:]        # after the initial starts
        # later child stopped first, then restarts in start order
        assert tail.index(("stop", 12, 1)) < tail.index(("start", 11, 2))
        assert tail.index(("start", 11, 2)) < tail.index(("start", 12, 2))
    finally:
        srv.close()


def test_one_for_all_restarts_everyone_stop_reverse_start_in_order():
    srv, sup, host = _rig(ONE_FOR_ALL)
    try:
        host.kill(sup.id, 11)
        _pump(sup, host, 6)
        assert host.running == {10: 2, 11: 2, 12: 2}
        tail = host.log[3:]
        # stops: reverse start order (12 then 10; 11 is already dead)
        assert tail.index(("stop", 12, 1)) < tail.index(("stop", 10, 1))
        # starts: spec order
        s = [e for e in tail if e[0] == "start"]
        assert s == [("start", 10, 2), ("start", 11, 2), ("start", 12, 2)]
    finally:
        srv.close()


def test_max_intensity_shutdown():
    """More than MaxR restarts within MaxT rounds: the supervisor stops
    every child and terminates (supervisor shutdown semantics)."""
    srv, sup, host = _rig(ONE_FOR_ONE, max_r=2, max_t=50)
    try:
        for _ in range(3):                   # 3 restarts > MaxR=2
            host.kill(sup.id, 11)
            _pump(sup, host, 4)
        assert sup.terminated
        assert host.running == {}            # all children stopped
        _pump(sup, host, 3)
        assert host.running == {}            # and nothing restarts
    finally:
        srv.close()


def test_intensity_window_expires():
    """Restarts spaced WIDER than MaxT don't accumulate: the supervisor
    keeps healing indefinitely."""
    srv, sup, host = _rig(ONE_FOR_ONE, max_r=1, max_t=6)
    try:
        for _ in range(3):
            host.kill(sup.id, 11)
            _pump(sup, host, 8)              # > MaxT rounds apart
        assert not sup.terminated
        assert host.running[11] == 4
    finally:
        srv.close()


def test_transient_child_not_restarted_on_normal_exit():
    srv, sup, host = _rig(ONE_FOR_ONE, types=(PERMANENT, TRANSIENT,
                                              PERMANENT))
    try:
        host.kill(sup.id, 11, reason=NORMAL)
        _pump(sup, host, 5)
        assert 11 not in host.running                 # not restarted
        assert sup.count_children() == {"specs": 3, "active": 2}
        # …but an ABNORMAL exit of a transient child does restart it
        assert sup.restart_child(11)
        _pump(sup, host, 4)
        host.kill(sup.id, 11, reason=CRASH)
        _pump(sup, host, 5)
        assert host.running[11] == 3
    finally:
        srv.close()


def test_temporary_child_never_restarted_and_spec_discarded():
    srv, sup, host = _rig(ONE_FOR_ONE, types=(PERMANENT, TEMPORARY,
                                              PERMANENT))
    try:
        host.kill(sup.id, 11, reason=CRASH)
        _pump(sup, host, 5)
        assert 11 not in host.running
        assert sup.count_children() == {"specs": 2, "active": 2}
    finally:
        srv.close()


def test_which_children_and_admin_api():
    srv, sup, host = _rig(ONE_FOR_ONE)
    try:
        host.kill(sup.id, 11)
        _pump(sup, host, 5)
        assert sup.which_children() == [(10, 1, True), (11, 2, True),
                                        (12, 1, True)]
        # delete refuses while running; works once stopped
        assert not sup.delete_child(12)
        sup._stop(12)
        _pump(sup, host, 3)
        assert sup.delete_child(12)
        assert sup.count_children() == {"specs": 2, "active": 2}
    finally:
        srv.close()


def test_stale_exit_from_old_incarnation_ignored():
    """A late EXIT carrying a superseded incarnation must not trigger a
    second restart (the Mref-generation pairing of the monitor layer)."""
    srv, sup, host = _rig(ONE_FOR_ONE)
    try:
        host.kill(sup.id, 11)                # EXIT inc=1
        _pump(sup, host, 5)
        assert host.running[11] == 2
        host.forward(sup.id, [OP_EXIT, 11, 1, CRASH])   # stale replay
        _pump(sup, host, 5)
        assert host.running[11] == 2         # unchanged
    finally:
        srv.close()


def test_rest_for_one_across_two_host_nodes():
    """Children hosted on DIFFERENT nodes: supervision orders ride the
    bridge transport across the cluster."""
    srv = bridge_rig(4)
    try:
        h1, h2 = HostVM(srv, 1), HostVM(srv, 2)
        sup = SupervisorVM(srv, 0, [(10, 1, PERMANENT), (11, 2, PERMANENT),
                                    (12, 1, PERMANENT)],
                           strategy=REST_FOR_ONE)
        sup.start_all()
        _pump(sup, h1, 4, hosts=[h1, h2])
        assert h1.running == {10: 1, 12: 1} and h2.running == {11: 1}
        h2.kill(sup.id, 11)
        _pump(sup, h1, 6, hosts=[h1, h2])
        assert h2.running == {11: 2}
        assert h1.running == {10: 1, 12: 2}  # 12 restarted, 10 untouched
        for vm in (h1, h2, sup):
            vm.close()
    finally:
        srv.close()
