"""Full-horizon telemetry spool (the ISSUE 19 acceptance suite):

1. the spool is BIT-DETERMINISTIC across execution regimes — a
   kill + fresh-engine-restore run and a ``pipeline_depth > 1`` run
   produce files byte-identical to the uninterrupted run's (the spool
   records only device-derived values at pinned chunk boundaries),
2. it FLIPS observability verdicts both directions: an incident whose
   every ring window expired is "unobservable" on ring evidence and a
   real "closed" span once the spool is ingested — and a handcrafted
   spool that attests the window WITHOUT the detection flips the same
   span to "undetected" (the gate failure ring expiry used to hide),
3. draining is host-side only (census parity: zero traced eqns) and
   its cost is accounted (``spool_s`` chunk stamps, perfwatch's
   gap-vs-spool attribution), bounded loosely against execution time,
4. every spool record's event name is registered in
   ``telemetry.EVENTS`` and ``opslog.ingest_spool`` is idempotent
   (re-ingest appends nothing — the dedup-identity merge contract).

One module-scoped storm soak feeds all of it: TINY rings (16 rows) and
a partition injected early then healed, so by the run's end every ring
has wrapped far past the incident — exactly the span the spool exists
to preserve.
"""

import hashlib
import json

import jax
import pytest

import support  # noqa: F401  (sys.path side effect for partisan_tpu)
from partisan_tpu import opslog, perfwatch, soak, spool, telemetry
from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config, ControlConfig
from partisan_tpu.models.plumtree import Plumtree

N = 16
# partition at +4, healed at +10, run to +60: the 16-row rings retain
# only rounds ~44..60 at the end, so the incident is ring-expired
STORM_EVENTS = ((4, soak.Partition()), (10, soak.Heal()))
ROUNDS = 60
KILL_AT = 30


def _mk():
    cfg = Config(n_nodes=N, seed=5, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups",
                 metrics=True, metrics_ring=16, latency=True,
                 health=1, health_ring=16,
                 control=ControlConfig(healing=True))
    return Cluster(cfg, model=Plumtree())


def _storm(start):
    return soak.Storm(events=STORM_EVENTS, start=start, period=0)


def _sha(path):
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _cfg(**kw):
    kw.setdefault("chunk_fixed", 10)
    kw.setdefault("poll_latency", True)
    return soak.SoakConfig(**kw)


@pytest.fixture(scope="module")
def spool_run(tmp_path_factory):
    """The shared storm soak, spooled three ways: an uninterrupted
    reference, a killed run whose fresh-engine resume REOPENS the same
    spool file, and a pipelined (depth-2) run."""
    tmp = tmp_path_factory.mktemp("spool")
    cl = _mk()
    st = cl.init()
    m = cl.manager.join_many(cl.cfg, st.manager,
                             list(range(1, N)), [0] * (N - 1))
    st = cl.steps(st._replace(manager=m), 20)
    st = st._replace(model=cl.model.broadcast(st.model, 0, 0,
                                              int(st.rnd)))
    st = cl.steps(st, 5)
    r0 = int(jax.device_get(st.rnd))

    ref_path = str(tmp / "ref.spool.jsonl")
    sp_ref = spool.Spool(ref_path)
    eng = soak.Soak(make_cluster=lambda: cl, storm=_storm(r0),
                    cfg=_cfg(), spool=sp_ref)
    res_ref = eng.run(st, rounds=ROUNDS)
    sp_ref.close()

    ckpt = tmp_path_factory.mktemp("spool_ckpt")
    kr_path = str(tmp / "kr.spool.jsonl")
    sp_a = spool.Spool(kr_path)
    eng_a = soak.Soak(make_cluster=lambda: cl, storm=_storm(r0),
                      cfg=_cfg(checkpoint_dir=str(ckpt)), spool=sp_a)
    eng_a.run(st, until_round=r0 + KILL_AT)
    sp_a.close()
    # the fresh-process path: new cluster, new spool OBJECT on the same
    # file (the constructor recovers dedup keys + marks from disk)
    sp_b = spool.Spool(kr_path)
    eng_b = soak.Soak(make_cluster=_mk, storm=_storm(r0),
                      cfg=_cfg(checkpoint_dir=str(ckpt)), spool=sp_b)
    eng_b.run(resume=True, until_round=r0 + ROUNDS)
    sp_b.close()

    pipe_path = str(tmp / "pipe.spool.jsonl")
    sp_p = spool.Spool(pipe_path)
    eng_p = soak.Soak(make_cluster=lambda: cl, storm=_storm(r0),
                      cfg=_cfg(pipeline_depth=2, checkpoint_every=20),
                      spool=sp_p)
    eng_p.run(st, rounds=ROUNDS)
    sp_p.close()

    return {"r0": r0, "cl": cl, "boot": st, "res_ref": res_ref,
            "ref": ref_path, "kr": kr_path, "pipe": pipe_path,
            "stats": sp_ref.stats()}


def test_spool_bit_identical_across_regimes(spool_run):
    """Acceptance: kill/restore AND pipelined spools byte-identical to
    the uninterrupted run's."""
    h_ref = _sha(spool_run["ref"])
    assert h_ref == _sha(spool_run["kr"]), \
        "kill/restore spool differs from the uninterrupted run's"
    assert h_ref == _sha(spool_run["pipe"]), \
        "pipelined spool differs from the uninterrupted run's"
    st = spool_run["stats"]
    assert st["rows"] > 0 and st["start"] == spool_run["r0"]
    # the resumed file kept its ORIGINAL header: exactly one meta line
    with open(spool_run["kr"]) as f:
        metas = [ln for ln in f if "spool_meta" in ln]
    assert len(metas) == 1


def test_spool_flips_unobservable_to_closed(spool_run):
    """The coverage flip: ring-expired partition is "unobservable" on
    final-ring evidence, a measured CLOSED span once the spool extends
    coverage to the run's entry round."""
    r0 = spool_run["r0"]
    res = spool_run["res_ref"]

    j_ring = opslog.from_soak(res, storm=_storm(r0), slo_rounds=8)
    (part,) = [s for s in opslog.match(j_ring)["spans"]
               if s["rule"] == "partition"]
    assert part["status"] == "unobservable"
    # ...BECAUSE the final rings start after the cause, not because the
    # planes were off
    assert j_ring.streams["health"] > part["cause_round"]
    assert j_ring.streams["metrics"] > part["cause_round"]
    # unobservable is reported, never gated
    assert opslog.gate(opslog.match(j_ring))["ok"]

    j_sp = opslog.ingest_spool(
        spool_run["ref"],
        journal=opslog.from_soak(res, storm=_storm(r0), slo_rounds=8),
        slo_rounds=8)
    assert "spool" in j_sp.streams
    for s in ("health", "metrics", "latency"):
        assert j_sp.streams[s] == r0, s
    m = opslog.match(j_sp)
    (part,) = [s for s in m["spans"] if s["rule"] == "partition"]
    assert part["status"] == "closed"
    assert part["cause_round"] == r0 + 4
    assert part["detect_latency"] >= 0
    assert part["recover_round"] >= r0 + 10
    assert m["counts"]["unobservable"] == 0
    assert m["orphans"] == []
    assert opslog.gate(m)["ok"]
    # the recovery marker is a spool-sourced FALLING edge (the replay
    # adapters run with falling=True over the spooled series)
    from partisan_tpu import health as health_mod

    ring = health_mod.snapshot(res.state.health)["rounds"]
    ring_lo = min(int(r) for r in ring if int(r) >= 0)
    healed = [e for e in j_sp.entries
              if e.event == "partisan.health.overlay_healed"]
    assert healed and min(e.round for e in healed) < ring_lo


def test_handcrafted_spool_flips_unobservable_to_undetected(tmp_path):
    """The other direction: a spool that attests the incident window
    with NO detection turns "unobservable" into "undetected" — the
    real gate failure ring expiry used to mask."""
    j = opslog.Journal()
    j.cover("inject", 0)
    j.append(5, "inject", "inject.Partition", cause_id="p0")
    j.cover("health", 50)        # the final ring's window: too late
    j.start, j.end = 0, 60
    (span,) = opslog.match(j)["spans"]
    assert span["status"] == "unobservable"
    assert opslog.gate(opslog.match(j))["ok"]

    sp = tmp_path / "flat.spool.jsonl"
    lines = [json.dumps({"spool_meta": {
        "version": 1, "start": 0, "planes": ["health"],
        "channels": []}})]
    for r in range(0, 61, 2):
        lines.append(json.dumps({
            "round": r, "stream": "health", "event": spool.EV_HEALTH,
            "measurements": {"components": 1, "isolated": 0,
                             "deg_min": 3, "deg_max": 5,
                             "sym_violations": 0, "joins": 0,
                             "leaves": 0, "ups": 0, "downs": 0}}))
    sp.write_text("\n".join(lines) + "\n")

    j2 = opslog.ingest_spool(str(sp), journal=j)
    assert j2.streams["health"] == 0
    (span,) = opslog.match(j2)["spans"]
    assert span["status"] == "undetected"
    verdict = opslog.gate(opslog.match(j2))
    assert not verdict["ok"] and verdict["undetected"] == 1


def test_drain_traces_zero_eqns(spool_run, tmp_path):
    """The drain is host-side bookkeeping only: a direct Spool.drain
    over a live state changes NOTHING in any traced program (the
    perfwatch census-parity pin)."""
    from partisan_tpu.lint.cost import bench_round_program, \
        census_program

    base = census_program(bench_round_program(64))
    cl, st = spool_run["cl"], spool_run["boot"]
    sp = spool.Spool(str(tmp_path / "census.spool.jsonl"))
    sp.arm(int(jax.device_get(st.rnd)))
    ptr = sp.drain(st, int(jax.device_get(st.rnd)),
                   channels=tuple(c.name for c in cl.cfg.channels))
    sp.close()
    assert ptr["rows"] > 0
    under = census_program(bench_round_program(64))
    assert {p: c.eqns for p, c in base.phases.items()} == \
        {p: c.eqns for p, c in under.phases.items()}
    assert base.total.eqns == under.total.eqns


def test_drain_cost_stamped_and_attributed(spool_run):
    """Every polled chunk row carries its drain's host seconds, the
    decomposition reports them as a spool column (not dispatch gap),
    and the cost stays a small fraction of execution time."""
    rows = [r for r in spool_run["res_ref"].chunks
            if isinstance(r, dict) and "wall_s" in r]
    assert rows and all("spool_s" in r and r["spool_s"] >= 0
                        and "spool" in r for r in rows)
    dec = perfwatch.decompose_chunks(spool_run["res_ref"].chunks)
    assert dec.get("spool_s", 0) >= 0
    # loose overhead bound: tiny-ring drains must not rival execution
    assert sum(r["spool_s"] for r in rows) < 0.5 * dec["in_execution_s"]


def test_decompose_attributes_spool_out_of_gap():
    """Unit math: a drain between chunk K's ready and chunk K+1's
    submit lands in K+1's gap_s — decompose moves min(spool, gap) into
    the spool column, and the LAST row's drain (no later gap) is still
    spool time."""
    rows = [
        {"wall_s": 1.0, "gap_s": 0.5, "spool_s": 0.2},
        {"wall_s": 1.0, "gap_s": 0.3, "spool_s": 0.05},
        {"wall_s": 1.0, "gap_s": 0.01, "spool_s": 0.4},
    ]
    dec = perfwatch.decompose(rows)
    # row 1 gap untouched (no prior drain); row 2: 0.3 - 0.2; row 3:
    # 0.01 fully absorbed (clamped at the gap); final drain 0.4 added
    assert dec["gap_s"] == pytest.approx(0.5 + 0.1 + 0.0)
    assert dec["spool_s"] == pytest.approx(0.2 + 0.01 + 0.4)
    assert dec["in_execution_s"] == pytest.approx(3.0)


def test_every_record_event_is_registered(spool_run):
    """Satellite 3: the spool writes only ``telemetry.EVENTS`` names
    (dot-joined), under the stream opslog ranks them by."""
    registered = {".".join(name) for name in telemetry.EVENTS}
    meta, records = spool.read(spool_run["ref"])
    assert meta["start"] == spool_run["r0"]
    assert records
    for rec in records:
        assert rec["event"] in registered, rec["event"]
        assert rec["stream"] == spool.STREAM_OF[rec["event"]]
    # the run spooled every plane the scenario armed
    events = {r["event"] for r in records}
    assert {spool.EV_METRICS, spool.EV_HEALTH, spool.EV_CTL_HEALING,
            spool.EV_LATENCY} <= events


def test_ingest_spool_is_idempotent(spool_run):
    """Re-ingesting the same spool appends nothing: entry identity
    dedups, coverage min-merges, the span set is unchanged."""
    once = opslog.ingest_spool(spool_run["ref"])
    n1, spans1 = len(once.entries), opslog.match(once)["spans"]
    twice = opslog.ingest_spool(spool_run["ref"], journal=once)
    assert twice is once
    assert len(twice.entries) == n1
    assert opslog.match(twice)["spans"] == spans1
