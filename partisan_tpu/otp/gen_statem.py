"""partisan_gen_statem: the statem event loop (reference
priv/otp/24/partisan_gen_statem.erl, 3008 LoC).

The package owns the loop semantics the reference suite exercises
(test/partisan_gen_statem_SUITE.erl):

- events dispatch to a user module's ``handle_event``; a call's reply
  rides the Mref pairing of the gen protocol,
- POSTPONE: events postponed in a state are replayed — in original
  arrival order, ahead of newer events — when the state changes,
- STATE timeout: armed on entering a state (module-declared per-state),
  NOT cancelled by event arrival, cancelled by a state transition,
- EVENT timeout: armed by an action, cancelled by ANY event arrival.

Timeouts fire as *internal events* (``EV_STATE_TIMEOUT`` /
``EV_EVENT_TIMEOUT``) delivered to the same ``handle_event`` — the OTP
shape, where a timeout is just another event the module handles.

The module returns a :class:`Result` action: transition (or keep_state),
an optional reply for calls, postpone, and an optional event-timeout
arm.  Client side: :class:`partisan_tpu.otp.gen.Caller` (use
``op=gen.OP_EVENT`` via ``Caller.event`` for async events).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Protocol

from partisan_tpu.otp import gen

# internal events (negative so they never collide with wire event codes)
EV_STATE_TIMEOUT = -1
EV_EVENT_TIMEOUT = -2


class Result(NamedTuple):
    """Action returned by ``handle_event``.

    ``next_state=None`` is keep_state; ``reply`` answers a call (with
    ``error`` flagging an error reply); ``postpone`` re-queues the event
    until the next state change; ``event_timeout`` arms the idle timer.
    """

    next_state: Optional[int] = None
    reply: Optional[int] = None
    error: bool = False
    postpone: bool = False
    event_timeout: Optional[int] = None


class Module(Protocol):
    init_state: int

    def handle_event(self, state: int, ev: int, arg: int,
                     is_call: bool) -> Result:
        ...

    def state_timeout(self, state: int) -> Optional[int]:
        """Rounds of state_timeout armed on ENTERING ``state`` (None =
        no timer).  Optional — absence means no state timeouts."""
        ...


class GenStatem(gen.Proc):
    def __init__(self, port: gen.Port, module: Module) -> None:
        super().__init__(port)
        self.module = module
        self.state = module.init_state
        self.postponed: list = []       # [(src, words)] in arrival order
        self.state_deadline: Optional[int] = None
        self.event_deadline: Optional[int] = None
        self.rnd = 0
        self._started = False           # initial state_timeout pending

    # -- the gen_statem event loop -------------------------------------
    def process(self, rnd: int) -> None:
        self.rnd = rnd
        if not self._started:
            # entering the INITIAL state arms its state_timeout too
            self._started = True
            self._arm_state_timeout()
        queue = list(self.drain())
        # Timer events fire BEFORE new external events if their deadline
        # passed (the timer message was already "sent").
        if self.state_deadline is not None and rnd >= self.state_deadline:
            self.state_deadline = None
            if self._dispatch_internal(EV_STATE_TIMEOUT):
                queue = self.postponed + queue
                self.postponed = []
        if self.event_deadline is not None:
            if queue:
                self.event_deadline = None      # any event cancels it
            elif rnd >= self.event_deadline:
                self.event_deadline = None
                if self._dispatch_internal(EV_EVENT_TIMEOUT):
                    queue = self.postponed + queue
                    self.postponed = []
        while queue:
            src, words = queue.pop(0)
            # consuming ANY event cancels a pending event timeout —
            # including one armed by an earlier event of this batch
            self.event_deadline = None
            changed = self._handle(src, words)
            if changed:
                # postponed events replay in original order, ahead of
                # the not-yet-processed remainder of the queue
                queue = self.postponed + queue
                self.postponed = []

    def _dispatch_internal(self, ev: int) -> bool:
        res = self.module.handle_event(self.state, ev, 0, False)
        return self._apply(res)

    def _handle(self, src: int, words) -> bool:
        op = words[0]
        if op not in (gen.OP_CALL, gen.OP_EVENT):
            return False
        mref, ev, arg = words[1], words[2], words[3]
        res = self.module.handle_event(self.state, ev, arg,
                                       op == gen.OP_CALL)
        if res.postpone:
            self.postponed.append((src, words))
            return False
        changed = self._apply(res)
        if op == gen.OP_CALL and res.reply is not None:
            gen.reply(self, src, mref, not res.error, res.reply)
        return changed

    def _apply(self, res: Result) -> bool:
        if res.event_timeout is not None:
            self.event_deadline = self.rnd + res.event_timeout
        if res.next_state is None:
            return False                        # keep_state
        changed = res.next_state != self.state
        self.state = res.next_state
        if changed:
            self.state_deadline = None          # cancelled by transition
            self._arm_state_timeout()
        return changed

    def _arm_state_timeout(self) -> None:
        arm = getattr(self.module, "state_timeout", None)
        if arm is not None:
            t = arm(self.state)
            if t is not None:
                self.state_deadline = self.rnd + t
