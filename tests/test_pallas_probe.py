"""CLI smoke for the standing Pallas re-probe (tools/pallas_probe.py):
the probe must run end-to-end on any backend (interpret fallback
off-TPU) and emit per-probe JSON lines plus a verdict line — the tool
the next relay update is re-checked with (VERDICT r5 next #8)."""

import json
import os
import subprocess
import sys


def test_pallas_probe_cli_smoke():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "pallas_probe.py"),
         "--shapes", "1024"],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [json.loads(x) for x in out.stdout.strip().splitlines()]
    assert any(r.get("probe") == "minimal_256x256" and r["ok"]
               for r in lines), lines
    assert any(r.get("probe") == "gridded_interleave_n1024" and r["ok"]
               for r in lines), lines
    verdict = lines[-1]
    assert "verdict" in verdict and "note" in verdict, verdict
    # off-TPU the probe must say it measured correctness only
    assert verdict["verdict"] in ("PASS-INTERPRET", "PASS"), verdict
