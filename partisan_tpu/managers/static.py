"""Static peer-service manager.

TPU rebuild of ``partisan_static_peer_service_manager`` (reference
src/partisan_static_peer_service_manager.erl): membership changes ONLY
by explicit join/leave — no gossip, no overlay maintenance, no healing.
A join establishes a (bidirectional) connection; both ends record the
peer (the hello/state handshake, peer_service_server.erl:150-166).

State is one adjacency bitmap.  Crash-stopped peers keep their slots —
exactly like the reference, where the strategy state outlives a dead TCP
connection and the reconnect loop re-establishes it on recovery.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import Array

from partisan_tpu.comm import LocalComm
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import msg as msg_ops


class StaticState(NamedTuple):
    joined: Array  # bool[n_local, n_global] — established connections


class Static:
    name = "static"

    def init(self, cfg: Config, comm: LocalComm) -> StaticState:
        return StaticState(
            joined=jnp.zeros((comm.n_local, comm.n_global), jnp.bool_))

    def step(self, cfg: Config, comm: LocalComm, state: StaticState,
             ctx: RoundCtx) -> tuple[StaticState, Array]:
        emitted = msg_ops.zero_stack(cfg, (comm.n_local, 0))
        return state, emitted

    def neighbors(self, cfg: Config, state: StaticState,
                  comm: LocalComm | None = None) -> Array:
        n_local, n_global = state.joined.shape
        all_ids = jnp.arange(n_global, dtype=jnp.int32)
        return jnp.where(state.joined, all_ids[None, :], jnp.int32(-1))

    def members(self, cfg: Config, state: StaticState,
                comm: LocalComm | None = None) -> Array:
        n_local, n_global = state.joined.shape
        gids = (comm.local_ids() if comm is not None
                else jnp.arange(n_local, dtype=jnp.int32))
        self_row = jnp.arange(n_global)[None, :] == gids[:, None]
        return state.joined | self_row

    # ---- scenario scripting (host-side; single-device layout) --------
    def join(self, cfg: Config, state: StaticState, node: int,
             target: int) -> StaticState:
        j = state.joined.at[node, target].set(True)
        j = j.at[target, node].set(True)
        return StaticState(joined=j)

    def leave(self, cfg: Config, state: StaticState, node: int) -> StaticState:
        j = state.joined.at[node, :].set(False)
        j = j.at[:, node].set(False)
        return StaticState(joined=j)
