"""Cluster-level tests for the full-mesh manager + anti-entropy model —
the sim analogues of reference test/partisan_SUITE.erl basic_test /
leave_test / rejoin_test and the demers_anti_entropy gossip demo."""

import jax.numpy as jnp

from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config
from partisan_tpu import faults as faults_mod
from partisan_tpu.models.anti_entropy import AntiEntropy
from partisan_tpu.ops import orset


def converged_members(cl, st, expect_n):
    m = cl.manager.members(cl.cfg, st.manager)
    alive = st.faults.alive
    rows = m[alive]
    counts = jnp.sum(rows, axis=1)
    return bool(jnp.all(counts == expect_n)) and bool(
        jnp.all(rows == rows[0][None, :])
    )


def chain_join(cl, st):
    """Every node i>0 joins via node 0 (the SUITE's star bootstrap)."""
    m = st.manager
    for i in range(1, cl.cfg.n_nodes):
        m = cl.manager.join(cl.cfg, m, i, 0)
    return st._replace(manager=m)


def test_basic_join_convergence():
    cfg = Config(n_nodes=8, seed=42)
    cl = Cluster(cfg)
    st = chain_join(cl, cl.init())
    st, rounds = cl.run_until(
        st, lambda s: converged_members(cl, s, 8), max_rounds=200)
    assert rounds != -1, "membership never converged"
    # Everyone sees everyone: full mesh.
    m = cl.manager.members(cfg, st.manager)
    assert bool(jnp.all(m))


def test_leave():
    cfg = Config(n_nodes=6, seed=7)
    cl = Cluster(cfg)
    st = chain_join(cl, cl.init())
    st, r = cl.run_until(st, lambda s: converged_members(cl, s, 6), 200)
    assert r != -1
    st = st._replace(manager=cl.manager.leave(cfg, st.manager, 3))
    st, r = cl.run_until(
        st,
        lambda s: bool(
            jnp.all(~cl.manager.members(cfg, s.manager)[:, 3])
        ),
        200,
    )
    assert r != -1, "leave never propagated"


def test_rejoin_fresh_incarnation():
    cfg = Config(n_nodes=4, seed=3)
    cl = Cluster(cfg)
    st = chain_join(cl, cl.init())
    st, r = cl.run_until(st, lambda s: converged_members(cl, s, 4), 200)
    assert r != -1
    st = st._replace(manager=cl.manager.leave(cfg, st.manager, 2))
    st, r = cl.run_until(
        st, lambda s: bool(jnp.all(~cl.manager.members(cfg, s.manager)[:, 2])), 200)
    assert r != -1
    st = st._replace(manager=cl.manager.rejoin(cfg, st.manager, 2, 0))
    st, r = cl.run_until(st, lambda s: converged_members(cl, s, 4), 200)
    assert r != -1, "rejoin never converged"


def test_crash_fault_freezes_node():
    cfg = Config(n_nodes=4, seed=1)
    cl = Cluster(cfg)
    st = chain_join(cl, cl.init())
    st = st._replace(faults=faults_mod.crash(st.faults, 3))
    st = cl.steps(st, 30)
    # Node 3's view is frozen at what it had when it crashed: itself plus
    # the join target it learned host-side in chain_join.
    m = cl.manager.members(cfg, st.manager)
    assert m[3].tolist() == [True, False, False, True]
    # Others converged among themselves without node 3's gossip... they may
    # still BELIEVE 3 is a member (no failure detector pruning yet), but
    # they must have found each other.
    assert bool(jnp.all(m[:3, :3]))


def test_anti_entropy_broadcast_converges():
    cfg = Config(n_nodes=16, seed=9)
    model = AntiEntropy()
    cl = Cluster(cfg, model=model)
    st = chain_join(cl, cl.init())
    st, r = cl.run_until(st, lambda s: converged_members(cl, s, 16), 300)
    assert r != -1
    st = st._replace(model=model.broadcast(st.model, node=0, slot=0))
    st, r = cl.run_until(
        st,
        lambda s: float(model.coverage(s.model, s.faults.alive, 0)) == 1.0,
        max_rounds=200,
    )
    assert r != -1, "anti-entropy broadcast never covered the cluster"


def test_anti_entropy_under_link_drop():
    cfg = Config(n_nodes=16, seed=11)
    model = AntiEntropy()
    cl = Cluster(cfg, model=model)
    st = chain_join(cl, cl.init())
    st, r = cl.run_until(st, lambda s: converged_members(cl, s, 16), 300)
    assert r != -1
    st = st._replace(
        faults=st.faults._replace(link_drop=jnp.float32(0.05)),
        model=model.broadcast(st.model, node=2, slot=1),
    )
    st, r = cl.run_until(
        st,
        lambda s: float(model.coverage(s.model, s.faults.alive, 1)) == 1.0,
        max_rounds=400,
    )
    assert r != -1, "anti-entropy did not survive 5% link drop"


def test_partition_blocks_then_heals():
    cfg = Config(n_nodes=8, seed=5)
    model = AntiEntropy()
    cl = Cluster(cfg, model=model)
    st = chain_join(cl, cl.init())
    st, r = cl.run_until(st, lambda s: converged_members(cl, s, 8), 300)
    assert r != -1
    st = st._replace(
        faults=faults_mod.inject_partition(st.faults, [0, 1, 2, 3], [4, 5, 6, 7]),
        model=model.broadcast(st.model, node=0, slot=0),
    )
    st = cl.steps(st, 60)
    cov = float(model.coverage(st.model, st.faults.alive, 0))
    assert cov <= 0.5, f"broadcast crossed a partition: {cov}"
    st = st._replace(faults=faults_mod.resolve_partition(st.faults))
    st, r = cl.run_until(
        st, lambda s: float(model.coverage(s.model, s.faults.alive, 0)) == 1.0, 200)
    assert r != -1, "broadcast did not heal after partition resolution"


def test_determinism():
    cfg = Config(n_nodes=8, seed=123)
    model = AntiEntropy()

    def run():
        cl = Cluster(cfg, model=model)
        st = chain_join(cl, cl.init())
        st = st._replace(model=model.broadcast(st.model, 0, 0))
        return cl.steps(st, 50)

    a, b = run(), run()
    assert bool(orset.equal(a.manager.view, b.manager.view).all())
    assert bool(jnp.all(a.model.store == b.model.store))
    assert int(a.stats.delivered) == int(b.stats.delivered)
