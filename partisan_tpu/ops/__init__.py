"""TPU-friendly primitive ops: vclocks, OR-set membership, message routing,
state-gossip merges, per-node RNG discipline."""
