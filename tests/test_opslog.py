"""Unit contracts of the unified ops journal (partisan_tpu/opslog.py):
entry ordering, identity/dedup, JSON-lines persistence/merge, the
telemetry event-name registry sync guard, the incident-span matcher's
semantics on synthetic timelines, and the SLO error-budget math.

Everything here is host-side and synthetic — no cluster, no device
work.  The end-to-end journal built from a REAL soak run (and the
kill/restore bit-parity of its span set) lives in tests/test_incident.py.
"""

import ast
import pathlib

import numpy as np
import pytest

from partisan_tpu import opslog, telemetry

REPO = pathlib.Path(__file__).resolve().parents[1]


# ---------------------------------------------------------------------------
# journal: ordering, identity, persistence
# ---------------------------------------------------------------------------

def test_sorted_entries_follow_documented_total_order():
    """At one round: injections (ground truth) < chunk rows < detection
    planes < control reactions < synthesized ops markers; unknown
    streams rank between the known tail and ops; rounds dominate."""
    j = opslog.Journal()
    j.append(5, "ops", "ops.crowd_ended")
    j.append(5, "metrics", "partisan.metrics.drop_spike")
    j.append(5, "chunk", "chunk")
    j.append(5, "control", "partisan.control.healing_escalated")
    j.append(5, "inject", "inject.LinkDrop")
    j.append(5, "mystery", "whatever")
    j.append(3, "health", "partisan.health.churn")
    got = [(e.round, e.stream) for e in j.sorted_entries()]
    assert got == [(3, "health"), (5, "inject"), (5, "chunk"),
                   (5, "metrics"), (5, "control"), (5, "mystery"),
                   (5, "ops")]


def test_append_dedups_on_identity_first_copy_wins():
    j = opslog.Journal()
    first = j.append(30, "chunk", "chunk", measurements={"k": 10})
    dup = j.append(30, "chunk", "chunk", measurements={"k": 99})
    assert first is not None and dup is None
    assert len(j.entries) == 1
    assert j.entries[0].measurements == {"k": 10}
    # a dup index in the metadata is a distinct identity (two same-class
    # injections landing on one round)
    assert j.append(30, "chunk", "chunk", metadata={"dup": 1}) is not None
    assert len(j.entries) == 2


def test_severity_defaults():
    assert opslog.severity_of("inject.Partition") == "warn"
    assert opslog.severity_of("inject.Heal") == "info"
    assert opslog.severity_of("partisan.health.partition_detected") \
        == "error"
    assert opslog.severity_of("partisan.health.churn_settled") == "info"
    assert opslog.severity_of("chunk") == "info"
    assert opslog.severity_of("ops.slo_recovered") == "info"
    assert opslog.severity_of("no.such.event") == "info"


def test_bus_handler_journals_registry_events():
    j = opslog.Journal()
    bus = telemetry.Bus()
    bus.attach("j", ("partisan",), j.bus_handler(default_round=40))
    telemetry.emit(bus, telemetry.HEALTH_CHURN,
                   {"joins": 1, "leaves": 0, "ups": 0, "downs": 2},
                   {"round": 7})
    telemetry.emit(bus, telemetry.LATENCY_SLO_BREACH,
                   {"age_rounds": 9.0, "count": 3, "max_age_rounds": 12},
                   {"channel": "gossip", "quantile": 0.99,
                    "slo_rounds": 6})
    (churn, slo) = j.sorted_entries()
    assert (churn.round, churn.stream, churn.severity) == (7, "health",
                                                           "warn")
    assert churn.event == "partisan.health.churn"
    # no round metadata -> the handler's default (journal end)
    assert (slo.round, slo.channel) == (40, "gossip")


def test_jsonl_roundtrip_and_resume_merge(tmp_path):
    p = tmp_path / "ops.jsonl"
    a = opslog.Journal()
    a.start, a.end = 0, 30
    a.cover("inject", 0)
    a.cover("health", 10)
    a.append(5, "inject", "inject.Partition", cause_id="5:inject.Partition",
             measurements={}, metadata={"mode": None})
    a.append(12, "health", "partisan.health.partition_detected",
             measurements={"components": 2, "isolated": 1},
             metadata={"round": 12})
    a.to_jsonl(p)

    back = opslog.Journal.from_jsonl(p)
    assert back.streams == a.streams
    assert (back.start, back.end) == (0, 30)
    assert [e.key() for e in back.sorted_entries()] \
        == [e.key() for e in a.sorted_entries()]
    assert back.sorted_entries()[0].cause_id == "5:inject.Partition"

    # the kill/restore path: a resumed run re-journals an overlapping
    # window and APPENDS — the merge dedups and widens the bounds
    b = opslog.Journal()
    b.start, b.end = 5, 60
    b.cover("health", 10)
    b.append(5, "inject", "inject.Partition",
             cause_id="5:inject.Partition")          # duplicate identity
    b.append(18, "health", "partisan.health.overlay_healed",
             measurements={"components": 1})
    b.to_jsonl(p, append=True)
    merged = opslog.Journal.from_jsonl(p)
    assert len(merged.entries) == 3
    assert (merged.start, merged.end) == (0, 60)
    assert merged.streams == {"inject": 0, "health": 10}


# ---------------------------------------------------------------------------
# telemetry event-name registry (ISSUE 17 satellite): one registry,
# no ad-hoc event strings anywhere in the package or the tools
# ---------------------------------------------------------------------------

def _literal_event_tuples():
    """Every tuple literal of string constants starting with
    "partisan" in partisan_tpu/ and tools/ — the AST sweep that keeps
    the registry the single namespace for event names."""
    found = []
    for sub in ("partisan_tpu", "tools"):
        for p in sorted((REPO / sub).rglob("*.py")):
            for node in ast.walk(ast.parse(p.read_text())):
                if not (isinstance(node, ast.Tuple) and node.elts):
                    continue
                if not all(isinstance(e, ast.Constant)
                           and isinstance(e.value, str)
                           for e in node.elts):
                    continue
                vals = tuple(e.value for e in node.elts)
                if vals[0] == "partisan":
                    found.append((f"{p.relative_to(REPO)}:{node.lineno}",
                                  vals))
    return found


def test_every_event_tuple_literal_is_registered():
    """Full event names (3+ parts) must be telemetry.EVENTS keys;
    shorter tuples are bus-subscription prefixes and must prefix some
    registered name.  An unregistered ad-hoc tuple anywhere in the
    package or tools fails here BY NAME — the sync guard."""
    registered = set(telemetry.EVENTS)
    prefixes = {name[:k] for name in registered
                for k in range(1, len(name))}
    tuples = _literal_event_tuples()
    # the registry's own constant definitions are in the sweep, so an
    # empty result would mean the scanner broke, not a clean tree
    assert len([v for _, v in tuples if len(v) >= 3]) \
        >= len(registered)
    for where, vals in tuples:
        if len(vals) >= 3:
            assert vals in registered, \
                f"{where}: unregistered event tuple {vals}"
        else:
            assert vals in prefixes, \
                f"{where}: unknown event prefix {vals}"


def test_emit_refuses_unregistered_and_incomplete_events():
    bus = telemetry.Bus()
    with pytest.raises(ValueError, match="unregistered"):
        telemetry.emit(bus, ("partisan", "health", "made_up"), {}, {})
    with pytest.raises(ValueError, match="required"):
        telemetry.emit(bus, telemetry.HEALTH_CHURN,
                       {"joins": 1}, {"round": 3})
    assert len(telemetry.EVENTS) >= 34


# ---------------------------------------------------------------------------
# falling-edge recovery markers (the matcher's close events)
# ---------------------------------------------------------------------------

def test_health_transitions_emit_churn_settled_falling_edge():
    snap = {"components": np.array([1, 1, 1, 1]),
            "isolated": np.zeros(4, int),
            "rounds": np.array([0, 5, 10, 15]),
            "joins": np.array([0, 2, 2, 0]),
            "leaves": np.zeros(4, int),
            "ups": np.zeros(4, int), "downs": np.zeros(4, int)}
    from partisan_tpu import health
    kinds = [t["kind"] for t in health.transitions(snap, falling=True)]
    assert kinds == ["churn", "churn_settled"]
    # off by default: historical event counts unchanged
    assert [t["kind"] for t in health.transitions(snap)] == ["churn"]


def test_metrics_replay_falling_edges_close_drop_spikes():
    snap = {"shed": np.zeros(5, int),
            "drops": np.array([[0], [4], [4], [0], [0]]),
            "edges_min": np.array([2, 2, 2, 2, 2]),
            "alive": np.full(5, 8), "rounds": np.arange(5)}
    rec = telemetry.Recorder()
    bus = telemetry.Bus()
    bus.attach("t", ("partisan", "metrics"), rec)
    n = telemetry.replay_metrics_events(bus, snap, falling=True)
    assert [e[0] for e in rec.events] == [
        telemetry.METRICS_DROP_SPIKE, telemetry.METRICS_DROP_CLEARED]
    assert n == 2


# ---------------------------------------------------------------------------
# the incident-span matcher, on synthetic timelines
# ---------------------------------------------------------------------------

def _journal(entries, streams=None, end=40):
    j = opslog.Journal()
    j.start, j.end = 0, end
    for s, lo in (streams or {}).items():
        j.cover(s, lo)
    for rnd, stream, event, kw in entries:
        j.append(rnd, stream, event, **kw)
    return j


def _partition_timeline(*, healed=True, react_round=13):
    rows = [
        (10, "inject", "inject.Partition",
         {"cause_id": "10:inject.Partition"}),
        (12, "health", "partisan.health.partition_detected",
         {"measurements": {"components": 2}}),
        (react_round, "control", "partisan.control.healing_escalated",
         {"metadata": {"direction": "escalate"}}),
    ]
    if healed:
        rows.append((18, "health", "partisan.health.overlay_healed", {}))
    return rows


def test_match_closed_span_measures_every_leg():
    j = _journal(_partition_timeline(), streams={"health": 0})
    m = opslog.match(j)
    (span,) = m["spans"]
    assert span["status"] == "closed"
    assert (span["rule"], span["cause_id"]) \
        == ("partition", "10:inject.Partition")
    assert (span["detect_round"], span["detect_latency"]) == (12, 2)
    assert (span["react_round"], span["react_latency"]) == (13, 1)
    assert (span["recover_round"], span["recover_latency"]) == (18, 8)
    assert m["orphans"] == []
    assert opslog.gate(m)["ok"]


def test_match_open_undetected_and_unobservable():
    # detected but never recovered -> open (gates)
    m_open = opslog.match(_journal(_partition_timeline(healed=False),
                                   streams={"health": 0}))
    assert m_open["spans"][0]["status"] == "open"
    assert not opslog.gate(m_open)["ok"]
    # observable cause with no plane event -> undetected (gates)
    m_und = opslog.match(_journal(
        [(10, "inject", "inject.Partition", {})], streams={"health": 0}))
    assert m_und["spans"][0]["status"] == "undetected"
    assert not opslog.gate(m_und)["ok"]
    # the attesting streams' ring windows start after the cause (or the
    # planes are off) -> unobservable: reported, NOT gated
    m_uno = opslog.match(_journal(
        [(10, "inject", "inject.Partition", {})], streams={"health": 25}))
    assert m_uno["spans"][0]["status"] == "unobservable"
    v = opslog.gate(m_uno)
    assert v["ok"] and v["unobservable"] == 1


def test_match_folds_causes_with_no_recovery_between():
    # downs-only: a recovery candidate needs ups/joins, so this churn
    # detects without also closing the span
    base = {"measurements": {"joins": 0, "leaves": 0, "ups": 0,
                             "downs": 1}}
    up = {"measurements": {"joins": 0, "leaves": 0, "ups": 1,
                           "downs": 0}}
    folded = opslog.match(_journal([
        (10, "inject", "inject.Churn", {}),
        (11, "health", "partisan.health.churn", base),
        (14, "inject", "inject.Churn", {}),
        (20, "health", "partisan.health.churn", up),
    ], streams={"health": 0}))
    (span,) = folded["spans"]
    assert span["causes_folded"] == 2 and span["status"] == "closed"
    # a recovery BETWEEN the causes splits them into two incidents
    split = opslog.match(_journal([
        (10, "inject", "inject.Churn", {}),
        (11, "health", "partisan.health.churn", base),
        (12, "health", "partisan.health.churn_settled", {}),
        (14, "inject", "inject.Churn", {}),
        (15, "health", "partisan.health.churn", base),
        (21, "health", "partisan.health.churn_settled", {}),
    ], streams={"health": 0}))
    assert [s["status"] for s in split["spans"]] == ["closed", "closed"]
    assert [s["recover_round"] for s in split["spans"]] == [12, 21]


def test_match_flash_crowd_recovers_on_last_window_edge():
    """recover_last: the crowd is over when the LAST breach window
    closed, not the first."""
    m = opslog.match(_journal([
        (10, "inject", "inject.SetRate", {"measurements": {"x1000": 8}}),
        (10, "traffic", "partisan.traffic.flash_crowd",
         {"measurements": {"rate_x1000": 8}}),
        (15, "ops", "ops.slo_recovered", {}),
        (20, "ops", "ops.crowd_ended", {}),
    ], streams={"traffic": 0}), crowd_x1000=5)
    (span,) = m["spans"]
    assert span["rule"] == "flash_crowd" and span["status"] == "closed"
    assert span["recover_round"] == 20
    # below the crowd threshold the SetRate is not a fault at all
    calm = opslog.match(_journal(
        [(10, "inject", "inject.SetRate",
          {"measurements": {"x1000": 2}})],
        streams={"traffic": 0}), crowd_x1000=5)
    assert calm["spans"] == []


def test_match_reports_orphan_reactions():
    """A controller escalation no span claims is an orphan; one AFTER
    its incident's recovery is outside the incident interval and
    orphans too.  Relax-direction healing moves are routine decay, not
    reactions."""
    m = opslog.match(_journal([
        (5, "control", "partisan.control.healing_escalated",
         {"metadata": {"direction": "escalate"}}),
        (6, "control", "partisan.control.healing_escalated",
         {"metadata": {"direction": "relax"}}),
    ]))
    assert [o["round"] for o in m["orphans"]] == [5]
    assert m["orphans"][0]["kind"] == "ops_orphan"
    late = opslog.match(_journal(
        _partition_timeline(react_round=25), streams={"health": 0}))
    (span,) = late["spans"]
    assert span["status"] == "closed" and span["react_round"] is None
    assert [o["round"] for o in late["orphans"]] == [25]
    assert opslog.gate(late)["ok"]       # orphans report, never gate


# ---------------------------------------------------------------------------
# SLO error budgets
# ---------------------------------------------------------------------------

def _chunk(rnd, k, p99):
    return (rnd, "chunk", "chunk",
            {"measurements": {"k": k}, "metadata": {"p99": p99}})


def test_error_budget_burn_and_exhaustion():
    j = _journal([
        _chunk(0, 10, {"gossip": 5.0, "rpc": 4.0}),
        _chunk(10, 10, {"gossip": 20.0, "rpc": 4.0}),
        _chunk(20, 10, {"gossip": 20.0, "rpc": 4.0}),
        _chunk(30, 10, {"gossip": 5.0, "rpc": None}),
    ])
    budgets = {b["channel"]: b
               for b in opslog.error_budgets(j, slo_rounds=10)}
    g = budgets["gossip"]
    # 40 polled rounds, budget 25% = 10; chunks at 10 and 20 breach
    # (p99 > bound; == passes), burning 20 rounds -> burn 2.0 and the
    # line is crossed at the SECOND breaching chunk
    assert (g["rounds"], g["budget_rounds"]) == (40, 10.0)
    assert (g["breach_rounds"], g["burn"]) == (20, 2.0)
    assert g["exhausted_round"] == 20
    r = budgets["rpc"]
    assert (r["breach_rounds"], r["burn"], r["exhausted_round"]) \
        == (0, 0.0, None)
    # the gate: an exhausted channel fails unless exempted
    matched = {"counts": {"spans": 0, "closed": 0, "open": 0,
                          "undetected": 0, "unobservable": 0,
                          "orphans": 0}}
    assert not opslog.gate(matched, list(budgets.values()))["ok"]
    v = opslog.gate(matched, list(budgets.values()), exempt=("gossip",))
    assert v["ok"] and v["budget_exhausted"] == []
